"""Serving-plane supervisor: stall detection, checkpoint restarts, and
explicit degradation tiers.

Same design rules as the live plane's dial-path breakers
(``net/policy.py``): no threads, no wall-clock reads outside the injected
``clock``, every transition counted — so every behavior is testable with a
fake clock, deterministically.

The watchdog is *polled* by whoever owns the serving loop (the bench
child, the streaming scenario runner, a socket frontend).  Liveness is
tracked through two heartbeat stamps the loop refreshes: ``note_chunk()``
after every engine chunk and ``note_verifier()`` after every verification
flush.  ``poll()`` then:

1. restarts the engine from its last durable snapshot when no chunk has
   completed within ``chunk_stall_s`` (the engine is wedged or its process
   was replaced — the restart path is ``StreamingEngine.restore()``, which
   reuses the shared compiled rollout, so recovery never recompiles);
2. reports a dead verifier pool when no flush landed within
   ``verifier_stall_s`` and invokes the ``on_verifier_restart`` callback
   (the owner rebuilds its :class:`~..crypto.pipeline.ValidationPipeline`
   and resubmits its retry window);
3. walks the overload ladder on ring depth with watermark hysteresis:

   ``normal`` → ``shed_priority`` → ``drop_oldest``

   Tier 1 installs the ring's shed set (topics below the top priority are
   refused at the door, each refusal counted under ``shed_priority`` in the
   conservation ledger).  Tier 2 additionally swaps the backpressure policy
   to ``drop_oldest`` (freshest-wins), restoring the *currently desired*
   policy on the way back down: with a :mod:`.controller` attached, that is
   the controller's ``KnobState.backpressure_policy`` — the single source
   of truth — so a controller retune that happened mid-escalation is never
   reverted to a stale construction-time policy.  Every shed is loudly
   attributed — the ledger's ``silent_drops`` stays zero through every
   tier.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

TIER_NAMES = ("normal", "shed_priority", "drop_oldest")


class Watchdog:
    """Poll-driven supervisor over one engine + ring pair.

    ``topic_priority[t]`` ranks topic ``t`` (higher = more important);
    tier 1 sheds every topic whose priority is below the maximum.  With a
    uniform priority vector there is nothing to shed and tier 1 is an
    (attributed) no-op on the way to tier 2.
    """

    def __init__(
        self,
        engine,
        ring,
        checkpoint_path: Optional[str] = None,
        chunk_stall_s: float = 30.0,
        verifier_stall_s: Optional[float] = None,
        high_watermark: Optional[int] = None,
        low_watermark: Optional[int] = None,
        topic_priority: Optional[Sequence[int]] = None,
        on_engine_restart: Optional[Callable[[dict], None]] = None,
        on_verifier_restart: Optional[Callable[[], None]] = None,
        metrics=None,
        clock=time.monotonic,
        blackbox=None,
        postmortem_path: Optional[str] = None,
    ) -> None:
        if chunk_stall_s <= 0:
            raise ValueError("chunk_stall_s must be > 0")
        self.engine = engine
        self.ring = ring
        self.checkpoint_path = checkpoint_path
        self.chunk_stall_s = chunk_stall_s
        self.verifier_stall_s = verifier_stall_s
        self.high_watermark = (
            int(high_watermark) if high_watermark is not None
            else ring.capacity
        )
        self.low_watermark = (
            int(low_watermark) if low_watermark is not None
            else max(0, ring.capacity // 2)
        )
        if not (0 <= self.low_watermark < self.high_watermark):
            raise ValueError(
                "need 0 <= low_watermark < high_watermark "
                f"(got {self.low_watermark} / {self.high_watermark})"
            )
        n_topics = engine.model.t
        if topic_priority is None:
            topic_priority = [0] * n_topics
        if len(topic_priority) != n_topics:
            raise ValueError(
                f"topic_priority has {len(topic_priority)} entries for "
                f"{n_topics} topics"
            )
        self.topic_priority = [int(p) for p in topic_priority]
        top = max(self.topic_priority)
        self._shed_set = [
            t for t, p in enumerate(self.topic_priority) if p < top
        ]
        self.on_engine_restart = on_engine_restart
        self.on_verifier_restart = on_verifier_restart
        self.metrics = metrics
        self.clock = clock
        # r18 black box: when wired, restart_engine dumps the last-K chunk
        # frames to ``postmortem_path`` — the forensic record of the run-up
        # to the death, not just the final counters.
        self.blackbox = blackbox
        self.postmortem_path = postmortem_path
        self.tier = 0
        self._orig_policy = ring.policy
        # Attached by serve.controller.Controller: when present, the
        # controller's KnobState is the single source of truth for the
        # desired backpressure policy (see _desired_policy).
        self.controller = None
        self._last_chunk: Optional[float] = None
        self._last_verifier: Optional[float] = None
        self.engine_restarts = 0
        self.verifier_restarts = 0
        self.tier_log: List[Tuple[float, str, str]] = []  # (t, tier, reason)
        if self.metrics is not None:
            # The tier is a gauge from birth (r20): /metrics shows
            # "normal" as an explicit 0, not an absent family.
            self.metrics.gauge("serve.watchdog.tier", self.tier)

    # -- liveness stamps (called by the serving loop) -----------------------

    def note_chunk(self) -> None:
        self._last_chunk = self.clock()

    def note_verifier(self) -> None:
        self._last_verifier = self.clock()

    # -- supervision ---------------------------------------------------------

    def poll(self) -> List[str]:
        """One supervision pass; returns the (possibly empty) list of
        actions taken: "engine_restart", "verifier_restart", "tier_up",
        "tier_down"."""
        now = self.clock()
        actions: List[str] = []
        if (
            self._last_chunk is not None
            and now - self._last_chunk >= self.chunk_stall_s
        ):
            self.restart_engine(
                f"no chunk for {now - self._last_chunk:.1f}s "
                f"(stall threshold {self.chunk_stall_s:.1f}s)"
            )
            actions.append("engine_restart")
        if (
            self.verifier_stall_s is not None
            and self._last_verifier is not None
            and now - self._last_verifier >= self.verifier_stall_s
        ):
            self.verifier_restarts += 1
            self._inc("serve.watchdog.verifier_restarts")
            self._last_verifier = self.clock()
            if self.on_verifier_restart is not None:
                self.on_verifier_restart()
            actions.append("verifier_restart")
        depth = self.ring.depth
        if depth >= self.high_watermark and self.tier < 2:
            self._set_tier(self.tier + 1, f"depth {depth} >= high "
                           f"{self.high_watermark}")
            actions.append("tier_up")
        elif depth <= self.low_watermark and self.tier > 0:
            self._set_tier(self.tier - 1, f"depth {depth} <= low "
                           f"{self.low_watermark}")
            actions.append("tier_down")
        return actions

    def restart_engine(self, reason: str) -> dict:
        """Restore the engine from its last durable snapshot and reset the
        chunk stamp.  Public so an owner that *knows* its engine died (the
        chaos runner, a process supervisor) can restart without waiting out
        the stall threshold."""
        path = self.checkpoint_path
        if hasattr(self.engine, "recovery_context"):
            # Hand the restore path the supervision context so reopened
            # spans are annotated with WHY the world stopped, not just for
            # how long.
            self.engine.recovery_context = {
                "tier": self.tier_name, "reason": reason,
            }
        info = self.engine.restore(path)
        self.engine_restarts += 1
        self._inc("serve.watchdog.engine_restarts")
        self._last_chunk = self.clock()
        self.tier_log.append((self.clock(), TIER_NAMES[self.tier],
                              f"engine restart: {reason}"))
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None:
            tracer.event("engine_restart", t=self.clock(), reason=reason,
                         tier=self.tier_name)
        if self.blackbox is not None and self.postmortem_path is not None:
            self.blackbox.dump(self.postmortem_path, extra={
                "reason": reason,
                "tier": self.tier_name,
                "engine_restarts": self.engine_restarts,
                "tier_log": [[t, name, why]
                             for t, name, why in self.tier_log],
                "restore_info": dict(info),
            })
        if self.on_engine_restart is not None:
            self.on_engine_restart(info)
        return info

    def reattach(self, engine, ring) -> None:
        """Point supervision at a replacement engine+ring pair (the staged
        crash path discards both) and RE-APPLY the current tier's controls
        to the new ring — a fresh ring is born with no shed set and its
        constructed policy, which under an active escalation would silently
        exit the tier the ladder decided on."""
        self.engine = engine
        self.ring = ring
        if self.tier >= 1:
            ring.set_shed_topics(self._shed_set)
        if self.tier >= 2:
            ring.set_policy("drop_oldest")
        else:
            ring.set_policy(self._desired_policy())

    @property
    def tier_name(self) -> str:
        return TIER_NAMES[self.tier]

    # -- internals -----------------------------------------------------------

    def _desired_policy(self) -> str:
        """The policy de-escalation restores: the controller's current
        desired policy when one is attached (single source of truth —
        satellite fix r20), else the policy memorized at construction."""
        if self.controller is not None:
            return self.controller.knobs.backpressure_policy
        return self._orig_policy

    def _set_tier(self, tier: int, reason: str) -> None:
        self.tier = tier
        if tier >= 1:
            self.ring.set_shed_topics(self._shed_set)
        else:
            self.ring.set_shed_topics(())
        if tier >= 2:
            self.ring.set_policy("drop_oldest")
        else:
            self.ring.set_policy(self._desired_policy())
        self.tier_log.append((self.clock(), TIER_NAMES[tier], reason))
        self._inc("serve.watchdog.tier_changes")
        if self.metrics is not None:
            self.metrics.gauge("serve.watchdog.tier", tier)
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None:
            # tier_log transitions double as ledger events, so the trace
            # timeline shows WHEN the ladder moved among the spans it bent.
            tracer.event("watchdog_tier", t=self.clock(),
                         tier=TIER_NAMES[tier], reason=reason)

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)
