"""Host ingest ring: batch an unbounded publish stream into device chunks.

Producers (socket handlers, the bench's load generators, the scenario
streaming runner) ``push`` (topic, payload, publisher) tuples; the
:class:`~.engine.StreamingEngine` ``pop_batch``-es them into the fixed-shape
publish slots of its next rollout chunk.  The ring is a preallocated
circular buffer under one lock — "lock-free-ish" in the honest sense that
the hot path is a couple of index updates inside an uncontended mutex, not
a CAS loop; the contention profile that matters here is one producer-side
caller vs one consumer-side engine thread.

Backpressure is an explicit, named policy — never an implicit drop:

- ``block``       — ``push`` waits (bounded by ``timeout``) for space; a
                    timed-out push returns ``False`` to ITS caller, so no
                    message ever vanishes unacknowledged;
- ``drop_oldest`` — the ring evicts its head to admit the newcomer
                    (freshest-wins streams), counting every eviction;
- ``reject``      — a full ring refuses the newcomer (caller retries).

``accounting()`` exposes the conservation check the streaming SLO grades:
every accepted message is either still queued, handed to the device, or
attributed to a named policy counter — ``silent_drops`` is the residual and
must be zero under every policy.

Queue-depth and policy counters land on an (optional) existing
:class:`~..utils.metrics.MetricsRegistry` under ``serve.ingest.*``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, Optional

BACKPRESSURE_POLICIES = ("block", "drop_oldest", "reject")


@dataclass(frozen=True)
class IngestItem:
    """One queued publish: identity, payload, and its ingest timestamp
    (host clock at ``push`` — the start of the ingest→delivery latency the
    engine measures exactly)."""

    seq: int            # ring-assigned, monotonically increasing
    topic: int
    publisher: int
    payload: bytes
    valid: bool         # upstream validation verdict (gates relay on device)
    t_ingest: float     # host clock at push


class IngestRing:
    """Bounded FIFO ring of :class:`IngestItem` with explicit backpressure.

    Thread-safe; ``push`` and ``pop_batch`` may run from different threads.
    Zero-length payloads are legal (a bare topic beacon is a real pubsub
    message shape).
    """

    def __init__(
        self,
        capacity: int,
        policy: str = "block",
        metrics=None,
        clock=time.monotonic,
    ) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if policy not in BACKPRESSURE_POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r}; "
                f"have: {', '.join(BACKPRESSURE_POLICIES)}"
            )
        self.capacity = capacity
        self.policy = policy
        self.metrics = metrics
        self._clock = clock
        self._buf: List[Optional[IngestItem]] = [None] * capacity
        self._head = 0          # index of the oldest item
        self._size = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self.max_depth = 0
        self._accepted = 0
        self._popped = 0
        self._dropped_oldest = 0
        self._rejected = 0
        self._block_waits = 0

    # -- producer side ------------------------------------------------------

    def push(
        self,
        topic: int,
        payload: bytes,
        publisher: int,
        valid: bool = True,
        timeout: Optional[float] = None,
    ) -> bool:
        """Enqueue one publish; returns True iff it was admitted.

        ``timeout`` only applies under the ``block`` policy (None = wait
        forever).  A False return means the CALLER still owns the message —
        the ring never took it, so nothing was dropped silently.
        """
        with self._lock:
            if self._size >= self.capacity:
                if self.policy == "reject":
                    self._rejected += 1
                    self._metric_inc("serve.ingest.rejected")
                    return False
                if self.policy == "drop_oldest":
                    self._evict_oldest_locked()
                else:  # block
                    self._block_waits += 1
                    self._metric_inc("serve.ingest.block_waits")
                    if not self._not_full.wait_for(
                        lambda: self._size < self.capacity, timeout=timeout
                    ):
                        self._rejected += 1
                        self._metric_inc("serve.ingest.rejected")
                        return False
            item = IngestItem(
                seq=self._seq,
                topic=int(topic),
                publisher=int(publisher),
                payload=bytes(payload),
                valid=bool(valid),
                t_ingest=self._clock(),
            )
            self._seq += 1
            self._buf[(self._head + self._size) % self.capacity] = item
            self._size += 1
            self._accepted += 1
            self.max_depth = max(self.max_depth, self._size)
            self._metric_inc("serve.ingest.accepted")
            self._metric_depth()
            return True

    # -- consumer side ------------------------------------------------------

    def pop_batch(self, max_n: int) -> List[IngestItem]:
        """Dequeue up to ``max_n`` items in FIFO order (may be empty)."""
        out: List[IngestItem] = []
        with self._lock:
            take = min(max_n, self._size)
            for _ in range(take):
                item = self._buf[self._head]
                assert item is not None
                self._buf[self._head] = None
                self._head = (self._head + 1) % self.capacity
                self._size -= 1
                out.append(item)
            self._popped += len(out)
            if out:
                self._not_full.notify_all()
                self._metric_depth()
        return out

    # -- introspection ------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return self._size

    def accounting(self) -> dict:
        """Conservation ledger.  ``silent_drops`` is the residual between
        what was accepted and what is accounted for — the streaming SLO's
        zero-silent-drops channel reads it directly."""
        with self._lock:
            silent = (
                self._accepted - self._popped - self._dropped_oldest
                - self._size
            )
            return {
                "accepted": self._accepted,
                "popped": self._popped,
                "in_queue": self._size,
                "dropped_oldest": self._dropped_oldest,
                "rejected": self._rejected,
                "block_waits": self._block_waits,
                "max_depth": self.max_depth,
                "silent_drops": silent,
            }

    # -- internals ----------------------------------------------------------

    def _evict_oldest_locked(self) -> None:
        self._buf[self._head] = None
        self._head = (self._head + 1) % self.capacity
        self._size -= 1
        self._dropped_oldest += 1
        self._metric_inc("serve.ingest.dropped_oldest")

    def _metric_inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def _metric_depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve.ingest.depth", self._size)
