"""Host ingest ring: batch an unbounded publish stream into device chunks.

Producers (socket handlers, the bench's load generators, the scenario
streaming runner) ``push`` (topic, payload, publisher) tuples; the
:class:`~.engine.StreamingEngine` ``pop_batch``-es them into the fixed-shape
publish slots of its next rollout chunk.  The ring is a preallocated
circular buffer under one lock — "lock-free-ish" in the honest sense that
the hot path is a couple of index updates inside an uncontended mutex, not
a CAS loop; the contention profile that matters here is one producer-side
caller vs one consumer-side engine thread.

Backpressure is an explicit, named policy — never an implicit drop:

- ``block``       — ``push`` waits (bounded by ``timeout``) for space; a
                    timed-out push returns ``False`` to ITS caller, so no
                    message ever vanishes unacknowledged;
- ``drop_oldest`` — the ring evicts its head to admit the newcomer
                    (freshest-wins streams), counting every eviction;
- ``reject``      — a full ring refuses the newcomer (caller retries).

On top of the policy, the watchdog's first degradation tier can install a
*shed set* (``set_shed_topics``): pushes for shed topics are refused at the
door and counted under ``shed_priority`` — like ``reject``, the caller
still owns the message, so the shed never enters the conservation formula
as anything but an attributed refusal.

``snapshot()`` / ``restore_snapshot()`` round-trip the buffer contents AND
the full counter set so a restored ring resumes the same conservation
ledger.  Restore reinstates counters verbatim — replayed items must NOT
re-increment ``accepted`` (they were counted at their original admission;
re-pushing them would double-count and break ``silent_drops == 0``).

``accounting()`` exposes the conservation check the streaming SLO grades:
every accepted message is either still queued, handed to the device, or
attributed to a named policy counter — ``silent_drops`` is the residual and
must be zero under every policy.

Queue-depth and policy counters land on an (optional) existing
:class:`~..utils.metrics.MetricsRegistry` under ``serve.ingest.*``.
"""

from __future__ import annotations

import base64
import threading
import time
from dataclasses import dataclass
from typing import FrozenSet, Iterable, List, Optional

from ..obs.spans import content_hash

BACKPRESSURE_POLICIES = ("block", "drop_oldest", "reject")


@dataclass(frozen=True)
class IngestItem:
    """One queued publish: identity, payload, and its ingest timestamp
    (host clock at ``push`` — the start of the ingest→delivery latency the
    engine measures exactly)."""

    seq: int            # ring-assigned, monotonically increasing
    topic: int
    publisher: int
    payload: bytes
    valid: bool         # upstream validation verdict (gates relay on device)
    t_ingest: float     # host clock at push


class IngestRing:
    """Bounded FIFO ring of :class:`IngestItem` with explicit backpressure.

    Thread-safe; ``push`` and ``pop_batch`` may run from different threads.
    Zero-length payloads are legal (a bare topic beacon is a real pubsub
    message shape).
    """

    def __init__(
        self,
        capacity: int,
        policy: str = "block",
        metrics=None,
        clock=time.monotonic,
        tracer=None,
    ) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if policy not in BACKPRESSURE_POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r}; "
                f"have: {', '.join(BACKPRESSURE_POLICIES)}"
            )
        self.capacity = capacity
        self.policy = policy
        self.metrics = metrics
        self.tracer = tracer   # optional obs.SpanLedger (ring_accept stamps)
        self._clock = clock
        self._buf: List[Optional[IngestItem]] = [None] * capacity
        self._head = 0          # index of the oldest item
        self._size = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self.max_depth = 0
        self._accepted = 0
        self._popped = 0
        self._dropped_oldest = 0
        self._dropped_oldest_valid = 0
        self._rejected = 0
        self._block_waits = 0
        self._shed_topics: FrozenSet[int] = frozenset()
        self._shed_priority = 0
        # The active policy is a gauge from birth (r20): its index in
        # BACKPRESSURE_POLICIES, so /metrics shows which backpressure mode
        # is live without scraping tier logs.
        self._metric_gauge(
            "serve.ingest.policy", BACKPRESSURE_POLICIES.index(policy)
        )

    # -- producer side ------------------------------------------------------

    def push(
        self,
        topic: int,
        payload: bytes,
        publisher: int,
        valid: bool = True,
        timeout: Optional[float] = None,
    ) -> bool:
        """Enqueue one publish; returns True iff it was admitted.

        ``timeout`` only applies under the ``block`` policy (None = wait
        forever).  A False return means the CALLER still owns the message —
        the ring never took it, so nothing was dropped silently.
        """
        with self._lock:
            if int(topic) in self._shed_topics:
                self._shed_priority += 1
                self._metric_inc("serve.ingest.shed_priority")
                return False
            if self._size >= self.capacity:
                if self.policy == "reject":
                    self._rejected += 1
                    self._metric_inc("serve.ingest.rejected")
                    return False
                if self.policy == "drop_oldest":
                    self._evict_oldest_locked()
                else:  # block
                    self._block_waits += 1
                    self._metric_inc("serve.ingest.block_waits")
                    if not self._not_full.wait_for(
                        lambda: self._size < self.capacity, timeout=timeout
                    ):
                        self._rejected += 1
                        self._metric_inc("serve.ingest.rejected")
                        return False
            item = IngestItem(
                seq=self._seq,
                topic=int(topic),
                publisher=int(publisher),
                payload=bytes(payload),
                valid=bool(valid),
                t_ingest=self._clock(),
            )
            self._seq += 1
            self._buf[(self._head + self._size) % self.capacity] = item
            self._size += 1
            self._accepted += 1
            self.max_depth = max(self.max_depth, self._size)
            self._metric_inc("serve.ingest.accepted")
            self._metric_depth()
            if self.tracer is not None and item.valid:
                self.tracer.stamp(
                    content_hash(item.topic, item.publisher, item.payload),
                    "ring_accept", t=item.t_ingest,
                    seq=item.seq, topic=item.topic,
                )
            return True

    # -- consumer side ------------------------------------------------------

    def pop_batch(self, max_n: int) -> List[IngestItem]:
        """Dequeue up to ``max_n`` items in FIFO order (may be empty)."""
        out: List[IngestItem] = []
        with self._lock:
            take = min(max_n, self._size)
            for _ in range(take):
                item = self._buf[self._head]
                assert item is not None
                self._buf[self._head] = None
                self._head = (self._head + 1) % self.capacity
                self._size -= 1
                out.append(item)
            self._popped += len(out)
            if out:
                self._not_full.notify_all()
                self._metric_depth()
        return out

    # -- degradation controls (driven by the serve watchdog) ----------------

    def set_shed_topics(self, topics: Iterable[int]) -> None:
        """Install the shed set: pushes for these topics are refused at the
        door and counted under ``shed_priority``.  Pass an empty iterable to
        clear.  The refusal is loud (counter + metric) and caller-owned —
        it never appears in the silent-drop residual."""
        with self._lock:
            self._shed_topics = frozenset(int(t) for t in topics)
            self._metric_gauge(
                "serve.ingest.shed_topics", len(self._shed_topics)
            )

    def set_policy(self, policy: str) -> None:
        """Swap the backpressure policy at runtime (watchdog tier 2 moves
        block→drop_oldest under sustained overload, and back)."""
        if policy not in BACKPRESSURE_POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r}; "
                f"have: {', '.join(BACKPRESSURE_POLICIES)}"
            )
        with self._lock:
            self.policy = policy
            self._metric_gauge(
                "serve.ingest.policy", BACKPRESSURE_POLICIES.index(policy)
            )
            # Leaving `block` must release anyone parked on the condition so
            # they re-evaluate under the new policy.
            self._not_full.notify_all()

    # -- checkpoint ----------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-safe snapshot of buffer contents + the full ledger, taken
        under the lock (payloads base64-encoded)."""
        with self._lock:
            items = []
            for i in range(self._size):
                item = self._buf[(self._head + i) % self.capacity]
                assert item is not None
                items.append({
                    "seq": item.seq,
                    "topic": item.topic,
                    "publisher": item.publisher,
                    "payload": base64.b64encode(item.payload).decode("ascii"),
                    "valid": item.valid,
                    "t_ingest": item.t_ingest,
                })
            return {
                "capacity": self.capacity,
                "policy": self.policy,
                "items": items,
                "counters": {
                    "seq": self._seq,
                    "accepted": self._accepted,
                    "popped": self._popped,
                    "dropped_oldest": self._dropped_oldest,
                    "dropped_oldest_valid": self._dropped_oldest_valid,
                    "rejected": self._rejected,
                    "block_waits": self._block_waits,
                    "shed_priority": self._shed_priority,
                    "max_depth": self.max_depth,
                },
            }

    def restore_snapshot(self, snap: dict) -> int:
        """Reinstate buffer contents and counters from :meth:`snapshot`.

        Counters are restored VERBATIM — replayed items were already counted
        as accepted at their original admission, so restoring must not go
        through ``push`` (that would double-count ``accepted`` and turn the
        conservation residual negative).  Returns the number of queued
        items reinstated for replay."""
        items = snap["items"]
        if len(items) > self.capacity:
            raise ValueError(
                f"snapshot holds {len(items)} items but ring capacity is "
                f"{self.capacity}"
            )
        counters = snap["counters"]
        with self._lock:
            self._buf = [None] * self.capacity
            for i, d in enumerate(items):
                self._buf[i] = IngestItem(
                    seq=int(d["seq"]),
                    topic=int(d["topic"]),
                    publisher=int(d["publisher"]),
                    payload=base64.b64decode(d["payload"]),
                    valid=bool(d["valid"]),
                    t_ingest=float(d["t_ingest"]),
                )
            self._head = 0
            self._size = len(items)
            self._seq = int(counters["seq"])
            self._accepted = int(counters["accepted"])
            self._popped = int(counters["popped"])
            self._dropped_oldest = int(counters["dropped_oldest"])
            self._dropped_oldest_valid = int(
                counters.get("dropped_oldest_valid", 0)
            )
            self._rejected = int(counters["rejected"])
            self._block_waits = int(counters["block_waits"])
            self._shed_priority = int(counters.get("shed_priority", 0))
            self.max_depth = int(counters["max_depth"])
            self._not_full.notify_all()
            self._metric_depth()
            return self._size

    # -- introspection ------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return self._size

    def accounting(self) -> dict:
        """Conservation ledger.  ``silent_drops`` is the residual between
        what was accepted and what is accounted for — the streaming SLO's
        zero-silent-drops channel reads it directly."""
        with self._lock:
            silent = (
                self._accepted - self._popped - self._dropped_oldest
                - self._size
            )
            valid_in_queue = sum(
                1 for i in range(self._size)
                if self._buf[(self._head + i) % self.capacity].valid
            )
            return {
                "accepted": self._accepted,
                "popped": self._popped,
                "in_queue": self._size,
                "valid_in_queue": valid_in_queue,
                "dropped_oldest": self._dropped_oldest,
                "dropped_oldest_valid": self._dropped_oldest_valid,
                "rejected": self._rejected,
                "block_waits": self._block_waits,
                "shed_priority": self._shed_priority,
                "max_depth": self.max_depth,
                "silent_drops": silent,
            }

    # -- internals ----------------------------------------------------------

    def _evict_oldest_locked(self) -> None:
        victim = self._buf[self._head]
        self._buf[self._head] = None
        self._head = (self._head + 1) % self.capacity
        self._size -= 1
        self._dropped_oldest += 1
        if victim is not None and victim.valid:
            self._dropped_oldest_valid += 1
        self._metric_inc("serve.ingest.dropped_oldest")

    def _metric_inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def _metric_gauge(self, name: str, value) -> None:
        if self.metrics is not None:
            self.metrics.gauge(name, value)

    def _metric_depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve.ingest.depth", self._size)
