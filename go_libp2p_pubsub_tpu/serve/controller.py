"""Self-tuning serving plane: the loop from telemetry back to the knobs.

Every perf knob on the serving plane used to be a static flag chosen at
init; real deployments drift (diurnal ramps, burst storms, loss-regime
shifts — the Filecoin/ETH2 measurement literature), so ROADMAP item 4 asks
for a controller that closes the loop from the telemetry plane to the
runtime controls.  Same design rules as ``net/policy.py`` and
``.watchdog``: no threads, no wall-clock reads outside the injected
``clock``, every transition counted and attributable — the controller is
*polled* by whoever owns the serving loop, once per chunk boundary.

Each poll reads the live pressure signals — ring depth vs the current
geometry's drain rate, the *carry* of pending messages across chunk
boundaries (the loss-regime signature: propagation outrunning the chunk
length), chunk wall vs checkpoint wall, verify-stage wall from the shared
:class:`~..utils.metrics.MetricsRegistry` — and moves knobs in two
classes:

- runtime knobs, through the existing ``set_*`` controls: backpressure
  policy (``IngestRing.set_policy``), shed watermarks (the watchdog's,
  retuned to the active geometry so the degradation ladder and the tuner
  are ONE composed control surface), snapshot cadence
  (``engine.snapshot_every``), verify batch grouping
  (``ValidationPipeline.flush_threshold``);
- the chunk geometry, which DOES recompile — except the engine pre-warms a
  bounded ladder of geometries on one jitted rollout
  (:meth:`~.engine.StreamingEngine.set_geometry`), so stepping the ladder
  never compiles: ``compile_cache_size() == ladder_size()`` holds across
  the whole run, crash/restore included.

Every decision is stamped into the span/trace plane as a
``controller_decision`` ledger event carrying its triggering evidence
(depth, carry, walls), so a verdict flip is attributable to the
measurement that caused it — and mirrored as ``serve.controller.*``
gauges on /metrics.

The desired-policy handshake (r20 satellite fix): the watchdog's tier-2
escalation overrides the ring policy, and its DE-escalation restores the
controller's ``KnobState.backpressure_policy`` — the single source of
truth — not the policy memorized at construction.  Symmetrically, the
controller never writes the ring policy while the watchdog holds tier 2.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .tuning import ChunkGeometry, ControllerPolicy, Decision, KnobState


class Controller:
    """Poll-driven tuner over one engine + ring (+ optional watchdog and
    validation pipeline), sharing their injected clock and registry."""

    def __init__(
        self,
        engine,
        ring,
        policy: Optional[ControllerPolicy] = None,
        watchdog=None,
        pipe=None,
        metrics=None,
        tracer=None,
        clock=time.monotonic,
    ) -> None:
        self.engine = engine
        self.ring = ring
        self.policy = policy if policy is not None else ControllerPolicy()
        self.watchdog = watchdog
        self.pipe = pipe
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        # The calm rung is the engine's constructed geometry (the spec's
        # static choice) — where the controller returns after pressure.
        self._calm = engine.geometry
        self.knobs = KnobState(
            geometry_index=self._ladder_index(engine.geometry),
            backpressure_policy=ring.policy,
            snapshot_every=int(getattr(engine, "snapshot_every", 0)),
            flush_threshold=(
                int(pipe.flush_threshold) if pipe is not None else 0
            ),
            high_watermark=(
                int(watchdog.high_watermark) if watchdog is not None else 0
            ),
            low_watermark=(
                int(watchdog.low_watermark) if watchdog is not None else 0
            ),
        )
        # The spec's policy: what "calm" restores the backpressure knob to.
        self._base_policy = ring.policy
        self.decisions: List[Decision] = []
        self.polls = 0
        self._pending_age: Dict[Any, int] = {}
        self._calm_polls = 0
        self._last_block_waits = 0
        self._last_restores = int(getattr(engine, "restores", 0))
        if watchdog is not None:
            # One composed control surface: the watchdog consults the
            # controller's KnobState for the policy de-escalation restores.
            watchdog.controller = self
        self._export_gauges()

    # -- the control loop ----------------------------------------------------

    def poll(self) -> List[Decision]:
        """One tuning pass at a chunk boundary; returns the decisions it
        took (possibly empty).  Reads only host-side telemetry — never
        touches device state — so a poll costs microseconds."""
        self.polls += 1
        # The engine's live geometry is ground truth: a restore may have
        # adopted the snapshot's rung behind the controller's back.
        self.knobs.geometry_index = self._ladder_index(self.engine.geometry)
        ev = self._evidence()
        new: List[Decision] = []
        new += self._tune_geometry(ev)
        new += self._tune_snapshot_cadence(ev)
        new += self._tune_flush_threshold(ev)
        new += self._tune_backpressure(ev)
        if self.metrics is not None:
            self.metrics.inc("serve.controller.polls")
        self._export_gauges()
        return new

    def reattach(self, engine, ring) -> None:
        """Point the tuner at a replacement engine+ring pair (the staged
        crash path discards both).  The knob state and the decision record
        SURVIVE — they are the controller's memory, and the watchdog's
        ``reattach`` re-applies the desired policy from them — while
        per-pair baselines (pending ages, ring counters, the restore
        count) reset to the new pair's."""
        self.engine = engine
        self.ring = ring
        self._pending_age = {}
        self._calm_polls = 0
        self._last_block_waits = 0
        self._last_restores = int(getattr(engine, "restores", 0))
        self.knobs.geometry_index = self._ladder_index(engine.geometry)
        self._export_gauges()

    def controls(self) -> Dict[str, Any]:
        """JSON-safe digest for /debug/obs: the live knob values, the
        watchdog tier, and the most recent decisions."""
        doc: Dict[str, Any] = {
            "knobs": self.knobs.to_dict(),
            "geometry": list(self.engine.geometry.as_tuple()),
            "ladder": [list(g.as_tuple()) for g in self.engine.ladder],
            "ring_policy": self.ring.policy,
            "decisions": [d.to_dict() for d in self.decisions[-8:]],
            "n_decisions": len(self.decisions),
            "polls": self.polls,
        }
        if self.watchdog is not None:
            doc["watchdog_tier"] = self.watchdog.tier
            doc["watchdog_tier_name"] = self.watchdog.tier_name
        return doc

    # -- evidence ------------------------------------------------------------

    def _evidence(self) -> Dict[str, Any]:
        """The poll's measurement snapshot — attached verbatim to every
        decision it triggers."""
        eng = self.engine
        depth = self.ring.depth
        # Carry: how many chunk boundaries the oldest pending message has
        # survived, keyed on the engine's chunk counter (NOT on polls — a
        # poll with no intervening chunk must not age anything).  Carry 1
        # (published near a chunk's end, completing next chunk) is normal;
        # carry >= carry_up_chunks means rounds-to-deliver exceeds the
        # chunk length — the loss-regime signature.
        cr = int(eng.chunks_run)
        live = set(eng.pending.keys())
        self._pending_age = {
            k: self._pending_age.get(k, cr) for k in live
        }
        carry = max(
            (cr - first for first in self._pending_age.values()), default=0
        )
        wall = float(getattr(eng, "last_chunk_wall_s", 0.0))
        snaps = int(getattr(eng, "snapshots_taken", 0))
        avg_snap_s = (
            float(getattr(eng, "snapshot_seconds", 0.0)) / snaps
            if snaps else 0.0
        )
        verify_s = None
        verify_batch = 0
        if self.metrics is not None:
            verify_s = self.metrics.latest("crypto.pipeline.verify_s")
            vb = self.metrics.latest("crypto.pipeline.batch")
            verify_batch = int(vb) if vb is not None else 0
        acct_waits = 0
        try:
            acct_waits = int(self.ring.accounting()["block_waits"])
        except Exception:
            pass
        return {
            "depth": int(depth),
            "capacity": int(self.ring.capacity),
            "slots": int(eng.geometry.slots),
            "carry": int(carry),
            "chunk_wall_s": wall,
            "avg_snapshot_s": avg_snap_s,
            "verify_s": float(verify_s) if verify_s is not None else 0.0,
            "verify_batch": verify_batch,
            "block_waits": acct_waits,
            "tier": (self.watchdog.tier if self.watchdog is not None else 0),
        }

    # -- knob movers ---------------------------------------------------------

    def _tune_geometry(self, ev: Dict[str, Any]) -> List[Decision]:
        eng = self.engine
        if eng.ladder_size() < 2:
            return []
        pol = self.policy
        cur = eng.geometry
        depth_pressure = ev["depth"] >= pol.depth_up_frac * cur.slots
        carry_pressure = ev["carry"] >= pol.carry_up_chunks
        target: Optional[ChunkGeometry] = None
        reason = ""
        if carry_pressure:
            # Propagation outruns the chunk: pick the longest rung so one
            # dispatch covers the delayed rounds (ties: widest drains too).
            target = max(
                eng.ladder, key=lambda g: (g.chunk_steps, g.slots)
            )
            reason = (
                f"pending carry {ev['carry']} chunks >= "
                f"{pol.carry_up_chunks}: rounds-to-deliver outrun "
                f"chunk_steps {cur.chunk_steps}"
            )
        elif depth_pressure:
            # Backlog outruns the drain rate: pick the widest rung (ties:
            # shortest wall).
            target = max(
                eng.ladder, key=lambda g: (g.slots, -g.chunk_steps)
            )
            reason = (
                f"depth {ev['depth']} >= "
                f"{pol.depth_up_frac:.2f} x {cur.slots} slots"
            )
        if target is not None and target.as_tuple() != cur.as_tuple():
            self._calm_polls = 0
            return self._apply_geometry(target, reason, ev)
        # De-escalation: hysteretic return to the calm rung.
        calm_now = (
            ev["depth"] <= pol.depth_down_frac * self._calm.slots
            and ev["carry"] == 0
        )
        self._calm_polls = self._calm_polls + 1 if calm_now else 0
        if (
            self._calm_polls >= pol.cooldown_polls
            and cur.as_tuple() != self._calm.as_tuple()
        ):
            self._calm_polls = 0
            return self._apply_geometry(
                self._calm,
                f"calm for {pol.cooldown_polls} polls (depth "
                f"{ev['depth']} <= {pol.depth_down_frac:.2f} x "
                f"{self._calm.slots}, carry 0)",
                ev,
            )
        return []

    def _apply_geometry(
        self, target: ChunkGeometry, reason: str, ev: Dict[str, Any]
    ) -> List[Decision]:
        old = self.engine.geometry
        self.engine.set_geometry(*target.as_tuple())
        self.knobs.geometry_index = self._ladder_index(target)
        out = [self._decide(
            "geometry",
            f"{old.chunk_steps}x{old.pub_width}",
            f"{target.chunk_steps}x{target.pub_width}",
            reason, ev,
        )]
        # Composed control surface: the watchdog's shed watermarks follow
        # the active drain rate, so "overloaded" always means "more than
        # the CURRENT geometry can drain", not the construction-time one.
        if self.watchdog is not None:
            high = min(
                self.ring.capacity,
                max(2, int(self.policy.watermark_high_chunks * target.slots)),
            )
            low = min(max(0, target.slots // 2), high - 1)
            old_marks = (
                self.watchdog.high_watermark, self.watchdog.low_watermark
            )
            if (high, low) != old_marks:
                self.watchdog.high_watermark = high
                self.watchdog.low_watermark = low
                self.knobs.high_watermark = high
                self.knobs.low_watermark = low
                out.append(self._decide(
                    "watermarks",
                    f"{old_marks[0]}/{old_marks[1]}",
                    f"{high}/{low}",
                    f"retuned to geometry "
                    f"{target.chunk_steps}x{target.pub_width} "
                    f"({target.slots} slots/chunk)",
                    ev,
                ))
        return out

    def _tune_snapshot_cadence(self, ev: Dict[str, Any]) -> List[Decision]:
        eng = self.engine
        if self.knobs.snapshot_every < 1 or eng.snapshot_path is None:
            return []      # snapshots disabled: nothing to pace
        pol = self.policy
        cur = self.knobs.snapshot_every
        restores = int(getattr(eng, "restores", 0))
        crashed = restores > self._last_restores
        self._last_restores = restores
        new = cur
        reason = ""
        if crashed:
            # A restore just happened: tighten to the floor — the cheapest
            # moment to buy back durability is right after paying for its
            # absence.
            new = pol.snapshot_every_min
            reason = f"restore observed (restores={restores}): tighten"
        elif ev["chunk_wall_s"] > 0.0 and ev["avg_snapshot_s"] > 0.0:
            frac = ev["avg_snapshot_s"] / (cur * ev["chunk_wall_s"])
            if frac > pol.snapshot_cost_frac:
                new = min(pol.snapshot_every_max, cur * 2)
                reason = (
                    f"checkpoint wall {frac:.2f} of chunk wall > "
                    f"{pol.snapshot_cost_frac:.2f}: stretch"
                )
            elif frac < pol.snapshot_cost_frac / 4 and cur > \
                    pol.snapshot_every_min:
                new = max(pol.snapshot_every_min, cur // 2)
                reason = (
                    f"checkpoint wall {frac:.2f} of chunk wall < "
                    f"{pol.snapshot_cost_frac / 4:.2f}: tighten"
                )
        if new == cur:
            return []
        eng.snapshot_every = new
        self.knobs.snapshot_every = new
        return [self._decide("snapshot_every", cur, new, reason, ev)]

    def _tune_flush_threshold(self, ev: Dict[str, Any]) -> List[Decision]:
        if self.pipe is None:
            return []
        pol = self.policy
        cur = int(self.pipe.flush_threshold)
        # Only tune while the threshold BINDS (the last verify batch
        # actually filled it): when submit volume never reaches the
        # threshold, batch grouping is set by the caller's flush cadence
        # and moving the knob would be evidence-free churn.
        if ev["verify_batch"] < cur:
            return []
        new = cur
        reason = ""
        if (
            ev["chunk_wall_s"] > 0.0
            and ev["verify_s"] > pol.verify_cost_frac * ev["chunk_wall_s"]
            and cur > pol.flush_threshold_min
        ):
            new = max(pol.flush_threshold_min, cur // 2)
            reason = (
                f"verify wall {ev['verify_s']:.4f}s > "
                f"{pol.verify_cost_frac:.2f} x chunk wall "
                f"{ev['chunk_wall_s']:.4f}s at a full batch: split batches"
            )
        elif (
            ev["chunk_wall_s"] > 0.0
            and ev["verify_s"] < pol.verify_cost_frac * ev["chunk_wall_s"] / 4
            and cur < pol.flush_threshold_max
        ):
            new = min(pol.flush_threshold_max, cur * 2)
            reason = (
                f"verify wall {ev['verify_s']:.4f}s well under chunk wall "
                "at a full batch: regroup larger"
            )
        if new == cur:
            return []
        self.pipe.flush_threshold = new
        self.knobs.flush_threshold = new
        return [self._decide("flush_threshold", cur, new, reason, ev)]

    def _tune_backpressure(self, ev: Dict[str, Any]) -> List[Decision]:
        pol_cur = self.knobs.backpressure_policy
        waits = ev["block_waits"]
        blocked_since = waits - self._last_block_waits
        self._last_block_waits = waits
        want = pol_cur
        reason = ""
        if (
            pol_cur == "block"
            and blocked_since > 0
            and ev["depth"] >= ev["capacity"]
        ):
            # Producers are parking on a full ring: fail fast instead of
            # stalling the whole ingest path (every rejection is counted,
            # caller-owned — never a silent drop).
            want = "reject"
            reason = (
                f"{blocked_since} producer waits on a full ring "
                f"(depth {ev['depth']} = capacity): fail fast"
            )
        elif (
            pol_cur != self._base_policy
            and ev["depth"] <= self.policy.depth_down_frac * ev["capacity"]
            and ev["carry"] == 0
        ):
            want = self._base_policy
            reason = (
                f"depth {ev['depth']} back under "
                f"{self.policy.depth_down_frac:.2f} x capacity: restore "
                "the configured policy"
            )
        if want == pol_cur:
            return []
        self.knobs.backpressure_policy = want
        # The watchdog's tier 2 owns the LIVE ring policy while escalated;
        # the knob state still records the controller's desire, and the
        # de-escalation path restores it (the single-source-of-truth fix).
        if self.watchdog is None or self.watchdog.tier < 2:
            self.ring.set_policy(want)
        return [self._decide("backpressure_policy", pol_cur, want,
                             reason, ev)]

    # -- bookkeeping ---------------------------------------------------------

    def _ladder_index(self, geom: ChunkGeometry) -> int:
        for i, g in enumerate(self.engine.ladder):
            if g.as_tuple() == geom.as_tuple():
                return i
        raise ValueError(
            f"geometry {geom.as_tuple()} is not on the engine's ladder"
        )

    def _decide(
        self, knob: str, old, new, reason: str, ev: Dict[str, Any]
    ) -> Decision:
        d = Decision(
            t=self.clock(), knob=knob, old=old, new=new, reason=reason,
            evidence=dict(ev),
        )
        self.decisions.append(d)
        if self.metrics is not None:
            self.metrics.inc("serve.controller.decisions")
            self.metrics.inc(f"serve.controller.decisions.{knob}")
        if self.tracer is not None:
            # The span plane is the audit log: every decision lands as a
            # ledger event with its evidence, so a verdict flip is
            # attributable to the measurement that triggered it.
            self.tracer.event(
                "controller_decision", t=d.t, knob=knob,
                old=str(old), new=str(new), reason=reason,
                **{f"ev_{k}": v for k, v in ev.items()},
            )
        return d

    def _export_gauges(self) -> None:
        if self.metrics is None:
            return
        g = self.engine.geometry
        self.metrics.gauge(
            "serve.controller.geometry_index", self.knobs.geometry_index
        )
        self.metrics.gauge("serve.controller.chunk_steps", g.chunk_steps)
        self.metrics.gauge("serve.controller.pub_width", g.pub_width)
        self.metrics.gauge(
            "serve.controller.snapshot_every", self.knobs.snapshot_every
        )
        self.metrics.gauge(
            "serve.controller.flush_threshold", self.knobs.flush_threshold
        )
        from .ingest import BACKPRESSURE_POLICIES

        self.metrics.gauge(
            "serve.controller.desired_policy",
            BACKPRESSURE_POLICIES.index(self.knobs.backpressure_policy),
        )
