"""Streaming serving plane: resident rollout fed by a host ingest ring.

The closed-loop bench replays a fixed signed window inside one scan; this
package is the serving shape the BASELINE north star actually describes —
an unbounded publish stream flowing through a host-side ring buffer
(:mod:`.ingest`) into a device-resident chunked rollout (:mod:`.engine`)
whose compiled program never changes shape, so the stream rides one XLA
compilation for its whole lifetime.

Crash safety lives in the same package: the engine writes atomic durable
snapshots and restores from them without recompiling (:mod:`.engine`),
supervised by a fake-clock-testable watchdog that restarts wedged engines
and walks explicit degradation tiers under overload (:mod:`.watchdog`).

Self-tuning lives here too (r20): a poll-driven controller
(:mod:`.controller`) closes the loop from the telemetry plane back to the
runtime knobs — and steps a pre-warmed ladder of chunk geometries
(:mod:`.tuning`) with zero unplanned recompiles.
"""

from .controller import Controller
from .engine import PendingMessage, StreamingEngine, content_hash
from .ingest import BACKPRESSURE_POLICIES, IngestItem, IngestRing
from .tuning import ChunkGeometry, ControllerPolicy, Decision, KnobState
from .watchdog import TIER_NAMES, Watchdog

__all__ = [
    "BACKPRESSURE_POLICIES",
    "ChunkGeometry",
    "Controller",
    "ControllerPolicy",
    "Decision",
    "IngestItem",
    "IngestRing",
    "KnobState",
    "PendingMessage",
    "StreamingEngine",
    "TIER_NAMES",
    "Watchdog",
    "content_hash",
]
