"""Streaming serving plane: resident rollout fed by a host ingest ring.

The closed-loop bench replays a fixed signed window inside one scan; this
package is the serving shape the BASELINE north star actually describes —
an unbounded publish stream flowing through a host-side ring buffer
(:mod:`.ingest`) into a device-resident chunked rollout (:mod:`.engine`)
whose compiled program never changes shape, so the stream rides one XLA
compilation for its whole lifetime.
"""

from .engine import PendingMessage, StreamingEngine
from .ingest import BACKPRESSURE_POLICIES, IngestItem, IngestRing

__all__ = [
    "BACKPRESSURE_POLICIES",
    "IngestItem",
    "IngestRing",
    "PendingMessage",
    "StreamingEngine",
]
