"""Tuning-plane data: the controller's policy, state, and decision record.

The serving plane's knobs split into two classes.  *Runtime* knobs swap
without touching the compiled program — backpressure policy, shed
watermarks, snapshot cadence, verify batch grouping — and the controller
moves them through the existing ``set_*`` controls.  The one knob that
recompiles is the chunk *geometry* (``chunk_steps`` scan rows x
``pub_width`` publish slots): for that the engine pre-warms a small, fixed
ladder of geometries on the SAME jitted rollout, so the controller can step
along the ladder at a chunk boundary with zero unplanned recompiles
(``compile_cache_size() == ladder size`` is the contract, crash/restore
included).

Everything here is pure data, in the spec-module style: dataclasses with
loud validation, JSON-safe ``to_dict`` forms, no behavior.  The behavior
lives in :mod:`.controller`.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class ChunkGeometry:
    """One rung of the pre-warmed ladder: the compiled chunk's fixed event
    shape.  ``slots`` is the chunk's publish throughput (items drained per
    dispatch); ``chunk_steps`` is the device rounds one dispatch advances —
    the two axes the controller trades off (wide chunks drain bursts, long
    chunks cover delayed propagation under loss)."""

    chunk_steps: int
    pub_width: int

    def __post_init__(self) -> None:
        if self.chunk_steps < 1 or self.pub_width < 1:
            raise ValueError("chunk_steps and pub_width must be >= 1")

    @property
    def slots(self) -> int:
        return self.chunk_steps * self.pub_width

    def as_tuple(self) -> Tuple[int, int]:
        return (self.chunk_steps, self.pub_width)


@dataclass(frozen=True)
class ControllerPolicy:
    """The controller's reaction thresholds — all poll-relative, no wall
    clock, so a fake-clock test drives every branch deterministically.

    Geometry selection reads two pressure signals each poll:

    - *depth pressure*: ring depth vs the current geometry's ``slots``
      (``depth >= depth_up_frac * slots`` wants more slots);
    - *carry pressure*: the max number of chunk boundaries any pending
      message has survived (``carry >= carry_up_chunks`` means propagation
      outruns the chunk length — the loss-regime signature — and wants
      more ``chunk_steps``).

    De-escalation is hysteretic: only after ``cooldown_polls`` consecutive
    calm polls (depth below ``depth_down_frac`` of the CALM geometry's
    slots and no carry) does the controller step back to the calm rung.
    """

    # Geometry ladder triggers.
    depth_up_frac: float = 0.75
    depth_down_frac: float = 0.5
    carry_up_chunks: int = 2
    cooldown_polls: int = 2
    # Snapshot cadence: stretch when checkpoint wall dominates chunk wall,
    # tighten back toward the floor when calm.
    snapshot_every_min: int = 1
    snapshot_every_max: int = 8
    snapshot_cost_frac: float = 0.25
    # Verify batch grouping: halve the flush threshold when verify wall
    # dominates, double it back (bounded) when verify is cheap.
    flush_threshold_min: int = 64
    flush_threshold_max: int = 1 << 20
    verify_cost_frac: float = 0.5
    # Watermark composition: on a geometry switch the watchdog's shed
    # watermarks are retuned to the new drain rate — high at
    # ``watermark_high_chunks`` chunks of backlog, low at half a chunk.
    watermark_high_chunks: float = 2.0

    def __post_init__(self) -> None:
        if not (0.0 < self.depth_down_frac < self.depth_up_frac):
            raise ValueError(
                "need 0 < depth_down_frac < depth_up_frac "
                f"(got {self.depth_down_frac} / {self.depth_up_frac})"
            )
        if self.carry_up_chunks < 1:
            raise ValueError("carry_up_chunks must be >= 1")
        if self.cooldown_polls < 1:
            raise ValueError("cooldown_polls must be >= 1")
        if not (1 <= self.snapshot_every_min <= self.snapshot_every_max):
            raise ValueError(
                "need 1 <= snapshot_every_min <= snapshot_every_max"
            )
        if not (1 <= self.flush_threshold_min <= self.flush_threshold_max):
            raise ValueError(
                "need 1 <= flush_threshold_min <= flush_threshold_max"
            )
        if self.watermark_high_chunks <= 0.5:
            raise ValueError("watermark_high_chunks must be > 0.5")


@dataclass
class KnobState:
    """The single source of truth for every runtime knob the controller
    owns.  The watchdog reads ``backpressure_policy`` here on de-escalation
    (instead of the policy it memorized at construction), so a controller
    retune mid-escalation is never reverted by the tier ladder — the two
    control surfaces compose through this one record."""

    geometry_index: int = 0
    backpressure_policy: str = "block"
    snapshot_every: int = 0
    flush_threshold: int = 4096
    high_watermark: int = 0
    low_watermark: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class Decision:
    """One controller action: which knob moved, from what to what, and the
    evidence that triggered it.  Stamped verbatim into the span ledger
    (``controller_decision`` events) so a verdict flip is attributable to
    the measurement that caused it."""

    t: float
    knob: str
    old: Any
    new: Any
    reason: str
    evidence: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "t": self.t,
            "knob": self.knob,
            "old": self.old,
            "new": self.new,
            "reason": self.reason,
            "evidence": dict(self.evidence),
        }


def validate_ladder(
    ladder, base: Tuple[int, int]
) -> List[ChunkGeometry]:
    """Normalize a geometry ladder (sequence of (chunk_steps, pub_width)
    pairs or :class:`ChunkGeometry`) and require it to contain ``base`` —
    the engine's constructed geometry must be a rung, or the pre-warm
    contract (cache size == ladder size) could not hold."""
    rungs: List[ChunkGeometry] = []
    for g in ladder:
        if isinstance(g, ChunkGeometry):
            rungs.append(g)
        else:
            steps, width = g
            rungs.append(ChunkGeometry(int(steps), int(width)))
    if len(rungs) < 1:
        raise ValueError("geometry ladder must have at least one rung")
    if len({r.as_tuple() for r in rungs}) != len(rungs):
        raise ValueError("geometry ladder has duplicate rungs")
    if tuple(base) not in {r.as_tuple() for r in rungs}:
        raise ValueError(
            f"engine geometry {tuple(base)} is not on the ladder "
            f"{[r.as_tuple() for r in rungs]}"
        )
    return rungs
