"""Device-resident streaming rollout: one compiled chunk, replayed forever.

The scenario engine's trick (``ops/schedule.py``) was to make a whole
campaign the ``xs`` of one ``lax.scan``; the streaming engine turns that
inside out — the *shapes* of the event tensors are frozen once
(``chunk_steps`` scan rows x ``pub_width`` publish slots, padded with the
schedule's ``-1`` sentinels and gated by the model's ``lax.cond``
publishes) and every chunk replays the SAME compiled program on freshly
filled tensors.  GossipSub state flows chunk-to-chunk through donated
buffers, so an unbounded publish stream rides one XLA compilation with no
per-chunk allocation of the resident state.

Latency is exact, not modeled: each message carries the host-clock
timestamp its :class:`~.ingest.IngestRing` ``push`` stamped, and the engine
reports ingest→delivery as host seconds from that stamp to the end of the
chunk in which the message's delivered count crossed the completion
threshold.  The quantization this implies (delivery is observed at chunk
boundaries, so latencies are rounded UP to the next boundary) is a
documented property of the measurement, not an approximation inside it.

The flight-recorder tail (the last round of every in-scan telemetry
channel, including the latency histogram) is carried across chunks so a
scrape mid-stream sees current telemetry without any extra device work.

Crash safety (r14): ``snapshot()`` writes a durable checkpoint — device
state + flight tail as the array payload, every piece of host bookkeeping
(slot cursors, pending/publish logs, dedup hashes) plus the ingest ring's
buffer and conservation ledger as JSON meta — through the same atomic
write→fsync→rename path as ``utils/checkpoint``.  ``restore()`` on a
warmed engine resumes from the last chunk boundary WITHOUT recompiling:
the resident program lives in a module-level cache keyed on the model's
value semantics, so a freshly constructed engine over an equal model (the
crash-restart path) reuses the already-compiled chunk.  Replayed
accepted-but-undelivered ring messages are deduplicated by content hash
(topic ‖ publisher ‖ payload) at publish time, making delivery
exactly-once across a crash even when producers resubmit at-least-once.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

try:
    # Dynamic, thread-local override of the persistent-compile-cache floor,
    # read by jax's _cache_write per compilation (verified on 0.4.37).
    from jax._src.config import (
        persistent_cache_min_compile_time_secs as _persistent_cache_floor,
    )
except ImportError:  # pragma: no cover - jax moved the State: global flip

    @contextlib.contextmanager
    def _persistent_cache_floor(value):
        old = jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update("jax_persistent_cache_min_compile_time_secs", value)
        try:
            yield
        finally:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", old)

from ..models.multitopic import MultiTopicGossipSub
from ..obs.spans import content_hash  # canonical definition (r18); re-exported
from ..ops import schedule as sched
from ..utils import checkpoint as ckpt
from ..utils.trace import xla_trace
from .ingest import IngestItem, IngestRing
from .tuning import ChunkGeometry, validate_ladder

# The resident program per model VALUE (models define __eq__/__hash__ over
# their config).  Keyed here — not per-engine — so the crash-restart path
# (fresh engine over an equal model) shares the compiled chunk instead of
# paying a recompile.  Engines sharing a model must keep their
# (chunk_steps, pub_width) shapes inside ONE pre-declared geometry ladder:
# each rung is a compiled variant of the same jitted rollout, so
# compile_cache_size() == ladder size (1 without a ladder) is the
# zero-unplanned-recompiles contract the tests assert.
_ROLLOUT_CACHE: Dict[MultiTopicGossipSub, object] = {}


def _resident_rollout(model: MultiTopicGossipSub):
    fn = _ROLLOUT_CACHE.get(model)
    if fn is None:
        fn = jax.jit(
            lambda st, ev: model.rollout_events(st, ev, record=True),
            donate_argnums=(0,),
        )
        _ROLLOUT_CACHE[model] = fn
    return fn


@dataclasses.dataclass
class PendingMessage:
    """A published message awaiting its completion threshold."""

    seq: int
    topic: int
    slot: int
    publisher: int
    t_ingest: float       # host clock at ring push
    t_publish: float      # host clock when its chunk was dispatched
    step_published: int   # global device step of its publish row
    chash: str = ""       # content hash (exactly-once identity)


class StreamingEngine:
    """Resident chunked rollout over a :class:`MultiTopicGossipSub`.

    ``run_chunk`` pops up to ``chunk_steps * pub_width`` ring items, packs
    them into a fixed-shape ``MultiTopicEvents`` (publishes spread
    round-robin over the chunk's rows), and invokes the donated-buffer
    compiled rollout.  ``compile_cache_size()`` must stay 1 after warmup —
    the no-recompilation contract the tests assert.
    """

    def __init__(
        self,
        model: MultiTopicGossipSub,
        ring: IngestRing,
        chunk_steps: int = 8,
        pub_width: int = 4,
        completion_frac: float = 0.99,
        seed: int = 0,
        metrics=None,
        clock=time.monotonic,
        snapshot_path: Optional[str] = None,
        snapshot_every: int = 0,
        tracer=None,
        blackbox=None,
        profile_every: int = 0,
        profile_dir: Optional[str] = None,
        geometry_ladder=None,
    ) -> None:
        if chunk_steps < 1 or pub_width < 1:
            raise ValueError("chunk_steps and pub_width must be >= 1")
        if not (0.0 < completion_frac <= 1.0):
            raise ValueError("completion_frac must be in (0, 1]")
        if snapshot_every < 0:
            raise ValueError("snapshot_every must be >= 0")
        if snapshot_every > 0 and snapshot_path is None:
            raise ValueError("snapshot_every needs a snapshot_path")
        if profile_every < 0:
            raise ValueError("profile_every must be >= 0")
        if profile_every > 0 and profile_dir is None:
            raise ValueError("profile_every needs a profile_dir")
        self.model = model
        self.ring = ring
        self.chunk_steps = chunk_steps
        self.pub_width = pub_width
        # Pre-declared chunk geometries (r20 self-tuning): the constructed
        # (chunk_steps, pub_width) must be a rung; warmup() compiles every
        # rung so set_geometry() later switches between ALREADY-compiled
        # variants — the chunk-shape knob without an unplanned recompile.
        self.ladder = validate_ladder(
            geometry_ladder if geometry_ladder is not None
            else [(chunk_steps, pub_width)],
            (chunk_steps, pub_width),
        )
        self.completion_frac = completion_frac
        self.metrics = metrics
        self._clock = clock
        self.snapshot_path = snapshot_path
        self.snapshot_every = snapshot_every
        # Observability plane (r18) — all host-side, all optional; with
        # every knob at its default the engine is bit- and counter-identical
        # to the untraced r17 behavior.
        self.tracer = tracer
        self.blackbox = blackbox
        self.profile_every = profile_every
        self.profile_dir = profile_dir
        self.profile_captures = 0
        self.latencies_exact_s: List[float] = []  # span-interpolated (traced)
        self.last_recovery_gap_s: Optional[float] = None
        self.last_chunk_wall_s = 0.0
        # Set by the watchdog just before restore() so the recovery
        # annotation on reopened spans carries the tier/reason context.
        self.recovery_context: Dict[str, str] = {}
        self.state = model.init(seed=seed)
        # The resident program: donated state in, fixed event shapes —
        # shared process-wide per model value (see _ROLLOUT_CACHE), so the
        # crash-restart path never recompiles.
        self._rollout = _resident_rollout(model)
        self._next_slot = [0] * model.t          # per-topic cyclic allocator
        self.pending: Dict[Tuple[int, int], PendingMessage] = {}
        self.latencies_s: List[float] = []       # completed, host seconds
        self.publish_log: List[PendingMessage] = []   # every VALID publish
        self.invalid_published: List[Tuple[int, int]] = []  # (topic, slot)
        self.chunks_run = 0
        # Device rounds advanced so far — an explicit accumulator, NOT
        # chunks_run * chunk_steps, because ladder switches make chunks
        # variable-length (step_published must stay device-exact across
        # geometry changes for the exact-latency interpolation).
        self.steps_run = 0
        self.geometry_switches = 0
        self.published = 0
        self.completed = 0
        self.evicted = 0       # window slot recycled before completion
        self.restores = 0
        self.replay_deduped = 0        # valid items skipped: already published
        self.duplicate_completions = 0  # same content completed twice
        self.clock_anomalies = 0       # negative ingest→delivery intervals
        self.snapshots_taken = 0
        self.snapshot_seconds = 0.0    # cumulative wall time in snapshot()
        self._seen_hashes: set = set()        # every VALID publish, ever
        self._completed_hashes: set = set()   # every completed content
        self.flight_tail: Dict[str, np.ndarray] = {}
        # Degraded-links knob: when set, every chunk's first event row
        # carries this ingress delay for all peers (schedule ``delay``
        # semantics are per-family: pend-hold for multitopic, decimation
        # loss for the hybrid).  The set is idempotent device-side, so
        # re-stamping each chunk keeps restarts and restores consistent
        # with whatever the runner last requested.
        self.ingress_delay: Optional[int] = None

    # -- lifecycle ----------------------------------------------------------

    def warmup(self) -> None:
        """Run one all-quiet chunk PER LADDER RUNG to pay every compile
        before traffic arrives (the serving analog of the bench's
        compile+warm pass), ending on the constructed geometry.  Advances
        the device state by the ladder's total idle rounds.

        Warmup chunks never auto-snapshot: on the crash-restart path a
        fresh engine warms up *before* ``restore()``, and an auto-snapshot
        here would clobber the very checkpoint it is about to restore."""
        base = (self.chunk_steps, self.pub_width)
        self._in_warmup = True
        try:
            # Base rung last, so the engine exits warmup on its
            # constructed geometry with a matching flight tail.
            for g in self.ladder:
                if g.as_tuple() == base:
                    continue
                self.chunk_steps, self.pub_width = g.as_tuple()
                self._dispatch(self._empty_events())
            self.chunk_steps, self.pub_width = base
            self._dispatch(self._empty_events())
            # The completion fold is its own jitted function, first called
            # when a real chunk folds — pay that compile here too, or the
            # first traffic-bearing chunk eats a ~100ms stall and the
            # message riding it walks straight into the latency p99.
            jax.device_get(self.model.stream_digest(self.state))
            if self.snapshot_path is not None:
                # Same reasoning for the checkpoint path: the first
                # serialization of the full state is cold (~100ms) and
                # auto-snapshots run inside run_chunk's wall.  Warm it
                # against memory only — warmup must never write
                # snapshot_path (see the restore note above).
                ckpt.warm_serialize(
                    {"state": self.state,
                     "flight_tail": dict(self.flight_tail)}
                )
        finally:
            self._in_warmup = False

    def compile_cache_size(self) -> int:
        """Number of compiled variants of the resident chunk — the ladder
        size (1 without a ladder) after warmup, and STILL the ladder size
        after any number of chunks, geometry switches, or crash/restore
        cycles — or shapes drifted (an unplanned recompile)."""
        return self._rollout._cache_size()

    def ladder_size(self) -> int:
        """Number of pre-warmed chunk geometries (1 without a ladder) —
        the value ``compile_cache_size()`` must equal after warmup."""
        return len(self.ladder)

    @property
    def geometry(self) -> ChunkGeometry:
        return ChunkGeometry(self.chunk_steps, self.pub_width)

    def set_geometry(self, chunk_steps: int, pub_width: int) -> None:
        """Switch the NEXT chunk's shape to another pre-warmed rung (chunk
        boundaries only — the caller is the serving loop, which only holds
        the engine between ``run_chunk`` calls).  Raises on a geometry
        that is not on the ladder: switching would compile a new variant,
        which is exactly the unplanned recompile this API exists to
        prevent."""
        want = (int(chunk_steps), int(pub_width))
        if want == (self.chunk_steps, self.pub_width):
            return
        if want not in {g.as_tuple() for g in self.ladder}:
            raise ValueError(
                f"geometry {want} is not on the pre-warmed ladder "
                f"{[g.as_tuple() for g in self.ladder]}; switching would "
                "recompile"
            )
        self.chunk_steps, self.pub_width = want
        self.geometry_switches += 1
        if self.metrics is not None:
            self.metrics.inc("serve.engine.geometry_switches")
            self.metrics.gauge("serve.engine.chunk_steps", self.chunk_steps)
            self.metrics.gauge("serve.engine.pub_width", self.pub_width)

    # -- the chunk loop -----------------------------------------------------

    def run_chunk(self) -> dict:
        """Pop one chunk's worth of ingest, publish, advance chunk_steps
        rounds, and fold completions.  Returns a host-side summary."""
        events = self._empty_events()
        items = self.ring.pop_batch(self.chunk_steps * self.pub_width)
        base_step = self.steps_run
        t_dispatch = self._clock()
        cursor = 0
        for item in items:
            if item.valid:
                # Exactly-once gate: a content hash already published (this
                # incarnation or a restored one) is a producer resubmission
                # or a replayed duplicate — skip it loudly, never twice.
                chash = content_hash(item.topic, item.publisher, item.payload)
                if chash in self._seen_hashes:
                    self.replay_deduped += 1
                    if self.metrics is not None:
                        self.metrics.inc("serve.engine.replay_deduped")
                    continue
            row = cursor % self.chunk_steps
            col = cursor // self.chunk_steps
            cursor += 1
            slot = self._alloc_slot(item)
            events.pub_topic[row, col] = item.topic
            events.pub_src[row, col] = item.publisher
            events.pub_slot[row, col] = slot
            events.pub_valid[row, col] = item.valid
            if item.valid:
                self._seen_hashes.add(chash)
                p = PendingMessage(
                    seq=item.seq, topic=item.topic, slot=slot,
                    publisher=item.publisher, t_ingest=item.t_ingest,
                    t_publish=t_dispatch, step_published=base_step + row,
                    chash=chash,
                )
                self.pending[(item.topic, slot)] = p
                self.publish_log.append(p)
                if self.tracer is not None:
                    self.tracer.stamp(
                        chash, "chunk_dispatch", t=t_dispatch,
                        chunk=self.chunks_run, step=p.step_published,
                        slot=slot,
                    )
            else:
                self.invalid_published.append((item.topic, slot))
            self.published += 1
        return self._dispatch(events, n_items=len(items))

    def run_until_drained(self, max_chunks: int = 64) -> int:
        """Chunk until the ring is empty and no message is pending (or the
        chunk budget runs out).  Returns chunks run by this call."""
        n = 0
        while n < max_chunks and (self.ring.depth > 0 or self.pending):
            self.run_chunk()
            n += 1
        return n

    # -- checkpoint ----------------------------------------------------------

    def _model_key(self) -> str:
        """Config fingerprint stored in checkpoint meta — a sanity check
        that a snapshot is restored onto an equal model (the array
        shape/dtype validation in utils.checkpoint does the heavy part).
        Models with their own fingerprint (the coded hybrid) provide
        ``stream_model_key``; the default is the multitopic form."""
        fn = getattr(self.model, "stream_model_key", None)
        if fn is not None:
            return fn()
        m = self.model
        return (
            f"multitopic t={m.t} n={m.n} k={m.k} m={m.m} w={m.w} "
            f"hb={m.heartbeat_steps}"
        )

    def snapshot(self, path: Optional[str] = None) -> str:
        """Write a durable checkpoint at the current chunk boundary.

        Array payload: device protocol state + the flight-recorder tail.
        JSON meta: every piece of host bookkeeping needed to resume —
        slot cursors, pending + publish logs, dedup hashes, counters —
        plus the ingest ring's buffer contents and conservation ledger.
        Atomic via utils.checkpoint (write → fsync → rename), so a crash
        mid-save never shadows the previous good snapshot."""
        path = path if path is not None else self.snapshot_path
        if path is None:
            raise ValueError("snapshot needs a path (ctor or argument)")
        if self.chunks_run < 1 or not self.flight_tail:
            raise RuntimeError(
                "snapshot() needs a warmed engine (run warmup() first so "
                "the flight tail has its resident structure)"
            )
        t0 = time.monotonic()
        meta = {
            "kind": "streaming-engine",
            "model": self._model_key(),
            "chunk_steps": self.chunk_steps,
            "pub_width": self.pub_width,
            "completion_frac": self.completion_frac,
            "chunks_run": self.chunks_run,
            "steps_run": self.steps_run,
            "published": self.published,
            "completed": self.completed,
            "evicted": self.evicted,
            "replay_deduped": self.replay_deduped,
            "duplicate_completions": self.duplicate_completions,
            "clock_anomalies": self.clock_anomalies,
            "next_slot": list(self._next_slot),
            "publish_log": [dataclasses.asdict(p) for p in self.publish_log],
            "pending_keys": sorted(
                [t, s] for (t, s) in self.pending.keys()
            ),
            "invalid_published": [
                [t, s] for (t, s) in self.invalid_published
            ],
            "latencies_s": list(self.latencies_s),
            "seen_hashes": sorted(self._seen_hashes),
            "completed_hashes": sorted(self._completed_hashes),
            "ring": self.ring.snapshot(),
            "ingress_delay": self.ingress_delay,
            # r18 observability: the wall stamp dates the checkpoint so a
            # restore can measure the crash gap; span state rides along so
            # in-flight spans survive (absent when untraced — restore
            # tolerates both).
            "t_wall": self._clock(),
            "latencies_exact_s": list(self.latencies_exact_s),
        }
        if self.tracer is not None:
            meta["spans"] = self.tracer.snapshot()
        # Coded models expose decode progress — recorded so an operator
        # (and the crash tests) can see partial ranks were checkpointed
        # mid-generation, not just full decodes.
        rank_fn = getattr(self.model, "decode_rank_summary", None)
        if rank_fn is not None:
            meta["decode_ranks"] = {
                k: int(v) for k, v in rank_fn(self.state).items()
            }
        ckpt.save(
            path,
            {"state": self.state, "flight_tail": dict(self.flight_tail)},
            meta=meta,
        )
        self.snapshots_taken += 1
        self.snapshot_seconds += time.monotonic() - t0
        if self.metrics is not None:
            self.metrics.inc("serve.engine.snapshots")
        return path

    def restore(self, path: Optional[str] = None) -> dict:
        """Resume from the last snapshot WITHOUT recompiling.

        Call on a *warmed* engine (fresh-process flow: construct → warmup()
        → restore()); warmup provides the resident template structure and —
        via the shared rollout cache — costs no compile when an equal model
        was already compiled this process.  Overwrites device state, flight
        tail, and all host bookkeeping with the snapshot's, and reinstates
        the ingest ring's buffer + ledger so accepted-but-undelivered
        messages replay through the normal chunk path (the content-hash
        dedup makes the replay exactly-once).  Returns a summary dict."""
        path = path if path is not None else self.snapshot_path
        if path is None:
            raise ValueError("restore needs a path (ctor or argument)")
        if self.chunks_run < 1 or not self.flight_tail:
            raise RuntimeError(
                "restore() needs a warmed engine (run warmup() first; the "
                "warmed flight tail is the restore template)"
            )
        meta = ckpt.meta(path)
        if meta.get("kind") != "streaming-engine":
            raise ValueError(
                f"{path} is not a streaming-engine checkpoint "
                f"(kind={meta.get('kind')!r})"
            )
        if meta["model"] != self._model_key():
            raise ValueError(
                "checkpoint/model config mismatch: "
                f"snapshot={meta['model']!r} engine={self._model_key()!r}"
            )
        snap_geom = (int(meta["chunk_steps"]), int(meta["pub_width"]))
        if snap_geom != (self.chunk_steps, self.pub_width):
            # A ladder engine adopts the snapshot's geometry (the rung the
            # controller had selected at checkpoint time) — it is already
            # compiled, so the switch costs nothing.  Off-ladder shapes
            # still refuse: restoring would compile a new variant.
            if snap_geom in {g.as_tuple() for g in self.ladder}:
                self.set_geometry(*snap_geom)
            else:
                raise ValueError(
                    "checkpoint chunk shapes "
                    f"({meta['chunk_steps']}x{meta['pub_width']}) not on "
                    f"the engine's ladder "
                    f"{[g.as_tuple() for g in self.ladder]}; restoring "
                    "would break the pre-warmed-variants contract"
                )
        tree = ckpt.restore(
            path, {"state": self.state, "flight_tail": dict(self.flight_tail)}
        )
        self.state = tree["state"]
        self.flight_tail = {
            k: np.asarray(jax.device_get(v))
            for k, v in tree["flight_tail"].items()
        }
        self.completion_frac = float(meta["completion_frac"])
        self.chunks_run = int(meta["chunks_run"])
        # Pre-ladder checkpoints (constant geometry) reconstruct the step
        # accumulator the way the old code computed base_step.
        self.steps_run = int(meta.get(
            "steps_run", self.chunks_run * int(meta["chunk_steps"])
        ))
        self.published = int(meta["published"])
        self.completed = int(meta["completed"])
        self.evicted = int(meta["evicted"])
        self.replay_deduped = int(meta["replay_deduped"])
        self.duplicate_completions = int(meta["duplicate_completions"])
        self.clock_anomalies = int(meta.get("clock_anomalies", 0))
        self._next_slot = [int(x) for x in meta["next_slot"]]
        self.publish_log = [
            PendingMessage(**d) for d in meta["publish_log"]
        ]
        by_key = {(p.topic, p.slot): p for p in self.publish_log}
        self.pending = {
            (int(t), int(s)): by_key[(int(t), int(s))]
            for t, s in meta["pending_keys"]
        }
        self.invalid_published = [
            (int(t), int(s)) for t, s in meta["invalid_published"]
        ]
        self.latencies_s = [float(x) for x in meta["latencies_s"]]
        self._seen_hashes = set(meta["seen_hashes"])
        self._completed_hashes = set(meta["completed_hashes"])
        if meta.get("ingress_delay") is not None:
            self.ingress_delay = int(meta["ingress_delay"])
        replayed = self.ring.restore_snapshot(meta["ring"])
        self.latencies_exact_s = [
            float(x) for x in meta.get("latencies_exact_s", [])
        ]
        # Recovery gap: how long the world stood still between the
        # checkpoint's wall stamp and this restore.  Annotated onto every
        # reopened span (with the watchdog's tier/reason context when it
        # drove the restart) so a crash reads as a measured gap, not a hole.
        gap: Optional[float] = None
        if meta.get("t_wall") is not None:
            gap = max(0.0, self._clock() - float(meta["t_wall"]))
            self.last_recovery_gap_s = gap
        if self.tracer is not None and meta.get("spans") is not None:
            self.tracer.restore_snapshot(meta["spans"])
            rctx = {str(k): str(v) for k, v in self.recovery_context.items()}
            if gap is not None:
                self.tracer.event("crash_recovery", gap_s=gap, **rctx)
                self.tracer.annotate_open("crash_recovery", gap_s=gap, **rctx)
        self.recovery_context = {}
        self.restores += 1
        if self.metrics is not None:
            self.metrics.inc("serve.engine.restores")
        return {
            "chunk": self.chunks_run,
            "replayed": replayed,
            "pending": len(self.pending),
            "completed": self.completed,
            "recovery_gap_s": gap,
        }

    # -- views --------------------------------------------------------------

    def latency_quantiles(
        self, qs=(0.5, 0.99), mode: str = "chunk"
    ) -> Dict[str, float]:
        """{"p50": ..., "p99": ...} over completed ingest→delivery
        latencies (host seconds); NaN when nothing completed yet.

        ``mode="chunk"`` is the r12 measurement (delivery observed at the
        chunk boundary, latencies rounded UP to it).  ``mode="exact"``
        reads the span plane's device-round interpolation instead —
        populated only on traced runs, and elementwise ≤ the chunk value
        by construction, so exact quantiles never exceed chunk ones."""
        from ..utils.metrics import quantiles

        if mode == "chunk":
            return quantiles(self.latencies_s, qs)
        if mode == "exact":
            return quantiles(self.latencies_exact_s, qs)
        raise ValueError(f"unknown latency mode {mode!r}; "
                         "have: chunk, exact")

    # -- internals ----------------------------------------------------------

    def set_ingress_delay(self, delay: Optional[int]) -> None:
        """Set (or clear with ``None``) the all-peer ingress delay stamped
        into each subsequent chunk.  Pass 0 to actively RESET peers to the
        lossless fabric — the device state latches the last set value, so
        clearing to ``None`` merely stops re-stamping."""
        self.ingress_delay = None if delay is None else int(delay)

    def _empty_events(self) -> sched.MultiTopicEvents:
        ev = sched.empty_multitopic_events(
            self.chunk_steps, self.model.n, self.pub_width
        )
        if self.ingress_delay is not None:
            ev.delay[0, :] = self.ingress_delay
        return ev

    def _alloc_slot(self, item: IngestItem) -> int:
        slot = self._next_slot[item.topic]
        self._next_slot[item.topic] = (slot + 1) % self.model.m
        stale = self.pending.pop((item.topic, slot), None)
        if stale is not None:
            # Window recycle outran delivery tracking: the old message is
            # closed out as evicted (counted, never silently lost).
            self.evicted += 1
            if self.metrics is not None:
                self.metrics.inc("serve.engine.evicted")
            if self.tracer is not None and stale.chash:
                self.tracer.close(stale.chash, status="evicted")
        return slot

    def _dispatch(self, events: sched.MultiTopicEvents, n_items: int = 0):
        t_start = self._clock()
        # Flag-gated XLA capture every Nth chunk (off by default; never on
        # warmup chunks) — the on-chip campaign's free profiler hook.
        do_profile = (
            self.profile_every > 0
            and not getattr(self, "_in_warmup", False)
            and (self.chunks_run + 1) % self.profile_every == 0
        )
        profiler = (
            xla_trace(self.profile_dir) if do_profile
            else contextlib.nullcontext()
        )
        # Chunk executables must NEVER enter the persistent compile cache:
        # the CPU backend segfaults executing a DESERIALIZED donated-state
        # chunk program (see tests/conftest.py).  The repo-wide 10 s floor
        # only keeps them out while compiles stay fast — on a loaded box a
        # chunk compile crosses the floor and poisons the cache for every
        # later process.  Opt out at the one site that compiles them.
        with profiler, _persistent_cache_floor(float("inf")):
            self.state, record = self._rollout(self.state, events)
        if do_profile:
            self.profile_captures += 1
        # Exact device rounds (traced runs only): a separate host-called
        # jitted digest over the persistent first-receipt record — the
        # resident chunk program itself is untouched, so tracing can never
        # change device semantics or add a compiled chunk variant.
        # Dispatched asynchronously BEFORE the blocking digest fetch so its
        # compute overlaps the sync the engine pays anyway; only the (tiny)
        # result transfer below is tracing-specific latency.
        deliver_dev = None
        if self.tracer is not None:
            fn = getattr(self.model, "stream_deliver_steps", None)
            if fn is not None:
                deliver_dev = fn(
                    self.state, self.chunk_steps, self.completion_frac
                )
        digest = jax.device_get(self.model.stream_digest(self.state))
        t_done = self._clock()
        self.chunks_run += 1
        self.steps_run += self.chunk_steps
        self.last_chunk_wall_s = t_done - t_start
        deliver_steps = (
            np.asarray(jax.device_get(deliver_dev))
            if deliver_dev is not None else None
        )
        completed_now = self._fold_completions(
            digest, t_done, t_start=t_start, deliver_steps=deliver_steps
        )
        # Flight-recorder tail: the final round of each telemetry channel
        # (one device_get; lat_hist's last row is the window-cumulative
        # histogram at the chunk boundary).
        host_rec = jax.device_get(record)
        self.flight_tail = {
            k: np.asarray(v)[-1] for k, v in host_rec.items()
        }
        if self.metrics is not None:
            self.metrics.gauge("serve.engine.pending", len(self.pending))
            self.metrics.inc("serve.engine.chunks")
        if self.blackbox is not None:
            acct = self.ring.accounting()
            frame = {
                "chunk": self.chunks_run - 1,
                "step": int(digest["step"]),
                "items": n_items,
                "completed_now": completed_now,
                "pending": len(self.pending),
                "queue_depth": acct["in_queue"],
                "chunk_wall_s": self.last_chunk_wall_s,
                "published": self.published,
                "completed": self.completed,
                "evicted": self.evicted,
                "replay_deduped": self.replay_deduped,
                "shed_priority": acct["shed_priority"],
                "dropped_oldest": acct["dropped_oldest"],
                "rejected": acct["rejected"],
                "warmup": bool(getattr(self, "_in_warmup", False)),
            }
            if self.metrics is not None:
                v = self.metrics.latest("crypto.pipeline.verify_s")
                if v is not None:
                    frame["verify_s"] = v
            self.blackbox.record(frame)
        if (
            self.snapshot_every > 0
            and not getattr(self, "_in_warmup", False)
            and self.chunks_run % self.snapshot_every == 0
        ):
            self.snapshot()
        return {
            "chunk": self.chunks_run - 1,
            "items": n_items,
            "completed_now": completed_now,
            "pending": len(self.pending),
            "step": int(digest["step"]),
        }

    def _fold_completions(
        self,
        digest: dict,
        t_done: float,
        t_start: Optional[float] = None,
        deliver_steps: Optional[np.ndarray] = None,
    ) -> int:
        delivered = np.asarray(digest["delivered"])        # [T, M]
        participants = np.asarray(digest["participants"])  # [T]
        step_end = int(digest["step"])
        done = 0
        for (topic, slot), p in list(self.pending.items()):
            target = max(1, int(self.completion_frac * participants[topic]))
            if int(delivered[topic, slot]) >= target:
                lat = t_done - p.t_ingest
                if lat < 0.0:
                    # Host clock skew can make delivery appear to precede
                    # ingest; clamp and count — never report a negative
                    # latency silently.
                    self.clock_anomalies += 1
                    if self.metrics is not None:
                        self.metrics.inc("serve.engine.clock_anomalies")
                    lat = 0.0
                self.latencies_s.append(lat)
                if self.tracer is not None:
                    # Exact delivery time: the chunk-boundary stamp pulled
                    # back to the message's actual device round, linearly
                    # interpolated inside this chunk's host wall window.
                    # r_local is clamped to the chunk, so t_exact <= t_done
                    # and the exact latency never exceeds the chunk-
                    # quantized one.
                    t_exact = t_done
                    r = -1
                    if deliver_steps is not None and t_start is not None:
                        r = int(deliver_steps[topic, slot])
                        if r >= 0:
                            r_local = min(
                                max(r - (step_end - self.chunk_steps), 0),
                                self.chunk_steps - 1,
                            )
                            t_exact = t_start + (
                                (r_local + 1) / self.chunk_steps
                            ) * (t_done - t_start)
                    lat_exact = min(max(0.0, t_exact - p.t_ingest), lat)
                    self.latencies_exact_s.append(lat_exact)
                    if p.chash:
                        self.tracer.stamp(
                            p.chash, "device_delivery", t=t_exact,
                            round=r, lat_s=lat_exact, lat_chunk_s=lat,
                        )
                        self.tracer.close(p.chash, t=t_exact)
                self.completed += 1
                if p.chash:
                    if p.chash in self._completed_hashes:
                        self.duplicate_completions += 1
                        if self.metrics is not None:
                            self.metrics.inc("serve.engine.duplicates")
                    self._completed_hashes.add(p.chash)
                del self.pending[(topic, slot)]
                done += 1
        if done and self.metrics is not None:
            self.metrics.inc("serve.engine.completed", done)
        return done
