"""Device-resident streaming rollout: one compiled chunk, replayed forever.

The scenario engine's trick (``ops/schedule.py``) was to make a whole
campaign the ``xs`` of one ``lax.scan``; the streaming engine turns that
inside out — the *shapes* of the event tensors are frozen once
(``chunk_steps`` scan rows x ``pub_width`` publish slots, padded with the
schedule's ``-1`` sentinels and gated by the model's ``lax.cond``
publishes) and every chunk replays the SAME compiled program on freshly
filled tensors.  GossipSub state flows chunk-to-chunk through donated
buffers, so an unbounded publish stream rides one XLA compilation with no
per-chunk allocation of the resident state.

Latency is exact, not modeled: each message carries the host-clock
timestamp its :class:`~.ingest.IngestRing` ``push`` stamped, and the engine
reports ingest→delivery as host seconds from that stamp to the end of the
chunk in which the message's delivered count crossed the completion
threshold.  The quantization this implies (delivery is observed at chunk
boundaries, so latencies are rounded UP to the next boundary) is a
documented property of the measurement, not an approximation inside it.

The flight-recorder tail (the last round of every in-scan telemetry
channel, including the latency histogram) is carried across chunks so a
scrape mid-stream sees current telemetry without any extra device work.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Tuple

import jax
import numpy as np

from ..models.multitopic import MultiTopicGossipSub
from ..ops import schedule as sched
from .ingest import IngestItem, IngestRing


@dataclasses.dataclass
class PendingMessage:
    """A published message awaiting its completion threshold."""

    seq: int
    topic: int
    slot: int
    publisher: int
    t_ingest: float       # host clock at ring push
    t_publish: float      # host clock when its chunk was dispatched
    step_published: int   # global device step of its publish row


class StreamingEngine:
    """Resident chunked rollout over a :class:`MultiTopicGossipSub`.

    ``run_chunk`` pops up to ``chunk_steps * pub_width`` ring items, packs
    them into a fixed-shape ``MultiTopicEvents`` (publishes spread
    round-robin over the chunk's rows), and invokes the donated-buffer
    compiled rollout.  ``compile_cache_size()`` must stay 1 after warmup —
    the no-recompilation contract the tests assert.
    """

    def __init__(
        self,
        model: MultiTopicGossipSub,
        ring: IngestRing,
        chunk_steps: int = 8,
        pub_width: int = 4,
        completion_frac: float = 0.99,
        seed: int = 0,
        metrics=None,
        clock=time.monotonic,
    ) -> None:
        if chunk_steps < 1 or pub_width < 1:
            raise ValueError("chunk_steps and pub_width must be >= 1")
        if not (0.0 < completion_frac <= 1.0):
            raise ValueError("completion_frac must be in (0, 1]")
        self.model = model
        self.ring = ring
        self.chunk_steps = chunk_steps
        self.pub_width = pub_width
        self.completion_frac = completion_frac
        self.metrics = metrics
        self._clock = clock
        self.state = model.init(seed=seed)
        # The resident program: donated state in, fixed event shapes.  The
        # inner rollout_events jit is keyed on the model's value semantics,
        # so engines over equal configs share both cache layers.
        self._rollout = jax.jit(
            lambda st, ev: model.rollout_events(st, ev, record=True),
            donate_argnums=(0,),
        )
        self._next_slot = [0] * model.t          # per-topic cyclic allocator
        self.pending: Dict[Tuple[int, int], PendingMessage] = {}
        self.latencies_s: List[float] = []       # completed, host seconds
        self.publish_log: List[PendingMessage] = []   # every VALID publish
        self.invalid_published: List[Tuple[int, int]] = []  # (topic, slot)
        self.chunks_run = 0
        self.published = 0
        self.completed = 0
        self.evicted = 0       # window slot recycled before completion
        self.flight_tail: Dict[str, np.ndarray] = {}

    # -- lifecycle ----------------------------------------------------------

    def warmup(self) -> None:
        """Run one all-quiet chunk to pay the compile before traffic
        arrives (the serving analog of the bench's compile+warm pass).
        Advances the device state by ``chunk_steps`` idle rounds."""
        self._dispatch(self._empty_events())

    def compile_cache_size(self) -> int:
        """Number of compiled variants of the resident chunk — 1 after
        warmup, and STILL 1 after any number of chunks, or shapes drifted."""
        return self._rollout._cache_size()

    # -- the chunk loop -----------------------------------------------------

    def run_chunk(self) -> dict:
        """Pop one chunk's worth of ingest, publish, advance chunk_steps
        rounds, and fold completions.  Returns a host-side summary."""
        events = self._empty_events()
        items = self.ring.pop_batch(self.chunk_steps * self.pub_width)
        base_step = self.chunks_run * self.chunk_steps
        t_dispatch = self._clock()
        for i, item in enumerate(items):
            row = i % self.chunk_steps
            col = i // self.chunk_steps
            slot = self._alloc_slot(item)
            events.pub_topic[row, col] = item.topic
            events.pub_src[row, col] = item.publisher
            events.pub_slot[row, col] = slot
            events.pub_valid[row, col] = item.valid
            if item.valid:
                p = PendingMessage(
                    seq=item.seq, topic=item.topic, slot=slot,
                    publisher=item.publisher, t_ingest=item.t_ingest,
                    t_publish=t_dispatch, step_published=base_step + row,
                )
                self.pending[(item.topic, slot)] = p
                self.publish_log.append(p)
            else:
                self.invalid_published.append((item.topic, slot))
            self.published += 1
        return self._dispatch(events, n_items=len(items))

    def run_until_drained(self, max_chunks: int = 64) -> int:
        """Chunk until the ring is empty and no message is pending (or the
        chunk budget runs out).  Returns chunks run by this call."""
        n = 0
        while n < max_chunks and (self.ring.depth > 0 or self.pending):
            self.run_chunk()
            n += 1
        return n

    # -- views --------------------------------------------------------------

    def latency_quantiles(self, qs=(0.5, 0.99)) -> Dict[str, float]:
        """{"p50": ..., "p99": ...} over completed ingest→delivery
        latencies (host seconds); NaN when nothing completed yet."""
        from ..utils.metrics import quantiles

        return quantiles(self.latencies_s, qs)

    # -- internals ----------------------------------------------------------

    def _empty_events(self) -> sched.MultiTopicEvents:
        return sched.empty_multitopic_events(
            self.chunk_steps, self.model.n, self.pub_width
        )

    def _alloc_slot(self, item: IngestItem) -> int:
        slot = self._next_slot[item.topic]
        self._next_slot[item.topic] = (slot + 1) % self.model.m
        stale = self.pending.pop((item.topic, slot), None)
        if stale is not None:
            # Window recycle outran delivery tracking: the old message is
            # closed out as evicted (counted, never silently lost).
            self.evicted += 1
            if self.metrics is not None:
                self.metrics.inc("serve.engine.evicted")
        return slot

    def _dispatch(self, events: sched.MultiTopicEvents, n_items: int = 0):
        self.state, record = self._rollout(self.state, events)
        digest = jax.device_get(self.model.stream_digest(self.state))
        t_done = self._clock()
        self.chunks_run += 1
        completed_now = self._fold_completions(digest, t_done)
        # Flight-recorder tail: the final round of each telemetry channel
        # (one device_get; lat_hist's last row is the window-cumulative
        # histogram at the chunk boundary).
        host_rec = jax.device_get(record)
        self.flight_tail = {
            k: np.asarray(v)[-1] for k, v in host_rec.items()
        }
        if self.metrics is not None:
            self.metrics.gauge("serve.engine.pending", len(self.pending))
            self.metrics.inc("serve.engine.chunks")
        return {
            "chunk": self.chunks_run - 1,
            "items": n_items,
            "completed_now": completed_now,
            "pending": len(self.pending),
            "step": int(digest["step"]),
        }

    def _fold_completions(self, digest: dict, t_done: float) -> int:
        delivered = np.asarray(digest["delivered"])        # [T, M]
        participants = np.asarray(digest["participants"])  # [T]
        done = 0
        for (topic, slot), p in list(self.pending.items()):
            target = max(1, int(self.completion_frac * participants[topic]))
            if int(delivered[topic, slot]) >= target:
                self.latencies_s.append(t_done - p.t_ingest)
                self.completed += 1
                del self.pending[(topic, slot)]
                done += 1
        if done and self.metrics is not None:
            self.metrics.inc("serve.engine.completed", done)
        return done
