"""Wire protocol: byte-compatible with the reference's JSON codec.

The reference frames every protocol interaction as one ``Message`` struct
serialized with Go's ``encoding/json`` over a libp2p stream
(``/root/reference/pubsub.go:122-153``).  Compatibility notes, each mirrored
here exactly so the live plane (net/live.py) can interoperate with a Go peer:

- ``MessageType``: Data=0, Join=1, Part=2, Update=3, State=4
  (``pubsub.go:138-144``).
- ``Type`` has no json tag -> always serialized as ``"Type"`` with an integer
  value, even when zero.
- ``Data []byte`` -> Go marshals byte slices as **base64** strings, json key
  ``"data"``, omitted when empty.
- ``Peers []string`` -> json key is ``"parents"`` (NOT "peers";
  ``pubsub.go:149``), omitted when empty.
- ``TreeWidth`` / ``TreeMaxWidth`` / ``NumPeers`` -> lowercase keys, omitted
  when zero (``omitempty``).
- Framing: concatenated JSON objects on the stream; Go's ``json.Encoder``
  appends ``\\n`` after each object and ``json.Decoder`` finds object
  boundaries itself (``pubsub.go:122-134``).  ``MessageDecoder`` below is the
  incremental equivalent.
"""

from __future__ import annotations

import base64
import codecs
import enum
import json
from dataclasses import dataclass, field
from typing import Iterator, List, Optional


class MessageType(enum.IntEnum):
    """Five-variant protocol message tag (reference ``pubsub.go:136-144``)."""

    DATA = 0
    JOIN = 1
    PART = 2
    UPDATE = 3
    STATE = 4


@dataclass
class Message:
    """The single message struct serving all five protocol purposes.

    Mirrors reference ``pubsub.go:146-153``.  Field semantics (``SURVEY.md``
    §2.2):

    - ``JOIN``   — first message on any new stream toward a prospective parent.
    - ``UPDATE`` — welcome (``peers == [senderID]`` plus fanout params) or
      redirect (``peers == [childID]``); receiver distinguishes by comparing
      ``peers`` against the sender (``subtree.go:283``).
    - ``STATE``  — child->parent accounting: ``num_peers`` subtree size plus
      grandchild id list.
    - ``PART``   — graceful leave notice.
    - ``DATA``   — application payload, root-originated.
    """

    type: MessageType = MessageType.DATA
    data: bytes = b""
    peers: List[str] = field(default_factory=list)
    tree_width: int = 0
    tree_max_width: int = 0
    num_peers: int = 0
    # Repair-replay marker (this build's extension, net/live.py): a Data
    # frame re-sent to a re-adopted orphan because the adopter cannot know
    # what the dead parent delivered.  On a Join it is a recovery request:
    # "replay me your retained forward-log window after admitting me".
    # Serialized only when set, so normal traffic stays byte-identical to
    # the reference encoder; a Go peer's ``encoding/json`` ignores the
    # unknown key on the frames that carry it.
    replay: bool = False
    # Failover extensions (net/live.py root-failover):
    # - ``epoch``: fencing counter; 0 (the whole pre-failover regime) is
    #   omitted on the wire so clean-path frames stay byte-identical to the
    #   reference encoder.  After a successor promotion every Data/Update
    #   frame carries the new epoch and receivers reject lower values.
    # - ``successors``: the root's rank-ordered successor list (its direct
    #   children in admission order), piggybacked on Update frames.
    # - ``roster``: the root's two-level membership view (direct children +
    #   reported grandchildren), the electorate a successor quorum-probes
    #   before promoting itself.
    epoch: int = 0
    successors: List[str] = field(default_factory=list)
    roster: List[str] = field(default_factory=list)
    # Distributed-tracing extensions (r19, obs/merge.py): the origin marks a
    # Data frame ``traced`` when its span ledger sampled the message, so
    # every downstream host stamps hop spans without re-negotiating the
    # sampling decision on the wire (the decision itself is recomputable
    # from the payload — the marker just spares untraced frames the hash).
    # ``clock_offset`` is the ORIGIN's host-clock offset estimate (seconds,
    # relative to the deployment's reference clock): receivers record it on
    # the recv stamp so the cross-host merge can normalize timestamps even
    # for hosts whose own estimate never reached the collector.  Both are
    # serialized only when set — untraced traffic stays byte-identical to
    # the reference encoder.
    traced: bool = False
    clock_offset: float = 0.0
    # In-memory span-key memo — NEVER serialized and excluded from
    # equality: hosts stamp a traced frame at several sites (recv, deliver,
    # forward) and the sha256 identity is the same at each, so the first
    # stamp caches it here for the rest of the frame's life on this host.
    span_key: Optional[str] = field(
        default=None, init=False, compare=False, repr=False)

    def to_json_obj(self) -> dict:
        # Field order matches the Go struct declaration order so encoded bytes
        # are identical to the reference encoder's output.
        obj: dict = {"Type": int(self.type)}
        if self.data:
            obj["data"] = base64.b64encode(self.data).decode("ascii")
        if self.peers:
            obj["parents"] = list(self.peers)
        if self.tree_width:
            obj["treewidth"] = self.tree_width
        if self.tree_max_width:
            obj["treemaxwidth"] = self.tree_max_width
        if self.num_peers:
            obj["numpeers"] = self.num_peers
        if self.replay:
            obj["replay"] = True
        if self.epoch:
            obj["epoch"] = self.epoch
        if self.successors:
            obj["successors"] = list(self.successors)
        if self.roster:
            obj["roster"] = list(self.roster)
        if self.traced:
            obj["traced"] = True
        if self.clock_offset:
            obj["clockoff"] = self.clock_offset
        return obj

    @classmethod
    def from_json_obj(cls, obj: dict) -> "Message":
        data = obj.get("data", "")
        return cls(
            type=MessageType(obj.get("Type", 0)),
            data=base64.b64decode(data) if data else b"",
            peers=list(obj.get("parents", []) or []),
            tree_width=int(obj.get("treewidth", 0)),
            tree_max_width=int(obj.get("treemaxwidth", 0)),
            num_peers=int(obj.get("numpeers", 0)),
            replay=bool(obj.get("replay", False)),
            epoch=int(obj.get("epoch", 0)),
            successors=list(obj.get("successors", []) or []),
            roster=list(obj.get("roster", []) or []),
            traced=bool(obj.get("traced", False)),
            clock_offset=float(obj.get("clockoff", 0.0)),
        )


def encode_message(m: Message) -> bytes:
    """Encode one message the way Go's ``json.Encoder.Encode`` does.

    Compact separators (Go emits no spaces) plus a trailing newline
    (``json.Encoder`` appends one after every value).
    """
    return json.dumps(m.to_json_obj(), separators=(",", ":")).encode() + b"\n"


def decode_message(buf: bytes) -> Message:
    """Decode exactly one message from ``buf`` (ignoring trailing bytes)."""
    obj, _ = json.JSONDecoder().raw_decode(buf.decode())
    return Message.from_json_obj(obj)


class MessageDecoder:
    """Incremental stream decoder: feed bytes, iterate complete messages.

    The equivalent of handing a ``json.Decoder`` the stream and letting it
    find object boundaries (``pubsub.go:126-134``): raw concatenated JSON
    objects, whitespace between objects tolerated.
    """

    def __init__(self) -> None:
        self._buf = ""
        self._dec = json.JSONDecoder()
        # Incremental UTF-8: a multi-byte rune split across socket reads must
        # buffer, not raise (Go emits non-ASCII peer ids as raw UTF-8).
        self._utf8 = codecs.getincrementaldecoder("utf-8")()

    def feed(self, data: bytes) -> None:
        self._buf += self._utf8.decode(data)

    def __iter__(self) -> Iterator[Message]:
        return self

    def __next__(self) -> Message:
        m = self.next_message()
        if m is None:
            raise StopIteration
        return m

    def next_message(self) -> Optional[Message]:
        s = self._buf.lstrip()
        if not s:
            self._buf = ""
            return None
        try:
            obj, end = self._dec.raw_decode(s)
        except json.JSONDecodeError:
            # Incomplete object: keep buffering.  A syntactically corrupt
            # stream surfaces as an ever-growing buffer; callers bound it.
            self._buf = s
            return None
        except RecursionError:
            # Pathological nesting (e.g. a "[[[[..." flood) blows the
            # scanner's stack long before any object completes.  Treat it
            # like an incomplete object: buffer, and let the caller's
            # pending-bytes bound abort the stream.
            self._buf = s
            return None
        self._buf = s[end:]
        return Message.from_json_obj(obj)

    def pending_bytes(self) -> int:
        return len(self._buf)
