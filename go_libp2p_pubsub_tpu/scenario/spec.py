"""Declarative scenario specs: an adversity campaign as serializable data.

BASELINE config (d) and the ROADMAP's "handle as many scenarios as you can
imagine" were served by three disconnected mechanisms — ``utils.faults``
FaultPlans, ``models/attacks.py`` ad-hoc runners, and per-test link
profiles.  A :class:`ScenarioSpec` composes all of them onto ONE timeline:
phased churn (abrupt or graceful, with optional rejoin), attack waves
(sybil colocation, eclipse, invalid spam, gossip-promise spam, backoff
graft spam), link-degradation windows, and traffic workload generators
(constant / burst / hot-publisher), plus the SLO thresholds the run is
graded against.

A spec is pure data: dataclasses with an exact JSON round-trip
(``to_dict``/``from_dict``/``to_json``/``from_json``), so a scenario can be
committed, diffed, and replayed bit-for-bit (``scenario.runner.save_trace``
stores the spec next to the flight record it produced).  All randomness is
derived from ``seed`` through per-component substreams at compile time —
the lowered event tensors are a pure function of the spec.

Lowering to device event tensors lives in ``scenario.compiler``; execution
and verdicts in ``scenario.runner``; the named canon in ``scenario.canon``.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

FAMILIES = ("gossipsub", "treecast", "multitopic", "rlnc", "hybrid")
WORKLOAD_KINDS = ("constant", "burst", "hot")
ATTACK_KINDS = (
    "sybil", "eclipse", "spam", "promise_spam", "graft_spam",
    # The literature's remaining catalogue (arXiv 2007.02754 / 2212.05197):
    "cold_boot_eclipse", "covert_flash", "score_farm", "self_promo_ihave",
    "partition_flood",
)


@dataclass(frozen=True)
class Workload:
    """A traffic generator on the scenario timeline.

    - ``constant``: ``n_msgs`` publishes every ``every`` steps over
      [start, stop), each from a random honest alive peer (or ``src``).
    - ``burst``: ``n_msgs`` publishes all at ``start`` (flash crowd), each
      from a distinct random honest peer unless ``src`` pins one.
    - ``hot``: like constant but REQUIRES ``src`` — the hot-publisher
      pattern (one peer produces the topic's whole feed).
    """

    kind: str = "constant"
    start: int = 0
    stop: Optional[int] = None     # exclusive; None = scenario end
    every: int = 1
    n_msgs: int = 1                # per event (burst: total, at `start`)
    src: Optional[int] = None
    valid: bool = True
    topic: int = 0                 # multitopic family only

    def __post_init__(self) -> None:
        if self.kind not in WORKLOAD_KINDS:
            raise ValueError(f"unknown workload kind {self.kind!r}")
        if self.kind == "hot" and self.src is None:
            raise ValueError("hot workload requires src")
        if self.every < 1:
            raise ValueError("workload every must be >= 1")
        if self.n_msgs < 1:
            raise ValueError("workload n_msgs must be >= 1")


@dataclass(frozen=True)
class ChurnPhase:
    """A window of membership churn.

    Every ``every`` steps in [start, stop), ``kills_per_event`` victims are
    drawn (random honest alive peers, or cycled from ``peers``) and either
    killed abruptly (default) or removed gracefully (``graceful=True``:
    unsubscribe for the mesh families, Part for the tree).  With
    ``rejoin_after`` set, each victim comes back that many steps later
    (revive / resubscribe / join walk) — churn with rejoin, or with a
    single event, partition-and-heal.
    """

    start: int = 0
    stop: int = 1
    every: int = 8
    kills_per_event: int = 1
    graceful: bool = False
    rejoin_after: Optional[int] = None
    peers: Optional[List[int]] = None

    def __post_init__(self) -> None:
        if self.every < 1:
            raise ValueError("churn every must be >= 1")
        if self.stop <= self.start:
            raise ValueError("churn stop must be > start")


@dataclass(frozen=True)
class AttackWave:
    """An adversary campaign window (gossipsub family; ``spam`` and
    ``promise_spam`` also lower for multitopic).

    - ``sybil``: peers [0, n_attackers) share one IP-colocation group for
      the whole run (P6 defense under test).
    - ``eclipse``: the attackers are the ``target``'s CONVERGED mesh at
      scenario start (derived at compile time); during [start, stop) they
      receive but never relay (post-step silence) and never serve IWANTs
      (gossip mute).  ``spam_every``/``graft_spam`` compose spam flavors
      onto the same attacker set.
    - ``spam``: attackers [0, n_attackers) publish one invalid message each
      every ``spam_every`` steps in [start, stop) (P4 defense).
    - ``promise_spam``: attackers advertise but never serve IWANTs during
      the window (P7 promise tracking).
    - ``graft_spam``: attackers re-GRAFT through their prune-backoff every
      heartbeat for the WHOLE run (constructor-bound ``graft_spammers``),
      plus the window's invalid spam when ``spam_every > 0`` (P7 backoff
      violations).
    - ``cold_boot_eclipse``: ``n_attackers`` of the ``target``'s CONNECTED
      neighbors monopolize its mesh from step 0 — the compiler forces the
      target's mesh to attacker edges only and zeroes the score history on
      every touched edge (no banked P1/P2 to prune against); during
      [start, stop) the monopolists receive but never relay nor serve.
    - ``covert_flash``: attackers [0, n_attackers) behave honestly until
      ``defect_step``, then defect simultaneously (silence + gossip mute
      until ``stop``, plus invalid spam every ``spam_every`` steps when
      ``spam_every > 0``) — tests that defense reaction time beats banked
      reputation.
    - ``score_farm``: attackers publish VALID messages every ``spam_every``
      steps for the first ``farm_steps`` of the window (banking P1/P2
      credit), then flip to invalid spam for the remainder — tests that
      P4 penalties overcome farmed credit.
    - ``self_promo_ihave``: attackers publish valid self-originated traffic
      every ``spam_every`` steps and craft their IHAVEs to advertise ONLY
      ids they originated, while never serving the IWANTs those ads
      attract — inflated promise/delivery standing vs P7 promise tracking.
    - ``partition_flood``: a random ``partition_frac`` cohort of honest
      peers is partitioned away during [start, stop); at
      ``stop + flood_offset`` the attackers open an invalid spam flood
      (every ``spam_every`` steps to scenario end) timed to pollute the
      heal's gossip backfill.
    """

    kind: str = "spam"
    start: int = 0
    stop: Optional[int] = None     # exclusive; None = scenario end
    n_attackers: int = 0
    target: Optional[int] = None   # eclipse / cold_boot_eclipse only
    spam_every: int = 0            # 0 = no spam publishes
    graft_spam: bool = False       # also bind attackers as graft spammers
    defect_step: Optional[int] = None  # covert_flash: step the mask drops
    farm_steps: int = 0            # score_farm: valid-publish window length
    flood_offset: int = 0          # partition_flood: heal -> flood delay
    partition_frac: float = 0.0    # partition_flood: cohort fraction

    def __post_init__(self) -> None:
        if self.kind not in ATTACK_KINDS:
            raise ValueError(f"unknown attack kind {self.kind!r}")
        targeted = ("eclipse", "cold_boot_eclipse")
        if self.kind in targeted and self.target is None:
            raise ValueError(f"{self.kind} wave requires target")
        if self.kind != "eclipse" and self.n_attackers < 1:
            raise ValueError(f"{self.kind} wave requires n_attackers >= 1")
        spam_kinds = ("spam", "score_farm", "self_promo_ihave",
                      "partition_flood")
        if self.kind in spam_kinds and self.spam_every < 1:
            raise ValueError(f"{self.kind} wave requires spam_every >= 1")
        # Kind-specific fields are rejected elsewhere rather than silently
        # ignored — a farm window on an eclipse wave is a spec bug.
        if self.defect_step is not None and self.kind != "covert_flash":
            raise ValueError("defect_step is covert_flash-only")
        if self.kind == "covert_flash":
            if self.defect_step is None or self.defect_step < 0:
                raise ValueError(
                    "covert_flash wave requires defect_step >= 0"
                )
        if self.farm_steps and self.kind != "score_farm":
            raise ValueError("farm_steps is score_farm-only")
        if self.kind == "score_farm" and self.farm_steps < 1:
            raise ValueError("score_farm wave requires farm_steps >= 1")
        if self.flood_offset and self.kind != "partition_flood":
            raise ValueError("flood_offset is partition_flood-only")
        if self.partition_frac and self.kind != "partition_flood":
            raise ValueError("partition_frac is partition_flood-only")
        if self.kind == "partition_flood":
            if self.flood_offset < 0:
                raise ValueError("flood_offset must be >= 0")
            if not (0.0 < self.partition_frac < 1.0):
                raise ValueError(
                    "partition_flood wave requires partition_frac in (0, 1)"
                )
            if self.stop is None:
                raise ValueError(
                    "partition_flood wave requires an explicit stop (the "
                    "heal the flood is timed against)"
                )


@dataclass(frozen=True)
class LinkWindow:
    """A link-degradation window: ingress gossip delay ``delay`` (rounds)
    installed on ``peers`` (or a random ``frac`` of peers) during
    [start, stop), restored to the ideal fabric at ``stop``."""

    start: int = 0
    stop: int = 1
    delay: int = 1
    peers: Optional[List[int]] = None
    frac: float = 0.0

    def __post_init__(self) -> None:
        if self.stop <= self.start:
            raise ValueError("link window stop must be > start")
        if self.delay < 0:
            raise ValueError("link delay must be >= 0")
        if self.peers is None and not (0.0 < self.frac <= 1.0):
            raise ValueError("link window needs peers or frac in (0, 1]")


@dataclass(frozen=True)
class SLO:
    """Pass/fail thresholds graded from the run's flight record.  ``None``
    disables a criterion.  Latency criteria read the PR-1 histogram
    (``hist_quantile`` over the final cumulative ``lat_hist`` row);
    delivery reads the final state's delivery stats; capture reads the
    attacker channels; the ``*_total``/``orphans`` criteria are the tree
    family's delivery surface (the tree record has no latency histogram,
    so latency SLOs are rejected there at compile time)."""

    min_delivery_frac: Optional[float] = None
    max_p50: Optional[float] = None                  # rounds
    max_p99: Optional[float] = None                  # rounds
    max_capture_frac: Optional[float] = None         # max over the series
    max_final_attacker_mesh_edges: Optional[int] = None
    min_final_target_honest_edges: Optional[int] = None
    # Score-standing criteria (attack waves only — graded from the
    # ``attacker_score_mean`` / ``honest_score_min`` campaign channels):
    # the ceiling asserts the defense buried the attackers' standing; the
    # floor asserts no honest peer was collaterally penalized below it.
    max_final_attacker_score: Optional[float] = None
    min_final_honest_score: Optional[float] = None
    min_delivered_total: Optional[int] = None        # tree
    max_final_orphans: Optional[int] = None          # tree
    # Failover criteria (live plane, scenario.live_runner): graded from the
    # ``final_epoch`` / ``epoch_spread`` / ``duplicate_deliveries`` record
    # channels.  ``min_final_epoch`` asserts a promotion actually happened
    # (every survivor at epoch >= N); ``max_epoch_spread`` asserts the
    # survivors CONVERGED (spread 0 = no forked regime); the duplicates cap
    # is the exactly-once delivery bound across replay/heal overlap.
    min_final_epoch: Optional[int] = None
    max_epoch_spread: Optional[int] = None
    max_duplicate_deliveries: Optional[int] = None
    # Streaming criteria (serving plane, scenario.streaming_runner): graded
    # from the ``queue_depth_peak`` / ``ingest_lat_max_s`` / ``silent_drops``
    # record channels.  ``max_queue_depth`` bounds ingest backlog under the
    # offered load; ``max_ingest_latency_s`` bounds worst-case exact
    # ingest→delivery (host seconds, quantized to chunk boundaries);
    # ``max_silent_drops`` is the conservation bound — every message must be
    # delivered, queued, or attributed to a named backpressure counter
    # (0 under ``block`` means the ring NEVER loses a message it accepted).
    max_queue_depth: Optional[int] = None
    max_ingest_latency_s: Optional[float] = None
    max_silent_drops: Optional[int] = None
    # Streaming crash-safety criteria (r14, graded from the runner's
    # ``recovery_s`` / ``lost_after_restart`` channels — emitted on every
    # streaming run, zeros when no fault fired, so the SLO never passes
    # vacuously).  ``max_recovery_s`` bounds crash→resumed wall time;
    # ``max_lost_after_restart`` is the exactly-once floor: accepted valid
    # messages neither delivered, in flight, nor attributed to a named shed
    # counter after the run (0 = no accepted message vanished in the
    # crash).  ``max_duplicate_deliveries`` (above) reads the engine's
    # content-hash duplicate counter on this plane.
    max_recovery_s: Optional[float] = None
    max_lost_after_restart: Optional[int] = None
    # Degraded-links comparison (r16, hybrid streaming runs with
    # ``compare_eager`` set): ceiling on hybrid p99 ingest→delivery divided
    # by the eager-forced twin's p99 over the same timeline.  < 1.0 asserts
    # the adaptive hybrid strictly beat pure eager under the injected loss;
    # when the eager twin completes FEWER messages than the hybrid the
    # ratio is reported as 0.0 (unboundedly worse eager tail).
    max_p99_vs_eager_ratio: Optional[float] = None
    # Self-tuning criteria (r20, streaming runs with a ``controller`` dict
    # and ``compare_static`` set — graded from the runner's
    # ``p99_vs_best_static_ratio`` / ``controller_decisions`` /
    # ``unplanned_recompiles`` channels).
    # ``max_p99_vs_best_static_ratio``: ceiling on the self-tuned engine's
    # p99 ingest→delivery divided by the BEST p99 any single static rung of
    # the same ladder achieves over the same timeline — < 1.0 asserts the
    # controller strictly beat every static configuration; when no static
    # twin completes at least as many messages as the tuned engine the
    # ratio is reported as 0.0 (every static tail is unboundedly worse).
    # ``min_controller_decisions`` asserts the controller actually acted
    # (no vacuous pass on a loop that never moved a knob);
    # ``max_unplanned_recompiles`` is the pre-warm contract: the engine's
    # ``compile_cache_size() - ladder_size()`` after the whole run,
    # crash/restore included (0 = stepping the ladder never compiled).
    max_p99_vs_best_static_ratio: Optional[float] = None
    min_controller_decisions: Optional[int] = None
    max_unplanned_recompiles: Optional[int] = None


@dataclass
class ScenarioSpec:
    """One named, seeded, fully declarative adversity campaign."""

    name: str
    family: str = "gossipsub"
    n_steps: int = 32
    seed: int = 0
    model: Dict[str, Any] = field(default_factory=dict)
    workloads: List[Workload] = field(default_factory=list)
    churn: List[ChurnPhase] = field(default_factory=list)
    attacks: List[AttackWave] = field(default_factory=list)
    links: List[LinkWindow] = field(default_factory=list)
    # Bridge for existing FaultPlan schedules: {"kills": {step: [ids]},
    # "leaves": {step: [ids]}} — the compiler lowers them alongside churn
    # (see ScenarioSpec.from_fault_plan / compiler._lower_faults).
    faults: Optional[Dict[str, Dict[str, List[int]]]] = None
    # Live-plane overrides for scenario.live_runner (ignored by the sim
    # compiler): {"n_hosts": int, "step_ms": float}.  None = the runner's
    # defaults — keeping this a plain optional dict preserves the exact
    # JSON round-trip for specs that never touch the live plane.
    live: Optional[Dict[str, Any]] = None
    # Streaming-plane config for scenario.streaming_runner (ignored by the
    # sim compiler): {"streaming_only": bool, "chunk_steps": int,
    # "capacity": int, "policy": str, "pub_width": int,
    # "completion_frac": float}.  Same plain-dict shape as ``live`` so the
    # JSON round-trip stays exact for specs that never stream.
    #
    # Fault-injection keys (r14 chaos, all optional, lowered by
    # compiler.compile_streaming_plan onto StreamingPlan.faults):
    #   "snapshot_every": int       — engine auto-snapshot period in chunks
    #                                 (defaults to 1 when a crash is staged)
    #   "crash_at_chunk": int       — kill the engine+ring after that many
    #                                 traffic chunks; recovery = fresh engine
    #                                 over an equal model + restore()
    #   "verifier_crash_at_chunk": int — drop the validation pipeline with a
    #                                 batch in flight; the producer resubmits
    #                                 its retry window (at-least-once), the
    #                                 engine's dedup keeps delivery
    #                                 exactly-once
    #   "producer_stall": {"start": int, "steps": int} — publishes scheduled
    #                                 in the window are deferred to its end
    #                                 (stall-then-flood)
    #   "clock_skew": {"at_chunk": int, "skew_s": float} — step the host
    #                                 clock the latency stamps read
    #
    # Degraded-links keys (r16 adaptive coded gossip, hybrid family):
    #   "loss": {"start_chunk": int, "stop_chunk": int, "delay": int} —
    #                                 stamp an all-peer ingress delay for
    #                                 chunks [start_chunk, stop_chunk) and
    #                                 reset to 0 after; ``delay`` semantics
    #                                 are per-family (pend-hold for
    #                                 multitopic, DECIMATION loss for the
    #                                 hybrid — the r11 asymmetry)
    #   "compare_eager": bool       — also run an eager-forced twin engine
    #                                 (switch thresholds pinned above 1.0)
    #                                 over the same timeline and emit the
    #                                 ``p99_vs_eager_ratio`` channel
    #
    # Self-tuning keys (r20 controller, both streaming families):
    #   "controller": {"ladder": [[chunk_steps, pub_width], ...],
    #                  "policy": {ControllerPolicy field overrides}} —
    #                                 run with a serve.controller.Controller
    #                                 polled at every chunk boundary over a
    #                                 pre-warmed geometry ladder (must
    #                                 contain the spec's base geometry);
    #                                 zero unplanned recompiles is asserted
    #                                 via the ``unplanned_recompiles``
    #                                 channel
    #   "compare_static": bool      — also replay the same timeline through
    #                                 one STATIC twin engine per ladder rung
    #                                 (controller disabled, no faults) and
    #                                 emit ``p99_vs_best_static_ratio`` /
    #                                 ``best_static_p99_s`` — the self-tuned
    #                                 vs best-static A/B
    #   "loss_regimes": [{"start_step": int, "stop_step": int,
    #                     "delay": int}, ...] — step-keyed (NOT chunk-keyed:
    #                                 fair across geometries) non-overlapping
    #                                 ingress-delay windows; same per-family
    #                                 delay semantics as "loss"
    streaming: Optional[Dict[str, Any]] = None
    slo: SLO = field(default_factory=SLO)
    description: str = ""
    # Provenance stamp for archived replay artifacts (r21 co-evolution):
    # {"defense_digest": str, "found_by": str, "search_seed": int, ...}.
    # Never read by the compiler — a plain optional dict (like ``live`` /
    # ``streaming``) so the exact JSON round-trip holds and specs that
    # predate the field still load.
    meta: Optional[Dict[str, Any]] = None

    def __post_init__(self) -> None:
        if self.family not in FAMILIES:
            raise ValueError(f"unknown family {self.family!r}")
        if self.n_steps < 1:
            raise ValueError("n_steps must be >= 1")

    @property
    def live_only(self) -> bool:
        """True when the scenario exercises behavior that exists only on
        the socket plane (root failover, partition heal) and therefore has
        no sim lowering.  Marked via ``live={"live_only": True, ...}`` so
        the JSON round-trip stays exact."""
        return bool((self.live or {}).get("live_only"))

    @property
    def streaming_only(self) -> bool:
        """True when the scenario is a serving-plane campaign (unbounded
        ingest through the ring into the resident engine) with no closed-sim
        lowering.  Marked via ``streaming={"streaming_only": True, ...}``."""
        return bool((self.streaming or {}).get("streaming_only"))

    # -- FaultPlan bridge ---------------------------------------------------

    @classmethod
    def from_fault_plan(cls, name: str, plan, n_steps: int, **kw):
        """Wrap a ``utils.faults.FaultPlan`` as a scenario (kills/leaves
        become the spec's ``faults`` schedule; everything else from kw)."""
        import numpy as np

        faults = {
            "kills": {
                str(t): [int(i) for i in np.flatnonzero(m)]
                for t, m in sorted(plan.kills.items())
            },
            "leaves": {
                str(t): [int(i) for i in np.flatnonzero(m)]
                for t, m in sorted(plan.leaves.items())
            },
        }
        return cls(name=name, n_steps=n_steps, faults=faults, **kw)

    # -- JSON round-trip ----------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, indent=2)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ScenarioSpec":
        d = dict(d)
        d["workloads"] = [Workload(**w) for w in d.get("workloads", [])]
        d["churn"] = [ChurnPhase(**c) for c in d.get("churn", [])]
        d["attacks"] = [AttackWave(**a) for a in d.get("attacks", [])]
        d["links"] = [LinkWindow(**l) for l in d.get("links", [])]
        slo = d.get("slo", {})
        d["slo"] = slo if isinstance(slo, SLO) else SLO(**slo)
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "ScenarioSpec":
        return cls.from_dict(json.loads(s))
