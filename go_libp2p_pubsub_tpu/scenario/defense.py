"""Defense configuration registry + the co-evolution invariant gate (r21).

The fuzzer (``tools/scenario_fuzz.py``) and the co-evolution loop
(``tools/coevolve.py``) both grade attack campaigns against a *defense*:
a score-parameter dict lowered into :class:`~..config.ScoreParams` by the
scenario compiler.  This module is the single home for those dicts —
``STANDING_DEFENSE`` (pre-taxonomy shipped config), ``HARDENED_DEFENSE``
(the cold-boot fix), and ``PROMOTED_DEFENSE`` (whatever the last
co-evolution run promoted, loaded from the committed
``promoted_defense.json`` next to this file; falls back to HARDENED when
no promotion has ever happened).

It also hosts :func:`check_invariants`, the machine-checkable gate
distilled from ``tests/test_scoring_invariants.py``.  The co-evolution
loop may ONLY grade a defense candidate after this gate passes — the
formal-model constraints (P4/P7 penalty monotonicity, P6 penalty
non-positivity, bounded mesh capture, honest-score floor) are what make
an automated search over P1-P7 weight space safe to promote.  The gate is
a plain function so the loop can *reject* candidates instead of crashing,
and so the audit artifact can record exactly which invariant each
rejected candidate violated.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "STANDING_DEFENSE",
    "HARDENED_DEFENSE",
    "PROMOTED_DEFENSE",
    "PROMOTED_PATH",
    "defense_digest",
    "load_promoted",
    "check_invariants",
]

# The standing defense: the scored config the canon shipped BEFORE the
# taxonomy PR — P4 hammer + P6 colocation, P3 at its shipped default
# (disabled; upstream guidance is that its threshold must be rate-tuned).
STANDING_DEFENSE: Dict[str, float] = {
    "invalid_message_deliveries_weight": -30.0,
    "ip_colocation_factor_weight": -1.0,
    "ip_colocation_factor_threshold": 1.0,
}

# The hardened config: the fix for the cold-boot monopoly the first fuzz
# hunt found.  P3 enabled with a threshold tuned to the fuzz mesh's
# observed steady delivery rate (~2 msgs / decay interval on the every-2
# workload).
HARDENED_DEFENSE: Dict[str, float] = dict(
    STANDING_DEFENSE,
    mesh_message_deliveries_weight=-1.0,
    mesh_message_deliveries_threshold=1.5,
    mesh_message_deliveries_activation_s=3.0,
)

# Where a co-evolution run publishes its surviving config.  Committed, so
# the shipped default is the promoted config — not a hand-picked one.
PROMOTED_PATH = os.path.join(os.path.dirname(__file__),
                             "promoted_defense.json")


def defense_digest(defense: Dict[str, float]) -> str:
    """Stable short digest of a defense dict (keys sorted, JSON encoded).

    Stamped into fuzz red reports and replay artifacts so every archived
    red names the exact config it was red AGAINST.
    """
    return hashlib.sha256(
        json.dumps(defense, sort_keys=True).encode()
    ).hexdigest()[:12]


def load_promoted(path: Optional[str] = None) -> Dict[str, float]:
    """The last promoted defense, or HARDENED when none is committed.

    The artifact is written by ``tools/coevolve.py`` as
    ``{"defense": {...}, "digest": ..., ...provenance...}``; only the
    ``defense`` dict is the config, the rest is audit trail.
    """
    p = PROMOTED_PATH if path is None else path
    try:
        with open(p) as f:
            doc = json.load(f)
        return dict(doc["defense"])
    except (OSError, KeyError, ValueError):
        return dict(HARDENED_DEFENSE)


PROMOTED_DEFENSE: Dict[str, float] = load_promoted()


# ---------------------------------------------------------------------------
# invariant gate
# ---------------------------------------------------------------------------

def _score_params(defense: Dict[str, float]):
    from ..config import ScoreParams
    return ScoreParams(**defense)


def _check_p4(defense: Dict[str, float], violations: List[str]) -> None:
    """More invalid deliveries may never RAISE a slot's score, and with a
    negative weight every extra invalid delivery must strictly lower it
    (mirrors test_p4_monotonicity_sweep at the ops level)."""
    import jax.numpy as jnp
    from ..ops import scoring as scoring_ops

    params = _score_params(defense)
    counts = np.array([0.0, 1.0, 2.0, 4.0, 8.0, 16.0])
    c = scoring_ops.TopicCounters.zeros(1, len(counts))._replace(
        invalid_message_deliveries=jnp.asarray([counts], jnp.float32),
    )
    s = np.asarray(scoring_ops.topic_score(c, params))[0]
    if not np.all(np.diff(s) <= 1e-6):
        violations.append(
            "p4_monotonicity: score increases with more invalid "
            f"deliveries (weight "
            f"{params.invalid_message_deliveries_weight:+g})"
        )
    elif params.invalid_message_deliveries_weight < 0 \
            and not np.all(np.diff(s) < 0):
        violations.append(
            "p4_monotonicity: invalid deliveries do not strictly lower "
            "the score despite a negative weight"
        )


def _check_p7(defense: Dict[str, float], violations: List[str]) -> None:
    """Behaviour penalty: more violations may never raise the global
    score (mirrors test_p7_monotonicity_sweep)."""
    import jax.numpy as jnp
    from ..ops import scoring as scoring_ops

    params = _score_params(defense)
    pens = np.array([0.0, 1.0, 2.0, 5.0, 10.0], np.float32)
    g = scoring_ops.GlobalCounters.zeros(len(pens))._replace(
        behaviour_penalty=jnp.asarray(pens)
    )
    s = np.asarray(scoring_ops.global_score(g, params))
    if not np.all(np.diff(s) <= 1e-6):
        violations.append(
            "p7_monotonicity: behaviour violations raise the global "
            f"score (weight {params.behaviour_penalty_weight:+g})"
        )


def _check_p6(defense: Dict[str, float], violations: List[str]) -> None:
    """Colocation penalty must be non-positive for every group size
    (mirrors test_colocation_penalty_monotone_sweep's sign half)."""
    import jax.numpy as jnp
    from ..ops import scoring as scoring_ops

    params = _score_params(defense)
    n = 64
    for k in (2, 8, 32):
        groups = np.arange(n, dtype=np.int32)
        groups[:k] = 0
        pen = np.asarray(
            scoring_ops.colocation_penalty(jnp.asarray(groups), params)
        )
        if not np.all(pen <= 1e-9):
            violations.append(
                f"p6_sign: colocation penalty positive for group size {k} "
                f"(weight {params.ip_colocation_factor_weight:+g})"
            )
            return


def _check_capture_and_floor(
    defense: Dict[str, float], violations: List[str]
) -> None:
    """Deep gate: k colocated sybils hold <= 2.5x their fair share of
    honest mesh slots under the candidate's P6, and no honest peer's
    score is dragged below the collateral floor on the same rollout
    (mirrors test_bounded_mesh_capture_under_sybils + the honest-floor
    bound of test_honest_score_floor_under_every_canon_attack)."""
    from ..models.attacks import sybil_colocation_attack
    from ..models.gossipsub import GossipSub

    params = _score_params(defense)
    n, k = 64, 8
    gs = GossipSub(
        n_peers=n, n_slots=16, conn_degree=8, msg_window=16,
        heartbeat_steps=4, score_params=params,
    )
    st = gs.init(seed=5)
    st, report, att = sybil_colocation_attack(gs, st, k, n_steps=24)
    captured = int(report["attacker_mesh_edges"][-1])
    honest = ~np.asarray(att) & np.asarray(st.alive)
    honest_edges = int(np.asarray(
        (st.mesh & st.nbr_valid & honest[:, None]).sum()
    ))
    frac = captured / max(honest_edges, 1)
    if frac > 2.5 * k / n:
        violations.append(
            f"mesh_capture: {k} sybils hold {frac:.3f} of honest mesh "
            f"edges (bound {2.5 * k / n:.3f})"
        )
    floor = float(report["honest_score_min"][-1])
    if not np.isfinite(floor) or floor < -2.0 \
            or floor <= params.graylist_threshold / 2 \
            or floor <= params.publish_threshold / 2:
        violations.append(
            f"honest_floor: honest score floor {floor:.3f} breaches the "
            "collateral bound under sybil colocation"
        )


def check_invariants(
    defense: Dict[str, float], deep: bool = False
) -> Tuple[bool, List[str]]:
    """Gate a defense candidate against the formal-model invariants.

    Returns ``(ok, violations)``.  The shallow gate (construction +
    P4/P6/P7 ops sweeps) is cheap enough to run on every candidate; the
    deep gate adds the 64-peer sybil rollout (bounded mesh capture +
    honest-score floor) and is meant for candidates that survived the
    shallow gate and are about to be graded.  Never raises for a bad
    candidate — rejection is data, recorded in the audit trail.
    """
    violations: List[str] = []
    try:
        _score_params(defense)
    except (TypeError, ValueError) as e:
        return False, [f"params: {str(e).splitlines()[0][:100]}"]
    _check_p4(defense, violations)
    _check_p7(defense, violations)
    _check_p6(defense, violations)
    if deep and not violations:
        _check_capture_and_floor(defense, violations)
    return (not violations), violations
