"""The canon: named, committed adversity campaigns with SLO thresholds.

These are the regression surface PERF.md points at — each returns a fresh
:class:`~.spec.ScenarioSpec` (specs are cheap data; mutate your copy
freely).  Sizes are chosen to run the whole suite on a laptop CPU in tens
of seconds; the defense parameterizations mirror the known-good settings
the slow attack tests converged on, so a canon verdict flipping red means
the protocol moved, not the scenario.

``CANON`` maps name -> builder; ``build(name)`` / ``build_all()`` resolve.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from .spec import SLO, AttackWave, ChurnPhase, LinkWindow, ScenarioSpec, Workload

_MESH_64 = dict(n_peers=64, n_slots=16, conn_degree=8, msg_window=64,
                heartbeat_steps=4)


def steady_state() -> ScenarioSpec:
    """Healthy mesh, constant publish load, no adversity — the floor every
    other verdict is read against."""
    return ScenarioSpec(
        name="steady_state",
        family="gossipsub",
        n_steps=24,
        seed=7,
        model=dict(_MESH_64),
        workloads=[Workload(kind="constant", start=2, stop=20, every=2)],
        slo=SLO(min_delivery_frac=0.97, max_p50=2.0, max_p99=6.0),
        description="64-peer mesh, one publish every 2 rounds, no faults.",
    )


def flash_crowd() -> ScenarioSpec:
    """A burst of simultaneous publishes from distinct random peers — the
    flood_publish/fanout hot path under contention."""
    return ScenarioSpec(
        name="flash_crowd",
        family="gossipsub",
        n_steps=24,
        seed=11,
        model=dict(_MESH_64),
        workloads=[Workload(kind="burst", start=4, n_msgs=12)],
        slo=SLO(min_delivery_frac=0.97, max_p99=8.0),
        description="12 messages published in the same round.",
    )


def churn_10pct() -> ScenarioSpec:
    """~10% of the mesh abruptly killed across the run while traffic keeps
    flowing; deliveries must hold for the survivors."""
    return ScenarioSpec(
        name="churn_10pct",
        family="gossipsub",
        n_steps=40,
        seed=13,
        model=dict(_MESH_64),
        workloads=[Workload(kind="constant", start=2, stop=34, every=2)],
        churn=[ChurnPhase(start=6, stop=30, every=4, kills_per_event=1)],
        slo=SLO(min_delivery_frac=0.90, max_p99=10.0),
        description="6 abrupt kills (about 10% of 64) under constant load.",
    )


def partition_heal() -> ScenarioSpec:
    """A block of peers drops at once and revives 8 rounds later; gossip
    (IHAVE within the mcache window) must backfill what they missed."""
    return ScenarioSpec(
        name="partition_heal",
        family="gossipsub",
        n_steps=48,
        seed=17,
        model=dict(_MESH_64, params={"history_gossip": 3}),
        workloads=[Workload(kind="constant", start=2, stop=40, every=2)],
        churn=[ChurnPhase(
            start=12, stop=13, every=1, kills_per_event=10, rejoin_after=8,
        )],
        slo=SLO(min_delivery_frac=0.85),
        description="10 peers partitioned for 8 rounds, then healed.",
    )


def sybil_colocation() -> ScenarioSpec:
    """Sybils behind one IP try to saturate honest meshes; the P6
    colocation penalty must cap their capture."""
    return ScenarioSpec(
        name="sybil_colocation",
        family="gossipsub",
        n_steps=48,
        seed=19,
        model=dict(
            n_peers=96, n_slots=16, conn_degree=8, msg_window=32,
            heartbeat_steps=4,
            score_params={
                "ip_colocation_factor_weight": -1.0,
                "ip_colocation_factor_threshold": 1.0,
            },
        ),
        workloads=[Workload(kind="constant", start=2, stop=32, every=4)],
        attacks=[AttackWave(kind="sybil", n_attackers=12)],
        # The 12 penalized sybils (12.5% of peers) score below the gossip
        # threshold and stop receiving — delivery_frac ~0.88 IS the defense
        # working; the floor guards the honest 87.5%.
        slo=SLO(min_delivery_frac=0.85, max_capture_frac=0.30),
        description="12 colocated sybils vs the P6 defense.",
    )


def eclipse_backoff_spam() -> ScenarioSpec:
    """The target's whole converged mesh turns adversarial (receive, never
    relay) AND graft-spams through prune backoff; scoring must re-open
    honest mesh slots for the target."""
    return ScenarioSpec(
        name="eclipse_backoff_spam",
        family="gossipsub",
        n_steps=48,
        seed=23,
        model=dict(
            n_peers=96, n_slots=32, conn_degree=20, msg_window=32,
            heartbeat_steps=4,
            score_params={
                "mesh_message_deliveries_weight": -1.0,
                "mesh_message_deliveries_threshold": 1.5,
                "mesh_message_deliveries_activation_s": 3.0,
            },
        ),
        workloads=[Workload(kind="constant", start=2, stop=40, every=2)],
        attacks=[AttackWave(
            kind="eclipse", target=5, start=4, graft_spam=True,
        )],
        slo=SLO(min_final_target_honest_edges=1),
        description="Eclipse of peer 5 with backoff graft spam.",
    )


def spam_flood() -> ScenarioSpec:
    """Invalid-message flood; P4 must bury the spammers' scores while
    honest delivery holds."""
    return ScenarioSpec(
        name="spam_flood",
        family="gossipsub",
        n_steps=40,
        seed=29,
        model=dict(
            n_peers=96, n_slots=16, conn_degree=8, msg_window=64,
            heartbeat_steps=4,
            score_params={"invalid_message_deliveries_weight": -30.0},
        ),
        workloads=[Workload(kind="constant", start=2, stop=32, every=4)],
        attacks=[AttackWave(
            kind="spam", n_attackers=4, start=4, stop=24, spam_every=4,
        )],
        slo=SLO(min_delivery_frac=0.90),
        description="4 spammers, one invalid publish each every 4 rounds.",
    )


def cold_boot_eclipse() -> ScenarioSpec:
    """Monopolists own the target's mesh from step 0 — before any P1/P2
    history exists on either side (the compiler zeroes the touched edges'
    counters).  The P3 delivery-deficit defense must evict the silent
    monopolists on fresh evidence alone and re-open honest slots."""
    return ScenarioSpec(
        name="cold_boot_eclipse",
        family="gossipsub",
        n_steps=48,
        seed=67,
        model=dict(
            n_peers=96, n_slots=32, conn_degree=20, msg_window=32,
            heartbeat_steps=4,
            score_params={
                "mesh_message_deliveries_weight": -1.0,
                "mesh_message_deliveries_threshold": 1.5,
                "mesh_message_deliveries_activation_s": 3.0,
            },
        ),
        workloads=[Workload(kind="constant", start=2, stop=40, every=2)],
        attacks=[AttackWave(
            kind="cold_boot_eclipse", target=5, n_attackers=8,
            start=0, stop=40,
        )],
        # Measured (seed 67): target regains 3 honest edges, delivery 1.00,
        # attackers at -0.84; P3 drags honest bystanders to -0.71 before
        # activation, hence the generous floor.
        slo=SLO(
            min_delivery_frac=0.97,
            min_final_target_honest_edges=1,
            max_final_attacker_score=-0.25,
            min_final_honest_score=-2.0,
        ),
        description="8 score-less monopolists own peer 5's mesh at boot; "
                    "P3 deficit evidence must evict them.",
    )


def covert_flash() -> ScenarioSpec:
    """Attackers behave honestly for 16 rounds, then defect simultaneously
    (silence + gossip mute + invalid spam).  Reaction time is the test: the
    P4 hammer must bury the flash mob even though it defects with banked
    honest reputation."""
    return ScenarioSpec(
        name="covert_flash",
        family="gossipsub",
        n_steps=48,
        seed=71,
        model=dict(
            n_peers=96, n_slots=16, conn_degree=8, msg_window=64,
            heartbeat_steps=4,
            score_params={"invalid_message_deliveries_weight": -30.0},
        ),
        workloads=[Workload(kind="constant", start=2, stop=40, every=2)],
        attacks=[AttackWave(
            kind="covert_flash", n_attackers=6, start=0, stop=40,
            defect_step=16, spam_every=4,
        )],
        # Measured (seed 71): attackers end at -1.15, honest floor exactly
        # 0.0, delivery 1.00.
        slo=SLO(
            min_delivery_frac=0.97,
            max_final_attacker_score=-0.5,
            min_final_honest_score=-0.25,
        ),
        description="6 sleepers defect at step 16 with spam + silence.",
    )


def score_farm() -> ScenarioSpec:
    """Attackers bank P1/P2 credit with valid publishes for 16 rounds,
    then cash it in as invalid-spam cover.  The squared P4 penalty (and
    P2's fast decay) must overcome the farmed reputation."""
    return ScenarioSpec(
        name="score_farm",
        family="gossipsub",
        n_steps=48,
        seed=73,
        model=dict(
            n_peers=96, n_slots=16, conn_degree=8, msg_window=96,
            heartbeat_steps=4,
            score_params={"invalid_message_deliveries_weight": -80.0},
        ),
        workloads=[Workload(kind="constant", start=2, stop=40, every=4)],
        attacks=[AttackWave(
            kind="score_farm", n_attackers=3, start=2, farm_steps=16,
            spam_every=2,
        )],
        # Measured (seed 73): farmed credit peaks ~+0.5 mid-farm; the spam
        # phase drives the attackers to about -5.6 while honest stays at 0.
        slo=SLO(
            min_delivery_frac=0.97,
            max_final_attacker_score=-1.0,
            min_final_honest_score=-0.25,
        ),
        description="3 farmers bank 16 rounds of valid P2 credit, then "
                    "flip to invalid spam.",
    )


def self_promo_ihave() -> ScenarioSpec:
    """Crafted gossip: attackers publish valid self-originated traffic,
    advertise ONLY their own ids, and never serve the IWANTs those ads
    attract.  On a delayed fabric (where gossip actually carries traffic)
    every unserved ask charges P7 — promise tracking must bury the
    promoters while their P2 credit stays honestly earned."""
    return ScenarioSpec(
        name="self_promo_ihave",
        family="gossipsub",
        n_steps=48,
        seed=79,
        model=dict(
            n_peers=96, n_slots=16, conn_degree=8, msg_window=96,
            heartbeat_steps=2,
            score_params={"behaviour_penalty_weight": -5.0},
        ),
        workloads=[Workload(kind="constant", start=2, stop=40, every=2)],
        links=[LinkWindow(start=0, stop=44, delay=2, frac=1.0)],
        attacks=[AttackWave(
            kind="self_promo_ihave", n_attackers=4, start=2, stop=44,
            spam_every=4,
        )],
        # Measured (seed 79): broken-promise counter reaches ~2.7 per
        # attacker; squared P7 lands them at -9.2 with honest floor 0.0 and
        # delivery 0.994 despite the +2 global ingress delay.
        slo=SLO(
            min_delivery_frac=0.97,
            max_final_attacker_score=-2.0,
            min_final_honest_score=-0.25,
        ),
        description="4 self-promoters craft IHAVEs for their own ids and "
                    "ghost the asks; P7 promise tracking answers.",
    )


def partition_flood() -> ScenarioSpec:
    """A fifth of the mesh is partitioned away; the moment it heals, the
    attackers open an invalid-spam flood timed to pollute the gossip
    backfill the healed cohort depends on.  P4 must shut the flood down
    without starving the heal."""
    return ScenarioSpec(
        name="partition_flood",
        family="gossipsub",
        n_steps=56,
        seed=83,
        model=dict(
            n_peers=96, n_slots=16, conn_degree=8, msg_window=96,
            heartbeat_steps=4,
            params={"history_gossip": 3},
            score_params={"invalid_message_deliveries_weight": -30.0},
        ),
        workloads=[Workload(kind="constant", start=2, stop=48, every=2)],
        attacks=[AttackWave(
            kind="partition_flood", n_attackers=4, start=10, stop=26,
            partition_frac=0.2, flood_offset=2, spam_every=2,
        )],
        # Measured (seed 83): delivery 0.97 across the cut, attackers
        # buried at -8.7, honest floor 0.0.
        slo=SLO(
            min_delivery_frac=0.90,
            max_final_attacker_score=-2.0,
            min_final_honest_score=-0.25,
        ),
        description="19 peers cut for 16 rounds; spam flood opens 2 rounds "
                    "after the heal.",
    )


def fuzz_regression_cold_boot() -> ScenarioSpec:
    """Regression for the fuzzer's first finding (tools/scenario_fuzz.py,
    budget 40, seed 0, sample 0): ONE silent attacker that owns a single
    target mesh slot from boot keeps a clean standing for the whole
    campaign under the standing config — P3 disabled means no deficit
    evidence ever accrues, and the SLO's ``max_final_attacker_score``
    goes red (+0.08 > -0.25).  The committed red replay is
    ``tests/golden/fuzz_red_cold_boot.json``; this entry is its fixed
    twin — the SAME attack under the hardened config (P3 enabled) must
    grade green against the SAME standing SLO."""
    return ScenarioSpec(
        name="fuzz_regression_cold_boot",
        family="gossipsub",
        n_steps=24,
        seed=643811320,  # the fuzzed sample's own lowering seed
        model=dict(
            n_peers=64, n_slots=16, conn_degree=8, msg_window=128,
            heartbeat_steps=4,
            # HARDENED_DEFENSE in tools/scenario_fuzz.py: the standing
            # config + P3 — the fix for the cold-boot monopoly.
            score_params={
                "invalid_message_deliveries_weight": -30.0,
                "ip_colocation_factor_weight": -1.0,
                "ip_colocation_factor_threshold": 1.0,
                "mesh_message_deliveries_weight": -1.0,
                "mesh_message_deliveries_threshold": 1.5,
                "mesh_message_deliveries_activation_s": 3.0,
            },
        ),
        workloads=[Workload(kind="constant", start=2, stop=20, every=2)],
        attacks=[AttackWave(
            kind="cold_boot_eclipse", target=5, n_attackers=1,
            start=3, stop=24,
        )],
        # Measured: attacker buried at -7.67 on P3 deficit, target regains
        # 3 honest edges, delivery 0.941, honest floor 0.0 — green on the
        # fuzzer's standing SLO where the standing config grades red.
        slo=SLO(
            min_delivery_frac=0.90,
            max_capture_frac=0.35,
            min_final_target_honest_edges=1,
            max_final_attacker_score=-0.25,
            min_final_honest_score=-2.0,
        ),
        description="Fuzzer-found cold-boot monopoly (seed 0, sample 0), "
                    "minimized and replayed under the hardened config.",
    )


def degraded_links() -> ScenarioSpec:
    """A quarter of the mesh behind slow ingress links for a window —
    deliveries hold, the latency tail pays."""
    return ScenarioSpec(
        name="degraded_links",
        family="gossipsub",
        n_steps=32,
        seed=31,
        model=dict(_MESH_64),
        workloads=[Workload(kind="constant", start=2, stop=28, every=2)],
        links=[LinkWindow(start=6, stop=22, delay=2, frac=0.25)],
        slo=SLO(min_delivery_frac=0.95),
        description="25% of peers at +2 rounds ingress delay for 16 rounds.",
    )


def degraded_links_rlnc() -> ScenarioSpec:
    """The degraded-link campaign on the CODED plane: same 64-peer graph
    shape, a quarter of the peers behind lossy ingress (for rlnc the
    window is DECIMATION — off-gate fragments are lost, not held), graded
    by the same delivery SLO.  Rateless coding must ride through loss the
    two-phase mesh needs IWANT round trips to repair."""
    return ScenarioSpec(
        name="degraded_links_rlnc",
        family="rlnc",
        n_steps=40,
        seed=53,
        model=dict(n_peers=64, n_slots=16, conn_degree=8, msg_window=64,
                   gen_size=4),
        workloads=[Workload(kind="constant", start=2, stop=24, every=2)],
        links=[LinkWindow(start=6, stop=22, delay=2, frac=0.25)],
        slo=SLO(min_delivery_frac=0.95),
        description="25% of peers dropping 2 of 3 ingress rounds for 16 "
                    "rounds, coded fabric (gen_size=4).",
    )


def tree_churn_heal() -> ScenarioSpec:
    """TreeCast under leave/kill churn with rejoin: the repair walk must
    re-attach everyone and drain the root's queue."""
    return ScenarioSpec(
        name="tree_churn_heal",
        family="treecast",
        n_steps=64,
        seed=37,
        model=dict(max_peers=32, n_peers=24),
        workloads=[Workload(kind="constant", start=4, stop=48, every=8)],
        churn=[
            ChurnPhase(start=8, stop=32, every=8, kills_per_event=1,
                       graceful=True, rejoin_after=12),
            ChurnPhase(start=12, stop=36, every=12, kills_per_event=1,
                       rejoin_after=16),
        ],
        slo=SLO(max_final_orphans=0, min_delivered_total=1),
        description="Graceful leaves + abrupt kills with rejoin on a tree.",
    )


def multitopic_hot_publisher() -> ScenarioSpec:
    """One hot publisher per topic across a shared mesh fabric."""
    return ScenarioSpec(
        name="multitopic_hot_publisher",
        family="multitopic",
        n_steps=24,
        seed=41,
        model=dict(n_topics=2, n_peers=64, n_slots=16, conn_degree=8,
                   msg_window=64, heartbeat_steps=4),
        workloads=[
            Workload(kind="hot", src=3, topic=0, start=2, stop=20, every=2),
            Workload(kind="hot", src=9, topic=1, start=3, stop=20, every=2),
        ],
        slo=SLO(min_delivery_frac=0.90),
        description="Two topics, one pinned publisher each.",
    )


def root_kill_failover() -> ScenarioSpec:
    """LIVE-ONLY: the root (sole publisher, the protocol's single point of
    failure) is abruptly killed mid-run.  The survivors must converge on
    successor #1, which promotes itself under a new epoch, re-adopts the
    orphaned subtrees, and replays the uncertainty window; buffered
    publishes resume through the promoted root.  Graded on exact delivery
    (1.00 including replay), epoch agreement (everyone on the SAME new
    epoch — no fork), and zero duplicate deliveries."""
    return ScenarioSpec(
        name="root_kill_failover",
        family="gossipsub",
        n_steps=48,
        seed=43,
        workloads=[Workload(kind="constant", start=2, stop=44, every=2)],
        live={
            "n_hosts": 16,
            "kill_root_at": 12,
            "settle_s": 2.0,
            "live_only": True,
        },
        slo=SLO(
            min_delivery_frac=1.0,
            min_final_epoch=1,
            max_epoch_spread=0,
            max_duplicate_deliveries=0,
        ),
        description="Root killed at step 12; successor promotes, epoch "
                    "fences the old regime, survivors lose nothing.",
    )


def live_partition_heal() -> ScenarioSpec:
    """LIVE-ONLY: a minority cohort is blackholed away from the rest of the
    tree (dials fail, existing cross-cut streams reset on first write) and
    re-merges when the window lifts.  The minority must NOT mint an epoch
    (quorum gate: parked degraded read-only), and on heal the forward-log
    replay plus content-hash dedup must close the loss window without a
    single duplicate delivery."""
    return ScenarioSpec(
        name="live_partition_heal",
        family="gossipsub",
        n_steps=64,
        seed=47,
        workloads=[Workload(kind="constant", start=2, stop=56, every=2)],
        live={
            "n_hosts": 16,
            "settle_s": 2.0,
            "live_only": True,
            "partition": {"start": 12, "stop": 40, "peers": [1, 6, 9, 13]},
        },
        slo=SLO(
            min_delivery_frac=0.98,
            max_epoch_spread=0,
            max_duplicate_deliveries=0,
        ),
        description="4 peers blackholed for 28 steps; minority parks "
                    "(no split-brain epoch), heals by replay + dedup.",
    )


# One shared model dict for the streaming pair: the resident engine's jit
# cache keys on the model's value semantics, so equal configs let the
# second scenario reuse the first one's compiled chunk.
_STREAM_MESH = dict(n_topics=2, n_peers=64, n_slots=16, conn_degree=8,
                    msg_window=64, heartbeat_steps=4)


def streaming_steady() -> ScenarioSpec:
    """STREAMING-ONLY: constant two-topic load through the serving plane's
    ingest ring into the resident engine under the ``block`` policy.  The
    conservation SLO is the point: zero silent drops — every accepted
    message is delivered, queued, or attributed to a named counter — while
    the queue stays shallow and exact ingest→delivery latency (host clocks,
    quantized to chunk boundaries) stays bounded."""
    return ScenarioSpec(
        name="streaming_steady",
        family="multitopic",
        n_steps=32,
        seed=59,
        model=dict(_STREAM_MESH),
        workloads=[
            Workload(kind="constant", topic=0, start=0, stop=32, every=2),
            Workload(kind="constant", topic=1, start=1, stop=32, every=2),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 8,
            "capacity": 16,
            "policy": "block",
        },
        slo=SLO(
            min_delivery_frac=0.97,
            max_queue_depth=16,
            max_ingest_latency_s=30.0,   # generous: CPU chunks, not rounds
            max_silent_drops=0,
        ),
        description="Two-topic constant stream, block backpressure, zero "
                    "silent drops.",
    )


def streaming_burst_overload() -> ScenarioSpec:
    """STREAMING-ONLY: a flash crowd bigger than the ring under
    ``drop_oldest`` — overload is the SCENARIO.  The ring must shed load
    through its named eviction counter only (silent_drops stays 0), depth
    must never exceed capacity, and whatever actually reached the device
    must still deliver."""
    return ScenarioSpec(
        name="streaming_burst_overload",
        family="multitopic",
        n_steps=32,
        seed=61,
        model=dict(_STREAM_MESH),
        workloads=[
            Workload(kind="burst", topic=0, start=0, n_msgs=24),
            Workload(kind="constant", topic=1, start=2, stop=26, every=4),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 8,
            "capacity": 12,
            "policy": "drop_oldest",
        },
        slo=SLO(
            min_delivery_frac=0.95,
            max_queue_depth=12,
            max_silent_drops=0,
        ),
        description="24-message burst into a 12-deep ring; shed load is "
                    "counted eviction, never silent.",
    )


def streaming_engine_crash_recovery() -> ScenarioSpec:
    """STREAMING-ONLY chaos: the resident engine is killed after its second
    loaded chunk — host state, ring and all — and must come back from its
    last durable snapshot through the watchdog restart path.  The crash
    SLOs are the r14 contract: recovery bounded, ZERO accepted messages
    lost, ZERO duplicate deliveries (the replayed ring messages pass the
    engine's content-hash dedup), and the conservation ledger still exact
    across the checkpoint/restore cycle."""
    return ScenarioSpec(
        name="streaming_engine_crash_recovery",
        family="multitopic",
        n_steps=32,
        seed=101,
        model=dict(_STREAM_MESH),
        workloads=[
            Workload(kind="constant", topic=0, start=0, stop=32, every=2),
            Workload(kind="constant", topic=1, start=1, stop=32, every=2),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 8,
            "capacity": 16,
            "policy": "block",
            "snapshot_every": 1,
            "crash_at_chunk": 2,
        },
        slo=SLO(
            min_delivery_frac=0.97,
            max_queue_depth=16,
            max_silent_drops=0,
            max_recovery_s=60.0,         # generous: CPU restore + replay
            max_lost_after_restart=0,
            max_duplicate_deliveries=0,
        ),
        description="Engine killed after chunk 2; snapshot restore must "
                    "lose nothing and deliver nothing twice.",
    )


def streaming_verifier_crash() -> ScenarioSpec:
    """STREAMING-ONLY chaos: the validation pipeline dies with a batch in
    flight after the second chunk's submissions.  The producer resubmits
    its retry window at-least-once — including the previous, already
    admitted group — and the engine's content-hash dedup must keep
    delivery exactly-once (zero duplicates, zero losses, ledger exact)."""
    return ScenarioSpec(
        name="streaming_verifier_crash",
        family="multitopic",
        n_steps=32,
        seed=103,
        model=dict(_STREAM_MESH),
        workloads=[
            Workload(kind="constant", topic=0, start=0, stop=32, every=2),
            Workload(kind="constant", topic=1, start=1, stop=32, every=2),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 8,
            "capacity": 16,
            "policy": "block",
            "verifier_crash_at_chunk": 2,
        },
        slo=SLO(
            min_delivery_frac=0.97,
            max_queue_depth=16,
            max_silent_drops=0,
            max_lost_after_restart=0,
            max_duplicate_deliveries=0,
        ),
        description="Verifier pool dies mid-batch; at-least-once resubmit "
                    "+ content-hash dedup = exactly-once delivery.",
    )


# Shared hybrid-plane model dict (r16): single-topic adaptive coded mesh.
# Small mesh (CPU-honest canon runtimes) but a real generation size, so the
# crash canon restores genuinely partial decode ranks.  Same value-semantics
# sharing trick as _STREAM_MESH: both hybrid canons reuse one compiled chunk.
_HYBRID_MESH = dict(n_peers=32, n_slots=8, conn_degree=6,
                    msg_window=16, heartbeat_steps=4, gen_size=4,
                    switch_hi=0.35, switch_lo=0.15)


def streaming_degraded_links() -> ScenarioSpec:
    """STREAMING-ONLY (hybrid plane): a sustained degraded-link window —
    per-receiver ingress decimation delay=2 (2/3 of data-plane receipts
    lost) across the first three chunks — while a constant stream ingests.
    The per-edge loss estimator must cross ``switch_hi`` and flip lossy
    edges to RLNC coded fragments; the comparative SLO is the point: the
    adaptive plane's p99 ingest→delivery must beat an eager-forced twin
    replaying the identical timeline (ratio < 1, or 0.0 when eager never
    finishes at all).  The window ends before the drain so the twin gets
    clean fabric to catch up on — the ratio measures the coding gain, not
    an eager blackout."""
    return ScenarioSpec(
        name="streaming_degraded_links",
        family="hybrid",
        n_steps=32,
        seed=107,
        model=dict(_HYBRID_MESH),
        workloads=[
            Workload(kind="constant", topic=0, start=0, stop=24, every=2),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 8,
            "capacity": 16,
            "policy": "block",
            "loss": {"start_chunk": 0, "stop_chunk": 3, "delay": 2},
            "compare_eager": True,
        },
        slo=SLO(
            min_delivery_frac=0.97,
            max_queue_depth=16,
            max_silent_drops=0,
            max_p99_vs_eager_ratio=0.99,
        ),
        description="Three lossy chunks (delay=2); adaptive coded plane "
                    "must beat the eager-forced twin's p99.",
    )


def streaming_rlnc_crash_recovery() -> ScenarioSpec:
    """STREAMING-ONLY chaos (hybrid plane): the engine is killed after its
    second chunk while edges are coded and generations sit at PARTIAL rank
    — the checkpoint carries per-(peer, generation) decode basis state, so
    the restored engine resumes mid-decode instead of re-collecting
    fragments from rank 0.  The r14 crash contract still holds leaf-for-
    leaf: bounded recovery, zero accepted messages lost, zero duplicate
    deliveries, one compiled chunk across the kill."""
    return ScenarioSpec(
        name="streaming_rlnc_crash_recovery",
        family="hybrid",
        n_steps=32,
        seed=109,
        model=dict(_HYBRID_MESH),
        workloads=[
            Workload(kind="constant", topic=0, start=0, stop=24, every=2),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 8,
            "capacity": 16,
            "policy": "block",
            "snapshot_every": 1,
            "crash_at_chunk": 2,
            "loss": {"start_chunk": 0, "stop_chunk": 3, "delay": 2},
        },
        slo=SLO(
            min_delivery_frac=0.97,
            max_queue_depth=16,
            max_silent_drops=0,
            max_recovery_s=60.0,         # generous: CPU restore + replay
            max_lost_after_restart=0,
            max_duplicate_deliveries=0,
        ),
        description="Engine killed mid-generation under loss; restored "
                    "decode basis finishes delivery exactly-once.",
    )


# Dedicated mesh for the self-tuning canon (r20).  A DISTINCT value from
# _HYBRID_MESH on purpose: the drifting canon asserts
# ``compile_cache_size() == ladder_size()`` over its whole run, and a mesh
# value shared with another canon would let that canon's compiled chunk
# leak into (or out of) the assertion.  msg_window=64 keeps every ladder
# rung eviction-safe: the widest rung pops 32 slots per chunk, so a
# message published late in a chunk survives at least one full boundary
# before its slot cursor wraps — late-published burst tails fold their
# completions instead of being evicted.
_DRIFT_MESH = dict(n_peers=32, n_slots=8, conn_degree=6,
                   msg_window=64, heartbeat_steps=4, gen_size=4,
                   switch_hi=0.35, switch_lo=0.15)


def streaming_drifting_load() -> ScenarioSpec:
    """STREAMING-ONLY (hybrid plane, self-tuning): a drifting workload —
    a 480-message burst storm early, a diurnal constant trickle, a ramp
    doubling it, then a sustained loss-regime shift (ingress decimation
    delay=3, deliveries stretch to ~5 rounds) — served by the controller
    with a three-rung pre-warmed geometry ladder and an aggressive
    initial durability posture (snapshot every chunk).

    The comparative SLO is the whole point: the self-tuned engine must
    beat EVERY static configuration of the same engine on p99
    ingest→delivery.  The deciding phase is the burst: its tail latency
    is a SUM of many chunk walls, so host-noise on individual walls
    averages out and the gap between engines is structural, not lucky.
    The wide rung drains the burst in ~15 chunks but pays the ~10 ms
    every-chunk snapshot tax on each one (~150 ms of pure tax in the
    tail); the narrow rung needs ~30 chunks AND pays the tax; the long
    rung's per-message publish cost makes its burst chunks the most
    expensive of all.  Only the tuned engine clears it on cheap walls:
    it escalates to the wide rung on ring-depth pressure AND stretches
    the snapshot cadence once the measured snapshot cost exceeds
    ``snapshot_cost_frac`` of the chunk wall — decisions the statics by
    definition cannot make.  The burst sits BEFORE the loss window so
    every engine's burst tail drains on clean chunks; the loss phase
    (decode cost is data-dependent and hits all geometries alike —
    ~5 chunk walls per delivery) then multiplies the statics' snapshot
    tax again, while never dominating the tuned engine's p99.  The long
    rung is the carry escape hatch (``carry_up_chunks=8`` keeps it out
    of this canon: loss carry tops out near 4) — present, pre-warmed,
    asserted non-compiling, but never profitable here.  Zero unplanned
    recompiles are graded over the WHOLE run including the static
    twins, which reuse the tuned engine's model value and warm cache."""
    return ScenarioSpec(
        name="streaming_drifting_load",
        family="hybrid",
        n_steps=144,
        seed=113,
        model=dict(_DRIFT_MESH),
        workloads=[
            Workload(kind="constant", topic=0, start=0, stop=144, every=4),
            Workload(kind="constant", topic=0, start=42, stop=56, every=4),
            Workload(kind="burst", topic=0, n_msgs=480, start=16),
        ],
        streaming={
            "streaming_only": True,
            "chunk_steps": 4,
            "pub_width": 4,
            "capacity": 768,
            "policy": "block",
            "snapshot_every": 1,
            "controller": {
                "ladder": [[4, 4], [4, 8], [24, 1]],
                "policy": {"carry_up_chunks": 8},
            },
            "compare_static": True,
            "loss_regimes": [
                {"start_step": 96, "stop_step": 140, "delay": 3},
            ],
        },
        slo=SLO(
            min_delivery_frac=0.97,
            max_queue_depth=768,
            max_silent_drops=0,
            max_p99_vs_best_static_ratio=0.95,
            min_controller_decisions=4,
            max_unplanned_recompiles=0,
        ),
        description="Diurnal ramp + burst storm + loss-regime shift; the "
                    "self-tuned engine must beat every static rung on p99.",
    )


CANON: Dict[str, Callable[[], ScenarioSpec]] = {
    "steady_state": steady_state,
    "flash_crowd": flash_crowd,
    "churn_10pct": churn_10pct,
    "partition_heal": partition_heal,
    "sybil_colocation": sybil_colocation,
    "eclipse_backoff_spam": eclipse_backoff_spam,
    "spam_flood": spam_flood,
    "cold_boot_eclipse": cold_boot_eclipse,
    "covert_flash": covert_flash,
    "score_farm": score_farm,
    "self_promo_ihave": self_promo_ihave,
    "partition_flood": partition_flood,
    "fuzz_regression_cold_boot": fuzz_regression_cold_boot,
    "degraded_links": degraded_links,
    "degraded_links_rlnc": degraded_links_rlnc,
    "tree_churn_heal": tree_churn_heal,
    "multitopic_hot_publisher": multitopic_hot_publisher,
    "root_kill_failover": root_kill_failover,
    "live_partition_heal": live_partition_heal,
    "streaming_steady": streaming_steady,
    "streaming_burst_overload": streaming_burst_overload,
    "streaming_engine_crash_recovery": streaming_engine_crash_recovery,
    "streaming_verifier_crash": streaming_verifier_crash,
    "streaming_degraded_links": streaming_degraded_links,
    "streaming_rlnc_crash_recovery": streaming_rlnc_crash_recovery,
    "streaming_drifting_load": streaming_drifting_load,
}


def build(name: str) -> ScenarioSpec:
    try:
        return CANON[name]()
    except KeyError:
        raise KeyError(
            f"unknown canon scenario {name!r}; have: {', '.join(CANON)}"
        ) from None


def build_all(names: List[str] | None = None) -> List[ScenarioSpec]:
    return [build(n) for n in (names or list(CANON))]
