"""Streaming-plane scenario execution: spec timeline → ring → resident engine.

The sim plane lowers a campaign to event tensors and runs ONE scan; the
streaming plane replays the same declarative workloads as an *open* stream:
each timeline step's publishes are signed, batch-verified by the
:class:`~..crypto.pipeline.ValidationPipeline` (the crypto stage sits ahead
of enqueue, so a forged message enters the ring already marked invalid and
is asserted non-delivered on device), pushed through the
:class:`~..serve.ingest.IngestRing` under the spec's backpressure policy,
and drained by a resident :class:`~..serve.engine.StreamingEngine` whose
compiled chunk never changes shape.

The record it grades is host truth, not device telemetry: queue-depth
series from the ring, exact ingest→delivery latencies from the engine's
host clocks (quantized to chunk boundaries — see ``serve.engine``), and
the ring's conservation ledger (``silent_drops`` must be 0 under every
policy).  ``slo.evaluate`` reads these through the streaming SLO channels.

Chaos (r14): the plan's fault stages are injected at chunk boundaries,
deterministically —

- ``crash_at_chunk``: the engine AND ring are discarded (honest host-state
  loss) and replaced by a fresh pair over an equal model; recovery goes
  through ``Watchdog.restart_engine`` → ``StreamingEngine.restore()``,
  which reuses the shared compiled rollout (no recompile) and replays the
  snapshot's accepted-but-undelivered ring messages;
- ``verifier_crash_at_chunk``: the validation pipeline dies with a batch
  in flight (``drop_pending``); the producer resubmits its retry window —
  the last two chunk groups, at-least-once — and the engine's content-hash
  dedup keeps delivery exactly-once;
- ``producer_stall``: lowered into the timeline by the compiler
  (stall-then-flood);
- ``clock_skew``: the shared host clock steps by ``skew_s`` mid-run; the
  engine clamps-and-counts any negative ingest→delivery interval.

Every streaming run emits ``recovery_s`` / ``lost_after_restart`` /
``duplicate_deliveries`` channels (zeros when unfaulted) so the crash SLOs
always grade a real measurement.

Self-tuning (r20): a ``controller`` block on the plan runs the campaign
under a :class:`~..serve.controller.Controller` polled at every chunk
boundary over a pre-warmed geometry ladder — the step pointer follows the
engine's CURRENT chunk length, not the constructed one.  ``loss_regimes``
are step-keyed ingress-delay windows (fair across geometries), and
``compare_static`` replays the same timeline + regimes through one static
twin per ladder rung (controller off, faults off) to emit the
self-tuned-vs-best-static A/B channels the r20 SLOs grade.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import slo as slo_mod
from .compiler import StreamingPlan, build_model, compile_streaming_plan
from .spec import ScenarioSpec


class StreamingPlaneError(RuntimeError):
    """The streaming plane failed to COME UP for a scenario (model build,
    engine warmup).  ``tools/scenario_run.py`` maps this to exit 2 — an
    infrastructure failure, distinct from a red verdict (exit 1)."""


def streaming_supported(spec: ScenarioSpec) -> bool:
    """Can this spec run on the streaming plane?  It needs a resident
    engine family and an explicit ``streaming`` config block."""
    return (
        spec.streaming is not None
        and spec.family in ("multitopic", "hybrid")
        and not spec.churn
        and not spec.attacks
        and not spec.links
        and not spec.faults
    )


class _SkewClock:
    """Monotonic host clock with an injectable offset — the clock_skew
    fault's lever.  Shared by the ring (ingest stamps) and the engine
    (delivery stamps) so a skew step lands mid-measurement, exactly like a
    host NTP correction would."""

    def __init__(self, base=time.monotonic) -> None:
        self._base = base
        self.offset = 0.0

    def __call__(self) -> float:
        return self._base() + self.offset


@dataclasses.dataclass
class StreamingScenarioResult:
    """One streaming campaign: plan + verdict + host-truth record."""

    spec: ScenarioSpec
    plan: StreamingPlan
    record: Dict[str, np.ndarray]
    verdict: "slo_mod.Verdict"
    n_publishes: int
    accounting: Dict[str, int]
    engine_stats: Dict[str, Any]
    seconds: float = 0.0


def run_streaming_scenario(
    spec: ScenarioSpec,
    max_drain_chunks: int = 64,
    signer_backend: str = "auto",
    trace_out: Optional[str] = None,
    trace_sample: int = 1,
) -> StreamingScenarioResult:
    """Execute ``spec`` on the streaming plane and grade its SLOs.

    ``trace_out`` (r18): write the span artifact — per-message lifecycle
    spans through ring/pipeline/engine, ledger events, Chrome trace, OTLP
    record, Prometheus render, black-box frames — next to the verdict.
    With ``trace_out=None`` no observability object exists and the run is
    bit- and counter-identical to the untraced r17 path.  A staged crash
    discards the live ledger with the rest of the host state (honest
    loss); the restore path reinstates the checkpointed spans and
    annotates the reopened ones with the measured recovery gap."""
    from ..crypto import native
    from ..crypto.pipeline import ValidationPipeline, sign_envelope
    from ..serve import IngestRing, StreamingEngine, Watchdog

    t0 = time.monotonic()
    plan = compile_streaming_plan(spec)
    faults = plan.faults
    try:
        model = build_model(spec)
    except Exception as e:  # model kwargs are spec data, not code
        raise StreamingPlaneError(f"model build failed: {e}") from e

    clock = _SkewClock()
    ckpt_dir: Optional[str] = None
    ckpt_path: Optional[str] = None
    if plan.snapshot_every > 0:
        ckpt_dir = tempfile.mkdtemp(prefix="stream-ckpt-")
        ckpt_path = os.path.join(ckpt_dir, "engine.ckpt")

    tracing = trace_out is not None
    obs: Dict[str, Any] = {"ledger": None}
    obs_registry = None
    obs_blackbox = None
    if tracing or plan.controller is not None:
        from ..utils.metrics import MetricsRegistry

        # One registry for the whole run (the monitoring plane survives
        # engine crashes).  A controller run always gets one — the
        # serve.controller.* / serve.watchdog.* gauges are part of the
        # subsystem's contract, traced or not.
        obs_registry = MetricsRegistry(clock=clock)
    if tracing:
        from ..obs.blackbox import BlackBox
        from ..obs.spans import SpanLedger

        # The black box rides the registry's lifetime; the span ledger is
        # host state of the serving pair and is lost/restored WITH it.
        obs_blackbox = BlackBox(capacity=64, clock=clock)
        obs["ledger"] = SpanLedger(sample_n=trace_sample, clock=clock)

    def _mk_pair(seed: int):
        ring = IngestRing(
            capacity=plan.capacity, policy=plan.policy, clock=clock,
            metrics=obs_registry, tracer=obs["ledger"],
        )
        engine = StreamingEngine(
            model,
            ring,
            chunk_steps=plan.chunk_steps,
            pub_width=plan.pub_width,
            completion_frac=plan.completion_frac,
            seed=seed,
            clock=clock,
            snapshot_path=ckpt_path,
            snapshot_every=plan.snapshot_every,
            geometry_ladder=(
                plan.controller["ladder"] if plan.controller else None
            ),
            metrics=obs_registry,
            tracer=obs["ledger"],
            blackbox=obs_blackbox,
        )
        return ring, engine

    ring, engine = _mk_pair(spec.seed)
    try:
        engine.warmup()
    except Exception as e:
        raise StreamingPlaneError(f"engine warmup failed: {e}") from e

    # Degraded-link window (r16, hybrid plane): the stamp is re-asserted
    # before EVERY chunk off the runner's own monotone chunk counter, so a
    # staged crash (which rewinds the engine's chunk count) cannot shift
    # the window, and the post-window / drain chunks run on clean fabric.
    loss_w = faults.get("loss")
    # Hysteresis-oscillation window (r21): same monotone-counter stamping
    # discipline, but the delay flips lossy/clean every period_chunks
    # inside the window (starting lossy) — the adversary straddling the
    # hybrid's switch_hi/switch_lo band.
    osc_w = faults.get("loss_oscillate")

    def _stamp_loss(eng, ci: int) -> None:
        if osc_w is not None:
            inside = osc_w["start_chunk"] <= ci < osc_w["stop_chunk"]
            lossy = inside and (
                (ci - osc_w["start_chunk"]) // osc_w["period_chunks"]
            ) % 2 == 0
            eng.set_ingress_delay(osc_w["delay"] if lossy else 0)
            return
        if loss_w is None:
            return
        inside = loss_w["start_chunk"] <= ci < loss_w["stop_chunk"]
        eng.set_ingress_delay(loss_w["delay"] if inside else 0)

    # r20 drifting-workload regimes: STEP-keyed windows (so a controller
    # switching geometries and a static twin see the loss start and stop at
    # the same timeline steps), stamped off the chunk's FIRST step before
    # every dispatch.
    regimes = faults.get("loss_regimes")

    def _stamp_regime(eng, step: int) -> None:
        if regimes is None:
            return
        delay = 0
        for rw in regimes:
            if rw["start_step"] <= step < rw["stop_step"]:
                delay = rw["delay"]
                break
        eng.set_ingress_delay(delay)

    watchdog: Optional[Watchdog] = None
    if "crash_at_chunk" in faults or plan.controller is not None:
        # Supervision is exercised through its public restart path; the
        # stall threshold is irrelevant under injected (not timed) crashes.
        watchdog = Watchdog(
            engine, ring, checkpoint_path=ckpt_path,
            chunk_stall_s=3600.0, clock=clock,
            metrics=obs_registry,
            blackbox=obs_blackbox,
            postmortem_path=(
                f"{trace_out}.postmortem.json" if tracing else None
            ),
        )

    # Crypto stage ahead of enqueue: the verdict callback is the ONLY path
    # into the ring, so an envelope that fails batch verification is pushed
    # valid=False and the device's publish gate keeps it out of every mesh.
    # The ring is read through a holder because a staged crash replaces it.
    backend = (
        "native" if (signer_backend == "auto" and native.available())
        else ("python" if signer_backend == "auto" else signer_backend)
    )
    holder = {"ring": ring}
    rejected_pushes = 0
    admitted_valid = 0

    def _admit(env, ok, ctx):
        nonlocal rejected_pushes, admitted_valid
        topic, src = ctx
        admitted = holder["ring"].push(
            topic=topic, payload=env.payload, publisher=src,
            valid=ok, timeout=5.0,
        )
        if not admitted:
            rejected_pushes += 1
        elif ok:
            admitted_valid += 1

    def _mk_pipe():
        return ValidationPipeline(
            backend=backend, flush_threshold=4096, on_verdict_ctx=_admit,
            tracer=obs["ledger"], metrics=obs_registry,
        )

    pipe = _mk_pipe()

    controller = None
    if plan.controller is not None:
        from ..serve import Controller
        from ..serve.tuning import ControllerPolicy

        # The whole composed control surface: controller over engine +
        # ring + watchdog + validation pipeline, sharing the run's clock,
        # registry and ledger.  The ctor attaches itself to the watchdog,
        # making KnobState the single source of truth for the policy its
        # de-escalation restores.
        controller = Controller(
            engine,
            ring,
            policy=ControllerPolicy(**plan.controller["policy"]),
            watchdog=watchdog,
            pipe=pipe,
            metrics=obs_registry,
            tracer=obs["ledger"],
            clock=clock,
        )

    # Replay the timeline in chunk-sized groups: submit that group's
    # publishes through the crypto stage, flush (which enqueues), run one
    # resident chunk, sample depth.  Forged workloads (valid=False) are
    # signed with a key that does NOT match the envelope, so the pipeline —
    # not the spec bit — produces the False verdict the ring records.
    seed_bytes = spec.seed.to_bytes(8, "little")
    depth_series: List[int] = []
    frac_series: List[float] = []
    recovery_s_list: List[float] = []
    replayed_total = 0
    pipeline_restarts = 0
    seqno = 0
    n_valid_published = 0
    chunk_index = 0
    # Producer retry window for the verifier-crash fault: the last two
    # groups' (envelope, ctx) pairs, resubmitted at-least-once after a
    # pipeline death (drop_pending loses in-flight ctx by contract, so the
    # producer keeps its own copies — as a real at-least-once client would).
    retry_window: List[List[Tuple[Any, Tuple[int, int]]]] = []
    T = spec.n_steps
    base = 0
    while base < T:
        # The group spans the engine's CURRENT chunk length: under a
        # controller the geometry — and so the number of timeline steps one
        # dispatch advances — changes between chunks, and the step pointer
        # must follow the engine, not the plan's constructed geometry.
        # Without a controller this is plan.chunk_steps every iteration,
        # bit-identical to the fixed-stride loop it replaces.
        steps_this = engine.chunk_steps
        group: List[Tuple[Any, Tuple[int, int]]] = []
        for t in range(base, min(base + steps_this, T)):
            for topic, src, valid in plan.timeline[t]:
                env = sign_envelope(
                    seed_bytes + src.to_bytes(4, "little") + b"\x00" * 20,
                    f"topic-{topic}", seqno, b"stream-%d" % seqno,
                    backend="native" if backend == "native" else "python",
                )
                if not valid:
                    env = dataclasses.replace(
                        env, signature=b"\x00" * 64
                    )
                pipe.submit(env, ctx=(topic, src))
                group.append((env, (topic, src)))
                seqno += 1
                if valid:
                    n_valid_published += 1
        retry_window.append(group)
        del retry_window[:-2]
        if faults.get("verifier_crash_at_chunk") == chunk_index + 1:
            # The verifier pool dies with this group's batch in flight.
            # Restart = fresh pipeline; the producer replays its whole
            # retry window (at-least-once — the previous group was already
            # verified and admitted, so its copies exercise the engine's
            # exactly-once dedup).
            pipe.drop_pending()
            pipe = _mk_pipe()
            pipeline_restarts += 1
            if controller is not None:
                # The flush-threshold knob must keep acting on the LIVE
                # pipeline, not the dead one's corpse.
                controller.pipe = pipe
            for g in retry_window:
                for env, ctx in g:
                    pipe.submit(env, ctx=ctx)
        pipe.flush()
        depth_series.append(holder["ring"].depth)
        _stamp_loss(engine, chunk_index)
        _stamp_regime(engine, base)
        engine.run_chunk()
        chunk_index += 1
        if faults.get("crash_at_chunk") == chunk_index:
            # Honest host-state loss: engine AND ring discarded.  Recovery
            # = fresh pair over an equal model (warmup reuses the shared
            # compiled chunk — no recompile) + watchdog-driven restore.
            # The span ledger dies with them — the fresh one is populated
            # from the checkpoint by restore(), so spans closed since the
            # last snapshot are honestly lost, not resurrected.
            t_crash = time.monotonic()
            if tracing:
                from ..obs.spans import SpanLedger as _Ledger

                obs["ledger"] = _Ledger(sample_n=trace_sample, clock=clock)
            ring, engine = _mk_pair(spec.seed + 1)
            try:
                engine.warmup()
            except Exception as e:
                raise StreamingPlaneError(
                    f"post-crash warmup failed: {e}"
                ) from e
            assert watchdog is not None
            # reattach re-applies the current tier's shed set and policy to
            # the fresh ring (a new ring is born un-escalated) and, with a
            # controller, restores the DESIRED policy from its KnobState.
            watchdog.reattach(engine, ring)
            if controller is not None:
                controller.reattach(engine, ring)
                controller.tracer = obs["ledger"]
            info = watchdog.restart_engine(
                f"injected engine crash after chunk {chunk_index}"
            )
            replayed_total += info["replayed"]
            recovery_s_list.append(time.monotonic() - t_crash)
            holder["ring"] = ring
            # The surviving pipeline must stamp into the NEW ledger.
            pipe.tracer = obs["ledger"]
        skew = faults.get("clock_skew")
        if skew is not None and skew["at_chunk"] == chunk_index:
            clock.offset += skew["skew_s"]
        frac_series.append(
            engine.completed / max(1, len(engine.publish_log))
        )
        if controller is not None:
            # One supervision pass + one tuning pass per chunk boundary —
            # the composed control surface in its polling order: the
            # watchdog may escalate first, then the controller tunes
            # (never writing the ring policy while tier 2 holds it).
            watchdog.note_chunk()
            watchdog.poll()
            controller.poll()
        base += steps_this

    _stamp_loss(engine, chunk_index)  # drain runs on clean fabric
    _stamp_regime(engine, T)
    engine.run_until_drained(max_chunks=max_drain_chunks)
    acct = ring.accounting()
    lats = engine.latencies_s
    q = engine.latency_quantiles()

    # compare_eager (r16): replay the SAME timeline and loss windows through
    # an eager-forced twin — the identical hybrid model with switch
    # thresholds above 1.0, so loss_ewma (a probability) can never cross
    # them and every edge stays on the eager plane.  The twin is a perf
    # baseline, not a crypto exercise: publishes go straight to its ring
    # with the spec's validity bit (the main run already proved the
    # pipeline produces those verdicts), and crash/verifier faults are NOT
    # replayed — the ratio isolates the coding gain under loss.
    eager_p99 = float("nan")
    eager_completed = 0
    p99_ratio = float("nan")
    if plan.compare_eager:
        from ..serve import IngestRing as _Ring
        from ..serve import StreamingEngine as _Engine

        eager_spec = dataclasses.replace(
            spec,
            model={**dict(spec.model), "switch_hi": 2.0, "switch_lo": 1.5},
        )
        try:
            eager_model = build_model(eager_spec)
        except Exception as e:
            raise StreamingPlaneError(
                f"eager twin model build failed: {e}"
            ) from e
        ering = _Ring(
            capacity=plan.capacity, policy=plan.policy, clock=clock
        )
        eeng = _Engine(
            eager_model,
            ering,
            chunk_steps=plan.chunk_steps,
            pub_width=plan.pub_width,
            completion_frac=plan.completion_frac,
            seed=spec.seed,
            clock=clock,
        )
        try:
            eeng.warmup()
        except Exception as e:
            raise StreamingPlaneError(
                f"eager twin warmup failed: {e}"
            ) from e
        eseq = 0
        eci = 0
        for base in range(0, T, plan.chunk_steps):
            for t in range(base, min(base + plan.chunk_steps, T)):
                for topic, src, valid in plan.timeline[t]:
                    ering.push(
                        topic=topic, payload=b"stream-%d" % eseq,
                        publisher=src, valid=valid, timeout=5.0,
                    )
                    eseq += 1
            _stamp_loss(eeng, eci)
            eeng.run_chunk()
            eci += 1
        _stamp_loss(eeng, eci)
        eeng.run_until_drained(max_chunks=max_drain_chunks)
        eager_p99 = eeng.latency_quantiles()["p99"]
        eager_completed = eeng.completed
        if eager_completed < engine.completed:
            # Eager never finished messages the hybrid delivered: its tail
            # is unboundedly worse.  Report 0.0 so a max-ratio SLO passes
            # (NaN would fail closed and hide the win).
            p99_ratio = 0.0
        elif eager_p99 > 0.0 and np.isfinite(eager_p99):
            p99_ratio = q["p99"] / eager_p99

    # compare_static (r20): the self-tuned-vs-best-static A/B.  One twin
    # per ladder rung replays the SAME timeline under the SAME step-keyed
    # loss regimes — the drifting adversity is the point — with the
    # controller off and crash/verifier faults off, same fairness posture
    # as the eager twin: publishes go straight to the ring with the spec's
    # validity bit, so ingest stamps land at push in both runs.  The twins
    # reuse the tuned engine's model VALUE, so every rung is already warm
    # in the shared jit cache and the whole A/B adds zero compiles.
    static_results: List[Dict[str, Any]] = []
    best_static_p99 = float("nan")
    p99_static_ratio = float("nan")
    if plan.compare_static:
        from ..serve import IngestRing as _SRing
        from ..serve import StreamingEngine as _SEngine

        assert plan.controller is not None  # compiler enforces the pairing
        for steps_g, width_g in plan.controller["ladder"]:
            sring = _SRing(
                capacity=plan.capacity, policy=plan.policy, clock=clock
            )
            # The twin freezes EVERY knob at the tuned engine's initial
            # configuration — including the snapshot cadence.  A twin that
            # silently dropped the durability tax would be a different
            # (cheaper, less safe) engine, not a static configuration of
            # the same one.
            sckpt = None
            if ckpt_dir is not None and plan.snapshot_every > 0:
                sckpt = os.path.join(
                    ckpt_dir, f"static-{steps_g}x{width_g}.ckpt"
                )
            seng = _SEngine(
                model,
                sring,
                chunk_steps=steps_g,
                pub_width=width_g,
                completion_frac=plan.completion_frac,
                seed=spec.seed,
                clock=clock,
                snapshot_path=sckpt,
                snapshot_every=plan.snapshot_every,
            )
            try:
                seng.warmup()
            except Exception as e:
                raise StreamingPlaneError(
                    f"static twin {steps_g}x{width_g} warmup failed: {e}"
                ) from e
            sseq = 0
            sbase = 0
            while sbase < T:
                for t in range(sbase, min(sbase + steps_g, T)):
                    for topic, src, valid in plan.timeline[t]:
                        sring.push(
                            topic=topic, payload=b"stream-%d" % sseq,
                            publisher=src, valid=valid, timeout=5.0,
                        )
                        sseq += 1
                _stamp_regime(seng, sbase)
                seng.run_chunk()
                sbase += steps_g
            _stamp_regime(seng, T)
            seng.run_until_drained(max_chunks=max_drain_chunks)
            sq = seng.latency_quantiles()
            static_results.append({
                "geometry": [steps_g, width_g],
                "p50_s": float(sq["p50"]),
                "p99_s": float(sq["p99"]),
                "completed": int(seng.completed),
            })
        # A static twin only competes on p99 if it finished at least as
        # many messages as the tuned engine — a rung that never delivered
        # the tail has an unboundedly worse p99, whatever it measured.
        eligible = [
            r["p99_s"] for r in static_results
            if r["completed"] >= engine.completed and np.isfinite(r["p99_s"])
        ]
        if not eligible:
            p99_static_ratio = 0.0
        else:
            best_static_p99 = min(eligible)
            if best_static_p99 > 0.0:
                p99_static_ratio = q["p99"] / best_static_p99

    # The pre-warm contract, graded over the WHOLE run (warmup, controller
    # switches, crash/restore, drain, static twins): the shared jit cache
    # holds exactly the ladder's variants and nothing more.
    unplanned_recompiles = 0
    if plan.controller is not None:
        unplanned_recompiles = (
            engine.compile_cache_size() - engine.ladder_size()
        )

    # Exactly-once floor: every admitted valid message must end the run
    # delivered, deduplicated, in flight, still queued, or attributed to a
    # named shed counter.  The residual is what the crash actually LOST.
    lost_after_restart = (
        admitted_valid
        - engine.completed
        - engine.replay_deduped
        - engine.evicted
        - len(engine.pending)
        - acct["dropped_oldest_valid"]
        - acct["valid_in_queue"]
    )

    # Host-truth flight record, shaped like the other planes' (leading time
    # axis, scalars as length-1 series) so slo.evaluate reads uniformly.
    delivery_frac = engine.completed / max(1, len(engine.publish_log))
    record: Dict[str, np.ndarray] = {
        "queue_depth": np.asarray(depth_series, np.int64),
        "queue_depth_peak": np.asarray([acct["max_depth"]], np.int64),
        "ingest_lat_p50_s": np.asarray([q["p50"]], np.float64),
        "ingest_lat_p99_s": np.asarray([q["p99"]], np.float64),
        "ingest_lat_max_s": np.asarray(
            [max(lats) if lats else float("nan")], np.float64
        ),
        "silent_drops": np.asarray([acct["silent_drops"]], np.int64),
        "delivery_frac": np.asarray(
            frac_series + [delivery_frac], np.float64
        ),
        "recovery_s": np.asarray(
            [max(recovery_s_list) if recovery_s_list else 0.0], np.float64
        ),
        "lost_after_restart": np.asarray([lost_after_restart], np.int64),
        "duplicate_deliveries": np.asarray(
            [engine.duplicate_completions], np.int64
        ),
    }
    if plan.compare_eager:
        record["eager_p99_s"] = np.asarray([eager_p99], np.float64)
        record["p99_vs_eager_ratio"] = np.asarray([p99_ratio], np.float64)
    if plan.controller is not None:
        record["controller_decisions"] = np.asarray(
            [len(controller.decisions)], np.int64
        )
        record["unplanned_recompiles"] = np.asarray(
            [unplanned_recompiles], np.int64
        )
    if plan.compare_static:
        record["best_static_p99_s"] = np.asarray(
            [best_static_p99], np.float64
        )
        record["p99_vs_best_static_ratio"] = np.asarray(
            [p99_static_ratio], np.float64
        )
    verdict = slo_mod.evaluate(spec, record, plan.n_publishes)
    trace_summary: Optional[Dict[str, Any]] = None
    if tracing:
        from ..obs.export import build_span_artifact, write_json

        ledger = obs["ledger"]
        artifact = build_span_artifact(
            plane="streaming",
            scenario=spec.name,
            verdict=verdict.to_dict(),
            ledger=ledger,
            registry=obs_registry,
            blackbox=obs_blackbox,
            extra={
                "recovery_s": (
                    max(recovery_s_list) if recovery_s_list else 0.0
                ),
                "recovery_gap_s": engine.last_recovery_gap_s,
                "chunk_wall_s": engine.last_chunk_wall_s,
                "latency": {
                    "chunk": q,
                    "exact": engine.latency_quantiles(mode="exact"),
                },
                "controls": (
                    controller.controls() if controller is not None
                    else None
                ),
                # The self-tuned-vs-static A/B headline, so the artifact
                # answers "did the controller earn its keep" without the
                # caller re-deriving it from the record arrays.
                "controller": (
                    {
                        "tuned_p99_s": q["p99"],
                        "best_static_p99_s": best_static_p99,
                        "p99_vs_best_static_ratio": p99_static_ratio,
                        "decisions": len(controller.decisions),
                        "unplanned_recompiles": unplanned_recompiles,
                    }
                    if controller is not None and plan.compare_static
                    else None
                ),
            },
        )
        write_json(trace_out, artifact)
        trace_summary = ledger.summary()
    if ckpt_dir is not None:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return StreamingScenarioResult(
        spec=spec,
        plan=plan,
        record=record,
        verdict=verdict,
        n_publishes=plan.n_publishes,
        accounting=acct,
        engine_stats={
            "chunks_run": engine.chunks_run,
            "compile_cache_size": engine.compile_cache_size(),
            "published": engine.published,
            "completed": engine.completed,
            "evicted": engine.evicted,
            "valid_published": n_valid_published,
            "rejected_pushes": rejected_pushes,
            "admitted_valid": admitted_valid,
            "restores": engine.restores,
            "replayed": replayed_total,
            "replay_deduped": engine.replay_deduped,
            "duplicate_completions": engine.duplicate_completions,
            "clock_anomalies": engine.clock_anomalies,
            "snapshots_taken": engine.snapshots_taken,
            "pipeline_restarts": pipeline_restarts,
            "watchdog_restarts": (
                watchdog.engine_restarts if watchdog is not None else 0
            ),
            "recovery_s_list": list(recovery_s_list),
            "eager_completed": eager_completed,
            "pipeline": dict(pipe.stats),
            "trace_out": trace_out,
            "trace_summary": trace_summary,
            "recovery_gap_s": engine.last_recovery_gap_s,
            "controller": (
                None if controller is None else {
                    "decisions": len(controller.decisions),
                    "by_knob": {
                        k: sum(
                            1 for d in controller.decisions if d.knob == k
                        )
                        for k in sorted(
                            {d.knob for d in controller.decisions}
                        )
                    },
                    "geometry_switches": engine.geometry_switches,
                    "unplanned_recompiles": unplanned_recompiles,
                    "ladder": [
                        list(g.as_tuple()) for g in engine.ladder
                    ],
                    "final_knobs": controller.knobs.to_dict(),
                    "watchdog_tier": (
                        watchdog.tier_name if watchdog is not None
                        else "normal"
                    ),
                    "static": static_results,
                    "best_static_p99_s": best_static_p99,
                    "p99_vs_best_static_ratio": p99_static_ratio,
                }
            ),
        },
        seconds=time.monotonic() - t0,
    )
