"""Streaming-plane scenario execution: spec timeline → ring → resident engine.

The sim plane lowers a campaign to event tensors and runs ONE scan; the
streaming plane replays the same declarative workloads as an *open* stream:
each timeline step's publishes are signed, batch-verified by the
:class:`~..crypto.pipeline.ValidationPipeline` (the crypto stage sits ahead
of enqueue, so a forged message enters the ring already marked invalid and
is asserted non-delivered on device), pushed through the
:class:`~..serve.ingest.IngestRing` under the spec's backpressure policy,
and drained by a resident :class:`~..serve.engine.StreamingEngine` whose
compiled chunk never changes shape.

The record it grades is host truth, not device telemetry: queue-depth
series from the ring, exact ingest→delivery latencies from the engine's
host clocks (quantized to chunk boundaries — see ``serve.engine``), and
the ring's conservation ledger (``silent_drops`` must be 0 under every
policy).  ``slo.evaluate`` reads these through the streaming SLO channels.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import slo as slo_mod
from .compiler import StreamingPlan, build_model, compile_streaming_plan
from .spec import ScenarioSpec


class StreamingPlaneError(RuntimeError):
    """The streaming plane failed to COME UP for a scenario (model build,
    engine warmup).  ``tools/scenario_run.py`` maps this to exit 2 — an
    infrastructure failure, distinct from a red verdict (exit 1)."""


def streaming_supported(spec: ScenarioSpec) -> bool:
    """Can this spec run on the streaming plane?  It needs the resident
    multitopic engine and an explicit ``streaming`` config block."""
    return (
        spec.streaming is not None
        and spec.family == "multitopic"
        and not spec.churn
        and not spec.attacks
        and not spec.links
        and not spec.faults
    )


@dataclasses.dataclass
class StreamingScenarioResult:
    """One streaming campaign: plan + verdict + host-truth record."""

    spec: ScenarioSpec
    plan: StreamingPlan
    record: Dict[str, np.ndarray]
    verdict: "slo_mod.Verdict"
    n_publishes: int
    accounting: Dict[str, int]
    engine_stats: Dict[str, Any]
    seconds: float = 0.0


def run_streaming_scenario(
    spec: ScenarioSpec,
    max_drain_chunks: int = 64,
    signer_backend: str = "auto",
) -> StreamingScenarioResult:
    """Execute ``spec`` on the streaming plane and grade its SLOs."""
    from ..crypto import native
    from ..crypto.pipeline import ValidationPipeline, sign_envelope
    from ..serve import IngestRing, StreamingEngine

    t0 = time.monotonic()
    plan = compile_streaming_plan(spec)
    try:
        model = build_model(spec)
    except Exception as e:  # model kwargs are spec data, not code
        raise StreamingPlaneError(f"model build failed: {e}") from e

    ring = IngestRing(capacity=plan.capacity, policy=plan.policy)
    engine = StreamingEngine(
        model,
        ring,
        chunk_steps=plan.chunk_steps,
        pub_width=plan.pub_width,
        completion_frac=plan.completion_frac,
        seed=spec.seed,
    )
    try:
        engine.warmup()
    except Exception as e:
        raise StreamingPlaneError(f"engine warmup failed: {e}") from e

    # Crypto stage ahead of enqueue: the verdict callback is the ONLY path
    # into the ring, so an envelope that fails batch verification is pushed
    # valid=False and the device's publish gate keeps it out of every mesh.
    backend = (
        "native" if (signer_backend == "auto" and native.available())
        else ("python" if signer_backend == "auto" else signer_backend)
    )
    rejected_pushes = 0

    def _admit(env, ok, ctx):
        nonlocal rejected_pushes
        topic, src = ctx
        admitted = ring.push(
            topic=topic, payload=env.payload, publisher=src,
            valid=ok, timeout=5.0,
        )
        if not admitted:
            rejected_pushes += 1

    pipe = ValidationPipeline(
        backend=backend, flush_threshold=4096, on_verdict_ctx=_admit
    )

    # Replay the timeline in chunk-sized groups: submit that group's
    # publishes through the crypto stage, flush (which enqueues), run one
    # resident chunk, sample depth.  Forged workloads (valid=False) are
    # signed with a key that does NOT match the envelope, so the pipeline —
    # not the spec bit — produces the False verdict the ring records.
    seed_bytes = spec.seed.to_bytes(8, "little")
    depth_series: List[int] = []
    frac_series: List[float] = []
    seqno = 0
    n_valid_published = 0
    T = spec.n_steps
    for base in range(0, T, plan.chunk_steps):
        for t in range(base, min(base + plan.chunk_steps, T)):
            for topic, src, valid in plan.timeline[t]:
                env = sign_envelope(
                    seed_bytes + src.to_bytes(4, "little") + b"\x00" * 20,
                    f"topic-{topic}", seqno, b"stream-%d" % seqno,
                    backend="native" if backend == "native" else "python",
                )
                if not valid:
                    env = dataclasses.replace(
                        env, signature=b"\x00" * 64
                    )
                pipe.submit(env, ctx=(topic, src))
                seqno += 1
                if valid:
                    n_valid_published += 1
        pipe.flush()
        depth_series.append(ring.depth)
        engine.run_chunk()
        frac_series.append(
            engine.completed / max(1, len(engine.publish_log))
        )

    engine.run_until_drained(max_chunks=max_drain_chunks)
    acct = ring.accounting()
    lats = engine.latencies_s
    q = engine.latency_quantiles()

    # Host-truth flight record, shaped like the other planes' (leading time
    # axis, scalars as length-1 series) so slo.evaluate reads uniformly.
    delivery_frac = engine.completed / max(1, len(engine.publish_log))
    record: Dict[str, np.ndarray] = {
        "queue_depth": np.asarray(depth_series, np.int64),
        "queue_depth_peak": np.asarray([acct["max_depth"]], np.int64),
        "ingest_lat_p50_s": np.asarray([q["p50"]], np.float64),
        "ingest_lat_p99_s": np.asarray([q["p99"]], np.float64),
        "ingest_lat_max_s": np.asarray(
            [max(lats) if lats else float("nan")], np.float64
        ),
        "silent_drops": np.asarray([acct["silent_drops"]], np.int64),
        "delivery_frac": np.asarray(
            frac_series + [delivery_frac], np.float64
        ),
    }
    verdict = slo_mod.evaluate(spec, record, plan.n_publishes)
    return StreamingScenarioResult(
        spec=spec,
        plan=plan,
        record=record,
        verdict=verdict,
        n_publishes=plan.n_publishes,
        accounting=acct,
        engine_stats={
            "chunks_run": engine.chunks_run,
            "compile_cache_size": engine.compile_cache_size(),
            "published": engine.published,
            "completed": engine.completed,
            "evicted": engine.evicted,
            "valid_published": n_valid_published,
            "rejected_pushes": rejected_pushes,
            "pipeline": dict(pipe.stats),
        },
        seconds=time.monotonic() - t0,
    )
