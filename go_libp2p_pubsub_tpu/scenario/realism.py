"""Realistic network texture for scenario campaigns (r21).

The canon's meshes are uniform expanders with uniform link quality and
Poisson-ish churn — nothing like the overlays the Filecoin/ETH2
evaluation measured (arXiv 2007.02754): degree distributions are heavy
tailed (a few supernodes carry a disproportionate share of edges),
latency follows geography (a handful of regions, cheap within, expensive
across), and participation is diurnal (peers leave for hours and come
back).  Attacks interact with all three: a sybil that camps a supernode's
slots, an eclipse staged while the victim's region sleeps.

This module supplies those textures as *declarative* scenario
ingredients, so fuzzed and co-evolved campaigns can draw them without the
spec losing its exact JSON round-trip:

- heavy-tailed topology — ``spec.model["topology"]`` dicts lowered by the
  compiler through :func:`topology_builder` into a GossipSub ``builder``
  closure.  Every closure carries a hashable ``config_key`` so equally
  configured models still share jit-compiled rollouts (the model's
  ``_config_key`` honors it instead of falling back to identity).
- geographic latency — :func:`geo_latency_links` projects a region
  latency matrix onto the sim's per-peer ingress-delay fault surface as
  one :class:`LinkWindow` per non-backbone region.
- diurnal churn — :func:`diurnal_churn` emits alternating night-window
  :class:`ChurnPhase` entries with rejoin (peers come back at dawn).

All randomness is drawn from ``np.random.default_rng([seed, _TAG_REALISM,
index])`` — tag 7, disjoint from the compiler's (1-4) and the fuzzer's
(5-6) substreams, so realism draws never alias either.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .spec import ChurnPhase, LinkWindow, ScenarioSpec

__all__ = [
    "TOPOLOGY_KINDS",
    "heavy_tailed_builder",
    "topology_builder",
    "geo_latency_links",
    "diurnal_churn",
    "apply_realism",
]

# Realism substream tag (see module docstring).
_TAG_REALISM = 7


# ---------------------------------------------------------------------------
# heavy-tailed topology
# ---------------------------------------------------------------------------

def heavy_tailed_builder(alpha: float = 2.5):
    """A GossipSub topology builder with a Pareto degree distribution.

    Target degrees are i.i.d. Pareto(``alpha``) draws scaled so their mean
    matches the model's ``conn_degree`` and clamped to [1, k-1] (a slot
    table can't hold more).  Smaller ``alpha`` = heavier tail = stronger
    supernodes; alpha <= 1 has no finite mean and is rejected.  Edges come
    from configuration-model stub pairing (self-loops dropped, duplicate
    edges merged), then the shared ``_assign_slots`` tail lowers the edge
    list to slot form — same invariants as the uniform builders.
    """
    if alpha <= 1.0:
        raise ValueError("heavy-tailed alpha must be > 1 (finite mean)")
    alpha = float(alpha)

    def build(
        rng: np.random.Generator, n: int, k: int, degree: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        from ..models.gossipsub import _assign_slots

        if degree >= k:
            raise ValueError(
                f"degree ({degree}) must be < slot count k ({k})"
            )
        if degree == 0 or n < 4:
            empty = np.full((n, k), -1, np.int64)
            return empty, empty.copy(), empty >= 0, np.zeros((n, k), bool)
        # Pareto Type I (x_m = 1) has mean alpha / (alpha - 1); rescale so
        # the target-degree mean is the requested conn_degree.
        x = rng.pareto(alpha, n) + 1.0
        deg = np.clip(
            np.rint(x * degree * (alpha - 1.0) / alpha).astype(np.int64),
            1, min(k - 1, n - 1),
        )
        # Configuration model: one stub per half-edge, shuffled, paired.
        stubs = np.repeat(np.arange(n, dtype=np.int64), deg)
        rng.shuffle(stubs)
        if len(stubs) % 2:
            stubs = stubs[:-1]
        a, b = stubs[0::2], stubs[1::2]
        keep = a != b  # drop self-loops
        a, b = a[keep], b[keep]
        e = np.unique(
            np.stack([np.minimum(a, b), np.maximum(a, b)], 1), axis=0
        )
        dialer = np.where(
            rng.integers(0, 2, len(e)).astype(bool), e[:, 0], e[:, 1]
        )
        return _assign_slots(e, dialer, n, k)

    build.config_key = ("heavy_tailed", alpha)
    return build


def _keyed(builder, key):
    """Wrap an existing builder function with a declared value identity."""
    def build(rng, n, k, degree):
        return builder(rng, n, k, degree)
    build.config_key = key
    return build


TOPOLOGY_KINDS = ("heavy_tailed", "local", "uniform")


def topology_builder(topo: Dict[str, Any]):
    """Lower a declarative ``spec.model["topology"]`` dict to a builder.

    Kinds: ``{"kind": "heavy_tailed", "alpha": float}`` (Pareto degrees),
    ``{"kind": "local", "spread": int | None}`` (ring locality), and
    ``{"kind": "uniform"}`` (the vectorized uniform builder, pinned
    explicitly).  Every returned closure has a ``config_key``.
    """
    from ..models import gossipsub as gsmod

    if not isinstance(topo, dict) or "kind" not in topo:
        raise ValueError("topology must be a dict with a 'kind' key")
    kind = topo["kind"]
    extras = set(topo) - {"kind", "alpha", "spread"}
    if extras:
        raise ValueError(f"unknown topology keys: {sorted(extras)}")
    if kind == "heavy_tailed":
        return heavy_tailed_builder(alpha=float(topo.get("alpha", 2.5)))
    if kind == "local":
        spread = topo.get("spread")
        spread = None if spread is None else int(spread)
        return _keyed(
            lambda rng, n, k, d: gsmod.build_topology_local(
                rng, n, k, d, spread=spread
            ),
            ("local", spread),
        )
    if kind == "uniform":
        return _keyed(gsmod.build_topology_fast, ("uniform",))
    raise ValueError(
        f"unknown topology kind {kind!r} (expected one of {TOPOLOGY_KINDS})"
    )


# ---------------------------------------------------------------------------
# geographic latency
# ---------------------------------------------------------------------------

def geo_latency_links(
    seed: int,
    n: int,
    n_steps: int,
    n_regions: int = 4,
    max_delay: int = 3,
) -> List[LinkWindow]:
    """Project a region latency matrix onto per-peer ingress delays.

    The sim's link fault surface is a per-peer ingress delay, so a full
    pairwise matrix projects onto it as each region's ring distance to
    the backbone (region 0): region r's members receive gossip
    ``min(dist, max_delay)`` rounds late for the whole run.  Region
    membership is a single categorical draw with a mild size skew (the
    backbone region is the largest, like real deployments).  One
    :class:`LinkWindow` per non-backbone region, explicit ``peers`` lists,
    pure in ``seed``.
    """
    if n_regions < 2:
        raise ValueError("n_regions must be >= 2")
    rng = np.random.default_rng([seed, _TAG_REALISM, 1])
    weights = 1.0 / (1.0 + np.arange(n_regions, dtype=np.float64))
    region = rng.choice(n_regions, size=n, p=weights / weights.sum())
    windows: List[LinkWindow] = []
    for r in range(1, n_regions):
        peers = [int(i) for i in np.flatnonzero(region == r)]
        if not peers:
            continue
        dist = min(r, n_regions - r)  # ring distance to the backbone
        windows.append(LinkWindow(
            start=0, stop=n_steps, delay=int(min(max(dist, 1), max_delay)),
            peers=peers,
        ))
    return windows


# ---------------------------------------------------------------------------
# diurnal churn
# ---------------------------------------------------------------------------

def diurnal_churn(
    seed: int,
    n_steps: int,
    period: int = 24,
    night_frac: float = 0.5,
    kills_per_event: int = 1,
    every: int = 4,
) -> List[ChurnPhase]:
    """Alternating day/night participation as :class:`ChurnPhase` entries.

    Each cycle of ``period`` steps ends with a night window of
    ``night_frac`` of the cycle during which peers leave gracefully every
    ``every`` steps and rejoin a night's length later (dawn).  Windows
    that would spill past the scenario end are clipped; pure in ``seed``
    (the seed currently only jitters each night's phase offset, drawn
    from the realism substream).
    """
    if period < 4:
        raise ValueError("diurnal period must be >= 4")
    if not (0.0 < night_frac < 1.0):
        raise ValueError("night_frac must be in (0, 1)")
    rng = np.random.default_rng([seed, _TAG_REALISM, 2])
    night = max(2, int(round(period * night_frac)))
    phases: List[ChurnPhase] = []
    start = period - night + int(rng.integers(0, max(1, every)))
    while start < n_steps - 2:
        stop = min(start + night, n_steps - 1)
        if stop > start:
            phases.append(ChurnPhase(
                start=start, stop=stop, every=every,
                kills_per_event=kills_per_event, graceful=True,
                rejoin_after=night,
            ))
        start += period
    return phases


# ---------------------------------------------------------------------------
# spec composition
# ---------------------------------------------------------------------------

def apply_realism(
    spec: ScenarioSpec,
    seed: int,
    topology: Optional[Dict[str, Any]] = None,
    geo: bool = False,
    diurnal: bool = False,
) -> ScenarioSpec:
    """Compose realism textures onto an existing (fuzzed) sim spec.

    Only adds what the spec doesn't already carry: geo link windows are
    appended to ``links``, diurnal phases to ``churn``, and the topology
    dict replaces ``model["topology"]``.  Returns a new spec; the input
    is never mutated.  Gossipsub-family only (the compiler rejects
    ``topology`` on other families).
    """
    model = dict(spec.model)
    if topology is not None:
        model["topology"] = dict(topology)
    links = list(spec.links)
    if geo:
        n = int(model.get("n_peers", 64))
        links = links + geo_latency_links(seed, n, spec.n_steps)
    churn = list(spec.churn)
    if diurnal:
        churn = churn + diurnal_churn(seed, spec.n_steps)
    return dataclasses.replace(
        spec, model=model, links=links, churn=churn,
    )
