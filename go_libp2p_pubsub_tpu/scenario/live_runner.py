"""Run a ScenarioSpec against the LIVE plane and grade the same SLOs.

PR 2 gave adversity campaigns a declarative form and an SLO verdict — but
only for the device-compiled sim plane.  This module lowers the same
:class:`~.spec.ScenarioSpec` onto real sockets: link-delay windows become
:class:`~..net.chaos.ChaosTransport` policies, churn phases become host
kills / graceful Parts / rejoins, workloads become root publishes, and the
run is graded by the **same** :func:`~.slo.evaluate` thresholds the sim
runner uses.  ``tools/scenario_run.py --plane live`` is the CLI face: the
canon gets a second, socket-level verdict column.

Semantics mirrored from ``scenario.compiler`` so the two planes lower one
spec the same way:

- identical seeded substreams (``_rng(seed, tag, index)``) — the same spec
  kills the same victim indices and degrades the same link cohorts on both
  planes;
- rejoins land before the same step's departures; victims are drawn from
  peers alive AND subscribed AND not protected; peer 0 (the live root) is
  always protected;
- one scenario "step" is a wall-clock quantum (``step_s``, default 50 ms):
  link delays of ``d`` rounds become ``d * step_s`` chaos delays, and
  latency is graded in rounds by re-quantizing receipt times.

Deliberate differences (documented, not silent): the live tree has exactly
one publisher (the root), so workload ``src`` is ignored; attack waves and
the multitopic family have no live lowering and are rejected
(``live_supported`` lets callers filter); ``valid=False`` workloads are
rejected (the runner drives the unsigned plane).

Delivery accounting is scoped exactly like the reference's dropping tests
(``pubsub_test.go:152-204``): loss is charged only against peers that
survive to scenario end — a killed member's in-flight messages are its own
loss, but every survivor must receive every message published while it was
subscribed, including across repair windows.
"""

from __future__ import annotations

import asyncio
import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..net.chaos import (
    ChaosTransport, LinkPolicy, install_partition, remove_partition,
)
from ..net.live import LiveNetwork, SyncHost, SyncSubscription
from . import slo as slo_mod
from .compiler import _TAG_CHURN, _TAG_LINK, _rng, _window
from .spec import ScenarioSpec

TOPIC = "scenario"


class LivePlaneError(RuntimeError):
    """The live plane failed to COME UP for a scenario (hosts, sockets,
    initial subscribes).  ``tools/scenario_run.py`` maps this to exit 2 —
    an infrastructure failure, distinct from a red verdict (exit 1)."""


@dataclasses.dataclass
class LiveScenarioResult:
    """One live-plane campaign: spec + verdict + synthesized record."""

    spec: ScenarioSpec
    verdict: "slo_mod.Verdict"
    record: Dict[str, np.ndarray]
    n_publishes: int
    chaos_trace: Dict[tuple, list]
    counters: Dict[str, float]
    seconds: float = 0.0
    # Failover time-to-heal: wall seconds from the root kill to the first
    # survivor observed promoted (None when the scenario kills no root).
    heal_s: Optional[float] = None
    # r19 cross-host tracing (trace_sample set): per-host span artifacts
    # (``obs-span-host/1``, one per host that ran a ledger), their merge
    # (``obs-span-merged/1``), and the merged propagation digest.  All None
    # when tracing was off.
    host_artifacts: Optional[List[dict]] = None
    merged_trace: Optional[dict] = None
    propagation: Optional[dict] = None


def live_supported(spec: ScenarioSpec) -> bool:
    """Can this spec be lowered onto the live plane?"""
    return (
        spec.family in ("gossipsub", "treecast")
        and not spec.attacks
        and all(w.valid for w in spec.workloads)
    )


def sim_supported(spec: ScenarioSpec) -> bool:
    """Can this spec be lowered onto the sim plane?  Live-only scenarios
    (root failover, socket-level partition heal) and streaming-only
    scenarios (unbounded ingest through the resident serving engine) have
    no closed-scan device lowering — the mirror image of
    :func:`live_supported` / ``streaming_runner.streaming_supported``."""
    return not spec.live_only and not spec.streaming_only


def _reject_unsupported(spec: ScenarioSpec) -> None:
    if spec.family == "multitopic":
        raise ValueError(
            "multitopic has no live lowering (the live plane runs one tree)"
        )
    if spec.attacks:
        raise ValueError(
            "attack waves are not lowered for the live plane (scoring/mesh "
            "defenses are sim-plane subsystems)"
        )
    if any(not w.valid for w in spec.workloads):
        raise ValueError(
            "valid=False workloads are not lowered for the live plane "
            "(the runner drives the unsigned tree)"
        )


@dataclasses.dataclass
class _Member:
    """One (peer-slot, generation): a live subscriber over some step window.

    A rejoin opens a NEW generation on the same slot — live hosts cannot be
    revived in place (a killed listener is gone), so the rejoined peer is a
    fresh host id occupying the same scenario-level identity.
    """

    peer: int
    host: SyncHost
    sub: SyncSubscription
    alive_from: int
    end_step: Optional[int] = None  # step it left/was killed (None = survivor)
    killed: bool = False
    receipts: Dict[int, float] = dataclasses.field(default_factory=dict)
    dups: int = 0  # same message index DELIVERED twice (dedup failure)
    stop: threading.Event = dataclasses.field(default_factory=threading.Event)
    thread: Optional[threading.Thread] = None


def _collect(member: _Member) -> None:
    """Collector thread: drain one member's deliveries with receipt times.
    A message index surfacing twice is a duplicate DELIVERY — the live
    plane's content-hash dedup failed — and is counted, not overwritten:
    the ``max_duplicate_deliveries`` SLO reads the sum."""
    while not member.stop.is_set():
        try:
            payload = member.sub.get(timeout=0.2)
        except (TimeoutError, asyncio.TimeoutError):
            continue
        except Exception:
            return  # subscription torn down mid-get (kill path)
        try:
            idx = int(payload.split(b":")[1])
        except (IndexError, ValueError):
            continue
        if idx in member.receipts:
            member.dups += 1
        else:
            member.receipts[idx] = time.monotonic()


def run_live_scenario(
    spec: ScenarioSpec,
    n_hosts: Optional[int] = None,
    step_s: Optional[float] = None,
    settle_s: Optional[float] = None,
    trace_out: Optional[str] = None,
    trace_sample: Optional[int] = None,
) -> LiveScenarioResult:
    """Lower ``spec`` onto a live tree under chaos and grade its SLOs.

    ``trace_out`` writes an ``obs-record-trace/1`` artifact from the
    synthesized flight record; the live plane steps on a real cadence, so
    the trace's time axis is seconds (``step_s`` per step).

    ``trace_sample`` (r19) turns on cross-host distributed tracing: every
    host runs its own :class:`~..obs.spans.SpanLedger` tracing the same
    deterministic 1-in-N message subset, the latency SLO is graded from
    span-exact propagation times instead of collector-thread receipt
    times, and — when ``trace_out`` is also given — the per-host ledgers
    plus their ``obs-span-merged/1`` merge land in a ``<trace_out
    stem>.spans/`` directory next to the record trace.
    """
    _reject_unsupported(spec)
    live_cfg = spec.live or {}
    n = int(n_hosts if n_hosts is not None else live_cfg.get("n_hosts", 16))
    dt = float(
        step_s if step_s is not None else live_cfg.get("step_ms", 50.0) / 1e3
    )
    if settle_s is None and "settle_s" in live_cfg:
        settle_s = float(live_cfg["settle_s"])
    if n < 2:
        raise ValueError("live scenario needs n_hosts >= 2 (root + 1)")
    T = spec.n_steps
    t_begin = time.monotonic()

    chaos = ChaosTransport(seed=spec.seed)
    # Repair must complete well inside one latency "round" budget but not
    # so eagerly that one slow adoption dial gives up: a handful of steps.
    repair_s = max(6 * dt, 0.3)
    net = LiveNetwork(repair_timeout_s=repair_s, chaos=chaos,
                      trace_sample=trace_sample)

    # -- plane bring-up (failures here are exit-2 material, not verdicts) --
    members: Dict[int, List[_Member]] = {}
    try:
        hosts = net.make_hosts(n)
        topic = hosts[0].new_topic(TOPIC)
        for p in range(1, n):
            sub = hosts[p].subscribe(hosts[0].id, TOPIC)
            m = _Member(peer=p, host=hosts[p], sub=sub, alive_from=0)
            m.thread = threading.Thread(target=_collect, args=(m,), daemon=True)
            m.thread.start()
            members[p] = [m]
    except Exception as e:
        net.shutdown()
        raise LivePlaneError(f"live plane failed to start: {e}") from e

    try:
        res = _drive(spec, net, chaos, hosts, topic, members, n, T, dt,
                     settle_s, t_begin)
        if trace_out is not None:
            from ..obs.export import build_record_artifact, write_json

            write_json(trace_out, build_record_artifact(
                plane="live", scenario=spec.name,
                verdict=res.verdict.to_dict(), record=res.record,
                time_per_step_s=dt,
            ))
            if res.merged_trace is not None:
                import os

                spans_dir = os.path.splitext(trace_out)[0] + ".spans"
                os.makedirs(spans_dir, exist_ok=True)
                for art in res.host_artifacts:
                    write_json(
                        os.path.join(spans_dir, f"host-{art['host']}.json"),
                        art,
                    )
                write_json(
                    os.path.join(spans_dir, "merged.json"), res.merged_trace
                )
        return res
    finally:
        for gens in members.values():
            for m in gens:
                m.stop.set()
        for gens in members.values():
            for m in gens:
                if m.thread is not None:
                    m.thread.join(timeout=2.0)
        net.shutdown()


def _drive(spec, net, chaos, hosts, topic, members, n, T, dt,
           settle_s, t_begin) -> LiveScenarioResult:
    # -- lowering: publish requests per step (compiler's workload walk; src
    #    is ignored — the live tree has one publisher, the root).
    requests: List[int] = []
    pub_steps: List[List[int]] = [[] for _ in range(T)]
    for w in spec.workloads:
        start, stop = _window(w.start, w.stop, T)
        steps = [start] if w.kind == "burst" else range(start, stop, w.every)
        for t in steps:
            for _ in range(w.n_msgs):
                pub_steps[t].append(len(requests))
                requests.append(t)

    # -- lowering: link windows -> chaos delay policies on the cohort's
    #    ingress (same substream as the compiler, so the same peer indices
    #    degrade on both planes).
    link_installs: List[List[Tuple[int, float]]] = [[] for _ in range(T)]
    link_removals: List[List[int]] = [[] for _ in range(T)]
    for li, w in enumerate(spec.links):
        start, stop = _window(w.start, w.stop, T)
        if w.peers is not None:
            cohort = [p for p in w.peers if 0 <= p < n]
        else:
            rng = _rng(spec.seed, _TAG_LINK, li)
            size = max(1, int(round(w.frac * n)))
            cohort = [int(p) for p in rng.choice(n, size=size, replace=False)]
        for p in cohort:
            link_installs[start].append((p, w.delay * dt))
            if stop < T:
                link_removals[stop].append(p)

    # -- lowering: churn timeline (compiler's walk, host mirrors and all).
    churn_events: List[List[tuple]] = [[] for _ in range(T)]
    for ci, ph in enumerate(spec.churn):
        start, stop = _window(ph.start, ph.stop, T)
        for t in range(start, stop, ph.every):
            churn_events[t].append(("phase", ci))
    if spec.faults:
        for t_str, ids in spec.faults.get("kills", {}).items():
            if 0 <= int(t_str) < T:
                churn_events[int(t_str)].append(("fault_kill", ids))
        for t_str, ids in spec.faults.get("leaves", {}).items():
            if 0 <= int(t_str) < T:
                churn_events[int(t_str)].append(("fault_leave", ids))
    churn_rngs = [
        _rng(spec.seed, _TAG_CHURN, ci) for ci in range(len(spec.churn))
    ]
    churn_cursor = [0] * len(spec.churn)
    rejoin_at: List[List[tuple]] = [[] for _ in range(T + 1)]

    alive = np.ones(n, bool)
    subscribed = np.ones(n, bool)
    protected = np.zeros(n, bool)
    protected[0] = True  # the root/publisher (compiler keeps slot 0 stable)
    subscribed[0] = False  # the root publishes, it does not subscribe

    peers_alive = np.zeros(T, np.int64)
    peers_orphaned = np.zeros(T, np.int64)

    def current(p: int) -> Optional[_Member]:
        gens = members.get(p)
        m = gens[-1] if gens else None
        return m if m is not None and m.end_step is None else None

    def depart(p: int, t: int, graceful: bool) -> None:
        m = current(p)
        if m is None:
            return
        m.end_step = t
        m.killed = not graceful
        m.stop.set()
        if graceful:
            m.sub.close()          # Part flows; host stays up
        else:
            m.host.close()         # abrupt: streams abort, no Part

    def rejoin(p: int, t: int, graceful: bool) -> None:
        prev = members[p][-1]
        host = prev.host if graceful else net.host()
        sub = host.subscribe(hosts[0].id, TOPIC)
        m = _Member(peer=p, host=host, sub=sub, alive_from=t)
        m.thread = threading.Thread(target=_collect, args=(m,), daemon=True)
        m.thread.start()
        members[p].append(m)

    # -- failover lowering (live-only adversities, spec.live) ---------------
    live_cfg = spec.live or {}
    kill_root_at = live_cfg.get("kill_root_at")  # step: abrupt root kill
    part_cfg = live_cfg.get("partition")  # {"start","stop","peers"}: blackhole
    root_dead = False
    t_kill: Optional[float] = None
    heal_s: Optional[float] = None
    promoted: Optional[_Member] = None
    pending_pubs: List[int] = []  # published while no root exists yet
    partition_sides: Optional[Tuple[List[str], List[str]]] = None

    def find_promoted() -> Optional[_Member]:
        for gens in members.values():
            for m in gens:
                if m.end_step is None and m.sub.sub.node.is_root:
                    return m
        return None

    def flush_pending(via: _Member) -> None:
        for idx in pending_pubs:
            via.sub.publish_message(pub_payloads[idx])
            pub_wall[idx] = time.monotonic()
        pending_pubs.clear()

    # -- the paced campaign loop -------------------------------------------
    t0 = time.monotonic()
    pub_payloads = [f"scn:{i}".encode() for i in range(len(requests))]
    # Actual publish wall times: latency is graded against the moment the
    # root's fan-out returned, not the nominal step, so a repair stall that
    # slips the pacing loop does not masquerade as delivery latency.
    pub_wall = [0.0] * len(requests)
    for t in range(T):
        target_t = t0 + t * dt
        while True:
            now = time.monotonic()
            if now >= target_t:
                break
            time.sleep(min(dt, target_t - now))
        if part_cfg is not None and t == int(part_cfg["start"]):
            # Blackhole + reset the minority cohort away from everyone else:
            # dials across the cut fail, the first write on any existing
            # cross-cut stream aborts it (both ends must DETECT the cut;
            # drop-only faults are silent).  Host ids are resolved at
            # install time so rejoined generations partition correctly.
            minority = set(int(p) for p in part_cfg["peers"])
            side_a = [
                m.host.id for p in sorted(minority)
                if (m := current(p)) is not None
            ]
            side_b = [hosts[0].id] + [
                m.host.id for p in range(1, n)
                if p not in minority and (m := current(p)) is not None
            ]
            partition_sides = (side_a, side_b)
            install_partition(chaos.table, side_a, side_b)
        if part_cfg is not None and t == int(part_cfg["stop"]) \
                and partition_sides is not None:
            remove_partition(chaos.table, *partition_sides)
            partition_sides = None
        if kill_root_at is not None and t == int(kill_root_at) \
                and not root_dead:
            hosts[0].close()  # abrupt: streams abort, no Part, no handover
            root_dead = True
            t_kill = time.monotonic()
        if root_dead and promoted is None:
            promoted = find_promoted()
            if promoted is not None:
                heal_s = time.monotonic() - t_kill
                flush_pending(promoted)
        for p, delay_s in link_installs[t]:
            m = current(p)
            if m is not None:
                chaos.table.set(LinkPolicy(delay_s=delay_s), dst=m.host.id)
        for p in link_removals[t]:
            for m in members.get(p, []):
                chaos.table.remove(dst=m.host.id)
        # rejoins land before this step's new departures (compiler order).
        for ids, graceful in rejoin_at[t]:
            ids = [i for i in ids if not alive[i] or not subscribed[i]]
            for p in ids:
                rejoin(p, t, graceful)
            if graceful:
                subscribed[ids] = True
            else:
                alive[ids] = True
                subscribed[ids] = True
        for kind, payload in churn_events[t]:
            if kind == "phase":
                ci = payload
                ph = spec.churn[ci]
                if ph.peers is not None:
                    k0 = churn_cursor[ci]
                    victims = [
                        p for p in ph.peers[k0:k0 + ph.kills_per_event]
                        if 0 < p < n  # never the live root
                    ]
                    churn_cursor[ci] = k0 + ph.kills_per_event
                else:
                    pool = np.flatnonzero(alive & subscribed & ~protected)
                    take = min(ph.kills_per_event, len(pool))
                    victims = (
                        churn_rngs[ci].choice(pool, size=take, replace=False)
                        .tolist() if take else []
                    )
                for p in victims:
                    depart(p, t, ph.graceful)
                if ph.graceful:
                    subscribed[victims] = False
                else:
                    alive[victims] = False
                if ph.rejoin_after is not None and victims:
                    back = t + ph.rejoin_after
                    if back <= T - 1:
                        rejoin_at[back].append((victims, ph.graceful))
            elif kind == "fault_kill":
                ids = [i for i in payload if 0 < i < n]
                for p in ids:
                    depart(p, t, graceful=False)
                alive[ids] = False
            else:  # fault_leave
                ids = [i for i in payload if 0 < i < n]
                for p in ids:
                    depart(p, t, graceful=True)
                subscribed[ids] = False
        for idx in pub_steps[t]:
            if not root_dead:
                topic.publish_message(pub_payloads[idx])
                pub_wall[idx] = time.monotonic()
            elif promoted is not None:
                promoted.sub.publish_message(pub_payloads[idx])
                pub_wall[idx] = time.monotonic()
            else:
                # The root is dead and no successor has promoted yet: the
                # workload buffers, exactly as a real publisher fronting
                # this tree would have to, and flushes on promotion.
                pending_pubs.append(idx)
        # per-step observability (the treecast channels the SLO reads).
        peers_alive[t] = (0 if root_dead else 1) + sum(
            1 for p in range(1, n)
            if alive[p] and subscribed[p] and current(p) is not None
        )
        peers_orphaned[t] = _count_orphans(members, current, n)

    # -- settle: let repairs finish and delayed copies drain ---------------
    settle = (
        settle_s if settle_s is not None
        else max(0.75, 10 * dt + max(
            [w.delay * dt for w in spec.links], default=0.0))
    )
    settle_deadline = time.monotonic() + settle
    if root_dead and promoted is None:
        # Promotion may land after the last step: poll for it through the
        # settle window so buffered publishes still flush and get graded.
        while time.monotonic() < settle_deadline:
            promoted = find_promoted()
            if promoted is not None:
                heal_s = time.monotonic() - t_kill
                flush_pending(promoted)
                break
            time.sleep(dt)
    time.sleep(max(0.0, settle_deadline - time.monotonic()))
    if T:
        peers_orphaned[T - 1] = _count_orphans(members, current, n)

    # -- cross-host span collection + merge (tracing on) -------------------
    host_artifacts = merged = propagation = None
    if net.trace_sample is not None:
        from ..obs.merge import build_host_span_artifact, merge_host_artifacts
        from ..obs.spans import live_span_key

        # Every SyncHost ever created — killed originals and rejoined
        # generations included: a dead host's ledger still holds the stamps
        # it recorded while alive, which is exactly what a real collector
        # would have scraped before the crash.
        host_artifacts = [
            build_host_span_artifact(sh.id, sh.ledger)
            for sh in net._sync_hosts if sh.ledger is not None
        ]
        merged = merge_host_artifacts(host_artifacts, scenario=spec.name)
        propagation = merged["propagation"]

    # -- synthesize the flight-record channels and grade -------------------
    n_pub = len(requests)
    record = _synthesize_record(
        spec, members, requests, pub_wall, t0, dt, T,
        peers_alive, peers_orphaned,
    )
    if merged is not None and spec.family == "gossipsub" and T:
        # Span-exact latency: re-grade the lat_hist channel from merged
        # end-to-end propagation times (origin publish stamp → subscriber
        # deliver stamp) instead of collector-thread receipt times.  The
        # traced subset is the deterministic 1-in-N sample; quantile SLOs
        # grade the sample.  Protoid survives promotion, so post-failover
        # publishes key identically.
        protoid = f"{hosts[0].id}/{TOPIC}"
        traced_keys = {
            live_span_key(protoid, pub_payloads[i]) for i in range(n_pub)
        }
        B = record["lat_hist"].shape[1]
        span_hist = np.zeros((T, B), np.int64)
        span_lats: List[float] = []
        for tr in merged["traces"]:
            if tr["key"] not in traced_keys or tr["publish"] is None:
                continue
            for d in tr["deliveries"]:
                recv_step = min(T - 1, max(0, int((d["t"] - t0) / dt)))
                lat = max(0, int(d["latency_s"] / dt))
                span_hist[recv_step, min(lat, B - 1)] += 1
                span_lats.append(d["latency_s"])
        if span_lats:
            record["lat_hist"] = np.cumsum(span_hist, axis=0)
            from ..utils.metrics import quantiles

            q = quantiles(span_lats, (0.5, 0.99))
            record["span_prop_p50_s"] = np.full(T, q["p50"], np.float64)
            record["span_prop_p99_s"] = np.full(T, q["p99"], np.float64)
    # Failover channels (family-agnostic; constant series read at [-1] by
    # slo.evaluate): the surviving members' epoch agreement and the total
    # duplicate deliveries across every generation.
    epochs = [
        m.sub.sub.node.epoch
        for p in range(1, n) if (m := current(p)) is not None
    ]
    record["final_epoch"] = np.full(
        max(T, 1), min(epochs) if epochs else 0, np.int64)
    record["epoch_spread"] = np.full(
        max(T, 1), (max(epochs) - min(epochs)) if epochs else 0, np.int64)
    record["duplicate_deliveries"] = np.full(
        max(T, 1),
        sum(m.dups for gens in members.values() for m in gens), np.int64)
    verdict = slo_mod.evaluate(spec, record, n_pub)
    if merged is not None:
        merged["verdict"] = verdict.to_dict()
    return LiveScenarioResult(
        spec=spec,
        verdict=verdict,
        record=record,
        n_publishes=n_pub,
        chaos_trace=chaos.trace(),
        counters=net.registry.counters(),
        seconds=round(time.monotonic() - t_begin, 3),
        heal_s=round(heal_s, 3) if heal_s is not None else None,
        host_artifacts=host_artifacts,
        merged_trace=merged,
        propagation=propagation,
    )


def _count_orphans(members, current, n: int) -> int:
    c = 0
    for p in range(1, n):
        m = current(p)
        if m is None:
            continue
        node = m.sub.sub.node
        if node.is_root:
            continue  # a promoted successor HAS no parent by design
        ps = node.parent_stream
        if not node.closed and (ps is None or ps.closed):
            c += 1
    return c


def _synthesize_record(
    spec: ScenarioSpec,
    members: Dict[int, List[_Member]],
    pub_step_of: List[int],
    pub_wall: List[float],
    t0: float,
    dt: float,
    T: int,
    peers_alive: np.ndarray,
    peers_orphaned: np.ndarray,
) -> Dict[str, np.ndarray]:
    """Build the flight-record channels :func:`~.slo.evaluate` reads.

    Gossip-family channels: cumulative ``delivery_frac`` over the
    survivor-scoped expected pairs, and a cumulative latency histogram in
    ROUNDS (receipt wall time re-quantized to steps) matching the sim
    recorder's ``lat_hist`` shape.  Treecast channels: total receipts,
    per-step liveness, and the orphan count.
    """
    n_pub = len(pub_step_of)
    # Expected pairs: survivors only (end_step is None), messages published
    # while the generation was subscribed.
    pairs_expected: List[Tuple[_Member, int]] = []
    for gens in members.values():
        for m in gens:
            if m.end_step is not None:
                continue
            for i in range(n_pub):
                if pub_step_of[i] >= m.alive_from:
                    pairs_expected.append((m, i))

    # Receipt latency (rounds) per delivered pair, over ALL generations —
    # victims' pre-death receipts count toward the treecast totals.  Latency
    # is wall time since the publish's fan-out returned, quantized to steps.
    lat_rounds: List[Tuple[int, int, int]] = []  # (pub_step, recv_step, lat)
    for gens in members.values():
        for m in gens:
            for i, t_recv in m.receipts.items():
                recv_step = min(T - 1, max(0, int((t_recv - t0) / dt)))
                lat = max(0, int((t_recv - pub_wall[i]) / dt))
                lat_rounds.append((pub_step_of[i], recv_step, lat))

    record: Dict[str, np.ndarray] = {}
    if spec.family == "treecast":
        delivered_total = np.zeros(T, np.int64)
        for _, recv_step, _ in lat_rounds:
            delivered_total[recv_step] += 1
        record["msgs_delivered_total"] = np.cumsum(delivered_total)
        record["peers_alive"] = peers_alive
        record["peers_orphaned"] = peers_orphaned
        return record

    # gossipsub family: delivery_frac + lat_hist.
    B = max(T, 8)
    frac = np.ones(T, np.float64)
    hist = np.zeros((T, B), np.int64)
    exp_by_pubstep = np.zeros(T, np.int64)
    del_by_pubstep = np.zeros(T, np.int64)
    for m, i in pairs_expected:
        ps = pub_step_of[i]
        exp_by_pubstep[ps] += 1
        if i in m.receipts:
            del_by_pubstep[ps] += 1
    exp_c = np.cumsum(exp_by_pubstep)
    del_c = np.cumsum(del_by_pubstep)
    nonzero = exp_c > 0
    frac[nonzero] = del_c[nonzero] / exp_c[nonzero]
    for _, recv_step, lat in lat_rounds:
        hist[recv_step, min(lat, B - 1)] += 1
    record["delivery_frac"] = frac
    record["lat_hist"] = np.cumsum(hist, axis=0)
    return record
