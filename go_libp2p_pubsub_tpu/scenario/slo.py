"""SLO verdicts: grade a scenario's flight record against its thresholds.

Each enabled :class:`~.spec.SLO` field becomes one :class:`Criterion` with
the measured value next to the threshold, so a failing verdict says not
just *that* the campaign regressed but *which* guarantee broke and by how
much.  Every measurement is sourced from the flight record the rollout
scan emitted (PR 1's recorder plus the campaign channels) — the verdict
is a pure host-side reduction of device telemetry, never a re-simulation.

r19: on the live plane with cross-host tracing enabled, the runner
substitutes the ``lat_hist`` channel with one rebuilt from span-exact
propagation times (origin publish stamp → subscriber deliver stamp, merged
across per-host ledgers by ``obs.merge``) before grading, and adds
``span_prop_p50_s``/``span_prop_p99_s`` channels carrying the merged
second-domain quantiles.  The latency criteria below read the substituted
histogram unchanged — span-exact verdicts need no new criterion kinds.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from .spec import SLO, ScenarioSpec


@dataclasses.dataclass(frozen=True)
class Criterion:
    """One graded threshold: ``actual`` measured vs ``threshold`` bound."""

    name: str
    kind: str            # "max" | "min"
    threshold: float
    actual: float
    passed: bool

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Verdict:
    """A scenario's pass/fail with the per-criterion breakdown."""

    scenario: str
    passed: bool
    criteria: List[Criterion]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "scenario": self.scenario,
            "passed": self.passed,
            "criteria": [c.to_dict() for c in self.criteria],
        }

    def __str__(self) -> str:
        rows = [
            f"  {'PASS' if c.passed else 'FAIL'}  {c.name}: "
            f"{c.actual:.4g} ({c.kind} {c.threshold:.4g})"
            for c in self.criteria
        ]
        head = f"{'PASS' if self.passed else 'FAIL'}  {self.scenario}"
        return "\n".join([head] + rows)


def _crit(name: str, kind: str, threshold, actual) -> Criterion:
    actual = float(actual)
    threshold = float(threshold)
    ok = actual <= threshold if kind == "max" else actual >= threshold
    # NaN never passes: a criterion that could not be measured is a failure
    # of the scenario, not a vacuous success.
    if not np.isfinite(actual):
        ok = False
    return Criterion(name, kind, threshold, actual, bool(ok))


def evaluate(
    spec: ScenarioSpec,
    record: Dict[str, np.ndarray],
    n_publishes: int,
) -> Verdict:
    """Grade ``record`` (host-side flight record, time axis leading)
    against ``spec.slo`` -> :class:`Verdict`."""
    slo: SLO = spec.slo
    crits: List[Criterion] = []

    def have(key: str) -> bool:
        return key in record

    if spec.family == "treecast":
        if slo.min_delivered_total is not None:
            crits.append(_crit(
                "delivered_total", "min", slo.min_delivered_total,
                record["msgs_delivered_total"][-1],
            ))
        if slo.max_final_orphans is not None:
            crits.append(_crit(
                "final_orphans", "max", slo.max_final_orphans,
                record["peers_orphaned"][-1],
            ))
        if slo.min_delivery_frac is not None:
            # The tree record counts total receipts, not per-message rows:
            # normalize by the ideal receipt count (every publish reaching
            # every finally-alive peer).
            alive = float(record["peers_alive"][-1])
            ideal = max(n_publishes * alive, 1.0)
            crits.append(_crit(
                "delivery_frac", "min", slo.min_delivery_frac,
                float(record["msgs_delivered_total"][-1]) / ideal,
            ))
    else:
        from ..ops import histogram as hist_ops

        if slo.min_delivery_frac is not None:
            crits.append(_crit(
                "delivery_frac", "min", slo.min_delivery_frac,
                record["delivery_frac"][-1],
            ))
        if slo.max_p50 is not None or slo.max_p99 is not None:
            final_hist = np.asarray(record["lat_hist"][-1])
            if slo.max_p50 is not None:
                crits.append(_crit(
                    "latency_p50", "max", slo.max_p50,
                    hist_ops.hist_quantile(final_hist, 0.5),
                ))
            if slo.max_p99 is not None:
                crits.append(_crit(
                    "latency_p99", "max", slo.max_p99,
                    hist_ops.hist_quantile(final_hist, 0.99),
                ))
        if slo.max_capture_frac is not None:
            if not have("attacker_capture_frac"):
                raise ValueError(
                    "max_capture_frac SLO needs an attack wave (the "
                    "attacker channels are only recorded with attackers)"
                )
            crits.append(_crit(
                "capture_frac_peak", "max", slo.max_capture_frac,
                np.max(record["attacker_capture_frac"]),
            ))
        if slo.max_final_attacker_mesh_edges is not None:
            crits.append(_crit(
                "final_attacker_mesh_edges", "max",
                slo.max_final_attacker_mesh_edges,
                record["attacker_mesh_edges"][-1],
            ))
        if slo.min_final_target_honest_edges is not None:
            if not have("target_honest_mesh_edges"):
                raise ValueError(
                    "min_final_target_honest_edges SLO needs an eclipse "
                    "wave (no target, no target channel)"
                )
            crits.append(_crit(
                "final_target_honest_edges", "min",
                slo.min_final_target_honest_edges,
                record["target_honest_mesh_edges"][-1],
            ))
        if slo.max_final_attacker_score is not None:
            if not have("attacker_score_mean"):
                raise ValueError(
                    "max_final_attacker_score SLO needs an attack wave "
                    "(the score channels are only recorded with attackers)"
                )
            crits.append(_crit(
                "final_attacker_score", "max", slo.max_final_attacker_score,
                record["attacker_score_mean"][-1],
            ))
        if slo.min_final_honest_score is not None:
            if not have("honest_score_min"):
                raise ValueError(
                    "min_final_honest_score SLO needs an attack wave "
                    "(the score channels are only recorded with attackers)"
                )
            crits.append(_crit(
                "final_honest_score", "min", slo.min_final_honest_score,
                record["honest_score_min"][-1],
            ))

    # Failover criteria (family-agnostic: the live runner emits these
    # channels for whatever family it ran).  Requesting one without the
    # channel is a misconfigured scenario, not a vacuous pass.
    def _failover_channel(key: str, slo_name: str) -> np.ndarray:
        if not have(key):
            raise ValueError(
                f"{slo_name} SLO needs the {key!r} record channel "
                "(emitted by the live runner's failover scenarios)"
            )
        return record[key]

    if slo.min_final_epoch is not None:
        crits.append(_crit(
            "final_epoch", "min", slo.min_final_epoch,
            _failover_channel("final_epoch", "min_final_epoch")[-1],
        ))
    if slo.max_epoch_spread is not None:
        crits.append(_crit(
            "epoch_spread", "max", slo.max_epoch_spread,
            _failover_channel("epoch_spread", "max_epoch_spread")[-1],
        ))
    if slo.max_duplicate_deliveries is not None:
        crits.append(_crit(
            "duplicate_deliveries", "max", slo.max_duplicate_deliveries,
            _failover_channel(
                "duplicate_deliveries", "max_duplicate_deliveries"
            )[-1],
        ))

    # Streaming criteria (serving plane, scenario.streaming_runner).  Same
    # contract as failover: asking for a channel the runner didn't emit is
    # a misconfigured scenario, not a vacuous pass.
    def _streaming_channel(key: str, slo_name: str) -> np.ndarray:
        if not have(key):
            raise ValueError(
                f"{slo_name} SLO needs the {key!r} record channel "
                "(emitted by the streaming runner's serving scenarios)"
            )
        return record[key]

    if slo.max_queue_depth is not None:
        crits.append(_crit(
            "queue_depth_peak", "max", slo.max_queue_depth,
            _streaming_channel("queue_depth_peak", "max_queue_depth")[-1],
        ))
    if slo.max_ingest_latency_s is not None:
        crits.append(_crit(
            "ingest_lat_max_s", "max", slo.max_ingest_latency_s,
            _streaming_channel(
                "ingest_lat_max_s", "max_ingest_latency_s"
            )[-1],
        ))
    if slo.max_silent_drops is not None:
        crits.append(_crit(
            "silent_drops", "max", slo.max_silent_drops,
            _streaming_channel("silent_drops", "max_silent_drops")[-1],
        ))
    # Crash-safety criteria (r14): the streaming runner emits recovery_s /
    # lost_after_restart on EVERY run (zeros when no fault fired), so these
    # grade real measurements, never a vacuous pass.
    if slo.max_recovery_s is not None:
        crits.append(_crit(
            "recovery_s", "max", slo.max_recovery_s,
            _streaming_channel("recovery_s", "max_recovery_s")[-1],
        ))
    if slo.max_lost_after_restart is not None:
        crits.append(_crit(
            "lost_after_restart", "max", slo.max_lost_after_restart,
            _streaming_channel(
                "lost_after_restart", "max_lost_after_restart"
            )[-1],
        ))
    # Hybrid-plane comparative criterion (r16): the runner's eager-forced
    # twin emits p99_vs_eager_ratio; 0.0 encodes "eager completed fewer
    # messages than the hybrid" (unboundedly worse tail), which passes any
    # max-ratio bound.  NaN (twin produced no latencies at all) fails
    # closed as everywhere else.
    if slo.max_p99_vs_eager_ratio is not None:
        crits.append(_crit(
            "p99_vs_eager_ratio", "max", slo.max_p99_vs_eager_ratio,
            _streaming_channel(
                "p99_vs_eager_ratio", "max_p99_vs_eager_ratio"
            )[-1],
        ))
    # Self-tuning criteria (r20): the controller runner's A/B channels.
    # p99_vs_best_static_ratio < 1.0 asserts the self-tuned engine beat
    # EVERY static rung of its own ladder on p99 ingest→delivery (0.0
    # encodes "no static twin completed as many messages" — every static
    # tail unboundedly worse); min_controller_decisions rejects a loop that
    # never moved a knob; max_unplanned_recompiles grades the pre-warm
    # contract, compile_cache_size() - ladder_size() over the WHOLE run.
    if slo.max_p99_vs_best_static_ratio is not None:
        crits.append(_crit(
            "p99_vs_best_static_ratio", "max",
            slo.max_p99_vs_best_static_ratio,
            _streaming_channel(
                "p99_vs_best_static_ratio", "max_p99_vs_best_static_ratio"
            )[-1],
        ))
    if slo.min_controller_decisions is not None:
        crits.append(_crit(
            "controller_decisions", "min", slo.min_controller_decisions,
            _streaming_channel(
                "controller_decisions", "min_controller_decisions"
            )[-1],
        ))
    if slo.max_unplanned_recompiles is not None:
        crits.append(_crit(
            "unplanned_recompiles", "max", slo.max_unplanned_recompiles,
            _streaming_channel(
                "unplanned_recompiles", "max_unplanned_recompiles"
            )[-1],
        ))

    return Verdict(
        scenario=spec.name,
        passed=all(c.passed for c in crits),
        criteria=crits,
    )
