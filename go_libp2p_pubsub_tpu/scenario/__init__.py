"""Scenario engine: declarative, device-compiled adversity campaigns.

- :mod:`.spec` — the declarative layer (:class:`ScenarioSpec` + JSON).
- :mod:`.compiler` — lowering to ``ops.schedule`` event tensors.
- :mod:`.runner` — execution, traces, bit-for-bit replay.
- :mod:`.live_runner` — the same campaigns over real sockets + chaos.
- :mod:`.streaming_runner` — campaigns as open streams through the
  serving plane's ingest ring + resident engine.
- :mod:`.slo` — verdicts graded from the flight record.
- :mod:`.canon` — the named, committed campaign suite.
"""

from .canon import CANON, build, build_all
from .defense import (
    HARDENED_DEFENSE,
    PROMOTED_DEFENSE,
    STANDING_DEFENSE,
    check_invariants,
    defense_digest,
)
from .realism import (
    apply_realism,
    diurnal_churn,
    geo_latency_links,
    heavy_tailed_builder,
    topology_builder,
)
from .compiler import (
    CompiledScenario,
    StreamingPlan,
    compile_scenario,
    compile_streaming_plan,
)
from .live_runner import (
    LivePlaneError,
    LiveScenarioResult,
    live_supported,
    run_live_scenario,
    sim_supported,
)
from .streaming_runner import (
    StreamingPlaneError,
    StreamingScenarioResult,
    run_streaming_scenario,
    streaming_supported,
)
from .runner import (
    ScenarioResult,
    replay_trace,
    run_scenario,
    run_suite,
    save_trace,
    trace_document,
)
from .slo import Criterion, Verdict, evaluate
from .spec import (
    SLO,
    AttackWave,
    ChurnPhase,
    LinkWindow,
    ScenarioSpec,
    Workload,
)

__all__ = [
    "CANON",
    "AttackWave",
    "ChurnPhase",
    "CompiledScenario",
    "Criterion",
    "HARDENED_DEFENSE",
    "LinkWindow",
    "LivePlaneError",
    "LiveScenarioResult",
    "PROMOTED_DEFENSE",
    "SLO",
    "STANDING_DEFENSE",
    "ScenarioResult",
    "ScenarioSpec",
    "StreamingPlan",
    "StreamingPlaneError",
    "StreamingScenarioResult",
    "Verdict",
    "Workload",
    "apply_realism",
    "build",
    "build_all",
    "check_invariants",
    "compile_scenario",
    "compile_streaming_plan",
    "defense_digest",
    "diurnal_churn",
    "evaluate",
    "geo_latency_links",
    "heavy_tailed_builder",
    "live_supported",
    "replay_trace",
    "run_live_scenario",
    "run_scenario",
    "run_streaming_scenario",
    "run_suite",
    "save_trace",
    "sim_supported",
    "streaming_supported",
    "topology_builder",
    "trace_document",
]
