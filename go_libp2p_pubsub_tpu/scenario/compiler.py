"""Scenario compiler: lower a :class:`ScenarioSpec` to device event tensors.

The lowering is a pure host-side function of the spec: every random draw
(churn victims, workload publishers, link cohorts) comes from a
``np.random.default_rng([seed, tag, index])`` substream, so the same spec
always produces the same event tensors — the foundation of bit-for-bit
replay.  The output is a :class:`CompiledScenario`: the constructed model,
its initialized (and possibly adversary-prepared) state, and one
``ops.schedule`` event NamedTuple whose leading axis is the scan axis of
the model's ``rollout_events`` — the campaign executes in a single
``lax.scan`` with no host round-trips.

Model-family support matrix (unsupported combinations raise at compile
time rather than silently dropping events):

==============  =========  ========  ==========  ====
event           gossipsub  treecast  multitopic  rlnc
==============  =========  ========  ==========  ====
abrupt churn        x         x          x         x
graceful churn      x         x                    x
rejoin              x         x          x         x
attack waves        x                spam kinds
link windows        x                    x         x
workloads           x       (root)       x         x
==============  =========  ========  ==========  ====

(rlnc has no mesh/score plane, so attack waves do not lower; its link
windows install ingress DECIMATION — fragments outside the accept gate
are lost, not held — see ``models/rlnc.py``.  Multitopic lowers only the
``spam``/``promise_spam``/``sybil`` kinds; the taxonomy kinds in
``_GOSSIP_ONLY_KINDS`` need gossipsub's promo/silence tensors and score
surgery.)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..config import GossipSubParams, ScoreParams, SimParams, TreeOpts
from ..ops import schedule as sched
from ..ops.graphs import decode_index_plane
from .spec import ScenarioSpec

# Substream tags: each spec component draws from its own child stream, so
# adding/removing one component never shifts another's randomness.
_TAG_WORKLOAD, _TAG_CHURN, _TAG_LINK, _TAG_ATTACK = 1, 2, 3, 4


@dataclasses.dataclass
class CompiledScenario:
    """A spec lowered against a concrete model + initialized state."""

    spec: ScenarioSpec
    model: Any
    state: Any
    events: Any                       # ops.schedule.*Events (host numpy)
    attackers: Optional[np.ndarray]   # bool[N] union of wave attackers
    target: Optional[int]             # eclipse target (record channel)
    n_publishes: int


def _rng(seed: int, tag: int, index: int) -> np.random.Generator:
    return np.random.default_rng([seed, tag, index])


def _split_model_kwargs(spec: ScenarioSpec) -> Dict[str, Any]:
    kw = dict(spec.model)
    if "params" in kw:
        kw["params"] = GossipSubParams(**kw["params"])
    if "score_params" in kw:
        kw["score_params"] = ScoreParams(**kw["score_params"])
    return kw


def build_model(spec: ScenarioSpec, graft_spammers=None):
    """Construct the spec's model (host side; no state yet)."""
    if spec.family == "gossipsub":
        from ..models.gossipsub import GossipSub

        kw = _split_model_kwargs(spec)
        # Declarative topology (r21 realism): a {"kind": ...} dict lowered
        # to a builder closure carrying a value-semantic config_key, so
        # equally-textured models still share jit-compiled rollouts.
        topo = kw.pop("topology", None)
        if topo is not None:
            from .realism import topology_builder

            kw["builder"] = topology_builder(topo)
        return GossipSub(use_pallas=False, graft_spammers=graft_spammers, **kw)
    if "topology" in spec.model:
        raise ValueError("model topology dicts are gossipsub-only")
    if spec.family == "multitopic":
        from ..models.multitopic import MultiTopicGossipSub

        if graft_spammers is not None:
            raise ValueError("graft_spam waves are gossipsub-only")
        return MultiTopicGossipSub(**_split_model_kwargs(spec))
    if spec.family == "rlnc":
        from ..models.rlnc import RLNC

        if graft_spammers is not None:
            raise ValueError("graft_spam waves are gossipsub-only")
        return RLNC(**dict(spec.model))
    if spec.family == "hybrid":
        from ..models.hybrid import HybridGossipSub

        if graft_spammers is not None:
            raise ValueError("graft_spam waves are gossipsub-only")
        return HybridGossipSub(**_split_model_kwargs(spec))
    # treecast: model kwargs split into SimParams / TreeOpts fields.
    from ..models.treecast import TreeCast

    kw = dict(spec.model)
    kw.pop("n_peers", None)
    sim_names = {f.name for f in dataclasses.fields(SimParams)}
    opt_names = {f.name for f in dataclasses.fields(TreeOpts)}
    sim_kw = {k: v for k, v in kw.items() if k in sim_names}
    opt_kw = {k: v for k, v in kw.items() if k in opt_names}
    unknown = set(kw) - sim_names - opt_names
    if unknown:
        raise ValueError(f"unknown treecast model keys: {sorted(unknown)}")
    return TreeCast(params=SimParams(**sim_kw), opts=TreeOpts(**opt_kw))


def _init_tree_state(model, spec: ScenarioSpec):
    """A fully joined tree of ``n_peers`` (batched join walk, host loop)."""
    import jax.numpy as jnp

    from ..ops import tree as tree_ops

    n_peers = spec.model.get("n_peers", model.params.max_peers)
    if n_peers > model.params.max_peers:
        raise ValueError("n_peers exceeds max_peers")
    st = model.init(root=0)
    mask = np.zeros(model.params.max_peers, bool)
    mask[:n_peers] = True
    st = tree_ops.begin_subscribe_many(st, jnp.asarray(mask))
    for _ in range(8 * n_peers):
        if bool(np.asarray(st.joined[:n_peers]).all()):
            break
        st = tree_ops.step(st)
    else:
        raise RuntimeError("tree join walk did not converge")
    return st


# Both targeted kinds need the flight recorder's single target channel, so
# a scenario carries at most one of them.
_TARGETED_KINDS = ("eclipse", "cold_boot_eclipse")
# The taxonomy extension rides the gossipsub event tensors (promo/silence)
# and score surgery that the multitopic plane does not carry.
_GOSSIP_ONLY_KINDS = (
    "cold_boot_eclipse", "covert_flash", "score_farm", "self_promo_ihave",
    "partition_flood",
)


def _targeted_wave(spec: ScenarioSpec):
    waves = [a for a in spec.attacks if a.kind in _TARGETED_KINDS]
    if len(waves) > 1:
        raise ValueError(
            "at most one eclipse / cold_boot_eclipse wave per scenario"
        )
    return waves[0] if waves else None


def _window(start: int, stop: Optional[int], n_steps: int) -> Tuple[int, int]:
    stop = n_steps if stop is None else min(stop, n_steps)
    if not (0 <= start < n_steps) or stop <= start:
        raise ValueError(
            f"event window [{start}, {stop}) outside scenario [0, {n_steps})"
        )
    return start, stop


def compile_scenario(spec: ScenarioSpec) -> CompiledScenario:
    """Lower ``spec`` -> (model, initialized state, event tensors)."""
    if spec.family == "hybrid":
        # The hybrid's closed-sim surface speaks the streaming engine's
        # chunk dialect (MultiTopicEvents, T = 1); its campaigns run
        # through compile_streaming_plan / streaming_runner instead.
        raise ValueError(
            "hybrid family is streaming-only (set "
            'streaming={"streaming_only": True, ...})'
        )
    if spec.family == "treecast":
        return _compile_tree(spec)
    return _compile_gossip_like(spec)


# ---------------------------------------------------------------------------
# gossipsub / multitopic lowering
# ---------------------------------------------------------------------------

def _compile_gossip_like(spec: ScenarioSpec) -> CompiledScenario:
    import jax.numpy as jnp

    T, multitopic = spec.n_steps, spec.family == "multitopic"
    rlnc = spec.family == "rlnc"
    if rlnc and spec.attacks:
        raise ValueError(
            "attack waves are not lowered for rlnc (no mesh/score plane "
            "to eclipse, spam or graft against)"
        )

    # -- model + state (eclipse needs the converged mesh, so init first;
    #    graft_spam rebinds the constructor and re-inits with the same seed,
    #    which reproduces the same topology and warmup mesh).
    model = build_model(spec)
    st = model.init(seed=spec.seed)
    n = model.n
    ecl = _targeted_wave(spec)
    target = ecl.target if ecl else None

    # Per-wave attacker masks (spam/mute lowering is wave-scoped) plus the
    # union the record channels and publisher draws exclude.
    attackers = np.zeros(n, bool)
    wave_att: List[np.ndarray] = []
    for w in spec.attacks:
        wa = np.zeros(n, bool)
        if multitopic and w.kind in _GOSSIP_ONLY_KINDS:
            raise ValueError(f"{w.kind} waves are gossipsub-only")
        if w.kind in _TARGETED_KINDS:
            if multitopic:
                raise ValueError("eclipse waves are gossipsub-only")
            nbrs = np.asarray(decode_index_plane(np.asarray(st.nbrs)))
            if not (0 <= w.target < n):
                raise ValueError(f"{w.kind} target {w.target} out of range")
            if w.kind == "eclipse":
                mesh = np.asarray(st.mesh)
                att_ids = sorted(
                    {int(nbrs[w.target, s]) for s in range(model.k)
                     if mesh[w.target, s]}
                )
                if not att_ids:
                    raise ValueError(
                        "eclipse target has an empty mesh at init"
                    )
            else:  # cold_boot_eclipse: connected neighbors, slot order
                valid = np.asarray(st.nbr_valid)
                conn = list(dict.fromkeys(
                    int(nbrs[w.target, s]) for s in range(model.k)
                    if valid[w.target, s]
                ))
                if len(conn) < w.n_attackers:
                    raise ValueError(
                        f"cold_boot_eclipse wants {w.n_attackers} "
                        f"monopolists but target {w.target} has only "
                        f"{len(conn)} connected neighbors"
                    )
                att_ids = conn[: w.n_attackers]
            wa[att_ids] = True
        else:
            if w.kind == "graft_spam" and multitopic:
                raise ValueError("graft_spam waves are gossipsub-only")
            wa[: w.n_attackers] = True
        wave_att.append(wa)
        attackers |= wa

    if any(w.graft_spam or w.kind == "graft_spam" for w in spec.attacks):
        model = build_model(spec, graft_spammers=attackers)
        st = model.init(seed=spec.seed)

    # Sybil colocation: attacker identities share one IP group (applied to
    # the state once — P6 scores it from the next heartbeat on).
    if any(w.kind == "sybil" for w in spec.attacks):
        group = np.asarray(st.gcounters.ip_group).copy()
        group[attackers] = int(group.min(initial=0))
        st = st._replace(
            gcounters=st.gcounters._replace(ip_group=jnp.asarray(group))
        )

    # Cold-boot monopoly: rewrite the target's converged mesh so its ONLY
    # mesh edges are the monopolists (symmetric via nbrs/rev), and zero the
    # per-slot score counters on every edge the target touches — the attack
    # lands before any P1/P2 history exists, on either side, so pruning the
    # silent monopolists must come from fresh deficit evidence alone.
    for ai, w in enumerate(spec.attacks):
        if w.kind != "cold_boot_eclipse":
            continue
        import jax

        wa = wave_att[ai]
        mesh = np.asarray(st.mesh).copy()
        nbrs = np.asarray(decode_index_plane(np.asarray(st.nbrs)))
        rev = np.asarray(decode_index_plane(np.asarray(st.rev)))
        valid = np.asarray(st.nbr_valid)
        counters = jax.tree.map(lambda x: np.asarray(x).copy(), st.counters)
        for s in range(model.k):
            if not valid[w.target, s]:
                continue
            j, r = int(nbrs[w.target, s]), int(rev[w.target, s])
            keep = bool(wa[j])
            mesh[w.target, s] = keep
            mesh[j, r] = keep
            for f in counters:
                f[w.target, s] = 0.0
                f[j, r] = 0.0
        st = st._replace(
            mesh=jnp.asarray(mesh),
            counters=jax.tree.map(jnp.asarray, counters),
        )

    # -- publish requests per step (src resolution deferred to the timeline
    #    walk so publishers are drawn from peers alive at that step).
    # request = (picker_rng | None, src | None, valid, topic)
    requests: List[List[tuple]] = [[] for _ in range(T)]
    for wi, w in enumerate(spec.workloads):
        start, stop = _window(w.start, w.stop, T)
        rng = _rng(spec.seed, _TAG_WORKLOAD, wi)
        if w.kind == "burst":
            steps = [start]
        else:
            steps = range(start, stop, w.every)
        for t in steps:
            for _ in range(w.n_msgs):
                requests[t].append((rng, w.src, w.valid, w.topic))
    for ai, w in enumerate(spec.attacks):
        ids = [int(a) for a in np.flatnonzero(wave_att[ai])]
        if w.kind == "covert_flash":
            start, stop = _window(w.start, w.stop, T)
            if not (start <= w.defect_step < stop):
                raise ValueError(
                    f"covert_flash defect_step {w.defect_step} outside the "
                    f"wave window [{start}, {stop})"
                )
            # Honest until the defect; invalid spam only after it.
            if w.spam_every:
                for t in range(w.defect_step, stop, w.spam_every):
                    for a in ids:
                        requests[t].append((None, a, False, 0))
        elif w.kind == "score_farm":
            start, stop = _window(w.start, w.stop, T)
            farm_end = start + w.farm_steps
            if farm_end >= stop:
                raise ValueError(
                    f"score_farm farm_steps {w.farm_steps} leaves no spam "
                    f"phase in the wave window [{start}, {stop})"
                )
            # Bank valid-delivery credit, then cash it in as spam cover.
            for t in range(start, farm_end, w.spam_every):
                for a in ids:
                    requests[t].append((None, a, True, 0))
            for t in range(farm_end, stop, w.spam_every):
                for a in ids:
                    requests[t].append((None, a, False, 0))
        elif w.kind == "self_promo_ihave":
            # Valid self-originated traffic feeds the crafted IHAVEs.
            start, stop = _window(w.start, w.stop, T)
            for t in range(start, stop, w.spam_every):
                for a in ids:
                    requests[t].append((None, a, True, 0))
        elif w.kind == "partition_flood":
            start, stop = _window(w.start, w.stop, T)
            flood = stop + w.flood_offset
            if flood >= T:
                raise ValueError(
                    f"partition_flood flood start {flood} is past the "
                    f"scenario end ({T} steps)"
                )
            for t in range(flood, T, w.spam_every):
                for a in ids:
                    requests[t].append((None, a, False, 0))
        elif w.spam_every or w.kind == "spam":
            every = w.spam_every if w.spam_every else 1
            start, stop = _window(w.start, w.stop, T)
            for t in range(start, stop, every):
                for a in ids:
                    requests[t].append((None, a, False, 0))

    n_publishes = sum(len(r) for r in requests)
    if n_publishes > model.m:
        raise ValueError(
            f"scenario publishes {n_publishes} messages but the window "
            f"holds {model.m}; grow msg_window (slot recycling would make "
            f"the flight recorder's delivery fraction unaccountable)"
        )
    pub_width = max(1, max((len(r) for r in requests), default=0))

    if multitopic:
        events = sched.empty_multitopic_events(T, n, pub_width)
    else:
        events = sched.empty_gossip_events(T, n, pub_width)

    # -- attack windows -> mute / silence / promo tensors (wave-scoped).
    for ai, w in enumerate(spec.attacks):
        wa = wave_att[ai]
        if w.kind in ("eclipse", "promise_spam", "cold_boot_eclipse"):
            start, stop = _window(w.start, w.stop, T)
            events.mute_on[start] |= wa
            if stop < T:
                events.mute_off[stop] |= wa
            if w.kind in _TARGETED_KINDS:
                events.silence[start:stop] |= wa[None, :]
        elif w.kind == "covert_flash":
            start, stop = _window(w.start, w.stop, T)
            # The mask drops at defect_step, not at wave start.
            events.mute_on[w.defect_step] |= wa
            if stop < T:
                events.mute_off[stop] |= wa
            events.silence[w.defect_step : stop] |= wa[None, :]
        elif w.kind == "self_promo_ihave":
            start, stop = _window(w.start, w.stop, T)
            # Crafted IHAVEs (self-originated ids only) + never serving the
            # IWANTs those ads attract.
            events.promo_on[start] |= wa
            events.mute_on[start] |= wa
            if stop < T:
                events.promo_off[stop] |= wa
                events.mute_off[stop] |= wa

    if not multitopic and not rlnc and events.silence.any() \
            and model.max_edge_delay:
        raise ValueError(
            "eclipse silence requires the ideal eager fabric "
            "(max_edge_delay == 0): squelching fresh_w would desync the "
            "per-edge fresh history"
        )

    # -- link-degradation windows -> delay set/restore rows.
    for li, w in enumerate(spec.links):
        start, stop = _window(w.start, w.stop, T)
        if w.peers is not None:
            cohort = np.asarray(w.peers, int)
            if cohort.size and (cohort.min() < 0 or cohort.max() >= n):
                raise ValueError(f"link window peers out of range [0, {n})")
        else:
            rng = _rng(spec.seed, _TAG_LINK, li)
            size = max(1, int(round(w.frac * n)))
            cohort = rng.choice(n, size=size, replace=False)
        row = events.delay[start].copy()
        row[cohort] = w.delay
        events.delay[start] = row
        if stop < T:
            row = events.delay[stop].copy()
            row[cohort] = np.where(events.delay[stop][cohort] < 0, 0,
                                   events.delay[stop][cohort])
            events.delay[stop] = row

    # -- timeline walk: churn + faults + publish src resolution, against a
    #    host mirror of liveness/subscription so victims and publishers are
    #    always drawn from peers actually present at that step.
    alive = np.ones(n, bool)
    subscribed = np.asarray(st.subscribed).copy() if not multitopic else (
        np.asarray(st.subscribed).any(axis=0)
    )
    protected = attackers.copy()
    if target is not None:
        protected[target] = True
    protected[0] = True  # keep a stable publisher/root candidate

    churn_events: List[List[tuple]] = [[] for _ in range(T)]  # (phase, kind)
    for ci, ph in enumerate(spec.churn):
        start, stop = _window(ph.start, ph.stop, T)
        if ph.graceful and multitopic:
            raise ValueError("graceful churn is not lowered for multitopic")
        for t in range(start, stop, ph.every):
            churn_events[t].append(("phase", ci))
    if spec.faults:
        for t_str, ids in spec.faults.get("kills", {}).items():
            t = int(t_str)
            if 0 <= t < T:
                churn_events[t].append(("fault_kill", ids))
        for t_str, ids in spec.faults.get("leaves", {}).items():
            t = int(t_str)
            if 0 <= t < T:
                churn_events[t].append(("fault_leave", ids))
            if multitopic:
                raise ValueError("fault leaves are not lowered for multitopic")

    churn_rngs = [
        _rng(spec.seed, _TAG_CHURN, ci) for ci in range(len(spec.churn))
    ]
    churn_cursor = [0] * len(spec.churn)  # cycle index into explicit peers
    rejoin_at: List[List[tuple]] = [[] for _ in range(T + 1)]  # (ids, graceful)

    # partition_flood cohorts ride the fault/rejoin machinery (kill at
    # start, revive at stop) so the liveness mirror below stays correct for
    # victim and publisher draws — never raw kill/revive tensor writes.
    for ai, w in enumerate(spec.attacks):
        if w.kind != "partition_flood":
            continue
        start, stop = _window(w.start, w.stop, T)
        rng = _rng(spec.seed, _TAG_ATTACK, ai)
        pool = np.flatnonzero(~protected)
        size = min(max(1, int(round(w.partition_frac * n))), len(pool))
        if size == 0:
            raise ValueError(
                "partition_flood found no honest unprotected peers to cut"
            )
        cohort = np.sort(rng.choice(pool, size=size, replace=False)).tolist()
        churn_events[start].append(("fault_kill", cohort))
        rejoin_at[stop].append((cohort, False))

    slot = 0

    for t in range(T):
        # rejoins land before this step's new departures.
        for ids, graceful in rejoin_at[t]:
            ids = [i for i in ids if not alive[i] or not subscribed[i]]
            if not ids:
                continue
            if graceful:
                events.sub_on[t][ids] = True
                subscribed[ids] = True
            else:
                if multitopic:
                    raise ValueError(
                        "rejoin is not lowered for multitopic (no revive "
                        "event tensor)"
                    )
                events.revive[t][ids] = True
                alive[ids] = True
        for kind, payload in churn_events[t]:
            if kind == "phase":
                ci = payload
                ph = spec.churn[ci]
                if ph.peers is not None:
                    k0 = churn_cursor[ci]
                    victims = [
                        p for p in ph.peers[k0 : k0 + ph.kills_per_event]
                        if 0 <= p < n
                    ]
                    churn_cursor[ci] = k0 + ph.kills_per_event
                else:
                    pool = np.flatnonzero(alive & subscribed & ~protected)
                    take = min(ph.kills_per_event, len(pool))
                    victims = (
                        churn_rngs[ci].choice(pool, size=take, replace=False)
                        .tolist() if take else []
                    )
                if not victims:
                    continue
                if ph.graceful:
                    events.sub_off[t][victims] = True
                    subscribed[victims] = False
                else:
                    events.kill[t][victims] = True
                    alive[victims] = False
                if ph.rejoin_after is not None:
                    back = t + ph.rejoin_after
                    if back <= T - 1:
                        rejoin_at[back].append((victims, ph.graceful))
            elif kind == "fault_kill":
                ids = [i for i in payload if 0 <= i < n]
                events.kill[t][ids] = True
                alive[ids] = False
            else:  # fault_leave -> graceful semantics (unsubscribe)
                ids = [i for i in payload if 0 <= i < n]
                events.sub_off[t][ids] = True
                subscribed[ids] = False
        for rng, src, valid, topic in requests[t]:
            if src is None:
                pool = np.flatnonzero(alive & subscribed & ~attackers)
                if len(pool) == 0:
                    raise ValueError(
                        f"no eligible publisher alive at step {t}"
                    )
                src = int(rng.choice(pool))
            elif not (0 <= src < n):
                raise ValueError(f"publisher {src} out of range [0, {n})")
            entry = {"src": src, "slot": slot, "valid": bool(valid)}
            if multitopic:
                if not (0 <= topic < model.t):
                    raise ValueError(f"topic {topic} out of range")
                entry["topic"] = topic
            sched.add_publish(events, t, entry)
            slot += 1

    return CompiledScenario(
        spec=spec, model=model, state=st, events=events,
        attackers=attackers if attackers.any() else None,
        target=target, n_publishes=n_publishes,
    )


# ---------------------------------------------------------------------------
# treecast lowering
# ---------------------------------------------------------------------------

def _compile_tree(spec: ScenarioSpec) -> CompiledScenario:
    T = spec.n_steps
    if spec.attacks:
        raise ValueError("attack waves are not lowered for treecast")
    if spec.links:
        raise ValueError("link windows are not lowered for treecast "
                         "(use set_link_profile on the state)")
    slo = spec.slo
    if any(v is not None for v in (
        slo.max_p50, slo.max_p99, slo.max_capture_frac,
        slo.max_final_attacker_mesh_edges, slo.min_final_target_honest_edges,
    )):
        raise ValueError(
            "latency/capture SLOs need the mesh flight recorder; the tree "
            "record grades delivery totals and orphan backlog"
        )

    model = build_model(spec)
    st = _init_tree_state(model, spec)
    n = model.params.max_peers
    n_peers = spec.model.get("n_peers", n)

    requests: List[int] = [0] * T
    for wi, w in enumerate(spec.workloads):
        start, stop = _window(w.start, w.stop, T)
        steps = [start] if w.kind == "burst" else range(start, stop, w.every)
        for t in steps:
            requests[t] += w.n_msgs
    n_publishes = sum(requests)
    if n_publishes > model.params.queue_cap:
        raise ValueError(
            f"{n_publishes} root publishes exceed queue_cap "
            f"{model.params.queue_cap}"
        )
    pub_width = max(1, max(requests, default=0))
    events = sched.empty_tree_events(T, n, pub_width)

    alive = np.zeros(n, bool)
    alive[:n_peers] = True
    protected = np.zeros(n, bool)
    protected[0] = True  # the root

    churn_events: List[List[tuple]] = [[] for _ in range(T)]
    for ci, ph in enumerate(spec.churn):
        start, stop = _window(ph.start, ph.stop, T)
        for t in range(start, stop, ph.every):
            churn_events[t].append(("phase", ci))
    if spec.faults:
        for t_str, ids in spec.faults.get("kills", {}).items():
            t = int(t_str)
            if 0 <= t < T:
                churn_events[t].append(("fault_kill", ids))
        for t_str, ids in spec.faults.get("leaves", {}).items():
            t = int(t_str)
            if 0 <= t < T:
                churn_events[t].append(("fault_leave", ids))

    churn_rngs = [
        _rng(spec.seed, _TAG_CHURN, ci) for ci in range(len(spec.churn))
    ]
    churn_cursor = [0] * len(spec.churn)
    rejoin_at: List[List[list]] = [[] for _ in range(T + 1)]
    msg_id = 0

    for t in range(T):
        for ids in rejoin_at[t]:
            ids = [i for i in ids if not alive[i]]
            if ids:
                events.sub[t][ids] = True
                alive[ids] = True
        for kind, payload in churn_events[t]:
            if kind == "phase":
                ci = payload
                ph = spec.churn[ci]
                if ph.peers is not None:
                    k0 = churn_cursor[ci]
                    victims = [
                        p for p in ph.peers[k0 : k0 + ph.kills_per_event]
                        if 0 <= p < n
                    ]
                    churn_cursor[ci] = k0 + ph.kills_per_event
                else:
                    pool = np.flatnonzero(alive & ~protected)
                    take = min(ph.kills_per_event, len(pool))
                    victims = (
                        churn_rngs[ci].choice(pool, size=take, replace=False)
                        .tolist() if take else []
                    )
                if not victims:
                    continue
                field = events.leave if ph.graceful else events.kill
                field[t][victims] = True
                alive[victims] = False
                if ph.rejoin_after is not None:
                    back = t + ph.rejoin_after
                    if back <= T - 1:
                        rejoin_at[back].append(victims)
            elif kind == "fault_kill":
                ids = [i for i in payload if 0 <= i < n]
                events.kill[t][ids] = True
                alive[ids] = False
            else:
                ids = [i for i in payload if 0 <= i < n]
                events.leave[t][ids] = True
                alive[ids] = False
        for _ in range(requests[t]):
            sched.add_publish(events, t, {"msg": msg_id})
            msg_id += 1

    return CompiledScenario(
        spec=spec, model=model, state=st, events=events,
        attackers=None, target=None, n_publishes=n_publishes,
    )


# ---------------------------------------------------------------------------
# streaming lowering (serving plane)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StreamingPlan:
    """A spec lowered for the serving plane: a host publish TIMELINE, not
    device event tensors.  The streaming runner replays ``timeline`` through
    the ingest ring into a resident :class:`~..serve.engine.StreamingEngine`;
    the device-side shapes are fixed by (chunk_steps, pub_width), never by
    the campaign length, which is what lets the stream be unbounded."""

    spec: ScenarioSpec
    timeline: List[List[tuple]]   # per step: [(topic, src, valid), ...]
    n_publishes: int
    chunk_steps: int
    capacity: int
    policy: str
    pub_width: int
    completion_frac: float
    # Chaos lowering (r14): validated fault stages for the runner to
    # inject at chunk boundaries, and the engine's snapshot period.
    faults: Dict[str, Any] = dataclasses.field(default_factory=dict)
    snapshot_every: int = 0
    # r16: hybrid plane — run an eager-forced twin over the same timeline
    # and report the p99 ingest->delivery ratio as a channel.
    compare_eager: bool = False
    # r20: self-tuning — normalized {"ladder": [(steps, width), ...],
    # "policy": {ControllerPolicy overrides}} when the spec asks for a
    # controller, and the self-tuned-vs-best-static A/B flag (one static
    # twin per ladder rung over the same timeline).
    controller: Optional[Dict[str, Any]] = None
    compare_static: bool = False


def compile_streaming_plan(spec: ScenarioSpec) -> StreamingPlan:
    """Lower ``spec`` for the streaming plane.

    Honest support matrix: only the ``multitopic`` and ``hybrid`` families
    have a resident engine, and the serving plane lowers WORKLOADS only —
    churn, attack and link windows mutate device event tensors mid-scan,
    which the fixed-shape resident chunk deliberately does not carry
    (publishes and, on the hybrid plane, the per-chunk ingress-loss stamp
    are the only per-chunk variables).  Requesting them raises rather than
    silently ignoring campaign components.
    """
    if spec.family not in ("multitopic", "hybrid"):
        raise ValueError(
            f"streaming plane requires the multitopic or hybrid family, "
            f"got {spec.family!r}"
        )
    if spec.churn or spec.attacks or spec.links or spec.faults:
        raise ValueError(
            "churn/attack/link/fault components are not lowered for the "
            "streaming plane (publishes are the only per-chunk variable)"
        )
    T = spec.n_steps
    n = int(spec.model.get("n_peers", 1024))
    # The hybrid is a single-topic plane (T = 1): workload topics clip to 0.
    n_topics = (
        1 if spec.family == "hybrid"
        else int(spec.model.get("n_topics", 4))
    )
    cfg = dict(spec.streaming or {})
    chunk_steps = int(cfg.get("chunk_steps", 8))
    capacity = int(cfg.get("capacity", 64))
    policy = str(cfg.get("policy", "block"))
    # Default pub_width lets ONE chunk drain a full ring: ceil(cap / steps).
    pub_width = int(cfg.get("pub_width", max(1, -(-capacity // chunk_steps))))
    completion_frac = float(cfg.get("completion_frac", 0.99))
    faults = _lower_streaming_faults(cfg, T, chunk_steps)
    controller = _lower_controller(cfg, chunk_steps, pub_width)
    compare_static = bool(cfg.get("compare_static", False))
    if compare_static and controller is None:
        raise ValueError(
            "compare_static needs a \"controller\" dict (the static twins "
            "are the ladder's rungs — nothing to compare without a ladder)"
        )
    compare_eager = bool(cfg.get("compare_eager", False))
    if (
        compare_eager or "loss" in faults or "loss_oscillate" in faults
    ) and spec.family != "hybrid":
        raise ValueError(
            "loss windows / compare_eager are hybrid-family features "
            "(only the hybrid model stamps per-chunk ingress loss)"
        )
    # A staged crash needs a snapshot to come back from; default to
    # every-chunk snapshots so the boundary crash loses nothing.
    snapshot_every = int(
        cfg.get("snapshot_every", 1 if "crash_at_chunk" in faults else 0)
    )
    if snapshot_every < 0:
        raise ValueError("snapshot_every must be >= 0")
    if "crash_at_chunk" in faults and snapshot_every == 0:
        raise ValueError(
            "crash_at_chunk needs snapshot_every >= 1 (nothing to restore "
            "from otherwise)"
        )

    timeline: List[List[tuple]] = [[] for _ in range(T)]
    for wi, w in enumerate(spec.workloads):
        start, stop = _window(w.start, w.stop, T)
        rng = _rng(spec.seed, _TAG_WORKLOAD, wi)
        if not (0 <= w.topic < n_topics):
            raise ValueError(f"topic {w.topic} out of range [0, {n_topics})")
        steps = [start] if w.kind == "burst" else range(start, stop, w.every)
        for t in steps:
            for _ in range(w.n_msgs):
                # No churn on this plane, so every peer is alive: publishers
                # draw uniformly (same per-workload substream discipline as
                # the sim compiler, so seeds reproduce bit-for-bit).
                src = int(rng.integers(n)) if w.src is None else w.src
                if not (0 <= src < n):
                    raise ValueError(f"publisher {src} out of range [0, {n})")
                timeline[t].append((w.topic, src, bool(w.valid)))

    if "producer_stall" in faults:
        # Stall-then-flood: the producer is wedged through the window, and
        # everything it would have published lands in one step at wake-up.
        stall = faults["producer_stall"]
        wake = stall["start"] + stall["steps"]
        deferred: List[tuple] = []
        for t in range(stall["start"], wake):
            deferred.extend(timeline[t])
            timeline[t] = []
        timeline[wake] = deferred + timeline[wake]

    return StreamingPlan(
        spec=spec,
        timeline=timeline,
        n_publishes=sum(len(r) for r in timeline),
        chunk_steps=chunk_steps,
        capacity=capacity,
        policy=policy,
        pub_width=pub_width,
        completion_frac=completion_frac,
        faults=faults,
        snapshot_every=snapshot_every,
        compare_eager=compare_eager,
        controller=controller,
        compare_static=compare_static,
    )


def _lower_controller(
    cfg: Dict[str, Any], chunk_steps: int, pub_width: int
) -> Optional[Dict[str, Any]]:
    """Validate the streaming dict's ``controller`` key: the geometry
    ladder must contain the spec's base geometry (the pre-warm contract),
    and policy overrides must name real :class:`ControllerPolicy` fields
    with values its validation accepts — both checked at compile time, so
    a bad spec fails before any engine warms."""
    if cfg.get("controller") is None:
        return None
    from ..serve.tuning import ControllerPolicy, validate_ladder

    ctl = dict(cfg["controller"])
    unknown = set(ctl) - {"ladder", "policy"}
    if unknown:
        raise ValueError(
            f"unknown controller keys {sorted(unknown)} "
            "(expected \"ladder\" and optional \"policy\")"
        )
    ladder_cfg = ctl.get("ladder")
    if not ladder_cfg:
        raise ValueError("controller needs a non-empty \"ladder\"")
    rungs = validate_ladder(
        [tuple(int(x) for x in g) for g in ladder_cfg],
        (chunk_steps, pub_width),
    )
    overrides = dict(ctl.get("policy") or {})
    try:
        ControllerPolicy(**overrides)
    except TypeError as e:
        raise ValueError(f"bad controller policy override: {e}") from None
    return {
        "ladder": [r.as_tuple() for r in rungs],
        "policy": overrides,
    }


def _lower_streaming_faults(
    cfg: Dict[str, Any], n_steps: int, chunk_steps: int
) -> Dict[str, Any]:
    """Validate the streaming dict's fault keys into StreamingPlan.faults.

    Chunk-indexed faults fire after that many TRAFFIC chunks (1-based, so
    ``crash_at_chunk=1`` kills the engine right after its first loaded
    chunk); they must land inside the campaign's chunk count.  Unknown
    behavior is rejected loudly, matching the sim compiler's posture."""
    n_chunks = -(-n_steps // chunk_steps)
    faults: Dict[str, Any] = {}
    for key in ("crash_at_chunk", "verifier_crash_at_chunk"):
        if cfg.get(key) is not None:
            at = int(cfg[key])
            if not (1 <= at <= n_chunks):
                raise ValueError(
                    f"{key}={at} outside the campaign's chunk range "
                    f"[1, {n_chunks}]"
                )
            faults[key] = at
    if cfg.get("producer_stall") is not None:
        st = dict(cfg["producer_stall"])
        start, steps = int(st.get("start", 0)), int(st.get("steps", 0))
        if steps < 1 or start < 0:
            raise ValueError("producer_stall needs start >= 0, steps >= 1")
        if start + steps >= n_steps:
            raise ValueError(
                f"producer_stall window [{start}, {start + steps}) must end "
                f"before the campaign's last step ({n_steps - 1}) so the "
                "deferred flood still publishes"
            )
        faults["producer_stall"] = {"start": start, "steps": steps}
    if cfg.get("clock_skew") is not None:
        sk = dict(cfg["clock_skew"])
        at = int(sk.get("at_chunk", 1))
        if not (1 <= at <= n_chunks):
            raise ValueError(
                f"clock_skew.at_chunk={at} outside the campaign's chunk "
                f"range [1, {n_chunks}]"
            )
        faults["clock_skew"] = {
            "at_chunk": at, "skew_s": float(sk.get("skew_s", 0.0)),
        }
    if cfg.get("loss") is not None:
        # Degraded-link window (r16, hybrid plane): chunks in
        # [start_chunk, stop_chunk) ingest with per-receiver decimation
        # ``delay`` stamped on the event tensors; the stamp resets to 0 at
        # stop_chunk so the drain (and any eager twin) runs on clean fabric.
        lw = dict(cfg["loss"])
        start = int(lw.get("start_chunk", 0))
        stop = int(lw.get("stop_chunk", n_chunks))
        delay = int(lw.get("delay", 1))
        if delay < 1:
            raise ValueError("loss.delay must be >= 1 (decimation period)")
        if not (0 <= start < stop <= n_chunks):
            raise ValueError(
                f"loss window [{start}, {stop}) outside the campaign's "
                f"chunk range [0, {n_chunks}]"
            )
        faults["loss"] = {
            "start_chunk": start, "stop_chunk": stop, "delay": delay,
        }
    if cfg.get("loss_oscillate") is not None:
        # r21 hysteresis-oscillation attack (hybrid plane): the adversary
        # flips the link between lossy (decimation ``delay``) and clean
        # every ``period_chunks`` chunks inside [start_chunk, stop_chunk),
        # starting lossy.  Tuned to straddle the hybrid's switch_hi /
        # switch_lo band, it tries to force worst-of-both behavior out of
        # the eager<->coded estimator (each flip lands just as the EWMA
        # crosses a threshold).
        ow = dict(cfg["loss_oscillate"])
        start = int(ow.get("start_chunk", 0))
        stop = int(ow.get("stop_chunk", n_chunks))
        period = int(ow.get("period_chunks", 1))
        delay = int(ow.get("delay", 1))
        if delay < 1:
            raise ValueError(
                "loss_oscillate.delay must be >= 1 (decimation period)"
            )
        if period < 1:
            raise ValueError("loss_oscillate.period_chunks must be >= 1")
        if not (0 <= start < stop <= n_chunks):
            raise ValueError(
                f"loss_oscillate window [{start}, {stop}) outside the "
                f"campaign's chunk range [0, {n_chunks}]"
            )
        if "loss" in faults:
            raise ValueError(
                "\"loss\" and \"loss_oscillate\" stamp the same ingress-"
                "delay lever — use one or the other"
            )
        faults["loss_oscillate"] = {
            "start_chunk": start, "stop_chunk": stop,
            "period_chunks": period, "delay": delay,
        }
    if cfg.get("loss_regimes") is not None:
        # r20 drifting-workload windows: STEP-keyed (not chunk-keyed) so
        # the same spec is fair across chunk geometries — a controller
        # switching rungs and a static twin see the loss start and stop at
        # the same timeline steps.  Windows must be ordered and disjoint.
        regimes: List[Dict[str, int]] = []
        for i, rw in enumerate(cfg["loss_regimes"]):
            rw = dict(rw)
            start = int(rw.get("start_step", 0))
            stop = int(rw.get("stop_step", n_steps))
            delay = int(rw.get("delay", 1))
            if delay < 1:
                raise ValueError(
                    f"loss_regimes[{i}].delay must be >= 1"
                )
            if not (0 <= start < stop <= n_steps):
                raise ValueError(
                    f"loss_regimes[{i}] window [{start}, {stop}) outside "
                    f"the campaign's step range [0, {n_steps}]"
                )
            if regimes and start < regimes[-1]["stop_step"]:
                raise ValueError(
                    f"loss_regimes[{i}] starts at step {start}, inside the "
                    f"previous window (ends {regimes[-1]['stop_step']}) — "
                    "windows must be ordered and disjoint"
                )
            regimes.append(
                {"start_step": start, "stop_step": stop, "delay": delay}
            )
        if "loss" in faults or "loss_oscillate" in faults:
            raise ValueError(
                "\"loss\"/\"loss_oscillate\" (chunk-keyed) and "
                "\"loss_regimes\" (step-keyed) stamp the same ingress-"
                "delay lever — use one or the other"
            )
        faults["loss_regimes"] = regimes
    return faults
