"""Scenario execution: run campaigns, grade SLOs, write replayable traces.

``run_scenario`` compiles a spec, executes the whole campaign in the
model's single-scan ``rollout_events`` with ``record=True``, and grades
the flight record into a :class:`~.slo.Verdict`.  ``save_trace`` persists
(spec + seed + flight record) as one JSON document; ``replay_trace``
re-compiles the embedded spec, re-runs it, and compares the fresh flight
record against the stored one bit-for-bit.

Bit-for-bit means EXACT: floats go through ``float.hex`` (no decimal
rounding — ``utils.metrics.flight_summary`` rounds to 6dp and is therefore
a display surface, not a replay surface), NaN/Inf become explicit tokens,
and the replay comparison is string equality on the re-encoded record.
Determinism holds because the event tensors are a pure function of the
spec (host ``default_rng`` substreams) and the scan itself is one XLA
program replayed on the same input — same spec + same seed => the same
program on the same bytes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from . import slo as slo_mod
from .compiler import CompiledScenario, compile_scenario
from .spec import ScenarioSpec

TRACE_VERSION = 1


@dataclasses.dataclass
class ScenarioResult:
    """One executed campaign: the compiled form, device outputs, verdict."""

    compiled: CompiledScenario
    final_state: Any
    record: Dict[str, np.ndarray]
    verdict: slo_mod.Verdict

    @property
    def spec(self) -> ScenarioSpec:
        return self.compiled.spec


def _run_compiled(comp: CompiledScenario):
    """Dispatch to the family's ``rollout_events`` (record=True)."""
    import jax.numpy as jnp

    if comp.spec.family == "gossipsub":
        att = (
            jnp.asarray(comp.attackers) if comp.attackers is not None else None
        )
        return comp.model.rollout_events(
            comp.state, comp.events, attackers=att, target=comp.target,
            record=True,
        )
    return comp.model.rollout_events(comp.state, comp.events, record=True)


def run_scenario(
    spec_or_compiled: Union[ScenarioSpec, CompiledScenario],
    trace_out: Optional[str] = None,
) -> ScenarioResult:
    """Compile (if needed) and execute one scenario, verdict included.

    ``trace_out`` writes an ``obs-record-trace/1`` artifact: the sim plane
    has no host clock (one scan, device time only), so the trace's time
    axis is the step index and the channels are the flight record's
    per-step series rendered as Chrome counter events.
    """
    comp = (
        spec_or_compiled
        if isinstance(spec_or_compiled, CompiledScenario)
        else compile_scenario(spec_or_compiled)
    )
    final, record_dev = _run_compiled(comp)
    record = {k: np.asarray(v) for k, v in record_dev.items()}
    verdict = slo_mod.evaluate(comp.spec, record, comp.n_publishes)
    if trace_out is not None:
        from ..obs.export import build_record_artifact, write_json

        write_json(trace_out, build_record_artifact(
            plane="sim", scenario=comp.spec.name,
            verdict=verdict.to_dict(), record=record,
        ))
    return ScenarioResult(
        compiled=comp, final_state=final, record=record, verdict=verdict
    )


def run_suite(
    specs: List[ScenarioSpec],
) -> List[ScenarioResult]:
    """Run a list of scenarios in order -> their results (one process,
    one device; each campaign is still a single scan)."""
    return [run_scenario(s) for s in specs]


# ---------------------------------------------------------------------------
# exact-float flight-record encoding
# ---------------------------------------------------------------------------

def _encode_scalar(x) -> Any:
    if isinstance(x, (bool, np.bool_)):
        return bool(x)
    if isinstance(x, (int, np.integer)):
        return int(x)
    f = float(x)
    if np.isnan(f):
        return "NaN"
    if np.isinf(f):
        return "Infinity" if f > 0 else "-Infinity"
    # float.hex round-trips the exact bit pattern; repr-decimal does too in
    # CPython, but hex makes the exactness contract explicit in the file.
    return f.hex()


def _encode_array(a: np.ndarray) -> Any:
    if a.ndim == 0:
        return _encode_scalar(a[()])
    return [_encode_array(x) for x in a]


def flight_to_jsonable(record: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Flight record -> JSON-safe dict with EXACT float encoding (hex
    floats, NaN/Inf tokens) — the replay-comparison surface."""
    out = {}
    for k in sorted(record):
        arr = np.asarray(record[k])
        out[k] = {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "data": _encode_array(arr),
        }
    return out


def _decode_scalar(x, dtype: np.dtype):
    if isinstance(x, str):
        if x == "NaN":
            return dtype.type(np.nan)
        if x == "Infinity":
            return dtype.type(np.inf)
        if x == "-Infinity":
            return dtype.type(-np.inf)
        return dtype.type(float.fromhex(x))
    return dtype.type(x)


def jsonable_to_flight(doc: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Inverse of :func:`flight_to_jsonable`."""
    out = {}
    for k, ent in doc.items():
        dtype = np.dtype(ent["dtype"])

        def conv(x):
            if isinstance(x, list):
                return [conv(v) for v in x]
            return _decode_scalar(x, dtype)

        out[k] = np.asarray(conv(ent["data"]), dtype=dtype).reshape(
            ent["shape"]
        )
    return out


# ---------------------------------------------------------------------------
# traces: save + bit-for-bit replay
# ---------------------------------------------------------------------------

def trace_document(result: ScenarioResult) -> Dict[str, Any]:
    """The replayable trace: spec + seed + flight record + verdict."""
    return {
        "trace_version": TRACE_VERSION,
        "spec": result.spec.to_dict(),
        "seed": result.spec.seed,
        "n_publishes": result.compiled.n_publishes,
        "flight": flight_to_jsonable(result.record),
        "verdict": result.verdict.to_dict(),
    }


def save_trace(path: str, result: ScenarioResult) -> None:
    with open(path, "w") as f:
        json.dump(trace_document(result), f, sort_keys=True, indent=1)
        f.write("\n")


def replay_trace(
    path_or_doc: Union[str, Dict[str, Any]],
) -> Tuple[ScenarioResult, bool, List[str]]:
    """Re-run a saved trace's spec and compare flight records EXACTLY.

    Returns ``(fresh_result, matched, mismatched_channels)`` where
    ``matched`` is True iff every channel of the fresh flight record
    re-encodes to exactly the stored bytes (same dtype, shape, and bit
    pattern for every value — NaNs compare equal by token).
    """
    if isinstance(path_or_doc, str):
        with open(path_or_doc) as f:
            doc = json.load(f)
    else:
        doc = path_or_doc
    spec = ScenarioSpec.from_dict(doc["spec"])
    result = run_scenario(spec)
    fresh = flight_to_jsonable(result.record)
    stored = doc["flight"]
    mismatches = [
        k for k in sorted(set(fresh) | set(stored))
        if fresh.get(k) != stored.get(k)
    ]
    return result, not mismatches, mismatches
