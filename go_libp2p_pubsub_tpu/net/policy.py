"""Retry/backoff policy for the live plane's control paths.

The reference's failure story is one blanket repair timeout and a rejoin
that originally ``panic``ed (``client.go:14``, ``client.go:96-98``); every
dial is a single attempt.  Under the chaos layer (``net/chaos.py``) that
thinness becomes measurable: one blackholed dial strands a subtree for a
full repair timeout.  This module is the hardening: every dial-shaped
operation in ``live.py`` runs under a :class:`RetryPolicy` —

- bounded attempts with **decorrelated-jitter exponential backoff**
  (``sleep = min(cap, U(base, prev * 3))``, the AWS-architecture variant
  that avoids synchronized retry storms),
- an overall **deadline** so retries never outlive the protocol window
  they serve (e.g. rejoin retries are capped by the repair timeout),
- a per-class **circuit breaker** (closed -> open after N consecutive
  failures -> half-open probe after a cooldown) so a dead destination
  class fails fast instead of serially burning backoff budget,
- and a counter in the shared :class:`~..utils.metrics.MetricsRegistry`
  for **every** transition: ``live.retry.<cls>.{attempt,retry,success,
  exhausted,timeout}`` and ``live.breaker.<cls>.{opened,half_open,closed,
  fastfail}``.

Also home of :class:`LiveCallTimeout`, the typed error
``LiveNetwork.call`` raises instead of a bare
``concurrent.futures.TimeoutError`` so a stuck coroutine is named in the
failure, not guessed from a stack.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Awaitable, Callable, Dict, Optional, Tuple

from ..config import RetryOpts
from ..utils.metrics import MetricsRegistry
from .transport import StreamClosed

# Exceptions a retried operation may recover from: transport failures,
# unknown-peer lookups (the peer may register between attempts), and
# timeouts.  Anything else is a bug and propagates immediately.
RETRYABLE = (StreamClosed, KeyError, OSError, ConnectionError,
             asyncio.TimeoutError)


class LiveCallTimeout(TimeoutError):
    """A ``LiveNetwork.call`` that outlived its deadline, carrying the name
    of the coroutine that was in flight."""

    def __init__(self, coro_name: str, timeout_s: float):
        super().__init__(
            f"live call {coro_name!r} timed out after {timeout_s:g}s"
        )
        self.coro_name = coro_name
        self.timeout_s = timeout_s


class CircuitOpen(StreamClosed):
    """Fast-fail raised while a class's breaker is open.  Subclasses
    :class:`StreamClosed` so every existing ``except StreamClosed`` site
    degrades exactly as a failed dial would — the breaker changes *when*
    the failure surfaces, never *what* callers must handle."""

    def __init__(self, cls: str):
        super().__init__(f"circuit breaker open for class {cls!r}")
        self.cls = cls


class CircuitBreaker:
    """Per-class breaker: closed -> open after ``failures_to_open``
    consecutive failures -> half-open single probe after ``reset_s``."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        cls: str,
        failures_to_open: int,
        reset_s: float,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cls = cls
        self.failures_to_open = failures_to_open
        self.reset_s = reset_s
        self.registry = registry
        self.clock = clock
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0

    def _inc(self, event: str) -> None:
        if self.registry is not None:
            self.registry.inc(f"live.breaker.{self.cls}.{event}")

    def allow(self) -> bool:
        """May an attempt proceed right now?  Transitions open -> half-open
        when the cooldown has elapsed (the single probe)."""
        if self.state == self.OPEN:
            if self.clock() - self._opened_at >= self.reset_s:
                self.state = self.HALF_OPEN
                self._inc("half_open")
                return True
            self._inc("fastfail")
            return False
        return True

    def record_success(self) -> None:
        if self.state != self.CLOSED:
            self._inc("closed")
        self.state = self.CLOSED
        self.consecutive_failures = 0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN or (
            self.state == self.CLOSED
            and self.consecutive_failures >= self.failures_to_open
        ):
            if self.state != self.OPEN:
                self._inc("opened")
            self.state = self.OPEN
            self._opened_at = self.clock()


class RetryPolicy:
    """Deadline + decorrelated-jitter backoff + attempt budget + breakers.

    One instance is shared per :class:`~.live.LiveTopicManager` (one per
    host), so breaker state reflects that host's view of each operation
    class.  ``rng``/``clock``/``sleep`` are injectable for deterministic
    tests.
    """

    def __init__(
        self,
        opts: Optional[RetryOpts] = None,
        registry: Optional[MetricsRegistry] = None,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Optional[Callable[[float], Awaitable[None]]] = None,
    ):
        self.opts = opts or RetryOpts()
        self.registry = registry
        self.rng = rng or random.Random()
        self.clock = clock
        self.sleep = sleep or asyncio.sleep
        self._breakers: Dict[str, CircuitBreaker] = {}

    def _inc(self, name: str) -> None:
        if self.registry is not None:
            self.registry.inc(name)

    def breaker(self, cls: str) -> CircuitBreaker:
        br = self._breakers.get(cls)
        if br is None:
            br = CircuitBreaker(
                cls,
                failures_to_open=self.opts.breaker_failures,
                reset_s=self.opts.breaker_reset_s,
                registry=self.registry,
                clock=self.clock,
            )
            self._breakers[cls] = br
        return br

    def backoff_delays(self):
        """The decorrelated-jitter delay sequence (pure, for tests): yields
        the sleep before attempt 2, 3, ... up to ``max_attempts``."""
        o = self.opts
        prev = o.base_delay_s
        for _ in range(o.max_attempts - 1):
            prev = min(o.max_delay_s, self.rng.uniform(o.base_delay_s, prev * 3))
            yield prev

    async def run(
        self,
        cls: str,
        fn: Callable[[], Awaitable],
        retry_on: Tuple[type, ...] = RETRYABLE,
        max_attempts: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ):
        """Run ``await fn()`` under the policy; returns its result or
        raises the last failure (or :class:`CircuitOpen` when fast-failed).
        """
        o = self.opts
        attempts = max_attempts if max_attempts is not None else o.max_attempts
        deadline = self.clock() + (
            deadline_s if deadline_s is not None else o.deadline_s
        )
        br = self.breaker(cls)
        if not br.allow():
            raise CircuitOpen(cls)
        prev = o.base_delay_s
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            self._inc(f"live.retry.{cls}.attempt")
            try:
                result = await fn()
            except retry_on as e:
                if isinstance(e, CircuitOpen):
                    # A nested fast-fail: retrying here would just spin on
                    # the same open breaker.
                    raise
                br.record_failure()
                last = e
                if attempt >= attempts or not br.allow():
                    break
                prev = min(o.max_delay_s,
                           self.rng.uniform(o.base_delay_s, prev * 3))
                delay = min(prev, deadline - self.clock())
                if delay < 0:
                    break
                self._inc(f"live.retry.{cls}.retry")
                await self.sleep(delay)
                if self.clock() >= deadline:
                    break
            else:
                br.record_success()
                self._inc(f"live.retry.{cls}.success")
                return result
        self._inc(f"live.retry.{cls}.exhausted")
        assert last is not None
        raise last

    async def probe(
        self,
        fn: Callable[[], Awaitable],
        timeout_s: float = 0.25,
        cls: str = "probe",
    ):
        """Single-attempt, short-deadline liveness probe: no retries, no
        backoff, no breaker involvement.  A quorum check (live.py failover)
        must measure reachability *now* — burning decorrelated-jitter budget
        on each roster member would stretch time-to-heal by the whole
        electorate.  Returns ``fn()``'s result, or ``None`` on any retryable
        failure/timeout (accounted as ``live.retry.<cls>.{attempt,success,
        exhausted}``)."""
        self._inc(f"live.retry.{cls}.attempt")
        try:
            result = await asyncio.wait_for(fn(), timeout=timeout_s)
        except RETRYABLE:
            self._inc(f"live.retry.{cls}.exhausted")
            return None
        self._inc(f"live.retry.{cls}.success")
        return result

    async def wait_for(self, aw: Awaitable, timeout_s: float, cls: str):
        """``asyncio.wait_for`` with the timeout accounted to ``cls`` in
        the registry — the typed replacement for the live plane's bare
        waits."""
        try:
            return await asyncio.wait_for(aw, timeout=timeout_s)
        except asyncio.TimeoutError:
            self._inc(f"live.retry.{cls}.timeout")
            raise
