"""Live transport plane: asyncio TCP streams with protocol-id routing.

This is the DCN-side communication backend (SURVEY.md §5.8): the structural
equivalent of the vendored libp2p host the reference builds on —
``host.Host`` / ``net.Stream`` / ``h.NewStream`` / ``h.SetStreamHandler``
(``/root/reference/pubsub.go:10-13,74``, ``subtree.go:257``) — rebuilt on
asyncio TCP so host processes can interoperate over real sockets while the
device-resident sim plane (``ops/``, ``parallel/``) rides ICI.

Mapping:

- ``host.Host``              -> :class:`LiveHost` (one TCP listener per host)
- ``peer.ID``                -> string host id, resolved via :class:`Peerstore`
- ``protocol.ID`` routing    -> one-line JSON handshake ``{"proto":..,"peer":..}``
  sent by the dialer; the acceptor dispatches to the handler registered for
  that protocol id (``h.SetStreamHandler``, ``pubsub.go:74``, ``client.go:85``)
- ``net.Stream``             -> :class:`Stream`: one TCP connection per stream,
  carrying concatenated JSON wire messages (:mod:`..wire`)

The reference multiplexes streams over one connection via libp2p's muxer; a
connection-per-stream keeps the transport dependency-free, and stream counts
here are O(tree edges), not O(messages).
"""

from __future__ import annotations

import asyncio
import json
from typing import Awaitable, Callable, Dict, Optional, Tuple

from ..wire import Message, MessageDecoder, encode_message

StreamHandler = Callable[["Stream"], Awaitable[None]]

# Upper bound on buffered undecoded bytes before the stream is declared
# corrupt (the reference relies on json.Decoder erroring; a pure buffer needs
# an explicit bound).
MAX_PENDING_BYTES = 1 << 20


class StreamClosed(Exception):
    """Read/write on a closed or failed stream — the analog of the io errors
    ``processMessages`` / ``forwardMessage`` key their failure detection on
    (``client.go:105``, ``subtree.go:334``)."""


class Peerstore:
    """host id -> dial address registry (go-libp2p-peerstore analog).

    The reference tests full-mesh ``Connect`` all hosts so later redirect
    dials succeed (``pubsub_test.go:37-57``); registering addresses here is
    the same precondition.
    """

    def __init__(self, validate_ids: bool = False) -> None:
        # ``validate_ids=True`` is the reference's regime: peer ids must be
        # well-formed base58 multihashes (``translPeerIDs``,
        # ``subtree.go:228-239``) and wire-carried candidate lists are
        # filtered through ``utils.base58.transl_peer_ids`` before dialing.
        # The default keeps ids opaque strings (sim/test convenience).
        self._addrs: Dict[str, Tuple[str, int]] = {}
        self.validate_ids = validate_ids

    def add(self, peer_id: str, host: str, port: int) -> None:
        if self.validate_ids:
            from ..utils.base58 import parse_peer_id

            parse_peer_id(peer_id)  # raises ValueError on malformed ids
        self._addrs[peer_id] = (host, port)

    def addr(self, peer_id: str) -> Tuple[str, int]:
        try:
            return self._addrs[peer_id]
        except KeyError:
            # Name who IS registered (capped at 10): a failed redirect dial
            # is usually a peerstore wiring bug, and the candidate list makes
            # it diagnosable from the message alone.
            known = sorted(self._addrs)
            shown = ", ".join(repr(p) for p in known[:10])
            if len(known) > 10:
                shown += f", ... +{len(known) - 10} more"
            raise KeyError(
                f"no address registered for peer {peer_id!r}; "
                f"known peers: [{shown}]"
            ) from None

    def known(self) -> Dict[str, Tuple[str, int]]:
        return dict(self._addrs)


class Stream:
    """One bidirectional wire-message stream (``net.Stream`` analog)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        remote_peer: str,
        protoid: str,
        on_close: Optional[Callable[["Stream"], None]] = None,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._decoder = MessageDecoder()
        self.remote_peer = remote_peer  # s.Conn().RemotePeer() (subtree.go:140)
        self.protoid = protoid
        self._closed = False
        self._on_close = on_close

    def _notify_close(self) -> None:
        if self._on_close is not None:
            self._on_close(self)
            self._on_close = None

    async def write_message(self, m: Message) -> None:
        """``writeMessage`` (``pubsub.go:122-125``): one encoded JSON object."""
        if self._closed:
            raise StreamClosed("write on closed stream")
        try:
            self._writer.write(encode_message(m))
            await self._writer.drain()
        except (ConnectionError, RuntimeError, OSError) as e:
            self._closed = True
            self._notify_close()
            raise StreamClosed(str(e)) from e

    async def read_message(self) -> Message:
        """``readMessage`` (``pubsub.go:127-134``): next JSON object, however
        the bytes were chunked on the socket."""
        while True:
            m = self._decoder.next_message()
            if m is not None:
                return m
            if self._decoder.pending_bytes() > MAX_PENDING_BYTES:
                self.abort()
                raise StreamClosed("oversized/corrupt message on stream")
            if self._closed:
                raise StreamClosed("read on closed stream")
            try:
                data = await self._reader.read(65536)
            except (ConnectionError, OSError) as e:
                self._closed = True
                self._notify_close()
                raise StreamClosed(str(e)) from e
            if not data:
                self._closed = True
                self._notify_close()
                raise StreamClosed("EOF")
            try:
                self._decoder.feed(data)
            except UnicodeDecodeError as e:
                # Genuinely invalid UTF-8 on the wire (split runes are handled
                # by the decoder's incremental buffering).
                self.abort()
                raise StreamClosed(f"invalid UTF-8 on stream: {e}") from e

    def close(self) -> None:
        """Graceful close (FIN): the remote's pending reads still drain."""
        if not self._closed:
            self._closed = True
            try:
                self._writer.close()
            except Exception:
                pass
        self._notify_close()

    def abort(self) -> None:
        """Abrupt teardown (RST-ish): the abrupt-kill fault of the dropping
        tests (``pubsub_test.go:178,252``)."""
        if not self._closed:
            self._closed = True
            try:
                self._writer.transport.abort()
            except Exception:
                pass
        self._notify_close()

    @property
    def closed(self) -> bool:
        return self._closed


class LiveHost:
    """A live peer process endpoint (``host.Host`` analog).

    Owns one TCP listener; inbound connections carry a one-line JSON
    handshake naming the dialer and the protocol id, then become
    :class:`Stream` objects dispatched to the registered handler — the
    transport-level mirror of libp2p's per-protocol stream routing.
    """

    def __init__(
        self,
        peer_id: str,
        peerstore: Peerstore,
        bind: str = "127.0.0.1",
        chaos=None,
    ):
        self.id = peer_id
        self.peerstore = peerstore
        # Optional fault injector (net/chaos.ChaosTransport): None keeps
        # every stream un-wrapped — the clean path has zero chaos cost.
        self.chaos = chaos
        self._bind = bind
        self._server: Optional[asyncio.AbstractServer] = None
        self._handlers: Dict[str, StreamHandler] = {}
        self._tasks: set = set()
        self._streams: set = set()
        self.closed = False

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._accept, self._bind, 0)
        port = self._server.sockets[0].getsockname()[1]
        self.peerstore.add(self.id, self._bind, port)

    async def aclose(self, graceful: bool = True) -> None:
        """Tear the host down.

        ``graceful=False`` is the abrupt ``hosts[i].Close()`` kill: every open
        stream is aborted so remotes see hard errors, no Part flows.
        """
        self.closed = True
        if self._server is not None:
            self._server.close()
        for s in list(self._streams):
            if graceful:
                s.close()
            else:
                s.abort()
        for t in list(self._tasks):
            t.cancel()

    # -- streams -------------------------------------------------------------

    def set_stream_handler(self, protoid: str, fn: StreamHandler) -> None:
        """``h.SetStreamHandler`` (``pubsub.go:74``, ``client.go:85``)."""
        self._handlers[protoid] = fn

    def remove_stream_handler(self, protoid: str) -> None:
        """``h.RemoveStreamHandler`` (``pubsub.go:100``, ``client.go:32``)."""
        self._handlers.pop(protoid, None)

    async def new_stream(self, peer_id: str, protoid: str) -> Stream:
        """Dial a peer for a protocol (``h.NewStream``, ``subtree.go:257``)."""
        if self.closed:
            raise StreamClosed(f"host {self.id} is closed")
        if self.chaos is not None and not self.chaos.allow_dial(
            self.id, peer_id, protoid
        ):
            raise StreamClosed(f"dial {peer_id} blackholed (chaos)")
        host, port = self.peerstore.addr(peer_id)
        try:
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(
                (json.dumps({"proto": protoid, "peer": self.id}) + "\n").encode()
            )
            await writer.drain()
        except (ConnectionError, OSError) as e:
            raise StreamClosed(f"dial {peer_id} failed: {e}") from e
        s = Stream(
            reader, writer, remote_peer=peer_id, protoid=protoid,
            on_close=self._streams.discard,
        )
        self._streams.add(s)
        if self.chaos is not None:
            return self.chaos.wrap(s, self.id, spawn=self.spawn)
        return s

    def spawn(self, coro) -> asyncio.Task:
        """Track a protocol task for teardown (goroutine-spawn analog)."""
        t = asyncio.ensure_future(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return t

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if self.closed:
            writer.transport.abort()
            return
        try:
            line = await reader.readline()
            hs = json.loads(line)
            protoid, remote = hs["proto"], hs["peer"]
            if self.peerstore.validate_ids:
                # Strict-id regime: the accept boundary is where adversarial
                # ids arrive; a malformed claimed id would be admitted as a
                # child but unreachable via redirects (validating joiners
                # filter it from candidate lists) — refuse it outright.
                from ..utils.base58 import parse_peer_id

                parse_peer_id(remote)
        except Exception:
            writer.close()
            return
        handler = self._handlers.get(protoid)
        if handler is None:
            # No protocol registered (topic closed/unknown): refuse.
            writer.close()
            return
        s = Stream(
            reader, writer, remote_peer=remote, protoid=protoid,
            on_close=self._streams.discard,
        )
        self._streams.add(s)
        if self.chaos is not None:
            # Egress faults are symmetric: the acceptor's writes back to the
            # dialer run under the (acceptor, dialer, proto) link policy.
            self.spawn(handler(self.chaos.wrap(s, self.id, spawn=self.spawn)))
            return
        self.spawn(handler(s))
