"""Network planes: in-array simulated fabric and the live asyncio host plane."""
