"""Live host plane: asyncio TCP transport + the tree protocol over real
sockets, byte-compatible with the reference's JSON wire format (SURVEY.md
§2.2, §5.8).  The in-array simulated fabric lives in ``api.SimNetwork``."""

from .live import (
    LiveNetwork,
    LiveSubscription,
    LiveTopic,
    LiveTopicManager,
    SyncHost,
    SyncSubscription,
    SyncTopic,
)
from .transport import LiveHost, Peerstore, Stream, StreamClosed

__all__ = [
    "LiveHost",
    "LiveNetwork",
    "LiveSubscription",
    "LiveTopic",
    "LiveTopicManager",
    "Peerstore",
    "Stream",
    "StreamClosed",
    "SyncHost",
    "SyncSubscription",
    "SyncTopic",
]
