"""Live host plane: asyncio TCP transport + the tree protocol over real
sockets, byte-compatible with the reference's JSON wire format (SURVEY.md
§2.2, §5.8).  The in-array simulated fabric lives in ``api.SimNetwork``.
Fault injection for this plane lives in :mod:`.chaos`; the retry/backoff
policy every control path runs under lives in :mod:`.policy`."""

from .chaos import ChaosStream, ChaosTransport, LinkPolicy, LinkPolicyTable
from .live import (
    LiveNetwork,
    LiveSubscription,
    LiveTopic,
    LiveTopicManager,
    SyncHost,
    SyncSubscription,
    SyncTopic,
)
from .policy import (
    CircuitBreaker,
    CircuitOpen,
    LiveCallTimeout,
    RetryPolicy,
)
from .transport import LiveHost, Peerstore, Stream, StreamClosed

__all__ = [
    "ChaosStream",
    "ChaosTransport",
    "CircuitBreaker",
    "CircuitOpen",
    "LinkPolicy",
    "LinkPolicyTable",
    "LiveCallTimeout",
    "LiveHost",
    "LiveNetwork",
    "LiveSubscription",
    "LiveTopic",
    "LiveTopicManager",
    "Peerstore",
    "RetryPolicy",
    "Stream",
    "StreamClosed",
    "SyncHost",
    "SyncSubscription",
    "SyncTopic",
]
