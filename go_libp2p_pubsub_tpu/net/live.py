"""Live host plane: the dissemination-tree protocol over real sockets.

This is SURVEY.md §7 step 6 — the DCN-side twin of the device-resident sim
engine (``ops/tree.py``).  It speaks the byte-compatible JSON wire protocol
(:mod:`..wire`) over :mod:`.transport` streams and implements the same
protocol state machine the reference implements with goroutines:

- admit/redirect           ``handleJoin``/``redirectJoin`` (``subtree.go:106-194``)
- join walk                ``joinToPeer``/``joinParents`` (``subtree.go:196-307``)
- fan-out                  ``forwardMessage`` (``subtree.go:319-354``) — but
  concurrent via ``asyncio.gather`` (the reference's ``// TODO: in parallel``,
  ``subtree.go:325``, done)
- repair                   ``redistributeChildren`` (``subtree.go:356-375``)
- receive loop             ``processMessages`` (``client.go:100-132``) with
  pause/adopt/resume (``client.go:105-122``)

Deliberate deviations from reference bugs (SURVEY.md §2.4), mirrored from the
sim engine so both planes behave identically:

- §2.4.3  ``State.NumPeers`` carries the *real* subtree size (the reference
  never increments ``sub.size`` so always reports 0).  The wire formula
  ``parent_size = NumPeers + 1`` is preserved, so a Go peer interprets our
  States correctly.
- §2.4.4  ``State.Peers`` carries the sender's *full* direct-children list
  (the reference sends only the newest grandchild, so repair loses earlier
  ones).  A Go parent doing ``c.children = m.Peers`` gets strictly better data.
- §2.4.5  all-children-dead admits instead of nil-dereferencing.
- §2.4.6  ``Topic.close_tree`` tears the tree down; plain ``close`` keeps the
  reference's leaky behavior for parity.
- §2.4.7  admission is serialized by an asyncio lock on *every* path
  (the reference skips the lock on the Part-repair path).
- §2.4.8  repair timeout triggers an implemented rejoin-at-root instead of
  ``panic("not yet implemented")`` (``client.go:96-98``).
- §2.4.10 fanout params received in welcomes are validated
  (``TreeOpts.validated_from_wire``) instead of adopted blind.
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..config import SUB_REPAIR_TIMEOUT_S, DELIVERY_BUFFER, TreeOpts
from ..wire import Message, MessageType
from .transport import LiveHost, Peerstore, Stream, StreamClosed

MAX_JOIN_HOPS = 64  # bound on the redirect walk (reference: unbounded recursion)


@dataclass
class _Child:
    """Per-child bookkeeping (``child``, ``subtree.go:36-44``)."""

    stream: Stream
    size: int = 1              # subtree size incl. the child itself
    child_ids: List[str] = field(default_factory=list)  # its direct children
    dead: bool = False


class _TreeNode:
    """Shared subtree state machine for roots and subscribers
    (``subtree``, ``subtree.go:16-34``)."""

    def __init__(
        self,
        host: LiveHost,
        protoid: str,
        opts: TreeOpts,
        repair_timeout_s: float = SUB_REPAIR_TIMEOUT_S,
    ) -> None:
        self.host = host
        self.protoid = protoid
        self.width = opts.tree_width
        self.max_width = opts.tree_max_width
        self.repair_timeout_s = repair_timeout_s
        self.children: Dict[str, _Child] = {}
        self.chlock = asyncio.Lock()  # chlock (subtree.go:18) — held on ALL
        # admission paths, fixing the reference's unlocked Part path (§2.4.7)
        self.parent_stream: Optional[Stream] = None
        self.pause: asyncio.Queue = asyncio.Queue(maxsize=4)  # repair handoff
        self.root_id: Optional[str] = None  # for rejoin-at-root
        self.closed = False

    # -- accounting ----------------------------------------------------------

    def subtree_size(self) -> int:
        """Real size of my subtree incl. self (fixes §2.4.3)."""
        return 1 + sum(c.size for c in self.children.values() if not c.dead)

    def live_child_ids(self) -> List[str]:
        return [cid for cid, c in self.children.items() if not c.dead]

    async def notify_parent_state(self) -> None:
        """Upward accounting (``subtree.go:137-146``), with real size and the
        full children list (§2.4.3/§2.4.4).  ``num_peers`` excludes self so
        the receiver's ``size = NumPeers + 1`` lands on the true size."""
        s = self.parent_stream
        if s is None or s.closed:
            return
        try:
            await s.write_message(
                Message(
                    type=MessageType.STATE,
                    num_peers=self.subtree_size() - 1,
                    peers=self.live_child_ids(),
                )
            )
        except StreamClosed:
            pass  # parent death is handled by the read loop

    # -- admission (server side of the join walk) ----------------------------

    async def handle_join(self, s: Stream, prio: bool) -> None:
        """Admit or redirect a joiner (``handleJoin``, ``subtree.go:106-154``).

        Caller must hold ``chlock`` — enforced by the two call sites
        (stream handlers and repair), unlike the reference's Part path.
        """
        width = self.max_width if prio else self.width
        live = self.live_child_ids()
        if len(live) >= width and live:
            await self._redirect_join(s, live)
            return
        # Admit: welcome Update names me as parent + fanout params
        # (subtree.go:121-128).
        try:
            await s.write_message(
                Message(
                    type=MessageType.UPDATE,
                    peers=[self.host.id],
                    tree_width=self.width,
                    tree_max_width=self.max_width,
                )
            )
        except StreamClosed:
            return
        # Re-admission of an existing child (e.g. its rejoin raced our repair
        # dial): retire the stale record first so its reader task can't later
        # evict the fresh one.
        stale = self.children.pop(s.remote_peer, None)
        if stale is not None:
            stale.dead = True
            stale.stream.close()
        child = _Child(stream=s)
        self.children[s.remote_peer] = child
        self.host.spawn(self._handle_child_messages(s.remote_peer, child))
        await self.notify_parent_state()

    async def _redirect_join(self, s: Stream, live: List[str]) -> None:
        """Load-balancing redirect to the min-size live child
        (``redirectJoin``, ``subtree.go:156-194``)."""
        minc = min(live, key=lambda cid: self.children[cid].size)
        # The reference pre-increments the chosen child's size so consecutive
        # redirects spread (subtree.go:176-178); sizes here are corrected by
        # the next real State, so the increment is the same heuristic.
        self.children[minc].size += 1
        try:
            await s.write_message(Message(type=MessageType.UPDATE, peers=[minc]))
        except StreamClosed:
            pass
        s.close()

    async def _handle_child_messages(self, cid: str, child: _Child) -> None:
        """Per-child upward reader (``handleChildMessages``,
        ``subtree.go:46-76``): State updates accounting, Part (or stream
        death) triggers redistribution."""
        try:
            while True:
                m = await child.stream.read_message()
                if m.type == MessageType.STATE:
                    child.size = m.num_peers + 1  # wire formula (subtree.go:59)
                    child.child_ids = list(m.peers)
                    await self.notify_parent_state()
                elif m.type == MessageType.PART:
                    await self._drop_child(cid, child)
                    return
                # Data/Join/Update from a child are protocol violations; the
                # reference logs and ignores (subtree.go:71-73).
        except asyncio.CancelledError:
            raise  # host teardown: do NOT run repair on a dying node
        except StreamClosed:
            if not self.closed and not child.dead:
                # Abrupt child death seen as read error: repair now instead of
                # waiting for the next publish's write error.  Same observable
                # contract (loss windows only shrink).
                await self._drop_child(cid, child)

    async def _drop_child(self, cid: str, child: _Child) -> None:
        child.dead = True
        child.stream.close()
        # Identity check: only remove/redistribute if this record is still the
        # current one — a stale reader task must not evict a re-admitted child.
        if self.children.get(cid) is not child:
            return
        del self.children[cid]
        await self._redistribute(child.child_ids)
        await self.notify_parent_state()

    async def _redistribute(self, grandchild_ids: List[str]) -> None:
        """Re-adopt a dead child's children with priority capacity
        (``redistributeChildren``, ``subtree.go:356-375``) — all of them, not
        just the newest (§2.4.4)."""
        for gid in grandchild_ids:
            if self.closed or gid == self.host.id or gid in self.children:
                continue
            try:
                s = await self.host.new_stream(gid, self.protoid)
            except (StreamClosed, KeyError):
                continue  # grandchild also gone; its subtree rejoins via timeout
            async with self.chlock:
                # The orphan may have rejoined on its own while we dialed.
                if self.closed or gid in self.children:
                    s.close()
                    continue
                await self.handle_join(s, prio=True)

    # -- data plane ----------------------------------------------------------

    async def forward_message(self, m: Message) -> None:
        """Fan out to all live children **concurrently** (``forwardMessage``,
        ``subtree.go:319-354``, with the ``TODO: in parallel`` done).  Write
        failures mark children dead; their recorded children are re-adopted."""
        targets = [(cid, c) for cid, c in self.children.items() if not c.dead]
        if not targets:
            return

        async def send(c: _Child):
            await c.stream.write_message(m)

        results = await asyncio.gather(
            *(send(c) for _, c in targets), return_exceptions=True
        )
        dead = [tc for tc, r in zip(targets, results) if isinstance(r, Exception)]
        # Mark ALL failed children dead before redistributing any of them:
        # otherwise the first redistribution's redirect walk can pick a
        # sibling that also just failed this gather but is not yet marked,
        # stranding the grandchild on a dead parent until repair timeout.
        for _, c in dead:
            c.dead = True
        for cid, c in dead:
            # _drop_child's identity check also makes this a no-op when the
            # child's own reader task already dropped (and redistributed) it.
            await self._drop_child(cid, c)

    # -- join walk (client side) ---------------------------------------------

    async def join_to_peer(self, s: Stream) -> Stream:
        """Dial-side join (``joinToPeer``, ``subtree.go:196-226``): send Join,
        adopt validated fanout params from the welcome, walk redirects."""
        await s.write_message(Message(type=MessageType.JOIN))
        welcome = await s.read_message()
        if welcome.tree_width and welcome.tree_max_width:
            # §2.4.10: validate instead of adopting blind (subtree.go:211-213).
            opts = TreeOpts.validated_from_wire(
                welcome.tree_width, welcome.tree_max_width
            )
            self.width, self.max_width = opts.tree_width, opts.tree_max_width
        return await self._join_parents(s, welcome, hops=0)

    async def _join_parents(self, s: Stream, welcome: Message, hops: int) -> Stream:
        """Redirect walk (``joinParents``, ``subtree.go:241-307``): try each
        candidate parent; a welcome naming the sender means accepted, anything
        else is a further redirect."""
        if hops > MAX_JOIN_HOPS:
            raise StreamClosed("join walk exceeded max hops")
        last_err: Optional[Exception] = None
        for cand in welcome.peers:
            if cand == s.remote_peer:
                return s  # the sender admitted me: reuse this stream
            try:
                cs = await self.host.new_stream(cand, self.protoid)
                await cs.write_message(Message(type=MessageType.JOIN))
                w2 = await cs.read_message()
                if w2.type != MessageType.UPDATE:
                    cs.close()
                    continue
                return await self._join_parents(cs, w2, hops + 1)
            except (StreamClosed, KeyError) as e:
                last_err = e
                continue
        s.close()
        raise StreamClosed(f"could not join any candidate parent: {last_err}")

    async def drain_stale_adoptions(self) -> None:
        """Close adoption streams that lost the race with another repair (or
        with rejoin-at-root), sending Part so the would-be adopter drops its
        child record cleanly.  No State ever flowed on these streams, so the
        adopter's record has no grandchildren and its redistribute is a
        no-op — nothing gets double-adopted."""
        while True:
            try:
                s = self.pause.get_nowait()
            except asyncio.QueueEmpty:
                return
            try:
                await s.write_message(Message(type=MessageType.PART))
            except StreamClosed:
                pass
            s.close()

    # -- teardown ------------------------------------------------------------

    async def close(self) -> None:
        """Graceful leave (``subtree.Close``, ``subtree.go:78-98``): close
        child streams, Part upstream."""
        self.closed = True
        for c in self.children.values():
            c.stream.close()
        self.children.clear()
        s = self.parent_stream
        if s is not None and not s.closed:
            try:
                await s.write_message(Message(type=MessageType.PART))
            except StreamClosed:
                pass
            s.close()


class LiveTopic:
    """Root-side topic over the live plane (``Topic``, ``pubsub.go:33-120``)."""

    def __init__(self, tm: "LiveTopicManager", title: str, opts: TreeOpts):
        self.tm = tm
        self.title = title
        self.protoid = f"{tm.host.id}/{title}"  # (root, title) namespacing
        self.node = _TreeNode(tm.host, self.protoid, opts)
        tm.host.set_stream_handler(self.protoid, self._stream_handler)

    async def _stream_handler(self, s: Stream) -> None:
        """Root inbound streams must open with Join (``pubsub.go:74-92``)."""
        try:
            m = await s.read_message()
        except StreamClosed:
            return
        if m.type != MessageType.JOIN:
            s.close()  # "not a join message" (pubsub.go:81-85)
            return
        async with self.node.chlock:  # AddPeer's chlock (pubsub.go:106-108)
            await self.node.handle_join(s, prio=False)

    async def publish_message(self, data: bytes) -> None:
        """``PublishMessage`` (``pubsub.go:111-120``).  Signing remains a
        validator hook (the reference's ``TODO: add signature``); see
        ``crypto/`` for the batched ed25519 pipeline."""
        await self.node.forward_message(Message(type=MessageType.DATA, data=data))

    async def close(self) -> None:
        """Reference-parity close (``pubsub.go:99-103``): unregister only;
        the tree is leaked exactly as the reference leaks it (§2.4.6)."""
        self.tm.host.remove_stream_handler(self.protoid)
        self.tm.topics.pop(self.title, None)

    async def close_tree(self) -> None:
        """Fixed-semantics close: also tear the subtree down."""
        await self.close()
        await self.node.close()


class LiveSubscription:
    """Subscriber session over the live plane (``client``, ``client.go:18-34``)."""

    def __init__(
        self,
        tm: "LiveTopicManager",
        root_id: str,
        title: str,
        repair_timeout_s: float,
        out_buffer: int = DELIVERY_BUFFER,
    ):
        self.tm = tm
        self.protoid = f"{root_id}/{title}"
        self.node = _TreeNode(
            tm.host,
            self.protoid,
            TreeOpts(),
            repair_timeout_s=repair_timeout_s,
        )
        self.node.root_id = root_id
        # client.out, cap 16 (client.go:79): a full queue blocks the receive
        # loop — backpressure by design.
        self.out: asyncio.Queue = asyncio.Queue(maxsize=out_buffer)
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        """The Subscribe flow (``client.go:65-94``)."""
        host = self.tm.host
        s = await host.new_stream(self.node.root_id, self.protoid)
        host.set_stream_handler(self.protoid, self._stream_handler)
        self.node.parent_stream = await self.node.join_to_peer(s)
        await self.node.notify_parent_state()
        self._task = host.spawn(self._process_messages())

    async def _stream_handler(self, s: Stream) -> None:
        """Interior-node inbound control (``client.streamHandler``,
        ``client.go:36-63``): Join -> admit under me; Update -> I was adopted
        by a repairer, hand the new parent stream to the receive loop."""
        try:
            m = await s.read_message()
        except StreamClosed:
            return
        if m.type == MessageType.JOIN:
            async with self.node.chlock:
                await self.node.handle_join(s, prio=False)
        elif m.type == MessageType.UPDATE:
            try:
                ns = await self.node._join_parents(s, m, hops=0)
            except StreamClosed:
                return
            await self.node.pause.put(ns)  # sub.pause handoff (client.go:56)
        else:
            s.close()

    async def _process_messages(self) -> None:
        """Receive/relay loop (``processMessages``, ``client.go:100-132``):
        deliver before forwarding; on parent death pause for repair, and past
        the deadline rejoin at the root (the reference panics here, §2.4.8)."""
        node = self.node
        while not node.closed:
            try:
                m = await node.parent_stream.read_message()
            except StreamClosed:
                if node.closed:
                    return
                node.parent_stream = None
                try:
                    node.parent_stream = await asyncio.wait_for(
                        node.pause.get(), timeout=node.repair_timeout_s
                    )
                except asyncio.TimeoutError:
                    if not await self._rejoin_root():
                        # Unreachable root: this subscription is over, but an
                        # adoption may still race in — Part any queued streams
                        # so no repairer retains us as an unread child.
                        await node.drain_stale_adoptions()
                        return
                # A second repairer (or an adoption racing the rejoin) may
                # have queued another stream: keep the parent we have, Part
                # the losers so no node retains us as an unread child.
                await node.drain_stale_adoptions()
                await node.notify_parent_state()
                continue
            if m.type == MessageType.DATA:
                await self.out.put(m.data)        # deliver (client.go:124-127)
                await node.forward_message(m)     # then relay (client.go:130)
            elif m.type == MessageType.UPDATE:
                # Unexpected mid-stream Update: ignore (reference logs).
                continue

    async def _rejoin_root(self) -> bool:
        """``rejoinRoot`` — implemented (vs ``panic``, ``client.go:96-98``)."""
        try:
            s = await self.tm.host.new_stream(self.node.root_id, self.protoid)
            self.node.parent_stream = await self.node.join_to_peer(s)
            return True
        except (StreamClosed, KeyError):
            self.node.closed = True
            return False

    async def close(self) -> None:
        """Graceful leave (``client.Close``, ``client.go:30-34``)."""
        self.node.closed = True
        self.tm.host.remove_stream_handler(self.protoid)
        if self._task is not None:
            self._task.cancel()
        await self.node.close()


class LiveTopicManager:
    """Topic registry on one live host (``TopicManager``, ``pubsub.go:19-31``)."""

    def __init__(self, host: LiveHost, repair_timeout_s: float = SUB_REPAIR_TIMEOUT_S):
        self.host = host
        self.repair_timeout_s = repair_timeout_s
        self.topics: Dict[str, LiveTopic] = {}

    async def new_topic(self, title: str, opts: Optional[TreeOpts] = None) -> LiveTopic:
        t = LiveTopic(self, title, opts or TreeOpts())
        self.topics[title] = t
        return t

    async def subscribe(self, root_id: str, title: str) -> LiveSubscription:
        sub = LiveSubscription(self, root_id, title, self.repair_timeout_s)
        await sub.start()
        return sub


# ---------------------------------------------------------------------------
# synchronous facade (one asyncio loop on a background thread)
# ---------------------------------------------------------------------------


class LiveNetwork:
    """Sync facade over the live plane for tests/tools: one event loop on a
    daemon thread; the API mirrors the sim plane's ``SimNetwork``."""

    def __init__(self, repair_timeout_s: float = SUB_REPAIR_TIMEOUT_S):
        self.peerstore = Peerstore()
        self.repair_timeout_s = repair_timeout_s
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)
        self._thread.start()
        self._counter = 0

    def call(self, coro, timeout: float = 30.0):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(timeout)

    def host(self) -> "SyncHost":
        peer_id = f"livepeer-{self._counter}"
        self._counter += 1
        h = LiveHost(peer_id, self.peerstore)
        self.call(h.start())
        return SyncHost(self, h)

    def make_hosts(self, count: int) -> List["SyncHost"]:
        return [self.host() for _ in range(count)]

    def shutdown(self) -> None:
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


class SyncHost:
    """Sync wrapper over :class:`LiveHost` + its topic manager."""

    def __init__(self, net: LiveNetwork, host: LiveHost):
        self.net = net
        self.live = host
        self.id = host.id
        self.tm = LiveTopicManager(host, repair_timeout_s=net.repair_timeout_s)

    def new_topic(self, title: str, opts: Optional[TreeOpts] = None) -> "SyncTopic":
        return SyncTopic(self.net, self.net.call(self.tm.new_topic(title, opts)))

    def subscribe(self, root_id: str, title: str) -> "SyncSubscription":
        return SyncSubscription(
            self.net, self.net.call(self.tm.subscribe(root_id, title))
        )

    def close(self, graceful: bool = False) -> None:
        """Abrupt kill by default — ``hosts[i].Close()`` in the dropping tests."""
        self.net.call(self.live.aclose(graceful=graceful))


class SyncTopic:
    def __init__(self, net: LiveNetwork, topic: LiveTopic):
        self.net = net
        self.topic = topic

    def publish_message(self, data: bytes) -> None:
        self.net.call(self.topic.publish_message(data))

    def close(self) -> None:
        self.net.call(self.topic.close())

    def close_tree(self) -> None:
        self.net.call(self.topic.close_tree())


class SyncSubscription:
    def __init__(self, net: LiveNetwork, sub: LiveSubscription):
        self.net = net
        self.sub = sub

    def get(self, timeout: float = 5.0) -> bytes:
        """Blocking read under the tests' 5 s deadline (``pubsub_test.go:125``)."""

        async def _get():
            return await asyncio.wait_for(self.sub.out.get(), timeout)

        return self.net.call(_get(), timeout=timeout + 5)

    def try_get(self) -> Optional[bytes]:
        async def _try():
            try:
                return self.sub.out.get_nowait()
            except asyncio.QueueEmpty:
                return None

        return self.net.call(_try())

    def clear(self) -> None:
        """Drain pending deliveries (``clearWaitingMessages``,
        ``pubsub_test.go:85-99``)."""
        while self.try_get() is not None:
            pass

    def close(self) -> None:
        self.net.call(self.sub.close())
