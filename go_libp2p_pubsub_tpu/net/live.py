"""Live host plane: the dissemination-tree protocol over real sockets.

This is SURVEY.md §7 step 6 — the DCN-side twin of the device-resident sim
engine (``ops/tree.py``).  It speaks the byte-compatible JSON wire protocol
(:mod:`..wire`) over :mod:`.transport` streams and implements the same
protocol state machine the reference implements with goroutines:

- admit/redirect           ``handleJoin``/``redirectJoin`` (``subtree.go:106-194``)
- join walk                ``joinToPeer``/``joinParents`` (``subtree.go:196-307``)
- fan-out                  ``forwardMessage`` (``subtree.go:319-354``) — but
  concurrent via ``asyncio.gather`` (the reference's ``// TODO: in parallel``,
  ``subtree.go:325``, done)
- repair                   ``redistributeChildren`` (``subtree.go:356-375``)
- receive loop             ``processMessages`` (``client.go:100-132``) with
  pause/adopt/resume (``client.go:105-122``)

Deliberate deviations from reference bugs (SURVEY.md §2.4), mirrored from the
sim engine so both planes behave identically:

- §2.4.3  ``State.NumPeers`` carries the *real* subtree size (the reference
  never increments ``sub.size`` so always reports 0).  The wire formula
  ``parent_size = NumPeers + 1`` is preserved, so a Go peer interprets our
  States correctly.
- §2.4.4  ``State.Peers`` carries the sender's *full* direct-children list
  (the reference sends only the newest grandchild, so repair loses earlier
  ones).  A Go parent doing ``c.children = m.Peers`` gets strictly better data.
- §2.4.5  all-children-dead admits instead of nil-dereferencing.
- §2.4.6  ``Topic.close_tree`` tears the tree down; plain ``close`` keeps the
  reference's leaky behavior for parity.
- §2.4.7  admission is serialized by an asyncio lock on *every* path
  (the reference skips the lock on the Part-repair path).
- §2.4.8  repair timeout triggers an implemented rejoin-at-root instead of
  ``panic("not yet implemented")`` (``client.go:96-98``).
- §2.4.10 fanout params received in welcomes are validated
  (``TreeOpts.validated_from_wire``) instead of adopted blind.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import dataclasses
import hashlib
import json
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..config import SUB_REPAIR_TIMEOUT_S, DELIVERY_BUFFER, RetryOpts, TreeOpts
from ..crypto.pipeline import Envelope, ValidationPipeline, sign_envelope
from ..obs.spans import SpanLedger, live_span_key
from ..utils.log import get_logger, kv
from ..utils.metrics import MetricsRegistry
from ..wire import Message, MessageType
from .policy import LiveCallTimeout, RetryPolicy
from .transport import LiveHost, Peerstore, Stream, StreamClosed

MAX_JOIN_HOPS = 64  # bound on the redirect walk (reference: unbounded recursion)

# The host plane's structured logger (the go-log "pubsub" analog, §5.5):
# protocol events — join admission/redirect, child drops, repair adoptions,
# rejoins — log here with key=value fields; per-message publish stays at
# DEBUG so the data plane never pays formatting at INFO.
_log = get_logger("live")


class _BatchValidator:
    """Batched signature validation for one subscription's receive loop.

    The live-plane realization of :class:`ValidationPipeline`'s batch
    amortization — the component the reference left as ``// TODO: add
    signature`` (``/root/reference/pubsub.go:117``).  The receive loop
    ``submit``s each Data frame and keeps reading; a single flusher task
    verifies everything queued since the last flush in ONE pipeline call,
    run in an executor thread so the event loop (and therefore the socket
    reads that feed the next batch) never blocks on curve arithmetic.  Under
    burst load batches grow naturally; when idle a message verifies alone
    with no added latency.  Verdicts are consumed strictly in arrival order,
    preserving FIFO delivery.

    A verdict gates BOTH delivery and relay: an envelope that fails
    structural screening (not parseable, wrong topic, non-monotonic seqno)
    or signature verification is dropped and never forwarded to children —
    invalid traffic dies one hop from where it entered.
    """

    def __init__(
        self,
        sub: "LiveSubscription",
        topic: str,
        backend: str,
        max_pending: int = 512,
    ) -> None:
        self.sub = sub
        self.topic = topic
        # flush_threshold is effectively infinite: cadence is owned by the
        # flusher task, not by queue depth.
        self.pipeline = ValidationPipeline(backend=backend, flush_threshold=1 << 30)
        self.max_pending = max_pending
        self._queue: List = []  # (Message, Envelope | None) in arrival order
        self._task: Optional[asyncio.Task] = None
        self._space = asyncio.Event()
        self._space.set()
        self.rejected_structural = 0
        self.rejected_signature = 0
        self.last_seqno = -1

    async def submit(self, m: Message) -> None:
        """Queue one Data frame for verification (backpressure-bounded)."""
        await self._space.wait()
        env: Optional[Envelope] = None
        try:
            env = Envelope.from_wire(m.data)
        except Exception:
            env = None  # not an envelope at all
        if env is not None and (
            env.topic != self.topic
            or len(env.pubkey) != 32
            or len(env.signature) != 64
        ):
            env = None  # wrong-topic replay or truncated authenticator
        self._queue.append((m, env))
        if len(self._queue) >= self.max_pending:
            self._space.clear()
        if self._task is None or self._task.done():
            self._task = self.sub.tm.host.spawn(self._flush_loop())

    async def _flush_loop(self) -> None:
        loop = asyncio.get_event_loop()
        while self._queue:
            batch, self._queue = self._queue, []
            self._space.set()
            envs = [e for _, e in batch if e is not None]
            for e in envs:
                self.pipeline.submit(e)
            try:
                results = (
                    await loop.run_in_executor(None, self.pipeline.flush)
                    if envs
                    else []
                )
            except Exception:
                # Backend infrastructure failure: the pipeline re-queued its
                # envelopes internally; drop that requeue (we still hold the
                # frames) and put the batch back at the head of our queue so
                # the next flush re-pairs every frame with its own verdict —
                # nothing is lost and later batches cannot misalign against
                # leftover verdicts.
                self.pipeline.drop_pending()
                self._queue = batch + self._queue
                raise
            # Match verdicts by envelope identity, never by position: a
            # partial failure path that leaves the pipeline and this loop
            # holding different batch views must fail closed (missing
            # verdict == rejected), not shift credit across envelopes.
            verdicts = {id(e): ok for e, ok in results}
            for m, env in batch:
                if env is None:
                    self.rejected_structural += 1
                    continue
                ok = verdicts.get(id(env), False)
                # Monotonic-seqno replay guard: the tree delivers FIFO from a
                # single root, so a valid stream is strictly increasing; a
                # replayed (or cross-captured) envelope arrives late and out
                # of order and is dropped here even though its signature
                # verifies.
                if not ok:
                    self.rejected_signature += 1
                    continue
                if env.seqno <= self.last_seqno:
                    # A repair replay of an envelope this subscriber already
                    # consumed is expected traffic, not a replay attack.
                    if not m.replay:
                        self.rejected_structural += 1
                    continue
                self.last_seqno = env.seqno
                await self.sub.out.put(env.payload)
                self.sub.node.trace_stamp(m, "deliver", seqno=env.seqno)
                await self.sub.node.forward_message(m)


@dataclass
class _Child:
    """Per-child bookkeeping (``child``, ``subtree.go:36-44``)."""

    stream: Stream
    size: int = 1              # subtree size incl. the child itself
    child_ids: List[str] = field(default_factory=list)  # its direct children
    dead: bool = False
    # Forward-log index at admission: forwards with idx >= this reached the
    # child directly, earlier ones predate it (the repair-replay boundary).
    admitted_fwd_idx: int = 0


# Repair-replay window: how many recent DATA forwards a node keeps for
# re-sending to a re-adopted orphan.  Repair completes within a few dials
# (milliseconds-to-seconds); 32 messages of history covers any plausible
# number of publishes inside that window at a bounded memory cost.
FWD_LOG_CAP = 32

# Replay-dedup window at each subscriber: payload digests of the most
# recently seen Data frames.  Must be >= FWD_LOG_CAP so no replayed frame
# can outlive the memory of its original delivery.
SEEN_DATA_CAP = 256

# Failover bookkeeping bounds: how many ranked successors ride on each
# Update (the root's direct children, admission-ordered) and how large the
# piggybacked two-level roster (children + reported grandchildren) may
# grow.  Both lists are advisory state pushed down the tree, not the tree
# itself, so capping them bounds frame size without losing safety — a
# member beyond the caps still heals through the normal join walk.
SUCCESSOR_CAP = 8
ROSTER_CAP = 64

# How long a parked (degraded read-only) successor sleeps between re-probe
# rounds while it waits for its partition to heal.
PARK_RETRY_S = 0.25


class _TreeNode:
    """Shared subtree state machine for roots and subscribers
    (``subtree``, ``subtree.go:16-34``)."""

    def __init__(
        self,
        host: LiveHost,
        protoid: str,
        opts: TreeOpts,
        repair_timeout_s: float = SUB_REPAIR_TIMEOUT_S,
        metrics: Optional[MetricsRegistry] = None,
        retry: Optional[RetryPolicy] = None,
        ledger: Optional[SpanLedger] = None,
    ) -> None:
        self.host = host
        self.protoid = protoid
        self.width = opts.tree_width
        self.max_width = opts.tree_max_width
        self.repair_timeout_s = repair_timeout_s
        self.metrics = metrics  # shared registry (the /metrics counters)
        # Per-host span ledger (r19 distributed tracing, obs/merge.py).
        # None means tracing off: every stamp site below is guarded so the
        # untraced plane stays bit- and counter-identical to r18.
        self.ledger = ledger
        # Every dial-shaped operation (subscribe dial, join-walk hops,
        # adoption dials, rejoin-at-root) runs under this policy; shared per
        # topic manager so breaker state is per (host, operation class).
        self.retry = retry if retry is not None else RetryPolicy(registry=metrics)
        self.children: Dict[str, _Child] = {}
        # Repair-replay log: the last FWD_LOG_CAP DATA messages this node
        # fanned out, tagged with a monotone index.  Index assignment (in
        # ``forward_message``) and admission stamping (in ``handle_join``)
        # both happen in event-loop-synchronous sections, so "forwarded
        # before this child was admitted" is a total order — the replay in
        # ``_redistribute`` can be exact: no loss, no duplicates.
        self._fwd_log: List[Tuple[int, Message]] = []
        self._fwd_idx = 0
        self.chlock = asyncio.Lock()  # chlock (subtree.go:18) — held on ALL
        # admission paths, fixing the reference's unlocked Part path (§2.4.7)
        self.parent_stream: Optional[Stream] = None
        self.pause: asyncio.Queue = asyncio.Queue(maxsize=4)  # repair handoff
        self.root_id: Optional[str] = None  # for rejoin-at-root
        self.closed = False
        # -- failover state (epoch fencing + successor election) ------------
        # ``epoch`` 0 is the whole pre-failover regime (omitted on the wire
        # for byte parity); each successor promotion increments it and
        # every node rejects Data/Update frames fenced below its own epoch.
        self.epoch = 0
        self.is_root = False        # True on LiveTopic nodes and post-promotion
        self.degraded = False       # parked minority successor (read-only)
        # Advisory state pushed down by the root on Update frames: the
        # ranked successor list and the two-level membership roster the
        # quorum check reads.
        self.successors: List[str] = []
        self.roster: List[str] = []
        self._last_roster_bcast: Optional[tuple] = None
        # Durable topic state (utils/checkpoint.save_topic_state): written on
        # epoch/roster transitions when a path is configured.
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_meta: Dict[str, int] = {}
        self._ckpt_lock = asyncio.Lock()

    def _inc(self, name: str, value: float = 1.0) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, value)

    def trace_stamp(self, m: Message, stage: str, **attrs) -> None:
        """Hop-level span stamp for a traced Data frame.  The key is
        computed from (protoid, payload) — identical on every host the
        frame crosses, so per-host ledgers line up with no id exchange —
        and memoized on the frame: a host stamps the same Message object
        at recv, deliver, and forward, and the sha256 runs on the shared
        event-loop thread, so one hash per frame per host matters.
        A no-op unless tracing is on AND the origin marked the frame."""
        if self.ledger is None or not m.traced:
            return
        key = m.span_key
        if key is None:
            key = live_span_key(self.protoid, m.data)
            m.span_key = key
        self.ledger.stamp(
            key, stage, **attrs
        )

    async def dial_retry(self, peer_id: str, cls: str = "dial",
                         max_attempts: Optional[int] = None) -> Stream:
        """Dial under the retry policy, with the attempt accounted to
        ``cls`` (``live.retry.<cls>.*`` counters)."""
        return await self.retry.run(
            cls,
            lambda: self.host.new_stream(peer_id, self.protoid),
            max_attempts=max_attempts,
        )

    # -- accounting ----------------------------------------------------------

    def subtree_size(self) -> int:
        """Real size of my subtree incl. self (fixes §2.4.3)."""
        return 1 + sum(c.size for c in self.children.values() if not c.dead)

    def live_child_ids(self) -> List[str]:
        return [cid for cid, c in self.children.items() if not c.dead]

    # -- failover: epoch fencing + successor/roster propagation --------------

    def successor_list(self) -> List[str]:
        """Rank-ordered successor list: my live direct children in admission
        order (dict insertion order IS admission order).  Deterministic at
        every subscriber, so all survivors converge on the same #1."""
        return self.live_child_ids()[:SUCCESSOR_CAP]

    def roster_list(self) -> List[str]:
        """Two-level membership view: direct children plus their reported
        children (State frames carry the full grandchild list, §2.4.4).
        With ``tree_width=2`` the direct children alone are far too few to
        be a meaningful electorate; two levels are what the root actually
        knows without new protocol traffic."""
        roster: List[str] = []
        for cid, c in self.children.items():
            if c.dead:
                continue
            if cid not in roster:
                roster.append(cid)
            for gid in c.child_ids:
                if gid not in roster:
                    roster.append(gid)
        return roster[:ROSTER_CAP]

    def adopt_epoch(self, epoch: int, why: str) -> None:
        """Move forward to a higher epoch (higher always wins)."""
        if epoch <= self.epoch:
            return
        self._inc("live.failover.epoch_adopted")
        _log.info(
            "epoch_adopted",
            extra=kv(peer=self.host.id, epoch=epoch, prev=self.epoch, why=why),
        )
        self.epoch = epoch

    def fence_frame(self, m: Message) -> bool:
        """Epoch fence: True iff the frame may be processed.  A frame fenced
        below my epoch is a zombie — traffic from a root (or relay chain)
        that was deposed by a promotion — and is dropped so a returning
        stale root cannot fork the tree.  A higher epoch is adopted: frames
        only flow root-down, so the sender is ahead of me, not stale."""
        if self.epoch and m.epoch < self.epoch:
            self._inc("live.failover.stale_epoch_rejected")
            return False
        if m.epoch > self.epoch:
            self.adopt_epoch(m.epoch, why="frame")
        return True

    def absorb_update(self, m: Message) -> None:
        """Record successor/roster state piggybacked on an Update frame
        (welcome or mid-stream roster broadcast).  Caller fences first."""
        if m.successors:
            self.successors = list(m.successors)
        if m.roster:
            self.roster = list(m.roster)

    async def roster_changed(self) -> None:
        """Root-only: membership moved — recompute the successor list and
        roster, push them down the tree on an Update frame, and checkpoint.
        Deduplicated against the last broadcast so State-driven calls are
        cheap no-ops when nothing actually changed."""
        if not self.is_root or self.closed:
            return
        succ, roster = self.successor_list(), self.roster_list()
        snap = (self.epoch, tuple(succ), tuple(roster))
        if snap == self._last_roster_bcast:
            return
        self._last_roster_bcast = snap
        self.successors, self.roster = succ, roster
        self._inc("live.failover.roster_broadcast")
        await self.forward_message(Message(
            type=MessageType.UPDATE,
            epoch=self.epoch,
            successors=succ,
            roster=roster,
        ))
        await self.save_checkpoint()

    async def save_checkpoint(self) -> None:
        """Write durable topic state ``{epoch, seq, successors, roster,
        children}`` via the atomic temp+fsync+rename path.  File I/O runs in
        an executor so the event loop (and the socket reads behind it) never
        blocks on disk; the lock serializes writers so a slow disk cannot
        interleave two snapshots."""
        if self.checkpoint_path is None or self.closed:
            return
        from ..utils import checkpoint as _ckpt

        state = {
            "epoch": self.epoch,
            "successors": list(self.successors),
            "roster": list(self.roster),
            "children": self.live_child_ids(),
            **self.checkpoint_meta,
        }
        loop = asyncio.get_event_loop()
        async with self._ckpt_lock:
            await loop.run_in_executor(
                None, _ckpt.save_topic_state, self.checkpoint_path, state
            )
        self._inc("live.failover.checkpointed")

    def load_checkpoint(self) -> bool:
        """Restore durable topic state if a checkpoint exists; returns
        whether one was loaded.  A restarted host re-enters at its saved
        epoch, so it refuses welcomes from (and is fenced out of) any tree
        regime older than the one it last saw."""
        if self.checkpoint_path is None or not os.path.exists(self.checkpoint_path):
            return False
        from ..utils import checkpoint as _ckpt

        state = _ckpt.load_topic_state(self.checkpoint_path)
        self.epoch = int(state.get("epoch", 0))
        self.successors = list(state.get("successors", []))
        self.roster = list(state.get("roster", []))
        for k in ("seq",):
            if k in state:
                self.checkpoint_meta[k] = int(state[k])
        self._inc("live.failover.resumed")
        _log.info(
            "checkpoint_resumed",
            extra=kv(peer=self.host.id, epoch=self.epoch,
                     successors=len(self.successors)),
        )
        return True

    async def notify_parent_state(self) -> None:
        """Upward accounting (``subtree.go:137-146``), with real size and the
        full children list (§2.4.3/§2.4.4).  ``num_peers`` excludes self so
        the receiver's ``size = NumPeers + 1`` lands on the true size."""
        s = self.parent_stream
        if s is None or s.closed:
            return
        try:
            await s.write_message(
                Message(
                    type=MessageType.STATE,
                    num_peers=self.subtree_size() - 1,
                    peers=self.live_child_ids(),
                )
            )
        except StreamClosed:
            pass  # parent death is handled by the read loop

    # -- admission (server side of the join walk) ----------------------------

    async def handle_join(self, s: Stream, prio: bool,
                          want_replay: bool = False) -> None:
        """Admit or redirect a joiner (``handleJoin``, ``subtree.go:106-154``).

        Caller must hold ``chlock`` — enforced by the two call sites
        (stream handlers and repair), unlike the reference's Part path.

        ``want_replay`` is the wire ``replay`` flag carried on the Join: a
        recovering member (post-failover rejoin, partition heal) asks for
        the admitter's whole retained forward-log window right after the
        welcome; content-hash dedup at the receiver absorbs the overlap.
        """
        width = self.max_width if prio else self.width
        live = self.live_child_ids()
        if len(live) >= width and live:
            await self._redirect_join(s, live)
            return
        # Admit: welcome Update names me as parent + fanout params
        # (subtree.go:121-128), plus the failover piggyback: my epoch and
        # the successor/roster view (the root computes its own; interior
        # nodes relay what the root last broadcast).  All three serialize
        # only when nonzero/nonempty, so a pristine tree's welcome stays
        # byte-identical to the reference encoder.
        succ = self.successor_list() if self.is_root else list(self.successors)
        roster = self.roster_list() if self.is_root else list(self.roster)
        try:
            await s.write_message(
                Message(
                    type=MessageType.UPDATE,
                    peers=[self.host.id],
                    tree_width=self.width,
                    tree_max_width=self.max_width,
                    epoch=self.epoch,
                    successors=succ,
                    roster=roster,
                )
            )
        except StreamClosed:
            return
        # Re-admission of an existing child (e.g. its rejoin raced our repair
        # dial): retire the stale record first so its reader task can't later
        # evict the fresh one.
        stale = self.children.pop(s.remote_peer, None)
        if stale is not None:
            stale.dead = True
            stale.stream.close()
        child = _Child(stream=s, admitted_fwd_idx=self._fwd_idx)
        self.children[s.remote_peer] = child
        self._inc("live.join_admitted")
        _log.info(
            "join_admitted",
            extra=kv(
                parent=self.host.id, child=s.remote_peer, prio=prio,
                children=len(self.children),
            ),
        )
        self.host.spawn(self._handle_child_messages(s.remote_peer, child))
        if want_replay:
            # Recovery join: replay everything still retained.  The joiner
            # asked because it cannot know what it missed; dedup on its side
            # drops what it already has (at-least-once wire, exactly-once
            # delivery — same contract as repair replay).
            await self._replay_fwd_log(
                s.remote_peer,
                since=self._fwd_log[0][0] if self._fwd_log else self._fwd_idx,
            )
        await self.roster_changed()
        await self.notify_parent_state()

    async def _redirect_join(self, s: Stream, live: List[str]) -> None:
        """Load-balancing redirect to the min-size live child
        (``redirectJoin``, ``subtree.go:156-194``)."""
        minc = min(live, key=lambda cid: self.children[cid].size)
        # The reference pre-increments the chosen child's size so consecutive
        # redirects spread (subtree.go:176-178); sizes here are corrected by
        # the next real State, so the increment is the same heuristic.
        self.children[minc].size += 1
        self._inc("live.join_redirected")
        _log.info(
            "join_redirected",
            extra=kv(parent=self.host.id, child=s.remote_peer, to=minc),
        )
        try:
            # epoch rides along (omitted at 0) so a post-failover joiner's
            # welcome fence doesn't mistake a current-regime redirect for a
            # zombie frame.
            await s.write_message(Message(
                type=MessageType.UPDATE, peers=[minc], epoch=self.epoch,
            ))
        except StreamClosed:
            pass
        s.close()

    async def _handle_child_messages(self, cid: str, child: _Child) -> None:
        """Per-child upward reader (``handleChildMessages``,
        ``subtree.go:46-76``): State updates accounting, Part (or stream
        death) triggers redistribution."""
        try:
            while True:
                m = await child.stream.read_message()
                if m.type == MessageType.STATE:
                    child.size = m.num_peers + 1  # wire formula (subtree.go:59)
                    child.child_ids = list(m.peers)
                    # Grandchild set moved: the roster may have too (dedup'd
                    # inside roster_changed, so unchanged States are free).
                    await self.roster_changed()
                    await self.notify_parent_state()
                elif m.type == MessageType.PART:
                    await self._drop_child(cid, child)
                    return
                # Data/Join/Update from a child are protocol violations; the
                # reference logs and ignores (subtree.go:71-73).
        except asyncio.CancelledError:
            raise  # host teardown: do NOT run repair on a dying node
        except StreamClosed:
            if not self.closed and not child.dead:
                # Abrupt child death seen as read error: repair now instead of
                # waiting for the next publish's write error.  Same observable
                # contract (loss windows only shrink).
                await self._drop_child(cid, child)

    async def _drop_child(self, cid: str, child: _Child) -> None:
        child.dead = True
        child.stream.close()
        # Identity check: only remove/redistribute if this record is still the
        # current one — a stale reader task must not evict a re-admitted child.
        if self.children.get(cid) is not child:
            return
        del self.children[cid]
        self._inc("live.child_dropped")
        _log.info(
            "child_dropped",
            extra=kv(
                parent=self.host.id, child=cid,
                orphans=len(child.child_ids),
            ),
        )
        # Replay horizon: everything fanned out since the DEAD child was
        # admitted.  A write into a dying socket can "succeed" into the TCP
        # buffer and vanish, so the last confirmed-delivered message is
        # unknowable — replay the whole uncertainty window and let the
        # replay-flag dedup at the receivers drop what actually arrived.
        await self._redistribute(child.child_ids, since=child.admitted_fwd_idx)
        await self.roster_changed()
        await self.notify_parent_state()

    async def _redistribute(self, grandchild_ids: List[str],
                            requeued: bool = False,
                            since: Optional[int] = None) -> None:
        """Re-adopt a dead child's children with priority capacity
        (``redistributeChildren``, ``subtree.go:356-375``) — all of them, not
        just the newest (§2.4.4).

        Adoption dials run under the retry policy; orphans whose dials
        exhaust it are re-queued for one deferred pass before the orphan's
        own repair-timeout rejoin takes over — an unreachable orphan costs
        retries, never a silently stranded subtree.

        ``since`` is the forward-log index from which delivery through the
        dead parent is uncertain (its own admission point): everything
        logged in [since, orphan re-admission) is replayed to the fresh
        child right after the welcome, marked with the wire ``replay`` flag
        so receivers can drop what the dead parent did deliver."""
        missed: List[str] = []
        for gid in grandchild_ids:
            if self.closed or gid == self.host.id or gid in self.live_child_ids():
                continue
            try:
                s = await self.dial_retry(gid, cls="adopt")
            except (StreamClosed, KeyError):
                if requeued:
                    self._inc("live.orphan_abandoned")
                else:
                    missed.append(gid)
                continue
            async with self.chlock:
                # Re-check liveness AFTER the dial completed: the orphan may
                # have rejoined — on its own, or via a concurrent repair —
                # while we dialed/backed off, and admitting this stream too
                # would double-adopt it.  Part tells it this adoption lost.
                if self.closed or gid in self.live_child_ids():
                    try:
                        await s.write_message(Message(type=MessageType.PART))
                    except StreamClosed:
                        pass
                    s.close()
                    continue
                self._inc("live.repair_adopted")
                _log.info(
                    "repair_adopted",
                    extra=kv(parent=self.host.id, grandchild=gid),
                )
                await self.handle_join(s, prio=True)
                if since is not None:
                    await self._replay_fwd_log(gid, since)
        if missed and not self.closed:
            self._inc("live.orphans_requeued", len(missed))
            self.host.spawn(self._deferred_redistribute(missed, since))

    async def _replay_fwd_log(self, cid: str, since: int) -> None:
        """Close the repair loss window: re-send the DATA messages whose
        delivery through the dead parent is uncertain to the just-admitted
        child.  Caller holds ``chlock``; the new child's ``admitted_fwd_idx``
        bounds the replay above (anything after it reaches the child through
        the normal fan-out), and the wire ``replay`` flag lets every receiver
        drop copies it already has — at-least-once on the wire, exactly-once
        at delivery."""
        child = self.children.get(cid)
        if child is None or child.dead:
            return
        # Re-stamp replayed frames with MY epoch: logged frames may predate
        # a promotion (epoch 0/old), and receivers already at the new epoch
        # would fence them out even though the content is legitimate.
        pending = [
            dataclasses.replace(
                m, replay=True, epoch=self.epoch if self.epoch else m.epoch
            )
            for i, m in self._fwd_log
            if since <= i < child.admitted_fwd_idx
        ]
        for m in pending:
            self.trace_stamp(m, "replay_send", to=cid)
            try:
                await child.stream.write_message(m)
            except StreamClosed:
                return  # the fresh child died too: the next repair's problem
        if pending:
            self._inc("live.repair_replayed", len(pending))

    async def _deferred_redistribute(self, gids: List[str],
                                     since: Optional[int] = None) -> None:
        """Second-chance pass for orphans whose adoption dials exhausted the
        retry budget — scheduled well inside the repair window so a slow
        restart is re-adopted here instead of falling back to rejoin."""
        await asyncio.sleep(min(1.0, self.repair_timeout_s / 2))
        if not self.closed:
            await self._redistribute(gids, requeued=True, since=since)

    # -- data plane ----------------------------------------------------------

    async def forward_message(self, m: Message) -> None:
        """Fan out to all live children **concurrently** (``forwardMessage``,
        ``subtree.go:319-354``, with the ``TODO: in parallel`` done).  Write
        failures mark children dead; their recorded children are re-adopted."""
        # Log + index the fan-out in the same synchronous section that
        # snapshots the target set: the repair replay relies on "admitted
        # before/after forward i" being a total order.
        if m.type == MessageType.DATA:
            self._fwd_log.append((self._fwd_idx, m))
            self._fwd_idx += 1
            if len(self._fwd_log) > FWD_LOG_CAP:
                del self._fwd_log[0]
        targets = [(cid, c) for cid, c in self.children.items() if not c.dead]
        if not targets:
            return
        if m.type == MessageType.DATA:
            self.trace_stamp(m, "send", fanout=len(targets))

        async def send(c: _Child):
            await c.stream.write_message(m)

        results = await asyncio.gather(
            *(send(c) for _, c in targets), return_exceptions=True
        )
        dead = [tc for tc, r in zip(targets, results) if isinstance(r, Exception)]
        # Mark ALL failed children dead before redistributing any of them:
        # otherwise the first redistribution's redirect walk can pick a
        # sibling that also just failed this gather but is not yet marked,
        # stranding the grandchild on a dead parent until repair timeout.
        for _, c in dead:
            c.dead = True
        for cid, c in dead:
            self._inc("live.forward_write_failed")
            # _drop_child's identity check also makes this a no-op when the
            # child's own reader task already dropped (and redistributed) it.
            # Its repair replays the forward log (this message included) to
            # the re-adopted grandchildren, so the fan-out that exposed the
            # death costs the orphan subtree nothing.
            await self._drop_child(cid, c)

    # -- join walk (client side) ---------------------------------------------

    async def join_to_peer(self, s: Stream, want_replay: bool = False) -> Stream:
        """Dial-side join (``joinToPeer``, ``subtree.go:196-226``): send Join,
        adopt validated fanout params from the welcome, walk redirects.
        ``want_replay`` marks the Join as a recovery (failover rejoin /
        partition heal): the eventual admitter replays its retained window."""
        await s.write_message(Message(type=MessageType.JOIN, replay=want_replay))
        welcome = await s.read_message()
        if welcome.tree_width and welcome.tree_max_width:
            # §2.4.10: validate instead of adopting blind (subtree.go:211-213).
            opts = TreeOpts.validated_from_wire(
                welcome.tree_width, welcome.tree_max_width
            )
            self.width, self.max_width = opts.tree_width, opts.tree_max_width
        return await self._join_parents(s, welcome, hops=0,
                                        want_replay=want_replay)

    async def _join_parents(self, s: Stream, welcome: Message, hops: int,
                            want_replay: bool = False) -> Stream:
        """Redirect walk (``joinParents``, ``subtree.go:241-307``): try each
        candidate parent; a welcome naming the sender means accepted, anything
        else is a further redirect."""
        if hops > MAX_JOIN_HOPS:
            raise StreamClosed("join walk exceeded max hops")
        # Epoch fence on the welcome itself: a candidate parent still living
        # in a deposed epoch is a zombie subtree — attaching under it would
        # fork the tree.  Refuse the whole welcome (its candidate list is
        # the same stale regime) and let the caller try the next successor.
        if self.epoch and welcome.epoch < self.epoch:
            self._inc("live.failover.stale_epoch_rejected")
            s.close()
            raise StreamClosed(
                f"stale-epoch welcome ({welcome.epoch} < {self.epoch}) "
                f"from {s.remote_peer}"
            )
        last_err: Optional[Exception] = None
        candidates = welcome.peers
        if self.host.peerstore.validate_ids:
            # translPeerIDs boundary (subtree.go:228-239): drop malformed
            # base58 ids from the wire-carried candidate list before dialing.
            from ..utils.base58 import transl_peer_ids

            candidates = transl_peer_ids(candidates)
        for cand in candidates:
            if cand == s.remote_peer:
                # The sender admitted me: adopt its epoch and failover view,
                # reuse this stream.
                if welcome.epoch > self.epoch:
                    self.adopt_epoch(welcome.epoch, why="welcome")
                self.absorb_update(welcome)
                return s
            try:
                # Two attempts per candidate: the walk itself is the outer
                # retry (next candidate), so per-hop budget stays small.
                cs = await self.dial_retry(cand, cls="join", max_attempts=2)
                await cs.write_message(
                    Message(type=MessageType.JOIN, replay=want_replay)
                )
                w2 = await cs.read_message()
                if w2.type != MessageType.UPDATE:
                    cs.close()
                    continue
                return await self._join_parents(cs, w2, hops + 1,
                                                want_replay=want_replay)
            except (StreamClosed, KeyError) as e:
                last_err = e
                continue
        s.close()
        raise StreamClosed(f"could not join any candidate parent: {last_err}")

    async def drain_stale_adoptions(self) -> None:
        """Close adoption streams that lost the race with another repair (or
        with rejoin-at-root), sending Part so the would-be adopter drops its
        child record cleanly.  No State ever flowed on these streams, so the
        adopter's record has no grandchildren and its redistribute is a
        no-op — nothing gets double-adopted."""
        while True:
            try:
                s = self.pause.get_nowait()
            except asyncio.QueueEmpty:
                return
            try:
                await s.write_message(Message(type=MessageType.PART))
            except StreamClosed:
                pass
            s.close()

    # -- teardown ------------------------------------------------------------

    async def close(self) -> None:
        """Graceful leave (``subtree.Close``, ``subtree.go:78-98``): close
        child streams, Part upstream."""
        self.closed = True
        for c in self.children.values():
            c.stream.close()
        self.children.clear()
        s = self.parent_stream
        if s is not None and not s.closed:
            try:
                await s.write_message(Message(type=MessageType.PART))
            except StreamClosed:
                pass
            s.close()


class LiveTopic:
    """Root-side topic over the live plane (``Topic``, ``pubsub.go:33-120``)."""

    def __init__(
        self,
        tm: "LiveTopicManager",
        title: str,
        opts: TreeOpts,
        signer_seed: Optional[bytes] = None,
        checkpoint_path: Optional[str] = None,
    ):
        self.tm = tm
        self.title = title
        self.protoid = f"{tm.host.id}/{title}"  # (root, title) namespacing
        self.node = _TreeNode(
            tm.host, self.protoid, opts, metrics=tm.registry, retry=tm.retry,
            ledger=tm.ledger,
        )
        self.node.is_root = True
        # Publisher identity: with a seed, every publish travels as a signed
        # Envelope (crypto/pipeline) inside the Data frame — the fix for the
        # reference's `// TODO: add signature` (pubsub.go:117).
        self.signer_seed = signer_seed
        self._seqno = 0
        # Durable topic state: with a path, {epoch, seq, successors, roster}
        # persists across restarts (atomic temp+fsync+rename), so a
        # restarted root re-enters at the epoch it last saw instead of
        # resurrecting a stale regime.  NOTE: re-occupying the old tree also
        # requires a stable peer identity (the validate_ids regime, where
        # ids derive from keys) — with throwaway ids the checkpoint still
        # protects the epoch/seq counters.
        self.node.checkpoint_path = checkpoint_path
        if self.node.load_checkpoint():
            self._seqno = self.node.checkpoint_meta.get("seq", 0)
        tm.host.set_stream_handler(self.protoid, self._stream_handler)

    async def _stream_handler(self, s: Stream) -> None:
        """Root inbound streams must open with Join (``pubsub.go:74-92``)."""
        try:
            m = await s.read_message()
        except StreamClosed:
            return
        if m.type != MessageType.JOIN:
            s.close()  # "not a join message" (pubsub.go:81-85)
            return
        async with self.node.chlock:  # AddPeer's chlock (pubsub.go:106-108)
            await self.node.handle_join(s, prio=False, want_replay=m.replay)

    async def publish_message(self, data: bytes) -> None:
        """``PublishMessage`` (``pubsub.go:111-120``).

        With a ``signer_seed``, the payload is wrapped in a signed Envelope
        (topic- and seqno-domain-separated ed25519) so subscribers created
        with ``validate=`` batch-verify before delivering or relaying —
        filling the reference's ``// TODO: add signature`` (pubsub.go:117).
        Without a seed, raw bytes flow exactly as v0's unsigned plane does.
        """
        if self.signer_seed is not None:
            env = sign_envelope(
                self.signer_seed, self.title, self._seqno, data, backend="auto"
            )
            self._seqno += 1
            data = env.to_wire()
        else:
            self._seqno += 1  # unsigned plane: seq is the publish count
        self.node.checkpoint_meta["seq"] = self._seqno
        self.node._inc("live.msgs_published")
        _log.debug(
            "publish",
            extra=kv(topic=self.title, root=self.tm.host.id, bytes=len(data)),
        )
        # Distributed tracing (r19): the ORIGIN decides whether this message
        # is traced — the same deterministic hash-mod sampling every host's
        # ledger applies — and marks the frame so downstream hosts stamp hop
        # spans without rehashing untraced traffic.  The frame also carries
        # this host's clock-offset estimate for the cross-host merge.
        traced, clock_off = False, 0.0
        if self.tm.ledger is not None:
            key = live_span_key(self.protoid, data)
            if self.tm.ledger.sampled(key):
                traced = True
                clock_off = self.tm.trace_clock_offset
                self.tm.ledger.stamp(
                    key, "publish", bytes=len(data), epoch=self.node.epoch,
                )
        # Data carries the current epoch (omitted at 0): post-failover
        # receivers fence out anything a deposed root keeps publishing.
        m = Message(
            type=MessageType.DATA, data=data, epoch=self.node.epoch,
            traced=traced, clock_offset=clock_off,
        )
        if traced:
            m.span_key = key
        await self.node.forward_message(m)

    async def close(self) -> None:
        """Reference-parity close (``pubsub.go:99-103``): unregister only;
        the tree is leaked exactly as the reference leaks it (§2.4.6)."""
        self.tm.host.remove_stream_handler(self.protoid)
        self.tm.topics.pop(self.title, None)

    async def close_tree(self) -> None:
        """Fixed-semantics close: also tear the subtree down."""
        await self.close()
        await self.node.close()


class LiveSubscription:
    """Subscriber session over the live plane (``client``, ``client.go:18-34``)."""

    def __init__(
        self,
        tm: "LiveTopicManager",
        root_id: str,
        title: str,
        repair_timeout_s: float,
        out_buffer: int = DELIVERY_BUFFER,
        validate: Optional[str] = None,
        checkpoint_path: Optional[str] = None,
    ):
        self.tm = tm
        self.protoid = f"{root_id}/{title}"
        self.node = _TreeNode(
            tm.host,
            self.protoid,
            TreeOpts(),
            repair_timeout_s=repair_timeout_s,
            metrics=tm.registry,
            retry=tm.retry,
            ledger=tm.ledger,
        )
        self.node.root_id = root_id
        # Successors checkpoint too (they may be promoted): a restarted
        # successor re-enters at its saved epoch, so stale-regime welcomes
        # are refused from the very first join walk.
        self.node.checkpoint_path = checkpoint_path
        self.node.load_checkpoint()
        # client.out, cap 16 (client.go:79): a full queue blocks the receive
        # loop — backpressure by design.
        self.out: asyncio.Queue = asyncio.Queue(maxsize=out_buffer)
        self._task: Optional[asyncio.Task] = None
        # validate= names a crypto backend ("native"/"device"/"python"): every
        # Data frame must then be a valid signed Envelope for this topic or it
        # is neither delivered nor relayed.
        self.validator = (
            _BatchValidator(self, title, validate) if validate else None
        )
        # Replay dedup for the unsigned plane: payload digests of recently
        # seen Data frames.  A frame carrying the wire ``replay`` flag whose
        # payload is here already arrived through the dead parent before it
        # died — drop it (no deliver, no relay).  Unflagged duplicates are
        # legitimate application traffic and always flow.  (The signed plane
        # needs none of this: the monotonic-seqno guard already drops
        # re-delivered envelopes.)
        self._seen_data: set = set()
        self._seen_order: deque = deque()

    async def start(self) -> None:
        """The Subscribe flow (``client.go:65-94``)."""
        host = self.tm.host
        s = await self.node.dial_retry(self.node.root_id, cls="dial")
        host.set_stream_handler(self.protoid, self._stream_handler)
        self.node.parent_stream = await self.node.join_to_peer(s)
        await self.node.notify_parent_state()
        self._task = host.spawn(self._process_messages())

    async def _stream_handler(self, s: Stream) -> None:
        """Interior-node inbound control (``client.streamHandler``,
        ``client.go:36-63``): Join -> admit under me; Update -> I was adopted
        by a repairer, hand the new parent stream to the receive loop."""
        try:
            m = await s.read_message()
        except StreamClosed:
            return
        if m.type == MessageType.JOIN:
            async with self.node.chlock:
                await self.node.handle_join(s, prio=False, want_replay=m.replay)
        elif m.type == MessageType.UPDATE:
            ps = self.node.parent_stream
            if self.node.is_root or (ps is not None and not ps.closed):
                # Adoption aimed at a node that is not actually orphaned —
                # a partition hid the live parent from the repairer, or we
                # already promoted.  REFUSE with Part instead of queueing:
                # a parked adoption would leave the adopter a phantom child
                # it believes it repaired, and worse, let a recovering
                # ancestor later be redirect-walked into its own (dark)
                # subtree — a delivery cycle that starves the whole
                # component.  Refused, the cut-off component stays one
                # coherent subtree under its parked head and re-merges as
                # a unit when the partition lifts.
                self.node._inc("live.adoption_refused")
                try:
                    await s.write_message(Message(type=MessageType.PART))
                except StreamClosed:
                    pass
                s.close()
                return
            try:
                ns = await self.node._join_parents(s, m, hops=0)
            except StreamClosed:
                return
            await self.node.pause.put(ns)  # sub.pause handoff (client.go:56)
        else:
            s.close()

    def _remember(self, h: bytes) -> bool:
        """Record a payload digest in the dedup window; False if the digest
        was already present (the frame is a duplicate)."""
        if h in self._seen_data:
            return False
        self._seen_data.add(h)
        self._seen_order.append(h)
        if len(self._seen_order) > SEEN_DATA_CAP:
            self._seen_data.discard(self._seen_order.popleft())
        return True

    async def _process_messages(self) -> None:
        """Receive/relay loop (``processMessages``, ``client.go:100-132``):
        deliver before forwarding; on parent death pause for repair, past
        the deadline rejoin at the root (the reference panics here, §2.4.8),
        and — this build's failover extension — past THAT walk the successor
        list: converge on the highest-ranked reachable successor, promote if
        I am next in line and can reach a quorum of the roster, park
        degraded otherwise."""
        node = self.node
        while not node.closed:
            if node.parent_stream is None:
                return  # promoted to root: the server-side handlers take over
            sender = node.parent_stream.remote_peer
            try:
                m = await node.parent_stream.read_message()
            except StreamClosed:
                if node.closed:
                    return
                node.parent_stream = None
                if node.ledger is not None:
                    # Cross-host failover forensics: when this parent death
                    # turns out to be a root kill, the merge pairs the
                    # earliest parent_lost with the promotion to draw the
                    # recovery gap across the hosts that observed it.
                    node.ledger.event(
                        "parent_lost", parent=sender, epoch=node.epoch,
                    )
                try:
                    # Typed wait: a timeout lands in the registry as
                    # live.retry.repair.timeout before the rejoin fallback.
                    node.parent_stream = await node.retry.wait_for(
                        node.pause.get(), node.repair_timeout_s, cls="repair"
                    )
                except asyncio.TimeoutError:
                    if not await self._rejoin_root():
                        if not await self._failover():
                            # Root unreachable and nothing to fail over to:
                            # this subscription is over, but an adoption may
                            # still race in — Part any queued streams so no
                            # repairer retains us as an unread child.
                            node.closed = True
                            await node.drain_stale_adoptions()
                            return
                # A second repairer (or an adoption racing the rejoin) may
                # have queued another stream: keep the parent we have, Part
                # the losers so no node retains us as an unread child.
                await node.drain_stale_adoptions()
                if node.is_root:
                    return  # promoted: no parent to read from
                await node.notify_parent_state()
                continue
            if m.type == MessageType.DATA:
                # Epoch fence before anything else: zombie-regime traffic is
                # neither delivered, relayed, nor validated.
                if not node.fence_frame(m):
                    continue
                node.trace_stamp(
                    m, "recv", replay=m.replay, epoch=m.epoch,
                    origin_offset=m.clock_offset, **{"from": sender},
                )
                if self.validator is not None:
                    # Verdict-gated path: the batch validator delivers and
                    # relays (in arrival order) only what verifies (its
                    # monotonic-seqno guard is the dedup on this plane).
                    await self.validator.submit(m)
                    continue
                # Content-hash dedup on EVERY Data frame (not just flagged
                # replays): a chaos-duplicated frame, a replay overlap, or a
                # post-heal re-merge all collapse to one delivery.
                if not self._remember(hashlib.sha256(m.data).digest()):
                    node._inc("live.dup_suppressed")
                    continue
                await self.out.put(m.data)        # deliver (client.go:124-127)
                node.trace_stamp(m, "deliver")
                await node.forward_message(m)     # then relay (client.go:130)
            elif m.type == MessageType.UPDATE:
                # Mid-stream Update: the failover piggyback channel — the
                # root's successor/roster broadcast riding down the tree.
                # (The reference ignores mid-stream Updates.)
                if not node.fence_frame(m):
                    continue
                node.absorb_update(m)
                await node.forward_message(m)     # propagate to my subtree
                if node.checkpoint_path is not None:
                    await node.save_checkpoint()

    async def _rejoin_root(self, recover: bool = True) -> bool:
        """``rejoinRoot`` — implemented (vs ``panic``, ``client.go:96-98``).

        The whole dial+walk runs under the retry policy with the repair
        timeout as its deadline: a transiently unreachable root costs
        backoff, not the subscription (the reference-shaped single attempt
        gave up on the first refused dial).  ``recover`` marks the Join
        with the replay flag so the admitter closes the loss window from
        its forward log.  Failure no longer ends the subscription — the
        caller escalates to the successor failover."""
        self.node._inc("live.rejoin_root")
        _log.info(
            "rejoin_root",
            extra=kv(peer=self.tm.host.id, root=self.node.root_id),
        )

        async def _attempt() -> Stream:
            s = await self.tm.host.new_stream(self.node.root_id, self.protoid)
            return await self.node.join_to_peer(s, want_replay=recover)

        try:
            self.node.parent_stream = await self.node.retry.run(
                "rejoin", _attempt, deadline_s=self.node.repair_timeout_s
            )
            return True
        except (StreamClosed, KeyError, OSError, asyncio.TimeoutError):
            return False

    # -- root failover (the §2.4.8 rejoin's missing other half) --------------

    async def _failover(self) -> bool:
        """The root is gone past the rejoin deadline.  Walk the successor
        list the root pushed down before dying: join the highest-ranked
        reachable successor; if every higher rank is unreachable and I am
        next in line, quorum-probe the roster and promote myself; if the
        quorum is unreachable (minority side of a partition), park in
        degraded read-only and keep probing until the partition heals or
        the subscription closes.  Returns False only when there is no
        successor knowledge at all (the pre-failover contract: subscription
        over)."""
        node = self.node
        me = self.tm.host.id
        if not node.successors:
            return False
        node._inc("live.failover.engaged")
        while not node.closed:
            epoch_at_walk = node.epoch
            succs = list(node.successors)
            rank = succs.index(me) if me in succs else None
            ahead = succs if rank is None else succs[:rank]
            for cand in ahead:
                if cand == me:
                    continue
                try:
                    s = await node.dial_retry(
                        cand, cls="failover", max_attempts=2
                    )
                    node.parent_stream = await node.join_to_peer(
                        s, want_replay=True
                    )
                except (StreamClosed, KeyError, OSError, asyncio.TimeoutError):
                    continue
                if node.degraded:
                    node.degraded = False
                    node._inc("live.failover.unparked")
                    self._trace_failover_merged("rejoined_successor")
                node._inc("live.failover.rejoined_successor")
                _log.info(
                    "failover_rejoined",
                    extra=kv(peer=me, parent=cand, epoch=node.epoch),
                )
                return True
            # The walk failed — but did the world move while we walked?  A
            # promotion elsewhere surfaces here as (a) an adoption handoff
            # already queued in pause, or (b) an epoch bump absorbed from a
            # welcome mid-walk (the walk itself then died on stale-epoch
            # welcomes from peers the new roster broadcast hadn't reached
            # yet).  Either way a live regime claimed us: promoting now
            # would mint a second root inside a healthy component.  Take
            # the invitation, or re-walk under the new successor list.
            try:
                ns = node.pause.get_nowait()
            except asyncio.QueueEmpty:
                pass
            else:
                node.parent_stream = ns
                if node.degraded:
                    node.degraded = False
                    self._trace_failover_merged("adopted")
                node._inc("live.failover.adopted")
                _log.info(
                    "failover_adopted", extra=kv(peer=me, epoch=node.epoch)
                )
                return True
            if node.epoch != epoch_at_walk:
                continue
            if rank is not None:
                # I am the highest-ranked successor still standing: promote
                # only with a reachable quorum — split-brain rule: the
                # minority side must never mint an epoch.
                if await self._quorum_reachable():
                    await self._promote()
                    return True
                node._inc("live.failover.quorum_lost")
            # Park: degraded read-only.  Wake on an adoption handoff, else
            # re-probe the root and re-walk the successors next round.
            if not node.degraded:
                node.degraded = True
                node._inc("live.failover.parked")
                if node.ledger is not None:
                    # Park opens the cross-host failover window: the merge
                    # draws the gap from here to the matching merge/heal
                    # event, and every in-flight traced message on this
                    # host carries the annotation.
                    node.ledger.event(
                        "failover_parked", epoch=node.epoch,
                        rank=-1 if rank is None else rank,
                    )
                    node.ledger.annotate_open(
                        "failover_park", epoch=node.epoch,
                    )
                _log.info(
                    "failover_parked",
                    extra=kv(peer=me, epoch=node.epoch, rank=rank),
                )
            try:
                ns = await asyncio.wait_for(node.pause.get(), PARK_RETRY_S)
            except asyncio.TimeoutError:
                pass
            else:
                node.parent_stream = ns
                node.degraded = False
                node._inc("live.failover.unparked")
                self._trace_failover_merged("adopted_while_parked")
                return True
            if await self._probe_root_once():
                return True
        return False

    def _trace_failover_merged(self, how: str) -> None:
        """Close the failover window on this host's ledger: the parked
        (degraded read-only) side rejoined a live regime."""
        node = self.node
        if node.ledger is not None:
            node.ledger.event(
                "failover_merged", how=how, epoch=node.epoch,
            )
            node.ledger.annotate_open("failover_merge", epoch=node.epoch)

    async def _probe_root_once(self) -> bool:
        """One cheap rejoin attempt at the original root (park loop): the
        common heal path — the partition lifts and the root is right there."""
        node = self.node

        async def _attempt() -> Stream:
            s = await self.tm.host.new_stream(node.root_id, self.protoid)
            return await node.join_to_peer(s, want_replay=True)

        ns = await node.retry.probe(
            _attempt, timeout_s=max(2 * PARK_RETRY_S, 0.5), cls="park"
        )
        if ns is None:
            return False
        node.parent_stream = ns
        if node.degraded:
            node.degraded = False
            node._inc("live.failover.unparked")
            self._trace_failover_merged("healed")
        _log.info(
            "failover_healed",
            extra=kv(peer=self.tm.host.id, root=node.root_id, epoch=node.epoch),
        )
        return True

    async def _quorum_reachable(self) -> bool:
        """Probe the roster (minus me and the dead root): promotion needs a
        strict majority of the electorate (roster ∪ me) reachable right now.
        Single-attempt short-timeout probes — a quorum check measures the
        present, it does not retry its way into the past."""
        node = self.node
        me = self.tm.host.id
        electorate = [
            r for r in node.roster if r not in (me, node.root_id)
        ]
        total = len(electorate) + 1           # the electorate includes me
        need = total // 2 + 1                 # strict majority
        if not electorate:
            # No roster beyond myself: a 1-member electorate, quorum of one.
            return True

        async def _probe_one(rid: str) -> bool:
            async def _dial() -> Stream:
                return await self.tm.host.new_stream(rid, self.protoid)

            s = await node.retry.probe(_dial, timeout_s=0.25, cls="probe")
            if s is None:
                return False
            s.close()  # reachability only; the receiver sees EOF and moves on
            return True

        results = await asyncio.gather(*(_probe_one(r) for r in electorate))
        reachable = 1 + sum(results)
        ok = reachable >= need
        node._inc("live.failover.quorum_probe")
        _log.info(
            "quorum_probe",
            extra=kv(peer=me, reachable=reachable, total=total, ok=ok),
        )
        return ok

    async def _promote(self) -> None:
        """Successor #1 with a quorum: become the root.  Bump the epoch
        (fencing out the dead/zombie regime), re-adopt the dead root's
        other direct children with the existing repair machinery, replay
        the forward-log uncertainty window, and broadcast the new regime."""
        node = self.node
        me = self.tm.host.id
        node.epoch += 1
        node.is_root = True
        node.degraded = False
        node.parent_stream = None
        node._inc("live.failover.promoted")
        if node.ledger is not None:
            # Promotion closes the recovery window the earliest parent_lost
            # opened — the merged Chrome trace renders the pair as one
            # annotated gap, graded against the runner's heal_s.
            node.ledger.event("promoted", epoch=node.epoch)
        orphans = [x for x in node.successors if x != me]
        _log.info(
            "promoted",
            extra=kv(peer=me, epoch=node.epoch, orphans=len(orphans)),
        )
        # The dead root's OTHER direct children are the orphaned subtree
        # heads; deeper roster members still hang off live parents and must
        # not be re-dialed (double-parenting).  Replay horizon: the whole
        # retained window — what of it the dead root delivered is unknowable,
        # and receiver-side dedup absorbs the overlap.
        since = node._fwd_log[0][0] if node._fwd_log else node._fwd_idx
        await node._redistribute(orphans, since=since)
        node._last_roster_bcast = None  # force the first new-epoch broadcast
        await node.roster_changed()

    async def publish_message(self, data: bytes) -> None:
        """Publish as a PROMOTED root (epoch >= 1).  The original publisher
        is gone; the tree's data plane continues from the successor.  Only
        the unsigned plane can be resumed this way — signing would need the
        dead root's key, which is exactly what a successor must not have."""
        node = self.node
        if not node.is_root:
            raise RuntimeError(
                "publish_message requires a promoted (root) subscription"
            )
        if self.validator is not None:
            raise RuntimeError(
                "cannot publish on the signed plane from a promoted "
                "successor (the root's signing key died with it)"
            )
        self._remember(hashlib.sha256(data).digest())
        node._inc("live.msgs_published")
        traced, clock_off = False, 0.0
        if self.tm.ledger is not None:
            key = live_span_key(self.protoid, data)
            if self.tm.ledger.sampled(key):
                traced = True
                clock_off = self.tm.trace_clock_offset
                self.tm.ledger.stamp(
                    key, "publish", bytes=len(data), epoch=node.epoch,
                    promoted=True,
                )
        m = Message(
            type=MessageType.DATA, data=data, epoch=node.epoch,
            traced=traced, clock_offset=clock_off,
        )
        if traced:
            m.span_key = key
        await self.out.put(data)  # self-delivery: I am still a subscriber
        node.trace_stamp(m, "deliver")
        await node.forward_message(m)

    async def close(self) -> None:
        """Graceful leave (``client.Close``, ``client.go:30-34``)."""
        self.node.closed = True
        self.tm.host.remove_stream_handler(self.protoid)
        if self._task is not None:
            self._task.cancel()
        if self.validator is not None and self.validator._task is not None:
            self.validator._task.cancel()
        await self.node.close()


class LiveTopicManager:
    """Topic registry on one live host (``TopicManager``, ``pubsub.go:19-31``).

    ``registry`` (optional, usually shared across a whole network) collects
    the plane's protocol counters — joins, redirects, drops, repairs,
    publishes — for the ``/metrics`` endpoint.
    """

    def __init__(
        self,
        host: LiveHost,
        repair_timeout_s: float = SUB_REPAIR_TIMEOUT_S,
        registry: Optional[MetricsRegistry] = None,
        retry_opts: Optional[RetryOpts] = None,
        ledger: Optional[SpanLedger] = None,
        trace_clock_offset: float = 0.0,
    ):
        self.host = host
        self.repair_timeout_s = repair_timeout_s
        self.registry = registry
        # r19 cross-host tracing: the host's span ledger (None = tracing
        # off) and its host-clock offset estimate relative to the cluster
        # reference clock.  The offset rides traced frames so the merge can
        # normalize skewed timestamps without any clock-sync protocol.
        self.ledger = ledger
        self.trace_clock_offset = trace_clock_offset
        # One policy per host: breaker state is this host's view of each
        # operation class (dial/join/adopt/rejoin).
        self.retry = RetryPolicy(retry_opts, registry=registry)
        self.topics: Dict[str, LiveTopic] = {}
        self.subscriptions: List[LiveSubscription] = []

    async def new_topic(
        self,
        title: str,
        opts: Optional[TreeOpts] = None,
        signer_seed: Optional[bytes] = None,
        checkpoint_path: Optional[str] = None,
    ) -> LiveTopic:
        t = LiveTopic(self, title, opts or TreeOpts(), signer_seed=signer_seed,
                      checkpoint_path=checkpoint_path)
        self.topics[title] = t
        return t

    async def subscribe(
        self, root_id: str, title: str, validate: Optional[str] = None,
        checkpoint_path: Optional[str] = None,
    ) -> LiveSubscription:
        sub = LiveSubscription(
            self, root_id, title, self.repair_timeout_s, validate=validate,
            checkpoint_path=checkpoint_path,
        )
        await sub.start()
        self.subscriptions.append(sub)
        return sub


# ---------------------------------------------------------------------------
# observability endpoint: /metrics (Prometheus) + /debug/tree (JSON)
# ---------------------------------------------------------------------------


def tree_snapshot(sources: Dict[str, LiveTopicManager]) -> Dict[str, dict]:
    """JSON topology snapshot per topic manager — the servable descendant
    of the reference's private ``printTree`` debugger
    (``pubsub_test.go:204-229``): each topic's children (with subtree
    sizes) and each subscription's current parent.  Pure reads of
    loop-owned state, so the obs server's handler thread may call it
    without touching the event loop."""
    snap: Dict[str, dict] = {}
    for host_id, tm in sources.items():
        topics = {
            title: {
                "subtree_size": t.node.subtree_size(),
                "children": {
                    cid: c.size
                    for cid, c in t.node.children.items()
                    if not c.dead
                },
            }
            for title, t in tm.topics.items()
        }
        subs = {}
        for sub in tm.subscriptions:
            ps = sub.node.parent_stream
            subs[sub.protoid] = {
                "parent": (
                    ps.remote_peer if ps is not None and not ps.closed
                    else None
                ),
                "subtree_size": sub.node.subtree_size(),
                "children": {
                    cid: c.size
                    for cid, c in sub.node.children.items()
                    if not c.dead
                },
            }
        snap[host_id] = {"topics": topics, "subscriptions": subs}
    return snap


# ---------------------------------------------------------------------------
# synchronous facade (one asyncio loop on a background thread)
# ---------------------------------------------------------------------------


class LiveNetwork:
    """Sync facade over the live plane for tests/tools: one event loop on a
    daemon thread; the API mirrors the sim plane's ``SimNetwork``."""

    def __init__(
        self,
        repair_timeout_s: float = SUB_REPAIR_TIMEOUT_S,
        validate_ids: bool = False,
        chaos=None,
        retry_opts: Optional[RetryOpts] = None,
        trace_sample: Optional[int] = None,
    ):
        self.peerstore = Peerstore(validate_ids=validate_ids)
        self.repair_timeout_s = repair_timeout_s
        # Optional net.chaos.ChaosTransport shared by every host, so a
        # (src, dst, proto) link's fault stream is network-global; None
        # leaves every stream un-wrapped (the zero-overhead clean path).
        self.chaos = chaos
        self.retry_opts = retry_opts
        # r19 cross-host tracing: trace 1-in-N messages per the ledger's
        # deterministic hash-mod rule.  None = tracing off — no ledger is
        # created anywhere and the plane stays bit- and counter-identical
        # to the untraced regime.
        self.trace_sample = trace_sample
        self.registry = MetricsRegistry()
        self._sync_hosts: List["SyncHost"] = []
        self._metrics_server = None  # lazily-started obs.ObsHTTPServer
        self._loop = asyncio.new_event_loop()
        # LIVE_DEBUG=1: asyncio debug mode on the plane's loop — unawaited
        # coroutine warnings, slow-callback reports (anything over 100 ms
        # holding the loop, i.e. anything that would stall every socket on
        # the host), and full task creation tracebacks.  Costs real overhead,
        # so it is opt-in via environment, never default.
        if os.environ.get("LIVE_DEBUG") == "1":
            self._loop.set_debug(True)
            self._loop.slow_callback_duration = 0.1
            _log.info("live_debug_enabled", extra=kv(slow_callback_s=0.1))
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)
        self._thread.start()
        self._counter = 0

    def call(self, coro, timeout: float = 30.0):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        try:
            return fut.result(timeout)
        except (concurrent.futures.TimeoutError, asyncio.TimeoutError):
            if fut.done():
                # The coroutine itself raised a TimeoutError (e.g. an inner
                # wait_for): that is its result, not a stuck call.
                raise
            # The CALL outlived its deadline: cancel the orphaned coroutine
            # and name it in the failure (the satellite contract — a bare
            # concurrent.futures.TimeoutError says nothing about what hung).
            fut.cancel()
            name = getattr(coro, "__qualname__", None) or repr(coro)
            raise LiveCallTimeout(name, timeout) from None

    def serve_metrics(self, bind: str = "127.0.0.1") -> Tuple[str, int]:
        """Start the ``/metrics`` + ``/debug/tree`` endpoint; return (host, port).

        One endpoint per network: all hosts share the network registry, and
        the topology snapshot covers every host created via :meth:`host`.
        r19: delegates to :class:`~..obs.ObsHTTPServer` — one HTTP serving
        path and one exposition formatter for both planes — with the live
        topology snapshot mounted as an ``extra_json`` endpoint.
        """
        if self._metrics_server is None:
            from ..obs.server import ObsHTTPServer

            srv = ObsHTTPServer(
                self.registry,
                host=bind,
                extra_json={
                    "/debug/tree": lambda: tree_snapshot(
                        {h.id: h.tm for h in self._sync_hosts}
                    ),
                },
            )
            srv.start()
            self._metrics_server = srv
        return self._metrics_server._bind[0], self._metrics_server.port

    def host(self) -> "SyncHost":
        if self.peerstore.validate_ids:
            # Real base58 ids (identity-multihash form) derived from the
            # host counter — the regime the reference operates in.
            from ..utils.base58 import peer_id_from_ed25519_pub

            peer_id = peer_id_from_ed25519_pub(
                self._counter.to_bytes(32, "big")
            )
        else:
            peer_id = f"livepeer-{self._counter}"
        self._counter += 1
        h = LiveHost(peer_id, self.peerstore, chaos=self.chaos)
        self.call(h.start())
        return SyncHost(self, h)

    def make_hosts(self, count: int) -> List["SyncHost"]:
        return [self.host() for _ in range(count)]

    def shutdown(self) -> None:
        if self._metrics_server is not None:
            try:
                self._metrics_server.stop()
            except Exception:
                pass
            self._metrics_server = None
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


class SyncHost:
    """Sync wrapper over :class:`LiveHost` + its topic manager."""

    def __init__(self, net: LiveNetwork, host: LiveHost):
        self.net = net
        self.live = host
        self.id = host.id
        # Every host builds its OWN ledger: the hash-mod sampling rule is
        # deterministic in the message key, so all hosts agree on which
        # messages to trace with zero coordination; the merge step folds
        # the per-host ledgers back into end-to-end traces.
        self.ledger = (
            SpanLedger(sample_n=net.trace_sample)
            if net.trace_sample is not None else None
        )
        self.tm = LiveTopicManager(
            host, repair_timeout_s=net.repair_timeout_s, registry=net.registry,
            retry_opts=net.retry_opts, ledger=self.ledger,
        )
        net._sync_hosts.append(self)

    def new_topic(
        self,
        title: str,
        opts: Optional[TreeOpts] = None,
        signer_seed: Optional[bytes] = None,
        checkpoint_path: Optional[str] = None,
    ) -> "SyncTopic":
        return SyncTopic(
            self.net,
            self.net.call(self.tm.new_topic(
                title, opts, signer_seed=signer_seed,
                checkpoint_path=checkpoint_path,
            )),
        )

    def subscribe(
        self, root_id: str, title: str, validate: Optional[str] = None,
        checkpoint_path: Optional[str] = None,
    ) -> "SyncSubscription":
        return SyncSubscription(
            self.net, self.net.call(self.tm.subscribe(
                root_id, title, validate, checkpoint_path=checkpoint_path,
            ))
        )

    def close(self, graceful: bool = False) -> None:
        """Abrupt kill by default — ``hosts[i].Close()`` in the dropping tests."""
        self.net.call(self.live.aclose(graceful=graceful))


class SyncTopic:
    def __init__(self, net: LiveNetwork, topic: LiveTopic):
        self.net = net
        self.topic = topic

    def publish_message(self, data: bytes) -> None:
        self.net.call(self.topic.publish_message(data))

    def close(self) -> None:
        self.net.call(self.topic.close())

    def close_tree(self) -> None:
        self.net.call(self.topic.close_tree())


class SyncSubscription:
    def __init__(self, net: LiveNetwork, sub: LiveSubscription):
        self.net = net
        self.sub = sub

    def get(self, timeout: float = 5.0) -> bytes:
        """Blocking read under the tests' 5 s deadline (``pubsub_test.go:125``)."""

        async def _get():
            return await asyncio.wait_for(self.sub.out.get(), timeout)

        return self.net.call(_get(), timeout=timeout + 5)

    def try_get(self) -> Optional[bytes]:
        async def _try():
            try:
                return self.sub.out.get_nowait()
            except asyncio.QueueEmpty:
                return None

        return self.net.call(_try())

    def publish_message(self, data: bytes) -> None:
        """Publish from a PROMOTED subscription (post-failover root)."""
        self.net.call(self.sub.publish_message(data))

    def is_promoted(self) -> bool:
        return self.sub.node.is_root

    def clear(self) -> None:
        """Drain pending deliveries (``clearWaitingMessages``,
        ``pubsub_test.go:85-99``)."""
        while self.try_get() is not None:
            pass

    def close(self) -> None:
        self.net.call(self.sub.close())
