"""Chaos layer for the live plane: seeded, deterministic link faults.

The reference evaluates failure handling by literally killing hosts in
tests (``pubsub_test.go:178``) — there is no way to make a *link* lossy,
slow, or flaky while both endpoints stay up, which is exactly the regime
the resilience papers grade on (arXiv:2007.02754 §4 runs GossipSub attacks
over real degraded links).  The sim plane already models per-edge delay and
drop as tensors (``ops/tree.py`` link profiles); this module gives the
asyncio plane the same capability at the socket boundary.

Design:

- :class:`LinkPolicy` — one link's fault parameters: drop, fixed+jittered
  delay, duplication, reordering, bandwidth cap, mid-stream reset, dial
  blackhole.
- :class:`LinkPolicyTable` — (src, dst, proto) -> policy with ``"*"``
  wildcards (fnmatch patterns); most-specific match wins, later entries
  break ties.  Mutable at runtime: the scenario live-runner installs and
  removes window policies mid-campaign.
- :class:`ChaosTransport` — the injector.  Holds one ``random.Random`` per
  (src, dst, proto) link, seeded from ``(seed, src, dst, proto)`` via
  sha256, so the per-link fault decision stream is a pure function of the
  seed and the offered message sequence — independent of wall clock and of
  every other link.  Every non-trivial decision is appended to a per-link
  event trace, the surface the golden determinism test asserts on.
- :class:`ChaosStream` — wraps a :class:`.transport.Stream`; reads pass
  through (ingress faults are the peer's egress faults), writes consult
  the table.  Held-back messages drain through a single per-stream pump
  task ordered by (due-time, submit-seq), so FIFO is preserved unless a
  reorder fault explicitly holds a message back.

Fault *decisions* are drawn synchronously at submit time in message order;
only the *delivery* of delayed copies touches the event loop clock.  With
no policy installed for a link, writes take the inline fast path — zero
added awaits — which is what keeps the clean-path overhead unmeasurable
(PERF.md "Retry policy and chaos overhead").
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
import random
from dataclasses import dataclass
from fnmatch import fnmatchcase
from typing import Callable, Dict, List, Optional, Tuple

from ..wire import Message, encode_message
from .transport import Stream, StreamClosed

Link = Tuple[str, str, str]  # (src, dst, proto)


def _check_prob(name: str, p: float) -> None:
    if not (0.0 <= p <= 1.0):
        raise ValueError(f"{name} must be in [0, 1], got {p}")


def _check_nonneg(name: str, v: float) -> None:
    if v < 0:
        raise ValueError(f"{name} must be >= 0, got {v}")


@dataclass(frozen=True)
class LinkPolicy:
    """Fault parameters for one directed link (egress side).

    - ``drop_prob``      — silent per-message loss (the sim fabric's
      per-copy drop; no error surfaces to the writer).
    - ``delay_s`` / ``jitter_s`` — fixed + uniform-jittered hold before the
      bytes leave.
    - ``duplicate_prob`` — the message is sent twice.
    - ``reorder_prob`` / ``reorder_extra_s`` — the message is held back an
      extra beat so a later submit can overtake it.
    - ``bandwidth_bytes_per_s`` — serialization cap (0 = uncapped): each
      message occupies the link for ``len/bw`` seconds and queues behind
      earlier ones.
    - ``reset_prob`` / ``reset_after_msgs`` — mid-stream RST: the write
      aborts the underlying connection instead of sending (``reset_after``
      fires once, on the Nth submitted message; 0 = never).
    - ``blackhole``      — dials on this link fail outright (checked in
      ``LiveHost.new_stream`` before connecting).
    """

    drop_prob: float = 0.0
    delay_s: float = 0.0
    jitter_s: float = 0.0
    duplicate_prob: float = 0.0
    reorder_prob: float = 0.0
    reorder_extra_s: float = 0.002
    bandwidth_bytes_per_s: float = 0.0
    reset_prob: float = 0.0
    reset_after_msgs: int = 0
    blackhole: bool = False

    def __post_init__(self) -> None:
        for n in ("drop_prob", "duplicate_prob", "reorder_prob", "reset_prob"):
            _check_prob(n, getattr(self, n))
        for n in ("delay_s", "jitter_s", "reorder_extra_s",
                  "bandwidth_bytes_per_s"):
            _check_nonneg(n, getattr(self, n))
        if self.reset_after_msgs < 0:
            raise ValueError("reset_after_msgs must be >= 0")

    def is_noop(self) -> bool:
        return not (
            self.drop_prob or self.delay_s or self.jitter_s
            or self.duplicate_prob or self.reorder_prob
            or self.bandwidth_bytes_per_s or self.reset_prob
            or self.reset_after_msgs or self.blackhole
        )


class LinkPolicyTable:
    """(src, dst, proto) -> :class:`LinkPolicy`, with ``"*"`` wildcards.

    Patterns are ``fnmatch`` globs per field.  Resolution picks the rule
    with the most non-``"*"`` fields (specificity); among equals the most
    recently added wins, so a scenario can shadow a broad baseline with a
    targeted override and restore it by removing the override.
    """

    def __init__(self) -> None:
        self._rules: List[Tuple[str, str, str, LinkPolicy]] = []

    def set(self, policy: LinkPolicy, src: str = "*", dst: str = "*",
            proto: str = "*") -> None:
        # Copy-on-write so the event-loop thread can resolve concurrently
        # with a scenario thread editing windows.
        self._rules = self._rules + [(src, dst, proto, policy)]

    def remove(self, src: str = "*", dst: str = "*", proto: str = "*") -> int:
        """Remove rules registered with exactly this pattern triple; returns
        how many were removed."""
        keep = [r for r in self._rules if r[:3] != (src, dst, proto)]
        n = len(self._rules) - len(keep)
        self._rules = keep
        return n

    def clear(self) -> None:
        self._rules = []

    def policy_for(self, src: str, dst: str, proto: str) -> Optional[LinkPolicy]:
        best: Optional[LinkPolicy] = None
        best_spec = -1
        for rs, rd, rp, pol in self._rules:
            if (fnmatchcase(src, rs) and fnmatchcase(dst, rd)
                    and fnmatchcase(proto, rp)):
                spec = sum(f != "*" for f in (rs, rd, rp))
                if spec >= best_spec:  # later entries break ties
                    best, best_spec = pol, spec
        return best


@dataclass(frozen=True)
class ChaosDecision:
    """The per-message fault outcome ``ChaosTransport.decide`` draws."""

    drop: bool = False
    copies: int = 1
    hold_s: float = 0.0     # delay + jitter + reorder hold
    ser_s: float = 0.0      # bandwidth-cap serialization time
    reset: bool = False


class ChaosTransport:
    """Deterministic per-link fault injector.

    One instance per :class:`..live.LiveNetwork` (shared by every host, so
    a link's identity is global).  All decision draws happen in message-
    submit order from a per-link PRNG seeded by ``(seed, src, dst, proto)``
    — same seed, same offered sequence => same event trace, asserted by the
    golden test in ``tests/test_chaos.py``.
    """

    def __init__(self, seed: int = 0, table: Optional[LinkPolicyTable] = None):
        self.seed = int(seed)
        self.table = table if table is not None else LinkPolicyTable()
        self._rngs: Dict[Link, random.Random] = {}
        self._counts: Dict[Link, int] = {}
        self._traces: Dict[Link, List[tuple]] = {}

    # -- determinism core ----------------------------------------------------

    def _rng(self, link: Link) -> random.Random:
        rng = self._rngs.get(link)
        if rng is None:
            h = hashlib.sha256(
                f"{self.seed}|{link[0]}|{link[1]}|{link[2]}".encode()
            ).digest()
            rng = random.Random(int.from_bytes(h[:8], "big"))
            self._rngs[link] = rng
        return rng

    def _record(self, link: Link, event: tuple) -> None:
        self._traces.setdefault(link, []).append(event)

    def trace(self, link: Optional[Link] = None):
        """The recorded event trace — one link's list, or the whole dict."""
        if link is not None:
            return list(self._traces.get(link, []))
        return {k: list(v) for k, v in self._traces.items()}

    def reset_trace(self) -> None:
        self._traces.clear()

    def policy_for(self, src: str, dst: str, proto: str) -> Optional[LinkPolicy]:
        return self.table.policy_for(src, dst, proto)

    def allow_dial(self, src: str, dst: str, proto: str) -> bool:
        """Dial-time blackhole check (no RNG draw: blackholes are windows,
        not probabilities)."""
        pol = self.table.policy_for(src, dst, proto)
        if pol is not None and pol.blackhole:
            self._record((src, dst, proto), ("blackhole_dial",))
            return False
        return True

    def decide(self, link: Link, policy: LinkPolicy, nbytes: int) -> ChaosDecision:
        """Draw one message's fault outcome (submit order == draw order).

        Draw sequence is fixed — drop, duplicate, reorder, jitter, reset —
        and each draw happens only when its parameter is enabled, so a
        policy's trace is stable under edits to unrelated fields.
        """
        rng = self._rng(link)
        idx = self._counts.get(link, 0)
        self._counts[link] = idx + 1

        if policy.drop_prob and rng.random() < policy.drop_prob:
            self._record(link, ("drop", idx))
            return ChaosDecision(drop=True)
        copies = 1
        if policy.duplicate_prob and rng.random() < policy.duplicate_prob:
            copies = 2
            self._record(link, ("dup", idx))
        hold = policy.delay_s
        if policy.reorder_prob and rng.random() < policy.reorder_prob:
            hold += policy.reorder_extra_s
            self._record(link, ("reorder", idx))
        if policy.jitter_s:
            hold += rng.uniform(0.0, policy.jitter_s)
        if hold > 0:
            self._record(link, ("delay", idx, int(round(hold * 1e6))))
        reset = bool(policy.reset_prob and rng.random() < policy.reset_prob)
        if policy.reset_after_msgs and idx + 1 == policy.reset_after_msgs:
            reset = True
        if reset:
            self._record(link, ("reset", idx))
        ser = (
            nbytes / policy.bandwidth_bytes_per_s
            if policy.bandwidth_bytes_per_s else 0.0
        )
        return ChaosDecision(copies=copies, hold_s=hold, ser_s=ser, reset=reset)

    # -- stream wrapping -----------------------------------------------------

    def wrap(self, stream: Stream, local_id: str,
             spawn: Callable[..., "asyncio.Task"]) -> "ChaosStream":
        """Wrap an egress/ingress stream for ``local_id``'s side of the
        connection.  ``spawn`` must be the owning host's task tracker so the
        pump dies with the host."""
        return ChaosStream(stream, self, local_id, spawn)


class ChaosStream:
    """A :class:`.transport.Stream` with chaos applied to writes.

    Duck-types the Stream surface ``live.py`` uses (``write_message`` /
    ``read_message`` / ``close`` / ``abort`` / ``closed`` /
    ``remote_peer`` / ``protoid``).  Reads delegate untouched — ingress
    faults belong to the remote side's wrapper.
    """

    def __init__(self, inner: Stream, chaos: ChaosTransport, local_id: str,
                 spawn: Callable[..., "asyncio.Task"]):
        self._inner = inner
        self._chaos = chaos
        self._local = local_id
        self._spawn = spawn
        self._link: Link = (local_id, inner.remote_peer, inner.protoid)
        self._heap: List[Tuple[float, int, Message]] = []
        self._seq = 0
        self._wake: Optional[asyncio.Event] = None
        self._pump: Optional[asyncio.Task] = None
        self._link_free = 0.0
        self._failed: Optional[str] = None

    # -- Stream surface ------------------------------------------------------

    @property
    def remote_peer(self) -> str:
        return self._inner.remote_peer

    @property
    def protoid(self) -> str:
        return self._inner.protoid

    @property
    def closed(self) -> bool:
        return self._inner.closed

    async def read_message(self) -> Message:
        return await self._inner.read_message()

    def close(self) -> None:
        self._cancel_pump()
        self._inner.close()

    def abort(self) -> None:
        self._cancel_pump()
        self._inner.abort()

    # -- chaos write path ----------------------------------------------------

    async def write_message(self, m: Message) -> None:
        if self._failed is not None:
            raise StreamClosed(self._failed)
        pol = self._chaos.policy_for(self._local, self._inner.remote_peer,
                                     self._inner.protoid)
        if (pol is None or pol.is_noop()) and not self._heap:
            await self._inner.write_message(m)
            return
        if pol is None or pol.is_noop():
            # A window just closed but held messages are still queued: keep
            # FIFO by routing through the pump at zero hold.
            d = ChaosDecision()
        else:
            d = self._chaos.decide(self._link, pol, len(encode_message(m)))
        if d.reset:
            self._inner.abort()
            raise StreamClosed("stream reset (chaos)")
        if d.drop:
            return
        loop = asyncio.get_event_loop()
        due = loop.time() + d.hold_s
        if d.ser_s:
            due = max(due, self._link_free)
            self._link_free = due + d.ser_s
        for _ in range(d.copies):
            heapq.heappush(self._heap, (due, self._seq, m))
            self._seq += 1
        if self._wake is None:
            self._wake = asyncio.Event()
        self._wake.set()
        if self._pump is None or self._pump.done():
            self._pump = self._spawn(self._pump_loop())

    async def _pump_loop(self) -> None:
        loop = asyncio.get_event_loop()
        try:
            while self._heap:
                due, _, m = self._heap[0]
                now = loop.time()
                if due > now:
                    # Sleep until the head is due, but wake early if an
                    # earlier-due entry arrives.
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(self._wake.wait(),
                                               timeout=due - now)
                    except asyncio.TimeoutError:
                        pass
                    continue  # re-read the (possibly new) head
                heapq.heappop(self._heap)
                await self._inner.write_message(m)
        except StreamClosed as e:
            # Asynchronous write failure: surface on the next submit (the
            # live plane's forward path marks the child dead there).
            self._failed = str(e)
            self._heap.clear()

    def _cancel_pump(self) -> None:
        if self._pump is not None and not self._pump.done():
            self._pump.cancel()
        self._heap.clear()


# ---------------------------------------------------------------------------
# partition helpers (scenario.live_runner's blackhole windows)
# ---------------------------------------------------------------------------

# A network partition must be *detectable*, not just silent: ``blackhole``
# only refuses NEW dials, and a pure ``drop_prob=1.0`` link lets writes
# "succeed" into the void, so neither side would ever notice the cut.
# ``reset_prob=1.0`` makes the first write on an existing cross-partition
# stream abort the connection — both sides see StreamClosed and run their
# repair/failover machinery, which is the behavior a real L3 partition
# (RST or timeout) produces.
PARTITION_POLICY = LinkPolicy(blackhole=True, reset_prob=1.0)


def install_partition(table: LinkPolicyTable, side_a, side_b,
                      policy: LinkPolicy = PARTITION_POLICY) -> int:
    """Cut every directed link between two host-id cohorts; returns the
    number of rules installed (for symmetry with :func:`remove_partition`)."""
    n = 0
    for a in side_a:
        for b in side_b:
            table.set(policy, src=a, dst=b)
            table.set(policy, src=b, dst=a)
            n += 2
    return n


def remove_partition(table: LinkPolicyTable, side_a, side_b) -> int:
    """Lift a partition installed by :func:`install_partition`; returns the
    number of rules removed."""
    n = 0
    for a in side_a:
        for b in side_b:
            n += table.remove(src=a, dst=b)
            n += table.remove(src=b, dst=a)
    return n
