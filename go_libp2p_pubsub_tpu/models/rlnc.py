"""RLNC — coded gossip: random linear network coding as a first-class model.

The third propagation model beside GossipSub/TreeCast (ROADMAP item 5,
OPTIMUMP2P arxiv 2508.04833).  Where the mesh families move whole messages
(eager push + the IHAVE/IWANT round trip), every RLNC relay forwards a
fresh random GF(256) combination of whatever it already holds for a
*generation* (one published message = ``gen_size`` source fragments), and
a receiver "delivers" the moment its decode basis reaches full rank — from
ANY ``gen_size`` independent fragments, no matter which relays they came
through.  There is no two-phase recovery path at all: redundancy is
algebraic, so lossy links cost extra coded rounds instead of
IHAVE -> IWANT -> transfer round trips.

State is one structured elimination basis per (peer, generation)
(``ops.gf256.rref_insert``; u8[N, G, Kg, Kg]) plus the same topology /
liveness / message-window planes as GossipSub, so the model plugs into the
existing surfaces unchanged:

- ``rollout(record=True)`` emits the SAME flight-recorder channels
  (delivery frac, latency histogram via ``ops/histogram.py``, backlog —
  now measured in held FRAGMENTS of undecoded generations);
- ``rollout_events`` consumes ``ops.schedule.GossipEvents`` tensors, so
  the scenario compiler's churn / link-delay / workload lowering applies
  as-is and ``scenario.slo.evaluate`` grades verdicts from the record;
- ``delivery_stats`` reads the same ``first_step`` receipt table.

Semantics mapping (documented deviations from the mesh families):

- there is no mesh: every live edge relays every round, and the
  ``mesh_degree_*`` record channels report live-edge degree;
- no scoring plane: ``score_p10/50/90`` are recorded as 0.0 (the SLO
  canon never grades them for this family);
- ``gossip_delay`` d models a DEGRADED link as ingress decimation: the
  peer accepts incoming fragments only every (d+1)-th round and fragments
  sent in between are LOST.  The mesh families instead *hold* pending
  transfers (lossless, late).  Decimation is the honest lossy-link analog
  for a rateless code — exactly the regime where coding is predicted to
  win — but it means identical ``LinkWindow`` specs are a *harsher*
  fabric here than for GossipSub (PERF.md r11 honesty notes);
- ``gossip_mute`` peers hold receive-only (no coded emissions) — the
  nearest analog of the promise-breaking adversary;
- event ``silence`` suppresses a peer's emissions for the FOLLOWING round
  (the mesh families squelch the just-received fresh plane post-step);
  the compiler rejects attack waves for this family, so canon scenarios
  never exercise it.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import gf256
from ..ops import histogram as hist_ops
from ..ops.graphs import decode_index_plane, encode_index_plane, index_dtype
from .gossipsub import (
    FLIGHT_HIST_BINS,
    build_topology,
    build_topology_fast,
    compute_edge_live,
)


class RLNCState(NamedTuple):
    """Coded-gossip state: N peers, K neighbor slots, G generations in the
    message window, Kg = ``gen_size`` source fragments per generation."""

    nbrs: jax.Array        # [N, K] connection slots -> remote peer id, in
    #                        narrow index storage (see GossipState.nbrs)
    rev: jax.Array         # [N, K] remote's slot index back to me (narrow)
    nbr_valid: jax.Array   # bool[N, K]
    alive: jax.Array       # bool[N]
    subscribed: jax.Array  # bool[N] topic membership
    edge_live: jax.Array   # bool[N, K] nbr_valid & alive[nbrs] (cached)
    basis: jax.Array       # u8[N, G, Kg, Kg] structured decode basis per
    #                        (peer, generation) — pivot-slot form, rank on
    #                        the diagonal (ops.gf256.rref_insert)
    first_step: jax.Array  # i32[N, G] decode-complete (full rank) stamp;
    #                        -1 = never.  The delivery-receipt table every
    #                        recorder/stat surface reads.
    msg_valid: jax.Array   # bool[G] validation verdict per generation
    msg_birth: jax.Array   # i32[G] publish step
    msg_active: jax.Array  # bool[G] generation still being relayed
    msg_used: jax.Array    # bool[G] ever published (until slot reuse)
    gossip_mute: jax.Array   # bool[N] receive-only peers (no emissions)
    gossip_delay: jax.Array  # i32[N] degraded-ingress decimation: accept
    #                          incoming fragments only when
    #                          step % (delay + 1) == 0; 0 = ideal fabric
    silenced: jax.Array      # bool[N] emissions suppressed this round
    #                          (event plane; always False outside campaigns)
    key: jax.Array           # PRNG key (coefficient substreams)
    step: jax.Array          # i32


class RLNC:
    """Single-topic coded-gossip simulator with static shapes."""

    def __init__(
        self,
        n_peers: int = 1024,
        n_slots: int = 32,
        conn_degree: int = 16,
        msg_window: int = 64,
        gen_size: int = 8,
        builder=None,
        peer_uid: Optional[np.ndarray] = None,
        use_mxu: Optional[bool] = None,
        index_dtype_override=None,
    ):
        if gen_size < 1:
            raise ValueError("gen_size must be >= 1")
        if gen_size > 255:
            raise ValueError("gen_size must be <= 255 (GF(256) coefficients)")
        # use_mxu routes the encode combination through the carry-less
        # int8-dot decomposition (``gf256.gf_combine_mxu``) instead of the
        # table lookups — bit-exact either way, so the default follows the
        # proven-faster path per backend: the MXU form exists for the
        # systolic array, the table form wins on CPU (PERF.md r15).
        if use_mxu is None:
            use_mxu = jax.default_backend() == "tpu"
        self.use_mxu = bool(use_mxu)
        self.n = n_peers
        self.k = n_slots
        self.m = msg_window       # generations in flight (the window)
        self.gen_size = gen_size  # Kg source fragments per generation
        self.conn_degree = conn_degree
        self.builder = builder    # explicit topology builder (seed pinning)
        # r22: narrow index storage (same scheme as GossipSub) — topology is
        # static here (no PX), so the planes are encoded once at build_graph
        # and decoded in-kernel at their two read sites.
        if index_dtype_override is None:
            self.idx_dtype = index_dtype(n_peers)
            self.rev_dtype = index_dtype(n_slots)
        else:
            dt = np.dtype(index_dtype_override)
            if dt.kind == "u" and n_peers + 1 > np.iinfo(dt).max:
                raise ValueError(
                    f"index_dtype_override {dt.name} cannot hold "
                    f"n_peers + 1 = {n_peers + 1}"
                )
            self.idx_dtype = dt
            self.rev_dtype = dt
        if peer_uid is None:
            self.peer_uid = None
        else:
            pu = np.asarray(peer_uid)
            if pu.shape != (n_peers,):
                raise ValueError(f"peer_uid must be [N={n_peers}]")
            if not np.array_equal(np.sort(pu), np.arange(n_peers)):
                raise ValueError("peer_uid must be a permutation of 0..N-1")
            self.peer_uid = jnp.asarray(pu, jnp.int32)

    # Value semantics for the jit cache (the GossipSub convention): the
    # model is a pure function of its configuration.
    def _config_key(self):
        if self.builder is not None:
            return id(self)
        return (
            type(self), self.n, self.k, self.m, self.gen_size,
            self.conn_degree, self.use_mxu,
            str(self.idx_dtype), str(self.rev_dtype),
            None if self.peer_uid is None
            else bytes(np.asarray(self.peer_uid)),
        )

    def __eq__(self, other):
        return (
            type(other) is type(self)
            and self._config_key() == other._config_key()
        )

    def __hash__(self):
        return hash(self._config_key())

    def build_graph(self, seed: int = 0):
        """Connection topology -> (nbrs, rev, nbr_valid) as jnp arrays.

        Same builder dispatch (and same rng draw order) as
        ``GossipSub.build_graph``, so an RLNC model constructed with the
        same (n, k, degree, seed) runs on the IDENTICAL fixed-seed graph —
        the head-to-head bench's apples-to-apples topology guarantee.
        """
        rng = np.random.default_rng(seed)
        builder = self.builder or (
            build_topology if self.n <= 4096 else build_topology_fast
        )
        nbrs, rev, valid, _outbound = builder(
            rng, self.n, self.k, self.conn_degree
        )
        return (
            jnp.asarray(encode_index_plane(nbrs, self.n, dtype=self.idx_dtype)),
            jnp.asarray(encode_index_plane(rev, self.k, dtype=self.rev_dtype)),
            jnp.asarray(valid),
        )

    def init(
        self, seed: int = 0, subscribed: Optional[np.ndarray] = None
    ) -> RLNCState:
        """Fresh state; no warmup needed (there is no mesh to converge)."""
        nbrs, rev, valid = self.build_graph(seed)
        n, m, kg = self.n, self.m, self.gen_size
        alive0 = jnp.ones((n,), bool)
        sub0 = (
            jnp.ones((n,), bool) if subscribed is None
            else jnp.asarray(subscribed)
        )
        return RLNCState(
            nbrs=nbrs,
            rev=rev,
            nbr_valid=valid,
            alive=alive0,
            subscribed=sub0,
            edge_live=compute_edge_live(valid, nbrs, alive0),
            basis=jnp.zeros((n, m, kg, kg), jnp.uint8),
            first_step=jnp.full((n, m), -1, jnp.int32),
            msg_valid=jnp.zeros((m,), bool),
            msg_birth=jnp.zeros((m,), jnp.int32),
            msg_active=jnp.zeros((m,), bool),
            msg_used=jnp.zeros((m,), bool),
            gossip_mute=jnp.zeros((n,), bool),
            gossip_delay=jnp.zeros((n,), jnp.int32),
            silenced=jnp.zeros((n,), bool),
            key=jax.random.PRNGKey(seed),
            step=jnp.asarray(0, jnp.int32),
        )

    # -- views ---------------------------------------------------------------

    def rank(self, st: RLNCState) -> jax.Array:
        """i32[N, G] decode rank per (peer, generation)."""
        return gf256.gf_rank(st.basis)

    # -- events --------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def publish(
        self,
        st: RLNCState,
        src: jax.Array,
        slot: jax.Array,
        valid: jax.Array,
    ) -> RLNCState:
        """Seed a generation at ``src`` in window ``slot`` (recycling it).

        The publisher holds the source fragments, i.e. the identity basis
        (full rank), and stamps its own receipt at latency zero — matching
        ``GossipSub.publish``'s self-stamp.  All other peers' bases for the
        recycled slot are cleared (a stale basis would decode the OLD
        generation into a phantom receipt of the new one — the coded twin
        of ``seed_message``'s pend-plane scrub).

        A generation whose envelope FAILED validation never enters relay
        (``msg_active`` stays False, so ``can_send`` masks it) — the coded
        analog of the mesh sim's verdict-gated forwarding: you cannot
        validate a fragment, only a decoded message, so a publisher-known
        forged generation is refused at the source and the bench asserts
        zero propagation.
        """
        kg = self.gen_size
        eye = jnp.eye(kg, dtype=jnp.uint8)
        basis = (
            st.basis.at[:, slot].set(jnp.zeros((kg, kg), jnp.uint8))
            .at[src, slot].set(eye)
        )
        return st._replace(
            basis=basis,
            first_step=st.first_step.at[:, slot].set(-1)
            .at[src, slot].set(st.step),
            msg_valid=st.msg_valid.at[slot].set(valid),
            msg_birth=st.msg_birth.at[slot].set(st.step),
            msg_active=st.msg_active.at[slot].set(valid),
            msg_used=st.msg_used.at[slot].set(True),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def kill_peers(self, st: RLNCState, mask: jax.Array) -> RLNCState:
        alive = st.alive & ~mask
        return st._replace(
            alive=alive,
            edge_live=compute_edge_live(st.nbr_valid, st.nbrs, alive),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def set_gossip_delay(self, st: RLNCState, delay: jax.Array) -> RLNCState:
        """Install per-peer ingress decimation (see module docstring: a
        delay-d peer accepts fragments every (d+1)-th round, others LOST)."""
        return st._replace(gossip_delay=delay.astype(jnp.int32))

    @functools.partial(jax.jit, static_argnums=0)
    def set_gossip_mute(self, st: RLNCState, mask: jax.Array) -> RLNCState:
        """Mark peers (bool[N]) receive-only: they decode but never emit."""
        return st._replace(gossip_mute=mask)

    @functools.partial(jax.jit, static_argnums=0)
    def set_subscribed(self, st: RLNCState, sub: jax.Array) -> RLNCState:
        """Change topic membership; non-members neither emit nor accept."""
        return st._replace(subscribed=sub)

    # -- transition ----------------------------------------------------------

    def _step_core(self, st: RLNCState) -> Tuple[RLNCState, jax.Array]:
        """One coded round -> (new state, per-generation new-receipt counts).

        1. every eligible holder draws ONE random coefficient row per
           (out-slot, generation) and emits the coded combination of its
           basis rows over each live edge (``gf_combine`` — the batched
           byte-matmul encode);
        2. receivers gather their in-edge fragments (sender j's slot
           ``rev[i, s]`` fragment), mask ineligible ones to the zero
           vector, and fold them through the vectorized elimination kernel
           (``rref_insert`` vmapped over [N, G], one in-slot at a time);
        3. a basis reaching full rank stamps ``first_step`` — the delivery
           receipt the flight recorder and SLO plane consume.
        """
        n, k, g, kg = self.n, self.k, self.m, self.gen_size
        key_c, key_n = jax.random.split(st.key)

        rank = gf256.gf_rank(st.basis)                     # i32[N, G]
        # Sender eligibility per (peer, gen): holds something, is a live
        # participant, and the generation is still in relay.
        can_send = (
            (rank > 0)
            & (st.alive & st.subscribed & ~st.gossip_mute
               & ~st.silenced)[:, None]
            & (st.msg_active & st.msg_used)[None, :]
        )                                                   # bool[N, G]

        # Per-edge coded fragments: coefficient rows keyed on canonical
        # identity (placement-proof, like every mesh-plane draw), one row
        # per (sender, out-slot, generation).
        coeffs = gf256.coeffs_by_uid(
            key_c, (n, k, g, kg), self.peer_uid
        )                                                   # u8[N, K, G, Kg]
        combine = gf256.gf_combine_mxu if self.use_mxu else gf256.gf_combine
        frag_out = combine(
            coeffs, st.basis[:, None]
        )                                                   # u8[N, K, G, Kg]

        # Receiver gather: in-slot s of peer i carries sender j = nbrs[i,s]
        # and j's fragment for THIS edge sits at j's out-slot rev[i,s].
        j = jnp.clip(decode_index_plane(st.nbrs), 0, n - 1)
        flat_idx = j * k + jnp.clip(decode_index_plane(st.rev), 0, k - 1)  # i32[N, K]
        incoming = frag_out.reshape(n * k, g, kg)[flat_idx]  # u8[N, K, G, Kg]
        sender_ok = can_send[j]                              # bool[N, K, G]

        # Ingress gate: decimated peers accept only every (delay+1)-th
        # round; everyone else every round.  Fragments outside the gate are
        # zeroed — a zero vector is a no-op insert, so masking IS dropping.
        accept = (
            st.alive & st.subscribed
            & (jnp.mod(st.step, st.gossip_delay + 1) == 0)
        )                                                   # bool[N]
        ok = sender_ok & (st.edge_live & accept[:, None])[:, :, None]
        incoming = jnp.where(ok[..., None], incoming, jnp.uint8(0))

        insert = jax.vmap(jax.vmap(gf256.rref_insert))      # over [N, G]

        def fold(s, basis):
            return insert(basis, incoming[:, s])[0]

        basis = jax.lax.fori_loop(0, k, fold, st.basis)

        # Delivery receipts: bases that JUST reached full rank.
        done_new = (
            (gf256.gf_rank(basis) == kg) & (st.first_step < 0)
        )                                                   # bool[N, G]
        first_step = jnp.where(done_new, st.step, st.first_step)
        per_msg = done_new.sum(axis=0, dtype=jnp.int32)     # i32[G]
        return (
            st._replace(
                basis=basis, first_step=first_step, key=key_n,
                step=st.step + 1,
            ),
            per_msg,
        )

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: RLNCState) -> RLNCState:
        return self._step_core(st)[0]

    @functools.partial(jax.jit, static_argnums=0)
    def step_recorded(self, st: RLNCState):
        """(state, per-generation new-receipt counts i32[G]) — the latency
        histogram's per-round increment source, like GossipSub's."""
        return self._step_core(st)

    def run(self, st: RLNCState, n_steps: int) -> RLNCState:
        return self.rollout(st, n_steps, record=False)[0]

    @functools.partial(jax.jit, static_argnames=("self", "n_steps", "record"))
    def rollout(self, st: RLNCState, n_steps: int, record: bool = True):
        """``n_steps`` coded rounds -> (final state, flight record | None).

        Identical recorder architecture to ``GossipSub.rollout``: the
        cumulative latency histogram rides the scan carry, seeded from the
        stamp table (``latency_histogram_seed``'s scalar fast path covers
        the fresh-publish case) and advanced per round from the receipts
        stamped that round.  ``first_step``/``msg_birth`` have the same
        [N, G]/[G] shape contract the mesh families use, so the histogram
        ops apply unchanged.
        """
        if not record:
            def bare(s, _):
                return self.step(s), None

            return jax.lax.scan(bare, st, None, length=n_steps)

        hist0 = hist_ops.latency_histogram_seed(
            st.first_step, st.msg_birth, st.msg_used & st.msg_valid,
            st.alive & st.subscribed, FLIGHT_HIST_BINS,
        )

        def body(carry, _):
            s, hist = carry
            s2, per_msg = self._step_core(s)
            hist = hist + hist_ops.latency_histogram_increment(
                per_msg, s2.msg_birth, s2.msg_used & s2.msg_valid,
                s.step, FLIGHT_HIST_BINS,
            )
            return (s2, hist), self.flight_record_round(s2, hist)

        (final, _), record_ys = jax.lax.scan(
            body, (st, hist0), None, length=n_steps
        )
        return final, record_ys

    # -- scenario engine -----------------------------------------------------

    def _apply_events(self, st: RLNCState, ev) -> RLNCState:
        """Apply one step's ``GossipEvents`` slice (same application order
        as ``GossipSub._apply_events``; every branch ``lax.cond``-gated).

        ``silence`` is folded here as next-round emission suppression (set
        before the step, cleared by the next event row) — see the module
        docstring for the timing deviation vs the mesh families.
        """

        def upd_alive(s):
            alive = (s.alive & ~ev.kill) | ev.revive
            return s._replace(
                alive=alive,
                edge_live=compute_edge_live(s.nbr_valid, s.nbrs, alive),
            )

        st = jax.lax.cond(
            ev.kill.any() | ev.revive.any(), upd_alive, lambda s: s, st
        )
        st = jax.lax.cond(
            ev.sub_off.any() | ev.sub_on.any(),
            lambda s: s._replace(
                subscribed=(s.subscribed & ~ev.sub_off) | ev.sub_on
            ),
            lambda s: s,
            st,
        )
        st = jax.lax.cond(
            ev.mute_on.any() | ev.mute_off.any(),
            lambda s: s._replace(
                gossip_mute=(s.gossip_mute & ~ev.mute_off) | ev.mute_on
            ),
            lambda s: s,
            st,
        )
        st = jax.lax.cond(
            (ev.delay >= 0).any(),
            lambda s: s._replace(
                gossip_delay=jnp.where(
                    ev.delay >= 0, ev.delay, s.gossip_delay
                )
            ),
            lambda s: s,
            st,
        )
        st = st._replace(silenced=ev.silence)
        for i in range(ev.pub_src.shape[0]):
            st = jax.lax.cond(
                ev.pub_src[i] >= 0,
                lambda s, j=i: self.publish(
                    s,
                    ev.pub_src[j],
                    jnp.clip(ev.pub_slot[j], 0, self.m - 1),
                    ev.pub_valid[j],
                ),
                lambda s: s,
                st,
            )
        return st

    @functools.partial(jax.jit, static_argnames=("self", "record"))
    def rollout_events(self, st: RLNCState, events, record: bool = True):
        """Run a whole ``GossipEvents`` schedule in ONE ``lax.scan`` ->
        (final state, flight record | None) — the scenario runner's
        entry point, signature-compatible with the non-gossipsub dispatch
        in ``scenario.runner._run_compiled``.

        Publisher self-receipts of in-scan publishes fold into the
        histogram at bin 0 exactly as in ``GossipSub.rollout_events``, so
        ``delivery_frac`` stays exact for slot-unique campaigns.
        """
        n_steps = int(events.kill.shape[0])

        if not record:
            def bare(s, ev):
                s = self._apply_events(s, ev)
                return self.step(s), None

            return jax.lax.scan(bare, st, events, length=n_steps)

        hist0 = hist_ops.latency_histogram_seed(
            st.first_step, st.msg_birth, st.msg_used & st.msg_valid,
            st.alive & st.subscribed, FLIGHT_HIST_BINS,
        )

        def body(carry, ev):
            s, hist = carry
            s = self._apply_events(s, ev)
            src_c = jnp.clip(ev.pub_src, 0, self.n - 1)
            pub_counted = (
                (ev.pub_src >= 0)
                & ev.pub_valid
                & s.alive[src_c]
                & s.subscribed[src_c]
            ).sum(dtype=jnp.int32)
            hist = hist.at[0].add(pub_counted)
            s2, per_msg = self._step_core(s)
            hist = hist + hist_ops.latency_histogram_increment(
                per_msg, s2.msg_birth, s2.msg_used & s2.msg_valid,
                s.step, FLIGHT_HIST_BINS,
            )
            return (s2, hist), self.flight_record_round(s2, hist)

        (final, _), record_ys = jax.lax.scan(
            body, (st, hist0), events, length=n_steps
        )
        return final, record_ys

    # -- flight recorder -----------------------------------------------------

    def flight_record_round(self, st: RLNCState, lat_hist: jax.Array):
        """One round's telemetry — the SAME channel names/dtypes as
        ``GossipSub.flight_record_round`` so ``scenario.slo.evaluate``,
        ``utils.metrics.flight_summary`` and the trace replay surface work
        unchanged.  ``mesh_degree_*`` report live-edge degree (there is no
        mesh); ``score_p*`` are 0.0 (no scoring plane); ``gossip_pending``
        is the decode BACKLOG in fragments: basis rows held for
        generations that have not yet reached full rank.
        """
        part = st.alive & st.subscribed
        part_n = jnp.maximum(part.sum(), 1)
        in_window = st.msg_used & st.msg_valid
        n_msgs = jnp.maximum(in_window.sum(), 1)
        deg = st.edge_live.sum(axis=1)
        deg_alive = jnp.where(part, deg, 0)
        rank = gf256.gf_rank(st.basis)                      # i32[N, G]
        backlog = jnp.where(
            (rank < self.gen_size) & st.msg_active[None, :], rank, 0
        ).sum()
        zero = jnp.asarray(0.0, jnp.float32)
        return {
            "step": st.step,
            "peers_alive": st.alive.sum(),
            "delivery_frac": lat_hist.sum() / (part_n * n_msgs),
            "mesh_degree_mean": deg_alive.sum() / part_n,
            "mesh_degree_max": deg.max(),
            "score_p10": zero,
            "score_p50": zero,
            "score_p90": zero,
            "gossip_pending": backlog,
            "lat_hist": lat_hist,
        }

    # -- metrics -------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def delivery_stats(self, st: RLNCState):
        """Per-generation delivery fraction and decode-latency percentiles
        (rounds) — same receipt-table arithmetic as GossipSub's."""
        part = st.alive & st.subscribed
        part_n = part.sum()
        delivered = ((st.first_step >= 0) & part[:, None]).sum(axis=0)
        frac = jnp.where(
            st.msg_used & st.msg_valid,
            delivered / jnp.maximum(part_n, 1),
            jnp.nan,
        )
        lat = jnp.where(
            st.first_step >= 0, st.first_step - st.msg_birth[None, :], -1
        )
        valid_lat = (
            (lat >= 0)
            & st.msg_used[None, :]
            & st.msg_valid[None, :]
            & part[:, None]
        )
        lat_f = jnp.where(valid_lat, lat.astype(jnp.float32), jnp.nan)
        p50 = jnp.nanmedian(lat_f)
        p99 = jnp.nanpercentile(lat_f, 99.0)
        return frac, p50, p99
