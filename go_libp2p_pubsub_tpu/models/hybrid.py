"""Adaptive coded gossip: per-edge eager <-> RLNC switching in one scan.

OPTIMUMP2P's framing (and our own r11 numbers) puts the two dissemination
planes at opposite ends of the loss axis: eager+IWANT is latency-optimal on
clean links but pays recovery round trips under loss, while RLNC coded
fragments need no recovery protocol at all — every accepted round adds an
independent equation, so sustained loss only stretches decode time instead
of triggering retransmission.  Real meshes are mixed (the Filecoin/ETH2
evaluation), so the right protocol is per-EDGE, not per-network.

:class:`HybridGossipSub` embeds a full single-topic :class:`GossipSub` and
adds a coded plane over the same topology:

- ``ops/loss_estimator.py`` maintains a per-edge loss EWMA from
  expected-vs-observed receipts, with hysteresis so edges don't flap;
- clean edges run the unmodified eager+IHAVE/IWANT machinery; edges whose
  estimate crosses ``switch_hi`` suppress eager and carry GF(256) RLNC
  fragments instead (generation = window slot, ``gen_size`` fragments,
  structured pivot-slot bases folded by ``gf256.rref_insert``);
- a decode completing (rank hits ``gen_size``) merges back into the gossip
  plane as a first receipt: possession bit, ``first_step`` stamp, and a
  fresh bit so the decoded message eager-relays onward over clean edges.

The switch is a masked merge inside the SAME ``lax.scan`` rollout — the
coded fold is ``lax.cond``-gated on any edge being coded, so an all-clean
fabric pays one predicate per round, and the whole hybrid state (including
every decode basis) rides one scan carry.  With loss estimation forced to
all-clean the rollout is leaf-for-leaf bit-identical to plain eager
GossipSub, flight-recorder channels included (asserted in
``tests/test_hybrid.py``) — the masks degenerate to value-level no-ops and
the coded plane's PRNG stream is separate from the gossip key chain.

Loss model: per-receiver ingress DECIMATION, the RLNC family's convention
(r11) — a peer with ``ingress_loss[i] = d`` accepts data-plane traffic
only on rounds where ``step % (d + 1) == 0``; off-round eager pushes and
pend-fold transfers are LOST (not held), off-round fragments are lost too.
The asymmetry against the mesh families' lossless ``gossip_delay`` hold is
deliberate: this model answers "what if the link actually drops frames",
which is the regime where coding pays.  A second, finer knob rides the
same gate (r17): ``ingress_loss_p[i] = p`` closes the receiver's round
with independent per-round probability p (Bernoulli, its own PRNG chain
separate from both the gossip and coded keys), so the loss axis is
continuous — the decimation grid can only express d/(d+1) in {0, 1/2,
2/3, 3/4, ...}, while the bench's crossover sweep needs points below
1/2.  Both gates AND together; p = 0.0 is a value-level no-op, so the
clean-fabric bit-identity guarantee is untouched.

Serving plane: the model speaks the streaming engine's dialect —
``MultiTopicEvents`` schedules with ``t = 1`` (``delay`` rows set
``ingress_loss``), a ``stream_digest`` in [T=1, M] shape, and value
semantics for the resident-rollout cache — so ``serve/engine.py`` threads
RLNC generations through its chunks unchanged, and its checkpoint payload
(the full model state) carries every per-(peer, generation) decode basis:
a crash mid-generation restores partial rank and finishes the decode
exactly-once (``tests/test_crash_safety.py``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bitpack
from ..ops import gf256
from ..ops import histogram as hist_ops
from ..ops import loss_estimator as loss_ops
from .gossipsub import (
    FLIGHT_HIST_BINS,
    GossipState,
    GossipSub,
    compute_edge_live,
)


class HybridState(NamedTuple):
    """Full hybrid carry: the embedded gossip state plus the coded plane.

    ``gossip`` is a complete :class:`GossipState`; the extra leaves are
    hybrid-only, so a forced-clean rollout leaves them at their init values
    and the embedded leaves bit-identical to a plain GossipSub run.
    """

    gossip: GossipState
    loss_ewma: jax.Array    # f32[N, K] per-edge loss estimate
    coded: jax.Array        # bool[N, K] edges currently on the coded plane
    basis: jax.Array        # u8[N, M, Kg, Kg] per-(peer, generation) decode
    #                         bases in rref_insert's pivot-slot form — the
    #                         crash-safe decode state the engine checkpoints
    ingress_loss: jax.Array  # i32[N] decimation period (0 = lossless)
    key_coded: jax.Array    # coded plane's PRNG (separate from gossip key)
    ingress_loss_p: jax.Array  # f32[N] Bernoulli per-round drop prob (0 = off)
    key_loss: jax.Array     # Bernoulli gate's PRNG (its own chain: neither
    #                         the gossip nor the coded stream may depend on
    #                         whether the fabric is lossy)


class HybridGossipSub:
    """Single-topic adaptive eager/RLNC hybrid with static shapes."""

    def __init__(
        self,
        n_peers: int = 1024,
        n_slots: int = 32,
        conn_degree: int = 16,
        msg_window: int = 64,
        heartbeat_steps: int = 8,
        gen_size: int = 4,
        switch_hi: float = 0.35,
        switch_lo: float = 0.15,
        ewma_alpha: float = 0.25,
        params=None,
        score_params=None,
        builder=None,
        peer_uid: Optional[np.ndarray] = None,
        use_mxu: Optional[bool] = None,
        index_dtype_override=None,
    ):
        if not (1 <= gen_size <= 255):
            raise ValueError(f"gen_size must be in [1, 255], got {gen_size}")
        if not (0.0 <= switch_lo < switch_hi):
            raise ValueError(
                f"need 0 <= switch_lo < switch_hi, got "
                f"lo={switch_lo} hi={switch_hi}"
            )
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError(f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        # The embedded eager plane: the ideal fabric (max_edge_delay=0, no
        # direct peering) — the hybrid's loss model is its own decimation
        # gate, and fresh-history / direct-edge modes would desync from the
        # decoded-bit merge into fresh_w.
        self.gs = GossipSub(
            n_peers=n_peers,
            n_slots=n_slots,
            conn_degree=conn_degree,
            msg_window=msg_window,
            params=params,
            score_params=score_params,
            heartbeat_steps=heartbeat_steps,
            use_pallas=False,
            builder=builder,
            peer_uid=peer_uid,
            index_dtype_override=index_dtype_override,
        )
        self.gen_size = gen_size
        self.switch_hi = float(switch_hi)
        self.switch_lo = float(switch_lo)
        self.ewma_alpha = float(ewma_alpha)
        # GF(256) kernel flavor: the MXU carry-less decomposition is the TPU
        # default (r15); the table path is bit-exact with it everywhere.
        if use_mxu is None:
            use_mxu = jax.default_backend() == "tpu"
        self.use_mxu = bool(use_mxu)

    # -- engine surface (MultiTopicGossipSub dialect, T = 1) ----------------

    t = 1

    @property
    def n(self) -> int:
        return self.gs.n

    @property
    def k(self) -> int:
        return self.gs.k

    @property
    def m(self) -> int:
        return self.gs.m

    @property
    def w(self) -> int:
        return self.gs.w

    @property
    def heartbeat_steps(self) -> int:
        return self.gs.heartbeat_steps

    # Value semantics for the jit cache (the engine's resident-rollout
    # contract): equal configs share compiled chunks across the crash
    # restart.
    def _config_key(self):
        return (
            type(self), self.gs._config_key(), self.gen_size,
            self.switch_hi, self.switch_lo, self.ewma_alpha, self.use_mxu,
        )

    def __eq__(self, other):
        return (
            type(other) is type(self)
            and self._config_key() == other._config_key()
        )

    def __hash__(self):
        return hash(self._config_key())

    def stream_model_key(self) -> str:
        """Config fingerprint for streaming-engine checkpoint meta."""
        return (
            f"hybrid t=1 n={self.n} k={self.k} m={self.m} w={self.w} "
            f"hb={self.heartbeat_steps} kg={self.gen_size} "
            f"hi={self.switch_hi} lo={self.switch_lo}"
        )

    # -- lifecycle ----------------------------------------------------------

    def init(
        self, seed: int = 0, subscribed: Optional[np.ndarray] = None
    ) -> HybridState:
        g = self.gs.init(seed, subscribed)
        n, k, m, kg = self.n, self.k, self.m, self.gen_size
        return HybridState(
            gossip=g,
            loss_ewma=jnp.zeros((n, k), jnp.float32),
            coded=jnp.zeros((n, k), bool),
            basis=jnp.zeros((n, m, kg, kg), jnp.uint8),
            ingress_loss=jnp.zeros((n,), jnp.int32),
            # A fold of the seed key, NOT a split of the gossip chain: the
            # gossip key stream must be untouched for bit-identity.
            key_coded=jax.random.fold_in(jax.random.PRNGKey(seed), 0xC0DE),
            ingress_loss_p=jnp.zeros((n,), jnp.float32),
            key_loss=jax.random.fold_in(jax.random.PRNGKey(seed), 0x1055),
        )

    def set_ingress_loss(self, st: HybridState, delay) -> HybridState:
        """Host-side loss knob: set every peer's decimation period (or a
        per-peer i32[N] vector).  0 restores the lossless fabric."""
        d = jnp.broadcast_to(
            jnp.asarray(delay, jnp.int32), (self.n,)
        )
        return st._replace(ingress_loss=d)

    def set_ingress_loss_p(self, st: HybridState, p) -> HybridState:
        """Host-side Bernoulli loss knob: every peer's round closes with
        independent probability ``p`` (scalar or per-peer f32[N]) — the
        continuous companion to :meth:`set_ingress_loss`'s d/(d+1) grid.
        0.0 restores the lossless fabric (a value-level no-op)."""
        if isinstance(p, (int, float)) and not 0.0 <= p < 1.0:
            raise ValueError(f"ingress_loss_p must be in [0, 1), got {p}")
        pv = jnp.broadcast_to(jnp.asarray(p, jnp.float32), (self.n,))
        return st._replace(ingress_loss_p=pv)

    @functools.partial(jax.jit, static_argnums=0)
    def publish(
        self, st: HybridState, src, slot, valid
    ) -> HybridState:
        """Publish into the window slot on BOTH planes: the gossip seed
        (window recycle + publisher stamp) plus the coded generation's
        identity basis at the publisher.  Invalid publishes never seed a
        generation — the coded plane only carries validated traffic (the
        eager plane still floods them, for scoring parity)."""
        g = self.gs.publish(st.gossip, src, slot, valid)
        kg = self.gen_size
        seed_rows = jnp.eye(kg, dtype=jnp.uint8) * jnp.asarray(
            valid, jnp.uint8
        )
        basis = st.basis.at[:, slot].set(jnp.zeros((kg, kg), jnp.uint8))
        basis = basis.at[src, slot].set(seed_rows)
        return st._replace(gossip=g, basis=basis)

    # -- one round ----------------------------------------------------------

    # Narrow index storage (r22): ``_step_core`` and ``_finish_round`` expect
    # the embedded gossip state in the WIDE kernel view (int32 nbrs/rev with
    # the -1 sentinel) — the public step/rollout entry points widen at entry
    # and narrow back at exit, matching GossipSub's own boundary convention,
    # so the scan carry stays narrow.
    def _widen(self, st: HybridState) -> HybridState:
        return st._replace(gossip=self.gs._widen_indices(st.gossip))

    def _narrow(self, st: HybridState) -> HybridState:
        return st._replace(gossip=self.gs._narrow_indices(st.gossip))

    def _step_core(self, st: HybridState, with_receipts: bool = False):
        """One hybrid network round (pre-heartbeat, pre-step-increment):
        gated eager propagate, cond-gated coded fold + decode merge, and the
        loss-estimator update.  Returns ``(state, per_msg | None)``.  The
        embedded gossip state must be in the wide kernel view (see
        :meth:`_widen`)."""
        g = st.gossip
        n, k, m, kg = self.n, self.k, self.m, self.gen_size
        # Per-receiver ingress decimation gate, the r11 RLNC convention:
        # rounds where the gate is closed LOSE all data-plane ingress.
        # The Bernoulli gate (r17) ANDs in on its own key chain, split
        # unconditionally so the draw stream is independent of the loss
        # values; uniform() lands in [0, 1), so p = 0.0 never closes it.
        kl, kln = jax.random.split(st.key_loss)
        accept = (jnp.mod(g.step, st.ingress_loss + 1) == 0) & (
            jax.random.uniform(kl, (n,)) >= st.ingress_loss_p
        )                                                         # bool[N]

        # Loss-estimator "expected" plane, computed BEFORE the round mutates
        # the state: while the message window carries live traffic, every
        # eager-eligible or coded live edge is expected to deliver each
        # round, so a closed ingress gate is a miss.  Keying on window
        # liveness rather than the sender's instantaneous fresh set matters
        # under real loss: dropped pushes kill the fresh chain within a
        # round or two, and an estimator that only counts fresh-holding
        # senders starves before it can cross the switch threshold.  The
        # estimate converges to the edge's true frame-loss rate
        # (d / (d + 1) under decimation) and stays at exactly 0.0 on a
        # clean fabric.
        j = jnp.clip(g.nbrs, 0, n - 1)
        relay_mesh = g.mesh & (
            g.scores >= self.gs.score_params.graylist_threshold
        )
        gen_live = g.msg_valid & g.msg_active & g.msg_used        # bool[M]
        rank = gf256.gf_rank(st.basis)                            # i32[N, M]
        send_gen = (rank > 0) & gen_live[None, :]                 # bool[N, M]
        expected = (
            g.edge_live & gen_live.any() & (relay_mesh | st.coded)
        )

        # Eager plane: coded edges suppressed, closed receivers drop their
        # ingress.  Both masks are value-level no-ops on a clean fabric.
        if with_receipts:
            g2, per_msg = self.gs._propagate(
                g, with_receipts=True,
                eager_edge_ok=~st.coded, ingress_ok=accept,
            )
        else:
            g2 = self.gs._propagate(
                g, eager_edge_ok=~st.coded, ingress_ok=accept,
            )
            per_msg = None

        # Coded plane: every coded edge's sender emits one fresh GF(256)
        # combination per active generation per round; receivers fold
        # accepted fragments into their pivot-slot bases and completed
        # decodes merge back into the gossip plane as first receipts.  The
        # key splits OUTSIDE the cond so the coded PRNG stream does not
        # depend on which rounds had coded edges.
        kc, kcn = jax.random.split(st.key_coded)

        def coded_round(op):
            gg, basis = op
            coeffs = gf256.coeffs_by_uid(
                kc, (n, k, m, kg), self.gs.peer_uid
            )
            combine = gf256.gf_combine_mxu if self.use_mxu else gf256.gf_combine
            frag = combine(coeffs, basis[:, None])        # u8[N, K, M, Kg]
            rv = jnp.clip(gg.rev, 0, k - 1)
            incoming = frag.reshape(n * k, m, kg)[j * k + rv]
            ok_edge = (
                st.coded & gg.edge_live
                & accept[:, None]
                & (gg.alive & gg.subscribed)[:, None]
            )
            ok = ok_edge[:, :, None] & (send_gen & ~gg.gossip_mute[:, None])[j]
            incoming = jnp.where(ok[..., None], incoming, jnp.uint8(0))
            insert = jax.vmap(jax.vmap(gf256.rref_insert))

            def fold(s, b):
                return insert(b, incoming[:, s])[0]

            basis = jax.lax.fori_loop(0, k, fold, basis)
            # Decode completion = first receipt: possession + fresh (the
            # decoded bytes eager-relay onward over clean edges) + latency
            # stamp.  Peers already stamped by the eager plane this round
            # (or ever) are skipped — exactly-once per (peer, message).
            done = (
                (gf256.gf_rank(basis) == kg)
                & gen_live[None, :]
                & (gg.first_step < 0)
            )
            done_w = bitpack.pack(done)
            gg = gg._replace(
                have_w=gg.have_w | done_w,
                fresh_w=gg.fresh_w | done_w,
                first_step=jnp.where(done, gg.step, gg.first_step),
            )
            per_coded = (
                done
                & (gg.alive & gg.subscribed)[:, None]
            ).sum(axis=0, dtype=jnp.int32)
            return gg, basis, per_coded

        def coded_skip(op):
            gg, basis = op
            return gg, basis, jnp.zeros((m,), jnp.int32)

        g3, basis2, per_coded = jax.lax.cond(
            st.coded.any(), coded_round, coded_skip, (g2, st.basis)
        )
        if per_msg is not None:
            per_msg = per_msg + per_coded

        est = loss_ops.update(
            loss_ops.LossEstimate(st.loss_ewma, st.coded),
            expected, accept[:, None],
            self.ewma_alpha, self.switch_hi, self.switch_lo,
        )
        nxt = st._replace(
            gossip=g3,
            loss_ewma=est.loss_ewma,
            coded=est.coded,
            basis=basis2,
            key_coded=kcn,
            key_loss=kln,
        )
        return nxt, per_msg

    def _finish_round(self, st: HybridState) -> HybridState:
        """Heartbeat cond + step increment, matching ``GossipSub.step``'s
        ordering on the embedded state."""
        g = jax.lax.cond(
            (st.gossip.step % self.heartbeat_steps)
            == self.heartbeat_steps - 1,
            self.gs._heartbeat,
            lambda s: s,
            st.gossip,
        )
        return st._replace(gossip=g._replace(step=g.step + 1))

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: HybridState) -> HybridState:
        st, _ = self._step_core(self._widen(st))
        return self._narrow(self._finish_round(st))

    @functools.partial(jax.jit, static_argnums=0)
    def step_recorded(self, st: HybridState):
        """``step`` plus the receipt tap (eager stampings + coded decode
        completions this round) — same state graph as ``step``."""
        st, per_msg = self._step_core(self._widen(st), with_receipts=True)
        return self._narrow(self._finish_round(st)), per_msg

    # -- rollouts -----------------------------------------------------------

    @functools.partial(jax.jit, static_argnames=("self", "n_steps", "record"))
    def rollout(self, st: HybridState, n_steps: int, record: bool = True):
        """``n_steps`` rounds in one scan -> (final state, record | None);
        the recorder architecture (carried cumulative latency histogram,
        per-round channel dict) mirrors ``GossipSub.rollout``."""
        if not record:
            def bare(s, _):
                return self.step(s), None

            return jax.lax.scan(bare, st, None, length=n_steps)

        g0 = st.gossip
        hist0 = hist_ops.latency_histogram_seed(
            g0.first_step, g0.msg_birth, g0.msg_used & g0.msg_valid,
            g0.alive & g0.subscribed, FLIGHT_HIST_BINS,
        )

        def body(carry, _):
            s, hist = carry
            s2, per_msg = self.step_recorded(s)
            hist = hist + hist_ops.latency_histogram_increment(
                per_msg, s2.gossip.msg_birth,
                s2.gossip.msg_used & s2.gossip.msg_valid,
                s.gossip.step, FLIGHT_HIST_BINS,
            )
            return (s2, hist), self.flight_record_round(s2, hist)

        (final, _), ys = jax.lax.scan(body, (st, hist0), None, length=n_steps)
        return final, ys

    @functools.partial(jax.jit, static_argnames=("self", "record"))
    def rollout_events(self, st: HybridState, events, record: bool = True):
        """Run a ``MultiTopicEvents`` schedule (the streaming engine's chunk
        dialect, T = 1) in one scan -> (final state, record | None).

        Event mapping: ``kill`` / ``mute_*`` hit the embedded gossip state;
        ``delay`` rows set ``ingress_loss`` (DECIMATION — the hybrid's loss
        model, NOT the multitopic pend-hold; same schedule field, per-family
        semantics, the r11 asymmetry); publishes seed both planes
        (``pub_topic`` is clipped into the single topic).
        """
        n_steps = int(events.kill.shape[0])

        def apply_events(s, ev):
            g = s.gossip
            g = jax.lax.cond(
                ev.kill.any(),
                lambda x: x._replace(
                    alive=x.alive & ~ev.kill,
                    edge_live=compute_edge_live(
                        x.nbr_valid, x.nbrs, x.alive & ~ev.kill
                    ),
                ),
                lambda x: x,
                g,
            )
            g = jax.lax.cond(
                ev.mute_on.any() | ev.mute_off.any(),
                lambda x: x._replace(
                    gossip_mute=(x.gossip_mute & ~ev.mute_off) | ev.mute_on
                ),
                lambda x: x,
                g,
            )
            s = s._replace(gossip=g)
            s = jax.lax.cond(
                (ev.delay >= 0).any(),
                lambda x: x._replace(
                    ingress_loss=jnp.where(
                        ev.delay >= 0, ev.delay, x.ingress_loss
                    )
                ),
                lambda x: x,
                s,
            )
            for i in range(ev.pub_src.shape[0]):
                s = jax.lax.cond(
                    (ev.pub_src[i] >= 0) & (ev.pub_topic[i] >= 0),
                    lambda x, jx=i: self.publish(
                        x,
                        ev.pub_src[jx],
                        jnp.clip(ev.pub_slot[jx], 0, self.m - 1),
                        ev.pub_valid[jx],
                    ),
                    lambda x: x,
                    s,
                )
            return s

        if not record:
            def bare(s, ev):
                s = apply_events(s, ev)
                s, _ = self._step_core(self._widen(s))
                return self._narrow(self._finish_round(s)), None

            return jax.lax.scan(bare, st, events, length=n_steps)

        g0 = st.gossip
        hist0 = hist_ops.latency_histogram_seed(
            g0.first_step, g0.msg_birth, g0.msg_used & g0.msg_valid,
            g0.alive & g0.subscribed, FLIGHT_HIST_BINS,
        )

        def body(carry, ev):
            s, hist = carry
            s = apply_events(s, ev)
            # Publisher self-receipts land in the histogram at bin 0 (the
            # GossipSub.rollout_events convention).
            src_c = jnp.clip(ev.pub_src, 0, self.n - 1)
            pub_counted = (
                (ev.pub_src >= 0)
                & (ev.pub_topic >= 0)
                & ev.pub_valid
                & s.gossip.alive[src_c]
                & s.gossip.subscribed[src_c]
            ).sum(dtype=jnp.int32)
            hist = hist.at[0].add(pub_counted)
            s2, per_msg = self._step_core(self._widen(s), with_receipts=True)
            hist = hist + hist_ops.latency_histogram_increment(
                per_msg, s2.gossip.msg_birth,
                s2.gossip.msg_used & s2.gossip.msg_valid,
                s.gossip.step, FLIGHT_HIST_BINS,
            )
            s2 = self._narrow(self._finish_round(s2))
            return (s2, hist), self.flight_record_round(s2, hist)

        (final, _), ys = jax.lax.scan(body, (st, hist0), events, length=n_steps)
        return final, ys

    # -- flight recorder / views --------------------------------------------

    def flight_record_round(self, st: HybridState, lat_hist: jax.Array):
        """The embedded GossipSub channels (bit-identical on a clean
        fabric) plus the hybrid's own: how many edges are coded, and the
        mean per-edge loss estimate over wired slots."""
        rec = self.gs.flight_record_round(st.gossip, lat_hist)
        wired = st.gossip.nbr_valid
        rec["coded_edges"] = (st.coded & wired).sum().astype(jnp.int32)
        rec["loss_ewma_mean"] = (
            jnp.where(wired, st.loss_ewma, 0.0).sum()
            / jnp.maximum(wired.sum(), 1)
        )
        return rec

    @functools.partial(jax.jit, static_argnums=0)
    def delivery_stats(self, st: HybridState):
        return self.gs.delivery_stats(st.gossip)

    @functools.partial(jax.jit, static_argnums=0)
    def stream_digest(self, st: HybridState):
        """Per-slot completion counters in the engine's [T=1, ...] shapes.

        Counted from ``first_step`` (the immutable receipt record, which
        the coded merge stamps too) rather than possession words, so a
        seen-cache TTL scrub never un-counts a delivery mid-stream.
        """
        g = st.gossip
        part = g.alive & g.subscribed
        delivered = ((g.first_step >= 0) & part[:, None]).sum(
            axis=0, dtype=jnp.int32
        )
        return {
            "delivered": delivered[None, :],
            "participants": part.sum(dtype=jnp.int32)[None],
            "msg_used": g.msg_used[None, :],
            "msg_valid": g.msg_valid[None, :],
            "msg_birth": g.msg_birth[None, :],
            "step": g.step,
        }

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def stream_deliver_steps(
        self, st: HybridState, chunk_steps: int, completion_frac
    ) -> jax.Array:
        """Per-slot delivery round within the chunk that just ran, in the
        engine's [T=1, M] shape: the first of the chunk's ``chunk_steps``
        rounds at which the count of participants with ``first_step <=
        round`` reached ``max(1, completion_frac * participants)`` (the
        coded merge stamps ``first_step`` too, so decoded-generation
        deliveries resolve exactly like eager ones); the chunk's first
        round when the threshold was crossed before it, -1 where it has
        not been crossed.  Counting over the chunk's rounds instead of
        sorting all N receipt steps keeps the traced-path cost a tiny
        fraction of the chunk itself.  Host-called by the streaming engine
        only when tracing is on; takes the frac so the engine can dispatch
        it before its blocking digest fetch."""
        g = st.gossip
        part = g.alive & g.subscribed
        participants = part.sum()                     # scalar
        target = jnp.maximum(
            1, (completion_frac * participants).astype(jnp.int32)
        )
        valid = (g.first_step >= 0) & part[:, None]   # [N, M]
        cand = g.step - chunk_steps + jnp.arange(chunk_steps)  # [S]
        counts = (
            valid[None, :, :]
            & (g.first_step[None, :, :] <= cand[:, None, None])
        ).sum(axis=1)                                 # [S, M]
        crossed = counts >= target                    # [S, M]
        first = jnp.argmax(crossed, axis=0)           # first crossing idx
        return jnp.where(crossed.any(axis=0), cand[first], -1)[None, :]

    def decode_rank_summary(self, st: HybridState) -> dict:
        """Host-side decode-progress counts for checkpoint meta: how many
        (peer, generation) bases are mid-decode vs fully decoded over live
        generations."""
        g = st.gossip
        rank = np.asarray(jax.device_get(gf256.gf_rank(st.basis)))
        live = np.asarray(
            jax.device_get(g.msg_used & g.msg_valid & g.msg_active)
        )[None, :]
        partial = int(((rank > 0) & (rank < self.gen_size) & live).sum())
        full = int(((rank == self.gen_size) & live).sum())
        return {"partial": partial, "full": full}
