"""Protocol models: treecast (v0 parity flagship), floodsub, gossipsub."""
