"""Protocol models: treecast (v0 parity flagship), floodsub, randomsub,
gossipsub, multitopic, attacks — the three upstream router families plus
the v0 tree."""
