"""Attack traces: scripted adversary scenarios over the GossipSub sim.

BASELINE.json config (d): "peer-scoring refresh under sybil/eclipse attack
traces".  The v0 reference has no adversary model at all — no signing
(``pubsub.go:117``), no validation, no scoring — so these scenarios encode
the capability envelope: each one drives the simulator with an adversary
schedule and records a per-step defense time series, all device-side (the
rollout is one ``lax.scan``; metrics are reduced in-scan, not on host).

Scenarios:
- **invalid spam** — attackers flood invalid messages (failed validation);
  P4 penalties must evict them from every honest mesh.
- **sybil colocation** — many attacker identities share one IP group; the
  P6 colocation penalty must keep them un-grafted regardless of conduct.
- **eclipse attempt** — attackers start fully occupying a target's mesh
  slots and go silent; P3 delivery-deficit penalties must rotate them out
  and restore the target's delivery.

Each runner returns ``(final_state, report)`` where ``report`` maps metric
name -> per-step array (host numpy), ready for assertions or plotting.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .gossipsub import GossipState, GossipSub


def _attacker_metrics(
    gs: GossipSub, st: GossipState, attackers: jax.Array
) -> Dict[str, jax.Array]:
    """In-scan reductions: adversary mesh occupancy + score standing."""
    n = gs.n
    att_slot = st.nbr_valid & attackers[jnp.clip(st.nbrs, 0, n - 1)]
    honest = ~attackers & st.alive
    in_honest_mesh = (st.mesh & att_slot & honest[:, None]).sum()
    att_scores = jnp.where(att_slot, st.scores, jnp.nan)
    return {
        "attacker_mesh_edges": in_honest_mesh.astype(jnp.int32),
        "attacker_score_mean": jnp.nanmean(att_scores),
        "honest_score_min": jnp.nanmin(
            jnp.where(
                st.nbr_valid & ~att_slot & jnp.isfinite(st.scores),
                st.scores,
                jnp.nan,
            )
        ),
    }


def run_with_metrics(
    gs: GossipSub,
    st: GossipState,
    n_steps: int,
    attackers: jax.Array,
) -> Tuple[GossipState, Dict[str, np.ndarray]]:
    """Roll ``n_steps`` collecting the defense time series each step."""

    def body(s, _):
        s = gs.step(s)
        return s, _attacker_metrics(gs, s, attackers)

    st, series = jax.lax.scan(body, st, None, length=n_steps)
    return st, {k: np.asarray(v) for k, v in jax.device_get(series).items()}


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def invalid_spam_attack(
    gs: GossipSub,
    st: GossipState,
    n_attackers: int,
    n_rounds: int = 6,
    steps_per_round: int = 4,
    seed: int = 0,
) -> Tuple[GossipState, Dict[str, np.ndarray], jax.Array]:
    """Attackers (peers 0..n_attackers-1) publish invalid messages each
    round; honest traffic continues from random publishers."""
    if n_attackers > gs.m // 2:
        raise ValueError(
            f"n_attackers ({n_attackers}) exceeds the publishable window "
            f"(msg_window // 2 = {gs.m // 2}); grow msg_window or shrink "
            "the attacker set — clamping silently would model a smaller "
            "attack than reported"
        )
    attackers = jnp.arange(gs.n) < n_attackers
    rng = np.random.default_rng(seed)
    series = []
    slot = 0
    for _ in range(n_rounds):
        # Every attacker seeds one invalid message; one honest publish too.
        for a in range(n_attackers):
            st = gs.publish(
                st,
                jnp.int32(a),
                jnp.int32(slot % gs.m),
                jnp.asarray(False),
            )
            slot += 1
        st = gs.publish(
            st,
            jnp.int32(int(rng.integers(n_attackers, gs.n))),
            jnp.int32(slot % gs.m),
            jnp.asarray(True),
        )
        slot += 1
        st, s = run_with_metrics(gs, st, steps_per_round, attackers)
        series.append(s)
    report = {
        k: np.concatenate([s[k] for s in series]) for k in series[0]
    }
    return st, report, attackers


def sybil_colocation_attack(
    gs: GossipSub,
    st: GossipState,
    n_sybils: int,
    n_steps: int = 32,
) -> Tuple[GossipState, Dict[str, np.ndarray], jax.Array]:
    """Sybil identities (peers 0..n_sybils-1) share one colocation group;
    the P6 penalty (``ops/scoring.colocation_penalty``) is the defense."""
    attackers = jnp.arange(gs.n) < n_sybils
    group = np.asarray(st.gcounters.ip_group).copy()
    group[:n_sybils] = 0
    st = st._replace(
        gcounters=st.gcounters._replace(ip_group=jnp.asarray(group))
    )
    st, report = run_with_metrics(gs, st, n_steps, attackers)
    return st, report, attackers


def eclipse_attempt(
    gs: GossipSub,
    st: GossipState,
    target: int,
    n_rounds: int = 8,
    msgs_per_round: int = 2,
    seed: int = 0,
) -> Tuple[GossipState, Dict[str, np.ndarray], jax.Array]:
    """The target's entire converged mesh turns adversarial and goes silent
    (receives but never relays): an eclipse — the target's data-plane view
    is fully attacker-controlled.  With P3 (mesh-delivery deficit) enabled
    in the model's score params and honest background traffic flowing, the
    silent slots build delivery deficits, get pruned (and held out by the
    prune backoff), and honest grafts restore the target's connectivity.

    Each round publishes ``msgs_per_round`` valid messages from random
    honest peers, then advances one heartbeat period with attacker relay
    suppressed on BOTH data planes: their fresh words are zeroed after
    every step (no eager relay) AND they are marked ``gossip_mute`` (no
    gossip service either — a mute peer advertises but never answers
    IWANTs; every ask it attracts charges its P7 behaviour penalty).
    Attackers stay alive and scoreable throughout.
    """
    n, k = gs.n, gs.k
    nbrs_np = np.asarray(st.nbrs)
    mesh_np = np.asarray(st.mesh)
    att_ids = sorted(
        {int(nbrs_np[target, s]) for s in range(k) if mesh_np[target, s]}
    )
    attackers = jnp.zeros((n,), bool).at[jnp.asarray(att_ids)].set(True)
    honest_ids = np.array(
        [i for i in range(n) if i not in att_ids and i != target]
    )
    silence = jnp.where(
        attackers[:, None], jnp.uint32(0), jnp.uint32(0xFFFFFFFF)
    )
    # First-class promise-breaking: the heartbeat's IWANT selection skips
    # serving from muted peers and charges their P7 directly — no state
    # surgery on advertisement snapshots needed (r3 verdict item 6).
    st = gs.set_gossip_mute(st, attackers)

    def body(s, _):
        s = gs.step(s)
        # Attacker silence on the eager plane: drop anything they would
        # relay next round.
        s = s._replace(fresh_w=s.fresh_w & silence)
        m = _attacker_metrics(gs, s, attackers)
        # Target-centric defense metric: mesh edges to honest peers.
        tgt_honest = (
            s.mesh[target]
            & s.nbr_valid[target]
            & ~attackers[jnp.clip(s.nbrs[target], 0, n - 1)]
        ).sum()
        m["target_honest_mesh_edges"] = tgt_honest.astype(jnp.int32)
        return s, m

    rng = np.random.default_rng(seed)
    series = []
    slot = 0
    for _ in range(n_rounds):
        for _ in range(msgs_per_round):
            st = gs.publish(
                st,
                jnp.int32(int(rng.choice(honest_ids))),
                jnp.int32(slot % gs.m),
                jnp.asarray(True),
            )
            slot += 1
        st, s = jax.lax.scan(body, st, None, length=gs.heartbeat_steps)
        series.append(jax.device_get(s))
    report = {
        k_: np.concatenate([np.asarray(s[k_]) for s in series])
        for k_ in series[0]
    }
    return st, report, attackers


def gossip_promise_spam_attack(
    n_peers: int = 64,
    n_attackers: int = 8,
    n_rounds: int = 10,
    seed: int = 0,
    **model_kwargs,
) -> Tuple[GossipSub, GossipState, Dict[str, np.ndarray], jax.Array]:
    """Advertise-heavily, serve-nothing spammers vs IWANT promise tracking.

    Attackers participate normally in the mesh and in IHAVE emission — they
    receive honest traffic and advertise it — but never answer an IWANT
    (``gossip_mute``).  Every ask they attract is a broken promise charged
    to their P7 behaviour penalty at the heartbeat (the spec's gossip
    promise tracking via the followup timeout, collapsed to the heartbeat
    in the lockstep model).  The squared P7 term must push their global
    score negative with NO manual advertisement muting, while honest peers
    accrue zero penalty and honest traffic still delivers.

    A short heartbeat period keeps messages mid-flight at heartbeat time so
    IHAVE/IWANT traffic actually flows (with long periods the eager push
    saturates possession first and nobody wants anything).
    """
    from ..config import ScoreParams
    from ..ops import scoring as scoring_ops

    model_kwargs.setdefault("heartbeat_steps", 2)
    sp = model_kwargs.pop("score_params", ScoreParams())
    gs = GossipSub(n_peers=n_peers, score_params=sp, **model_kwargs)
    st = gs.init(seed=seed)
    attackers = jnp.arange(n_peers) < n_attackers
    st = gs.set_gossip_mute(st, attackers)
    rng = np.random.default_rng(seed)

    def body(s, _):
        s = gs.step(s)
        m = _attacker_metrics(gs, s, attackers)
        m["attacker_behaviour_penalty"] = s.gcounters.behaviour_penalty.max(
            where=attackers, initial=0.0
        )
        m["attacker_global_score"] = jnp.nanmean(
            jnp.where(
                attackers, scoring_ops.global_score(s.gcounters, sp), jnp.nan
            )
        )
        m["honest_behaviour_penalty_max"] = jnp.where(
            ~attackers, s.gcounters.behaviour_penalty, 0.0
        ).max()
        return s, m

    series = []
    slot = 0
    for _ in range(n_rounds):
        # Honest publishes only: the attack is pure gossip-service abuse.
        for _ in range(3):
            st = gs.publish(
                st,
                jnp.int32(int(rng.integers(n_attackers, n_peers))),
                jnp.int32(slot % gs.m),
                jnp.asarray(True),
            )
            slot += 1
        st, s = jax.lax.scan(body, st, None, length=gs.heartbeat_steps)
        series.append(jax.device_get(s))
    report = {
        k_: np.concatenate([np.asarray(s[k_]) for s in series])
        for k_ in series[0]
    }
    return gs, st, report, attackers


def backoff_spam_attack(
    n_peers: int = 64,
    n_attackers: int = 6,
    n_rounds: int = 8,
    seed: int = 0,
    **model_kwargs,
) -> Tuple[GossipSub, GossipState, Dict[str, np.ndarray], jax.Array]:
    """GRAFT flooders vs the P7 behaviour penalty.

    Attackers spam invalid messages (so honest meshes prune them, starting
    prune-backoff countdowns) AND re-graft straight through the backoff
    window every heartbeat (``graft_spammers``).  Every refused attempt
    charges their ``behaviour_penalty``; the squared P7 term must push their
    score negative and keep them out of honest meshes even after the P4
    spam evidence has decayed away.

    Constructs its own model (the spammer set is constructor-bound — see
    ``GossipSub.graft_spammers``).  Returns (model, final_state, report,
    attacker_mask); the report adds ``attacker_behaviour_penalty`` and
    ``attacker_global_score`` to the standard defense series.
    """
    from ..config import ScoreParams
    from ..ops import scoring as scoring_ops

    attackers_np = np.arange(n_peers) < n_attackers
    sp = model_kwargs.pop("score_params", ScoreParams())
    gs = GossipSub(
        n_peers=n_peers,
        score_params=sp,
        graft_spammers=attackers_np,
        **model_kwargs,
    )
    st = gs.init(seed=seed)
    attackers = jnp.asarray(attackers_np)
    rng = np.random.default_rng(seed)

    def body(s, _):
        s = gs.step(s)
        m = _attacker_metrics(gs, s, attackers)
        m["attacker_behaviour_penalty"] = s.gcounters.behaviour_penalty.max(
            where=attackers, initial=0.0
        )
        m["attacker_global_score"] = jnp.nanmean(
            jnp.where(
                attackers, scoring_ops.global_score(s.gcounters, sp), jnp.nan
            )
        )
        return s, m

    series = []
    slot = 0
    for _ in range(n_rounds):
        # Attacker spam earns the prunes; one honest publish keeps honest
        # P2 credit flowing.
        for a in range(n_attackers):
            st = gs.publish(
                st, jnp.int32(a), jnp.int32(slot % gs.m), jnp.asarray(False)
            )
            slot += 1
        st = gs.publish(
            st,
            jnp.int32(int(rng.integers(n_attackers, n_peers))),
            jnp.int32(slot % gs.m),
            jnp.asarray(True),
        )
        slot += 1
        st, s = jax.lax.scan(body, st, None, length=gs.heartbeat_steps)
        series.append(jax.device_get(s))
    report = {
        k_: np.concatenate([np.asarray(s[k_]) for s in series])
        for k_ in series[0]
    }
    return gs, st, report, attackers
