"""Attack traces: scripted adversary scenarios over the GossipSub sim.

BASELINE.json config (d): "peer-scoring refresh under sybil/eclipse attack
traces".  The v0 reference has no adversary model at all — no signing
(``pubsub.go:117``), no validation, no scoring — so these scenarios encode
the capability envelope: each one drives the simulator with an adversary
schedule and records a per-step defense time series, all device-side.

Since the scenario engine landed, every runner lowers its campaign to an
``ops.schedule.GossipEvents`` tensor and executes it in the model's single
``rollout_events`` scan — publishes, mutes, and attacker silence are scan
``xs``, not host round-trips between scan segments.  The declarative form
of the same campaigns lives in ``scenario.canon``; these runners remain
the imperative fixtures the slow tests drive directly.

Each runner returns ``(final_state, report)`` where ``report`` maps metric
name -> per-step array (host numpy): the flight-recorder channels plus the
adversary-standing series (``attacker_mesh_edges``, ``attacker_score_mean``,
``honest_score_min``, and per-scenario extras), ready for assertions or
plotting.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import schedule as sched
from ..ops.graphs import decode_index_plane
from .gossipsub import GossipState, GossipSub


def _attacker_metrics(
    gs: GossipSub, st: GossipState, attackers: jax.Array
) -> Dict[str, jax.Array]:
    """In-scan reductions: adversary mesh occupancy + score standing."""
    n = gs.n
    att_slot = st.nbr_valid & attackers[
        jnp.clip(decode_index_plane(st.nbrs), 0, n - 1)
    ]
    honest = ~attackers & st.alive
    in_honest_mesh = (st.mesh & att_slot & honest[:, None]).sum()
    # Explicit masked reductions (GossipSub.masked_mean/min): NaN silently
    # when the attacker set is empty — never numpy's all-NaN-slice warning.
    return {
        "attacker_mesh_edges": in_honest_mesh.astype(jnp.int32),
        "attacker_score_mean": GossipSub.masked_mean(st.scores, att_slot),
        "honest_score_min": GossipSub.masked_min(
            st.scores,
            st.nbr_valid & ~att_slot & jnp.isfinite(st.scores),
        ),
    }


def run_with_metrics(
    gs: GossipSub,
    st: GossipState,
    n_steps: int,
    attackers: jax.Array,
) -> Tuple[GossipState, Dict[str, np.ndarray]]:
    """Roll ``n_steps`` collecting the defense time series each step."""

    def body(s, _):
        s = gs.step(s)
        return s, _attacker_metrics(gs, s, attackers)

    st, series = jax.lax.scan(body, st, None, length=n_steps)
    return st, {k: np.asarray(v) for k, v in jax.device_get(series).items()}


def _run_events(
    gs: GossipSub,
    st: GossipState,
    events,
    attackers,
    target=None,
) -> Tuple[GossipState, Dict[str, np.ndarray]]:
    """One ``rollout_events`` scan -> (final state, host-numpy report)."""
    st, record = gs.rollout_events(
        st, events, attackers=jnp.asarray(attackers), target=target,
        record=True,
    )
    return st, {k: np.asarray(v) for k, v in jax.device_get(record).items()}


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def invalid_spam_attack(
    gs: GossipSub,
    st: GossipState,
    n_attackers: int,
    n_rounds: int = 6,
    steps_per_round: int = 4,
    seed: int = 0,
) -> Tuple[GossipState, Dict[str, np.ndarray], jax.Array]:
    """Attackers (peers 0..n_attackers-1) publish invalid messages each
    round; honest traffic continues from random publishers."""
    if n_attackers > gs.m // 2:
        raise ValueError(
            f"n_attackers ({n_attackers}) exceeds the publishable window "
            f"(msg_window // 2 = {gs.m // 2}); grow msg_window or shrink "
            "the attacker set — clamping silently would model a smaller "
            "attack than reported"
        )
    attackers = np.arange(gs.n) < n_attackers
    rng = np.random.default_rng(seed)
    n_steps = n_rounds * steps_per_round
    events = sched.empty_gossip_events(n_steps, gs.n, n_attackers + 1)
    slot = 0
    for r in range(n_rounds):
        t = r * steps_per_round
        # Every attacker seeds one invalid message; one honest publish too.
        for a in range(n_attackers):
            sched.add_publish(
                events, t, {"src": a, "slot": slot % gs.m, "valid": False}
            )
            slot += 1
        sched.add_publish(
            events, t,
            {"src": int(rng.integers(n_attackers, gs.n)),
             "slot": slot % gs.m, "valid": True},
        )
        slot += 1
    st, report = _run_events(gs, st, events, attackers)
    return st, report, jnp.asarray(attackers)


def sybil_colocation_attack(
    gs: GossipSub,
    st: GossipState,
    n_sybils: int,
    n_steps: int = 32,
) -> Tuple[GossipState, Dict[str, np.ndarray], jax.Array]:
    """Sybil identities (peers 0..n_sybils-1) share one colocation group;
    the P6 penalty (``ops/scoring.colocation_penalty``) is the defense."""
    attackers = np.arange(gs.n) < n_sybils
    group = np.asarray(st.gcounters.ip_group).copy()
    group[:n_sybils] = 0
    st = st._replace(
        gcounters=st.gcounters._replace(ip_group=jnp.asarray(group))
    )
    events = sched.empty_gossip_events(n_steps, gs.n)
    st, report = _run_events(gs, st, events, attackers)
    return st, report, jnp.asarray(attackers)


def eclipse_attempt(
    gs: GossipSub,
    st: GossipState,
    target: int,
    n_rounds: int = 8,
    msgs_per_round: int = 2,
    seed: int = 0,
) -> Tuple[GossipState, Dict[str, np.ndarray], jax.Array]:
    """The target's entire converged mesh turns adversarial and goes silent
    (receives but never relays): an eclipse — the target's data-plane view
    is fully attacker-controlled.  With P3 (mesh-delivery deficit) enabled
    in the model's score params and honest background traffic flowing, the
    silent slots build delivery deficits, get pruned (and held out by the
    prune backoff), and honest grafts restore the target's connectivity.

    Each round publishes ``msgs_per_round`` valid messages from random
    honest peers, then advances one heartbeat period with attacker relay
    suppressed on BOTH data planes: their fresh words are zeroed after
    every step (the schedule's ``silence`` channel — no eager relay) AND
    they are marked ``gossip_mute`` (no gossip service either — a mute peer
    advertises but never answers IWANTs; every ask it attracts charges its
    P7 behaviour penalty).  Attackers stay alive and scoreable throughout.
    """
    n, k = gs.n, gs.k
    nbrs_np = np.asarray(decode_index_plane(np.asarray(st.nbrs)))
    mesh_np = np.asarray(st.mesh)
    att_ids = sorted(
        {int(nbrs_np[target, s]) for s in range(k) if mesh_np[target, s]}
    )
    attackers = np.zeros((n,), bool)
    attackers[att_ids] = True
    honest_ids = np.array(
        [i for i in range(n) if i not in att_ids and i != target]
    )
    rng = np.random.default_rng(seed)
    n_steps = n_rounds * gs.heartbeat_steps
    events = sched.empty_gossip_events(n_steps, n, msgs_per_round)
    # First-class promise-breaking: the heartbeat's IWANT selection skips
    # serving from muted peers and charges their P7 directly — no state
    # surgery on advertisement snapshots needed (r3 verdict item 6).
    events.mute_on[0] |= attackers
    events.silence[:] |= attackers[None, :]
    slot = 0
    for r in range(n_rounds):
        t = r * gs.heartbeat_steps
        for _ in range(msgs_per_round):
            sched.add_publish(
                events, t,
                {"src": int(rng.choice(honest_ids)),
                 "slot": slot % gs.m, "valid": True},
            )
            slot += 1
    st, report = _run_events(gs, st, events, attackers, target=target)
    return st, report, jnp.asarray(attackers)


def gossip_promise_spam_attack(
    n_peers: int = 64,
    n_attackers: int = 8,
    n_rounds: int = 10,
    seed: int = 0,
    **model_kwargs,
) -> Tuple[GossipSub, GossipState, Dict[str, np.ndarray], jax.Array]:
    """Advertise-heavily, serve-nothing spammers vs IWANT promise tracking.

    Attackers participate normally in the mesh and in IHAVE emission — they
    receive honest traffic and advertise it — but never answer an IWANT
    (``gossip_mute``).  Every ask they attract is a broken promise charged
    to their P7 behaviour penalty at the heartbeat (the spec's gossip
    promise tracking via the followup timeout, collapsed to the heartbeat
    in the lockstep model).  The squared P7 term must push their global
    score negative with NO manual advertisement muting, while honest peers
    accrue zero penalty and honest traffic still delivers.

    A short heartbeat period keeps messages mid-flight at heartbeat time so
    IHAVE/IWANT traffic actually flows (with long periods the eager push
    saturates possession first and nobody wants anything).
    """
    from ..config import ScoreParams

    model_kwargs.setdefault("heartbeat_steps", 2)
    sp = model_kwargs.pop("score_params", ScoreParams())
    gs = GossipSub(n_peers=n_peers, score_params=sp, **model_kwargs)
    st = gs.init(seed=seed)
    attackers = np.arange(n_peers) < n_attackers
    rng = np.random.default_rng(seed)
    n_steps = n_rounds * gs.heartbeat_steps
    events = sched.empty_gossip_events(n_steps, n_peers, 3)
    events.mute_on[0] |= attackers
    slot = 0
    for r in range(n_rounds):
        t = r * gs.heartbeat_steps
        # Honest publishes only: the attack is pure gossip-service abuse.
        for _ in range(3):
            sched.add_publish(
                events, t,
                {"src": int(rng.integers(n_attackers, n_peers)),
                 "slot": slot % gs.m, "valid": True},
            )
            slot += 1
    st, report = _run_events(gs, st, events, attackers)
    return gs, st, report, jnp.asarray(attackers)


def backoff_spam_attack(
    n_peers: int = 64,
    n_attackers: int = 6,
    n_rounds: int = 8,
    seed: int = 0,
    **model_kwargs,
) -> Tuple[GossipSub, GossipState, Dict[str, np.ndarray], jax.Array]:
    """GRAFT flooders vs the P7 behaviour penalty.

    Attackers spam invalid messages (so honest meshes prune them, starting
    prune-backoff countdowns) AND re-graft straight through the backoff
    window every heartbeat (``graft_spammers``).  Every refused attempt
    charges their ``behaviour_penalty``; the squared P7 term must push their
    score negative and keep them out of honest meshes even after the P4
    spam evidence has decayed away.

    Constructs its own model (the spammer set is constructor-bound — see
    ``GossipSub.graft_spammers``).  Returns (model, final_state, report,
    attacker_mask); the report adds ``attacker_behaviour_penalty`` and
    ``attacker_global_score`` to the standard defense series.
    """
    from ..config import ScoreParams

    attackers_np = np.arange(n_peers) < n_attackers
    sp = model_kwargs.pop("score_params", ScoreParams())
    gs = GossipSub(
        n_peers=n_peers,
        score_params=sp,
        graft_spammers=attackers_np,
        **model_kwargs,
    )
    st = gs.init(seed=seed)
    rng = np.random.default_rng(seed)
    n_steps = n_rounds * gs.heartbeat_steps
    events = sched.empty_gossip_events(n_steps, n_peers, n_attackers + 1)
    slot = 0
    for r in range(n_rounds):
        t = r * gs.heartbeat_steps
        # Attacker spam earns the prunes; one honest publish keeps honest
        # P2 credit flowing.
        for a in range(n_attackers):
            sched.add_publish(
                events, t, {"src": a, "slot": slot % gs.m, "valid": False}
            )
            slot += 1
        sched.add_publish(
            events, t,
            {"src": int(rng.integers(n_attackers, n_peers)),
             "slot": slot % gs.m, "valid": True},
        )
        slot += 1
    st, report = _run_events(gs, st, events, attackers_np)
    return gs, st, report, jnp.asarray(attackers_np)
