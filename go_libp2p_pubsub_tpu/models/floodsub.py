"""FloodSub — the dense baseline model.

Floods every message over every connection edge (no mesh, no gossip): the
protocol family the reference's README situates itself in ("a basic one to
many pubsub implementation", ``README.md:8``) and the first BASELINE.json
config ("in-process 10-peer floodsub broadcast").  Serves as the delivery
upper bound / bandwidth worst case against which GossipSub's mesh is judged.

State is a strict subset of the GossipSub layout (same adjacency form), and
the step is one gather-or per round — the simplest possible epidemic kernel.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.graphs import decode_index_plane, safe_gather
from .gossipsub import build_topology


class FloodState(NamedTuple):
    nbrs: jax.Array        # i32[N, K]
    nbr_valid: jax.Array   # bool[N, K]
    alive: jax.Array       # bool[N]
    have: jax.Array        # bool[N, M]
    fresh: jax.Array       # bool[N, M]
    first_step: jax.Array  # i32[N, M]
    msg_valid: jax.Array   # bool[M]
    msg_birth: jax.Array   # i32[M]
    msg_used: jax.Array    # bool[M] ever published
    step: jax.Array


class FloodSub:
    def __init__(self, n_peers: int = 1024, n_slots: int = 32,
                 conn_degree: int = 16, msg_window: int = 128):
        self.n, self.k, self.m = n_peers, n_slots, msg_window
        self.conn_degree = conn_degree

    def init(self, seed: int = 0) -> FloodState:
        rng = np.random.default_rng(seed)
        nbrs, _, valid, _ = build_topology(rng, self.n, self.k, self.conn_degree)
        n, m = self.n, self.m
        # Builders return narrow wrap-encoded planes (r22); this model keeps
        # the legacy signed form — decode restores the -1 sentinel.
        return FloodState(
            nbrs=jnp.asarray(decode_index_plane(nbrs), jnp.int32),
            nbr_valid=jnp.asarray(valid),
            alive=jnp.ones((n,), bool),
            have=jnp.zeros((n, m), bool),
            fresh=jnp.zeros((n, m), bool),
            first_step=jnp.full((n, m), -1, jnp.int32),
            msg_valid=jnp.zeros((m,), bool),
            msg_birth=jnp.zeros((m,), jnp.int32),
            msg_used=jnp.zeros((m,), bool),
            step=jnp.asarray(0, jnp.int32),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def publish(self, st: FloodState, src, slot, valid) -> FloodState:
        clear = jnp.zeros((self.n,), bool)
        return st._replace(
            have=st.have.at[:, slot].set(clear).at[src, slot].set(True),
            fresh=st.fresh.at[:, slot].set(clear).at[src, slot].set(True),
            first_step=st.first_step.at[:, slot].set(-1).at[src, slot].set(st.step),
            msg_valid=st.msg_valid.at[slot].set(valid),
            msg_birth=st.msg_birth.at[slot].set(st.step),
            msg_used=st.msg_used.at[slot].set(True),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: FloodState) -> FloodState:
        """Flood round: every peer relays last round's receipts on ALL edges."""
        n = self.n
        j = jnp.clip(st.nbrs, 0, n - 1)
        edge_ok = st.nbr_valid & safe_gather(st.alive, st.nbrs, False)
        arrived = (edge_ok[:, :, None] & st.fresh[j]).any(axis=1)
        new = arrived & ~st.have & st.alive[:, None]
        return st._replace(
            have=st.have | (new & st.msg_valid[None, :]),
            fresh=new & st.msg_valid[None, :],
            first_step=jnp.where(new & (st.first_step < 0), st.step, st.first_step),
            step=st.step + 1,
        )

    @functools.partial(jax.jit, static_argnames=("self", "n_steps"))
    def run(self, st: FloodState, n_steps: int) -> FloodState:
        def body(s, _):
            return self.step(s), None

        st, _ = jax.lax.scan(body, st, None, length=n_steps)
        return st

    @functools.partial(jax.jit, static_argnums=0)
    def delivery_stats(self, st: FloodState) -> Tuple[jax.Array, jax.Array]:
        """Delivery fraction + p50 latency over published VALID messages only
        (invalid messages stamp first_step at receive-and-reject time and must
        not pollute the latency median — same masking as GossipSub's stats)."""
        alive_n = jnp.maximum(st.alive.sum(), 1)
        counted = st.msg_used & st.msg_valid
        frac = jnp.where(
            counted, (st.have & st.alive[:, None]).sum(axis=0) / alive_n, jnp.nan
        )
        lat = jnp.where(
            (st.first_step >= 0) & counted[None, :],
            (st.first_step - st.msg_birth[None, :]).astype(jnp.float32),
            jnp.nan,
        )
        return frac, jnp.nanmedian(lat)
