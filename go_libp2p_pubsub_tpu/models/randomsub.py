"""RandomSub — gossip-by-sampling, the third upstream router family.

go-libp2p-pubsub ships three routers (FloodSub, RandomSub, GossipSub);
RandomSub forwards each message to a RANDOM sample of connected topic peers
instead of all of them (FloodSub) or a maintained mesh (GossipSub).  The
upstream sample size is ``max(D, sqrt(topic size))`` per emission.  The v0
reference has none of this (SURVEY.md §0); the model completes the router
family the way FloodSub/GossipSub do — same adjacency form, array-native.

Array formulation: each round, every peer draws a fresh keyed sample of
``emit`` connection slots (``top_mask`` over uniform noise, the same device
pattern as the gossip emission mask) and relays last round's receipts over
exactly those edges.  The choice is formulated TARGET-SIDE through the
reverse index (``chosen[nbrs[i,s], rev[i,s]]``) so the hot loop is a gather,
which partitions under GSPMD like the GossipSub kernels.

Probabilistic delivery: with sample size ~sqrt(N) the epidemic still
completes with high probability but with a longer tail than flooding —
exactly the upstream trade (bandwidth vs latency), pinned by the tests.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.graphs import decode_index_plane, safe_gather, top_mask
from .floodsub import FloodSub
from .gossipsub import build_topology


class RandomSubState(NamedTuple):
    nbrs: jax.Array        # i32[N, K]
    rev: jax.Array         # i32[N, K]
    nbr_valid: jax.Array   # bool[N, K]
    alive: jax.Array       # bool[N]
    have: jax.Array        # bool[N, M]
    fresh: jax.Array       # bool[N, M]
    first_step: jax.Array  # i32[N, M]
    msg_valid: jax.Array   # bool[M]
    msg_birth: jax.Array   # i32[M]
    msg_used: jax.Array    # bool[M]
    key: jax.Array         # PRNG key (per-round sample draws)
    step: jax.Array


class RandomSub(FloodSub):
    """RandomSub router: per-round random-sample relay.

    Subclasses :class:`FloodSub` and inherits its ``publish``, ``run``, and
    ``delivery_stats`` verbatim (same slot-recycle and stats-masking rules,
    one definition); only the construction (rev + PRNG state) and the relay
    step (sampled instead of dense) differ.

    ``d`` is the upstream ``RandomSubD`` floor; the per-round emission is
    ``max(d, ceil(sqrt(n_peers)))`` capped by the slot count — the upstream
    ``max(D, sqrt(topic size))`` rule with the topic assumed network-wide
    (subscription masking composes the same way as FloodSub's liveness).
    """

    def __init__(self, n_peers: int = 1024, n_slots: int = 32,
                 conn_degree: int = 16, msg_window: int = 128,
                 d: int = 6, emit: Optional[int] = None):
        self.n, self.k, self.m = n_peers, n_slots, msg_window
        self.conn_degree = conn_degree
        self.emit = (
            min(max(d, math.isqrt(n_peers - 1) + 1), n_slots)
            if emit is None else min(emit, n_slots)
        )

    def init(self, seed: int = 0) -> RandomSubState:
        rng = np.random.default_rng(seed)
        nbrs, rev, valid, _ = build_topology(
            rng, self.n, self.k, self.conn_degree
        )
        n, m = self.n, self.m
        # Builders return narrow wrap-encoded planes (r22); this model keeps
        # the legacy signed form — decode restores the -1 sentinel.
        return RandomSubState(
            nbrs=jnp.asarray(decode_index_plane(nbrs), jnp.int32),
            rev=jnp.asarray(decode_index_plane(rev), jnp.int32),
            nbr_valid=jnp.asarray(valid),
            alive=jnp.ones((n,), bool),
            have=jnp.zeros((n, m), bool),
            fresh=jnp.zeros((n, m), bool),
            first_step=jnp.full((n, m), -1, jnp.int32),
            msg_valid=jnp.zeros((m,), bool),
            msg_birth=jnp.zeros((m,), jnp.int32),
            msg_used=jnp.zeros((m,), bool),
            key=jax.random.PRNGKey(seed),
            step=jnp.asarray(0, jnp.int32),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def kill_peers(self, st: RandomSubState, mask) -> RandomSubState:
        return st._replace(alive=st.alive & ~mask)

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: RandomSubState) -> RandomSubState:
        """One round: every peer relays last round's receipts to a FRESH
        random sample of ``emit`` live connections (upstream RandomSub
        re-samples per emission; here per round)."""
        n, k = self.n, self.k
        kdraw, knext = jax.random.split(st.key)
        edge_live = st.nbr_valid & safe_gather(st.alive, st.nbrs, False)
        r = jax.random.uniform(kdraw, (n, k))
        chosen = top_mask(jnp.where(edge_live, r, -jnp.inf), self.emit)
        # Target-side pull: neighbor j = nbrs[i,s] sampled me iff
        # chosen[j, rev[i,s]] (the GSPMD-friendly reverse-index gather).
        jidx = jnp.clip(st.nbrs, 0, n - 1)
        ridx = jnp.clip(st.rev, 0, k - 1)
        towards_me = chosen[jidx, ridx] & edge_live
        arrived = (towards_me[:, :, None] & st.fresh[jidx]).any(axis=1)
        new = arrived & ~st.have & st.alive[:, None]
        return st._replace(
            have=st.have | (new & st.msg_valid[None, :]),
            fresh=new & st.msg_valid[None, :],
            first_step=jnp.where(
                new & (st.first_step < 0), st.step, st.first_step
            ),
            key=knext,
            step=st.step + 1,
        )
