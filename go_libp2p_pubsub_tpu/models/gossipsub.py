"""GossipSub — the scalable mesh model (north-star flagship for scale).

A device-resident GossipSub v1.1-shaped simulator: static neighbor-slot
adjacency, mesh overlay maintained by heartbeat kernels, eager push + lazy
IHAVE/IWANT gossip, full peer-score state updated by delivery attribution.
This is the model behind BASELINE.json configs (b) 1k-peer D=6 heartbeat sim,
(d) scoring under attack traces, and (e) the 100k-peer ICI-sharded epidemic
sim (see ``parallel/``).

The v0 reference contains none of this (SURVEY.md §0) — it is the capability
envelope the framework grows into; the protocol rules follow the public
GossipSub spec, with the simplifications documented in ``ops/gossip.py``.

Message windows are **bit-packed** (``ops/bitpack.py``): possession, fresh,
and gossip-pending state are uint32 words, so the propagate hot loop moves
32x less HBM traffic than the bool-tensor form — the difference between 1k
and 100k peers fitting on one chip.  ``ops/gossip.py`` keeps the unpacked
reference kernels the packed path is equivalence-tested against.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import GossipSubParams, ScoreParams
from ..ops import bitpack
from ..ops import gossip_packed as gossip_ops
from ..ops import histogram as hist_ops
from ..ops import scoring as scoring_ops
from ..ops.gossip import heartbeat_mesh, uniform_by_uid
from ..ops.graphs import (
    decode_index_plane,
    encode_index_plane,
    index_dtype,
    safe_gather,
    top_mask,
)
from ..ops.px import px_rewire
from ..ops.scoring import GlobalCounters, TopicCounters

# Flight-recorder latency histogram width (rounds).  One bin per round of
# latency with the tail clipped into the last bin: quantiles from the
# histogram match nanpercentile over raw latencies exactly while the rollout
# is shorter than this (see ops/histogram.py).
FLIGHT_HIST_BINS = 32


class GossipState(NamedTuple):
    """Single-topic mesh state.  N peers, K neighbor slots, M message window
    (stored packed: W = ceil(M/32) uint32 words per peer).

    Multi-topic operation stacks these via ``jax.vmap`` (topology shared,
    mesh/counters per topic); global score counters live outside the vmap.
    """

    nbrs: jax.Array         # [N, K] connection slots -> remote peer id, in
                            # the model's narrow index dtype (uint16 for
                            # N <= 65534, else i32; ops.graphs.index_dtype).
                            # -1 (no connection) is wrap-encoded in unsigned
                            # storage; kernels consume the widened int32 view
    rev: jax.Array          # [N, K] remote's slot index back to me, in
                            # index_dtype(K) (uint16 at any realistic K)
    nbr_valid: jax.Array    # bool[N, K]
    outbound: jax.Array     # bool[N, K] I dialed this edge (v1.1 d_out quota)
    alive: jax.Array        # bool[N]
    subscribed: jax.Array   # bool[N] topic membership (mesh/relay eligibility)
    edge_live: jax.Array    # bool[N, K] nbr_valid & alive[nbrs] — cached so
                            # the per-step hot loops never re-gather liveness
                            # (recomputed only at init / kill_peers / PX)
    nbr_sub: jax.Array      # bool[N, K] cached subscribed[nbrs] (recomputed
                            # at subscription events / PX only)
    mesh: jax.Array         # bool[N, K] symmetric mesh membership
    fanout: jax.Array       # bool[N, K] fanout peers of a non-subscribed
                            # publisher (spec's fanout map; see publish)
    fanout_age: jax.Array   # i32[N] heartbeats since last fanout publish
    backoff: jax.Array      # i32[N, K] prune-backoff heartbeats remaining
    counters: TopicCounters     # per-slot topic score counters
    gcounters: GlobalCounters   # per-peer global score inputs
    scores: jax.Array       # f32[N, K] cached neighbor scores (last heartbeat)
    have_w: jax.Array       # u32[N, W] possession (seen-cache within window)
    fresh_w: jax.Array      # u32[N, W] first-received last round
    gossip_pend_w: jax.Array  # u32[N, W] offers/transfers landing next round
    iwant_pend_w: jax.Array   # u32[N, W] IWANT transfers granted at the last
                              # heartbeat, landing in two rounds (the IHAVE ->
                              # IWANT -> transfer wire hops); moves into
                              # gossip_pend_w at the next propagate
    gossip_mute: jax.Array  # bool[N] peers that advertise but never serve
                            # IWANTs (promise-breaking adversary model; their
                            # refusals charge P7)
    self_promo: jax.Array   # bool[N] peers whose IHAVEs advertise only ids
                            # they ORIGINATED (crafted self-promotion
                            # gossip; see _heartbeat's advertise restriction)
    gossip_delay: jax.Array  # i32[N] ingress link latency: extra rounds a
                             # peer's pending gossip/flood transfers wait
                             # before folding into receipts (the per-edge
                             # delay model mirrored into the pend fold;
                             # 0 = ideal fabric)
    pend_hold: jax.Array     # i32[N] countdown until the pend fold is ready
    edge_delay: jax.Array    # i32[N, K] per-edge EAGER-path ingress latency:
                             # extra rounds a copy spends crossing the edge
                             # from nbrs[i, s] into i (the tree fabric's
                             # edge_delay twin for the mesh plane; 0 = ideal)
    fresh_hist: jax.Array    # u32[N, D, W] rolling history of each peer's
                             # fresh planes (D = max_edge_delay + 1); a
                             # delay-d edge reads its sender's plane from d
                             # rounds back.  D == 0 (max_edge_delay == 0)
                             # disables the machinery entirely
    first_step: jax.Array   # i32[N, M] first-receipt step, -1 = never
    msg_valid: jax.Array    # bool[M] validation verdict
    msg_birth: jax.Array    # i32[M] publish step
    msg_active: jax.Array   # bool[M] within the mcache/gossip window
    msg_used: jax.Array     # bool[M] ever published (persists until slot reuse)
    key: jax.Array          # PRNG key
    step: jax.Array         # i32


def build_topology(
    rng: np.random.Generator, n: int, k: int, degree: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Random ~degree-regular undirected graph in neighbor-slot form.

    Host-side one-time setup (the analog of the test fixtures' full-mesh
    ``connectUp``, ``pubsub_test.go:37-57``, but sparse).  Returns
    (nbrs, rev, nbr_valid, outbound); ``outbound[i, s]`` marks the dialing
    side of each edge (the first element of the pairing dials) — the v1.1
    ``d_out`` quota's notion of a connection I opened myself.

    Index planes come back in the narrowest storage dtype for their value
    domain (``ops.graphs.index_dtype``: uint16 for n <= 65534) with the -1
    invalid marker wrap-encoded; ``decode_index_plane`` restores the signed
    view.  The RNG draw order is dtype-independent, so a narrow topology is
    value-identical to the legacy int64 one.
    """
    if degree >= k:
        raise ValueError(f"degree ({degree}) must be < slot count k ({k})")
    nbrs = np.full((n, k), -1, np.int64)
    rev = np.full((n, k), -1, np.int64)
    outbound = np.zeros((n, k), bool)
    used = np.zeros(n, np.int64)
    adj = [set() for _ in range(n)]
    # Union of `degree` random perfect-matching-ish pairings.
    for _ in range(degree):
        perm = rng.permutation(n)
        for a in range(0, n - 1, 2):
            i, j = int(perm[a]), int(perm[a + 1])
            if j in adj[i] or used[i] >= k or used[j] >= k:
                continue
            si, sj = used[i], used[j]
            nbrs[i, si], nbrs[j, sj] = j, i
            rev[i, si], rev[j, sj] = sj, si
            outbound[i, si] = True  # i dialed j
            adj[i].add(j)
            adj[j].add(i)
            used[i] += 1
            used[j] += 1
    return (
        encode_index_plane(nbrs, n),
        encode_index_plane(rev, k),
        nbrs >= 0,
        outbound,
    )


def build_topology_fast(
    rng: np.random.Generator, n: int, k: int, degree: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized topology builder for large N (100k peers in ~100 ms where
    the per-edge Python loop of ``build_topology`` takes minutes).

    Same construction idea — union of ``degree`` random pairings — but each
    pairing is admitted with NumPy set-ops instead of per-edge Python.
    Duplicate edges across rounds are dropped (slightly lower mean degree,
    same as the loop version's skip rule).  Returns
    (nbrs, rev, nbr_valid, outbound); the dialing side of each edge is drawn
    uniformly at random.
    """
    if degree >= k:
        raise ValueError(f"degree ({degree}) must be < slot count k ({k})")
    if degree == 0:
        empty = np.full((n, k), -1, np.int64)
        return (
            encode_index_plane(empty, n),
            encode_index_plane(empty, k),
            empty >= 0,
            np.zeros((n, k), bool),
        )
    pairs = []
    for _ in range(degree):
        perm = rng.permutation(n).astype(np.int64)
        a, b = perm[0 : n - 1 : 2], perm[1:n:2]
        pairs.append(np.stack([np.minimum(a, b), np.maximum(a, b)], 1))
    e = np.unique(np.concatenate(pairs, 0), axis=0)  # dedup undirected edges
    dialer = np.where(
        rng.integers(0, 2, len(e)).astype(bool), e[:, 0], e[:, 1]
    )
    return _assign_slots(e, dialer, n, k)


def _assign_slots(
    e: np.ndarray, dialer: np.ndarray, n: int, k: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deduped undirected edge list -> slot-form (nbrs, rev, nbr_valid,
    outbound).  Shared tail of the vectorized builders: per-endpoint slot
    indices via cumulative counts, edges overflowing k dropped (BOTH
    directions must get a slot), rev back-pointers paired by edge id."""
    # Per-endpoint slot indices via cumulative counts; drop edges overflowing k.
    src = np.concatenate([e[:, 0], e[:, 1]])
    dst = np.concatenate([e[:, 1], e[:, 0]])
    order = np.argsort(src, kind="stable")
    src_s, dst_s = src[order], dst[order]
    counts = np.bincount(src_s, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot_s = np.arange(len(src_s)) - starts[src_s]
    ok_s = slot_s < k
    # An edge survives only if BOTH directions got a slot.
    eid = np.concatenate([np.arange(len(e)), np.arange(len(e))])[order]
    ok_edge = np.ones(len(e), bool)
    np.logical_and.at(ok_edge, eid, ok_s)
    keep = ok_edge[eid]
    src_s, dst_s, slot_s, eid = src_s[keep], dst_s[keep], slot_s[keep], eid[keep]
    # Recompute dense slots after the drop.
    counts = np.bincount(src_s, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot_s = np.arange(len(src_s)) - starts[src_s]
    nbrs = np.full((n, k), -1, np.int64)
    rev = np.full((n, k), -1, np.int64)
    outbound = np.zeros((n, k), bool)
    nbrs[src_s, slot_s] = dst_s
    outbound[src_s, slot_s] = dialer[eid] == src_s
    # rev: my slot back-pointer = the slot my counterpart assigned this edge.
    # Sort by (eid, src): the two directions of each edge become adjacent
    # pairs, and each direction's rev is its pair partner's slot.
    o2 = np.lexsort((src_s, eid))
    rev_sorted = np.empty(len(src_s), np.int64)
    rev_sorted[o2] = slot_s[o2].reshape(-1, 2)[:, ::-1].reshape(-1)
    rev[src_s, slot_s] = rev_sorted
    return (
        encode_index_plane(nbrs, n),
        encode_index_plane(rev, k),
        nbrs >= 0,
        outbound,
    )


def build_topology_local(
    rng: np.random.Generator, n: int, k: int, degree: int,
    spread: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Locality-structured ~degree-regular graph: each peer's edges land
    within ring distance ``spread`` (default n // 32) of it — the model of
    geographic peer clustering real P2P overlays exhibit, where a node's
    connections skew heavily toward its own region.

    The emitted peer ids are RANDOMLY RELABELED inside the builder, so the
    locality is invisible to id order: a sharded runner that wants the cut
    win must genuinely rediscover the clusters (``parallel/placement``).
    Contrast ``build_topology_fast``: a union of uniform pairings is an
    expander with no good balanced partition — locality-aware placement can
    only help on a graph that has locality, and this builder is the
    fixed-seed bench mesh's source of it.

    Dissemination still converges quickly: the uniform [1, spread] ring
    offsets advance an epidemic frontier ~spread peers per round, so the
    graph's effective diameter is ~n / (2 * spread) rounds (~16 at the
    default spread), not the n / (2k) of a nearest-neighbor ring.
    """
    if degree >= k:
        raise ValueError(f"degree ({degree}) must be < slot count k ({k})")
    if degree == 0 or n < 4:
        empty = np.full((n, k), -1, np.int64)
        return (
            encode_index_plane(empty, n),
            encode_index_plane(empty, k),
            empty >= 0,
            np.zeros((n, k), bool),
        )
    if spread is None:
        spread = max(4, n // 32)
    spread = int(min(spread, max(1, n // 2 - 1)))
    # Each peer proposes degree/2 edges (every undirected edge serves two
    # endpoints), at a uniform ring offset in [1, spread], either direction.
    src = np.tile(np.arange(n, dtype=np.int64), degree // 2)
    if degree % 2:
        src = np.concatenate(
            [src, rng.choice(n, n // 2, replace=False).astype(np.int64)]
        )
    delta = rng.integers(1, spread + 1, size=src.shape[0])
    sign = np.where(rng.integers(0, 2, src.shape[0]) > 0, 1, -1)
    dst = (src + delta * sign) % n
    e = np.stack([np.minimum(src, dst), np.maximum(src, dst)], 1)
    e = np.unique(e[src != dst], axis=0)
    # Hide the ring: relabel every id through a random permutation, then
    # re-canonicalize the pairs.  Same-seed runs stay reproducible (one rng).
    sigma = rng.permutation(n).astype(np.int64)
    e = np.sort(np.stack([sigma[e[:, 0]], sigma[e[:, 1]]], 1), axis=1)
    dialer = np.where(
        rng.integers(0, 2, len(e)).astype(bool), e[:, 0], e[:, 1]
    )
    return _assign_slots(e, dialer, n, k)


def compute_edge_live(
    nbr_valid: jax.Array, nbrs: jax.Array, alive: jax.Array
) -> jax.Array:
    """bool[N, K]: slot is wired AND its remote peer is alive.

    Liveness changes only at explicit events (init, kill_peers), so this
    per-element gather runs per event, not per step — at 100k peers a single
    [N, K] gather costs ~25 ms on a v5e chip, which the propagate and
    heartbeat hot loops must not pay every round.

    Accepts both the narrow wrap-encoded storage form and the wide signed
    view (``decode_index_plane`` is the identity on signed input), so every
    liveness-event call site works straight off the stored state.
    """
    return nbr_valid & safe_gather(alive, decode_index_plane(nbrs), False)


def seed_message(
    have_w, fresh_w, gossip_pend_w, iwant_pend_w, first_step,
    msg_valid, msg_birth, msg_active, msg_used,
    src, slot, valid, step, w,
):
    """Window-slot recycle + seed, shared by the single- and multi-topic
    models: clear the slot's bits for ALL peers (slot reuse), then stamp the
    publisher.  Returns the nine updated window leaves in argument order.

    Both pend planes (``gossip_pend_w`` and the heartbeat-granted
    ``iwant_pend_w``) must be cleared too: a stale pending transfer of the
    OLD message in a recycled slot would otherwise turn into a phantom
    delivery of the NEW message — peers would record first receipts for
    bytes they never received.
    """
    bm = bitpack.bit_mask(slot, w)               # u32[W] one-hot
    have_w = have_w & ~bm
    fresh_w = fresh_w & ~bm
    return (
        have_w.at[src].set(have_w[src] | bm),
        fresh_w.at[src].set(fresh_w[src] | bm),
        gossip_pend_w & ~bm,
        iwant_pend_w & ~bm,
        first_step.at[:, slot].set(-1).at[src, slot].set(step),
        msg_valid.at[slot].set(valid),
        msg_birth.at[slot].set(step),
        msg_active.at[slot].set(True),
        msg_used.at[slot].set(True),
    )


class GossipSub:
    """Single-topic GossipSub simulator with static shapes."""

    def __init__(
        self,
        n_peers: int = 1024,
        n_slots: int = 32,
        conn_degree: int = 16,
        msg_window: int = 128,
        params: Optional[GossipSubParams] = None,
        score_params: Optional[ScoreParams] = None,
        heartbeat_steps: int = 8,
        use_pallas: Optional[bool] = None,
        builder=None,
        graft_spammers: Optional[np.ndarray] = None,
        max_edge_delay: int = 0,
        pallas_shard_mesh=None,
        direct_edges: Optional[np.ndarray] = None,
        peer_uid: Optional[np.ndarray] = None,
        split_gather_mesh=None,
        fused_prologue: Optional[bool] = None,
        index_dtype_override=None,
    ):
        self.n = n_peers
        self.k = n_slots
        self.m = msg_window
        self.w = bitpack.n_words(msg_window)
        self.conn_degree = conn_degree
        # Narrow index-plane storage (r22): nbrs (peer ids, sentinel -1)
        # stores in index_dtype(N), rev (slot back-pointers) in
        # index_dtype(K) — uint16 up to 65534 values, halving the dominant
        # O(N*K) resident planes.  Kernels always consume the widened int32
        # view (decode at the jitted boundary), so results are bit-identical
        # to the int32 path; pass ``index_dtype_override=np.int32`` to force
        # the legacy wide storage (the identity tests' reference arm).
        if index_dtype_override is None:
            self.idx_dtype = index_dtype(n_peers)
            self.rev_dtype = index_dtype(n_slots)
        else:
            dt = np.dtype(index_dtype_override)
            if dt.kind == "u" and n_peers + 1 > np.iinfo(dt).max:
                raise ValueError(
                    f"index_dtype_override={dt.name} cannot hold "
                    f"n + 1 = {n_peers + 1} (max {np.iinfo(dt).max})"
                )
            self.idx_dtype = dt
            self.rev_dtype = dt
        self.params = params or GossipSubParams()
        self.score_params = score_params or ScoreParams()
        self.heartbeat_steps = heartbeat_steps
        self.builder = builder  # explicit topology builder (seed pinning)
        # Static ceiling for per-edge eager-path delay (rounds).  0 keeps
        # the ideal-fabric code path byte-for-byte (no history carried);
        # > 0 carries a (max_edge_delay + 1)-plane fresh history per peer.
        if max_edge_delay < 0:
            raise ValueError("max_edge_delay must be >= 0")
        self.max_edge_delay = max_edge_delay
        # Misbehaviour model (attack traces): bool[N] of peers that GRAFT
        # through their own prune-backoff window; their refused attempts
        # accrue the P7 behaviour penalty each heartbeat.  Constructor-bound
        # (not mutable state) so the jit cache never sees it change.
        self.graft_spammers = (
            None if graft_spammers is None else jnp.asarray(graft_spammers)
        )
        # Direct (explicit) peering, go-gossipsub's WithDirectPeers: a
        # constructor-bound symmetric bool[N, K] slot mask of operator-
        # configured always-forward edges.  Direct edges relay every round
        # regardless of mesh membership or the remote's score (their RPCs
        # bypass the graylist gate, as in go), and they are EXCLUDED from
        # mesh maintenance — never grafted, pruned, or backoff-tracked.
        # Model simplification (documented deviation): copies arriving over
        # direct edges still feed the per-slot delivery counters.
        if direct_edges is None:
            self.direct_edges = None
        else:
            de = np.asarray(direct_edges, bool)
            if de.shape != (n_peers, n_slots):
                raise ValueError(
                    f"direct_edges must be [N={n_peers}, K={n_slots}]"
                )
            self.direct_edges = jnp.asarray(de)
        # Pallas fast path.  A bare pallas_call does not partition under
        # GSPMD, so the sharded runner historically forced use_pallas=False;
        # passing ``pallas_shard_mesh`` (a jax.sharding.Mesh with a "peers"
        # axis) instead routes the round through the shard_map-wrapped
        # kernel (ops/pallas_gossip.propagate_packed_pallas_sharded), which
        # all-gathers the fresh table over ICI and runs the fused kernel on
        # each device's peer block.  Mosaic lowering is TPU-only, so other
        # backends auto-pick the jnp path; explicit True off-TPU runs the
        # kernel in the Pallas interpreter (slow; test path).
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self.use_pallas = use_pallas
        self.pallas_shard_mesh = pallas_shard_mesh
        # Canonical-id vector for placement-relabeled runs
        # (``parallel/placement``): ``peer_uid[i]`` is physical row i's
        # canonical peer id.  Every per-peer RNG draw routes through it
        # (``ops.gossip.uniform_by_uid``) so the relabeled rollout is
        # bit-identical to the canonical one under the inverse permutation.
        # None (the identity) keeps every kernel byte-for-byte unchanged.
        if peer_uid is None:
            self.peer_uid = None
        else:
            pu = np.asarray(peer_uid)
            if pu.shape != (n_peers,):
                raise ValueError(f"peer_uid must be [N={n_peers}]")
            if not np.array_equal(np.sort(pu), np.arange(n_peers)):
                raise ValueError("peer_uid must be a permutation of 0..N-1")
            self.peer_uid = jnp.asarray(pu, jnp.int32)
        # Split-gather fast path (``ops.gossip_packed.ring_gather_rows``):
        # a Mesh with a "peers" axis routes the jnp packed row gathers
        # through shard-local indexing + an overlapped ppermute ring instead
        # of one monolithic all-shard gather.
        self.split_gather_mesh = split_gather_mesh
        # Fused heartbeat prologue: share ONE clipped (jidx, ridx) pair and
        # ONE slot-pairing bitfield gather across the heartbeat's three
        # prologue kernels (neighbor_scores / heartbeat_mesh / px_rewire)
        # instead of each re-deriving its own — PX's [N, K] score gather
        # rides heartbeat_mesh's existing flags word.  Bit-exact with the
        # unfused chain (asserted leaf-for-leaf in tests); default ON
        # everywhere — it strictly removes work, and the win grows with N
        # on TPU where per-element gathers are latency-bound.
        if fused_prologue is None:
            fused_prologue = True
        self.fused_prologue = bool(fused_prologue)

    # Value semantics for the jit cache: the model is a pure function of
    # its configuration, so two identically-configured instances may share
    # compiled rollouts (``self`` is a static argnum everywhere).  Without
    # this, every ``compile_scenario``/test constructing a fresh model
    # recompiles the full scan body.  Instances carrying non-value extras
    # (a custom topology builder, a shard mesh) fall back to identity —
    # unless the builder declares its own value identity via a hashable
    # ``config_key`` attribute (scenario/realism.py's declarative
    # builders do), in which case two models wired to equally-configured
    # builders still share compiled rollouts.
    def _config_key(self):
        builder_key = getattr(self.builder, "config_key", None)
        if (
            (self.builder is not None and builder_key is None)
            or self.pallas_shard_mesh is not None
            or self.split_gather_mesh is not None
        ):
            return id(self)
        return (
            builder_key,
            type(self), self.n, self.k, self.m, self.conn_degree,
            self.params, self.score_params, self.heartbeat_steps,
            self.use_pallas, self.max_edge_delay, self.fused_prologue,
            str(self.idx_dtype), str(self.rev_dtype),
            None if self.graft_spammers is None
            else bytes(np.asarray(self.graft_spammers)),
            None if self.direct_edges is None
            else bytes(np.packbits(np.asarray(self.direct_edges))),
            None if self.peer_uid is None
            else bytes(np.asarray(self.peer_uid)),
        )

    def __eq__(self, other):
        return (
            type(other) is type(self)
            and self._config_key() == other._config_key()
        )

    def __hash__(self):
        return hash(self._config_key())

    def build_graph(self, seed: int = 0):
        """Connection topology only -> (nbrs, rev, nbr_valid, outbound) as
        jnp arrays.

        The loop builder is exact for small N; the vectorized one scales —
        crossing the 4096-peer threshold changes which builder (and which
        rng draw order) generates the topology, so the same seed yields a
        DIFFERENT graph on each side of it (documented seed-compatibility
        break; pass ``builder=`` to pin one explicitly).
        """
        rng = np.random.default_rng(seed)
        builder = self.builder or (
            build_topology if self.n <= 4096 else build_topology_fast
        )
        nbrs, rev, valid, outbound = builder(rng, self.n, self.k, self.conn_degree)
        # encode accepts both builder forms (narrow wrap-encoded or legacy
        # signed) and re-encodes into THIS model's storage dtype, validating
        # the id range rather than wrapping silently.
        return (
            jnp.asarray(encode_index_plane(nbrs, self.n, dtype=self.idx_dtype)),
            jnp.asarray(encode_index_plane(rev, self.k, dtype=self.rev_dtype)),
            jnp.asarray(valid),
            jnp.asarray(outbound),
        )

    def init(
        self, seed: int = 0, subscribed: Optional[np.ndarray] = None
    ) -> GossipState:
        """Fresh state; ``subscribed`` masks topic membership (default: all
        peers subscribed — non-members neither mesh nor relay, and publish
        via fanout/flood)."""
        nbrs, rev, valid, outbound = self.build_graph(seed)
        n, k, m, w = self.n, self.k, self.m, self.w
        if self.direct_edges is not None:
            # Direct peering is mutual (both operators configure it): the
            # mask must sit on wired slots and be symmetric over the pairing.
            de = np.asarray(self.direct_edges)
            nv = np.asarray(valid)
            if (de & ~nv).any():
                raise ValueError("direct_edges marks an unwired slot")
            jn = np.clip(decode_index_plane(np.asarray(nbrs)), 0, n - 1)
            rv = np.clip(decode_index_plane(np.asarray(rev)), 0, k - 1)
            if (de != (de[jn, rv] & nv)).any():
                raise ValueError(
                    "direct_edges must be symmetric over the slot pairing"
                )
        alive0 = jnp.ones((n,), bool)
        sub0 = (
            jnp.ones((n,), bool) if subscribed is None else jnp.asarray(subscribed)
        )
        st = GossipState(
            nbrs=nbrs,
            rev=rev,
            nbr_valid=valid,
            outbound=outbound,
            alive=alive0,
            subscribed=sub0,
            edge_live=compute_edge_live(valid, nbrs, alive0),
            nbr_sub=valid & safe_gather(sub0, decode_index_plane(nbrs), False),
            mesh=jnp.zeros((n, k), bool),
            fanout=jnp.zeros((n, k), bool),
            fanout_age=jnp.full((n,), jnp.iinfo(jnp.int32).max // 2, jnp.int32),
            backoff=jnp.zeros((n, k), jnp.int32),
            counters=TopicCounters.zeros(n, k),
            # Default colocation groups are identity labels (one group per
            # peer); under a placement relabeling the label must follow the
            # CANONICAL identity, not the physical row, for the relabeled
            # rollout to stay bit-identical (values are compared by group
            # membership only, so unique-per-peer semantics are unchanged).
            gcounters=(
                GlobalCounters.zeros(n) if self.peer_uid is None
                else GlobalCounters.zeros(n)._replace(ip_group=self.peer_uid)
            ),
            scores=jnp.zeros((n, k), jnp.float32),
            have_w=jnp.zeros((n, w), jnp.uint32),
            fresh_w=jnp.zeros((n, w), jnp.uint32),
            gossip_pend_w=jnp.zeros((n, w), jnp.uint32),
            iwant_pend_w=jnp.zeros((n, w), jnp.uint32),
            gossip_mute=jnp.zeros((n,), bool),
            self_promo=jnp.zeros((n,), bool),
            gossip_delay=jnp.zeros((n,), jnp.int32),
            pend_hold=jnp.zeros((n,), jnp.int32),
            edge_delay=jnp.zeros((n, k), jnp.int32),
            fresh_hist=jnp.zeros(
                (n, self.max_edge_delay + 1 if self.max_edge_delay else 0, w),
                jnp.uint32,
            ),
            first_step=jnp.full((n, m), -1, jnp.int32),
            msg_valid=jnp.zeros((m,), bool),
            msg_birth=jnp.zeros((m,), jnp.int32),
            msg_active=jnp.zeros((m,), bool),
            msg_used=jnp.zeros((m,), bool),
            key=jax.random.PRNGKey(seed),
            step=jnp.asarray(0, jnp.int32),
        )
        # Converge the mesh before traffic: a few warmup heartbeats.
        return self._warmup(st)

    # -- narrow index storage <-> wide kernel view --------------------------

    def _has_narrow_indices(self) -> bool:
        return self.idx_dtype.kind == "u" or self.rev_dtype.kind == "u"

    def _widen_indices(self, st: GossipState) -> GossipState:
        """Narrow-storage state -> the wide int32 view every internal kernel
        (``_propagate`` / ``_heartbeat`` / the packed and Pallas paths)
        consumes.  On the legacy int32 path this is the identity, so the
        interior compute graph is byte-for-byte today's — the bit-identity
        guarantee of the narrow storage reduces to decode/encode round-trip
        correctness at the boundary."""
        if not self._has_narrow_indices():
            return st
        return st._replace(
            nbrs=decode_index_plane(st.nbrs),
            rev=decode_index_plane(st.rev),
        )

    def _narrow_indices(self, st: GossipState) -> GossipState:
        """Wide int32 view -> narrow storage at the jitted exit.  Values are
        in [-1, n-1] by construction inside the kernels, so the plain cast's
        two's-complement wrap of -1 is exactly the encode."""
        if not self._has_narrow_indices():
            return st
        return st._replace(
            nbrs=st.nbrs.astype(self.idx_dtype),
            rev=st.rev.astype(self.rev_dtype),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def _warmup(self, st: GossipState) -> GossipState:
        st = self._widen_indices(st)
        st = self._heartbeat(self._heartbeat(self._heartbeat(st)))
        return self._narrow_indices(st)

    # -- views --------------------------------------------------------------

    def have_bool(self, st: GossipState) -> jax.Array:
        """Unpacked possession view bool[N, M] (tests / metrics)."""
        return bitpack.unpack(st.have_w, self.m)

    # -- events -------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def publish(
        self,
        st: GossipState,
        src: jax.Array,
        slot: jax.Array,
        valid: jax.Array,
    ) -> GossipState:
        """Seed a message at ``src`` in window ``slot`` (recycling the slot).

        ``valid=False`` publishes a message that will fail validation at
        every receiver — the attack-trace injection point (the reference's
        missing signature hole, ``pubsub.go:117``, made explicit).

        First-hop fan-out (spec rules, both reading ``publish_threshold``):

        - ``flood_publish=True``: the message is offered to ALL connected
          topic peers scoring at least ``publish_threshold`` (landing next
          round via the pend fold), alongside normal mesh relay;
        - ``flood_publish=False`` and ``src`` not subscribed: the publisher
          maintains a ``fanout`` set of up to D above-threshold topic peers
          (refreshed here and aged out by ``fanout_ttl_s`` at heartbeats)
          and offers to those — a non-member publisher has no mesh, so
          fanout is its only first hop.

        Flood/fanout copies carry no per-slot attribution, so they earn no
        P2/P3 delivery credit (and invalid messages never flood: they exist
        only on the eager path where P4 blame can land on a slot).
        """
        p, sp = self.params, self.score_params
        n, k = self.n, self.k
        (have_w, fresh_w, pend_w, iwant_pend_w, first_step,
         mv, mb, ma, mu) = seed_message(
            st.have_w, st.fresh_w, st.gossip_pend_w, st.iwant_pend_w,
            st.first_step, st.msg_valid, st.msg_birth, st.msg_active,
            st.msg_used, src, slot, valid, st.step, self.w,
        )
        kpub, knext = jax.random.split(st.key)
        scores_src = st.scores[src]                              # f32[K]
        eligible = (
            st.edge_live[src]
            & st.nbr_sub[src]
            & (scores_src >= sp.publish_threshold)
        )
        # Direct peers are covered by the unconditional always-forward path;
        # go's Publish never selects them into flood/fanout targets.
        if self.direct_edges is not None:
            eligible = eligible & ~self.direct_edges[src]
        fanout, fanout_age = st.fanout, st.fanout_age
        if p.flood_publish:
            targets = eligible
        else:
            # Fanout top-up to D for a non-subscribed publisher.
            cur = st.fanout[src] & eligible
            want = jnp.clip(p.d - cur.sum(), 0, p.d).astype(jnp.int32)
            r = jax.random.uniform(kpub, (1, k))
            add = top_mask(
                jnp.where((eligible & ~cur)[None, :], r, -jnp.inf),
                want[None],
                kmax=p.d,
            )[0]
            newf = cur | add
            is_sub = st.subscribed[src]
            targets = jnp.where(is_sub, jnp.zeros((k,), bool), newf)
            fanout = st.fanout.at[src].set(
                jnp.where(is_sub, st.fanout[src], newf)
            )
            fanout_age = st.fanout_age.at[src].set(
                jnp.where(is_sub, st.fanout_age[src], 0)
            )
        # Offered copies land next round through the pend fold (one hop of
        # latency, like any send).  Valid-only: see docstring.  A receiver
        # with ingress latency arms its hold now — but only if no hold is
        # already counting (bits arriving mid-hold join the in-flight batch;
        # re-arming would let sustained traffic defer the fold forever) and
        # only when a bit was actually placed (``valid`` — an invalid
        # publish must not touch victims' receive latency).
        bm = bitpack.bit_mask(slot, self.w)                      # u32[W]
        rows = jnp.where(targets, decode_index_plane(st.nbrs[src]), n)
        rows_c = jnp.clip(rows, 0, n - 1)
        gathered = pend_w[rows_c]                                # u32[K, W]
        upd = gathered | jnp.where(valid, bm, jnp.uint32(0))[None, :]
        pend_w = pend_w.at[rows].set(upd, mode="drop")
        # Arm only on an idle, EMPTY row: a row whose hold just expired still
        # carries a batch due to fold next round — arming again would defer
        # that due traffic by a fresh delay (the new bit instead joins the
        # due batch and lands early, the lesser distortion).
        cur_hold = st.pend_hold[rows_c]
        arm = valid & (cur_hold <= 0) & (gathered == 0).all(axis=-1)
        pend_hold = st.pend_hold.at[rows].set(
            jnp.where(arm, st.gossip_delay[rows_c], cur_hold), mode="drop"
        )
        # Per-edge delay mode: the fresh history must mirror every fresh_w
        # mutation — scrub the recycled slot from ALL planes (a stale plane
        # bit would turn into a phantom delayed delivery of the NEW message)
        # and stamp the publisher's bit into the CURRENT plane (the one
        # delay-0 edges read next round), exactly as fresh_w itself got it.
        fresh_hist = st.fresh_hist
        if self.max_edge_delay:
            dpl = self.max_edge_delay + 1
            cur = jnp.mod(st.step - 1, dpl)
            fresh_hist = fresh_hist & ~bm[None, None, :]
            row = jax.lax.dynamic_index_in_dim(
                fresh_hist[src], cur, axis=0, keepdims=False
            )
            # Unconditional like seed_message's fresh_w stamp (an invalid
            # publish relays on the eager path so P4 blame can land).
            fresh_hist = fresh_hist.at[src, cur].set(row | bm)
        return st._replace(
            have_w=have_w, fresh_w=fresh_w, gossip_pend_w=pend_w,
            iwant_pend_w=iwant_pend_w, pend_hold=pend_hold,
            fresh_hist=fresh_hist,
            first_step=first_step, msg_valid=mv,
            msg_birth=mb, msg_active=ma, msg_used=mu, fanout=fanout,
            fanout_age=fanout_age, key=knext,
        )

    @functools.partial(jax.jit, static_argnums=0)
    def kill_peers(self, st: GossipState, mask: jax.Array) -> GossipState:
        """Abrupt peer failure (liveness mask); the mesh self-heals at the
        next heartbeat — the fault-injection hook of the sim."""
        alive = st.alive & ~mask
        return st._replace(
            alive=alive,
            edge_live=compute_edge_live(st.nbr_valid, st.nbrs, alive),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def set_gossip_delay(self, st: GossipState, delay: jax.Array) -> GossipState:
        """Install per-peer ingress gossip latency (i32[N] extra rounds a
        peer's pending gossip/flood transfers wait before folding into its
        receipts).  The pend-fold mirror of the tree fabric's per-edge
        ``set_link_profile`` delay (SURVEY §2.3); zeros restore the ideal
        one-round fabric."""
        return st._replace(gossip_delay=delay.astype(jnp.int32))

    def set_edge_delay(self, st: GossipState, delay) -> GossipState:
        """Install per-edge EAGER-path ingress latency (i32[N, K]: extra
        rounds a copy spends crossing the edge from ``nbrs[i, s]`` into i).

        The mesh-plane twin of the tree fabric's ``set_link_profile`` delay
        (SURVEY §2.3, the mocknet analog): quantized to whole rounds,
        addressed by the RECEIVER's slot so repair/PX rewiring changes which
        peer sits behind a link, not the link's latency.  Requires the model
        to be built with ``max_edge_delay >= max(delay)`` (the history depth
        is a compile-time shape); zeros restore the ideal fabric.
        """
        delay = np.asarray(delay)
        if delay.max(initial=0) > self.max_edge_delay:
            raise ValueError(
                f"edge delay {int(delay.max())} exceeds this model's "
                f"max_edge_delay={self.max_edge_delay}; rebuild the model "
                f"with a larger ceiling"
            )
        if delay.min(initial=0) < 0:
            raise ValueError("edge delays must be >= 0")
        return st._replace(edge_delay=jnp.asarray(delay, jnp.int32))

    @functools.partial(jax.jit, static_argnums=0)
    def set_gossip_mute(self, st: GossipState, mask: jax.Array) -> GossipState:
        """Mark peers (bool[N]) as promise-breakers: they keep advertising
        IHAVEs but never serve the resulting IWANTs.  Every ask directed at
        them is counted as a broken promise and charged to their P7
        behaviour penalty at the heartbeat — the adversary model of the
        gossip-spam attack trace (the spec's gossip promise tracking)."""
        return st._replace(gossip_mute=mask)

    @functools.partial(jax.jit, static_argnums=0)
    def set_self_promo(self, st: GossipState, mask: jax.Array) -> GossipState:
        """Mark peers (bool[N]) as IHAVE self-promoters: their
        advertisements are restricted to messages they themselves
        ORIGINATED (receipt latency 0 — only the publisher is stamped at
        birth step), so they never gossip honest traffic onward.  The
        crafted-IHAVE adversary model of the ``self_promo_ihave`` scenario
        wave; composed with ``gossip_mute`` the asks their self-ads attract
        become broken promises charged to P7."""
        return st._replace(self_promo=mask)

    @functools.partial(jax.jit, static_argnums=0)
    def set_subscribed(self, st: GossipState, sub: jax.Array) -> GossipState:
        """Change topic membership (bool[N]).

        Unsubscribing prunes the peer's mesh edges immediately (the wire
        sends PRUNE on unsubscribe); subscribing drops any fanout state (the
        spec moves fanout peers into the mesh on join — here the next
        heartbeat grafts from scratch, which converges the same way).
        """
        nbr_sub = st.nbr_valid & safe_gather(
            sub, decode_index_plane(st.nbrs), False
        )
        return st._replace(
            subscribed=sub,
            nbr_sub=nbr_sub,
            mesh=st.mesh & sub[:, None] & nbr_sub,
            fanout=st.fanout & ~sub[:, None],
        )

    # -- transition ---------------------------------------------------------

    def seen_ttl_steps(self) -> int:
        """Rounds after which a receipt falls out of the seen-cache dedup."""
        p = self.params
        return (
            max(1, round(p.seen_ttl_s / p.heartbeat_interval_s))
            * self.heartbeat_steps
        )

    def fanout_ttl_heartbeats(self) -> int:
        """Heartbeats of publish silence after which fanout state ages out."""
        p = self.params
        return max(1, round(p.fanout_ttl_s / p.heartbeat_interval_s))

    def gossip_window_masks(self, st: GossipState):
        """(have_scrubbed u32[N, W], gossip_w u32[W]): the seen-TTL-scrubbed
        possession view the IWANT dedups against, and the packed
        advertisable window (valid & active & within history_gossip).
        Shared by ``_heartbeat`` and the bench's phase profiler so the
        profiled masks can never drift from the shipped ones."""
        p = self.params
        seen_expired = st.msg_used & (
            st.step - st.msg_birth > self.seen_ttl_steps()
        )
        have_scrubbed = st.have_w & ~bitpack.pack(seen_expired)
        gossip_age_ok = (
            st.step - st.msg_birth
            <= p.history_gossip * self.heartbeat_steps
        )
        gossip_w = bitpack.pack(st.msg_valid & st.msg_active & gossip_age_ok)
        return have_scrubbed, gossip_w

    def fanout_maintenance(
        self, key, fanout, fanout_age, subscribed, alive, edge_eligible,
        scores,
    ):
        """One heartbeat of fanout upkeep -> (fanout bool[N, K], age i32[N]):
        age out after ``fanout_ttl_s`` of publish silence, drop
        dead/below-threshold peers, top back up to D while active.  Shared
        by ``_heartbeat`` and the bench's phase profiler."""
        p, sp = self.params, self.score_params
        age = jnp.minimum(fanout_age + 1, jnp.iinfo(jnp.int32).max // 2)
        factive = (age <= self.fanout_ttl_heartbeats()) & ~subscribed & alive
        feligible = edge_eligible & (scores >= sp.publish_threshold)
        fkeep = fanout & feligible
        fwant = jnp.where(
            factive, jnp.clip(p.d - fkeep.sum(axis=1), 0, p.d), 0
        ).astype(jnp.int32)
        fadd = top_mask(
            jnp.where(
                feligible & ~fkeep,
                uniform_by_uid(key, (self.n, self.k), self.peer_uid),
                -jnp.inf,
            ),
            fwant,
            kmax=p.d,
        )
        return jnp.where(factive[:, None], fkeep | fadd, False), age

    def _heartbeat(self, st: GossipState) -> GossipState:
        p, sp = self.params, self.score_params
        khb, kgossip, kiwant, kfan, kpx, knext = jax.random.split(st.key, 6)

        # Fused prologue (default): ONE clipped (jidx, ridx) slot-pairing
        # index pair shared by the three prologue kernels below; px_rewire
        # additionally reuses heartbeat_mesh's bitfield gather for its
        # offer gate.  The unfused branch keeps each kernel self-contained
        # and is the bit-exactness reference.
        edge_idx = (
            (jnp.clip(st.nbrs, 0, self.n - 1), jnp.clip(st.rev, 0, self.k - 1))
            if self.fused_prologue else None
        )

        # Advance mesh clocks by one heartbeat interval; decay; re-score.
        c = scoring_ops.tick_mesh_clocks(st.counters, st.mesh, p.heartbeat_interval_s)
        c = scoring_ops.decay_topic_counters(c, sp)
        g = scoring_ops.decay_global_counters(st.gcounters, sp)
        scores = scoring_ops.neighbor_scores(
            c, g, st.nbrs, st.nbr_valid, sp,
            jidx=None if edge_idx is None else edge_idx[0],
        )

        # Topic participation: mesh forms only between alive+subscribed
        # endpoints (the model folds subscription into the liveness view the
        # kernels already symmetrize over).
        part = st.alive & st.subscribed
        edge_ok = st.edge_live & st.nbr_sub
        # Direct edges never join the mesh (go keeps explicit peers outside
        # mesh maintenance entirely) and carry no IHAVE/IWANT traffic —
        # their eager always-forward path covers them.
        if self.direct_edges is not None:
            edge_ok = edge_ok & ~self.direct_edges
        hb_idx = st.step // self.heartbeat_steps
        do_og = (hb_idx % p.opportunistic_graft_ticks) == 0

        hb_out = heartbeat_mesh(
            khb, st.mesh, scores, st.nbrs, st.rev, edge_ok, part, p,
            st.backoff, st.outbound, do_og,
            og_threshold=sp.opportunistic_graft_threshold,
            ignore_backoff=self.graft_spammers,
            uid=self.peer_uid,
            edge_idx=edge_idx,
            with_px_offer=self.fused_prologue,
        )
        new_mesh, grafted, pruned, backoff, bo_violations = hb_out[:5]
        px_offer_ok = hb_out[5] if self.fused_prologue else None
        c = scoring_ops.on_prune(c, pruned, sp)
        c = scoring_ops.on_graft(c, grafted)
        # P7: charge backoff-violating GRAFT attempts to their sender; the
        # squared penalty lands in everyone's view of that peer at the next
        # score refresh.
        g = g._replace(behaviour_penalty=g.behaviour_penalty + bo_violations)

        # Peer exchange on prune (v1.1 PX): pruned peers may open one new
        # connection toward a mesh neighbor of their pruner, gated by
        # accept_px_threshold.  The adjacency caches are regathered only
        # when a PX edge actually formed (rare; lax.cond skips the gathers
        # otherwise).
        px = px_rewire(
            kpx, st.nbrs, st.rev, st.nbr_valid, st.outbound, backoff,
            new_mesh, pruned, scores, st.alive, sp.accept_px_threshold,
            uid=self.peer_uid,
            edge_idx=edge_idx,
            offer_ok=px_offer_ok,
        )
        edge_live, nbr_sub = jax.lax.cond(
            px.connected.any(),
            lambda: (
                compute_edge_live(px.nbr_valid, px.nbrs, st.alive),
                px.nbr_valid & safe_gather(st.subscribed, px.nbrs, False),
            ),
            lambda: (st.edge_live, st.nbr_sub),
        )

        # Seen-cache TTL (applied to have_w below, and to the IWANT dedup so
        # the grant matches what the next round would have computed):
        # receipts older than seen_ttl_s fall out of the dedup window
        # (first_step keeps the delivery record for metrics).
        have_w, gossip_w = self.gossip_window_masks(st)

        # Two-phase IHAVE/IWANT, collapsed at the heartbeat: advertisements
        # are computed per receiving slot, each receiver immediately selects
        # its IWANT asks (one first-advertising slot per wanted id, capped
        # per advertiser), and the granted transfers land TWO propagate
        # rounds later via ``iwant_pend_w`` -> ``gossip_pend_w`` — the same
        # arrival round as the wire's IHAVE -> IWANT -> transfer hops.  The
        # [N, K, W] advertisement cube is TRANSIENT here (never carried in
        # state): at 100k peers it is ~51 MB that the r3 design read and
        # re-zeroed on every propagate round.  Deviation vs computing the
        # IWANT on the next round: offers folded between heartbeat and next
        # round (a publish racing the heartbeat) are not deduped against —
        # the same race an IWANT on the wire loses.
        # An advertiser serves unless it is a promise-breaker (gossip_mute)
        # — death is already excluded by edge_live in the selection.  The
        # receiver ignores IHAVEs from advertisers it scores below
        # gossip_threshold (go's handleIHave gate) and draws the ask target
        # in keyed random slot order, so a low-slot promise-breaker cannot
        # permanently starve ids an honest advertiser also offers.  The
        # fused kernel builds the advertisement cube directly in that
        # priority order (one [N,K,W] gather; bit-exact with the unfused
        # advertise+select pair, which stays as the tested reference).
        serve_ok = ~safe_gather(st.gossip_mute, px.nbrs, True)
        gossip_edges = edge_live & nbr_sub
        if self.direct_edges is not None:
            gossip_edges = gossip_edges & ~self.direct_edges
        # Self-promoters advertise ONLY ids they originated (receipt latency
        # 0 == the publisher's own birth stamp), feeding a restricted
        # advertise-source view into the exchange; the dedup view and the
        # stored possession stay untouched.  cond-gated: honest runs pay one
        # predicate, never the [N, M] origin unpack.
        adv_src = jax.lax.cond(
            st.self_promo.any(),
            lambda: jnp.where(
                st.self_promo[:, None],
                st.have_w & bitpack.pack(
                    (st.first_step == st.msg_birth[None, :])
                    & st.msg_used[None, :]
                ),
                st.have_w,
            ),
            lambda: st.have_w,
        )
        exchange_args = (
            kgossip, kiwant, adv_src, have_w, new_mesh, px.nbrs, px.rev,
            gossip_edges, part, scores, gossip_w, p,
            sp.gossip_threshold, serve_ok, p.max_iwant_length,
        )
        if self.use_pallas:
            from ..ops.pallas_gossip import gossip_exchange_packed_pallas

            # The kernel's XLA prep partitions under GSPMD, so the sharded
            # runner passes its device mesh and the row-local kernel runs
            # under shard_map.
            iwant_pend_w, broken = gossip_exchange_packed_pallas(
                *exchange_args, interpret=jax.default_backend() != "tpu",
                device_mesh=self.pallas_shard_mesh,
                uid=self.peer_uid,
            )
        else:
            iwant_pend_w, broken = gossip_ops.gossip_exchange_packed(
                *exchange_args,
                uid=self.peer_uid,
                device_mesh=self.split_gather_mesh,
            )
        # P7: broken promises charge the ADVERTISER (indexed by remote id).
        promise_ids = jnp.where(
            px.nbr_valid, px.nbrs, self.n
        ).reshape(-1)
        promise_viol = jax.ops.segment_sum(
            broken.reshape(-1), promise_ids, num_segments=self.n + 1
        )[: self.n]
        g = g._replace(behaviour_penalty=g.behaviour_penalty + promise_viol)

        # Fanout maintenance for non-subscribed publishers (direct edges
        # excluded: the always-forward path covers them, so they never
        # occupy one of the D fanout slots — go's getPeers filter).
        fanout_edges = edge_live & nbr_sub
        if self.direct_edges is not None:
            fanout_edges = fanout_edges & ~self.direct_edges
        fanout, age = self.fanout_maintenance(
            kfan, st.fanout, st.fanout_age, st.subscribed, st.alive,
            fanout_edges, scores,
        )

        # Expire messages out of the mcache history window.  (iwant_pend_w
        # needs no strike: the grant was gated by gossip_age_ok, which is
        # strictly narrower than the history window.)
        expired = st.msg_active & (
            st.step - st.msg_birth > p.history_length * self.heartbeat_steps
        )
        dead_w = bitpack.pack(expired)
        return st._replace(
            nbrs=px.nbrs,
            rev=px.rev,
            nbr_valid=px.nbr_valid,
            outbound=px.outbound,
            edge_live=edge_live,
            nbr_sub=nbr_sub,
            mesh=new_mesh,
            fanout=fanout,
            fanout_age=age,
            backoff=px.backoff,
            counters=c,
            gcounters=g,
            scores=scores,
            have_w=have_w,
            gossip_pend_w=st.gossip_pend_w & ~dead_w[None, :],
            # fresh_hist is deliberately NOT scrubbed here: the heartbeat
            # does not touch fresh_w either, and the ideal model relays an
            # expiry-raced fresh bit next round (stamping first_step and
            # charging P4 via valid_w) — the history must mirror fresh_w's
            # mutations exactly or the zero-delay bitwise identity breaks.
            iwant_pend_w=iwant_pend_w,
            msg_active=st.msg_active & ~expired,
            key=knext,
        )

    def _propagate(
        self,
        st: GossipState,
        with_receipts: bool = False,
        eager_edge_ok: Optional[jax.Array] = None,
        ingress_ok: Optional[jax.Array] = None,
    ):
        # Fold due gossip/flood deliveries (granted or offered last round)
        # into this round's receipts.  These copies arrive this round and
        # relay NEXT round (they join fresh_w after the eager push below) —
        # merging them into the relayed set here would move a message two
        # hops in one round, which both breaks wire parity and zeroes the
        # measured hop latency.  A peer with ingress latency (gossip_delay)
        # holds its pending transfers for that many extra rounds before they
        # fold; bits arriving mid-hold join the held batch.
        #
        # Hybrid hooks (models/hybrid.py): ``eager_edge_ok`` bool[N, K]
        # additionally gates which edges eager-push (coded edges suppress
        # eager), ``ingress_ok`` bool[N] is a per-receiver loss gate — a
        # round where it is False drops the peer's ENTIRE data-plane ingress
        # (eager pushes AND the pend fold; dropped pend bits leave the plane
        # and must be re-requested at a later heartbeat).  Control traffic
        # (IHAVE/IWANT) is not subject to the gate.  Both default to None,
        # which leaves this method's graph byte-identical to the pre-hybrid
        # form.
        ready = st.pend_hold <= 0
        ready_w = gossip_ops._as_mask(ready)[:, None]
        gossip_new = (
            st.gossip_pend_w & ready_w & ~st.have_w
            & gossip_ops._as_mask(st.alive)[:, None]
        )
        if ingress_ok is not None:
            gossip_new = gossip_new & gossip_ops._as_mask(ingress_ok)[:, None]
        held_w = st.gossip_pend_w & ~ready_w
        have_w = st.have_w | gossip_new

        # Eager push over the mesh, graylist-gated receiver-side: frames
        # from neighbors scored below graylist_threshold are ignored
        # entirely (ScoreParams.graylist_threshold, the spec's RPC gate).
        relay_mesh = st.mesh & (
            st.scores >= self.score_params.graylist_threshold
        )
        # Direct edges always relay (graylist bypass, mesh-independent);
        # edge_live in the kernel still masks dead remotes.  The gate is the
        # RECEIVER's own subscription (relay_mesh is receiver-indexed — the
        # kernel pulls fresh_w[nbrs[i,s]] into i): go sends to every direct
        # peer in the topic regardless of the sender's own membership.
        if self.direct_edges is not None:
            relay_mesh = relay_mesh | (
                self.direct_edges & st.subscribed[:, None]
            )
        if eager_edge_ok is not None:
            relay_mesh = relay_mesh & eager_edge_ok
        valid_w = bitpack.pack(st.msg_valid & st.msg_active)
        # Per-edge delay mode: each edge reads its sender's fresh plane from
        # edge_delay[i, s] rounds back (plane (step-1-d) mod D of the rolling
        # history) instead of the live fresh_w — one flattened row gather,
        # same cost shape as the ideal fabric's fresh_w[nbrs].
        if self.max_edge_delay:
            dpl = self.max_edge_delay + 1
            jrows = jnp.clip(st.nbrs, 0, self.n - 1)
            plane = jnp.mod(st.step - 1 - st.edge_delay, dpl)
            fresh_src = st.fresh_hist.reshape(self.n * dpl, self.w)[
                jrows * dpl + plane
            ]
        else:
            fresh_src = None
        # IDONTWANT suppression must see the receiver's PRE-FOLD possession
        # (st.have_w): the notifications are one hop old, so a message that
        # folded in via IWANT/flood THIS round races the eager copy and its
        # duplicate still crosses the wire (gossip.propagate's documented
        # one-round-delay semantics).  Under per-edge delay the notification
        # itself would take edge_delay rounds to cross back, which the
        # one-round snapshot cannot represent — suppression is conservatively
        # DISABLED in that mode (duplicates count, never misattributed)
        # rather than crediting senders with knowledge they could not have.
        idontwant = self.params.idontwant and not self.max_edge_delay
        idw = st.have_w if idontwant else None
        if idontwant and self.params.idontwant_wire_lag:
            # Wire-parity snapshot (idontwant_wire_lag): exclude the
            # immediately preceding round's first receipts (fresh_w IS that
            # set) — a notification sent on receipt in round t-1 is still
            # crossing the wire during round t, so the sender cannot have
            # acted on it before emitting this round's copy.
            idw = st.have_w & ~st.fresh_w
        if self.use_pallas and self.pallas_shard_mesh is not None:
            from ..ops.pallas_gossip import propagate_packed_pallas_sharded

            out = propagate_packed_pallas_sharded(
                self.pallas_shard_mesh,
                relay_mesh, st.nbrs, st.edge_live, st.alive, have_w,
                st.fresh_w, valid_w,
                interpret=jax.default_backend() != "tpu",
                fresh_src=fresh_src, idontwant=idontwant,
                idw_have_w=idw,
            )
        elif self.use_pallas:
            from ..ops.pallas_gossip import propagate_packed_pallas

            out = propagate_packed_pallas(
                relay_mesh, st.nbrs, st.edge_live, st.alive, have_w,
                st.fresh_w, valid_w,
                interpret=jax.default_backend() != "tpu",
                fresh_src=fresh_src, idontwant=idontwant,
                idw_have_w=idw,
            )
        else:
            out = gossip_ops.propagate_packed(
                relay_mesh, st.nbrs, st.edge_live, st.alive, have_w,
                st.fresh_w, valid_w, fresh_src=fresh_src,
                idontwant=idontwant, idw_have_w=idw,
                device_mesh=self.split_gather_mesh,
            )
        if ingress_ok is not None:
            # Per-receiver loss gate: a closed receiver's eager arrivals are
            # dropped on the floor — no possession, no fresh relay, and no
            # score credit (the copies never crossed the wire).  ``have_w``
            # going into the kernel already includes the (gated) pend fold,
            # so rebuilding possession from the masked first-receipt set is
            # exact.
            iok_w = gossip_ops._as_mask(ingress_ok)[:, None]
            iok_f = ingress_ok.astype(jnp.float32)[:, None]
            out = gossip_ops.PropagatePackedOut(
                have_w=have_w | (out.new_w & iok_w & valid_w),
                fresh_w=out.fresh_w & iok_w,
                new_w=out.new_w & iok_w,
                fmd_inc=out.fmd_inc * iok_f,
                mmd_inc=out.mmd_inc * iok_f,
                invalid_inc=out.invalid_inc * iok_f,
            )
        # One [N, M] stamping pass for both receipt sources (pend fold +
        # eager push): both record the same step, so the union stamps once.
        stamped = (
            bitpack.unpack(gossip_new | out.new_w, self.m)
            & (st.first_step < 0)
        )
        first_step = jnp.where(stamped, st.step, st.first_step)
        c = st.counters._replace(
            first_message_deliveries=st.counters.first_message_deliveries
            + out.fmd_inc,
            mesh_message_deliveries=st.counters.mesh_message_deliveries
            + out.mmd_inc,
            invalid_message_deliveries=st.counters.invalid_message_deliveries
            + out.invalid_inc,
        )
        # The heartbeat's granted IWANT transfers become next round's pend
        # fold (the second wire hop of the gossip exchange), joining any
        # bits still held by ingress latency.
        pend_next = held_w | st.iwant_pend_w
        incoming = (pend_next != 0).any(axis=1)
        pend_hold = jnp.where(
            ready,
            jnp.where(incoming, st.gossip_delay, 0),
            st.pend_hold - 1,
        )
        new_fresh = out.fresh_w | gossip_new
        fresh_hist = st.fresh_hist
        if self.max_edge_delay:
            # This round's fresh plane enters the rolling history at slot
            # step mod D (the slot delay-0 edges read next round).
            dpl = self.max_edge_delay + 1
            fresh_hist = jax.lax.dynamic_update_slice(
                st.fresh_hist, new_fresh[:, None, :],
                (jnp.int32(0), jnp.mod(st.step, dpl), jnp.int32(0)),
            )
        nxt = st._replace(
            have_w=out.have_w,
            # Pend-fold arrivals relay on the NEXT round (one hop per round).
            fresh_w=new_fresh,
            fresh_hist=fresh_hist,
            first_step=first_step,
            counters=c,
            gossip_pend_w=pend_next,
            iwant_pend_w=jnp.zeros_like(st.iwant_pend_w),
            pend_hold=pend_hold,
        )
        if not with_receipts:
            return nxt
        # Flight-recorder tap: per-message counts of the receipts stamped
        # this round, masked the way the latency histogram counts them.
        # Reusing ``stamped`` here fuses the count into the stamping pass —
        # any re-derivation from the post-step table costs a fresh [N, M]
        # pass per round (see ops.histogram.latency_histogram_increment).
        # The masks are stable inside a round (alive/subscribed/msg_used
        # flip only through the host API, msg_valid only at publish), so
        # pre-step masks equal post-step masks.
        counted = (
            stamped
            & (st.alive & st.subscribed)[:, None]
            & (st.msg_used & st.msg_valid)[None, :]
        )
        return nxt, counted.sum(axis=0, dtype=jnp.int32)

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: GossipState) -> GossipState:
        """One network round: eager-push propagation, plus heartbeat
        maintenance every ``heartbeat_steps`` rounds."""
        st = self._widen_indices(st)
        st = self._propagate(st)
        st = jax.lax.cond(
            (st.step % self.heartbeat_steps) == self.heartbeat_steps - 1,
            self._heartbeat,
            lambda s: s,
            st,
        )
        return self._narrow_indices(st._replace(step=st.step + 1))

    @functools.partial(jax.jit, static_argnums=0)
    def step_recorded(self, st: GossipState):
        """``step`` plus the flight recorder's receipt tap: returns
        ``(next state, i32[M] count of receipts first stamped this round)``.

        The state result is computed by the exact same graph as ``step``
        (the tap only adds a reduction over the stamping mask ``_propagate``
        already builds), so a recorded rollout stays bit-identical to a
        bare one.
        """
        st = self._widen_indices(st)
        st, per_msg = self._propagate(st, with_receipts=True)
        st = jax.lax.cond(
            (st.step % self.heartbeat_steps) == self.heartbeat_steps - 1,
            self._heartbeat,
            lambda s: s,
            st,
        )
        return self._narrow_indices(st._replace(step=st.step + 1)), per_msg

    @functools.partial(jax.jit, static_argnames=("self", "n_steps"))
    def run(self, st: GossipState, n_steps: int) -> GossipState:
        return self.rollout(st, n_steps, record=False)[0]

    @functools.partial(jax.jit, static_argnames=("self", "n_steps", "record"))
    def rollout(self, st: GossipState, n_steps: int, record: bool = True):
        """``n_steps`` rounds -> (final state, flight record | None).

        With ``record=True`` every round emits the compact metrics pytree of
        ``flight_record_round`` as the scan's ``ys`` — each leaf comes back
        stacked with a leading [n_steps] round axis, entirely device-side
        (no host transfer inside the scan; one ``device_get`` of the whole
        record costs ~n_steps * (9 scalars + one i32[FLIGHT_HIST_BINS]
        histogram)).  The cumulative latency histogram rides the scan CARRY:
        seeded once from the full stamp table, then advanced per round by
        the receipts stamped that round (``latency_histogram_increment``) —
        the one-shot [N*M] segment_sum costs about as much as a whole
        propagate round at 16k peers, so recomputing it per round would
        double the rollout (and ``latency_histogram_seed`` skips even the
        one-time scatter on fresh-publish states, where the seed is a
        scalar count of latency-zero publisher stamps).  Peers dead at
        rollout end may therefore still
        have receipts counted (they were alive when stamped) — matching
        what a per-round sampler observes, not a retroactive recount.
        ``record=False`` is the bench's bare rollout: the scan carries no
        histogram and no ys, so the recorder-off path is byte-identical to
        the old ``run``.
        """
        if not record:
            def bare(s, _):
                return self.step(s), None

            return jax.lax.scan(bare, st, None, length=n_steps)

        hist0 = hist_ops.latency_histogram_seed(
            st.first_step, st.msg_birth, st.msg_used & st.msg_valid,
            st.alive & st.subscribed, FLIGHT_HIST_BINS,
        )

        def body(carry, _):
            s, hist = carry
            # step() stamps new receipts with the PRE-increment round
            # counter (s.step == s2.step - 1), so every receipt counted in
            # per_msg shares the latency s.step - msg_birth.
            s2, per_msg = self.step_recorded(s)
            hist = hist + hist_ops.latency_histogram_increment(
                per_msg, s2.msg_birth, s2.msg_used & s2.msg_valid,
                s.step, FLIGHT_HIST_BINS,
            )
            return (s2, hist), self.flight_record_round(s2, hist)

        (final, _), record_ys = jax.lax.scan(
            body, (st, hist0), None, length=n_steps
        )
        return final, record_ys

    # -- scenario engine ----------------------------------------------------

    @staticmethod
    def masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
        """Mean of ``x`` over ``mask`` — NaN (silently) when the mask is
        empty.  The adversary-standing channels' reduction: equal to
        ``nanmean(where(mask, x, nan))`` for finite ``x`` but with the
        empty-set semantics explicit instead of riding numpy's all-NaN
        slice warning path."""
        cnt = mask.sum()
        total = jnp.where(mask, x, 0.0).sum()
        return jnp.where(cnt > 0, total / jnp.maximum(cnt, 1), jnp.nan)

    @staticmethod
    def masked_min(x: jax.Array, mask: jax.Array) -> jax.Array:
        """Min of ``x`` over ``mask`` — NaN (silently) when empty."""
        lo = jnp.where(mask, x, jnp.inf).min()
        return jnp.where(mask.any(), lo, jnp.nan)

    def _apply_events(self, st: GossipState, ev) -> GossipState:
        """Apply one step's slice of a ``GossipEvents`` schedule (scan body;
        every branch is ``lax.cond``-gated so quiet steps pay one predicate
        per event kind, not the event's gathers).

        Order: liveness (kills+revives) -> subscription deltas -> mute
        deltas -> delay sets -> publishes, matching the order the host API
        calls would have been issued between scan segments.  ``silence`` is
        NOT applied here — it acts after the step (see ``rollout_events``).
        """

        def upd_alive(s):
            alive = (s.alive & ~ev.kill) | ev.revive
            return s._replace(
                alive=alive,
                edge_live=compute_edge_live(s.nbr_valid, s.nbrs, alive),
            )

        st = jax.lax.cond(
            ev.kill.any() | ev.revive.any(), upd_alive, lambda s: s, st
        )

        def upd_sub(s):
            # set_subscribed's body inlined on the delta-composed mask.
            sub = (s.subscribed & ~ev.sub_off) | ev.sub_on
            nbr_sub = s.nbr_valid & safe_gather(
                sub, decode_index_plane(s.nbrs), False
            )
            return s._replace(
                subscribed=sub,
                nbr_sub=nbr_sub,
                mesh=s.mesh & sub[:, None] & nbr_sub,
                fanout=s.fanout & ~sub[:, None],
            )

        st = jax.lax.cond(
            ev.sub_off.any() | ev.sub_on.any(), upd_sub, lambda s: s, st
        )
        st = jax.lax.cond(
            ev.mute_on.any() | ev.mute_off.any(),
            lambda s: s._replace(
                gossip_mute=(s.gossip_mute & ~ev.mute_off) | ev.mute_on
            ),
            lambda s: s,
            st,
        )
        st = jax.lax.cond(
            ev.promo_on.any() | ev.promo_off.any(),
            lambda s: s._replace(
                self_promo=(s.self_promo & ~ev.promo_off) | ev.promo_on
            ),
            lambda s: s,
            st,
        )
        st = jax.lax.cond(
            (ev.delay >= 0).any(),
            lambda s: s._replace(
                gossip_delay=jnp.where(ev.delay >= 0, ev.delay, s.gossip_delay)
            ),
            lambda s: s,
            st,
        )
        # Publishes: the per-step budget P is a static shape, so this
        # unrolls into P conditional publish graphs (keep P small — it is
        # the busiest step's need, not the campaign total).
        for i in range(ev.pub_src.shape[0]):
            st = jax.lax.cond(
                ev.pub_src[i] >= 0,
                lambda s, j=i: self.publish(
                    s,
                    ev.pub_src[j],
                    jnp.clip(ev.pub_slot[j], 0, self.m - 1),
                    ev.pub_valid[j],
                ),
                lambda s: s,
                st,
            )
        return st

    def _campaign_record(
        self, st: GossipState, rec, attackers, target: Optional[int]
    ):
        """Extend one round's flight record with adversary-standing channels
        (the in-scan reductions the attack runners assert on)."""
        if attackers is not None:
            att_slot = st.nbr_valid & attackers[
                jnp.clip(decode_index_plane(st.nbrs), 0, self.n - 1)
            ]
            honest = ~attackers & st.alive
            honest_mesh = st.mesh & st.nbr_valid & honest[:, None]
            captured = (st.mesh & att_slot & honest[:, None]).sum()
            rec["attacker_mesh_edges"] = captured.astype(jnp.int32)
            # Mesh-capture ceiling: fraction of honest peers' mesh slots an
            # attacker occupies — the eclipse/sybil SLO channel.
            rec["attacker_capture_frac"] = captured / jnp.maximum(
                honest_mesh.sum(), 1
            )
            # Score-standing channels reduce over explicit masks (NaN when
            # the slice is empty — e.g. an all-False attacker set — rather
            # than numpy's warning-prone all-NaN path; see masked_mean).
            rec["attacker_score_mean"] = self.masked_mean(
                st.scores, att_slot
            )
            rec["honest_score_min"] = self.masked_min(
                st.scores,
                st.nbr_valid & ~att_slot & jnp.isfinite(st.scores),
            )
            rec["attacker_behaviour_penalty"] = (
                st.gcounters.behaviour_penalty.max(
                    where=attackers, initial=0.0
                )
            )
            rec["attacker_global_score"] = self.masked_mean(
                scoring_ops.global_score(st.gcounters, self.score_params),
                attackers,
            )
            rec["honest_behaviour_penalty_max"] = jnp.where(
                ~attackers, st.gcounters.behaviour_penalty, 0.0
            ).max()
        if target is not None:
            tgt_edges = st.mesh[target] & st.nbr_valid[target]
            if attackers is not None:
                tgt_edges = tgt_edges & ~attackers[
                    jnp.clip(decode_index_plane(st.nbrs[target]), 0, self.n - 1)
                ]
            rec["target_honest_mesh_edges"] = tgt_edges.sum().astype(jnp.int32)
        return rec

    @functools.partial(
        jax.jit, static_argnames=("self", "record", "target")
    )
    def rollout_events(
        self,
        st: GossipState,
        events,
        attackers: Optional[jax.Array] = None,
        target: Optional[int] = None,
        record: bool = True,
    ):
        """Run a whole event schedule (``ops.schedule.GossipEvents``) in ONE
        ``lax.scan`` -> (final state, flight record | None).

        The device-compiled form of the host-segmented
        ``utils.faults.run_with_faults`` / attack-runner round loops: every
        campaign event (kill, revive, subscription churn, mute, delay,
        publish, post-step silence) is a per-step tensor consumed as scan
        ``xs``, so there are no host round-trips mid-campaign.  Events at
        step t apply before round t's transition, exactly where the host
        API calls used to land between scan segments.

        With ``record=True`` the ys are ``flight_record_round`` extended by
        the adversary channels of ``_campaign_record`` (when ``attackers``
        / ``target`` are given); publisher self-receipts of in-scan
        publishes are folded into the latency histogram at bin 0, keeping
        ``delivery_frac`` exact for slot-unique campaigns.  ``silence``
        (post-step eager-plane squelch) assumes the ideal fabric — the
        scenario compiler rejects it when ``max_edge_delay > 0`` (the fresh
        history would desync from fresh_w).
        """
        n_steps = int(events.kill.shape[0])

        def silence_after(s, ev):
            return jax.lax.cond(
                ev.silence.any(),
                lambda x: x._replace(
                    fresh_w=jnp.where(
                        ev.silence[:, None], jnp.uint32(0), x.fresh_w
                    )
                ),
                lambda x: x,
                s,
            )

        if not record:
            def bare(s, ev):
                s = self._apply_events(s, ev)
                s = self.step(s)
                return silence_after(s, ev), None

            return jax.lax.scan(bare, st, events, length=n_steps)

        hist0 = hist_ops.latency_histogram_seed(
            st.first_step, st.msg_birth, st.msg_used & st.msg_valid,
            st.alive & st.subscribed, FLIGHT_HIST_BINS,
        )

        def body(carry, ev):
            s, hist = carry
            s = self._apply_events(s, ev)
            # Publisher self-receipts: an in-scan publish stamps its source
            # at latency 0, which the per-round increment (receipts stamped
            # by _propagate) never sees — count them here, masked the same
            # way the histogram counts receipts (valid message, counted
            # publisher).  Invalid publishes never enter the histogram.
            src_c = jnp.clip(ev.pub_src, 0, self.n - 1)
            pub_counted = (
                (ev.pub_src >= 0)
                & ev.pub_valid
                & s.alive[src_c]
                & s.subscribed[src_c]
            ).sum(dtype=jnp.int32)
            hist = hist.at[0].add(pub_counted)
            s2, per_msg = self.step_recorded(s)
            hist = hist + hist_ops.latency_histogram_increment(
                per_msg, s2.msg_birth, s2.msg_used & s2.msg_valid,
                s.step, FLIGHT_HIST_BINS,
            )
            s2 = silence_after(s2, ev)
            rec = self.flight_record_round(s2, hist)
            rec = self._campaign_record(s2, rec, attackers, target)
            return (s2, hist), rec

        (final, _), record_ys = jax.lax.scan(
            body, (st, hist0), events, length=n_steps
        )
        return final, record_ys

    # -- flight recorder ----------------------------------------------------

    def flight_record_round(self, st: GossipState, lat_hist: jax.Array):
        """One round's telemetry as a dict of device scalars (+ one i32[B]
        latency histogram) — the per-round sample the rollout scan stacks.

        ``lat_hist`` is the cumulative receipt histogram the rollout scan
        carries (see ``rollout``); it doubles as the delivery count
        (``lat_hist.sum()`` == receipts), so delivery fraction costs
        nothing extra.  Everything else is a cheap reduction over state the
        round already computed.  Score quantiles are taken over each peer's
        MEAN live-neighbor score, via the binned-histogram quantile rather
        than an [N] sort — XLA's CPU sort alone would eat most of the
        recorder's overhead budget, and a 128-bin approximation (error <=
        one bin of the per-round score range) is plenty for a
        score-distribution time series.
        """
        part = st.alive & st.subscribed
        part_n = jnp.maximum(part.sum(), 1)
        in_window = st.msg_used & st.msg_valid
        n_msgs = jnp.maximum(in_window.sum(), 1)
        mesh_deg = (st.mesh & st.nbr_valid).sum(axis=1)
        deg_alive = jnp.where(part, mesh_deg, 0)
        live_slots = jnp.maximum(st.nbr_valid.sum(axis=1), 1)
        peer_score = (
            jnp.where(st.nbr_valid, st.scores, 0.0).sum(axis=1) / live_slots
        )
        score_q = hist_ops.binned_quantiles(peer_score, part, (0.1, 0.5, 0.9))
        return {
            "step": st.step,
            "peers_alive": st.alive.sum(),
            "delivery_frac": lat_hist.sum() / (part_n * n_msgs),
            "mesh_degree_mean": deg_alive.sum() / part_n,
            "mesh_degree_max": mesh_deg.max(),
            "score_p10": score_q[0],
            "score_p50": score_q[1],
            "score_p90": score_q[2],
            "gossip_pending": bitpack.popcount(st.gossip_pend_w).sum(),
            "lat_hist": lat_hist,
        }

    # -- metrics ------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def delivery_stats(self, st: GossipState):
        """Per-message delivery fraction and latency percentiles (in rounds).

        The headline metrics of BASELINE.json: delivery parity + p50
        propagation latency.  Delivery is counted from ``first_step`` (the
        immutable receipt record) over alive+subscribed peers, so the
        seen-cache TTL clearing ``have_w`` bits never un-counts a delivery.
        """
        part = st.alive & st.subscribed
        part_n = part.sum()
        delivered = ((st.first_step >= 0) & part[:, None]).sum(axis=0)  # i32[M]
        frac = jnp.where(
            st.msg_used & st.msg_valid,
            delivered / jnp.maximum(part_n, 1),
            jnp.nan,
        )
        lat = jnp.where(
            st.first_step >= 0, st.first_step - st.msg_birth[None, :], -1
        )
        valid_lat = (
            (lat >= 0)
            & st.msg_used[None, :]
            & st.msg_valid[None, :]
            & part[:, None]
        )
        lat_f = jnp.where(valid_lat, lat.astype(jnp.float32), jnp.nan)
        p50 = jnp.nanmedian(lat_f)
        p99 = jnp.nanpercentile(lat_f, 99.0)
        return frac, p50, p99
