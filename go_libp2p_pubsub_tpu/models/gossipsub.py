"""GossipSub — the scalable mesh model (north-star flagship for scale).

A device-resident GossipSub v1.1-shaped simulator: static neighbor-slot
adjacency, mesh overlay maintained by heartbeat kernels, eager push + lazy
IHAVE/IWANT gossip, full peer-score state updated by delivery attribution.
This is the model behind BASELINE.json configs (b) 1k-peer D=6 heartbeat sim,
(d) scoring under attack traces, and (e) the 100k-peer ICI-sharded epidemic
sim (see ``parallel/``).

The v0 reference contains none of this (SURVEY.md §0) — it is the capability
envelope the framework grows into; the protocol rules follow the public
GossipSub spec, with the simplifications documented in ``ops/gossip.py``.

Message windows are **bit-packed** (``ops/bitpack.py``): possession, fresh,
and gossip-pending state are uint32 words, so the propagate hot loop moves
32x less HBM traffic than the bool-tensor form — the difference between 1k
and 100k peers fitting on one chip.  ``ops/gossip.py`` keeps the unpacked
reference kernels the packed path is equivalence-tested against.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import GossipSubParams, ScoreParams
from ..ops import bitpack
from ..ops import gossip_packed as gossip_ops
from ..ops import scoring as scoring_ops
from ..ops.gossip import heartbeat_mesh
from ..ops.scoring import GlobalCounters, TopicCounters


class GossipState(NamedTuple):
    """Single-topic mesh state.  N peers, K neighbor slots, M message window
    (stored packed: W = ceil(M/32) uint32 words per peer).

    Multi-topic operation stacks these via ``jax.vmap`` (topology shared,
    mesh/counters per topic); global score counters live outside the vmap.
    """

    nbrs: jax.Array         # i32[N, K] connection slots -> remote peer id
    rev: jax.Array          # i32[N, K] remote's slot index back to me
    nbr_valid: jax.Array    # bool[N, K]
    alive: jax.Array        # bool[N]
    edge_live: jax.Array    # bool[N, K] nbr_valid & alive[nbrs] — cached so
                            # the per-step hot loops never re-gather liveness
                            # (recomputed only at init / kill_peers)
    mesh: jax.Array         # bool[N, K] symmetric mesh membership
    backoff: jax.Array      # i32[N, K] prune-backoff heartbeats remaining
    counters: TopicCounters     # per-slot topic score counters
    gcounters: GlobalCounters   # per-peer global score inputs
    scores: jax.Array       # f32[N, K] cached neighbor scores (last heartbeat)
    have_w: jax.Array       # u32[N, W] possession (seen-cache within window)
    fresh_w: jax.Array      # u32[N, W] first-received last round
    gossip_pend_w: jax.Array  # u32[N, W] IWANT deliveries due next round
    first_step: jax.Array   # i32[N, M] first-receipt step, -1 = never
    msg_valid: jax.Array    # bool[M] validation verdict
    msg_birth: jax.Array    # i32[M] publish step
    msg_active: jax.Array   # bool[M] within the mcache/gossip window
    msg_used: jax.Array     # bool[M] ever published (persists until slot reuse)
    key: jax.Array          # PRNG key
    step: jax.Array         # i32


def build_topology(
    rng: np.random.Generator, n: int, k: int, degree: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Random ~degree-regular undirected graph in neighbor-slot form.

    Host-side one-time setup (the analog of the test fixtures' full-mesh
    ``connectUp``, ``pubsub_test.go:37-57``, but sparse).  Returns
    (nbrs, rev, nbr_valid).
    """
    if degree >= k:
        raise ValueError(f"degree ({degree}) must be < slot count k ({k})")
    nbrs = np.full((n, k), -1, np.int64)
    rev = np.full((n, k), -1, np.int64)
    used = np.zeros(n, np.int64)
    adj = [set() for _ in range(n)]
    # Union of `degree` random perfect-matching-ish pairings.
    for _ in range(degree):
        perm = rng.permutation(n)
        for a in range(0, n - 1, 2):
            i, j = int(perm[a]), int(perm[a + 1])
            if j in adj[i] or used[i] >= k or used[j] >= k:
                continue
            si, sj = used[i], used[j]
            nbrs[i, si], nbrs[j, sj] = j, i
            rev[i, si], rev[j, sj] = sj, si
            adj[i].add(j)
            adj[j].add(i)
            used[i] += 1
            used[j] += 1
    return nbrs, rev, nbrs >= 0


def build_topology_fast(
    rng: np.random.Generator, n: int, k: int, degree: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized topology builder for large N (100k peers in ~100 ms where
    the per-edge Python loop of ``build_topology`` takes minutes).

    Same construction idea — union of ``degree`` random pairings — but each
    pairing is admitted with NumPy set-ops instead of per-edge Python.
    Duplicate edges across rounds are dropped (slightly lower mean degree,
    same as the loop version's skip rule).
    """
    if degree >= k:
        raise ValueError(f"degree ({degree}) must be < slot count k ({k})")
    if degree == 0:
        empty = np.full((n, k), -1, np.int64)
        return empty, empty.copy(), empty >= 0
    pairs = []
    for _ in range(degree):
        perm = rng.permutation(n).astype(np.int64)
        a, b = perm[0 : n - 1 : 2], perm[1:n:2]
        pairs.append(np.stack([np.minimum(a, b), np.maximum(a, b)], 1))
    e = np.unique(np.concatenate(pairs, 0), axis=0)  # dedup undirected edges
    # Per-endpoint slot indices via cumulative counts; drop edges overflowing k.
    src = np.concatenate([e[:, 0], e[:, 1]])
    dst = np.concatenate([e[:, 1], e[:, 0]])
    order = np.argsort(src, kind="stable")
    src_s, dst_s = src[order], dst[order]
    counts = np.bincount(src_s, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot_s = np.arange(len(src_s)) - starts[src_s]
    ok_s = slot_s < k
    # An edge survives only if BOTH directions got a slot.
    eid = np.concatenate([np.arange(len(e)), np.arange(len(e))])[order]
    ok_edge = np.ones(len(e), bool)
    np.logical_and.at(ok_edge, eid, ok_s)
    keep = ok_edge[eid]
    src_s, dst_s, slot_s, eid = src_s[keep], dst_s[keep], slot_s[keep], eid[keep]
    # Recompute dense slots after the drop.
    counts = np.bincount(src_s, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot_s = np.arange(len(src_s)) - starts[src_s]
    nbrs = np.full((n, k), -1, np.int64)
    rev = np.full((n, k), -1, np.int64)
    nbrs[src_s, slot_s] = dst_s
    # rev: my slot back-pointer = the slot my counterpart assigned this edge.
    # Sort by (eid, src): the two directions of each edge become adjacent
    # pairs, and each direction's rev is its pair partner's slot.
    o2 = np.lexsort((src_s, eid))
    rev_sorted = np.empty(len(src_s), np.int64)
    rev_sorted[o2] = slot_s[o2].reshape(-1, 2)[:, ::-1].reshape(-1)
    rev[src_s, slot_s] = rev_sorted
    return nbrs, rev, nbrs >= 0


def compute_edge_live(
    nbr_valid: jax.Array, nbrs: jax.Array, alive: jax.Array
) -> jax.Array:
    """bool[N, K]: slot is wired AND its remote peer is alive.

    Liveness changes only at explicit events (init, kill_peers), so this
    per-element gather runs per event, not per step — at 100k peers a single
    [N, K] gather costs ~25 ms on a v5e chip, which the propagate and
    heartbeat hot loops must not pay every round.
    """
    from ..ops.graphs import safe_gather

    return nbr_valid & safe_gather(alive, nbrs, False)


def seed_message(
    have_w, fresh_w, gossip_pend_w, first_step,
    msg_valid, msg_birth, msg_active, msg_used,
    src, slot, valid, step, w,
):
    """Window-slot recycle + seed, shared by the single- and multi-topic
    models: clear the slot's bits for ALL peers (slot reuse), then stamp the
    publisher.  Returns the eight updated window leaves in argument order."""
    bm = bitpack.bit_mask(slot, w)               # u32[W] one-hot
    have_w = have_w & ~bm
    fresh_w = fresh_w & ~bm
    return (
        have_w.at[src].set(have_w[src] | bm),
        fresh_w.at[src].set(fresh_w[src] | bm),
        gossip_pend_w & ~bm,
        first_step.at[:, slot].set(-1).at[src, slot].set(step),
        msg_valid.at[slot].set(valid),
        msg_birth.at[slot].set(step),
        msg_active.at[slot].set(True),
        msg_used.at[slot].set(True),
    )


class GossipSub:
    """Single-topic GossipSub simulator with static shapes."""

    def __init__(
        self,
        n_peers: int = 1024,
        n_slots: int = 32,
        conn_degree: int = 16,
        msg_window: int = 128,
        params: Optional[GossipSubParams] = None,
        score_params: Optional[ScoreParams] = None,
        heartbeat_steps: int = 8,
        use_pallas: Optional[bool] = None,
    ):
        self.n = n_peers
        self.k = n_slots
        self.m = msg_window
        self.w = bitpack.n_words(msg_window)
        self.conn_degree = conn_degree
        self.params = params or GossipSubParams()
        self.score_params = score_params or ScoreParams()
        self.heartbeat_steps = heartbeat_steps
        # Pallas fast path: unsharded TPU arrays only.  The jnp ops partition
        # under GSPMD for the peer-sharded sim (see parallel/), while a
        # pallas_call would need shard_map — sharded runners must pass
        # use_pallas=False.  Mosaic lowering is TPU-only, so other backends
        # auto-pick the jnp path; explicit True off-TPU runs the kernel in
        # the Pallas interpreter (slow; test path).
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self.use_pallas = use_pallas

    def build_graph(self, seed: int = 0):
        """Connection topology only -> (nbrs, rev, nbr_valid) as jnp arrays
        (the loop builder is exact for small N; the vectorized one scales)."""
        rng = np.random.default_rng(seed)
        builder = build_topology if self.n <= 4096 else build_topology_fast
        nbrs, rev, valid = builder(rng, self.n, self.k, self.conn_degree)
        return (
            jnp.asarray(nbrs, jnp.int32),
            jnp.asarray(rev, jnp.int32),
            jnp.asarray(valid),
        )

    def init(self, seed: int = 0) -> GossipState:
        nbrs, rev, valid = self.build_graph(seed)
        n, k, m, w = self.n, self.k, self.m, self.w
        alive0 = jnp.ones((n,), bool)
        st = GossipState(
            nbrs=nbrs,
            rev=rev,
            nbr_valid=valid,
            alive=alive0,
            edge_live=compute_edge_live(valid, nbrs, alive0),
            mesh=jnp.zeros((n, k), bool),
            backoff=jnp.zeros((n, k), jnp.int32),
            counters=TopicCounters.zeros(n, k),
            gcounters=GlobalCounters.zeros(n),
            scores=jnp.zeros((n, k), jnp.float32),
            have_w=jnp.zeros((n, w), jnp.uint32),
            fresh_w=jnp.zeros((n, w), jnp.uint32),
            gossip_pend_w=jnp.zeros((n, w), jnp.uint32),
            first_step=jnp.full((n, m), -1, jnp.int32),
            msg_valid=jnp.zeros((m,), bool),
            msg_birth=jnp.zeros((m,), jnp.int32),
            msg_active=jnp.zeros((m,), bool),
            msg_used=jnp.zeros((m,), bool),
            key=jax.random.PRNGKey(seed),
            step=jnp.asarray(0, jnp.int32),
        )
        # Converge the mesh before traffic: a few warmup heartbeats.
        return self._warmup(st)

    @functools.partial(jax.jit, static_argnums=0)
    def _warmup(self, st: GossipState) -> GossipState:
        return self._heartbeat(self._heartbeat(self._heartbeat(st)))

    # -- views --------------------------------------------------------------

    def have_bool(self, st: GossipState) -> jax.Array:
        """Unpacked possession view bool[N, M] (tests / metrics)."""
        return bitpack.unpack(st.have_w, self.m)

    # -- events -------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def publish(
        self,
        st: GossipState,
        src: jax.Array,
        slot: jax.Array,
        valid: jax.Array,
    ) -> GossipState:
        """Seed a message at ``src`` in window ``slot`` (recycling the slot).

        ``valid=False`` publishes a message that will fail validation at
        every receiver — the attack-trace injection point (the reference's
        missing signature hole, ``pubsub.go:117``, made explicit).
        """
        (have_w, fresh_w, pend_w, first_step,
         mv, mb, ma, mu) = seed_message(
            st.have_w, st.fresh_w, st.gossip_pend_w, st.first_step,
            st.msg_valid, st.msg_birth, st.msg_active, st.msg_used,
            src, slot, valid, st.step, self.w,
        )
        return st._replace(
            have_w=have_w, fresh_w=fresh_w, gossip_pend_w=pend_w,
            first_step=first_step, msg_valid=mv, msg_birth=mb,
            msg_active=ma, msg_used=mu,
        )

    @functools.partial(jax.jit, static_argnums=0)
    def kill_peers(self, st: GossipState, mask: jax.Array) -> GossipState:
        """Abrupt peer failure (liveness mask); the mesh self-heals at the
        next heartbeat — the fault-injection hook of the sim."""
        alive = st.alive & ~mask
        return st._replace(
            alive=alive,
            edge_live=compute_edge_live(st.nbr_valid, st.nbrs, alive),
        )

    # -- transition ---------------------------------------------------------

    def _heartbeat(self, st: GossipState) -> GossipState:
        p, sp = self.params, self.score_params
        khb, kgossip, knext = jax.random.split(st.key, 3)

        # Advance mesh clocks by one heartbeat interval; decay; re-score.
        c = scoring_ops.tick_mesh_clocks(st.counters, st.mesh, p.heartbeat_interval_s)
        c = scoring_ops.decay_topic_counters(c, sp)
        g = scoring_ops.decay_global_counters(st.gcounters, sp)
        scores = scoring_ops.neighbor_scores(c, g, st.nbrs, st.nbr_valid, sp)

        new_mesh, grafted, pruned, backoff = heartbeat_mesh(
            khb, st.mesh, scores, st.nbrs, st.rev, st.edge_live, st.alive, p,
            st.backoff,
        )
        c = scoring_ops.on_prune(c, pruned, sp)
        c = scoring_ops.on_graft(c, grafted)

        gossip_pend_w = st.gossip_pend_w | gossip_ops.gossip_transfer_packed(
            kgossip,
            st.have_w,
            new_mesh,
            st.nbrs,
            st.rev,
            st.edge_live,
            st.alive,
            scores,
            bitpack.pack(st.msg_valid),
            p,
            sp.gossip_threshold,
        )

        # Expire messages out of the mcache history window.
        expired = st.msg_active & (
            st.step - st.msg_birth > p.history_length * self.heartbeat_steps
        )
        return st._replace(
            mesh=new_mesh,
            backoff=backoff,
            counters=c,
            gcounters=g,
            scores=scores,
            gossip_pend_w=gossip_pend_w & ~bitpack.pack(expired),
            msg_active=st.msg_active & ~expired,
            key=knext,
        )

    def _propagate(self, st: GossipState) -> GossipState:
        # Fold due gossip deliveries into this round's receipts.
        gossip_new = (
            st.gossip_pend_w & ~st.have_w & gossip_ops._as_mask(st.alive)[:, None]
        )
        have_w = st.have_w | gossip_new
        fresh_w = st.fresh_w | gossip_new
        first_step = jnp.where(
            bitpack.unpack(gossip_new, self.m) & (st.first_step < 0),
            st.step,
            st.first_step,
        )

        valid_w = bitpack.pack(st.msg_valid & st.msg_active)
        if self.use_pallas:
            from ..ops.pallas_gossip import propagate_packed_pallas

            out = propagate_packed_pallas(
                st.mesh, st.nbrs, st.edge_live, st.alive, have_w, fresh_w,
                valid_w, interpret=jax.default_backend() != "tpu",
            )
        else:
            out = gossip_ops.propagate_packed(
                st.mesh, st.nbrs, st.edge_live, st.alive, have_w, fresh_w,
                valid_w,
            )
        first_step = jnp.where(
            bitpack.unpack(out.new_w, self.m) & (first_step < 0),
            st.step,
            first_step,
        )
        c = st.counters._replace(
            first_message_deliveries=st.counters.first_message_deliveries
            + out.fmd_inc,
            mesh_message_deliveries=st.counters.mesh_message_deliveries
            + out.mmd_inc,
            invalid_message_deliveries=st.counters.invalid_message_deliveries
            + out.invalid_inc,
        )
        return st._replace(
            have_w=out.have_w,
            fresh_w=out.fresh_w,
            first_step=first_step,
            counters=c,
            gossip_pend_w=jnp.zeros_like(st.gossip_pend_w),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: GossipState) -> GossipState:
        """One network round: eager-push propagation, plus heartbeat
        maintenance every ``heartbeat_steps`` rounds."""
        st = self._propagate(st)
        st = jax.lax.cond(
            (st.step % self.heartbeat_steps) == self.heartbeat_steps - 1,
            self._heartbeat,
            lambda s: s,
            st,
        )
        return st._replace(step=st.step + 1)

    @functools.partial(jax.jit, static_argnames=("self", "n_steps"))
    def run(self, st: GossipState, n_steps: int) -> GossipState:
        def body(s, _):
            return self.step(s), None

        st, _ = jax.lax.scan(body, st, None, length=n_steps)
        return st

    # -- metrics ------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def delivery_stats(self, st: GossipState):
        """Per-message delivery fraction and latency percentiles (in rounds).

        The headline metrics of BASELINE.json: delivery parity + p50
        propagation latency.
        """
        alive_n = st.alive.sum()
        have = self.have_bool(st)
        delivered = (have & st.alive[:, None]).sum(axis=0)  # i32[M]
        frac = jnp.where(
            st.msg_used & st.msg_valid,
            delivered / jnp.maximum(alive_n, 1),
            jnp.nan,
        )
        lat = jnp.where(
            st.first_step >= 0, st.first_step - st.msg_birth[None, :], -1
        )
        valid_lat = (lat >= 0) & st.msg_used[None, :] & st.msg_valid[None, :]
        lat_f = jnp.where(valid_lat, lat.astype(jnp.float32), jnp.nan)
        p50 = jnp.nanmedian(lat_f)
        p99 = jnp.nanpercentile(lat_f, 99.0)
        return frac, p50, p99
