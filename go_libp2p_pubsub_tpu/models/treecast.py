"""TreeCast — the flagship v0-parity model.

The reference's single-rooted dissemination tree (``/root/reference/
subtree.go``) packaged as a model: static-shape state init, a jittable
lockstep ``forward`` step, and a demo-state builder used by the graft entry
point and benchmarks.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..config import SimParams, TreeOpts
from ..ops import tree as tree_ops
from ..ops.tree import TreeState


class TreeCast:
    """Data-parallel dissemination-tree pubsub over ``max_peers`` rows."""

    def __init__(self, params: SimParams | None = None, opts: TreeOpts | None = None):
        self.params = params or SimParams()
        self.opts = opts or TreeOpts()

    # Value semantics so identically-configured instances share the jit
    # cache (``self`` is static in the rollouts; the model is a pure
    # function of its two frozen param sets).
    def __eq__(self, other):
        return (
            type(other) is type(self)
            and (self.params, self.opts) == (other.params, other.opts)
        )

    def __hash__(self):
        return hash((type(self), self.params, self.opts))

    def init(self, root: int = 0) -> TreeState:
        return tree_ops.init_state(self.params, self.opts, root=root)

    @staticmethod
    def forward(state: TreeState) -> TreeState:
        """One lockstep network transition — the jittable hot path."""
        return tree_ops.step(state)

    @functools.partial(jax.jit, static_argnames=("self", "n_steps", "record"))
    def rollout(self, state: TreeState, n_steps: int, record: bool = True):
        """``n_steps`` lockstep transitions -> (final state, flight record).

        The tree plane's flight recorder (the GossipSub.rollout twin): with
        ``record=True`` each step emits the ``tree_metrics`` reduction dict
        as the scan's ``ys``, so join/repair convergence and delivery
        backlog come back as [n_steps] time series with no host transfer
        inside the scan.  ``record=False`` carries no ys (the bare rollout
        ``tree_ops.run_steps`` always was).
        """
        from ..utils.metrics import tree_metrics

        def body(s, _):
            s = tree_ops.step(s)
            return s, (tree_metrics(s) if record else None)

        return jax.lax.scan(body, state, None, length=n_steps)

    @functools.partial(jax.jit, static_argnames=("self", "record"))
    def rollout_events(self, state: TreeState, events, record: bool = True):
        """Run a whole event schedule (``ops.schedule.TreeEvents``) in ONE
        ``lax.scan`` -> (final state, flight record | None).

        The tree plane's twin of ``GossipSub.rollout_events``: kills,
        graceful leaves, join walks, and root publishes are per-step
        tensors consumed as scan ``xs`` — the device-compiled form of the
        host-segmented ``utils.faults.run_with_faults`` driving.  Events at
        step t apply before round t's transition.
        """
        from ..utils.metrics import tree_metrics

        n_steps = int(events.kill.shape[0])

        def body(s, ev):
            s = jax.lax.cond(
                ev.kill.any(),
                lambda x: x._replace(alive=x.alive & ~ev.kill),
                lambda x: x,
                s,
            )
            s = jax.lax.cond(
                ev.leave.any(),
                lambda x: x._replace(leaving=x.leaving | ev.leave),
                lambda x: x,
                s,
            )
            s = jax.lax.cond(
                ev.sub.any(),
                lambda x: tree_ops.begin_subscribe_many(x, ev.sub),
                lambda x: x,
                s,
            )
            s = jax.lax.cond(
                (ev.pub_msg >= 0).any(),
                lambda x: tree_ops.publish_many(x, ev.pub_msg),
                lambda x: x,
                s,
            )
            s = tree_ops.step(s)
            return s, (tree_metrics(s) if record else None)

        return jax.lax.scan(body, state, events, length=n_steps)

    def build_demo_state(self, n_peers: int, n_msgs: int = 4) -> TreeState:
        """A small joined tree with queued traffic, for compile checks/bench.

        Runs the join walk host-side (each subscribe is a few steps) then
        enqueues ``n_msgs`` publishes at the root.
        """
        if n_peers > self.params.max_peers:
            raise ValueError("n_peers exceeds SimParams.max_peers")
        st = self.init(root=0)
        for p in range(1, n_peers):
            st = tree_ops.begin_subscribe(st, jnp.int32(p))
            for _ in range(4 * n_peers):
                if bool(st.joined[p]):
                    break
                st = tree_ops.step(st)
        for m in range(n_msgs):
            st = tree_ops.publish(st, jnp.int32(m))
        return st


def entry_fn_and_args(
    n_peers: int = 16, params: SimParams | None = None
) -> Tuple[callable, Tuple[TreeState]]:
    """(jittable forward, example args) for the driver's compile check."""
    model = TreeCast(params or SimParams(max_peers=max(16, n_peers)))
    state = model.build_demo_state(n_peers)
    return TreeCast.forward, (state,)
