"""Multi-topic GossipSub: T independent meshes over one shared topology.

The reference keys everything by topic: one protocol registration and one
tree per ``(root, title)`` (``pubsub.go:55``, ``client.go:68``); peers join
topics independently.  The TPU-native form stacks the per-topic state with a
leading topic axis and ``jax.vmap``s the single-topic kernels over it:

- **shared across topics**: connection topology (``nbrs``/``rev``/
  ``nbr_valid``), liveness, global score counters (P5-P7 are per-peer, not
  per-topic), and the cached aggregate score;
- **per-topic** (leading ``T`` dim): mesh membership, topic score counters,
  packed message windows, message metadata, PRNG keys.

Scoring follows the v1.1 aggregation rule: a neighbor's score is the SUM of
its per-topic components across all topics plus the global components —
misbehaving in one topic (invalid spam, delivery deficits) degrades the
attacker's standing in every topic's mesh, which is the cross-topic defense
the spec's design intends.  Subscription is a per-(topic, peer) mask folded
into the topic's liveness view: unsubscribed peers neither receive nor relay
nor get grafted in that topic.

Uses the portable jnp kernels (vmap over a ``pallas_call`` is left out of
scope; ``use_pallas`` stays False internally).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import GossipSubParams, ScoreParams
from ..ops import bitpack
from ..ops import gossip_packed as gossip_ops
from ..ops import scoring as scoring_ops
from ..ops.gossip import heartbeat_mesh
from ..ops.scoring import GlobalCounters, TopicCounters
from ..ops.graphs import decode_index_plane
from .gossipsub import GossipState, GossipSub, compute_edge_live


class MultiTopicState(NamedTuple):
    # shared
    nbrs: jax.Array          # [N, K] narrow index storage (uint16 for
                             # N <= 65534; see GossipState.nbrs)
    rev: jax.Array           # [N, K] narrow slot back-pointers
    nbr_valid: jax.Array     # bool[N, K]
    outbound: jax.Array      # bool[N, K] dialed-by-me (shared: connections,
                             # not meshes, have a direction)
    alive: jax.Array         # bool[N]
    subscribed: jax.Array    # bool[T, N]
    edge_live: jax.Array     # bool[T, N, K] valid & remote alive+subscribed,
                             # cached per topic (recomputed at init/kill only)
    gcounters: GlobalCounters    # per-peer [N]
    scores: jax.Array        # f32[N, K] aggregate (cached at heartbeat)
    # per-topic (leading T)
    mesh: jax.Array          # bool[T, N, K]
    fanout: jax.Array        # bool[T, N, K] non-subscribed publishers' fanout
    fanout_age: jax.Array    # i32[T, N]
    backoff: jax.Array       # i32[T, N, K] prune-backoff (per topic, per spec)
    counters: TopicCounters  # f32[T, N, K] leaves
    have_w: jax.Array        # u32[T, N, W]
    fresh_w: jax.Array       # u32[T, N, W]
    gossip_pend_w: jax.Array # u32[T, N, W]
    iwant_pend_w: jax.Array  # u32[T, N, W] heartbeat-granted IWANT transfers
    gossip_mute: jax.Array   # bool[N] promise-breakers (shared: an attacker
                             # that never serves IWANTs is mute in every topic)
    gossip_delay: jax.Array  # i32[N] ingress gossip latency (shared: links,
                             # not topics, are slow)
    pend_hold: jax.Array     # i32[T, N] per-topic pend-fold countdown
    first_step: jax.Array    # i32[T, N, M]
    msg_valid: jax.Array     # bool[T, M]
    msg_birth: jax.Array     # i32[T, M]
    msg_active: jax.Array    # bool[T, M]
    msg_used: jax.Array      # bool[T, M]
    keys: jax.Array          # u32[T, 2] per-topic PRNG keys
    step: jax.Array          # i32


# Sharding classification of MultiTopicState for the peer-sharded multichip
# path (parallel.mesh.state_shardings): per-topic leaves stack as [T, N, ...]
# so their peer dim is axis 1; shared leaves lead with N; message metadata
# and per-topic PRNG keys replicate.  Exhaustive by name — adding a field
# without classifying it here fails multitopic_state_shardings.
MULTITOPIC_REPLICATED_FIELDS = frozenset({
    "msg_valid", "msg_birth", "msg_active", "msg_used", "keys", "step",
})
MULTITOPIC_PEER_DIMS = {
    name: 1
    for name in (
        "subscribed", "edge_live", "mesh", "fanout", "fanout_age", "backoff",
        "counters", "have_w", "fresh_w", "gossip_pend_w", "iwant_pend_w",
        "pend_hold", "first_step",
    )
}
_MT_PEER_DIM0_FIELDS = frozenset({
    "nbrs", "rev", "nbr_valid", "outbound", "alive", "gcounters", "scores",
    "gossip_mute", "gossip_delay",
})


def multitopic_state_shardings(st: MultiTopicState, mesh, n_peers: int):
    """NamedSharding pytree for a ``MultiTopicState``: shared leaves shard
    on dim 0, topic-stacked leaves on dim 1, metadata/keys replicate.
    Validates the classification above is exhaustive first."""
    from ..parallel.mesh import state_shardings

    unclassified = (
        set(st._fields) - MULTITOPIC_REPLICATED_FIELDS
        - set(MULTITOPIC_PEER_DIMS) - _MT_PEER_DIM0_FIELDS
    )
    if unclassified:
        raise ValueError(
            f"MultiTopicState fields without a sharding rule: "
            f"{sorted(unclassified)}; classify them in multitopic.py"
        )
    for name in _MT_PEER_DIM0_FIELDS | set(MULTITOPIC_PEER_DIMS):
        d = MULTITOPIC_PEER_DIMS.get(name, 0)
        for leaf in jax.tree.leaves(getattr(st, name)):
            if getattr(leaf, "ndim", 0) <= d or leaf.shape[d] != n_peers:
                raise ValueError(
                    f"peer-dim leaf {name} has shape "
                    f"{getattr(leaf, 'shape', None)}, expected dim {d} "
                    f"== {n_peers}"
                )
    return state_shardings(
        st, mesh, replicated=MULTITOPIC_REPLICATED_FIELDS,
        peer_dim={
            **{f: 0 for f in _MT_PEER_DIM0_FIELDS}, **MULTITOPIC_PEER_DIMS
        },
    )


class MultiTopicGossipSub:
    """T-topic GossipSub simulator sharing one connection graph."""

    def __init__(
        self,
        n_topics: int = 4,
        n_peers: int = 1024,
        n_slots: int = 32,
        conn_degree: int = 16,
        msg_window: int = 128,
        params: Optional[GossipSubParams] = None,
        score_params: Optional[ScoreParams] = None,
        heartbeat_steps: int = 8,
        index_dtype_override=None,
    ):
        self.t = n_topics
        self.gs = GossipSub(
            n_peers=n_peers,
            n_slots=n_slots,
            conn_degree=conn_degree,
            msg_window=msg_window,
            params=params,
            score_params=score_params,
            heartbeat_steps=heartbeat_steps,
            use_pallas=False,
            index_dtype_override=index_dtype_override,
        )
        self.n, self.k, self.m, self.w = (
            self.gs.n, self.gs.k, self.gs.m, self.gs.w,
        )
        self.params = self.gs.params
        self.score_params = self.gs.score_params
        self.heartbeat_steps = heartbeat_steps

    # Value semantics for the jit cache (see GossipSub.__eq__): the model
    # is (n_topics, inner single-topic config).
    def __eq__(self, other):
        return (
            type(other) is type(self)
            and (self.t, self.gs) == (other.t, other.gs)
        )

    def __hash__(self):
        return hash((type(self), self.t, self.gs))

    # -- construction -------------------------------------------------------

    def init(
        self, seed: int = 0, subscribed: Optional[np.ndarray] = None
    ) -> MultiTopicState:
        nbrs, rev, nbr_valid, outbound = self.gs.build_graph(seed)
        t, n, k, m, w = self.t, self.n, self.k, self.m, self.w
        if subscribed is None:
            subscribed = np.ones((t, n), bool)
        subscribed = jnp.asarray(subscribed)
        if subscribed.shape != (t, n):
            raise ValueError(f"subscribed must be [T={t}, N={n}]")
        zc = TopicCounters.zeros(n, k)
        alive0 = jnp.ones((n,), bool)
        st = MultiTopicState(
            nbrs=nbrs,
            rev=rev,
            nbr_valid=nbr_valid,
            outbound=outbound,
            alive=alive0,
            subscribed=subscribed,
            edge_live=jax.vmap(compute_edge_live, (None, None, 0))(
                nbr_valid, nbrs, alive0[None, :] & subscribed
            ),
            gcounters=GlobalCounters.zeros(n),
            scores=jnp.zeros((n, k), jnp.float32),
            mesh=jnp.zeros((t, n, k), bool),
            fanout=jnp.zeros((t, n, k), bool),
            fanout_age=jnp.full(
                (t, n), jnp.iinfo(jnp.int32).max // 2, jnp.int32
            ),
            backoff=jnp.zeros((t, n, k), jnp.int32),
            counters=jax.tree.map(
                lambda x: jnp.broadcast_to(x, (t, n, k)), zc
            ),
            have_w=jnp.zeros((t, n, w), jnp.uint32),
            fresh_w=jnp.zeros((t, n, w), jnp.uint32),
            gossip_pend_w=jnp.zeros((t, n, w), jnp.uint32),
            iwant_pend_w=jnp.zeros((t, n, w), jnp.uint32),
            gossip_mute=jnp.zeros((n,), bool),
            gossip_delay=jnp.zeros((n,), jnp.int32),
            pend_hold=jnp.zeros((t, n), jnp.int32),
            first_step=jnp.full((t, n, m), -1, jnp.int32),
            msg_valid=jnp.zeros((t, m), bool),
            msg_birth=jnp.zeros((t, m), jnp.int32),
            msg_active=jnp.zeros((t, m), bool),
            msg_used=jnp.zeros((t, m), bool),
            keys=jax.vmap(jax.random.fold_in, (None, 0))(
                jax.random.PRNGKey(seed), jnp.arange(t)
            ),
            step=jnp.asarray(0, jnp.int32),
        )
        return self._warmup(st)

    # Narrow index storage <-> wide kernel view (see GossipSub): the state
    # carries nbrs/rev in the inner model's narrow dtypes; _propagate and
    # _heartbeat consume the widened int32 view, restored at every public
    # jitted boundary so the interior graphs match the legacy int32 path
    # byte-for-byte.
    def _widen_indices(self, st: MultiTopicState) -> MultiTopicState:
        if not self.gs._has_narrow_indices():
            return st
        return st._replace(
            nbrs=decode_index_plane(st.nbrs),
            rev=decode_index_plane(st.rev),
        )

    def _narrow_indices(self, st: MultiTopicState) -> MultiTopicState:
        if not self.gs._has_narrow_indices():
            return st
        return st._replace(
            nbrs=st.nbrs.astype(self.gs.idx_dtype),
            rev=st.rev.astype(self.gs.rev_dtype),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def _warmup(self, st: MultiTopicState) -> MultiTopicState:
        st = self._widen_indices(st)
        st = self._heartbeat(self._heartbeat(self._heartbeat(st)))
        return self._narrow_indices(st)

    # -- events -------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def publish(
        self,
        st: MultiTopicState,
        topic: jax.Array,
        src: jax.Array,
        slot: jax.Array,
        valid: jax.Array,
    ) -> MultiTopicState:
        """Seed a message at ``src`` in ``topic``'s window ``slot`` (the
        shared ``seed_message`` recycle applied to the topic's slice), with
        the same first-hop rules as the single-topic model: flood-publish to
        above-``publish_threshold`` topic peers, or fanout for a
        non-subscribed publisher when flooding is off."""
        from ..ops.graphs import top_mask
        from .gossipsub import seed_message

        p, sp = self.params, self.score_params
        n, k = self.n, self.k
        (have_t, fresh_t, pend_t, iwant_t, fs_t, mv, mb, ma, mu) = seed_message(
            st.have_w[topic], st.fresh_w[topic], st.gossip_pend_w[topic],
            st.iwant_pend_w[topic], st.first_step[topic], st.msg_valid[topic],
            st.msg_birth[topic], st.msg_active[topic], st.msg_used[topic],
            src, slot, valid, st.step, self.w,
        )
        # Advance the topic's key so back-to-back publishes within one step
        # draw fresh fanout randomness (mirrors the single-topic split).
        kpub, knext = jax.random.split(st.keys[topic])
        eligible = st.edge_live[topic][src] & (
            st.scores[src] >= sp.publish_threshold
        )
        fanout, fanout_age = st.fanout, st.fanout_age
        if p.flood_publish:
            targets = eligible
        else:
            cur = st.fanout[topic, src] & eligible
            want = jnp.clip(p.d - cur.sum(), 0, p.d).astype(jnp.int32)
            add = top_mask(
                jnp.where(
                    (eligible & ~cur)[None, :],
                    jax.random.uniform(kpub, (1, k)),
                    -jnp.inf,
                ),
                want[None],
                kmax=p.d,
            )[0]
            newf = cur | add
            is_sub = st.subscribed[topic, src]
            targets = jnp.where(is_sub, jnp.zeros((k,), bool), newf)
            fanout = st.fanout.at[topic, src].set(
                jnp.where(is_sub, st.fanout[topic, src], newf)
            )
            fanout_age = st.fanout_age.at[topic, src].set(
                jnp.where(is_sub, st.fanout_age[topic, src], 0)
            )
        # Hold arming mirrors the single-topic publish exactly: only on an
        # idle empty row, only when a bit was placed (see GossipSub.publish).
        bm = bitpack.bit_mask(slot, self.w)
        rows = jnp.where(targets, decode_index_plane(st.nbrs[src]), n)
        rows_c = jnp.clip(rows, 0, n - 1)
        gathered = pend_t[rows_c]
        upd = gathered | jnp.where(valid, bm, jnp.uint32(0))[None, :]
        pend_t = pend_t.at[rows].set(upd, mode="drop")
        cur_hold = st.pend_hold[topic][rows_c]
        arm = valid & (cur_hold <= 0) & (gathered == 0).all(axis=-1)
        hold_t = st.pend_hold[topic].at[rows].set(
            jnp.where(arm, st.gossip_delay[rows_c], cur_hold), mode="drop"
        )
        return st._replace(
            have_w=st.have_w.at[topic].set(have_t),
            fresh_w=st.fresh_w.at[topic].set(fresh_t),
            gossip_pend_w=st.gossip_pend_w.at[topic].set(pend_t),
            iwant_pend_w=st.iwant_pend_w.at[topic].set(iwant_t),
            pend_hold=st.pend_hold.at[topic].set(hold_t),
            first_step=st.first_step.at[topic].set(fs_t),
            msg_valid=st.msg_valid.at[topic].set(mv),
            msg_birth=st.msg_birth.at[topic].set(mb),
            msg_active=st.msg_active.at[topic].set(ma),
            msg_used=st.msg_used.at[topic].set(mu),
            fanout=fanout,
            fanout_age=fanout_age,
            keys=st.keys.at[topic].set(knext),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def set_gossip_delay(
        self, st: MultiTopicState, delay: jax.Array
    ) -> MultiTopicState:
        """Install shared per-peer ingress gossip latency (i32[N]); see
        ``GossipSub.set_gossip_delay``."""
        return st._replace(gossip_delay=delay.astype(jnp.int32))

    @functools.partial(jax.jit, static_argnums=0)
    def set_gossip_mute(
        self, st: MultiTopicState, mask: jax.Array
    ) -> MultiTopicState:
        """Mark peers (bool[N]) as gossip promise-breakers in every topic
        (see ``GossipSub.set_gossip_mute``)."""
        return st._replace(gossip_mute=mask)

    @functools.partial(jax.jit, static_argnums=0)
    def kill_peers(self, st: MultiTopicState, mask: jax.Array) -> MultiTopicState:
        alive = st.alive & ~mask
        return st._replace(
            alive=alive,
            edge_live=jax.vmap(compute_edge_live, (None, None, 0))(
                st.nbr_valid, st.nbrs, alive[None, :] & st.subscribed
            ),
        )

    # -- transition ---------------------------------------------------------

    def _topic_alive(self, st: MultiTopicState) -> jax.Array:
        """bool[T, N]: a peer participates in a topic iff alive+subscribed."""
        return st.alive[None, :] & st.subscribed

    def _propagate(self, st: MultiTopicState) -> MultiTopicState:
        """One eager-push + IWANT round in every topic (vmapped single-topic
        round; the per-topic ``GossipState`` is assembled from the stacked
        slices, with shared leaves broadcast)."""
        gs = self.gs
        ones_nk = jnp.ones((self.n, self.k), bool)
        inactive_age = jnp.full((self.n,), jnp.iinfo(jnp.int32).max // 2,
                                jnp.int32)
        # Per-edge eager delay is single-topic only (gs.max_edge_delay == 0):
        # empty history + zero delays keep the ideal-fabric code path.
        no_edge_delay = jnp.zeros((self.n, self.k), jnp.int32)
        no_hist = jnp.zeros((self.n, 0, self.w), jnp.uint32)

        def one(mesh, fanout, backoff, counters, have_w, fresh_w, pend_w,
                iwant_w, hold, first_step, mv, mb, ma, mu, key, al, el, sub):
            g = GossipState(
                nbrs=st.nbrs, rev=st.rev, nbr_valid=st.nbr_valid,
                outbound=st.outbound, alive=al, subscribed=sub,
                edge_live=el, nbr_sub=ones_nk, mesh=mesh, fanout=fanout,
                fanout_age=inactive_age, backoff=backoff, counters=counters,
                gcounters=st.gcounters, scores=st.scores, have_w=have_w,
                fresh_w=fresh_w, gossip_pend_w=pend_w, iwant_pend_w=iwant_w,
                gossip_mute=st.gossip_mute,
                self_promo=jnp.zeros((self.n,), bool),
                gossip_delay=st.gossip_delay,
                pend_hold=hold, edge_delay=no_edge_delay, fresh_hist=no_hist,
                first_step=first_step,
                msg_valid=mv, msg_birth=mb, msg_active=ma, msg_used=mu,
                key=key, step=st.step,
            )
            o = gs._propagate(g)
            return (o.counters, o.have_w, o.fresh_w, o.gossip_pend_w,
                    o.iwant_pend_w, o.pend_hold, o.first_step)

        (counters, have_w, fresh_w, pend_w, iwant_w, hold,
         first_step) = jax.vmap(one)(
            st.mesh, st.fanout, st.backoff, st.counters, st.have_w,
            st.fresh_w, st.gossip_pend_w, st.iwant_pend_w, st.pend_hold,
            st.first_step, st.msg_valid, st.msg_birth, st.msg_active,
            st.msg_used, st.keys, self._topic_alive(st), st.edge_live,
            st.subscribed,
        )
        return st._replace(
            counters=counters, have_w=have_w, fresh_w=fresh_w,
            gossip_pend_w=pend_w, iwant_pend_w=iwant_w, pend_hold=hold,
            first_step=first_step,
        )

    def _heartbeat(self, st: MultiTopicState) -> MultiTopicState:
        p, sp = self.params, self.score_params

        # Tick + decay topic counters per topic; decay globals ONCE.
        c = jax.vmap(
            lambda ct, mesh_t: scoring_ops.decay_topic_counters(
                scoring_ops.tick_mesh_clocks(
                    ct, mesh_t, p.heartbeat_interval_s
                ),
                sp,
            )
        )(st.counters, st.mesh)
        g = scoring_ops.decay_global_counters(st.gcounters, sp)

        # v1.1 aggregation: sum of topic components over topics + globals.
        tsc = jax.vmap(lambda ct: scoring_ops.topic_score(ct, sp))(c)
        remote = scoring_ops.global_score(g, sp)[
            jnp.clip(st.nbrs, 0, self.n - 1)
        ]
        scores = jnp.where(st.nbr_valid, tsc.sum(axis=0) + remote, -jnp.inf)

        keys6 = jax.vmap(lambda k: jax.random.split(k, 6))(st.keys)
        topic_alive = self._topic_alive(st)
        hb_idx = st.step // self.heartbeat_steps
        do_og = (hb_idx % p.opportunistic_graft_ticks) == 0
        fanout_ttl_hb = max(1, round(p.fanout_ttl_s / p.heartbeat_interval_s))
        seen_ttl_steps = (
            max(1, round(p.seen_ttl_s / p.heartbeat_interval_s))
            * self.heartbeat_steps
        )

        # Promise-breaker view of each slot's remote — topology is shared, so
        # one gather serves every topic.
        from ..ops.graphs import safe_gather as _safe_gather

        serve_ok = ~_safe_gather(st.gossip_mute, st.nbrs, True)

        def one(mesh_t, fan_t, fage_t, bo_t, c_t, have_t, pend_t, mv, ma,
                mbirth, mused, k6, al, el, sub_t):
            khb, kgossip, kiwant, kfan, kpx, knext = k6
            new_mesh, grafted, pruned, bo2, bo_viol = heartbeat_mesh(
                khb, mesh_t, scores, st.nbrs, st.rev, el, al, p, bo_t,
                st.outbound, do_og,
                og_threshold=sp.opportunistic_graft_threshold,
                ignore_backoff=self.gs.graft_spammers,
            )
            c2 = scoring_ops.on_graft(
                scoring_ops.on_prune(c_t, pruned, sp), grafted
            )
            # PX rewires the SHARED connection layer, so it cannot run
            # inside this vmap (T topics racing scatter-writes into one
            # adjacency would break the slot pairing); the heartbeat
            # serializes it AFTER the vmap with a lax.scan over topics
            # (see below).  This topic's pruned mask and PX key are
            # returned for that pass.
            seen_expired = mused & (st.step - mbirth > seen_ttl_steps)
            have2 = have_t & ~bitpack.pack(seen_expired)
            gossip_age_ok = (
                st.step - mbirth <= p.history_gossip * self.heartbeat_steps
            )
            # Fused IHAVE/IWANT with promise accounting (see the
            # single-topic heartbeat): transfers land two rounds out via
            # iwant_pend_w, score-gated and randomly prioritized.
            iwant_t, broken_t = gossip_ops.gossip_exchange_packed(
                kgossip, kiwant, have_t, have2, new_mesh, st.nbrs, st.rev,
                el, al, scores, bitpack.pack(mv & ma & gossip_age_ok), p,
                sp.gossip_threshold, serve_ok, p.max_iwant_length,
            )
            # Fanout upkeep for this topic's non-subscribed publishers.
            fage2 = jnp.minimum(fage_t + 1, jnp.iinfo(jnp.int32).max // 2)
            factive = (fage2 <= fanout_ttl_hb) & ~sub_t & st.alive
            feligible = el & (scores >= sp.publish_threshold)
            fkeep = fan_t & feligible
            fwant = jnp.where(
                factive, jnp.clip(p.d - fkeep.sum(axis=1), 0, p.d), 0
            ).astype(jnp.int32)
            from ..ops.graphs import top_mask as _top_mask
            fadd = _top_mask(
                jnp.where(
                    feligible & ~fkeep,
                    jax.random.uniform(kfan, (self.n, self.k)),
                    -jnp.inf,
                ),
                fwant,
                kmax=p.d,
            )
            fan2 = jnp.where(factive[:, None], fkeep | fadd, False)

            expired = ma & (
                st.step - mbirth > p.history_length * self.heartbeat_steps
            )
            dead_w = bitpack.pack(expired)
            return (
                new_mesh, fan2, fage2, bo2, c2,
                have2,
                pend_t & ~dead_w[None, :],
                iwant_t,
                ma & ~expired, knext, bo_viol, broken_t, pruned, kpx,
            )

        (mesh, fanout, fanout_age, backoff, c, have_w, pend, iwant_w, mactive,
         keys, bo_viols, broken, pruned_t, kpx_t) = jax.vmap(one)(
            st.mesh, st.fanout, st.fanout_age, st.backoff, c, st.have_w,
            st.gossip_pend_w, st.msg_valid, st.msg_active, st.msg_birth,
            st.msg_used, keys6, topic_alive, st.edge_live, st.subscribed,
        )
        # P7 is a GLOBAL component: backoff-violating GRAFTs and broken
        # gossip promises in ANY topic accrue to the sender's one
        # behaviour-penalty counter (broken is charged by REMOTE id).
        promise_ids = jnp.where(st.nbr_valid, st.nbrs, self.n).reshape(-1)
        promise_viol = jax.ops.segment_sum(
            broken.sum(axis=0).reshape(-1), promise_ids,
            num_segments=self.n + 1,
        )[: self.n]
        g = g._replace(
            behaviour_penalty=g.behaviour_penalty
            + bo_viols.sum(axis=0)
            + promise_viol
        )

        # Peer exchange on prune (v1.1 PX), serialized across topics: each
        # topic's pruned peers may open one new connection toward a mesh
        # neighbor of their pruner (``ops/px.py``'s conflict discipline
        # holds within each call), and the scan threads the SHARED adjacency
        # through the topics so no two topics race writes into one slot.
        # Earlier topics win free slots first — spec-plausible (the wire has
        # no cross-topic PX ordering either).  Gossip/IHAVE above ran on the
        # pre-PX snapshot, a one-heartbeat lag a wire peer also sees.
        from ..ops.px import px_rewire

        def px_step(carry, xs):
            nbrs_c, rev_c, nv_c, ob_c = carry
            mesh_topic, pruned_topic, bo_topic, kpx = xs
            px = px_rewire(
                kpx, nbrs_c, rev_c, nv_c, ob_c, bo_topic, mesh_topic,
                pruned_topic, scores, st.alive, sp.accept_px_threshold,
            )
            return (px.nbrs, px.rev, px.nbr_valid, px.outbound), (
                px.backoff, px.connected
            )

        (nbrs2, rev2, nv2, ob2), (backoff, connected) = jax.lax.scan(
            px_step, (st.nbrs, st.rev, st.nbr_valid, st.outbound),
            (mesh, pruned_t, backoff, kpx_t),
        )
        # Per-topic liveness caches are regathered only when a PX edge
        # actually formed (rare; the cond skips T gathers otherwise).
        edge_live = jax.lax.cond(
            connected.any(),
            lambda: jax.vmap(compute_edge_live, (None, None, 0))(
                nv2, nbrs2, st.alive[None, :] & st.subscribed
            ),
            lambda: st.edge_live,
        )
        return st._replace(
            nbrs=nbrs2, rev=rev2, nbr_valid=nv2, outbound=ob2,
            edge_live=edge_live,
            mesh=mesh, fanout=fanout, fanout_age=fanout_age, backoff=backoff,
            counters=c, gcounters=g, scores=scores, have_w=have_w,
            gossip_pend_w=pend, iwant_pend_w=iwant_w, msg_active=mactive,
            keys=keys,
        )

    @functools.partial(jax.jit, static_argnums=0)
    def step(self, st: MultiTopicState) -> MultiTopicState:
        st = self._widen_indices(st)
        st = self._propagate(st)
        st = jax.lax.cond(
            (st.step % self.heartbeat_steps) == self.heartbeat_steps - 1,
            self._heartbeat,
            lambda s: s,
            st,
        )
        return self._narrow_indices(st._replace(step=st.step + 1))

    @functools.partial(jax.jit, static_argnames=("self", "n_steps"))
    def run(self, st: MultiTopicState, n_steps: int) -> MultiTopicState:
        def body(s, _):
            return self.step(s), None

        st, _ = jax.lax.scan(body, st, None, length=n_steps)
        return st

    # -- scenario engine ----------------------------------------------------

    def flight_record_round(self, st: MultiTopicState):
        """One round's telemetry across all topics (device scalars + one
        summed latency histogram).

        Unlike the single-topic recorder there is no receipt tap threaded
        through the vmapped propagate, so the histogram is RECOUNTED from
        the stamp table each round (``latency_histogram`` vmapped over
        topics) — an [T, N, M] pass per round that is fine at scenario
        scale and deliberately not the 100k-peer bench path.
        """
        from .gossipsub import FLIGHT_HIST_BINS
        from ..ops import histogram as hist_ops

        topic_alive = self._topic_alive(st)                   # [T, N]
        in_window = st.msg_used & st.msg_valid                # [T, M]
        hist = jax.vmap(
            hist_ops.latency_histogram, (0, 0, 0, 0, None)
        )(
            st.first_step, st.msg_birth, in_window, topic_alive,
            FLIGHT_HIST_BINS,
        ).sum(axis=0)
        expected = (
            topic_alive.sum(axis=1) * in_window.sum(axis=1)
        ).sum()
        mesh_deg = (st.mesh & st.nbr_valid[None]).sum(axis=2)  # [T, N]
        part_total = jnp.maximum(topic_alive.sum(), 1)
        return {
            "step": st.step,
            "peers_alive": st.alive.sum(),
            "delivery_frac": hist.sum() / jnp.maximum(expected, 1),
            "mesh_degree_mean": jnp.where(topic_alive, mesh_deg, 0).sum()
            / part_total,
            "gossip_pending": bitpack.popcount(st.gossip_pend_w).sum(),
            "lat_hist": hist,
        }

    @functools.partial(jax.jit, static_argnames=("self", "record"))
    def rollout_events(self, st: MultiTopicState, events, record: bool = True):
        """Run a whole event schedule (``ops.schedule.MultiTopicEvents``) in
        ONE ``lax.scan`` -> (final state, flight record | None); the
        multi-topic twin of ``GossipSub.rollout_events`` (kills, mute and
        delay windows, topic-stamped publishes)."""
        n_steps = int(events.kill.shape[0])

        def body(s, ev):
            s = jax.lax.cond(
                ev.kill.any(),
                lambda x: x._replace(
                    alive=x.alive & ~ev.kill,
                    edge_live=jax.vmap(compute_edge_live, (None, None, 0))(
                        x.nbr_valid, x.nbrs,
                        (x.alive & ~ev.kill)[None, :] & x.subscribed,
                    ),
                ),
                lambda x: x,
                s,
            )
            s = jax.lax.cond(
                ev.mute_on.any() | ev.mute_off.any(),
                lambda x: x._replace(
                    gossip_mute=(x.gossip_mute & ~ev.mute_off) | ev.mute_on
                ),
                lambda x: x,
                s,
            )
            s = jax.lax.cond(
                (ev.delay >= 0).any(),
                lambda x: x._replace(
                    gossip_delay=jnp.where(
                        ev.delay >= 0, ev.delay, x.gossip_delay
                    )
                ),
                lambda x: x,
                s,
            )
            for i in range(ev.pub_src.shape[0]):
                s = jax.lax.cond(
                    (ev.pub_src[i] >= 0) & (ev.pub_topic[i] >= 0),
                    lambda x, j=i: self.publish(
                        x,
                        jnp.clip(ev.pub_topic[j], 0, self.t - 1),
                        ev.pub_src[j],
                        jnp.clip(ev.pub_slot[j], 0, self.m - 1),
                        ev.pub_valid[j],
                    ),
                    lambda x: x,
                    s,
                )
            s = self.step(s)
            return s, (self.flight_record_round(s) if record else None)

        return jax.lax.scan(body, st, events, length=n_steps)

    # -- views / metrics ----------------------------------------------------

    def have_bool(self, st: MultiTopicState) -> jax.Array:
        """bool[T, N, M] possession view."""
        return bitpack.unpack(st.have_w, self.m)

    @functools.partial(jax.jit, static_argnums=0)
    def delivery_stats(self, st: MultiTopicState):
        """Per-topic (frac[T, M], p50[T], p99[T]) over subscribed+alive."""
        topic_alive = self._topic_alive(st)           # [T, N]
        have = self.have_bool(st)                     # [T, N, M]
        alive_n = jnp.maximum(topic_alive.sum(axis=1), 1)   # [T]
        delivered = (have & topic_alive[:, :, None]).sum(axis=1)  # [T, M]
        frac = jnp.where(
            st.msg_used & st.msg_valid,
            delivered / alive_n[:, None],
            jnp.nan,
        )
        lat = jnp.where(
            st.first_step >= 0,
            st.first_step - st.msg_birth[:, None, :],
            -1,
        )
        ok = (
            (lat >= 0)
            & st.msg_used[:, None, :]
            & st.msg_valid[:, None, :]
            & topic_alive[:, :, None]
        )
        lat_f = jnp.where(ok, lat.astype(jnp.float32), jnp.nan)
        flat = lat_f.reshape(self.t, -1)
        p50 = jnp.nanmedian(flat, axis=1)
        p99 = jnp.nanpercentile(flat, 99.0, axis=1)
        return frac, p50, p99

    @functools.partial(jax.jit, static_argnums=0)
    def stream_digest(self, st: MultiTopicState):
        """Per-slot completion counters for the streaming engine.

        One small device_get per chunk: the engine compares
        ``delivered[topic, slot]`` against its completion threshold to close
        out pending messages, so ingest→delivery latency comes from host
        clocks rather than a modeled round count.
        """
        topic_alive = self._topic_alive(st)           # [T, N]
        have = self.have_bool(st)                     # [T, N, M]
        return {
            "delivered": (have & topic_alive[:, :, None]).sum(axis=1),  # [T, M]
            "participants": topic_alive.sum(axis=1),                    # [T]
            "msg_used": st.msg_used,
            "msg_valid": st.msg_valid,
            "msg_birth": st.msg_birth,
            "step": st.step,
        }

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def stream_deliver_steps(
        self, st: MultiTopicState, chunk_steps: int, completion_frac
    ) -> jax.Array:
        """Per-(topic, slot) delivery ROUND within the chunk that just ran:
        the first of the chunk's ``chunk_steps`` rounds at which the count
        of participants with ``first_step <= round`` reached ``max(1,
        completion_frac * participants[t])``; the chunk's first round when
        the threshold was already crossed before it (the engine clamps to
        the chunk window anyway), -1 where it has not been crossed.
        Counting over the chunk's candidate rounds instead of sorting all
        N first-receipt steps keeps the traced-path cost a tiny fraction
        of the chunk itself.  Host-called by the streaming engine only
        when tracing is on — it is a separate jitted digest, never part of
        the resident chunk, and it takes the frac (not host-computed
        targets) so the engine can dispatch it before its blocking digest
        fetch."""
        topic_alive = self._topic_alive(st)           # [T, N]
        participants = topic_alive.sum(axis=1)        # [T]
        targets = jnp.maximum(
            1, (completion_frac * participants).astype(jnp.int32)
        )
        valid = (st.first_step >= 0) & topic_alive[:, :, None]  # [T, N, M]
        cand = st.step - chunk_steps + jnp.arange(chunk_steps)  # [S]
        counts = (
            valid[:, None, :, :]
            & (st.first_step[:, None, :, :] <= cand[None, :, None, None])
        ).sum(axis=2)                                 # [T, S, M]
        crossed = counts >= targets[:, None, None]    # [T, S, M]
        first = jnp.argmax(crossed, axis=1)           # first crossing idx
        return jnp.where(crossed.any(axis=1), cand[first], -1)  # [T, M]
