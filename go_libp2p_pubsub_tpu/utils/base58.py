"""base58btc codec + libp2p-style peer-id helpers.

The reference's ``translPeerIDs`` (``/root/reference/subtree.go:228-239``)
decodes the base58 peer-id strings carried in ``Message.Peers`` into
``peer.ID`` values before dialing them, erroring on malformed entries.  The
live plane keeps peer ids as opaque strings (``net/transport.Peerstore``),
so the equivalent boundary is validation: :func:`transl_peer_ids` filters a
wire-carried candidate list down to well-formed ids, and :class:`Peerstore`
construction can opt into strict ids (``validate_ids=True`` there).

Formats (the two libp2p peer-id shapes in the wild):

- sha256 multihash ids: ``0x12 0x20 || digest32`` -> base58 starts "Qm";
- identity multihash ids of an ed25519 public key protobuf:
  ``0x00 0x24 || 0x08 0x01 0x12 0x20 || pub32`` -> base58 starts "12D3KooW".
"""

from __future__ import annotations

from typing import List, Optional

ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(ALPHABET)}

# multihash codes (the two used by libp2p peer ids)
MH_IDENTITY = 0x00
MH_SHA2_256 = 0x12
# ed25519 public-key protobuf header: field 1 (KeyType) = 1 (Ed25519),
# field 2 (Data) length 32.
ED25519_PB_PREFIX = b"\x08\x01\x12\x20"


def b58encode(raw: bytes) -> str:
    """base58btc encode (Bitcoin alphabet, leading zero bytes -> '1's)."""
    n_zeros = len(raw) - len(raw.lstrip(b"\x00"))
    num = int.from_bytes(raw, "big")
    out = []
    while num > 0:
        num, rem = divmod(num, 58)
        out.append(ALPHABET[rem])
    return "1" * n_zeros + "".join(reversed(out))


def b58decode(s: str) -> bytes:
    """base58btc decode; raises ``ValueError`` on characters outside the
    alphabet (0, O, I, l are excluded by design)."""
    num = 0
    for c in s:
        try:
            num = num * 58 + _INDEX[c]
        except KeyError:
            raise ValueError(f"invalid base58 character {c!r}") from None
    n_zeros = len(s) - len(s.lstrip("1"))
    body = num.to_bytes((num.bit_length() + 7) // 8, "big") if num else b""
    return b"\x00" * n_zeros + body


def peer_id_from_sha256(digest: bytes) -> str:
    """sha256-multihash peer id ("Qm..." form) from a 32-byte digest."""
    if len(digest) != 32:
        raise ValueError(f"sha256 digest must be 32 bytes, got {len(digest)}")
    return b58encode(bytes([MH_SHA2_256, 32]) + digest)


def peer_id_from_ed25519_pub(pub: bytes) -> str:
    """identity-multihash peer id ("12D3KooW..." form) from a 32-byte
    ed25519 public key (inlined as the protobuf libp2p wraps keys in)."""
    if len(pub) != 32:
        raise ValueError(f"ed25519 public key must be 32 bytes, got {len(pub)}")
    inner = ED25519_PB_PREFIX + pub
    return b58encode(bytes([MH_IDENTITY, len(inner)]) + inner)


def parse_peer_id(s: str) -> bytes:
    """Decode + validate a peer-id string -> its multihash bytes.

    The decode half of ``translPeerIDs``: raises ``ValueError`` for anything
    that is not a well-formed base58 multihash of a known shape.
    """
    raw = b58decode(s)
    if len(raw) < 2:
        raise ValueError(f"peer id too short: {s!r}")
    code, length = raw[0], raw[1]
    body = raw[2:]
    if len(body) != length:
        raise ValueError(
            f"peer id length mismatch: header says {length}, got {len(body)}"
        )
    if code == MH_SHA2_256:
        if length != 32:
            raise ValueError(f"sha256 peer id must carry 32 bytes, got {length}")
    elif code == MH_IDENTITY:
        if not body.startswith(ED25519_PB_PREFIX) or len(body) != 36:
            raise ValueError(f"identity peer id is not an ed25519 key: {s!r}")
    else:
        raise ValueError(f"unknown multihash code 0x{code:02x} in peer id {s!r}")
    return raw


def ed25519_pub_from_peer_id(s: str) -> Optional[bytes]:
    """The 32-byte ed25519 public key inlined in an identity peer id, or
    ``None`` for digest-form ids (key not recoverable from a hash)."""
    raw = parse_peer_id(s)
    if raw[0] == MH_IDENTITY:
        return raw[2 + len(ED25519_PB_PREFIX):]
    return None


def transl_peer_ids(peers: List[str]) -> List[str]:
    """Filter a wire-carried candidate-parent list to well-formed peer ids.

    ``translPeerIDs`` (``subtree.go:228-239``) fails the whole join on the
    first malformed id; dropping just the bad entries keeps the remaining
    candidates usable — a documented deviation (the join walk then tries the
    valid ones instead of aborting).
    """
    out = []
    for s in peers:
        try:
            parse_peer_id(s)
        except ValueError:
            continue
        out.append(s)
    return out
