"""Metrics & observability: counters as reduced device arrays.

The reference's only observability is a ``go-log`` logger with ~20 call
sites and zero counters (SURVEY.md §5.5).  The TPU-native design inverts
this: the interesting quantities (deliveries, repairs, mesh health, score
distribution, validation throughput) already *are* device arrays inside the
state, so metrics are pure jitted reductions over state — no instrumentation
in the hot loop, no host sync until the host asks for a snapshot.

Two pieces:
- pure reduction functions ``tree_metrics`` / ``gossip_metrics`` over the
  engine states (device-side, jittable, safe to call every step);
- a tiny host-side ``MetricsRegistry`` aggregating named scalar series for
  export (the Prometheus-shaped surface the Go ecosystem would expect).
"""

from __future__ import annotations

import json
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import bitpack


# ---------------------------------------------------------------------------
# device-side reductions
# ---------------------------------------------------------------------------

@jax.jit
def tree_metrics(st) -> Dict[str, jax.Array]:
    """Reductions over a ``TreeState`` (the v0-parity engine).

    Mirrors what the reference could only learn by grepping logs: delivery
    totals (``client.go:124-127``), membership (``subtree.go:152``), orphan
    backlog (the repair window of SURVEY.md §3.6/§3.7).
    """
    alive = st.alive
    joined = st.joined & alive
    orphaned = alive & ~st.joined & (st.join_target >= 0)
    return {
        "peers_alive": alive.sum(),
        "peers_joined": joined.sum(),
        "peers_orphaned": orphaned.sum(),
        "msgs_delivered_total": st.out_len.sum(),
        "msgs_undrained": (st.out_len - st.out_drained).sum(),
        "queue_backlog": st.q_len.sum(),
        "max_queue_depth": st.q_len.max(),
        "tree_depth_proxy": st.subtree_size.max(),
        "step": st.step_num,
    }


@jax.jit
def gossip_metrics(st) -> Dict[str, jax.Array]:
    """Reductions over a ``GossipState``: mesh health + delivery + scoring."""
    alive = st.alive
    alive_n = jnp.maximum(alive.sum(), 1)
    mesh_deg = (st.mesh & st.nbr_valid).sum(axis=1)
    in_window = st.msg_used & st.msg_valid
    have = bitpack.unpack(st.have_w, st.msg_valid.shape[0])
    delivered = (have & alive[:, None]).sum(axis=0)
    frac = jnp.where(in_window, delivered / alive_n, jnp.nan)
    scores_live = jnp.where(st.nbr_valid, st.scores, jnp.nan)
    return {
        "peers_alive": alive.sum(),
        "mesh_degree_mean": jnp.where(alive, mesh_deg, 0).sum() / alive_n,
        "mesh_degree_max": mesh_deg.max(),
        "msgs_in_window": in_window.sum(),
        "delivery_frac_mean": jnp.nanmean(frac),
        "deliveries_total": (have & alive[:, None] & in_window[None, :]).sum(),
        "score_mean": jnp.nanmean(scores_live),
        "score_min": jnp.nanmin(scores_live),
        "gossip_pending": bitpack.popcount(st.gossip_pend_w).sum(),
        "step": st.step,
    }


def snapshot(metrics: Dict[str, jax.Array]) -> Dict[str, float]:
    """One host sync for a whole metrics dict (device_get once, not per key)."""
    host = jax.device_get(metrics)
    return {k: float(v) for k, v in host.items()}


def quantiles(samples, qs: Tuple[float, ...] = (0.5, 0.99)) -> Dict[str, float]:
    """Host-side exact quantiles over raw samples, keyed ``p50``-style.

    The streaming engine's ingest→delivery latencies are a host list, not a
    device histogram, so unlike ``flight_summary`` no bucket interpolation
    is involved.  Empty input yields NaNs (nothing completed yet).
    """
    import numpy as _np

    keys = [f"p{round(q * 100, 6):g}" for q in qs]
    if len(samples) == 0:
        return {k: float("nan") for k in keys}
    vals = _np.percentile(_np.asarray(samples, dtype=_np.float64),
                          [q * 100.0 for q in qs])
    return {k: float(v) for k, v in zip(keys, vals)}


def flight_summary(record: Dict[str, jax.Array]) -> Dict[str, Any]:
    """Host-side digest of a rollout flight record (one ``device_get``).

    ``record`` is the stacked ys pytree of ``GossipSub.rollout(record=True)``
    (or the treecast twin): scalar series come back as plain float lists
    keyed by name, and when a ``lat_hist`` series is present its FINAL row
    (the cumulative receipt histogram at rollout end) is kept alongside
    histogram-derived p50/p99 — the same quantile arithmetic
    ``delivery_stats`` computes from the raw [N, M] table, at i32[B] cost.
    This is the dict the bench embeds in its JSON line.
    """
    import numpy as np

    from ..ops.histogram import hist_quantile

    host = jax.device_get(record)
    out: Dict[str, Any] = {"series": {}}
    for name, arr in sorted(host.items()):
        a = np.asarray(arr)
        if a.ndim == 1:
            out["series"][name] = [round(float(v), 6) for v in a]
    if "lat_hist" in host:
        final = np.asarray(host["lat_hist"])[-1]
        out["lat_hist"] = [int(v) for v in final]
        out["lat_p50"] = float(hist_quantile(jnp.asarray(final), 0.5))
        out["lat_p99"] = float(hist_quantile(jnp.asarray(final), 0.99))
    return out


# ---------------------------------------------------------------------------
# host-side registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Named scalar time series with counter/gauge semantics.

    The host plane (``net/live.py``) and benchmark harnesses record here;
    ``export()`` emits JSON lines, the build's analog of a metrics endpoint.
    """

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._counters: Dict[str, float] = {}
        self._series: Dict[str, List[Tuple[float, float]]] = {}
        # Decorated key -> (base name, sorted (label, value) items); plain
        # keys have no entry and render label-less.
        self._meta: Dict[str, Tuple[str, Tuple[Tuple[str, str], ...]]] = {}
        self._help: Dict[str, str] = {}

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        key = self._key(name, labels)
        self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, str]] = None) -> None:
        key = self._key(name, labels)
        self._series.setdefault(key, []).append((self._clock(), float(value)))

    def describe(self, name: str, help_text: str) -> None:
        """Attach a HELP string to a metric's BASE name (pre-sanitization);
        undescribed metrics render their original dotted name as HELP."""
        self._help[name] = help_text

    def _key(self, name: str, labels: Optional[Dict[str, str]]) -> str:
        if not labels:
            return name
        items = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        key = name + "{" + ",".join(f"{k}={v}" for k, v in items) + "}"
        self._meta[key] = (name, items)
        return key

    def observe_state(self, prefix: str, metrics: Dict[str, jax.Array]) -> None:
        """Record a device metrics dict as gauges under ``prefix.*``."""
        for k, v in snapshot(metrics).items():
            self.gauge(f"{prefix}.{k}", v)

    def counters(self) -> Dict[str, float]:
        return dict(self._counters)

    def counter(self, name: str, default: float = 0.0) -> float:
        """One counter's current value (``default`` if never incremented) —
        the read side the retry/breaker tests and the live scenario runner
        use to assert on transition counts."""
        return self._counters.get(name, default)

    def latest(self, name: str) -> Optional[float]:
        s = self._series.get(name)
        return s[-1][1] if s else None

    def series_max(self, name: str) -> Optional[float]:
        """Max value ever recorded on a gauge series (peak queue depth and
        friends), or None if the series was never written."""
        s = self._series.get(name)
        return max(v for _, v in s) if s else None

    def export(self) -> str:
        """All counters + latest gauges as one JSON object string."""
        out: Dict[str, Any] = {f"counter.{k}": v for k, v in self._counters.items()}
        for name, series in self._series.items():
            out[f"gauge.{name}"] = series[-1][1]
        return json.dumps(out, sort_keys=True)

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format version 0.0.4) of all counters
        and the latest sample of every gauge series — the body the live and
        serving planes' ``/metrics`` endpoints serve.  Audited against the
        exposition format (r18): per-metric ``# HELP`` + ``# TYPE`` lines
        (HELP defaults to the original dotted name, with backslash/newline
        escaping), names sanitized to the metric grammar (dots and other
        illegal runes become ``_``), counters suffixed ``_total``, and
        labeled series rendered with escaped label values under ONE shared
        HELP/TYPE header per base metric."""
        lines: List[str] = []
        self._render_family(lines, "counter", self._counters,
                            lambda v: v)
        self._render_family(lines, "gauge", self._series,
                            lambda s: s[-1][1])
        return "\n".join(lines) + "\n"

    def _render_family(self, lines: List[str], kind: str, store: Dict,
                       value_of) -> None:
        groups: Dict[str, List[Tuple[Tuple[Tuple[str, str], ...], Any]]] = {}
        for key in store:
            base, labels = self._meta.get(key, (key, ()))
            groups.setdefault(base, []).append((labels, value_of(store[key])))
        for base in sorted(groups):
            pn = _prometheus_name(base) + ("_total" if kind == "counter"
                                           else "")
            help_text = _prometheus_help(self._help.get(base, base))
            lines.append(f"# HELP {pn} {help_text}")
            lines.append(f"# TYPE {pn} {kind}")
            for labels, value in sorted(groups[base], key=lambda p: p[0]):
                label_str = ""
                if labels:
                    label_str = "{" + ",".join(
                        f'{_prometheus_label_name(k)}='
                        f'"{_prometheus_label_value(v)}"'
                        for k, v in labels
                    ) + "}"
                lines.append(f"{pn}{label_str} {_prometheus_value(value)}")


def _prometheus_name(name: str) -> str:
    """Sanitize to the metric-name grammar ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not name or not re.match(r"[a-zA-Z_:]", name[0]):
        name = "_" + name
    return name


def _prometheus_label_name(name: str) -> str:
    """Label-name grammar is the metric grammar WITHOUT colons:
    ``[a-zA-Z_][a-zA-Z0-9_]*``."""
    name = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if not name or not re.match(r"[a-zA-Z_]", name[0]):
        name = "_" + name
    return name


def _prometheus_label_value(v: str) -> str:
    """Escape a label value per the exposition format: backslash, double
    quote, and line feed."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prometheus_help(text: str) -> str:
    """HELP text escaping: backslash and line feed only (quotes are legal
    in HELP)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _prometheus_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f.is_integer() else repr(f)
