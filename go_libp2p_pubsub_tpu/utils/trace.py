"""Tracing / profiling: step timers, XLA profiler capture, topology dumps.

The reference's entire tracing story is an unused debug tree-printer reaching
into private state (``printTree``, ``pubsub_test.go:204-229``) (SURVEY.md
§5.1).  Here the equivalents are first-class: wall-clock phase timers around
jitted calls (with ``block_until_ready`` so device work is actually measured),
an optional ``jax.profiler`` trace capture for XLA-level analysis, and
topology snapshot exporters that turn the device-resident overlay back into
host structures for inspection.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np


class StepTimer:
    """Accumulating named phase timer.

    ``with timer("propagate"): st = gs.step(st)`` — each phase records a
    wall-time sample; device work is fenced with ``block_until_ready`` on the
    value passed to ``fence`` (or skipped if none is set before exit).

    Every sample also keeps its start offset from the timer's construction,
    so the full phase timeline can be exported as a Chrome-trace /
    Perfetto-loadable JSON (``export_chrome_trace``) — the bench's phase
    breakdown becomes a viewable flame track instead of a flat dict.
    """

    def __init__(self):
        self.samples: Dict[str, List[float]] = {}
        # (name, start offset s, duration s) in completion order.
        self.events: List[Tuple[str, float, float]] = []
        self._epoch = time.perf_counter()
        self._fence_val: Any = None

    def fence(self, value: Any) -> Any:
        """Mark ``value`` to be block_until_ready'd when the phase closes."""
        self._fence_val = value
        return value

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if self._fence_val is not None:
                jax.block_until_ready(self._fence_val)
                self._fence_val = None
            dt = time.perf_counter() - t0
            self.samples.setdefault(name, []).append(dt)
            self.events.append((name, t0 - self._epoch, dt))

    def stats(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, xs in self.samples.items():
            a = np.asarray(xs)
            out[name] = {
                "count": int(a.size),
                "total_s": float(a.sum()),
                "mean_ms": float(a.mean() * 1e3),
                "p50_ms": float(np.percentile(a, 50) * 1e3),
                "max_ms": float(a.max() * 1e3),
            }
        return out

    def export_chrome_trace(self) -> str:
        """The recorded phases as Chrome trace-event JSON (complete "X"
        events, microsecond timestamps) — loadable in ``chrome://tracing``
        and Perfetto.  One process/thread track: the timer measures the
        host-side dispatch timeline, not per-device streams (use
        ``xla_trace`` for XLA-level tracks)."""
        events = [
            {
                "name": name,
                "cat": "phase",
                "ph": "X",
                "ts": round(start * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": 0,
                "tid": 0,
            }
            for name, start, dur in self.events
        ]
        return json.dumps(
            {"traceEvents": events, "displayTimeUnit": "ms"}, sort_keys=True
        )


@contextlib.contextmanager
def xla_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA profiler trace into ``log_dir`` (TensorBoard-viewable).

    No-op when ``log_dir`` is None, so callers can wire it to a config flag
    unconditionally.
    """
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# topology snapshot export (the printTree analog)
# ---------------------------------------------------------------------------

def export_tree(st) -> Dict[int, Any]:
    """TreeState -> nested {peer: {child: {...}}} dict rooted at ``st.root``.

    Host-side, for debugging and golden-topology assertions; the recursive
    shape mirrors what ``printTree`` printed from private Go state.
    """
    parent = np.asarray(jax.device_get(st.parent))
    joined = np.asarray(jax.device_get(st.joined))
    root = int(jax.device_get(st.root))
    kids: Dict[int, List[int]] = {}
    for p in range(parent.shape[0]):
        if joined[p] and parent[p] >= 0:
            kids.setdefault(int(parent[p]), []).append(p)

    # Iterative DFS: a width-1 chain is a legal topology, so depth can reach
    # N — far past Python's recursion limit at sim scale.
    out: Dict[int, Any] = {root: {}}
    stack: List[tuple] = [(root, out[root])]
    visited = {root}
    while stack:
        node, slot = stack.pop()
        for c in kids.get(node, []):
            if c in visited:  # cycle — never legal in a tree
                raise ValueError(f"cycle detected at peer {c}")
            visited.add(c)
            slot[c] = {}
            stack.append((c, slot[c]))
    return out


def tree_text(st) -> str:
    """Indented text rendering of ``export_tree`` (one peer per line)."""
    lines: List[str] = []
    stack: List[tuple] = [(node, 0, d) for node, d in
                          sorted(export_tree(st).items(), reverse=True)]
    while stack:
        node, depth, d = stack.pop()
        lines.append("  " * depth + str(node))
        stack.extend((c, depth + 1, d[c]) for c in sorted(d, reverse=True))
    return "\n".join(lines)


def export_mesh(st) -> Dict[int, List[int]]:
    """GossipState -> {peer: sorted mesh-neighbor ids} adjacency dict."""
    mesh = np.asarray(jax.device_get(st.mesh & st.nbr_valid))
    from ..ops.graphs import decode_index_plane

    nbrs = np.asarray(decode_index_plane(jax.device_get(st.nbrs)))
    alive = np.asarray(jax.device_get(st.alive))
    out: Dict[int, List[int]] = {}
    for p in range(mesh.shape[0]):
        if alive[p]:
            out[p] = sorted(int(nbrs[p, s]) for s in np.nonzero(mesh[p])[0])
    return out
