"""Aux subsystems (SURVEY.md §5): checkpointing, metrics, fault injection,
tracing/profiling, structured logging.

All device-facing pieces are pure functions over the engine states; nothing
here touches the hot loops.
"""

from . import checkpoint, faults, metrics, trace
from .log import get_logger, kv

__all__ = ["checkpoint", "faults", "metrics", "trace", "get_logger", "kv"]
