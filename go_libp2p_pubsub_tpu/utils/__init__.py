"""Aux subsystems: logging, metrics, checkpointing, fault injection, tracing."""
