"""Structured logging: the ``go-log`` "pubsub" logger, done host-side.

The reference logs through a package-level ``go-log`` logger named
``"pubsub"`` (``client.go:16``) with ~20 Error/Info call sites and no
structure (SURVEY.md §5.5).  The framework's device engines never log (pure
functions); the host plane (live transport, API layer, benchmarks) logs here
— stdlib ``logging`` with a key=value formatter so lines stay grep-able and
machine-parseable.
"""

from __future__ import annotations

import logging
import sys
from typing import Any

_CONFIGURED = False


class _KVFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        kvs = getattr(record, "kv", None)
        if kvs:
            pairs = " ".join(f"{k}={v}" for k, v in sorted(kvs.items()))
            return f"{base} {pairs}"
        return base


def get_logger(name: str = "pubsub") -> logging.Logger:
    """A logger under the ``pubsub`` hierarchy; idempotent handler setup.

    Any requested name is rooted under ``pubsub`` (``get_logger("bench")``
    -> ``pubsub.bench``) so every framework logger shares the one configured
    handler instead of silently propagating to a handler-less root.
    """
    global _CONFIGURED
    if name != "pubsub" and not name.startswith("pubsub."):
        name = f"pubsub.{name}"
    logger = logging.getLogger(name)
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            _KVFormatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        root = logging.getLogger("pubsub")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _CONFIGURED = True
    return logger


def kv(**fields: Any) -> dict:
    """Structured-field helper: ``log.info("joined", extra=kv(peer=3))``."""
    return {"kv": fields}
