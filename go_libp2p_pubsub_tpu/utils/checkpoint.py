"""Checkpoint / resume of device-resident sim state.

The reference has no persistence at all (SURVEY.md §5.4); its nearest analog
is the in-protocol pause/resume across a parent swap (``client.go:106-122``,
``subtree.go:31,315``), which preserves subscriber state while the transport
underneath is replaced.  This module is the framework-level generalization:
snapshot *any* state pytree (``TreeState``, ``GossipState``, stacked
multi-topic states, score counters) to disk and restore it into a fresh
process, so long-running 100k-peer simulations survive restarts.

Format: one ``.npz`` archive.  Leaves are addressed by their
``jax.tree_util`` keypath string, so nested NamedTuples round-trip without a
schema; restore is template-driven (the orbax "restore with target" pattern)
which validates structure, shape, and dtype against the live code's state
definition instead of trusting the file.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
from typing import Any, Dict, Optional

import jax
import numpy as np

_META_KEY = "__pubsub_ckpt_meta__"
_FORMAT_VERSION = 1
_TOPIC_STATE_VERSION = 1


def _atomic_write(path: str, write_fn) -> None:
    """Write a file atomically: temp file in the target directory, fsync,
    then ``os.replace``.  A crash at any point leaves either the previous
    file intact or the new one complete — never a torn write.  The fsync
    before the rename is what upgrades "atomic against concurrent readers"
    to "atomic against power loss": without it the rename can be durable
    while the data is not."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _leaf_paths(tree: Any):
    """[(keystr, leaf)] for every array leaf, in treedef order."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat], treedef


def warm_serialize(state: Any) -> int:
    """Pay ``save``'s first-call serialization cost against an in-memory
    buffer: the full-state ``device_get``, the pytree flatten, and the
    ``np.savez`` zip machinery all have cold paths worth ~100 ms on first
    use.  A server that snapshots on a cadence calls this during warmup so
    the first REAL snapshot doesn't land that stall inside a
    traffic-bearing chunk's wall.  Writes nothing to disk.  Returns the
    serialized byte count (useful as a capacity-planning gauge)."""
    pairs, _ = _leaf_paths(state)
    arrays = {
        key: np.asarray(jax.device_get(leaf)) for key, leaf in pairs
    }
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.tell()


def save(path: str, state: Any, meta: Optional[Dict[str, Any]] = None) -> None:
    """Snapshot ``state`` (any pytree of arrays) to ``path`` atomically.

    ``meta`` is an optional JSON-serializable dict stored alongside the
    arrays (e.g. step count, config hash, wall-clock).
    """
    pairs, _ = _leaf_paths(state)
    arrays = {}
    for key, leaf in pairs:
        if key in arrays:
            raise ValueError(f"duplicate keypath {key!r} in state pytree")
        arrays[key] = np.asarray(jax.device_get(leaf))
    header = {"format_version": _FORMAT_VERSION, "meta": meta or {}}
    arrays[_META_KEY] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8
    )
    # Write-then-fsync-then-rename so a crash mid-save never corrupts the
    # previous checkpoint — the property the reference's repair window lacks
    # for in-flight messages (SURVEY.md §3.7).
    _atomic_write(path, lambda f: np.savez(f, **arrays))


def meta(path: str) -> Dict[str, Any]:
    """Read just the metadata header of a checkpoint."""
    with np.load(path) as z:
        header = json.loads(bytes(z[_META_KEY]).decode("utf-8"))
    return header["meta"]


def restore(path: str, template: Any, device_put: bool = True) -> Any:
    """Load a checkpoint into the structure of ``template``.

    ``template`` supplies the pytree structure (e.g. a fresh
    ``tree_ops.init_state(...)`` / ``GossipSub.init()``); every leaf in the
    file must match the template leaf's shape and dtype.  Extra or missing
    leaves are errors — silent partial restores are how stale sims lie.
    """
    pairs, treedef = _leaf_paths(template)
    with np.load(path) as z:
        header = json.loads(bytes(z[_META_KEY]).decode("utf-8"))
        if header["format_version"] != _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {header['format_version']} != "
                f"supported {_FORMAT_VERSION}"
            )
        file_keys = {k for k in z.files if k != _META_KEY}
        want_keys = {k for k, _ in pairs}
        if file_keys != want_keys:
            missing = sorted(want_keys - file_keys)
            extra = sorted(file_keys - want_keys)
            raise ValueError(
                f"checkpoint/template mismatch: missing={missing} extra={extra}"
            )
        leaves = []
        for key, tmpl_leaf in pairs:
            arr = z[key]
            t = np.asarray(tmpl_leaf)
            if arr.shape != t.shape or arr.dtype != t.dtype:
                raise ValueError(
                    f"leaf {key!r}: checkpoint {arr.shape}/{arr.dtype} != "
                    f"template {t.shape}/{t.dtype}"
                )
            leaves.append(arr)
    out = jax.tree_util.tree_unflatten(treedef, leaves)
    if device_put:
        out = jax.device_put(out)
    return out


# ---------------------------------------------------------------------------
# durable topic state (live-plane root failover, net/live.py)
# ---------------------------------------------------------------------------


def save_topic_state(path: str, state: Dict[str, Any]) -> None:
    """Persist a live topic's control state ``{epoch, seq, successors,
    roster, ...}`` atomically (same write-temp/fsync/rename discipline as
    :func:`save`).  The payload is small JSON, not arrays: a restarted host
    reads it before joining so it re-enters at the *current* epoch instead
    of resurrecting a stale tree."""
    doc = {"format_version": _TOPIC_STATE_VERSION, "state": state}
    body = json.dumps(doc, sort_keys=True).encode("utf-8")
    _atomic_write(path, lambda f: f.write(body))


def load_topic_state(path: str) -> Dict[str, Any]:
    """Read a topic-state file written by :func:`save_topic_state`."""
    with open(path, "rb") as f:
        doc = json.loads(f.read().decode("utf-8"))
    if doc.get("format_version") != _TOPIC_STATE_VERSION:
        raise ValueError(
            f"topic state format {doc.get('format_version')} != "
            f"supported {_TOPIC_STATE_VERSION}"
        )
    return doc["state"]
