"""Fault injection: scheduled failures as data, not test plumbing.

The reference injects faults by literally killing hosts mid-test
(``hosts[1].Close()``, ``pubsub_test.go:178``) or closing a subscription for
a graceful ``Part`` (``pubsub_test.go:301``), and its failure *detection* is
scattered across read-EOF / write-error / Part paths (SURVEY.md §5.3).  In
the array engines liveness is already a mask tensor, so a fault campaign is
just a schedule of mask edits applied at chosen steps — deterministic,
replayable, and identical between the treecast and gossipsub engines.

Also provides the attack-trace generators behind BASELINE.json config (d):
sybil IP-colocation groups and eclipse (targeted mesh capture) campaigns for
the peer-scoring subsystem.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class FaultPlan:
    """A deterministic schedule of fault events over a rollout.

    ``kills[t]``  — bool[N] peers abruptly dead at the *start* of step t
                    (no Part; detection is lazy, like ``subtree.go:333-336``).
    ``leaves[t]`` — bool[N] peers requesting graceful leave at step t
                    (tree engine only; the ``Part`` path, ``subtree.go:78-98``).
    """

    kills: Dict[int, np.ndarray] = dataclasses.field(default_factory=dict)
    leaves: Dict[int, np.ndarray] = dataclasses.field(default_factory=dict)

    @staticmethod
    def _as_mask(peers, n: int) -> np.ndarray:
        """``peers`` (index list or bool mask) -> validated bool[n].

        Rejects out-of-range indices (negative ones would silently wrap in
        fancy indexing — a plan targeting peer ``n`` or ``-1`` is a bug in
        the caller, not a request for the last row) and bool masks whose
        length disagrees with ``n``.
        """
        peers = np.asarray(peers)
        if peers.size == 0:
            return np.zeros(n, bool)
        if peers.dtype == bool:
            if peers.shape != (n,):
                raise ValueError(
                    f"bool peer mask has shape {peers.shape}, expected ({n},)"
                )
            return peers.copy()
        if peers.size and not np.issubdtype(peers.dtype, np.integer):
            raise TypeError(
                f"peers must be integer indices or a bool mask, got dtype "
                f"{peers.dtype}"
            )
        if peers.size and (peers.min() < 0 or peers.max() >= n):
            bad = peers[(peers < 0) | (peers >= n)]
            raise ValueError(
                f"peer indices {bad.tolist()} out of range [0, {n})"
            )
        m = np.zeros(n, bool)
        m[peers] = True
        return m

    def kill_at(self, step: int, peers, n: int) -> "FaultPlan":
        mask = self._as_mask(peers, n)
        prev = self.kills.get(step)
        if prev is not None and prev.shape != (n,):
            raise ValueError(
                f"step {step} already has a kill mask for n={prev.shape[0]}, "
                f"cannot extend it with n={n}"
            )
        self.kills[step] = mask if prev is None else (prev | mask)
        return self

    def leave_at(self, step: int, peers, n: int) -> "FaultPlan":
        mask = self._as_mask(peers, n)
        prev = self.leaves.get(step)
        if prev is not None and prev.shape != (n,):
            raise ValueError(
                f"step {step} already has a leave mask for n={prev.shape[0]}, "
                f"cannot extend it with n={n}"
            )
        self.leaves[step] = mask if prev is None else (prev | mask)
        return self

    def event_steps(self) -> List[int]:
        return sorted(set(self.kills) | set(self.leaves))

    def liveness_timeline(self, n_steps: int, n: int) -> np.ndarray:
        """bool[T, N]: expected alive mask at each step under this plan
        (kills only — graceful leavers stay alive).  The oracle tests assert
        engine state against."""
        alive = np.ones(n, bool)
        out = np.empty((n_steps, n), bool)
        for t in range(n_steps):
            if t in self.kills:
                alive &= ~self.kills[t]
            out[t] = alive
        return out


def run_with_faults(
    st,
    n_steps: int,
    run_fn: Callable,
    plan: FaultPlan,
    kill_fn: Callable,
    leave_fn: Optional[Callable] = None,
):
    """Drive ``run_fn(st, k)`` for ``n_steps``, applying plan events.

    LEGACY host-segmented path: the scenario engine lowers the same plan to
    device event tensors instead (``scenario.ScenarioSpec.from_fault_plan``
    -> one un-segmented ``rollout_events`` scan).  Kept for callers that
    need custom ``kill_fn`` semantics or un-lowered state edits between
    segments.

    The rollout is segmented at event steps: scan between events (device
    speed), apply mask edits at the boundary (one tiny host round-trip per
    event).  Works for both engines:

    - tree:   ``run_with_faults(st, T, tree_ops.run_steps, plan,
               lambda s, m: s._replace(alive=s.alive & ~m),
               lambda s, m: s._replace(leaving=s.leaving | m))``
    - gossip: ``run_with_faults(st, T, gs.run, plan, gs.kill_peers)``
    """
    import jax.numpy as jnp

    events = [t for t in plan.event_steps() if t < n_steps]
    cursor = 0
    for t in events:
        if t > cursor:
            st = run_fn(st, t - cursor)
            cursor = t
        if t in plan.kills:
            st = kill_fn(st, jnp.asarray(plan.kills[t]))
        if t in plan.leaves:
            if leave_fn is None:
                raise ValueError("plan has leaves but no leave_fn given")
            st = leave_fn(st, jnp.asarray(plan.leaves[t]))
    if n_steps > cursor:
        st = run_fn(st, n_steps - cursor)
    return st


# ---------------------------------------------------------------------------
# attack-trace generators (BASELINE config (d))
# ---------------------------------------------------------------------------

def sybil_ip_groups(
    n: int, n_sybils: int, group: int = 0, honest_unique: bool = True
) -> np.ndarray:
    """i32[N] IP-group ids where peers [0, n_sybils) share one group.

    Feeds ``ScoreParams.ip_colocation_factor_*`` (the P6 penalty): colocated
    sybils score quadratically negative and fall below the graft threshold.
    """
    if honest_unique:
        groups = np.arange(n, dtype=np.int32)
    else:
        groups = np.zeros(n, np.int32)
    groups[:n_sybils] = group
    return groups


def eclipse_campaign(
    rng: np.random.Generator,
    n: int,
    target: int,
    n_attackers: int,
    start_step: int,
    n_steps: int,
    churn_every: int = 8,
) -> Tuple[np.ndarray, FaultPlan]:
    """An eclipse attempt on ``target``: attackers [n-n_attackers, n) plus a
    kill schedule that churns the target's honest neighbors so attackers can
    occupy the vacated mesh slots.

    Returns (attacker_mask bool[N], plan).  The scoring defense under test:
    behaviour/invalid penalties must keep attacker scores below the graft
    threshold so the mesh refills from honest peers instead.
    """
    attackers = np.zeros(n, bool)
    attackers[n - n_attackers:] = True
    plan = FaultPlan()
    honest = np.array([p for p in range(n) if not attackers[p] and p != target])
    for i, t in enumerate(range(start_step, start_step + n_steps, churn_every)):
        victims = rng.choice(honest, size=min(2, len(honest)), replace=False)
        plan.kill_at(t, victims, n)
    return attackers, plan
