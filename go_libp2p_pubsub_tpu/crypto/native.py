"""ctypes bindings for the native C++ batched ed25519 (native/ed25519/).

This is the host data plane's validator: the component that fills the
reference's ``// TODO: add signature`` hole (``/root/reference/pubsub.go:117``)
at wire speed.  The library is built on demand with ``g++`` (no pybind11 in
this image; plain C ABI + ctypes keeps the binding dependency-free) and
cached next to the sources.

API surface (all batched, thread-parallel in C++):

- :func:`verify_batch` — the hot entry: n (pk, sig, msg) triples -> bool[n]
- :func:`sign_batch` / :func:`public_key_batch` — test/bench traffic factories
- :func:`sha512` / :func:`verify` / :func:`sign` / :func:`public_key` —
  single-item conveniences

Correctness contract: byte-identical accept/reject behavior with
``ed25519_ref`` (the Python oracle) and ``ops/ed25519.py`` (the device
kernel); enforced by ``tests/test_ed25519.py``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "native", "ed25519")
_LIB_PATH = os.path.join(_SRC_DIR, "libed25519_tpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    """The g++ build of the native library failed."""


def _build() -> None:
    src = os.path.join(_SRC_DIR, "ed25519.cpp")
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-o", _LIB_PATH, src,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=_SRC_DIR)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native ed25519 build failed:\n{proc.stderr[-4000:]}"
        )


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_SRC_DIR, "ed25519.cpp")
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
        ):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.ed25519_sha512.argtypes = [u8p, ctypes.c_uint64, u8p]
        lib.ed25519_public_key.argtypes = [u8p, u8p]
        lib.ed25519_sign.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.ed25519_verify.argtypes = [u8p, u8p, u8p, ctypes.c_uint64]
        lib.ed25519_verify.restype = ctypes.c_int
        lib.ed25519_verify_batch.argtypes = [
            u8p, u8p, u8p, u64p, ctypes.c_int64, ctypes.c_int, u8p,
        ]
        lib.ed25519_sign_batch.argtypes = [
            u8p, u8p, u64p, ctypes.c_int64, ctypes.c_int, u8p,
        ]
        lib.ed25519_public_key_batch.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int, u8p,
        ]
        _lib = lib
        return lib


def available() -> bool:
    """True if the native library is present or buildable."""
    try:
        _load()
        return True
    except (NativeBuildError, OSError):
        return False


def _as_u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _concat_msgs(msgs: Sequence[bytes]):
    offs = np.zeros(len(msgs) + 1, dtype=np.uint64)
    np.cumsum([len(m) for m in msgs], out=offs[1:])
    blob = np.frombuffer(b"".join(msgs), dtype=np.uint8) if msgs else np.zeros(0, np.uint8)
    if blob.size == 0:
        blob = np.zeros(1, np.uint8)  # valid pointer for empty batches
    return np.ascontiguousarray(blob), offs


def _threads(n: int, threads: Optional[int]) -> int:
    if threads is not None:
        return max(1, threads)
    return max(1, min(os.cpu_count() or 1, n))


def sha512(msg: bytes) -> bytes:
    lib = _load()
    m = np.frombuffer(msg, dtype=np.uint8) if msg else np.zeros(1, np.uint8)
    out = np.zeros(64, np.uint8)
    lib.ed25519_sha512(_as_u8p(np.ascontiguousarray(m)), len(msg), _as_u8p(out))
    return out.tobytes()


def public_key(seed: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError(f"seed must be 32 bytes, got {len(seed)}")
    lib = _load()
    s = np.frombuffer(seed, dtype=np.uint8).copy()
    out = np.zeros(32, np.uint8)
    lib.ed25519_public_key(_as_u8p(s), _as_u8p(out))
    return out.tobytes()


def sign(seed: bytes, msg: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError(f"seed must be 32 bytes, got {len(seed)}")
    lib = _load()
    s = np.frombuffer(seed, dtype=np.uint8).copy()
    m = np.frombuffer(msg, dtype=np.uint8) if msg else np.zeros(1, np.uint8)
    out = np.zeros(64, np.uint8)
    lib.ed25519_sign(_as_u8p(s), _as_u8p(np.ascontiguousarray(m)), len(msg), _as_u8p(out))
    return out.tobytes()


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    # Malformed authenticators are a defined reject, matching ed25519_ref —
    # the C side reads exactly 32/64 bytes and must never read past a short
    # buffer.
    if len(pk) != 32 or len(sig) != 64:
        return False
    lib = _load()
    p = np.frombuffer(pk, dtype=np.uint8).copy()
    g = np.frombuffer(sig, dtype=np.uint8).copy()
    m = np.frombuffer(msg, dtype=np.uint8) if msg else np.zeros(1, np.uint8)
    return bool(lib.ed25519_verify(_as_u8p(p), _as_u8p(g), _as_u8p(np.ascontiguousarray(m)), len(msg)))


def verify_batch(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    threads: Optional[int] = None,
) -> np.ndarray:
    """Verify n signatures in parallel; returns bool[n]."""
    n = len(pks)
    if not (n == len(msgs) == len(sigs)):
        raise ValueError("pks/msgs/sigs length mismatch")
    if n == 0:
        return np.zeros(0, bool)
    lib = _load()
    pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).copy()
    sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).copy()
    if pk_arr.size != 32 * n or sig_arr.size != 64 * n:
        raise ValueError("pks must be 32 bytes and sigs 64 bytes each")
    blob, offs = _concat_msgs(msgs)
    out = np.zeros(n, np.uint8)
    lib.ed25519_verify_batch(
        _as_u8p(pk_arr), _as_u8p(sig_arr), _as_u8p(blob),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, _threads(n, threads), _as_u8p(out),
    )
    return out.astype(bool)


def sign_batch(
    seeds: Sequence[bytes], msgs: Sequence[bytes], threads: Optional[int] = None
) -> List[bytes]:
    """Sign n messages in parallel; returns n 64-byte signatures."""
    n = len(seeds)
    if n != len(msgs):
        raise ValueError("seeds/msgs length mismatch")
    if n == 0:
        return []
    lib = _load()
    seed_arr = np.frombuffer(b"".join(seeds), dtype=np.uint8).copy()
    if seed_arr.size != 32 * n:
        raise ValueError("seeds must be 32 bytes each")
    blob, offs = _concat_msgs(msgs)
    out = np.zeros(64 * n, np.uint8)
    lib.ed25519_sign_batch(
        _as_u8p(seed_arr), _as_u8p(blob),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, _threads(n, threads), _as_u8p(out),
    )
    raw = out.tobytes()
    return [raw[64 * i : 64 * (i + 1)] for i in range(n)]


def public_key_batch(
    seeds: Sequence[bytes], threads: Optional[int] = None
) -> List[bytes]:
    n = len(seeds)
    if n == 0:
        return []
    lib = _load()
    seed_arr = np.frombuffer(b"".join(seeds), dtype=np.uint8).copy()
    if seed_arr.size != 32 * n:
        raise ValueError("seeds must be 32 bytes each")
    out = np.zeros(32 * n, np.uint8)
    lib.ed25519_public_key_batch(
        _as_u8p(seed_arr), n, _threads(n, threads), _as_u8p(out)
    )
    raw = out.tobytes()
    return [raw[32 * i : 32 * (i + 1)] for i in range(n)]
