"""Batched message-validation pipeline.

The reference publishes unsigned messages with a ``// TODO: add signature``
(``/root/reference/pubsub.go:117``) and has no validation anywhere.  This
module is the framework's answer, shaped for batch throughput rather than
per-message calls: envelopes accumulate and verify in one shot on the chosen
backend —

- ``"native"``  — the C++ threaded batch verifier (host data plane default);
- ``"device"``  — the JAX limb-arithmetic kernel (TPU data plane);
- ``"python"``  — the pure-Python oracle (tests, last-resort fallback).

Envelope format (this framework's own; the reference has none to mirror):
the signature covers ``topic_len_u32 || topic || seqno_u64 || payload``, so a
signature cannot be replayed across topics or sequence numbers.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass
from typing import Callable, List, Literal, Sequence, Tuple

import numpy as np

from . import ed25519_ref

Backend = Literal["native", "device", "python"]


def signing_bytes(topic: str, seqno: int, payload: bytes) -> bytes:
    """The exact byte string a publisher signs (domain-separated by topic and
    sequence number)."""
    t = topic.encode()
    return struct.pack("<I", len(t)) + t + struct.pack("<Q", seqno) + payload


@dataclass(frozen=True)
class Envelope:
    """A signed message as it travels the wire: payload + authenticator."""

    topic: str
    seqno: int
    payload: bytes
    pubkey: bytes  # 32B ed25519
    signature: bytes  # 64B

    def to_wire(self) -> bytes:
        # Header layout == signature domain (one definition, can't drift).
        return (
            signing_bytes(self.topic, self.seqno, b"")
            + self.pubkey
            + self.signature
            + self.payload
        )

    @classmethod
    def from_wire(cls, raw: bytes) -> "Envelope":
        (tlen,) = struct.unpack_from("<I", raw, 0)
        topic = raw[4 : 4 + tlen].decode()
        off = 4 + tlen
        (seqno,) = struct.unpack_from("<Q", raw, off)
        off += 8
        pubkey = raw[off : off + 32]
        signature = raw[off + 32 : off + 96]
        payload = raw[off + 96 :]
        return cls(topic, seqno, payload, pubkey, signature)


def sign_envelope(
    seed: bytes,
    topic: str,
    seqno: int,
    payload: bytes,
    backend: Literal["python", "native", "auto"] = "python",
) -> Envelope:
    """Publisher-side signing.  ``backend="python"`` uses the oracle (tests);
    ``"native"`` the C++ implementation (~1000x faster per signature, the live
    data plane's choice); ``"auto"`` picks native when its build is available.
    Batch signing for load generation lives in ``native.sign_batch``."""
    if backend == "auto":
        from . import native

        backend = "native" if native.available() else "python"
    if backend == "native":
        from . import native

        msg = signing_bytes(topic, seqno, payload)
        return Envelope(
            topic, seqno, payload, native.public_key(seed), native.sign(seed, msg)
        )
    pk = ed25519_ref.public_key(seed)
    sig = ed25519_ref.sign(seed, signing_bytes(topic, seqno, payload))
    return Envelope(topic, seqno, payload, pk, sig)


def _verify_native(pks, msgs, sigs) -> np.ndarray:
    from . import native

    return native.verify_batch(pks, msgs, sigs)


def _verify_device(pks, msgs, sigs) -> np.ndarray:
    from ..ops import ed25519 as dev

    # batch_major=None / ladder=None defer to the per-backend measured
    # defaults (limb-major [22, B] kernel, windowed joint-table ladder at
    # default_window() bits per step; all variants verdict-identical).
    return dev.verify_batch(pks, msgs, sigs, batch_major=None, ladder=None)


def _verify_python(pks, msgs, sigs) -> np.ndarray:
    return np.array(
        [ed25519_ref.verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)], bool
    )


_BACKENDS: dict = {
    "native": _verify_native,
    "device": _verify_device,
    "python": _verify_python,
}


class ValidationPipeline:
    """Accumulate envelopes, verify in batches, deliver verdicts.

    The structural replacement for the reference's (absent) per-message
    validation hook: producers ``submit`` envelopes, the owner calls
    ``flush()`` at its cadence (heartbeat, step boundary, or queue-depth
    trigger), and verdicts come back as (envelope, bool) pairs in submit
    order.  Batching is the whole point: signature verification amortizes
    across the batch on every backend.
    """

    def __init__(
        self,
        backend: Backend = "native",
        flush_threshold: int = 256,
        on_verdict: Callable[[Envelope, bool], None] | None = None,
        on_verdict_ctx: Callable[[Envelope, bool, object], None] | None = None,
        tracer=None,
        metrics=None,
    ) -> None:
        if backend not in _BACKENDS:
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.flush_threshold = flush_threshold
        self.on_verdict = on_verdict
        self.on_verdict_ctx = on_verdict_ctx
        # r18 observability: an optional obs.SpanLedger stamps
        # verify_submit/verify_verdict when ctx carries the streaming
        # plane's (topic, src) routing tuple; an optional MetricsRegistry
        # publishes verdict counters + batch verify wall time under
        # ``crypto.pipeline.*`` — the one-registry telemetry plane.
        self.tracer = tracer
        self.metrics = metrics
        self._pending: List[Tuple[Envelope, object]] = []
        self.stats = {"validated": 0, "accepted": 0, "rejected": 0}

    def submit(self, env: Envelope, ctx: object = None) -> None:
        """Queue an envelope; ``ctx`` is opaque caller state (e.g. the
        streaming plane's routing tuple) handed back via ``on_verdict_ctx``
        so verdict delivery needs no side-channel lookup."""
        if self.tracer is not None:
            from ..obs.spans import envelope_span_key

            key = envelope_span_key(env.payload, ctx)
            if key is not None:
                self.tracer.stamp(key, "verify_submit",
                                  seqno=env.seqno, topic=env.topic)
        self._pending.append((env, ctx))
        if len(self._pending) >= self.flush_threshold:
            self.flush()

    def drop_pending(self) -> List[Envelope]:
        """Discard and return envelopes awaiting verification.

        For callers that keep their own copy of the batch: after a backend
        failure ``flush`` re-queues internally, and a caller that will retry
        by re-submitting must drop that requeue first or every envelope
        would be verified (and its ``on_verdict`` fired) twice.
        """
        dropped, self._pending = self._pending, []
        return [e for e, _ in dropped]

    def flush(self) -> List[Tuple[Envelope, bool]]:
        if not self._pending:
            return []
        pairs, self._pending = self._pending, []
        batch = [e for e, _ in pairs]
        # Structural screen BEFORE the backend call: a truncated/oversized key
        # or signature (attacker-crafted wire bytes) gets a False verdict —
        # it must not raise out of the batched backends and drop everyone
        # else's verdicts with it.
        well_formed = [
            len(e.pubkey) == 32 and len(e.signature) == 64 for e in batch
        ]
        good = [e for e, w in zip(batch, well_formed) if w]
        t_v0 = time.monotonic()
        try:
            verdicts = (
                _BACKENDS[self.backend](
                    [e.pubkey for e in good],
                    [signing_bytes(e.topic, e.seqno, e.payload) for e in good],
                    [e.signature for e in good],
                )
                if good
                else []
            )
        except Exception:
            # Backend infrastructure failure (e.g. native build unavailable):
            # re-queue the batch so no envelope silently loses its verdict,
            # then propagate so the caller can pick another backend.
            self._pending = pairs + self._pending
            raise
        verify_s = time.monotonic() - t_v0
        oks_good = iter(verdicts)
        oks = np.array(
            [bool(next(oks_good)) if w else False for w in well_formed], bool
        )
        out = list(zip(batch, (bool(o) for o in oks)))
        self.stats["validated"] += len(batch)
        self.stats["accepted"] += int(np.sum(oks))
        self.stats["rejected"] += len(batch) - int(np.sum(oks))
        if self.metrics is not None:
            self.metrics.inc("crypto.pipeline.validated", len(batch))
            self.metrics.inc("crypto.pipeline.accepted", int(np.sum(oks)))
            self.metrics.inc(
                "crypto.pipeline.rejected", len(batch) - int(np.sum(oks))
            )
            self.metrics.gauge("crypto.pipeline.verify_s", verify_s)
            self.metrics.gauge("crypto.pipeline.batch", len(batch))
        if self.tracer is not None:
            from ..obs.spans import envelope_span_key

            for (env, ctx), ok in zip(pairs, oks):
                key = envelope_span_key(env.payload, ctx)
                if key is not None:
                    self.tracer.stamp(key, "verify_verdict", ok=bool(ok))
                    if not ok:
                        # A rejected envelope never publishes: its span
                        # ends here, explicitly, instead of dangling open.
                        self.tracer.close(key, status="rejected")
        if self.on_verdict is not None:
            for env, ok in out:
                self.on_verdict(env, ok)
        if self.on_verdict_ctx is not None:
            for (env, ctx), ok in zip(pairs, (bool(o) for o in oks)):
                self.on_verdict_ctx(env, ok, ctx)
        return out


def verify_envelopes(
    envs: Sequence[Envelope], backend: Backend = "native"
) -> np.ndarray:
    """One-shot batch verify of prepared envelopes -> bool[n]."""
    pks = [e.pubkey for e in envs]
    msgs = [signing_bytes(e.topic, e.seqno, e.payload) for e in envs]
    sigs = [e.signature for e in envs]
    return _BACKENDS[backend](pks, msgs, sigs)
