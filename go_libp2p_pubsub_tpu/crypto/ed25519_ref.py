"""Pure-Python ed25519 (RFC 8032) — the framework's correctness oracle.

The reference has NO signing at all — ``PublishMessage`` carries a
``// TODO: add signature`` (``/root/reference/pubsub.go:117``); the north-star
pipeline (BASELINE.json config c, "batched ed25519 verification") fills that
hole.  Three implementations share this module's semantics:

1. this one — slow, obviously-correct big-int Python; signs test traffic and
   cross-checks the others;
2. ``native.py`` — the C++ batch verifier (host data plane);
3. ``ops/ed25519.py`` — the JAX limb-arithmetic batch verifier (device plane).

Verification is **non-cofactored**: accept iff ``[S]B == R + [k]A`` with
``k = SHA512(R || A || M) mod L``, the check OpenSSL/ref10 perform.  Malleable
signatures are rejected by requiring ``S < L``.
"""

from __future__ import annotations

import hashlib
from typing import Tuple

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P  # curve constant -121665/121666

# Base point: y = 4/5, x recovered even.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int:
    """x from y on -x^2 + y^2 = 1 + d x^2 y^2; raises if y is not on curve."""
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        if sign:
            raise ValueError("invalid point encoding")
        return 0
    # sqrt via x = x2^((p+3)/8); p = 5 mod 8
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
    if (x * x - x2) % P != 0:
        raise ValueError("not a square: invalid point encoding")
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
BASE = (_BX, _BY, 1, _BX * _BY % P)  # extended coordinates (X, Y, Z, T)
IDENT = (0, 1, 1, 0)


def point_add(p1, p2):
    """Extended-coordinates addition (complete formula for twisted Edwards)."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_mul(s: int, p) -> Tuple[int, int, int, int]:
    q = IDENT
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_add(p, p)
        s >>= 1
    return q


def point_equal(p1, p2) -> bool:
    x1, y1, z1, _ = p1
    x2, y2, z2, _ = p2
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def point_compress(p) -> bytes:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    x, y = x * zi % P, y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(b: bytes):
    if len(b) != 32:
        raise ValueError("point must be 32 bytes")
    enc = int.from_bytes(b, "little")
    y = enc & ((1 << 255) - 1)
    if y >= P:
        raise ValueError("y >= p: invalid point encoding")
    x = _recover_x(y, enc >> 255)
    return (x, y, 1, x * y % P)


def _sha512_int(*parts: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"".join(parts)).digest(), "little")


def secret_expand(secret: bytes) -> Tuple[int, bytes]:
    if len(secret) != 32:
        raise ValueError("secret key must be 32 bytes")
    h = hashlib.sha512(secret).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(secret: bytes) -> bytes:
    a, _ = secret_expand(secret)
    return point_compress(point_mul(a, BASE))


def sign(secret: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(secret)
    pk = point_compress(point_mul(a, BASE))
    r = _sha512_int(prefix, msg) % L
    big_r = point_compress(point_mul(r, BASE))
    k = _sha512_int(big_r, pk, msg) % L
    s = (r + k * a) % L
    return big_r + int.to_bytes(s, 32, "little")


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Non-cofactored verify: ``[S]B == R + [k]A``, rejecting ``S >= L``."""
    if len(pk) != 32 or len(sig) != 64:
        return False
    try:
        a = point_decompress(pk)
        r = point_decompress(sig[:32])
    except ValueError:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False  # malleability rejection
    k = _sha512_int(sig[:32], pk, msg) % L
    return point_equal(point_mul(s, BASE), point_add(r, point_mul(k, a)))


def keypair(seed: bytes) -> Tuple[bytes, bytes]:
    """Deterministic (secret, public) from a 32-byte seed."""
    return seed, public_key(seed)
