"""Message authentication: the reference's missing signature layer
(``// TODO: add signature``, ``/root/reference/pubsub.go:117``), built as
three interchangeable ed25519 implementations plus a batching pipeline.

- :mod:`.ed25519_ref` — pure-Python oracle (RFC 8032 semantics)
- :mod:`.native`      — C++ threaded batch verifier (built on demand)
- :mod:`~..ops.ed25519` — JAX device kernel (TPU batch verifier)
- :mod:`.pipeline`    — envelopes + batched validation pipeline
"""

from .ed25519_ref import keypair, public_key, sign, verify
from .pipeline import (
    Envelope,
    ValidationPipeline,
    sign_envelope,
    signing_bytes,
    verify_envelopes,
)

__all__ = [
    "Envelope",
    "ValidationPipeline",
    "keypair",
    "public_key",
    "sign",
    "sign_envelope",
    "signing_bytes",
    "verify",
    "verify_envelopes",
]
