"""Host API: the reference's L3/L4 surface over the array sim engine.

Shapes mirror ``/root/reference/pubsub.go:19-120`` (``TopicManager``,
``Topic``) and ``client.go:18-94`` (``client`` -> :class:`Subscription`):

- ``NewTopicManager(h)``           -> ``TopicManager(host)``
- ``tm.NewTopic(ctx, title, opts)``-> ``tm.new_topic(title, opts)``
- ``tm.Subscribe(ctx, root, top)`` -> ``tm.subscribe(root_id, title)``
- ``t.PublishMessage(b)``          -> ``topic.publish_message(b)``
- ``cli.Messages() <-chan []byte`` -> ``sub.get(...)`` / ``sub.try_get()``
- ``cli.Close()`` (Part + teardown)-> ``sub.close()``
- ``t.Close()``                    -> ``topic.close()``

The network backend is :class:`SimNetwork`: the in-process simulated fabric —
the analog of the mocknet fixture the reference ships for cluster-free testing
(``pubsub_test.go:18-25``) — owning one device-resident
:class:`~.ops.tree.TreeState` per topic and advancing every topic in lockstep
steps.  Message payload bytes stay host-side in a per-topic registry; only
``int32`` message ids live on device.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import jax.numpy as jnp
import numpy as np

from .config import SimParams, TreeOpts
from .ops import tree as tree_ops


class TimeoutError_(Exception):
    """Delivery wait exceeded its step budget (the 5 s timeout analog,
    ``pubsub_test.go:125``)."""


@dataclass
class _TopicEngine:
    """Per-topic simulation state + host-side payload registry."""

    protoid: str
    root: int
    opts: TreeOpts
    state: tree_ops.TreeState
    payloads: List[bytes] = field(default_factory=list)
    closed_root: bool = False
    repair_timeout_steps: int = 64

    def publish(self, data: bytes) -> None:
        msg_id = len(self.payloads)
        self.payloads.append(data)
        self.state = tree_ops.publish(self.state, jnp.int32(msg_id))

    def step(self) -> None:
        self.state = tree_ops.step(
            self.state, repair_timeout_steps=self.repair_timeout_steps
        )

    def drain(self, peer: int) -> List[bytes]:
        self.state, msgs, count = tree_ops.drain_out(self.state, jnp.int32(peer))
        ids = np.asarray(msgs)[: int(count)]
        return [self.payloads[i] for i in ids]


class SimNetwork:
    """In-process simulated network of hosts (mocknet analog).

    All hosts share one fabric; per-topic overlay state is device-resident.
    ``step()`` advances every topic one lockstep round; delivery waits
    (``Subscription.get``) auto-step up to a budget, which plays the role of
    wall-clock timeouts in the reference tests.
    """

    def __init__(self, params: Optional[SimParams] = None):
        self.params = params or SimParams()
        self._next_idx = itertools.count()
        self.hosts: Dict[str, "SimHost"] = {}
        self.engines: Dict[str, _TopicEngine] = {}

    def host(self) -> "SimHost":
        idx = next(self._next_idx)
        if idx >= self.params.max_peers:
            raise RuntimeError(
                f"SimNetwork is full ({self.params.max_peers} peers); "
                "raise SimParams.max_peers"
            )
        h = SimHost(self, idx)
        self.hosts[h.id] = h
        return h

    def make_hosts(self, count: int) -> List["SimHost"]:
        """Fixture analog of ``makeNetHosts`` (``pubsub_test.go:27-35``)."""
        return [self.host() for _ in range(count)]

    def step(self, count: int = 1) -> None:
        for _ in range(count):
            for eng in self.engines.values():
                eng.step()

    def set_link_profile(self, delay, drop_prob) -> None:
        """Install per-edge latency/drop tensors on every registered topic's
        fabric (the mocknet analog's link model, SURVEY §2.3).

        ``delay`` i32[N, W] extra steps per (parent, child-slot) edge;
        ``drop_prob`` f32[N, W] silent per-copy loss probability.  Applies to
        topics that exist now — create topics first, then shape the network.
        """
        d = jnp.asarray(delay)
        p = jnp.asarray(drop_prob)
        for eng in self.engines.values():
            eng.state = tree_ops.set_link_profile(eng.state, d, p)

    # -- used by host/topic objects -----------------------------------------
    def _engine(self, protoid: str) -> _TopicEngine:
        try:
            return self.engines[protoid]
        except KeyError:
            raise KeyError(f"no topic registered under protocol id {protoid!r}")


class SimHost:
    """A simulated peer process — the ``host.Host`` analog.

    ``close()`` is the abrupt kill used by the dropping tests
    (``pubsub_test.go:178,252``): the peer vanishes without sending Part and
    is discovered via write failures.
    """

    def __init__(self, net: SimNetwork, idx: int):
        self.net = net
        self.idx = idx
        self.id = f"simpeer-{idx}"
        self.closed = False

    def close(self) -> None:
        self.closed = True
        for eng in self.net.engines.values():
            eng.state = tree_ops.kill_peer(eng.state, jnp.int32(self.idx))

    def __repr__(self) -> str:
        return f"SimHost({self.id})"


class TopicManager:
    """Registry of topics on one host (``pubsub.go:19-31``)."""

    def __init__(self, host: SimHost):
        self.h = host
        self.topics: Dict[str, "Topic"] = {}

    def new_topic(self, title: str, opts: Optional[TreeOpts] = None) -> "Topic":
        """Create a topic rooted at this host (``pubsub.go:54-97``).

        The creator is the permanent root and sole publisher entry point;
        the protocol id namespaces the topic by (root, title)
        (``pubsub.go:55``).
        """
        opts = opts or TreeOpts()
        protoid = f"{self.h.id}/{title}"
        eng = _TopicEngine(
            protoid=protoid,
            root=self.h.idx,
            opts=opts,
            state=tree_ops.init_state(self.net.params, opts, root=self.h.idx),
            repair_timeout_steps=self.net.params.repair_timeout_steps,
        )
        self.net.engines[protoid] = eng
        t = Topic(self, title, protoid)
        self.topics[title] = t
        return t

    def subscribe(
        self, root_id: str, title: str, join_budget: Optional[int] = None
    ) -> "Subscription":
        """Join the tree rooted at ``root_id`` (``client.go:65-94``).

        Blocks (by stepping the sim) until the join walk lands — the analog of
        ``joinToPeer``'s synchronous welcome/redirect chain
        (``subtree.go:196-226``).
        """
        protoid = f"{root_id}/{title}"
        eng = self.net._engine(protoid)
        peer = self.h.idx
        eng.state = tree_ops.begin_subscribe(eng.state, jnp.int32(peer))
        budget = join_budget or 4 * self.net.params.max_peers
        for _ in range(budget):
            if bool(eng.state.joined[peer]):
                break
            self.net.step()
        else:
            raise TimeoutError_(f"{self.h.id} failed to join {protoid}")
        return Subscription(self, protoid, peer)

    @property
    def net(self) -> SimNetwork:
        return self.h.net


class Topic:
    """Root-side topic handle (``pubsub.go:33-120``)."""

    def __init__(self, tm: TopicManager, title: str, protoid: str):
        self.tm = tm
        self.title = title
        self.protoid = protoid

    def publish_message(self, data: bytes) -> None:
        """``PublishMessage`` (``pubsub.go:111-120``).

        Signing is a pluggable validator hook in this framework (the
        reference's ``// TODO: add signature``, ``pubsub.go:117``); the sim
        data plane carries payloads unsigned just as v0 does.
        """
        self.tm.net._engine(self.protoid).publish(data)

    def close(self) -> None:
        """Parity with ``Topic.Close`` (``pubsub.go:99-103``): unregisters the
        topic but does NOT tear down the tree — the reference leaks its child
        streams here (SURVEY.md §2.4.6).  Use :meth:`close_tree` for the
        fixed behavior."""
        self.tm.net._engine(self.protoid).closed_root = True
        self.tm.topics.pop(self.title, None)

    def close_tree(self) -> None:
        """Correct-semantics close: gracefully part the root so children are
        notified (the deviation documented in SURVEY.md §2.4.6)."""
        eng = self.tm.net._engine(self.protoid)
        eng.state = tree_ops.leave_peer(eng.state, jnp.int32(eng.root))
        self.close()


class Subscription:
    """Subscriber handle — the ``client`` analog (``client.go:18-34``)."""

    def __init__(self, tm: TopicManager, protoid: str, peer: int):
        self.tm = tm
        self.protoid = protoid
        self.peer = peer
        self._inbox: List[bytes] = []
        self.closed = False

    def _drain(self) -> None:
        self._inbox.extend(self.tm.net._engine(self.protoid).drain(self.peer))

    def try_get(self) -> Optional[bytes]:
        """Non-blocking read — the ``select/default`` drain in
        ``clearWaitingMessages`` (``pubsub_test.go:85-99``)."""
        self._drain()
        return self._inbox.pop(0) if self._inbox else None

    def get(self, step_budget: int = 256) -> bytes:
        """Blocking read with a step budget — ``<-ch.Messages()`` under the
        5 s test timeout (``pubsub_test.go:118-126``)."""
        self._drain()
        for _ in range(step_budget):
            if self._inbox:
                return self._inbox.pop(0)
            self.tm.net.step()
            self._drain()
        if self._inbox:
            return self._inbox.pop(0)
        raise TimeoutError_(
            f"timeout waiting for message on peer {self.peer} ({self.protoid})"
        )

    def messages(self) -> Iterator[bytes]:
        """Iterator over currently deliverable messages."""
        while True:
            m = self.try_get()
            if m is None:
                return
            yield m

    def clear(self) -> None:
        self._drain()
        self._inbox.clear()

    def close(self) -> None:
        """Graceful leave (``client.Close``, ``client.go:30-34``): Part to the
        parent; our children are re-adopted by our parent next step."""
        self.closed = True
        eng = self.tm.net._engine(self.protoid)
        eng.state = tree_ops.leave_peer(eng.state, jnp.int32(self.peer))
