"""Trace-artifact builders for the three scenario planes (``--trace-out``).

Two artifact shapes, discriminated by their ``format`` key (what
``tools/trace_view.py`` switches on):

- ``obs-span-artifact/1``   — streaming plane: the span ledger's full
  story (spans, events, Chrome trace, OTLP record, Prometheus render,
  black-box frames) next to the SLO verdict;
- ``obs-record-trace/1``    — sim/live planes: the flight record's scalar
  series as Chrome counter ("C") tracks plus per-channel summary stats.
  The sim plane has no host wall clock per round, so its time axis is the
  step index (1 step = 1 virtual µs unless a real cadence is given).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def write_json(path: str, doc: Dict[str, Any]) -> str:
    """Atomic JSON write (write → fsync → rename), same discipline as
    ``utils.checkpoint`` — a crash mid-write never leaves a torn artifact."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True, allow_nan=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def build_span_artifact(
    plane: str,
    scenario: str,
    verdict: Dict[str, Any],
    ledger,
    registry=None,
    blackbox=None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The streaming plane's trace artifact: one file holding everything an
    operator would ask the run for after the fact."""
    snap = ledger.snapshot()
    doc: Dict[str, Any] = {
        "format": "obs-span-artifact/1",
        "plane": plane,
        "scenario": scenario,
        "verdict": verdict,
        "summary": ledger.summary(),
        "spans": snap["spans"],
        "events": snap["events"],
        "chrome_trace": ledger.export_chrome_trace(),
        "otlp": ledger.export_otlp(),
    }
    if registry is not None:
        doc["metrics_prometheus"] = registry.render_prometheus()
    if blackbox is not None:
        doc["blackbox"] = {
            "recorded": blackbox.recorded,
            "frames": blackbox.frames(),
        }
    if extra:
        doc.update(extra)
    return doc


def build_record_artifact(
    plane: str,
    scenario: str,
    verdict: Dict[str, Any],
    record: Dict[str, Any],
    time_per_step_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Sim/live trace artifact from a flight record (dict of arrays with a
    leading time axis).  Scalar series become Chrome counter tracks; the
    artifact also carries per-channel min/mean/max so ``trace_view`` can
    summarize without reparsing the trace events."""
    dt = float(time_per_step_s) if time_per_step_s else None
    events = []
    channels: Dict[str, Any] = {}
    for name in sorted(record):
        a = np.asarray(record[name])
        if a.ndim != 1 or a.size == 0:
            continue
        vals = a.astype(np.float64)
        finite = vals[np.isfinite(vals)]
        channels[name] = {
            "len": int(vals.size),
            "min": float(finite.min()) if finite.size else float("nan"),
            "mean": float(finite.mean()) if finite.size else float("nan"),
            "max": float(finite.max()) if finite.size else float("nan"),
            "last": float(vals[-1]),
        }
        for i, v in enumerate(vals):
            if not np.isfinite(v):
                continue
            # Step index as the timeline when no real cadence exists: one
            # step renders as 1 µs, honest about being virtual time.
            ts = i * dt * 1e6 if dt is not None else float(i)
            events.append({
                "name": name, "cat": "channel", "ph": "C",
                "ts": round(ts, 3), "pid": 0, "tid": 0,
                "args": {name: float(v)},
            })
    return {
        "format": "obs-record-trace/1",
        "plane": plane,
        "scenario": scenario,
        "verdict": verdict,
        "time_axis": "seconds" if dt is not None else "steps",
        "channels": channels,
        "chrome_trace": {"traceEvents": events, "displayTimeUnit": "ms"},
    }
