"""Sampled per-message span ledger keyed on the ``content_hash`` identity.

A span is one message's host-clock lifecycle through the serving plane:

    verify_submit → verify_verdict → ring_accept → chunk_dispatch →
    device_delivery

(the crypto stage fronts the ring in the streaming plane, so submit/verdict
precede ring-accept; the exporters sort by timestamp, not by stage name).
Stages are *stamps* — (stage, host time, attrs) appended to the span — so a
retry or resubmission shows up as a repeated stamp instead of corrupting
state.  ``close`` is once-only: the second close of the same content is
counted (``duplicate_closes``) and ignored, mirroring the engine's
exactly-once delivery contract.

Sampling is deterministic on the key itself (``int(key[:8], 16) %
sample_n``), so every plane — ring, pipeline, engine, and a post-crash
incarnation replaying the same content — independently agrees on which
messages are sampled with no shared state.

The ledger is JSON-safe end to end: ``snapshot()``/``restore_snapshot()``
ride the engine checkpoint meta, so in-flight spans survive a crash and the
restore path annotates them with the measured recovery gap.  Exports:
Chrome trace-event JSON (the same ``{"traceEvents": ..., "displayTimeUnit":
"ms"}`` envelope as ``utils.trace.StepTimer.export_chrome_trace``) and an
OTLP-shaped ``resourceSpans`` record.  Timestamps are the injected host
clock (monotonic by default), NOT unix epoch — documented in the OTLP
resource attributes.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Dict, List, Optional

STAGES = (
    "ring_accept",
    "verify_submit",
    "verify_verdict",
    "chunk_dispatch",
    "device_delivery",
)

# Live-plane hop stages (r19, net/live.py): one message's path across
# hosts.  "publish" lands on the origin; "send"/"replay_send" on every
# fanning-out interior node; "recv"/"deliver" on every subscriber.  The
# ledger accepts any stage string — this tuple is the vocabulary the
# cross-host merge (obs/merge.py) orders hops by.
HOP_STAGES = ("publish", "send", "recv", "deliver", "replay_send")


def content_hash(topic: int, publisher: int, payload: bytes) -> str:
    """Stable identity of a publish for exactly-once dedup (hex).  Keyed on
    content, not ring seq — a resubmitted message gets a fresh seq but the
    same hash.  (Canonical definition; ``serve.engine`` re-exports it.)"""
    h = hashlib.sha256()
    h.update(int(topic).to_bytes(4, "little"))
    h.update(int(publisher).to_bytes(8, "little"))
    h.update(payload)
    return h.hexdigest()[:32]


def live_span_key(topic: str, payload: bytes) -> str:
    """Span identity of a live-plane Data frame (hex, 32 chars — the same
    shape as :func:`content_hash` so the deterministic hash-mod sampling
    applies unchanged).  Keyed on (topic, wire payload): every host on the
    frame's path computes the same key from the frame alone, so per-host
    ledgers agree on identity AND sampling with no coordination.  The wire
    payload (post-envelope on the signed plane) is hashed, not the
    application bytes — receivers never need to unwrap to key a frame."""
    h = hashlib.sha256()
    topic_b = topic.encode()
    h.update(len(topic_b).to_bytes(4, "little"))
    h.update(topic_b)
    h.update(payload)
    return h.hexdigest()[:32]


def envelope_span_key(payload: bytes, ctx: object) -> Optional[str]:
    """Span key for a pipeline envelope.  The streaming plane's routing
    ``ctx`` is ``(topic, src)``, which together with the payload is exactly
    the engine's content identity; any other ctx shape has no span."""
    if isinstance(ctx, (tuple, list)) and len(ctx) == 2:
        try:
            return content_hash(int(ctx[0]), int(ctx[1]), payload)
        except (TypeError, ValueError):
            return None
    return None


class SpanLedger:
    """Bounded, deterministic-sampled span store with global events.

    ``sample_n=1`` traces every message; ``sample_n=k`` traces the
    deterministic 1/k subset.  ``max_spans`` bounds memory — past it, new
    spans are counted under ``dropped_spans`` instead of created (stamps on
    EXISTING spans always land).
    """

    def __init__(
        self,
        sample_n: int = 1,
        clock=time.monotonic,
        max_spans: int = 65536,
    ) -> None:
        if sample_n < 1:
            raise ValueError("sample_n must be >= 1")
        if max_spans < 1:
            raise ValueError("max_spans must be >= 1")
        self.sample_n = int(sample_n)
        self.max_spans = int(max_spans)
        self._clock = clock
        self._spans: Dict[str, dict] = {}   # insertion-ordered
        self._events: List[dict] = []
        self.dropped_spans = 0
        self.duplicate_closes = 0

    # -- sampling -----------------------------------------------------------

    def sampled(self, key: str) -> bool:
        """Deterministic sampling verdict for a content-hash key; every
        stage (and every post-crash incarnation) computes the same answer
        from the key alone."""
        if self.sample_n == 1:
            return True
        try:
            return int(key[:8], 16) % self.sample_n == 0
        except (TypeError, ValueError):
            return False

    # -- write side ---------------------------------------------------------

    def stamp(self, key: str, stage: str, t: Optional[float] = None,
              **attrs: Any) -> bool:
        """Append one lifecycle stamp to ``key``'s span.  Returns True iff
        the stamp landed (sampled, span not closed, ledger not full)."""
        if not self.sampled(key):
            return False
        span = self._spans.get(key)
        if span is None:
            if len(self._spans) >= self.max_spans:
                self.dropped_spans += 1
                return False
            span = {"key": key, "stamps": [], "events": [],
                    "closed": False, "t_close": None, "attrs": {}}
            self._spans[key] = span
        elif span["closed"]:
            return False
        rec = {"stage": stage, "t": float(t if t is not None
                                          else self._clock())}
        if attrs:
            rec.update(_json_attrs(attrs))
        span["stamps"].append(rec)
        return True

    def close(self, key: str, t: Optional[float] = None,
              **attrs: Any) -> bool:
        """Close ``key``'s span exactly once.  A second close is counted
        under ``duplicate_closes`` and ignored; closing a key with no span
        (unsampled, or never stamped) is a no-op returning False."""
        if not self.sampled(key):
            return False
        span = self._spans.get(key)
        if span is None:
            return False
        if span["closed"]:
            self.duplicate_closes += 1
            return False
        span["closed"] = True
        span["t_close"] = float(t if t is not None else self._clock())
        if attrs:
            span["attrs"].update(_json_attrs(attrs))
        return True

    def event(self, name: str, t: Optional[float] = None,
              **attrs: Any) -> None:
        """Record a ledger-global instant event (tier transition, engine
        restart, recovery gap)."""
        rec = {"name": name, "t": float(t if t is not None
                                        else self._clock())}
        if attrs:
            rec.update(_json_attrs(attrs))
        self._events.append(rec)

    def annotate_open(self, name: str, t: Optional[float] = None,
                      **attrs: Any) -> int:
        """Attach an instant event to every OPEN span (the restore path's
        crash-gap annotation).  Returns the number of spans annotated."""
        tv = float(t if t is not None else self._clock())
        rec = {"name": name, "t": tv}
        if attrs:
            rec.update(_json_attrs(attrs))
        n = 0
        for span in self._spans.values():
            if not span["closed"]:
                span["events"].append(dict(rec))
                n += 1
        return n

    # -- read side ----------------------------------------------------------

    def spans(self) -> List[dict]:
        return [dict(s) for s in self._spans.values()]

    def get(self, key: str) -> Optional[dict]:
        s = self._spans.get(key)
        return dict(s) if s is not None else None

    def events(self) -> List[dict]:
        return list(self._events)

    @property
    def n_spans(self) -> int:
        return len(self._spans)

    @property
    def n_open(self) -> int:
        return sum(1 for s in self._spans.values() if not s["closed"])

    @property
    def n_closed(self) -> int:
        return len(self._spans) - self.n_open

    def summary(self) -> dict:
        """Host digest: span counts, per-transition latency quantiles
        (consecutive time-ordered stamps), event counts by name."""
        from ..utils.metrics import quantiles

        transitions: Dict[str, List[float]] = {}
        for span in self._spans.values():
            stamps = sorted(span["stamps"], key=lambda r: r["t"])
            for a, b in zip(stamps, stamps[1:]):
                transitions.setdefault(
                    f"{a['stage']}->{b['stage']}", []
                ).append(b["t"] - a["t"])
        ev_counts: Dict[str, int] = {}
        for e in self._events:
            ev_counts[e["name"]] = ev_counts.get(e["name"], 0) + 1
        for span in self._spans.values():
            for e in span["events"]:
                ev_counts[e["name"]] = ev_counts.get(e["name"], 0) + 1
        return {
            "sample_n": self.sample_n,
            "spans": len(self._spans),
            "open": self.n_open,
            "closed": self.n_closed,
            "dropped_spans": self.dropped_spans,
            "duplicate_closes": self.duplicate_closes,
            "transitions": {
                name: {"count": len(xs), **quantiles(xs, (0.5, 0.99))}
                for name, xs in sorted(transitions.items())
            },
            "events": ev_counts,
        }

    # -- checkpoint ---------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-safe full state (spans + events + counters) — rides the
        engine checkpoint meta so in-flight spans survive a crash."""
        return {
            "sample_n": self.sample_n,
            "spans": [dict(s) for s in self._spans.values()],
            "events": list(self._events),
            "dropped_spans": self.dropped_spans,
            "duplicate_closes": self.duplicate_closes,
        }

    def restore_snapshot(self, snap: dict) -> int:
        """Reinstate spans + events from :meth:`snapshot`, replacing current
        contents.  ``sample_n`` must match — a restored ledger that sampled
        differently would disagree with live stamping on the same keys.
        Returns the number of spans reinstated."""
        if int(snap["sample_n"]) != self.sample_n:
            raise ValueError(
                f"snapshot sample_n={snap['sample_n']} != ledger "
                f"sample_n={self.sample_n}; the deterministic sampling "
                "contract would break"
            )
        self._spans = {s["key"]: dict(s) for s in snap["spans"]}
        self._events = [dict(e) for e in snap["events"]]
        self.dropped_spans = int(snap.get("dropped_spans", 0))
        self.duplicate_closes = int(snap.get("duplicate_closes", 0))
        return len(self._spans)

    # -- exports ------------------------------------------------------------

    def export_chrome_trace(self) -> dict:
        """Chrome trace-event JSON dict (the ``StepTimer`` envelope: "X"
        complete events, µs timestamps, ``displayTimeUnit: ms``).  Each
        span gets its own tid track: one whole-span X event, one X segment
        per consecutive stamp pair, instant "i" events for span
        annotations; ledger-global events are process-scoped instants."""
        events: List[dict] = []
        for tid, span in enumerate(self._spans.values(), start=1):
            stamps = sorted(span["stamps"], key=lambda r: r["t"])
            if not stamps:
                continue
            t0 = stamps[0]["t"]
            t1 = span["t_close"] if span["t_close"] is not None \
                else stamps[-1]["t"]
            events.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": f"msg {span['key'][:12]}"},
            })
            events.append({
                "name": "span", "cat": "message", "ph": "X",
                "ts": round(t0 * 1e6, 3),
                "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                "pid": 0, "tid": tid,
                "args": {"key": span["key"], "closed": span["closed"],
                         **span["attrs"]},
            })
            for a, b in zip(stamps, stamps[1:]):
                events.append({
                    "name": f"{a['stage']}->{b['stage']}", "cat": "stage",
                    "ph": "X", "ts": round(a["t"] * 1e6, 3),
                    "dur": round(max(0.0, b["t"] - a["t"]) * 1e6, 3),
                    "pid": 0, "tid": tid,
                    "args": {k: v for k, v in b.items()
                             if k not in ("stage", "t")},
                })
            for e in span["events"]:
                events.append({
                    "name": e["name"], "cat": "annotation", "ph": "i",
                    "ts": round(e["t"] * 1e6, 3), "pid": 0, "tid": tid,
                    "s": "t",
                    "args": {k: v for k, v in e.items()
                             if k not in ("name", "t")},
                })
        for e in self._events:
            events.append({
                "name": e["name"], "cat": "ledger", "ph": "i",
                "ts": round(e["t"] * 1e6, 3), "pid": 0, "tid": 0, "s": "g",
                "args": {k: v for k, v in e.items()
                         if k not in ("name", "t")},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_otlp(
        self, service_name: str = "go_libp2p_pubsub_tpu.serve"
    ) -> dict:
        """OTLP-shaped JSON (``resourceSpans``/``scopeSpans``/``spans``).
        Trace/span ids derive from the content hash; timestamps are the
        injected host clock scaled to nanos (monotonic, NOT unix epoch —
        flagged in the resource attributes)."""
        spans_out = []
        for span in self._spans.values():
            stamps = sorted(span["stamps"], key=lambda r: r["t"])
            if not stamps:
                continue
            t0 = stamps[0]["t"]
            t1 = span["t_close"] if span["t_close"] is not None \
                else stamps[-1]["t"]
            otlp_events = [
                {
                    "timeUnixNano": str(int(rec["t"] * 1e9)),
                    "name": rec.get("stage", rec.get("name", "event")),
                    "attributes": [
                        _otlp_attr(k, v) for k, v in rec.items()
                        if k not in ("stage", "name", "t")
                    ],
                }
                for rec in stamps + sorted(span["events"],
                                           key=lambda r: r["t"])
            ]
            spans_out.append({
                "traceId": (span["key"] * 2)[:32],
                "spanId": span["key"][:16],
                "name": "message",
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(int(t0 * 1e9)),
                "endTimeUnixNano": str(int(t1 * 1e9)),
                "attributes": [
                    _otlp_attr("closed", span["closed"]),
                    *(_otlp_attr(k, v) for k, v in span["attrs"].items()),
                ],
                "events": otlp_events,
            })
        return {
            "resourceSpans": [{
                "resource": {
                    "attributes": [
                        _otlp_attr("service.name", service_name),
                        _otlp_attr("clock", "host-monotonic"),
                    ],
                },
                "scopeSpans": [{
                    "scope": {"name": "go_libp2p_pubsub_tpu.obs.spans"},
                    "spans": spans_out,
                }],
            }],
        }


def _json_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Coerce stamp/event attrs to JSON-safe scalars (numpy ints from the
    digest path are the usual offenders)."""
    out: Dict[str, Any] = {}
    for k, v in attrs.items():
        if isinstance(v, (bool, int, float, str)) or v is None:
            out[k] = v
        elif hasattr(v, "item"):
            out[k] = v.item()
        else:
            out[k] = str(v)
    return out


def _otlp_attr(key: str, v: Any) -> dict:
    if isinstance(v, bool):
        val = {"boolValue": v}
    elif isinstance(v, int):
        val = {"intValue": str(v)}
    elif isinstance(v, float):
        val = {"doubleValue": v}
    else:
        val = {"stringValue": str(v)}
    return {"key": key, "value": val}
