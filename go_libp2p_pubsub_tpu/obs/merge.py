"""Cross-host span merge: per-host ledgers → end-to-end per-message traces.

r18 gave each host a :class:`~.spans.SpanLedger`; r19 makes it distributed
(``net/live.py`` stamps hop spans on every traced frame's path).  This
module is the collector side: each host exports its ledger as an
``obs-span-host/1`` artifact, and :func:`merge_host_artifacts` folds any
number of them into ONE ``obs-span-merged/1`` artifact holding an
end-to-end trace per message — the origin's ``publish`` stamp through every
subscriber's ``deliver`` stamp — with per-message propagation quantiles,
a per-hop breakdown, and failover/park windows rendered as annotated gaps
spanning the hosts that observed them.

Clock model: span timestamps are each host's injected clock (monotonic by
default), NOT comparable across real machines.  Every host artifact carries
a ``clock_offset_s`` estimate (host clock minus the deployment's reference
clock) and the merge subtracts it before comparing timestamps; traced wire
frames additionally carry the ORIGIN's estimate so a receiver records it on
the recv stamp (``origin_offset``) even when the origin's artifact never
reaches the collector.  In-process test networks share one clock, so
offsets default to 0.0 and the subtraction is exact.

The merge is deterministic in the input *set*: artifacts are keyed and
sorted by host id, spans by content key, stamps by time — shuffling the
input list yields a byte-identical artifact (a test pins this).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..utils.metrics import quantiles

# Stage vocabulary (write side: net/live.py; see spans.HOP_STAGES).
_PUBLISH = "publish"
_SEND = "send"
_RECV = "recv"
_DELIVER = "deliver"
_REPLAY_SEND = "replay_send"

# Event names that open / close a failover window (write side: the live
# subscription's failover walk).  "parent_lost" marks when a host first
# observed the old regime die; any of the _HEAL names marks the moment a
# live regime claimed it back.
_LOST_EVENTS = ("parent_lost",)
_PARK_EVENTS = ("failover_parked",)
_HEAL_EVENTS = ("promoted", "failover_merged")


def build_host_span_artifact(
    host: str,
    ledger,
    clock_offset_s: float = 0.0,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """One live host's ledger as a self-contained, merge-ready artifact."""
    snap = ledger.snapshot()
    doc: Dict[str, Any] = {
        "format": "obs-span-host/1",
        "host": host,
        "clock_offset_s": float(clock_offset_s),
        "sample_n": snap["sample_n"],
        "spans": snap["spans"],
        "events": snap["events"],
        "dropped_spans": snap["dropped_spans"],
        "duplicate_closes": snap["duplicate_closes"],
        "summary": ledger.summary(),
    }
    if extra:
        doc.update(extra)
    return doc


def merge_host_artifacts(
    artifacts: List[Dict[str, Any]],
    scenario: Optional[str] = None,
    verdict: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Fold per-host ``obs-span-host/1`` artifacts into one
    ``obs-span-merged/1`` document (see module docstring)."""
    if not artifacts:
        raise ValueError("merge needs at least one host artifact")
    by_host: Dict[str, Dict[str, Any]] = {}
    for doc in artifacts:
        if doc.get("format") != "obs-span-host/1":
            raise ValueError(
                f"not an obs-span-host/1 artifact: {doc.get('format')!r}"
            )
        host = str(doc["host"])
        if host in by_host:
            raise ValueError(f"duplicate host artifact: {host!r}")
        by_host[host] = doc
    hosts = sorted(by_host)
    sample_ns = {int(by_host[h]["sample_n"]) for h in hosts}
    if len(sample_ns) != 1:
        # Hosts sampling at different rates would silently disagree on
        # which messages have cross-host traces — refuse to merge.
        raise ValueError(
            f"host artifacts disagree on sample_n: {sorted(sample_ns)}"
        )
    sample_n = sample_ns.pop()

    # -- normalize: every stamp/event onto the reference clock --------------
    # hops[key] = list of {host, stage, t, ...attrs}; events = global list.
    hops: Dict[str, List[dict]] = {}
    events: List[dict] = []
    for h in hosts:
        doc = by_host[h]
        off = float(doc.get("clock_offset_s", 0.0))
        for span in doc["spans"]:
            key = span["key"]
            for rec in span["stamps"]:
                hop = {k: v for k, v in rec.items() if k != "t"}
                hop["host"] = h
                hop["t"] = float(rec["t"]) - off
                hops.setdefault(key, []).append(hop)
            for ev in span.get("events", []):
                rec2 = {k: v for k, v in ev.items() if k != "t"}
                rec2["host"] = h
                rec2["t"] = float(ev["t"]) - off
                rec2["span"] = key
                events.append(rec2)
        for ev in doc["events"]:
            rec2 = {k: v for k, v in ev.items() if k != "t"}
            rec2["host"] = h
            rec2["t"] = float(ev["t"]) - off
            events.append(rec2)
    events.sort(key=lambda e: (e["t"], e["host"], e["name"]))

    # -- per-message end-to-end traces --------------------------------------
    traces: List[dict] = []
    all_latencies: List[float] = []
    per_hop: Dict[str, List[float]] = {}
    for key in sorted(hops):
        recs = sorted(hops[key],
                      key=lambda r: (r["t"], r["host"], r["stage"]))
        pubs = [r for r in recs if r["stage"] == _PUBLISH]
        delivers = [r for r in recs if r["stage"] == _DELIVER]
        t_pub = pubs[0]["t"] if pubs else None
        deliveries = []
        for d in delivers:
            row = {"host": d["host"], "t": d["t"]}
            if t_pub is not None:
                row["latency_s"] = d["t"] - t_pub
                all_latencies.append(row["latency_s"])
            deliveries.append(row)
        lat = [d["latency_s"] for d in deliveries if "latency_s" in d]
        trace: Dict[str, Any] = {
            "key": key,
            "hosts": sorted({r["host"] for r in recs}),
            "publish": (
                {"host": pubs[0]["host"], "t": t_pub} if pubs else None
            ),
            "deliveries": deliveries,
            "hops": recs,
        }
        if lat:
            q = quantiles(lat, (0.5, 0.99))
            trace["propagation"] = {
                "n": len(lat), "p50_s": q["p50"], "p99_s": q["p99"],
                "max_s": max(lat),
            }
        traces.append(trace)
        _accumulate_hop_breakdown(recs, per_hop)

    q_all = quantiles(all_latencies, (0.5, 0.99))
    propagation = {
        "sample_n": sample_n,
        "messages": sum(1 for t in traces if t["publish"] is not None),
        "deliveries": len(all_latencies),
        "p50_s": q_all["p50"],
        "p99_s": q_all["p99"],
        "max_s": max(all_latencies) if all_latencies else float("nan"),
        "per_hop": {
            name: {"count": len(xs), **quantiles(xs, (0.5, 0.99))}
            for name, xs in sorted(per_hop.items())
        },
    }

    gap = _recovery_gap(events)
    doc = {
        "format": "obs-span-merged/1",
        "plane": "live",
        "scenario": scenario,
        "verdict": verdict,
        "hosts": hosts,
        "sample_n": sample_n,
        "clock_offsets_s": {
            h: float(by_host[h].get("clock_offset_s", 0.0)) for h in hosts
        },
        "dropped_spans": sum(
            int(by_host[h].get("dropped_spans", 0)) for h in hosts),
        "traces": traces,
        "events": events,
        "propagation": propagation,
        "recovery_gap": gap,
        "chrome_trace": _merged_chrome_trace(hosts, traces, events, gap),
        "otlp": _merged_otlp(hosts, traces),
    }
    if extra:
        doc.update(extra)
    return doc


def propagation_latencies(merged: Dict[str, Any]) -> List[Tuple[str, str, float]]:
    """Flatten a merged artifact back to ``(key, host, latency_s)`` rows —
    what the live runner feeds the SLO's latency histogram."""
    out: List[Tuple[str, str, float]] = []
    for tr in merged["traces"]:
        for d in tr["deliveries"]:
            if "latency_s" in d:
                out.append((tr["key"], d["host"], d["latency_s"]))
    return out


def _accumulate_hop_breakdown(
    recs: List[dict], per_hop: Dict[str, List[float]]
) -> None:
    """Per-hop latency components for one trace.

    - ``publish->send``: the origin's local fan-out cost;
    - ``send->recv``:    one tree edge (wire + chaos), paired exactly: each
      recv stamp carries ``from`` (the sender id) and each host sends a
      given key once, so the edge is (sender's send stamp) → (this recv);
    - ``recv->send``:    relay turnaround on an interior host;
    - ``recv->deliver``: local delivery on the receiving host.
    Replayed copies (``replay_send`` and recvs flagged ``replay``) are
    excluded — a repair's second copy is not a propagation hop.
    """
    first_send: Dict[str, dict] = {}
    by_host: Dict[str, List[dict]] = {}
    for r in recs:
        by_host.setdefault(r["host"], []).append(r)
        if r["stage"] == _SEND and r["host"] not in first_send:
            first_send[r["host"]] = r
    for r in recs:
        if r["stage"] == _RECV and not r.get("replay"):
            sender = r.get("from")
            s = first_send.get(sender)
            if s is not None and s["t"] <= r["t"]:
                per_hop.setdefault("send->recv", []).append(r["t"] - s["t"])
    for host, rows in by_host.items():
        stages: Dict[str, dict] = {}  # first stamp of each stage on host
        for r in rows:
            stages.setdefault(r["stage"], r)
        pub, snd = stages.get(_PUBLISH), stages.get(_SEND)
        rcv, dlv = stages.get(_RECV), stages.get(_DELIVER)
        if pub is not None and snd is not None and snd["t"] >= pub["t"]:
            per_hop.setdefault("publish->send", []).append(
                snd["t"] - pub["t"])
        if rcv is not None and not rcv.get("replay"):
            if snd is not None and snd["t"] >= rcv["t"]:
                per_hop.setdefault("recv->send", []).append(
                    snd["t"] - rcv["t"])
            if dlv is not None and dlv["t"] >= rcv["t"]:
                per_hop.setdefault("recv->deliver", []).append(
                    dlv["t"] - rcv["t"])


def _recovery_gap(events: List[dict]) -> Optional[dict]:
    """The failover window across the hosts that observed it.

    A promotion regime (root kill): first ``parent_lost`` → first
    ``promoted``.  A park/merge regime (partition minority): first
    ``failover_parked`` → last heal-class event.  ``None`` when no heal
    ever happened (nothing to annotate)."""
    lost = [e for e in events if e["name"] in _LOST_EVENTS]
    parked = [e for e in events if e["name"] in _PARK_EVENTS]
    heals = [e for e in events if e["name"] in _HEAL_EVENTS]
    if not heals:
        return None
    promoted = [e for e in heals if e["name"] == "promoted"]
    if promoted and lost:
        start = min(e["t"] for e in lost)
        end = min(e["t"] for e in promoted)
        kind = "promotion"
        observers = lost + promoted
    elif parked:
        start = min(e["t"] for e in parked)
        end = max(e["t"] for e in heals)
        kind = "park_merge"
        observers = parked + heals
    else:
        return None
    return {
        "kind": kind,
        "start_s": start,
        "end_s": end,
        "gap_s": max(0.0, end - start),
        "hosts": sorted({e["host"] for e in observers}),
    }


def _merged_chrome_trace(
    hosts: List[str],
    traces: List[dict],
    events: List[dict],
    gap: Optional[dict],
) -> dict:
    """Chrome trace-event JSON: ONE track (tid) per host, pid 0; each
    message renders as an X segment on every host it touched (that host's
    first → last stamp), ledger events as instants on their host's track,
    and the failover window as an annotated gap on track 0."""
    tid_of = {h: i + 1 for i, h in enumerate(hosts)}
    out: List[dict] = [{
        "name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
        "args": {"name": "cluster"},
    }]
    for h in hosts:
        out.append({
            "name": "thread_name", "ph": "M", "pid": 0, "tid": tid_of[h],
            "args": {"name": f"host {h}"},
        })
    for tr in traces:
        by_host: Dict[str, List[dict]] = {}
        for r in tr["hops"]:
            by_host.setdefault(r["host"], []).append(r)
        for h in sorted(by_host):
            rows = by_host[h]
            t0, t1 = rows[0]["t"], rows[-1]["t"]
            out.append({
                "name": f"msg {tr['key'][:12]}", "cat": "message",
                "ph": "X", "ts": round(t0 * 1e6, 3),
                "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                "pid": 0, "tid": tid_of[h],
                "args": {
                    "key": tr["key"],
                    "stages": [r["stage"] for r in rows],
                },
            })
    for e in events:
        out.append({
            "name": e["name"], "cat": "ledger", "ph": "i",
            "ts": round(e["t"] * 1e6, 3), "pid": 0,
            "tid": tid_of.get(e["host"], 0), "s": "t",
            "args": {k: v for k, v in e.items()
                     if k not in ("name", "t", "host")},
        })
    if gap is not None:
        out.append({
            "name": "failover_gap", "cat": "annotation", "ph": "X",
            "ts": round(gap["start_s"] * 1e6, 3),
            "dur": round(gap["gap_s"] * 1e6, 3),
            "pid": 0, "tid": 0,
            "args": {"kind": gap["kind"], "gap_s": gap["gap_s"],
                     "hosts": gap["hosts"]},
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _merged_otlp(
    hosts: List[str],
    traces: List[dict],
    service_name: str = "go_libp2p_pubsub_tpu.live",
) -> dict:
    """OTLP-shaped record: one resource per host; each message becomes one
    span per host it touched, all sharing the content-derived traceId so a
    backend reassembles the cross-host trace."""
    from .spans import _otlp_attr

    resource_spans = []
    for i, h in enumerate(hosts):
        spans_out = []
        for tr in traces:
            rows = [r for r in tr["hops"] if r["host"] == h]
            if not rows:
                continue
            t0, t1 = rows[0]["t"], rows[-1]["t"]
            spans_out.append({
                "traceId": (tr["key"] * 2)[:32],
                "spanId": f"{i:04x}{tr['key'][:12]}",
                "name": "message",
                "kind": 1,
                "startTimeUnixNano": str(int(t0 * 1e9)),
                "endTimeUnixNano": str(int(t1 * 1e9)),
                "attributes": [_otlp_attr("host.id", h)],
                "events": [
                    {
                        "timeUnixNano": str(int(r["t"] * 1e9)),
                        "name": r["stage"],
                        "attributes": [
                            _otlp_attr(k, v) for k, v in r.items()
                            if k not in ("stage", "t", "host")
                        ],
                    }
                    for r in rows
                ],
            })
        resource_spans.append({
            "resource": {
                "attributes": [
                    _otlp_attr("service.name", service_name),
                    _otlp_attr("host.id", h),
                    _otlp_attr("clock", "reference-normalized"),
                ],
            },
            "scopeSpans": [{
                "scope": {"name": "go_libp2p_pubsub_tpu.obs.merge"},
                "spans": spans_out,
            }],
        })
    return {"resourceSpans": resource_spans}
