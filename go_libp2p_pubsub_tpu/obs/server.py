"""The repo's ONE telemetry HTTP server (r19: both planes serve through it).

One :class:`~..utils.metrics.MetricsRegistry` — shared by the engine, the
ingest ring, the watchdog, the validation pipeline, or a whole live
network — rendered through ``render_prometheus``:

- ``GET /metrics``    Prometheus text exposition (format 0.0.4);
- ``GET /debug/obs``  JSON observability digest: span-ledger summary, the
  black box's recent frames, and the serving plane's live control surface
  (controller knobs + watchdog tier + recent decisions) — when wired;
- plus any ``extra_json`` endpoints the caller plugs in — the live plane
  mounts its ``/debug/tree`` topology snapshot here, so both planes share
  one serving path and one exposition formatter (the hand-rolled asyncio
  ``MetricsHTTPServer`` that lived in ``net/live.py`` since r6 is gone).

Runs a stdlib ``ThreadingHTTPServer`` on a daemon thread — works for
synchronous host code and for the live plane alike (its snapshot callables
only read loop-owned state, never await).  Bind port 0 for an ephemeral
port (tests).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional


class ObsHTTPServer:
    """Thread-backed observability endpoint over one shared registry."""

    def __init__(
        self,
        registry,
        ledger=None,
        blackbox=None,
        host: str = "127.0.0.1",
        port: int = 0,
        extra_json: Optional[Dict[str, Callable[[], object]]] = None,
        controls: Optional[Callable[[], object]] = None,
    ) -> None:
        self.registry = registry
        self.ledger = ledger
        self.blackbox = blackbox
        # r20: zero-arg callable returning the serving plane's live control
        # surface (controller knobs, watchdog tier, recent decisions) —
        # merged into /debug/obs as doc["controls"].  Typically
        # serve.controller.Controller.controls.
        self.controls = controls
        # path -> zero-arg callable returning a JSON-serializable doc,
        # rendered sorted-keys like /debug/obs.  Reserved paths lose.
        self.extra_json = dict(extra_json or {})
        self._bind = (host, port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            raise RuntimeError("server already started")
        owner = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = owner.registry.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                    status = 200
                elif path == "/debug/obs":
                    body = json.dumps(
                        owner._debug_doc(), sort_keys=True
                    ).encode()
                    ctype = "application/json"
                    status = 200
                elif path in owner.extra_json:
                    body = json.dumps(
                        owner.extra_json[path](), sort_keys=True
                    ).encode()
                    ctype = "application/json"
                    status = 200
                else:
                    body = b"not found\n"
                    ctype = "text/plain"
                    status = 404
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:  # quiet: no stderr spam
                pass

        self._httpd = ThreadingHTTPServer(self._bind, Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-metrics", daemon=True
        )
        self._thread.start()
        return self.port

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._bind[0]}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _debug_doc(self) -> dict:
        doc: dict = {"counters": self.registry.counters()}
        if self.ledger is not None:
            doc["spans"] = self.ledger.summary()
        if self.blackbox is not None:
            doc["blackbox"] = {
                "recorded": self.blackbox.recorded,
                "frames": self.blackbox.frames()[-8:],
            }
        if self.controls is not None:
            doc["controls"] = self.controls()
        return doc
