"""Message-lifecycle tracing and unified telemetry (r18).

Three pieces, one plane:

- :mod:`.spans` — a sampled per-message span ledger keyed on the r14
  ``content_hash`` identity, stamped by the ingest ring, the validation
  pipeline, and the streaming engine; exported as Chrome-trace/Perfetto
  JSON (same envelope as ``utils.trace.StepTimer``) and an OTLP-shaped
  record.  Spans ride the engine's checkpoint so a crash is an annotated
  gap, not a hole.
- :mod:`.blackbox` — a bounded ring of last-K per-chunk telemetry frames
  the watchdog dumps to a post-mortem JSON on ``restart_engine``.
- :mod:`.server` — the serving plane's ``/metrics`` endpoint: one
  :class:`~..utils.metrics.MetricsRegistry` shared by engine, ring,
  watchdog, and pipeline, rendered through ``render_prometheus``.
- :mod:`.export` — trace-artifact builders for all three scenario planes
  (``--trace-out``), summarized by ``tools/trace_view.py``.
- :mod:`.merge` — r19 cross-host collector: per-host live ledgers
  (``obs-span-host/1``) fold into one ``obs-span-merged/1`` artifact of
  end-to-end publish→delivery traces with propagation quantiles, per-hop
  breakdown, and failover windows as annotated gaps.

Everything here is host-side and strictly additive: with no tracer
installed the serving plane runs bit- and counter-identical to r17.
"""

from .blackbox import BlackBox
from .export import build_record_artifact, build_span_artifact, write_json
from .merge import (
    build_host_span_artifact,
    merge_host_artifacts,
    propagation_latencies,
)
from .server import ObsHTTPServer
from .spans import (
    HOP_STAGES,
    STAGES,
    SpanLedger,
    content_hash,
    envelope_span_key,
    live_span_key,
)

__all__ = [
    "BlackBox",
    "HOP_STAGES",
    "ObsHTTPServer",
    "STAGES",
    "SpanLedger",
    "build_host_span_artifact",
    "build_record_artifact",
    "build_span_artifact",
    "content_hash",
    "envelope_span_key",
    "live_span_key",
    "merge_host_artifacts",
    "propagation_latencies",
    "write_json",
]
