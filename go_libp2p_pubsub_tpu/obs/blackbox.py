"""Flight black box: a bounded ring of per-chunk telemetry frames.

The engine records one frame per chunk (queue depth, verify latency, shed
and dedup counters, chunk wall time); the ring keeps the last K, so when
the watchdog restarts a wedged engine it can dump the run-up to the death —
the post-mortem a crashed serving plane otherwise reduces to final
counters.  The dump is a plain JSON file through the same
write→fsync→rename discipline as ``utils.checkpoint``, so a crash *during*
the dump never leaves a truncated artifact shadowing the story.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional


class BlackBox:
    """Last-K frame ring.  Host-side, lock-free by ownership: the engine
    thread records, the watchdog dumps from the same serving loop."""

    def __init__(self, capacity: int = 64, clock=time.monotonic) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._clock = clock
        self._frames: deque = deque(maxlen=self.capacity)
        self.recorded = 0

    def record(self, frame: Dict[str, Any]) -> None:
        f = dict(frame)
        f.setdefault("t", float(self._clock()))
        self._frames.append(f)
        self.recorded += 1

    def frames(self) -> List[Dict[str, Any]]:
        return [dict(f) for f in self._frames]

    def __len__(self) -> int:
        return len(self._frames)

    def dump(self, path: str, extra: Optional[Dict[str, Any]] = None) -> str:
        """Write the post-mortem JSON atomically; returns ``path``."""
        doc = {
            "format": "obs-blackbox/1",
            "dumped_t": float(self._clock()),
            "capacity": self.capacity,
            "recorded": self.recorded,
            "frames": self.frames(),
        }
        if extra:
            doc["extra"] = extra
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path
