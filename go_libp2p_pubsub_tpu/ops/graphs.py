"""Graph/segment utilities shared by the overlay and gossip kernels.

These are the array-program primitives that replace the reference's
per-node Go data structures: segment ranking replaces "who gets the next
child slot" serialization under ``chlock`` (``subtree.go:18``), and masked
argmin replaces the min-size child scan in ``redirectJoin``
(``subtree.go:161-169``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Plain Python ints: converted inside traced code; creating device arrays at
# import time would initialize a jax backend as a side effect of `import`.
INVALID = -1
BIG_I32 = 2**31 - 1


def index_dtype(n: int) -> np.dtype:
    """Narrowest storage dtype that holds every peer-index value for ``n``
    peers: the ids ``0..n-1``, the segment-sum sentinel row ``n``, and the
    wrap-encoded ``-1`` invalid marker — i.e. the smallest dtype whose range
    covers ``n + 1`` distinct non-negative values plus one sentinel.

    uint16 for ``n <= 65534`` (ids <= 65533 in builders, sentinel row
    ``n <= 65534``, and ``-1`` wraps to 65535 — all distinct exactly when
    ``n + 1 <= 65535``), int32 above.  Raises instead of silently wrapping
    when even int32 cannot hold ``n + 1``.

    Storage stays narrow; kernel arithmetic (e.g. the composite-key trick in
    :func:`segment_rank`, ``key * (n + 1) + arange``) always widens to int32
    first, so narrow-plane results are bit-identical to the int32 path.
    """
    if n < 0:
        raise ValueError(f"index_dtype: peer count must be >= 0, got {n}")
    if n + 1 <= np.iinfo(np.uint16).max:
        return np.dtype(np.uint16)
    if n + 1 <= np.iinfo(np.int32).max:
        return np.dtype(np.int32)
    raise ValueError(
        f"index_dtype: n + 1 = {n + 1} exceeds int32; no supported index "
        f"storage dtype can hold it"
    )


def encode_index_plane(arr, n: int, dtype=None) -> np.ndarray:
    """Host-side: a ``-1``-sentinel signed index plane -> narrow storage.

    Validates the value range first and raises a clear error rather than
    silently wrapping out-of-range ids: every entry must be in
    ``[-1, n - 1]`` (builder ids) — the sole negative value ``-1`` is
    wrap-encoded to the unsigned dtype's max (65535 for uint16), which can
    never collide with a valid id because :func:`index_dtype` only selects
    uint16 when ``n + 1 <= 65535``.

    ``dtype`` overrides the auto selection (e.g. ``np.int32`` to force the
    legacy wide path for identity testing); forcing a dtype too narrow for
    ``n`` raises.
    """
    dt = np.dtype(dtype) if dtype is not None else index_dtype(n)
    if dt.kind == "u" and n + 1 > np.iinfo(dt).max:
        raise ValueError(
            f"encode_index_plane: n + 1 = {n + 1} exceeds {dt.name} storage "
            f"(max {np.iinfo(dt).max}); use index_dtype(n) or int32"
        )
    a = np.asarray(arr)
    if a.dtype.kind == "u":  # already wrap-encoded: restore -1 first
        a = decode_index_plane(a)
    if a.size and (a.min() < -1 or a.max() >= n):
        raise ValueError(
            f"encode_index_plane: values outside [-1, {n - 1}] "
            f"(got min={a.min()}, max={a.max()}) would wrap silently"
        )
    return a.astype(dt)


def decode_index_plane(arr):
    """Narrow index storage -> int32 with the ``-1`` sentinel restored.

    Works on both host numpy arrays and traced jax values; signed input
    (the legacy int32 path, or builder int64) is a plain cast, so the
    decoded plane is byte-identical either way and XLA elides the no-op.
    """
    xp = jnp if isinstance(arr, jax.Array) else np
    if np.dtype(arr.dtype).kind == "u":
        sentinel = np.iinfo(arr.dtype).max
        wide = arr.astype(xp.int32)
        return xp.where(wide == sentinel, xp.int32(-1), wide)
    return arr.astype(xp.int32)


def segment_rank(targets: jax.Array, mask: jax.Array) -> jax.Array:
    """Rank of each masked element among elements sharing its target.

    ``targets``: i32[N] target index per element; ``mask``: bool[N] selects
    participating elements.  Returns i32[N]: 0-based ordinal (stable by
    element index) within each target group; unmasked elements get 0.

    This is how concurrent joiners aiming at the same parent are ordered
    where the reference serialized them under the parent's ``chlock``
    (``subtree.go:101-103``).
    """
    n = targets.shape[0]
    key = jnp.where(mask, targets, n).astype(jnp.int32)
    # Stable sort by (key, index): compose into one sortable key.
    composite = key * jnp.int32(n + 1) + jnp.arange(n, dtype=jnp.int32)
    order = jnp.argsort(composite)
    sorted_key = key[order]
    pos = jnp.arange(n, dtype=jnp.int32)
    is_first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sorted_key[1:] != sorted_key[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(is_first, pos, 0))
    rank_sorted = pos - seg_start
    return jnp.zeros((n,), jnp.int32).at[order].set(rank_sorted)


def masked_argmin(values: jax.Array, mask: jax.Array, axis: int = -1) -> jax.Array:
    """Index of the minimum among masked entries (ties -> lowest index).

    The array form of the min-size live-child scan in ``redirectJoin``
    (``subtree.go:161-169``), without its all-dead nil-deref bug
    (``subtree.go:172-176``): with an all-false mask the result is 0 and the
    caller must check ``mask.any(axis)`` itself.
    """
    v = jnp.where(mask, values, BIG_I32)
    return jnp.argmin(v, axis=axis).astype(jnp.int32)


def safe_gather(arr: jax.Array, idx: jax.Array, fill=0):
    """Gather ``arr[idx]`` treating negative indices as invalid -> ``fill``."""
    valid = idx >= 0
    clipped = jnp.clip(idx, 0, arr.shape[0] - 1)
    out = arr[clipped]
    if out.ndim > valid.ndim:  # row gather from a 2D table: broadcast the mask
        valid = valid.reshape(valid.shape + (1,) * (out.ndim - valid.ndim))
    return jnp.where(valid, out, fill)


def top_mask(
    vals: jax.Array, count, kmax: int | None = None
) -> jax.Array:
    """bool[N, K] mask of the per-row top-``count`` finite entries of
    ``vals`` (ineligible entries must be -inf; ties break to the lowest
    slot index).

    ``count`` is a static int or an i32[N] per-row quota; ``kmax`` bounds
    the iteration count when ``count`` is an array (defaults to K).

    Replaces argsort-based rank selection in the heartbeat: a TPU sort of
    [N, K] costs orders of magnitude more than ``count`` masked max-reduces
    when ``count`` (the mesh degree family: D, D_score, d_lazy) is small.
    """
    n, k = vals.shape
    static = isinstance(count, int)
    iters = count if static else min(int(kmax if kmax is not None else k), k)
    if static and iters <= 0:
        return jnp.zeros((n, k), bool)
    chosen = jnp.zeros((n, k), bool)
    neg_inf = jnp.float32(-jnp.inf)
    col = jnp.arange(k, dtype=jnp.int32)
    for t in range(iters):
        v = jnp.where(chosen, neg_inf, vals)
        idx = jnp.argmax(v, axis=1)                    # ties -> lowest index
        best = jnp.take_along_axis(v, idx[:, None], axis=1)[:, 0]
        ok = jnp.isfinite(best)
        if not static:
            ok = ok & (t < count)
        chosen = chosen | ((col[None, :] == idx[:, None]) & ok[:, None])
    return chosen


def nth_free_slot(row_used: jax.Array, rank: jax.Array) -> jax.Array:
    """Index of the ``rank``-th free (False) slot in a boolean row.

    ``row_used``: bool[W]; ``rank``: scalar i32.  Returns W when there is no
    such slot (caller scatters with mode='drop').
    """
    w = row_used.shape[0]
    slot_ids = jnp.where(~row_used, jnp.arange(w, dtype=jnp.int32), w)
    ordered = jnp.sort(slot_ids)
    return jnp.where(rank < w, ordered[jnp.clip(rank, 0, w - 1)], w).astype(jnp.int32)
