"""Graph/segment utilities shared by the overlay and gossip kernels.

These are the array-program primitives that replace the reference's
per-node Go data structures: segment ranking replaces "who gets the next
child slot" serialization under ``chlock`` (``subtree.go:18``), and masked
argmin replaces the min-size child scan in ``redirectJoin``
(``subtree.go:161-169``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Plain Python ints: converted inside traced code; creating device arrays at
# import time would initialize a jax backend as a side effect of `import`.
INVALID = -1
BIG_I32 = 2**31 - 1


def segment_rank(targets: jax.Array, mask: jax.Array) -> jax.Array:
    """Rank of each masked element among elements sharing its target.

    ``targets``: i32[N] target index per element; ``mask``: bool[N] selects
    participating elements.  Returns i32[N]: 0-based ordinal (stable by
    element index) within each target group; unmasked elements get 0.

    This is how concurrent joiners aiming at the same parent are ordered
    where the reference serialized them under the parent's ``chlock``
    (``subtree.go:101-103``).
    """
    n = targets.shape[0]
    key = jnp.where(mask, targets, n).astype(jnp.int32)
    # Stable sort by (key, index): compose into one sortable key.
    composite = key * jnp.int32(n + 1) + jnp.arange(n, dtype=jnp.int32)
    order = jnp.argsort(composite)
    sorted_key = key[order]
    pos = jnp.arange(n, dtype=jnp.int32)
    is_first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sorted_key[1:] != sorted_key[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(is_first, pos, 0))
    rank_sorted = pos - seg_start
    return jnp.zeros((n,), jnp.int32).at[order].set(rank_sorted)


def masked_argmin(values: jax.Array, mask: jax.Array, axis: int = -1) -> jax.Array:
    """Index of the minimum among masked entries (ties -> lowest index).

    The array form of the min-size live-child scan in ``redirectJoin``
    (``subtree.go:161-169``), without its all-dead nil-deref bug
    (``subtree.go:172-176``): with an all-false mask the result is 0 and the
    caller must check ``mask.any(axis)`` itself.
    """
    v = jnp.where(mask, values, BIG_I32)
    return jnp.argmin(v, axis=axis).astype(jnp.int32)


def safe_gather(arr: jax.Array, idx: jax.Array, fill=0):
    """Gather ``arr[idx]`` treating negative indices as invalid -> ``fill``."""
    valid = idx >= 0
    clipped = jnp.clip(idx, 0, arr.shape[0] - 1)
    out = arr[clipped]
    if out.ndim > valid.ndim:  # row gather from a 2D table: broadcast the mask
        valid = valid.reshape(valid.shape + (1,) * (out.ndim - valid.ndim))
    return jnp.where(valid, out, fill)


def top_mask(
    vals: jax.Array, count, kmax: int | None = None
) -> jax.Array:
    """bool[N, K] mask of the per-row top-``count`` finite entries of
    ``vals`` (ineligible entries must be -inf; ties break to the lowest
    slot index).

    ``count`` is a static int or an i32[N] per-row quota; ``kmax`` bounds
    the iteration count when ``count`` is an array (defaults to K).

    Replaces argsort-based rank selection in the heartbeat: a TPU sort of
    [N, K] costs orders of magnitude more than ``count`` masked max-reduces
    when ``count`` (the mesh degree family: D, D_score, d_lazy) is small.
    """
    n, k = vals.shape
    static = isinstance(count, int)
    iters = count if static else min(int(kmax if kmax is not None else k), k)
    if static and iters <= 0:
        return jnp.zeros((n, k), bool)
    chosen = jnp.zeros((n, k), bool)
    neg_inf = jnp.float32(-jnp.inf)
    col = jnp.arange(k, dtype=jnp.int32)
    for t in range(iters):
        v = jnp.where(chosen, neg_inf, vals)
        idx = jnp.argmax(v, axis=1)                    # ties -> lowest index
        best = jnp.take_along_axis(v, idx[:, None], axis=1)[:, 0]
        ok = jnp.isfinite(best)
        if not static:
            ok = ok & (t < count)
        chosen = chosen | ((col[None, :] == idx[:, None]) & ok[:, None])
    return chosen


def nth_free_slot(row_used: jax.Array, rank: jax.Array) -> jax.Array:
    """Index of the ``rank``-th free (False) slot in a boolean row.

    ``row_used``: bool[W]; ``rank``: scalar i32.  Returns W when there is no
    such slot (caller scatters with mode='drop').
    """
    w = row_used.shape[0]
    slot_ids = jnp.where(~row_used, jnp.arange(w, dtype=jnp.int32), w)
    ordered = jnp.sort(slot_ids)
    return jnp.where(rank < w, ordered[jnp.clip(rank, 0, w - 1)], w).astype(jnp.int32)
