"""Jitted array kernels: tree overlay, gossip, scoring, validation, graph utils."""
