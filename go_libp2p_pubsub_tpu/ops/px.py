"""Peer exchange on prune (GossipSub v1.1 PX) as a topology-rewire kernel.

When a peer prunes a mesh link for oversubscription, the spec has it include
a sample of its own mesh peers in the PRUNE so the pruned side can form new
connections — the mechanism that keeps a mesh from fragmenting as degrees
are trimmed.  The v0 reference has no notion of this (its tree repair dials
recorded grandchildren instead, ``/root/reference/subtree.go:356-375``); here
PX is the one operation that MUTATES the otherwise-static neighbor-slot
adjacency: a new (i, m) edge is written into a free slot on both endpoints.

Spec gates, both enforced score-side:

- the pruner only offers PX to peers it scores >= 0 (no feeding peers to a
  misbehaving node);
- the pruned peer only accepts PX from pruners it scores
  >= ``accept_px_threshold`` (``ScoreParams``) — a sybil cannot use PRUNE-PX
  to steer a victim toward attacker peers unless it first earned that score.

Parallel-conflict discipline (everything happens in one jitted heartbeat):
at most one PX connection forms per initiator and per acceptor per
heartbeat; an acceptor is never itself an initiator.  Winners are chosen by
a scatter-min over initiator ids, so every write below touches a distinct
(row, slot) and the slot-pairing invariant ``nbrs[m, rev[i,s]] == i`` is
preserved by construction.  Runs at heartbeat rate, far off the propagate
hot path.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .gossip import uniform_by_uid


class PxOut(NamedTuple):
    nbrs: jax.Array       # i32[N, K]
    rev: jax.Array        # i32[N, K]
    nbr_valid: jax.Array  # bool[N, K]
    outbound: jax.Array   # bool[N, K] (initiator side of a PX edge dials)
    backoff: jax.Array    # i32[N, K] (reset on the new slots)
    connected: jax.Array  # bool[N] diagnostic: peer initiated a PX edge


def px_rewire(
    key: jax.Array,
    nbrs: jax.Array,       # i32[N, K]
    rev: jax.Array,        # i32[N, K]
    nbr_valid: jax.Array,  # bool[N, K]
    outbound: jax.Array,   # bool[N, K]
    backoff: jax.Array,    # i32[N, K]
    mesh: jax.Array,       # bool[N, K] POST-heartbeat mesh (the PX sample pool)
    pruned: jax.Array,     # bool[N, K] edges pruned this heartbeat
    scores: jax.Array,     # f32[N, K]
    alive: jax.Array,      # bool[N]
    accept_px_threshold: float,
    uid: Optional[jax.Array] = None,  # i32[N] canonical id per physical row
    edge_idx: Optional[Tuple[jax.Array, jax.Array]] = None,  # shared (jidx, ridx)
    offer_ok: Optional[jax.Array] = None,  # bool[N, K] precomputed offer gate
) -> PxOut:
    """One PX round: each pruned peer may open one new connection to a
    random mesh neighbor of its pruner.  Returns the rewired adjacency.

    ``edge_idx`` / ``offer_ok`` are the fused-prologue hooks: the heartbeat
    shares one clipped ``(jidx, ridx)`` pair across its prologue kernels,
    and ``heartbeat_mesh(..., with_px_offer=True)`` already gathered the
    pruner's ``scores >= 0`` view on its bitfield gather — passing it here
    skips this kernel's only [N, K] slot-pairing gather (bit-exact: the
    compare commutes with the gather)."""
    n, k = nbrs.shape
    if edge_idx is None:
        jidx = jnp.clip(nbrs, 0, n - 1)
        ridx = jnp.clip(rev, 0, k - 1)
    else:
        jidx, ridx = edge_idx
    peer_ids = jnp.arange(n, dtype=jnp.int32)

    # Which pruned slots carry an acceptable PX offer.
    if offer_ok is None:
        offer_ok = scores[jidx, ridx] >= 0.0      # pruner j offers (its view of me)
    accept_ok = scores >= accept_px_threshold     # I trust pruner j enough
    px_edge = pruned & offer_ok & accept_ok & nbr_valid
    has_px = px_edge.any(axis=1)
    s_sel = jnp.argmax(px_edge, axis=1).astype(jnp.int32)  # first offering slot
    j_sel = jidx[peer_ids, s_sel]                          # the pruner, i32[N]

    # Candidate m: a uniformly random CURRENT mesh neighbor of the pruner
    # (the spec's "sample of my mesh" in the PRUNE).
    mesh_j = mesh[j_sel]                                   # bool[N, K] row gather
    rnd = uniform_by_uid(key, (n, k), uid)
    cand_slot = jnp.argmax(jnp.where(mesh_j, rnd, -jnp.inf), axis=1)
    has_cand = mesh_j.any(axis=1)
    m = jidx[j_sel, cand_slot.astype(jnp.int32)]           # i32[N]

    # Initiator validity: a live peer with a PX offer, a usable candidate
    # that is alive, not itself, not already a neighbor, and a free slot.
    already = ((nbrs == m[:, None]) & nbr_valid).any(axis=1)
    free_cnt = (~nbr_valid).sum(axis=1)
    init = (
        has_px
        & has_cand
        & alive
        & alive[m]
        & (m != peer_ids)
        & ~already
        & (free_cnt > 0)
    )
    # Acceptors must not be initiators (each row is written at most once).
    init = init & ~init[m]
    init = init & (free_cnt[m] > 0)

    # One initiator per acceptor: scatter-min of initiator ids onto targets.
    # The min runs over CANONICAL ids (uid) so the winning initiator is the
    # same peer under any renumbering — raw physical ids would pick a
    # placement-dependent winner and break relabeling equivariance.
    uid_vals = peer_ids if uid is None else uid.astype(jnp.int32)
    tgt = jnp.where(init, m, n)
    winner = (
        jnp.full((n + 1,), n, jnp.int32).at[tgt].min(uid_vals, mode="drop")
    )
    win = init & (winner[tgt] == uid_vals)

    # Slot assignment: first free slot on each side.
    fi = jnp.argmax(~nbr_valid, axis=1).astype(jnp.int32)  # mine
    fm = fi[m]                                             # the acceptor's

    rows_i = jnp.where(win, peer_ids, n)
    rows_m = jnp.where(win, m, n)

    nbrs = nbrs.at[rows_i, fi].set(m, mode="drop")
    nbrs = nbrs.at[rows_m, fm].set(peer_ids, mode="drop")
    rev = rev.at[rows_i, fi].set(fm, mode="drop")
    rev = rev.at[rows_m, fm].set(fi, mode="drop")
    nbr_valid = nbr_valid.at[rows_i, fi].set(True, mode="drop")
    nbr_valid = nbr_valid.at[rows_m, fm].set(True, mode="drop")
    outbound = outbound.at[rows_i, fi].set(True, mode="drop")
    outbound = outbound.at[rows_m, fm].set(False, mode="drop")
    backoff = backoff.at[rows_i, fi].set(0, mode="drop")
    backoff = backoff.at[rows_m, fm].set(0, mode="drop")

    return PxOut(nbrs, rev, nbr_valid, outbound, backoff, win)
