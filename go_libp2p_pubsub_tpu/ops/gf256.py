"""GF(256) field arithmetic — the coded-gossip (RLNC) byte-matrix plane.

OPTIMUMP2P (PAPERS.md, arxiv 2508.04833) replaces store-and-forward gossip
with random linear network coding: a message is a *generation* of K source
fragments, relays forward random GF(256) combinations of whatever they
hold, and a receiver decodes the moment it has ANY K linearly independent
combinations.  Everything a relay or receiver does is therefore linear
algebra over bytes — coefficient-row times basis-matrix products on encode
(``gf_combine``/``gf_matmul``) and Gaussian elimination on decode
(``rref_insert``/``gf_solve``) — which is the one workload in this repo
that is natively matmul-shaped (ROADMAP item 5), unlike the int32 VPU
crypto.

Representation: the field is GF(2^8) with the AES reduction polynomial
``x^8 + x^4 + x^3 + x + 1`` (0x11B) and generator 0x03.  Addition is XOR;
multiplication goes through log/antilog tables (``exp[log[a] + log[b]]``,
the antilog table doubled to 510 entries so the hot path needs no mod-255)
— on device that is two integer gathers and a table lookup per product,
with the zero cases masked (log(0) is undefined; anything times 0 is 0).

Two formulations of the matmul coexist (PERF.md r11/r15):

- ``gf_matmul``/``gf_combine`` — the *table-lookup* form: XLA lowers the
  products to integer gathers on the VPU.  Cheap per-element on CPU, but
  never touches the MXU.
- ``gf_matmul_mxu``/``gf_combine_mxu`` — the *carry-less decomposition*:
  each operand splits into its 8 bit planes, one int8 ``dot_general``
  counts the per-bit-pair overlaps across the contraction axis (the
  integer count's PARITY is the XOR-accumulated carry-less product bit),
  and the 15 polynomial coefficient planes fold back to bytes through the
  precomputed residues ``x^t mod 0x11B``.  Bit-exact with the table path
  (both are exact field arithmetic; asserted over the exhaustive 256x256
  product table in ``tests/test_rlnc.py``), selected by ``RLNC(use_mxu=)``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_POLY = 0x11B  # AES reduction polynomial
_GEN = 0x03    # multiplicative generator


def _build_tables() -> Tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(510, np.int32)
    log = np.zeros(256, np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        # x *= 0x03  ==  xtime(x) ^ x, reduced mod _POLY.
        x2 = (x << 1) ^ (_POLY if x & 0x80 else 0)
        x = x2 ^ x
    exp[255:510] = exp[0:255]  # doubled: exp[log a + log b] needs no mod
    return exp, log


# Host-side module constants; jnp.asarray inside the kernels constant-folds
# them into the compiled programs.
GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Elementwise GF(256) product (uint8, numpy broadcasting)."""
    a32 = a.astype(jnp.int32)
    b32 = b.astype(jnp.int32)
    exp = jnp.asarray(GF_EXP)
    log = jnp.asarray(GF_LOG)
    prod = exp[log[a32] + log[b32]]
    return jnp.where((a32 == 0) | (b32 == 0), 0, prod).astype(jnp.uint8)


def gf_inv(a: jax.Array) -> jax.Array:
    """Elementwise multiplicative inverse; maps 0 -> 0 (no inverse exists —
    callers must mask the zero case, as ``rref_insert``/``gf_solve`` do)."""
    a32 = a.astype(jnp.int32)
    exp = jnp.asarray(GF_EXP)
    log = jnp.asarray(GF_LOG)
    return jnp.where(a32 == 0, 0, exp[255 - log[a32]]).astype(jnp.uint8)


def gf_combine(coeffs: jax.Array, rows: jax.Array) -> jax.Array:
    """Coefficient combination ``XOR_k coeffs[..., k] * rows[..., k, :]``.

    ``coeffs`` u8[..., K], ``rows`` u8[..., K, L] -> u8[..., L], with numpy
    broadcasting across the leading batch axes.  This is the encode kernel:
    one coded fragment is a random coefficient row combined over a holder's
    basis rows.  The K axis is unrolled (K is a small static generation
    size), so the peak intermediate is one [..., L] product per term — the
    general ``gf_matmul`` materializes the full [..., M, K, L] product table
    and is kept for the small decode-side solves.
    """
    k = rows.shape[-2]
    acc = gf_mul(coeffs[..., 0:1], rows[..., 0, :])
    for i in range(1, k):
        acc = acc ^ gf_mul(coeffs[..., i : i + 1], rows[..., i, :])
    return acc


def gf_matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched GF(256) matrix product: u8[..., M, K] x u8[..., K, N] ->
    u8[..., M, N] (XOR-accumulated products over the contraction axis)."""
    a32 = a.astype(jnp.int32)[..., :, :, None]   # [..., M, K, 1]
    b32 = b.astype(jnp.int32)[..., None, :, :]   # [..., 1, K, N]
    exp = jnp.asarray(GF_EXP)
    log = jnp.asarray(GF_LOG)
    prod = jnp.where(
        (a32 == 0) | (b32 == 0), 0, exp[log[a32] + log[b32]]
    ).astype(jnp.uint8)
    return jax.lax.reduce(
        prod, np.uint8(0), jax.lax.bitwise_xor, dimensions=(prod.ndim - 2,)
    )


# Residues x^t mod 0x11B for t = 8..14: where the high coefficient planes of
# the 15-term carry-less product land after polynomial reduction.
_MXU_REDUCE = (0x1B, 0x36, 0x6C, 0xD8, 0xAB, 0x4D, 0x9A)


def gf_matmul_mxu(a: jax.Array, b: jax.Array) -> jax.Array:
    """:func:`gf_matmul` on the MXU: u8[..., M, K] x u8[..., K, N] ->
    u8[..., M, N], bit-exact with the table path.

    Decomposition: a GF(256) product is a carry-less (GF(2)[x]) 8x8-bit
    polynomial product followed by reduction mod 0x11B, and XOR
    accumulation over the contraction axis commutes with both.  Coefficient
    ``t`` of the accumulated carry-less product is the PARITY of
    ``sum_k sum_{i+j=t} a_i[m,k] * b_j[k,n]`` over the bit planes
    ``a_i = (a >> i) & 1`` — an integer bit-plane dot product.  One int8
    ``dot_general`` (the einsum below) computes all 64 plane-pair counts;
    int8 x int8 -> int32 contractions are the MXU's native shape, so this
    is the formulation that rides the systolic array instead of the VPU
    gather unit.  On CPU the 64 tiny matmuls usually LOSE to the table
    lookups — the flag defaults per backend (``models/rlnc.py``).
    """
    ap = (
        (a[..., None, :, :] >> jnp.arange(8, dtype=jnp.uint8)[:, None, None])
        & jnp.uint8(1)
    ).astype(jnp.int8)                                  # [..., 8, M, K]
    bp = (
        (b[..., None, :, :] >> jnp.arange(8, dtype=jnp.uint8)[:, None, None])
        & jnp.uint8(1)
    ).astype(jnp.int8)                                  # [..., 8, K, N]
    counts = jnp.einsum(
        "...imk,...jkn->...ijmn", ap, bp,
        preferred_element_type=jnp.int32,
    )                                                   # [..., 8, 8, M, N]
    acc = None
    for t in range(15):
        tot = None
        for i in range(max(0, t - 7), min(7, t) + 1):
            c = counts[..., i, t - i, :, :]
            tot = c if tot is None else tot + c
        par = (tot & 1).astype(jnp.uint8)               # coefficient plane t
        w = jnp.uint8((1 << t) if t < 8 else _MXU_REDUCE[t - 8])
        term = par * w
        acc = term if acc is None else acc ^ term
    return acc


def gf_combine_mxu(coeffs: jax.Array, rows: jax.Array) -> jax.Array:
    """:func:`gf_combine` through the MXU matmul: the encode kernel as a
    [1, K] x [K, L] byte product (same broadcasting contract)."""
    return gf_matmul_mxu(coeffs[..., None, :], rows)[..., 0, :]


def coeffs_by_uid(
    key: jax.Array,
    shape: Tuple[int, ...],
    uid: Optional[jax.Array] = None,
) -> jax.Array:
    """Random u8 coefficient draw keyed on canonical peer identity.

    The coded-gossip twin of ``ops.gossip.uniform_by_uid``: row axis 0 is
    the peer id, and a placement-relabeled run (``peer_uid`` set) gathers
    the draw through the canonical ids so the coefficients a peer emits
    depend on WHO it is, not where the placement put it.  ``uid=None`` is
    the identity fast path.
    """
    r = jax.random.randint(key, shape, 0, 256, dtype=jnp.int32).astype(
        jnp.uint8
    )
    return r if uid is None else r[uid]


# ---------------------------------------------------------------------------
# structured Gaussian elimination: the streaming decode-rank kernel
# ---------------------------------------------------------------------------


def rref_insert(basis: jax.Array, v: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Fold one received coefficient vector into a structured basis.

    ``basis`` is u8[K, K] in *pivot-slot* form: row p is either all-zero
    (absent) or has its first nonzero at column p, normalized to 1 —
    presence is readable off the diagonal, so no separate rank counter is
    carried.  The insert is the streaming half of Gaussian elimination:

    1. forward-eliminate ``v`` against every present row in pivot order
       (``fori_loop`` — after the sweep, v is zero at every present pivot);
    2. the residual's first nonzero column p is an EMPTY slot; normalize by
       ``gf_inv(v[p])`` and store there.

    A dependent (or zero) vector leaves the basis unchanged.  Returns
    ``(basis', inserted)``; rank is ``gf_rank(basis')``.  Fully traceable,
    O(K^2) table lookups — ``vmap`` it over [peers, generations] and the
    whole network's decode state advances as one batched kernel.
    """
    kk = basis.shape[-1]

    def eliminate(p, vec):
        present = basis[p, p] != 0
        factor = jnp.where(present, vec[p], 0).astype(jnp.uint8)
        return vec ^ gf_mul(jnp.broadcast_to(factor, (kk,)), basis[p])

    v = jax.lax.fori_loop(0, kk, eliminate, v.astype(jnp.uint8))
    nz = v != 0
    inserted = nz.any()
    p = jnp.argmax(nz)  # first nonzero column == the empty pivot slot
    newrow = gf_mul(jnp.broadcast_to(gf_inv(v[p]), (kk,)), v)
    basis = basis.at[p].set(jnp.where(inserted, newrow, basis[p]))
    return basis, inserted


def gf_rank(basis: jax.Array) -> jax.Array:
    """i32[...]: occupied pivot-slot count of structured bases
    (u8[..., K, K] as maintained by :func:`rref_insert`)."""
    diag = jnp.diagonal(basis, axis1=-2, axis2=-1)
    return (diag != 0).sum(axis=-1).astype(jnp.int32)


@functools.partial(jax.jit)
def gf_solve(a: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Solve ``A @ X = B`` over GF(256) by Gauss-Jordan with row pivoting.

    ``a`` u8[K, K], ``b`` u8[K, L] -> ``(x, ok)`` with ``x`` u8[K, L] and
    ``ok`` a bool scalar that is False when A is singular (x is then
    garbage).  The full-solve twin of the streaming :func:`rref_insert`:
    the decode path a receiver runs ONCE per generation, when its basis
    hits full rank and the payload fragments get recovered.  Static K/L,
    ``fori_loop`` over columns — device-side and vmap-able.
    """
    kk = a.shape[0]
    ab = jnp.concatenate(
        [a.astype(jnp.uint8), b.astype(jnp.uint8)], axis=1
    )

    def col(i, carry):
        ab, ok = carry
        cand = (jnp.arange(kk) >= i) & (ab[:, i] != 0)
        ok = ok & cand.any()
        piv = jnp.argmax(cand)
        ri, rp = ab[i], ab[piv]
        ab = ab.at[i].set(rp).at[piv].set(ri)
        row = gf_mul(gf_inv(ab[i, i])[None], ab[i])
        factors = jnp.where(jnp.arange(kk) == i, 0, ab[:, i]).astype(
            jnp.uint8
        )
        ab = (ab ^ gf_mul(factors[:, None], row[None, :])).at[i].set(row)
        return ab, ok

    ab, ok = jax.lax.fori_loop(0, kk, col, (ab, jnp.asarray(True)))
    return ab[:, kk:], ok
