"""Batched ed25519 verification as a JAX device kernel.

The TPU-native validator of BASELINE.json config (c): verify B signatures in
one jitted program, all curve arithmetic on device.  Same accept/reject
semantics as ``crypto/ed25519_ref.py`` (the Python oracle) and
``native/ed25519`` (the C++ host verifier): non-cofactored ``[S]B == R + [k]A``
with ``k = SHA512(R||A||M) mod L``, rejecting ``S >= L`` and non-canonical
point encodings.  SHA-512 runs host-side (OpenSSL-backed hashlib at ~GB/s —
hashing is not the bottleneck; curve ops are), everything after the hash runs
on device.

Representation — built for the TPU's int32 VPU lanes:

- Field elements of GF(2^255-19) are **22 signed int32 limbs, 12 bits each**
  (radix 2^12, 264-bit capacity, redundant).  Products a_i*b_j are < 2^24 and
  a 43-position convolution sums at most 22 of them: < 2^30, no int32
  overflow.  Negative limbs are legal between carry passes (subtraction needs
  no bias); arithmetic right-shift carries restore |limb| < 2^12.
- The fold constant for the redundant top is 2^264 mod p = 19*2^9 = 9728.
- Limb convolution is an einsum against a precomputed one-hot [43,22,22]
  tensor — XLA lowers it to a small matmul, which is exactly what the
  hardware wants; no gather/scatter in the hot loop.
- Points are extended twisted-Edwards (X, Y, Z, T) with the complete addition
  formula (valid for doubling and identity), so the ladders have **no
  data-dependent branches**.  Two verdict-identical double-scalarmult scans
  are available behind ``verify_batch(ladder=...)``: the 1-bit joint Straus
  scan (256 steps x double + 4-way select-add) and the r17 **w-bit windowed
  joint-table ladder** (ceil(256/w) steps x w dedicated doublings + one
  fused 4^w-way select-add, with a host comb for [i]B and a batch-parallel
  precompute plane for the joint grid).  ``lax.scan`` keeps each one XLA
  program.

Scalars (S and k) are public in verification, so variable-base bits arrive as
plain [B,256] arrays — no constant-time requirement.
"""

from __future__ import annotations

import functools
import hashlib
from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.ed25519_ref import D as _D_INT, L as _L_INT, P as _P_INT, _BX, _BY

LIMBS = 22
BITS = 12
RADIX = 1 << BITS
CONV = 2 * LIMBS - 1  # 43
FOLD = 9728  # 2^264 mod p = 19 * 2^9

# ---------------------------------------------------------------------------
# host-side constants
# ---------------------------------------------------------------------------


def _int_to_limbs(v: int) -> np.ndarray:
    return np.array([(v >> (BITS * i)) & (RADIX - 1) for i in range(LIMBS)], np.int32)


_ONE_HOT = np.zeros((CONV, LIMBS, LIMBS), np.int32)
for _i in range(LIMBS):
    for _j in range(LIMBS):
        _ONE_HOT[_i + _j, _i, _j] = 1

FE_D = _int_to_limbs(_D_INT)
FE_2D = _int_to_limbs(2 * _D_INT % _P_INT)
FE_BX = _int_to_limbs(_BX)
FE_BY = _int_to_limbs(_BY)
FE_BT = _int_to_limbs(_BX * _BY % _P_INT)
FE_SQRT_M1 = _int_to_limbs(pow(2, (_P_INT - 1) // 4, _P_INT))
FE_P = _int_to_limbs(_P_INT)
_POW_EXP_BITS = np.array(  # (p-5)/8, MSB first — decompression square root
    [((_P_INT - 5) // 8 >> i) & 1 for i in reversed(range(253))], np.int32
)

# ---------------------------------------------------------------------------
# field arithmetic on [..., LIMBS] int32
# ---------------------------------------------------------------------------


def _carry_once(x: jax.Array) -> jax.Array:
    """One ripple pass; the carry out of the top limb folds via 2^264 ≡ 9728."""
    c = x >> BITS  # arithmetic shift: correct for negative limbs
    lo = x - (c << BITS)
    shifted = jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)
    top = c[..., -1:]
    out = lo + shifted
    return out.at[..., 0].add(FOLD * top[..., 0])


def fe_norm(x: jax.Array) -> jax.Array:
    """Restore |limb| < 2^12 (three passes converge from conv magnitude)."""
    x = _carry_once(x)
    x = _carry_once(x)
    return _carry_once(x)


def fe_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    outer = a[..., :, None] * b[..., None, :]  # [..., 22, 22], < 2^24 each
    conv = jnp.einsum("...ij,kij->...k", outer, jnp.asarray(_ONE_HOT))
    lo, hi = conv[..., :LIMBS], conv[..., LIMBS:]
    hi = jnp.concatenate(
        [hi, jnp.zeros(hi.shape[:-1] + (LIMBS - hi.shape[-1],), hi.dtype)], axis=-1
    )
    return fe_norm(lo + FOLD * fe_norm(hi))


def fe_sq(a: jax.Array) -> jax.Array:
    return fe_mul(a, a)


def fe_add(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry_once(a + b)


def fe_sub(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry_once(a - b)  # signed limbs: no bias needed


def fe_canon(x: jax.Array) -> jax.Array:
    """Exact canonical form in [0, p): fold high bits, force limbs
    nonnegative, then one conditional subtract of p via a scanned ripple."""
    x = fe_norm(x)
    # Signed-normalized limbs put V in (-2^264, 2^264); adding 512p
    # (= 2^264 - 9728, a legal 22-limb constant) makes V nonnegative without
    # changing it mod p.  Then fold bits >= 255 twice:
    # V := (V mod 2^255) + 19*(V >> 255), landing V in [0, 2^255).
    x = fe_norm(x + jnp.asarray(_int_to_limbs(512 * _P_INT)))
    for _ in range(2):
        hi = x[..., 21] >> 3
        x = x.at[..., 21].add(-(hi << 3))
        x = x.at[..., 0].add(19 * hi)
        x = _carry_once(x)
        x = _carry_once(x)
    # V in [0, 2^255) < 2p: subtract p if V >= p, with an exact sequential
    # borrow ripple (22 steps, vectorized over the batch).
    p_l = jnp.asarray(FE_P)

    def borrow_step(carry, xi_pi):
        xi, pi = xi_pi
        d = xi - pi + carry
        b = (d < 0).astype(jnp.int32)
        return -b, (d + (b << BITS))

    carry0 = jnp.zeros(x.shape[:-1], jnp.int32)
    xs = jnp.moveaxis(x, -1, 0)
    ps = jnp.broadcast_to(p_l, x.shape)
    ps = jnp.moveaxis(ps, -1, 0)
    final_borrow, diffs = jax.lax.scan(borrow_step, carry0, (xs, ps))
    diffs = jnp.moveaxis(diffs, 0, -1)
    geq = final_borrow == 0  # no borrow out: x >= p
    return jnp.where(geq[..., None], diffs, x)


def fe_is_zero(x: jax.Array) -> jax.Array:
    return (fe_canon(x) == 0).all(axis=-1)


def fe_eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return fe_is_zero(fe_sub(a, b))


def fe_parity(x: jax.Array) -> jax.Array:
    return fe_canon(x)[..., 0] & 1


def fe_pow_const(a: jax.Array, exp_bits_msb_first: np.ndarray) -> jax.Array:
    """a^e for a fixed public exponent: MSB-first square-and-multiply under
    ``lax.scan`` (one fused program, ~2 muls/bit)."""

    def body(r, bit):
        r = fe_sq(r)
        r = jnp.where(bit > 0, fe_mul(r, a), r)
        return r, None

    one = jnp.zeros_like(a).at[..., 0].set(1)
    r, _ = jax.lax.scan(body, one, jnp.asarray(exp_bits_msb_first))
    return r


# ---------------------------------------------------------------------------
# points: extended coordinates as a pytree of [..., LIMBS]
# ---------------------------------------------------------------------------


class Point(NamedTuple):
    x: jax.Array
    y: jax.Array
    z: jax.Array
    t: jax.Array


def pt_identity(shape_prefix: Tuple[int, ...]) -> Point:
    zero = jnp.zeros(shape_prefix + (LIMBS,), jnp.int32)
    one = zero.at[..., 0].set(1)
    return Point(zero, one, one, zero)


def pt_add(p: Point, q: Point) -> Point:
    """Complete twisted-Edwards addition (same formula as the oracle's
    ``point_add``): total — valid for doubling and the identity, so the
    ladder needs no branches."""
    a = fe_mul(fe_sub(p.y, p.x), fe_sub(q.y, q.x))
    b = fe_mul(fe_add(p.y, p.x), fe_add(q.y, q.x))
    c = fe_mul(fe_mul(p.t, q.t), jnp.asarray(FE_2D))
    zz = fe_mul(p.z, q.z)
    d = fe_add(zz, zz)
    e, f, g, h = fe_sub(b, a), fe_sub(d, c), fe_add(d, c), fe_add(b, a)
    return Point(fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_dbl(p: Point) -> Point:
    """Dedicated extended-coordinate doubling (dbl-2008-hwcd, a = -1): 4
    squarings + 4 multiplications against the complete add's 9 muls.  Exact
    for every on-curve input including the identity — the result differs
    from ``pt_add(p, p)`` only by projective scale, which ``pt_eq`` absorbs.
    Used by the windowed ladder, where doublings dominate the scan."""
    a = fe_sq(p.x)
    b = fe_sq(p.y)
    zz = fe_sq(p.z)
    c = fe_add(zz, zz)
    g = fe_sub(b, a)                      # G = D + B with D = aA = -A
    f = fe_sub(g, c)
    h = fe_sub(fe_sub(jnp.zeros_like(a), a), b)
    e = fe_sub(fe_sub(fe_sq(fe_add(p.x, p.y)), a), b)
    return Point(fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_neg(p: Point) -> Point:
    zero = jnp.zeros_like(p.x)
    return Point(fe_sub(zero, p.x), p.y, p.z, fe_sub(zero, p.t))


def pt_select(points: List[Point], idx: jax.Array) -> Point:
    """4-way vectorized table lookup: idx in {0..3} per batch row."""
    stack = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *points)  # [4,B,L]
    sel = jax.nn.one_hot(idx, len(points), dtype=jnp.int32)  # [B,4]
    return jax.tree.map(
        lambda s: jnp.einsum("kbl,bk->bl", s, sel), stack
    )


def pt_select_stacked(stack: Point, idx: jax.Array) -> Point:
    """Row-major lookup against a pre-stacked [n, B, LIMBS] table: one
    one-hot contraction per coordinate (the windowed ladder's fused
    select; table size is read off the stack)."""
    sel = jax.nn.one_hot(idx, stack.x.shape[0], dtype=jnp.int32)  # [B, n]
    return jax.tree.map(lambda s: jnp.einsum("kbl,bk->bl", s, sel), stack)


def pt_eq(p: Point, q: Point) -> jax.Array:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    return fe_eq(fe_mul(p.x, q.z), fe_mul(q.x, p.z)) & fe_eq(
        fe_mul(p.y, q.z), fe_mul(q.y, p.z)
    )


def pt_decompress(y_limbs: jax.Array, sign: jax.Array) -> Tuple[Point, jax.Array]:
    """Batched point decompression; returns (point, valid mask).

    Same math as the oracle's ``point_decompress``: x = uv^3 (uv^7)^((p-5)/8)
    with u = y^2-1, v = d y^2+1, multiplying by sqrt(-1) when vx^2 == -u.
    Canonicity of y (y < p) is checked host-side on the raw bytes.
    """
    one = jnp.zeros_like(y_limbs).at[..., 0].set(1)
    y2 = fe_sq(y_limbs)
    u = fe_sub(y2, one)
    v = fe_add(fe_mul(y2, jnp.asarray(FE_D)), one)
    v3 = fe_mul(fe_sq(v), v)
    uv7 = fe_mul(fe_mul(fe_sq(v3), v), u)
    x = fe_mul(fe_mul(fe_pow_const(uv7, _POW_EXP_BITS), v3), u)
    vx2 = fe_mul(fe_sq(x), v)
    root_ok = fe_eq(vx2, u)
    neg_ok = fe_is_zero(fe_add(vx2, u))
    x = jnp.where(
        (~root_ok & neg_ok)[..., None], fe_mul(x, jnp.asarray(FE_SQRT_M1)), x
    )
    valid = root_ok | neg_ok
    x_is_zero = fe_is_zero(x)
    valid &= ~(x_is_zero & (sign > 0))  # -0 encoding is invalid
    zero = jnp.zeros_like(x)
    flip = fe_parity(x) != sign
    x = jnp.where(flip[..., None], fe_sub(zero, x), x)
    return Point(x, y_limbs, one, fe_mul(x, y_limbs)), valid


def straus_double_scalarmult(
    s_bits: jax.Array, k_bits: jax.Array, neg_a: Point
) -> Point:
    """R' = [s]B + [k](-A), one double + one table-add per bit (MSB first).

    The joint table {identity, B, -A, B-A} makes the add unconditional; the
    identity entry absorbs (0,0) bit pairs thanks to the complete formula.
    """
    b_shape = s_bits.shape[:-1]
    base = Point(
        jnp.broadcast_to(jnp.asarray(FE_BX), b_shape + (LIMBS,)),
        jnp.broadcast_to(jnp.asarray(FE_BY), b_shape + (LIMBS,)),
        jnp.zeros(b_shape + (LIMBS,), jnp.int32).at[..., 0].set(1),
        jnp.broadcast_to(jnp.asarray(FE_BT), b_shape + (LIMBS,)),
    )
    table = [pt_identity(b_shape), base, neg_a, pt_add(base, neg_a)]

    def body(q, bits):
        sb, kb = bits
        q = pt_add(q, q)
        q = pt_add(q, pt_select(table, sb + 2 * kb))
        return q, None

    # MSB-first over 256 bits: scan over the bit axis.
    sb = jnp.moveaxis(jnp.flip(s_bits, axis=-1), -1, 0)
    kb = jnp.moveaxis(jnp.flip(k_bits, axis=-1), -1, 0)
    q, _ = jax.lax.scan(body, pt_identity(b_shape), (sb, kb))
    return q


# ---------------------------------------------------------------------------
# batch-major (limb-major) mirror: [LIMBS, B] with the batch on the lane axis
# ---------------------------------------------------------------------------
#
# The row-major kernel above feeds the VPU ragged [B, 22] tensors: the limb
# axis (22) rides the 128-wide lane dimension at 17% occupancy and the batch
# rides sublanes.  The ``_bm`` mirror transposes the layout — limbs lead,
# batch trails — so every elementwise field op is [22, B] with the BATCH on
# the lane axis (full lanes for B >= 128), the limb convolution becomes an
# einsum contracting the leading [22, 22] axes over a lane-shaped operand,
# and ``fe_canon``'s borrow ripple scans the leading axis directly (no
# moveaxis).  Two more restructurings ride along (ISSUE 10):
#
# - the two point decompressions (A and R) share ONE fused [22, 2B]
#   ``(p-5)/8`` power ladder instead of running the 253-step scan twice;
# - the Straus table is stacked to [4, LIMBS, B] ONCE outside the 256-step
#   scan (the row-major form restacks the 4-entry table inside the body and
#   trusts loop-invariant code motion to hoist it).
#
# Same math, same exact integer arithmetic — verdict-identical to the
# row-major kernel (asserted over RFC 8032 vectors + a 256-signature random
# sweep in ``tests/test_ed25519.py``).  Select with ``verify_batch(...,
# batch_major=...)``; the default follows the measured-faster path per
# backend.


def _carry_once_bm(x: jax.Array) -> jax.Array:
    c = x >> BITS
    lo = x - (c << BITS)
    shifted = jnp.concatenate([jnp.zeros_like(c[:1]), c[:-1]], axis=0)
    out = lo + shifted
    return out.at[0].add(FOLD * c[-1])


def fe_norm_bm(x: jax.Array) -> jax.Array:
    x = _carry_once_bm(x)
    x = _carry_once_bm(x)
    return _carry_once_bm(x)


def fe_mul_bm(a: jax.Array, b: jax.Array) -> jax.Array:
    outer = a[:, None, :] * b[None, :, :]  # [22, 22, B], < 2^24 each
    conv = jnp.einsum("kij,ijb->kb", jnp.asarray(_ONE_HOT), outer)
    lo, hi = conv[:LIMBS], conv[LIMBS:]
    hi = jnp.concatenate(
        [hi, jnp.zeros((LIMBS - hi.shape[0],) + hi.shape[1:], hi.dtype)],
        axis=0,
    )
    return fe_norm_bm(lo + FOLD * fe_norm_bm(hi))


def fe_sq_bm(a: jax.Array) -> jax.Array:
    return fe_mul_bm(a, a)


def fe_add_bm(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry_once_bm(a + b)


def fe_sub_bm(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry_once_bm(a - b)


def _const_bm(limbs: np.ndarray) -> jax.Array:
    """Host limb vector [22] -> broadcastable [22, 1] device constant."""
    return jnp.asarray(limbs)[:, None]


def fe_canon_bm(x: jax.Array) -> jax.Array:
    x = fe_norm_bm(x)
    x = fe_norm_bm(x + _const_bm(_int_to_limbs(512 * _P_INT)))
    for _ in range(2):
        hi = x[21] >> 3
        x = x.at[21].add(-(hi << 3))
        x = x.at[0].add(19 * hi)
        x = _carry_once_bm(x)
        x = _carry_once_bm(x)

    def borrow_step(carry, xi_pi):
        xi, pi = xi_pi
        d = xi - pi + carry
        b = (d < 0).astype(jnp.int32)
        return -b, (d + (b << BITS))

    carry0 = jnp.zeros(x.shape[1:], jnp.int32)
    ps = jnp.broadcast_to(_const_bm(FE_P), x.shape)
    final_borrow, diffs = jax.lax.scan(borrow_step, carry0, (x, ps))
    geq = final_borrow == 0
    return jnp.where(geq[None], diffs, x)


def fe_is_zero_bm(x: jax.Array) -> jax.Array:
    return (fe_canon_bm(x) == 0).all(axis=0)


def fe_eq_bm(a: jax.Array, b: jax.Array) -> jax.Array:
    return fe_is_zero_bm(fe_sub_bm(a, b))


def fe_parity_bm(x: jax.Array) -> jax.Array:
    return fe_canon_bm(x)[0] & 1


def fe_pow_const_bm(a: jax.Array, exp_bits_msb_first: np.ndarray) -> jax.Array:
    def body(r, bit):
        r = fe_sq_bm(r)
        r = jnp.where(bit > 0, fe_mul_bm(r, a), r)
        return r, None

    one = jnp.zeros_like(a).at[0].set(1)
    r, _ = jax.lax.scan(body, one, jnp.asarray(exp_bits_msb_first))
    return r


def pt_identity_bm(batch: int) -> Point:
    zero = jnp.zeros((LIMBS, batch), jnp.int32)
    return Point(zero, zero.at[0].set(1), zero.at[0].set(1), zero)


def pt_add_bm(p: Point, q: Point) -> Point:
    a = fe_mul_bm(fe_sub_bm(p.y, p.x), fe_sub_bm(q.y, q.x))
    b = fe_mul_bm(fe_add_bm(p.y, p.x), fe_add_bm(q.y, q.x))
    c = fe_mul_bm(fe_mul_bm(p.t, q.t), _const_bm(FE_2D))
    zz = fe_mul_bm(p.z, q.z)
    d = fe_add_bm(zz, zz)
    e, f, g, h = (
        fe_sub_bm(b, a), fe_sub_bm(d, c), fe_add_bm(d, c), fe_add_bm(b, a)
    )
    return Point(
        fe_mul_bm(e, f), fe_mul_bm(g, h), fe_mul_bm(f, g), fe_mul_bm(e, h)
    )


def pt_dbl_bm(p: Point) -> Point:
    """Batch-major mirror of :func:`pt_dbl` (dbl-2008-hwcd, a = -1)."""
    a = fe_sq_bm(p.x)
    b = fe_sq_bm(p.y)
    zz = fe_sq_bm(p.z)
    c = fe_add_bm(zz, zz)
    g = fe_sub_bm(b, a)
    f = fe_sub_bm(g, c)
    h = fe_sub_bm(fe_sub_bm(jnp.zeros_like(a), a), b)
    e = fe_sub_bm(fe_sub_bm(fe_sq_bm(fe_add_bm(p.x, p.y)), a), b)
    return Point(
        fe_mul_bm(e, f), fe_mul_bm(g, h), fe_mul_bm(f, g), fe_mul_bm(e, h)
    )


def pt_neg_bm(p: Point) -> Point:
    zero = jnp.zeros_like(p.x)
    return Point(fe_sub_bm(zero, p.x), p.y, p.z, fe_sub_bm(zero, p.t))


def pt_select_stacked_bm(stack: Point, idx: jax.Array) -> Point:
    """Table lookup against a PRE-stacked [n, LIMBS, B] table: the stack is
    built once outside the ladder scan (the hoist), each step pays only the
    one-hot contraction.  n = 4 for the Straus joint table, 4^w for the
    windowed joint table — the size is read off the stack."""
    sel = jax.nn.one_hot(idx, stack.x.shape[0], dtype=jnp.int32)  # [B, n]
    return jax.tree.map(
        lambda s: jnp.einsum("klb,bk->lb", s, sel), stack
    )


def pt_eq_bm(p: Point, q: Point) -> jax.Array:
    return fe_eq_bm(fe_mul_bm(p.x, q.z), fe_mul_bm(q.x, p.z)) & fe_eq_bm(
        fe_mul_bm(p.y, q.z), fe_mul_bm(q.y, p.z)
    )


def pt_decompress_bm(
    y_limbs: jax.Array, sign: jax.Array
) -> Tuple[Point, jax.Array]:
    """Batch-major decompression: ``y_limbs`` [22, B'], ``sign`` [B'].  The
    verify kernel calls it ONCE on the concatenated A||R batch (B' = 2B), so
    the 253-step power ladder runs once instead of twice."""
    one = jnp.zeros_like(y_limbs).at[0].set(1)
    y2 = fe_sq_bm(y_limbs)
    u = fe_sub_bm(y2, one)
    v = fe_add_bm(fe_mul_bm(y2, _const_bm(FE_D)), one)
    v3 = fe_mul_bm(fe_sq_bm(v), v)
    uv7 = fe_mul_bm(fe_mul_bm(fe_sq_bm(v3), v), u)
    x = fe_mul_bm(fe_mul_bm(fe_pow_const_bm(uv7, _POW_EXP_BITS), v3), u)
    vx2 = fe_mul_bm(fe_sq_bm(x), v)
    root_ok = fe_eq_bm(vx2, u)
    neg_ok = fe_is_zero_bm(fe_add_bm(vx2, u))
    x = jnp.where(
        (~root_ok & neg_ok)[None], fe_mul_bm(x, _const_bm(FE_SQRT_M1)), x
    )
    valid = root_ok | neg_ok
    x_is_zero = fe_is_zero_bm(x)
    valid &= ~(x_is_zero & (sign > 0))
    zero = jnp.zeros_like(x)
    flip = fe_parity_bm(x) != sign
    x = jnp.where(flip[None], fe_sub_bm(zero, x), x)
    return Point(x, y_limbs, one, fe_mul_bm(x, y_limbs)), valid


def straus_double_scalarmult_bm(
    s_bits: jax.Array, k_bits: jax.Array, neg_a: Point
) -> Point:
    """Batch-major Straus ladder: bits stay [B, 256] (host layout), points
    are [LIMBS, B], and the 4-entry joint table is stacked once up front."""
    bsz = s_bits.shape[0]
    one = jnp.zeros((LIMBS, bsz), jnp.int32).at[0].set(1)
    base = Point(
        jnp.broadcast_to(_const_bm(FE_BX), (LIMBS, bsz)),
        jnp.broadcast_to(_const_bm(FE_BY), (LIMBS, bsz)),
        one,
        jnp.broadcast_to(_const_bm(FE_BT), (LIMBS, bsz)),
    )
    table = [pt_identity_bm(bsz), base, neg_a, pt_add_bm(base, neg_a)]
    tstack = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *table)

    def body(q, bits):
        sb, kb = bits
        q = pt_add_bm(q, q)
        q = pt_add_bm(q, pt_select_stacked_bm(tstack, sb + 2 * kb))
        return q, None

    sb = jnp.moveaxis(jnp.flip(s_bits, axis=-1), -1, 0)
    kb = jnp.moveaxis(jnp.flip(k_bits, axis=-1), -1, 0)
    q, _ = jax.lax.scan(body, pt_identity_bm(bsz), (sb, kb))
    return q


# ---------------------------------------------------------------------------
# windowed joint-table ladder (r17): w bits per step instead of 1
# ---------------------------------------------------------------------------
#
# The Straus scan above retires ONE bit of each scalar per step: 256 steps ×
# (1 double + 1 table add) = 512 serial point ops.  The windowed ladder
# retires w bits per step from a joint table T[j*2^w + i] = [i]B + [j](-A):
# ceil(256/w) steps × (w doublings + 1 fused table-select-add).  Serial
# additions drop 256 -> ceil(256/w) (4x at w=4) and doublings move to the
# dedicated 8-mul ``pt_dbl`` formula, so total serial point-op depth falls
# ~35-40%.  The precompute plane:
#
# - the [i]B side is a host-side constant comb (exact big-int arithmetic via
#   the Python oracle, cached per w) — zero device cost;
# - the [j](-A) side is the only serial device precompute: a chain of
#   2^w - 2 complete adds, batch-parallel;
# - the joint (i, j) grid is ONE broadcast complete-add over all 4^w pairs —
#   depth 1, but it is real work per table entry, which is why the best
#   window is backend-dependent: on CPU (FLOP-bound) the grid bill caps the
#   sweet spot at w=2; on TPU the grid vectorizes across lanes and w=4's
#   shorter scan should win (``default_window``).
#
# Scalars are public in verification, so a plain (unsigned, non-NAF) window
# decomposition is fine — no constant-time requirement, no data-dependent
# branches: every step is w doublings plus one one-hot select-add, and the
# identity entry at (0, 0) absorbs all-zero windows via the complete
# formula.  Same exact integer arithmetic as Straus — verdict-identical
# (asserted over RFC 8032 vectors, the corruption oracle, and a random
# batch in ``tests/test_ed25519.py``).


@functools.lru_cache(maxsize=None)
def _base_window_consts(w: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host comb for the fixed base: affine [i]B for i in [0, 2^w) as
    (x, y, t) limb arrays of shape [2^w, LIMBS] (z = 1 everywhere; the
    identity lands at i = 0 as (0, 1, 0)).  Exact big-int arithmetic via
    the Python oracle; cached per window size, so the cost is paid once
    per process, not per batch."""
    from ..crypto import ed25519_ref as _ref

    xs, ys, ts = [], [], []
    for i in range(1 << w):
        gx, gy, gz, _ = _ref.point_mul(i, _ref.BASE)
        zinv = pow(gz, _P_INT - 2, _P_INT)
        ax, ay = gx * zinv % _P_INT, gy * zinv % _P_INT
        xs.append(_int_to_limbs(ax))
        ys.append(_int_to_limbs(ay))
        ts.append(_int_to_limbs(ax * ay % _P_INT))
    return np.stack(xs), np.stack(ys), np.stack(ts)


def _scalar_windows(bits: jax.Array, w: int) -> jax.Array:
    """[..., 256] little-endian bits -> [..., ceil(256/w)] w-bit window
    values (little-endian window order; zero-padded above bit 255 when
    w does not divide 256)."""
    nbits = bits.shape[-1]
    nw = -(-nbits // w)
    pad = nw * w - nbits
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bits.dtype)], axis=-1
        )
    weights = jnp.asarray([1 << i for i in range(w)], jnp.int32)
    return jnp.einsum(
        "...nw,w->...n", bits.reshape(bits.shape[:-1] + (nw, w)), weights
    )


def _joint_table(neg_a: Point, window: int) -> Point:
    """Row-major joint table: stacked [4^w, B, LIMBS] with
    T[j*2^w + i] = [i]B + [j](-A)."""
    n = 1 << window
    b_shape = neg_a.x.shape[:-1]
    chain = [pt_identity(b_shape), neg_a]
    for _ in range(n - 2):
        chain.append(pt_add(chain[-1], neg_a))
    a_stack = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *chain)
    bx, by, bt = _base_window_consts(window)
    b_pt = Point(
        jnp.asarray(bx),
        jnp.asarray(by),
        jnp.zeros((n, LIMBS), jnp.int32).at[:, 0].set(1),
        jnp.asarray(bt),
    )
    extra = (1,) * len(b_shape)
    a_e = jax.tree.map(lambda v: v[:, None], a_stack)  # [2^w(j), 1(i), B, L]
    b_e = jax.tree.map(
        lambda v: v.reshape((1, n) + extra + (LIMBS,)), b_pt
    )
    grid = pt_add(a_e, b_e)  # one broadcast add over the whole (j, i) grid
    return jax.tree.map(
        lambda v: v.reshape((n * n,) + b_shape + (LIMBS,)), grid
    )


def _joint_table_bm(neg_a: Point, window: int) -> Point:
    """Batch-major joint table: stacked [4^w, LIMBS, B], same indexing as
    :func:`_joint_table`.  The (j, i) grid is flattened into the batch axis
    so the one broadcast add stays in the native [LIMBS, B'] layout."""
    n = 1 << window
    bsz = neg_a.x.shape[1]
    chain = [pt_identity_bm(bsz), neg_a]
    for _ in range(n - 2):
        chain.append(pt_add_bm(chain[-1], neg_a))
    a_stack = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *chain)
    bx, by, bt = _base_window_consts(window)
    ones = np.zeros((n, LIMBS), np.int32)
    ones[:, 0] = 1
    a_flat = jax.tree.map(
        lambda v: jnp.broadcast_to(
            v[:, None], (n, n, LIMBS, bsz)
        ).transpose(2, 0, 1, 3).reshape(LIMBS, n * n * bsz),
        a_stack,
    )
    b_flat = Point(*[
        jnp.broadcast_to(
            jnp.asarray(arr.T)[:, None, :, None], (LIMBS, n, n, bsz)
        ).reshape(LIMBS, n * n * bsz)
        for arr in (bx, by, ones, bt)
    ])
    grid = pt_add_bm(a_flat, b_flat)
    return jax.tree.map(
        lambda v: v.reshape(LIMBS, n * n, bsz).transpose(1, 0, 2), grid
    )


def windowed_double_scalarmult(
    s_bits: jax.Array, k_bits: jax.Array, neg_a: Point, window: int = 4
) -> Point:
    """R' = [s]B + [k](-A) via the w-bit joint table: ceil(256/w) steps of
    w dedicated doublings + 1 fused table-select-add (MSB-first windows)."""
    w = window
    table = _joint_table(neg_a, w)
    sw = jnp.moveaxis(jnp.flip(_scalar_windows(s_bits, w), axis=-1), -1, 0)
    kw = jnp.moveaxis(jnp.flip(_scalar_windows(k_bits, w), axis=-1), -1, 0)

    def body(q, wins):
        swi, kwi = wins
        for _ in range(w):
            q = pt_dbl(q)
        q = pt_add(q, pt_select_stacked(table, swi + (kwi << w)))
        return q, None

    q, _ = jax.lax.scan(body, pt_identity(s_bits.shape[:-1]), (sw, kw))
    return q


def windowed_double_scalarmult_bm(
    s_bits: jax.Array, k_bits: jax.Array, neg_a: Point, window: int = 4
) -> Point:
    """Batch-major windowed ladder: bits stay [B, 256] (host layout),
    points are [LIMBS, B], the 4^w joint table is stacked once up front."""
    w = window
    table = _joint_table_bm(neg_a, w)
    sw = jnp.moveaxis(jnp.flip(_scalar_windows(s_bits, w), axis=-1), -1, 0)
    kw = jnp.moveaxis(jnp.flip(_scalar_windows(k_bits, w), axis=-1), -1, 0)

    def body(q, wins):
        swi, kwi = wins
        for _ in range(w):
            q = pt_dbl_bm(q)
        q = pt_add_bm(q, pt_select_stacked_bm(table, swi + (kwi << w)))
        return q, None

    q, _ = jax.lax.scan(body, pt_identity_bm(s_bits.shape[0]), (sw, kw))
    return q


# ---------------------------------------------------------------------------
# the jitted batch kernel
# ---------------------------------------------------------------------------


@jax.jit
def _verify_kernel(
    a_y: jax.Array,      # i32[B, LIMBS] pubkey y limbs
    a_sign: jax.Array,   # i32[B] pubkey x sign bit
    r_y: jax.Array,      # i32[B, LIMBS] signature R y limbs
    r_sign: jax.Array,   # i32[B]
    s_bits: jax.Array,   # i32[B, 256] little-endian bits of S
    k_bits: jax.Array,   # i32[B, 256] little-endian bits of k = H(R||A||M) mod L
) -> jax.Array:
    a_pt, a_ok = pt_decompress(a_y, a_sign)
    r_pt, r_ok = pt_decompress(r_y, r_sign)
    r_prime = straus_double_scalarmult(s_bits, k_bits, pt_neg(a_pt))
    return a_ok & r_ok & pt_eq(r_prime, r_pt)


@jax.jit
def _verify_kernel_bm(
    a_y: jax.Array,      # i32[B, LIMBS] (host layout; transposed on entry)
    a_sign: jax.Array,   # i32[B]
    r_y: jax.Array,      # i32[B, LIMBS]
    r_sign: jax.Array,   # i32[B]
    s_bits: jax.Array,   # i32[B, 256]
    k_bits: jax.Array,   # i32[B, 256]
) -> jax.Array:
    """Batch-major verify: same inputs and verdicts as ``_verify_kernel``.

    One transpose at entry puts the batch on the lane axis; A and R then
    share a single fused [22, 2B] decompression (one 253-step power ladder
    instead of two) before the hoisted-table Straus ladder.
    """
    bsz = a_y.shape[0]
    ys = jnp.concatenate([a_y.T, r_y.T], axis=1)        # [22, 2B]
    signs = jnp.concatenate([a_sign, r_sign], axis=0)   # [2B]
    pt, valid = pt_decompress_bm(ys, signs)
    a_pt = jax.tree.map(lambda v: v[:, :bsz], pt)
    r_pt = jax.tree.map(lambda v: v[:, bsz:], pt)
    a_ok, r_ok = valid[:bsz], valid[bsz:]
    r_prime = straus_double_scalarmult_bm(s_bits, k_bits, pt_neg_bm(a_pt))
    return a_ok & r_ok & pt_eq_bm(r_prime, r_pt)


@functools.partial(jax.jit, static_argnames=("window",))
def _verify_kernel_windowed(
    a_y: jax.Array,
    a_sign: jax.Array,
    r_y: jax.Array,
    r_sign: jax.Array,
    s_bits: jax.Array,
    k_bits: jax.Array,
    window: int = 4,
) -> jax.Array:
    """Row-major verify through the windowed joint-table ladder; same
    inputs and verdicts as ``_verify_kernel``."""
    a_pt, a_ok = pt_decompress(a_y, a_sign)
    r_pt, r_ok = pt_decompress(r_y, r_sign)
    r_prime = windowed_double_scalarmult(s_bits, k_bits, pt_neg(a_pt), window)
    return a_ok & r_ok & pt_eq(r_prime, r_pt)


@functools.partial(jax.jit, static_argnames=("window",))
def _verify_kernel_windowed_bm(
    a_y: jax.Array,
    a_sign: jax.Array,
    r_y: jax.Array,
    r_sign: jax.Array,
    s_bits: jax.Array,
    k_bits: jax.Array,
    window: int = 4,
) -> jax.Array:
    """Batch-major verify through the windowed ladder: fused A||R
    decompression (as ``_verify_kernel_bm``) + the 4^w joint table."""
    bsz = a_y.shape[0]
    ys = jnp.concatenate([a_y.T, r_y.T], axis=1)        # [22, 2B]
    signs = jnp.concatenate([a_sign, r_sign], axis=0)   # [2B]
    pt, valid = pt_decompress_bm(ys, signs)
    a_pt = jax.tree.map(lambda v: v[:, :bsz], pt)
    r_pt = jax.tree.map(lambda v: v[:, bsz:], pt)
    a_ok, r_ok = valid[:bsz], valid[bsz:]
    r_prime = windowed_double_scalarmult_bm(
        s_bits, k_bits, pt_neg_bm(a_pt), window
    )
    return a_ok & r_ok & pt_eq_bm(r_prime, r_pt)


# ---------------------------------------------------------------------------
# host wrapper
# ---------------------------------------------------------------------------


def _bytes_to_bits256(rows: np.ndarray) -> np.ndarray:
    """[B,32] uint8 -> [B,256] int32, little-endian bit order."""
    return np.unpackbits(rows, axis=-1, bitorder="little").astype(np.int32)


def _enc_to_limbs_and_sign(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[B,32] uint8 point encodings -> (y limbs [B,22], sign [B])."""
    bits = np.unpackbits(rows, axis=-1, bitorder="little")  # [B,256]
    sign = bits[:, 255].astype(np.int32)
    y_bits = bits[:, :255].astype(np.int64)
    weights = 1 << np.arange(BITS, dtype=np.int64)
    limbs = np.zeros((rows.shape[0], LIMBS), np.int64)
    for l in range(LIMBS):
        seg = y_bits[:, l * BITS : min((l + 1) * BITS, 255)]
        limbs[:, l] = seg @ weights[: seg.shape[1]]
    return limbs.astype(np.int32), sign


def default_batch_major() -> bool:
    """Backend default for the kernel layout: the limb-major [22, B] form
    targets the TPU's 128-lane axis, and the fused single decompression
    ladder (one 253-step scan instead of two) also measures ~20-25% faster
    on the CPU fallback — batch-major is the default on every backend."""
    return True


def default_ladder() -> str:
    """Backend default for the double-scalarmult ladder (r17, measured —
    see PERF.md): the windowed joint-table ladder replaces the 1-bit
    Straus scan on every backend.  On the CPU fallback it measures well
    past the 10% bar at batch 512 (fewer serial adds AND fewer total
    muls once doublings use the dedicated 8-mul formula); on TPU the
    serial-depth cut is the point and the 4^w-entry grid precompute
    vectorizes across the lane axis."""
    return "windowed"


def default_window() -> int:
    """Measured per-backend window size for ``ladder="windowed"`` (see the
    ``ed25519_window_sweep`` bench row).  On CPU the joint-grid precompute
    is FLOP-bound — 4^w complete adds of real work — which caps the sweet
    spot at w=2 (measured best-of-20 at batch 64 AND 512: w2 −24/−27%
    wall vs Straus, w3 a wash, w4 a loss); accelerators build the grid at
    depth ~1 across lanes, so the shorter 64-step scan of w=4 should win
    there — a stated TPU bet, re-decided by the first on-chip sweep."""
    return 2 if jax.default_backend() == "cpu" else 4


def verify_batch(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    pad_to: int | None = None,
    batch_major: bool | None = None,
    ladder: str | None = None,
    window: int | None = None,
) -> np.ndarray:
    """Device-batched verify of n (pk, msg, sig) triples -> bool[n].

    Hashing + canonicity pre-checks (S < L, y < p — byte-level, branchy)
    run on host; decompression, the ladder, and the projective compare run
    in one jitted device program.  ``pad_to`` rounds the batch up
    (power-of-two padding avoids one recompile per batch size).
    ``batch_major`` selects the limb-major [22, B] kernel (verdict-identical
    to the row-major one); ``None`` takes :func:`default_batch_major`.
    ``ladder`` selects the scan: ``"straus"`` (1-bit joint table) or
    ``"windowed"`` (w-bit joint table, ``window`` bits per step, w = None
    -> :func:`default_window`); ``None`` takes :func:`default_ladder`.
    All four kernel variants are verdict-identical.
    """
    n = len(pks)
    if not (n == len(msgs) == len(sigs)):
        raise ValueError("pks/msgs/sigs length mismatch")
    if n == 0:
        return np.zeros(0, bool)

    pk_rows = np.frombuffer(b"".join(pks), np.uint8).reshape(n, 32)
    sig_rows = np.frombuffer(b"".join(sigs), np.uint8).reshape(n, 64)
    r_rows, s_rows = sig_rows[:, :32], sig_rows[:, 32:]

    # Host-side canonicity: S < L, y_A < p, y_R < p (cheap big-int checks).
    host_ok = np.ones(n, bool)
    for i in range(n):
        s_int = int.from_bytes(s_rows[i].tobytes(), "little")
        y_a = int.from_bytes(pk_rows[i].tobytes(), "little") & ((1 << 255) - 1)
        y_r = int.from_bytes(r_rows[i].tobytes(), "little") & ((1 << 255) - 1)
        host_ok[i] = (s_int < _L_INT) and (y_a < _P_INT) and (y_r < _P_INT)

    # k = SHA512(R || A || M) mod L, host-hashed.
    k_rows = np.zeros((n, 32), np.uint8)
    for i in range(n):
        d = hashlib.sha512(
            r_rows[i].tobytes() + pk_rows[i].tobytes() + msgs[i]
        ).digest()
        k = int.from_bytes(d, "little") % _L_INT
        k_rows[i] = np.frombuffer(k.to_bytes(32, "little"), np.uint8)

    b = pad_to or max(1, 1 << (n - 1).bit_length())
    if b < n:
        raise ValueError(f"pad_to ({b}) smaller than batch ({n})")

    def pad(a):
        return np.pad(a, ((0, b - n),) + ((0, 0),) * (a.ndim - 1))

    a_y, a_sign = _enc_to_limbs_and_sign(pk_rows)
    r_y, r_sign = _enc_to_limbs_and_sign(r_rows)
    if batch_major is None:
        batch_major = default_batch_major()
    if ladder is None:
        ladder = default_ladder()
    if ladder not in ("straus", "windowed"):
        raise ValueError(f"unknown ladder {ladder!r}")
    if window is not None and ladder != "windowed":
        raise ValueError("window only applies to ladder='windowed'")
    args = (
        jnp.asarray(pad(a_y)),
        jnp.asarray(pad(a_sign)),
        jnp.asarray(pad(r_y)),
        jnp.asarray(pad(r_sign)),
        jnp.asarray(pad(_bytes_to_bits256(s_rows))),
        jnp.asarray(pad(_bytes_to_bits256(k_rows))),
    )
    if ladder == "windowed":
        w = default_window() if window is None else window
        if not 1 <= w <= 6:
            raise ValueError(f"window {w} outside the practical range [1, 6]")
        kernel = (
            _verify_kernel_windowed_bm if batch_major else
            _verify_kernel_windowed
        )
        ok = kernel(*args, window=w)
    else:
        kernel = _verify_kernel_bm if batch_major else _verify_kernel
        ok = kernel(*args)
    return np.asarray(jax.device_get(ok))[:n] & host_ok
