"""``shard_map`` across jax versions.

The explicit-collective kernels (``pallas_gossip``'s shard_map wrappers, the
ring-gather fast path in ``gossip_packed``) need ``shard_map`` with replication
checking off — the kernels use ``axis_index``/``ppermute`` in ways the checker
rejects.  The API moved twice: modern jax exports ``jax.shard_map`` taking
``check_vma=``; 0.4.x has ``jax.experimental.shard_map.shard_map`` taking
``check_rep=``.  This shim resolves whichever exists at call time so the same
kernel source runs on both.
"""

from __future__ import annotations


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``shard_map(f)`` with replication checking disabled, on whichever
    shard_map API this jax build ships."""
    try:
        from jax import shard_map as sm

        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as sm

        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
