"""Bit-packed GossipSub hot-loop kernels — the 100k-peer scale path.

Same protocol semantics as ``ops/gossip.py`` (the bool-tensor reference
implementation, equivalence-tested in ``tests/test_gossip_packed.py``), with
the message window packed into uint32 words (``ops/bitpack.py``):

- ``propagate_packed`` — one eager-push round.  The [N, K, W] word cube is
  32x smaller than the reference cube; set ops are bitwise AND/OR/NOT,
  delivery counting is ``lax.population_count``, and first-delivering-slot
  attribution is an exclusive cumulative-OR over the slot axis
  (Hillis–Steele, log2 K steps — no serial scan).
- ``gossip_transfer_packed`` — heartbeat IHAVE/IWANT.  Reformulated from the
  reference's scatter-add into a **reverse-index gather**: a gossip target is
  always a slot-paired neighbor, so "peers push to chosen targets" is
  equivalently "each peer pulls from neighbors whose choice points back at
  it" via ``chosen[nbrs[t,s], rev[t,s]]``.  Gathers partition cleanly under
  GSPMD (scatters serialize); this is what lets the sharded 100k-peer sim
  ride ICI collectives.

The fused-downstream compute (everything after the XLA row gather) also has a
Pallas TPU kernel form in ``ops/pallas_gossip.py``; these jnp versions are
the portable reference the kernel is tested against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import GossipSubParams
from .graphs import top_mask

FULL = jnp.uint32(0xFFFFFFFF)


def _as_mask(b: jax.Array) -> jax.Array:
    """bool[...] -> uint32[...] word mask (all-ones / all-zeros)."""
    return jnp.where(b, FULL, jnp.uint32(0))


def exclusive_or_scan(x: jax.Array, axis: int) -> jax.Array:
    """Exclusive cumulative bitwise-OR along ``axis`` (log-step prefix)."""
    k = x.shape[axis]
    # Shift right by one: before[s] covers strictly-lower slots.
    zero = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, 1, axis=axis))
    p = jnp.concatenate(
        [zero, jax.lax.slice_in_dim(x, 0, k - 1, axis=axis)], axis=axis
    )
    sh = 1
    while sh < k:
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, min(sh, k), axis=axis))
        shifted = jnp.concatenate(
            [zeros, jax.lax.slice_in_dim(p, 0, k - sh, axis=axis)], axis=axis
        )
        p = p | shifted
        sh *= 2
    return p


class PropagatePackedOut(NamedTuple):
    have_w: jax.Array       # u32[N, W]
    fresh_w: jax.Array      # u32[N, W]
    new_w: jax.Array        # u32[N, W] first receipts this round (pre-validation)
    fmd_inc: jax.Array      # f32[N, K]
    mmd_inc: jax.Array      # f32[N, K]
    invalid_inc: jax.Array  # f32[N, K]


def propagate_packed(
    mesh: jax.Array,       # bool[N, K]
    nbrs: jax.Array,       # i32[N, K]
    edge_live: jax.Array,  # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,      # bool[N]
    have_w: jax.Array,     # u32[N, W]
    fresh_w: jax.Array,    # u32[N, W]
    valid_w: jax.Array,    # u32[W]  packed (msg_valid & msg_active)
) -> PropagatePackedOut:
    """One eager-push round over packed windows.

    Mirrors ``gossip.propagate`` exactly (see its docstring for the protocol
    rules); ``first_step`` stamping stays with the caller, which knows the
    step counter and holds the unpacked i32 lattice.
    """
    n = nbrs.shape[0]

    j = jnp.clip(nbrs, 0, n - 1)
    edge_ok = mesh & edge_live                                     # bool[N, K]
    inc = _as_mask(edge_ok)[:, :, None] & fresh_w[j]               # u32[N, K, W]

    before = exclusive_or_scan(inc, axis=1)
    first_sender = inc & ~before

    arrived = jax.lax.reduce(
        inc, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )                                                              # u32[N, W]
    new_w = arrived & ~have_w & _as_mask(alive)[:, None]
    newly = first_sender & new_w[:, None, :]

    pc = lambda x: jax.lax.population_count(x).sum(axis=-1).astype(jnp.float32)
    fmd_inc = pc(newly & valid_w)
    invalid_inc = pc(newly & ~valid_w)
    mmd_inc = pc(inc & valid_w)

    return PropagatePackedOut(
        have_w=have_w | (new_w & valid_w),
        fresh_w=new_w & valid_w,
        new_w=new_w,
        fmd_inc=fmd_inc,
        mmd_inc=mmd_inc,
        invalid_inc=invalid_inc,
    )


def gossip_transfer_packed(
    key: jax.Array,
    have_w: jax.Array,     # u32[N, W]
    mesh: jax.Array,       # bool[N, K]
    nbrs: jax.Array,       # i32[N, K]
    rev: jax.Array,        # i32[N, K]
    edge_live: jax.Array,  # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,      # bool[N]
    scores: jax.Array,     # f32[N, K]
    valid_w: jax.Array,    # u32[W]
    p: GossipSubParams,
    gossip_threshold: float,
) -> jax.Array:
    """Heartbeat IHAVE/IWANT over packed windows -> pending u32[N, W].

    Choice rule is identical to ``gossip.gossip_transfer``: each live peer
    advertises to ``d_lazy`` random non-mesh, live, above-threshold neighbor
    slots.  Delivery is computed target-side by the reverse-index gather
    described in the module docstring.
    """
    n, k = nbrs.shape
    d_lazy = min(p.d_lazy, k)
    if d_lazy <= 0:
        return jnp.zeros_like(have_w)
    eligible = (
        edge_live & ~mesh & alive[:, None] & (scores >= gossip_threshold)
    )
    r = jax.random.uniform(key, (n, k))
    chosen = top_mask(jnp.where(eligible, r, -jnp.inf), d_lazy)

    # Target side: neighbor j = nbrs[t, s] chose me iff chosen[j, rev[t, s]].
    jidx = jnp.clip(nbrs, 0, n - 1)
    ridx = jnp.clip(rev, 0, k - 1)
    towards_me = chosen[jidx, ridx] & edge_live                    # bool[N, K]
    offered = _as_mask(towards_me)[:, :, None] & have_w[jidx]      # u32[N, K, W]
    offered = jax.lax.reduce(
        offered, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )
    return offered & ~have_w & valid_w & _as_mask(alive)[:, None]
