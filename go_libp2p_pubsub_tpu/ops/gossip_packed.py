"""Bit-packed GossipSub hot-loop kernels — the 100k-peer scale path.

Same protocol semantics as ``ops/gossip.py`` (the bool-tensor reference
implementation, equivalence-tested in ``tests/test_gossip_packed.py``), with
the message window packed into uint32 words (``ops/bitpack.py``):

- ``propagate_packed`` — one eager-push round.  The [N, K, W] word cube is
  32x smaller than the reference cube; set ops are bitwise AND/OR/NOT,
  delivery counting is ``lax.population_count``, and first-delivering-slot
  attribution is an exclusive cumulative-OR over the slot axis
  (Hillis–Steele, log2 K steps — no serial scan).
- ``ihave_advertise_packed`` / ``iwant_select_packed`` — the two-phase
  heartbeat IHAVE/IWANT.  Reformulated from a scatter-add into a
  **reverse-index gather**: a gossip target is always a slot-paired
  neighbor, so "peers push to chosen targets" is equivalently "each peer
  pulls from neighbors whose choice points back at it" via
  ``chosen[nbrs[t,s], rev[t,s]]``.  Gathers partition cleanly under GSPMD
  (scatters serialize); this is what lets the sharded 100k-peer sim ride
  ICI collectives.

The fused-downstream compute (everything after the XLA row gather) also has a
Pallas TPU kernel form in ``ops/pallas_gossip.py``; these jnp versions are
the portable reference the kernel is tested against.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..config import GossipSubParams
from . import bitpack
from .gossip import gossip_emission_mask, iwant_priority
from .graphs import top_mask

FULL = jnp.uint32(0xFFFFFFFF)


def ring_gather_rows(
    table: jax.Array,       # [N, ...] row-sharded source
    idx: jax.Array,         # i32[N, K] row indices into ``table``
    device_mesh,            # jax.sharding.Mesh with axis ``axis``
    axis: str = "peers",
) -> jax.Array:
    """``table[idx]`` as shard-local indexing + a double-buffered ppermute
    ring — the sharded rollout's split-gather fast path.

    The monolithic GSPMD lowering of ``table[idx]`` all-gathers the full
    table to every device before indexing: O(n_devices) memory traffic per
    device regardless of how few rows actually cross shards.  Here round r
    has device d hold the block owned by shard (d + r) and resolve exactly
    the indices that land in it:

    - round 0 is the INTRA-shard half — pure local indexing, no
      communication at all.  A locality-aware placement
      (``parallel/placement``) makes this round resolve most rows.
    - rounds 1..n_sh-1 are the CROSS-shard half.  The next block is pushed
      into flight (``ppermute``) BEFORE the current block's gather runs, so
      each round's interconnect transfer overlaps the previous round's
      local compute — double buffering, never more than one extra block
      resident.

    Requires N % n_shards == 0 (the peer-dim sharding's own precondition).
    Bit-identical to ``table[idx]`` for in-range indices; out-of-range
    clipped like the callers' ``jnp.clip`` convention.
    """
    from .shard_compat import shard_map_compat

    P = jax.sharding.PartitionSpec
    n_sh = device_mesh.shape[axis]
    n = table.shape[0]
    if n % n_sh != 0:
        raise ValueError(f"rows ({n}) must divide device count ({n_sh})")
    blk = n // n_sh
    pairs = [((d + 1) % n_sh, d) for d in range(n_sh)]

    def local(table_l, idx_l):
        d = jax.lax.axis_index(axis)
        out = jnp.zeros(idx_l.shape + table_l.shape[1:], table_l.dtype)
        buf = table_l
        for r in range(n_sh):
            if r + 1 < n_sh:  # push next block into flight first
                nxt = jax.lax.ppermute(buf, axis, pairs)
            owner = (d + r) % n_sh
            loc = idx_l - owner * blk
            hit = (loc >= 0) & (loc < blk)
            rows = buf[jnp.clip(loc, 0, blk - 1)]
            shape_up = hit.reshape(hit.shape + (1,) * (rows.ndim - hit.ndim))
            out = jnp.where(shape_up, rows, out)
            if r + 1 < n_sh:
                buf = nxt
        return out

    row = P(axis)
    f = shard_map_compat(
        local, device_mesh, in_specs=(row, row), out_specs=row
    )
    return f(table, idx)


def _as_mask(b: jax.Array) -> jax.Array:
    """bool[...] -> uint32[...] word mask (all-ones / all-zeros)."""
    return jnp.where(b, FULL, jnp.uint32(0))


def _gather_packed_bits(
    plane: jax.Array, jidx: jax.Array, ridx: jax.Array
) -> jax.Array:
    """``plane[jidx, ridx]`` for a bool[N, K] plane, gathered bit-packed:
    pack along the slot axis (u32[N, ceil(K/32)]), gather one word per
    edge, extract the bit.  Same element count as the bool gather but an
    8x smaller table (and one word per peer when K <= 32) — the packed
    path's word-plane discipline for bool planes crossing a gather."""
    words = bitpack.pack(plane)                      # u32[N, ceil(K/32)]
    w = words[jidx, ridx // 32]
    return ((w >> (ridx % 32).astype(jnp.uint32)) & 1) > 0


def exclusive_or_scan(x: jax.Array, axis: int) -> jax.Array:
    """Exclusive cumulative bitwise-OR along ``axis`` (log-step prefix)."""
    k = x.shape[axis]
    # Shift right by one: before[s] covers strictly-lower slots.
    zero = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, 1, axis=axis))
    p = jnp.concatenate(
        [zero, jax.lax.slice_in_dim(x, 0, k - 1, axis=axis)], axis=axis
    )
    sh = 1
    while sh < k:
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, min(sh, k), axis=axis))
        shifted = jnp.concatenate(
            [zeros, jax.lax.slice_in_dim(p, 0, k - sh, axis=axis)], axis=axis
        )
        p = p | shifted
        sh *= 2
    return p


class PropagatePackedOut(NamedTuple):
    have_w: jax.Array       # u32[N, W]
    fresh_w: jax.Array      # u32[N, W]
    new_w: jax.Array        # u32[N, W] first receipts this round (pre-validation)
    fmd_inc: jax.Array      # f32[N, K]
    mmd_inc: jax.Array      # f32[N, K]
    invalid_inc: jax.Array  # f32[N, K]


def propagate_packed(
    mesh: jax.Array,       # bool[N, K]
    nbrs: jax.Array,       # i32[N, K]
    edge_live: jax.Array,  # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,      # bool[N]
    have_w: jax.Array,     # u32[N, W]
    fresh_w: jax.Array,    # u32[N, W]
    valid_w: jax.Array,    # u32[W]  packed (msg_valid & msg_active)
    fresh_src=None,        # u32[N, K, W] pre-gathered per-edge sender planes
                           # (per-edge delay mode); None -> fresh_w[nbrs]
    idontwant: bool = False,  # v1.2 duplicate suppression (see gossip.propagate)
    idw_have_w=None,       # u32[N, W] pre-fold possession snapshot the
                           # IDONTWANT notifications reflect; defaults to
                           # have_w (see gossip.propagate's idw_have)
    device_mesh=None,      # split-gather fast path: resolve the fresh-plane
                           # row gather via ring_gather_rows on this mesh
    axis: str = "peers",
) -> PropagatePackedOut:
    """One eager-push round over packed windows.

    Mirrors ``gossip.propagate`` exactly (see its docstring for the protocol
    rules); ``first_step`` stamping stays with the caller, which knows the
    step counter and holds the unpacked i32 lattice.
    """
    n = nbrs.shape[0]

    j = jnp.clip(nbrs, 0, n - 1)
    edge_ok = mesh & edge_live                                     # bool[N, K]
    if fresh_src is not None:
        src = fresh_src
    elif device_mesh is not None:
        src = ring_gather_rows(fresh_w, j, device_mesh, axis)
    else:
        src = fresh_w[j]
    inc = _as_mask(edge_ok)[:, :, None] & src                      # u32[N, K, W]

    before = exclusive_or_scan(inc, axis=1)
    first_sender = inc & ~before

    arrived = jax.lax.reduce(
        inc, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )                                                              # u32[N, W]
    new_w = arrived & ~have_w & _as_mask(alive)[:, None]
    newly = first_sender & new_w[:, None, :]

    pc = lambda x: jax.lax.population_count(x).sum(axis=-1).astype(jnp.float32)
    fmd_inc = pc(newly & valid_w)
    invalid_inc = pc(newly & ~valid_w)
    idw = have_w if idw_have_w is None else idw_have_w
    counted = inc if not idontwant else (inc & ~idw[:, None, :])
    mmd_inc = pc(counted & valid_w)

    return PropagatePackedOut(
        have_w=have_w | (new_w & valid_w),
        fresh_w=new_w & valid_w,
        new_w=new_w,
        fmd_inc=fmd_inc,
        mmd_inc=mmd_inc,
        invalid_inc=invalid_inc,
    )


def cap_ihave_packed(adv_w: jax.Array, max_len: int) -> jax.Array:
    """Word-granular ``max_ihave_length`` cap over packed advertisements
    (u32[..., W]): keep whole words while the cumulative popcount fits.
    Bit-identical to ``gossip.cap_ihave`` on the unpacked form."""
    counts = jax.lax.population_count(adv_w).astype(jnp.int32)
    cum = jnp.cumsum(counts, axis=-1)
    return adv_w & _as_mask(cum <= max_len)


def ihave_advertise_packed(
    key: jax.Array,
    have_w: jax.Array,     # u32[N, W]
    mesh: jax.Array,       # bool[N, K]
    nbrs: jax.Array,       # i32[N, K]
    rev: jax.Array,        # i32[N, K]
    edge_live: jax.Array,  # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,      # bool[N]
    scores: jax.Array,     # f32[N, K]
    gossip_w: jax.Array,   # u32[W] packed advertisable window (valid & recent)
    p: GossipSubParams,
    gossip_threshold: float,
    uid: Optional[jax.Array] = None,
) -> jax.Array:
    """Heartbeat IHAVE phase over packed windows -> adv u32[N, K, W]:
    ``adv[i, s]`` is what neighbor slot s advertised TO peer i.

    Choice rule is identical to ``gossip.ihave_advertise`` (adaptive
    ``gossip_factor`` emission, ``history_gossip`` window via ``gossip_w``,
    ``max_ihave_length`` cap).  The IWANT request and the transfer are the
    caller's next two propagate rounds — the wire protocol's two hops.
    """
    n, k = nbrs.shape
    d_lazy = min(p.d_lazy, k)
    if d_lazy <= 0:
        return jnp.zeros(
            (n, k, have_w.shape[1]), jnp.uint32
        )
    chosen = gossip_emission_mask(
        key, mesh, edge_live, alive, scores, p, gossip_threshold, uid
    )
    # Target side: neighbor j = nbrs[t, s] chose me iff chosen[j, rev[t, s]].
    # The chooser plane crosses the gather BIT-PACKED along the slot axis
    # (u32[N, ceil(K/32)] instead of bool[N, K] — the ring path's idiom,
    # r10): the gathered table is 8x smaller and, for K <= 32, the slot
    # lookup folds into a shift off a single word per edge.  Bit-exact.
    jidx = jnp.clip(nbrs, 0, n - 1)
    ridx = jnp.clip(rev, 0, k - 1)
    towards_me = _gather_packed_bits(chosen, jidx, ridx) & edge_live
    adv = _as_mask(towards_me)[:, :, None] & (have_w & gossip_w[None, :])[jidx]
    return cap_ihave_packed(adv, p.max_ihave_length)


def iwant_select_packed(
    key: jax.Array,
    adv_w: jax.Array,      # u32[N, K, W] advertisements received this heartbeat
    have_w: jax.Array,     # u32[N, W]
    edge_live: jax.Array,  # bool[N, K]
    scores: jax.Array,     # f32[N, K] receiver's score of each advertiser
    serve_ok: jax.Array,   # bool[N, K] the advertiser will actually serve
    alive: jax.Array,      # bool[N]
    max_iwant_length: int,
    gossip_threshold: float,
    uid: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """IWANT phase with promise accounting over packed windows ->
    (pend u32[N, W], broken f32[N, K]).

    Bit-exact with :func:`gossip.iwant_select` under the same key (see its
    docstring for the protocol rules: IHAVEs below ``gossip_threshold``
    ignored, one ask per id at a keyed RANDOM advertiser priority,
    word-granular ``max_iwant_length`` budget per advertiser, broken-promise
    counts for muted/dead advertisers).  The transfer lands via the caller's
    pend fold — the advertiser's mcache retention (``history_length >
    history_gossip``) guarantees an honest advertiser can still serve."""
    n, k = edge_live.shape
    accept = edge_live & (scores >= gossip_threshold)
    want = adv_w & ~have_w[:, None, :] & _as_mask(accept)[:, :, None]
    perm, inv = iwant_priority(key, n, k, uid)
    # ONE [N,K,W] cube gather into priority order; everything downstream
    # stays permuted.  The ask cap is per-slot (order-independent), ``pend``
    # is an OR over slots (order-independent), and only the [N,K] ``broken``
    # counts need un-permuting — a cheap plane gather, not a second 51 MB
    # cube gather at 100k peers.
    want_p = jnp.take_along_axis(want, perm[:, :, None], axis=1)
    before = exclusive_or_scan(want_p, axis=1)
    first_p = want_p & ~before                 # one advertiser per id, random order
    asked_p = cap_ihave_packed(first_p, max_iwant_length)
    serve_p = jnp.take_along_axis(serve_ok, perm, axis=1)
    served_p = asked_p & _as_mask(serve_p)[:, :, None]
    pend = jax.lax.reduce(
        served_p, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )
    broken_p = (
        jax.lax.population_count(asked_p & ~_as_mask(serve_p)[:, :, None])
        .sum(axis=-1)
        .astype(jnp.float32)
    )
    broken = jnp.take_along_axis(broken_p, inv, axis=1)
    return pend & _as_mask(alive)[:, None], broken


def gossip_exchange_packed(
    key_adv: jax.Array,
    key_iwant: jax.Array,
    have_w: jax.Array,       # u32[N, W] advertise source (pre-TTL-scrub)
    have_dedup_w: jax.Array, # u32[N, W] IWANT dedup view (TTL-scrubbed)
    mesh: jax.Array,         # bool[N, K]
    nbrs: jax.Array,         # i32[N, K]
    rev: jax.Array,          # i32[N, K]
    edge_live: jax.Array,    # bool[N, K]
    alive: jax.Array,        # bool[N]
    scores: jax.Array,       # f32[N, K]
    gossip_w: jax.Array,     # u32[W] packed advertisable window
    p: GossipSubParams,
    gossip_threshold: float,
    serve_ok: jax.Array,     # bool[N, K]
    max_iwant_length: int,
    uid: Optional[jax.Array] = None,
    device_mesh=None,        # split-gather fast path (see ring_gather_rows)
    axis: str = "peers",
) -> tuple[jax.Array, jax.Array]:
    """Fused IHAVE advertise + IWANT select -> (pend u32[N, W],
    broken f32[N, K]).

    Bit-exact with ``iwant_select_packed(ihave_advertise_packed(...), ...)``
    under the same keys (asserted in ``tests/test_gossip_packed.py``), but
    the advertisement cube is built DIRECTLY in the receiver's random
    priority order: all [N, K] planes permute first (cheap), then ONE
    permuted [N, K, W] row gather feeds the whole chain — the unpermuted
    cube of the unfused pair (~51 MB at 100k peers) never materializes.
    The heartbeat's hot path; the unfused pair remains the tested
    reference.

    With ``device_mesh`` the phase needs TWO remote lookups per slot — the
    advertisement row ``(have & gossip)[j]`` and the chooser bit
    ``chosen[j, rev]`` — so ``chosen`` is bit-packed and CONCATENATED onto
    the row table: one ring gather serves both, and the cross-shard half
    still overlaps the intra-shard compute (``ring_gather_rows``).
    """
    n, k = nbrs.shape
    d_lazy = min(p.d_lazy, k)
    if d_lazy <= 0:
        return (
            jnp.zeros_like(have_w),
            jnp.zeros((n, k), jnp.float32),
        )
    chosen = gossip_emission_mask(
        key_adv, mesh, edge_live, alive, scores, p, gossip_threshold, uid
    )
    perm, inv = iwant_priority(key_iwant, n, k, uid)
    take = lambda x: jnp.take_along_axis(x, perm, axis=1)
    jidx_p = take(jnp.clip(nbrs, 0, n - 1))
    ridx_p = take(jnp.clip(rev, 0, k - 1))
    edge_live_p = take(edge_live)
    if device_mesh is None:
        # Chooser bits gather bit-packed (see _gather_packed_bits) — the
        # monolithic twin of the ring path's concatenated packed plane.
        towards_me_p = _gather_packed_bits(chosen, jidx_p, ridx_p) & edge_live_p
        rows_p = (have_w & gossip_w[None, :])[jidx_p]
    else:
        w = have_w.shape[1]
        table = jnp.concatenate(
            [have_w & gossip_w[None, :], bitpack.pack(chosen)], axis=1
        )
        g = ring_gather_rows(table, jidx_p, device_mesh, axis)
        rows_p = g[..., :w]
        ch_words = jnp.take_along_axis(
            g[..., w:], (ridx_p // 32)[:, :, None], axis=2
        )[..., 0]
        ch_bit = (ch_words >> (ridx_p % 32).astype(jnp.uint32)) & 1
        towards_me_p = (ch_bit > 0) & edge_live_p
    adv_p = _as_mask(towards_me_p)[:, :, None] & rows_p
    adv_p = cap_ihave_packed(adv_p, p.max_ihave_length)
    accept_p = edge_live_p & (take(scores) >= gossip_threshold)
    want_p = (
        adv_p & ~have_dedup_w[:, None, :] & _as_mask(accept_p)[:, :, None]
    )
    before = exclusive_or_scan(want_p, axis=1)
    first_p = want_p & ~before
    asked_p = cap_ihave_packed(first_p, max_iwant_length)
    serve_p = take(serve_ok)
    served_p = asked_p & _as_mask(serve_p)[:, :, None]
    pend = jax.lax.reduce(
        served_p, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,)
    )
    broken_p = (
        jax.lax.population_count(asked_p & ~_as_mask(serve_p)[:, :, None])
        .sum(axis=-1)
        .astype(jnp.float32)
    )
    broken = jnp.take_along_axis(broken_p, inv, axis=1)
    return pend & _as_mask(alive)[:, None], broken
