"""Peer-score kernels: the GossipSub v1.1 score function as array programs.

The v0 reference has no scoring (``SURVEY.md`` §0); this implements the
north-star requirement (BASELINE.json config d: "peer-scoring refresh under
sybil/eclipse attack traces").  The score function follows the public
GossipSub v1.1 spec shape: per-topic components P1-P4 computed from
per-(peer, neighbor-slot) counters, global components P5-P7, with periodic
counter decay.

Everything is elementwise over ``[N, K]`` (peer x neighbor-slot) or ``[N]``
arrays — embarrassingly data-parallel, fused by XLA, shardable on the peer
axis.  The "vmapped per-peer reduction" of the north star is realized as
vectorized reductions over the slot axis.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..config import ScoreParams


class TopicCounters(NamedTuple):
    """Per-(local peer, neighbor slot) counters for one topic.

    ``time_in_mesh`` in score quanta; the delivery counters saturate at their
    caps; ``mesh_failure_penalty`` is the sticky deficit snapshot taken when a
    peer is pruned under-threshold.
    """

    time_in_mesh: jax.Array           # f32[N, K]
    first_message_deliveries: jax.Array  # f32[N, K]
    mesh_message_deliveries: jax.Array   # f32[N, K]
    mesh_failure_penalty: jax.Array      # f32[N, K]
    invalid_message_deliveries: jax.Array  # f32[N, K]
    mesh_time_active: jax.Array          # f32[N, K] seconds since graft (gates P3)

    @classmethod
    def zeros(cls, n: int, k: int) -> "TopicCounters":
        z = jnp.zeros((n, k), jnp.float32)
        return cls(z, z, z, z, z, z)


class GlobalCounters(NamedTuple):
    """Per-peer global score inputs (indexed by the *remote* peer id)."""

    app_score: jax.Array          # f32[N] P5 application-specific score
    ip_group: jax.Array           # i32[N] colocation group id (attack model)
    behaviour_penalty: jax.Array  # f32[N] P7 counter

    @classmethod
    def zeros(cls, n: int) -> "GlobalCounters":
        return cls(
            jnp.zeros((n,), jnp.float32),
            jnp.arange(n, dtype=jnp.int32),  # unique groups by default
            jnp.zeros((n,), jnp.float32),
        )


def topic_score(c: TopicCounters, p: ScoreParams) -> jax.Array:
    """P1-P4 for one topic -> f32[N, K]: my score of each neighbor slot."""
    p1 = jnp.minimum(
        c.time_in_mesh / p.time_in_mesh_quantum_s,
        p.time_in_mesh_cap,
    ) * p.time_in_mesh_weight

    p2 = jnp.minimum(
        c.first_message_deliveries, p.first_message_deliveries_cap
    ) * p.first_message_deliveries_weight

    # P3: squared deficit below the delivery threshold, only after the
    # activation window (fresh grafts aren't penalized).
    active = c.mesh_time_active >= p.mesh_message_deliveries_activation_s
    capped = jnp.minimum(c.mesh_message_deliveries, p.mesh_message_deliveries_cap)
    deficit = jnp.maximum(p.mesh_message_deliveries_threshold - capped, 0.0)
    p3 = jnp.where(active, deficit * deficit, 0.0) * p.mesh_message_deliveries_weight

    p3b = c.mesh_failure_penalty * p.mesh_failure_penalty_weight

    p4 = (
        c.invalid_message_deliveries * c.invalid_message_deliveries
    ) * p.invalid_message_deliveries_weight

    topic = (p1 + p2 + p3 + p3b + p4) * p.topic_weight
    return jnp.minimum(topic, p.topic_score_cap)


def colocation_penalty(ip_group: jax.Array, p: ScoreParams) -> jax.Array:
    """P6 -> f32[N]: squared surplus of peers sharing a colocation group.

    ``segment_sum`` over group ids counts group sizes on device — the sybil
    detector of the attack benchmarks.
    """
    n = ip_group.shape[0]
    group = ip_group % n  # group ids live in [0, N); callers hash IPs into it
    counts = jax.ops.segment_sum(
        jnp.ones((n,), jnp.float32), group, num_segments=n
    )
    surplus = jnp.maximum(counts[group] - p.ip_colocation_factor_threshold, 0.0)
    return surplus * surplus * p.ip_colocation_factor_weight


def global_score(g: GlobalCounters, p: ScoreParams) -> jax.Array:
    """P5 + P6 + P7 -> f32[N], indexed by remote peer id."""
    p5 = g.app_score * p.app_specific_weight
    p6 = colocation_penalty(g.ip_group, p)
    excess = jnp.maximum(g.behaviour_penalty - p.behaviour_penalty_threshold, 0.0)
    p7 = excess * excess * p.behaviour_penalty_weight
    return p5 + p6 + p7


def neighbor_scores(
    c: TopicCounters,
    g: GlobalCounters,
    nbrs: jax.Array,
    nbr_valid: jax.Array,
    p: ScoreParams,
    jidx: Optional[jax.Array] = None,
) -> jax.Array:
    """Full score of each neighbor slot -> f32[N, K].

    ``nbrs`` i32[N, K] maps slots to remote peer ids; invalid slots score
    -inf so top-k selections never pick them.  ``jidx`` optionally supplies
    the clipped neighbor-id plane (``clip(nbrs, 0, N-1)``) when the caller
    already computed it for the heartbeat's other kernels (the fused
    prologue shares one clip across scores/mesh/PX).
    """
    gs = global_score(g, p)  # f32[N] by remote id
    if jidx is None:
        jidx = jnp.clip(nbrs, 0, gs.shape[0] - 1)
    remote = gs[jidx]
    total = topic_score(c, p) + remote
    return jnp.where(nbr_valid, total, -jnp.inf)


def decay_topic_counters(c: TopicCounters, p: ScoreParams) -> TopicCounters:
    """Heartbeat decay (refreshScores analog), with decay-to-zero snapping."""

    def dec(x, rate):
        x = x * rate
        return jnp.where(x < p.decay_to_zero, 0.0, x)

    return c._replace(
        first_message_deliveries=dec(
            c.first_message_deliveries, p.first_message_deliveries_decay
        ),
        mesh_message_deliveries=dec(
            c.mesh_message_deliveries, p.mesh_message_deliveries_decay
        ),
        mesh_failure_penalty=dec(c.mesh_failure_penalty, p.mesh_failure_penalty_decay),
        invalid_message_deliveries=dec(
            c.invalid_message_deliveries, p.invalid_message_deliveries_decay
        ),
    )


def decay_global_counters(g: GlobalCounters, p: ScoreParams) -> GlobalCounters:
    b = g.behaviour_penalty * p.behaviour_penalty_decay
    return g._replace(behaviour_penalty=jnp.where(b < p.decay_to_zero, 0.0, b))


def on_graft(c: TopicCounters, grafted: jax.Array) -> TopicCounters:
    """Reset per-slot mesh clocks for newly grafted slots (bool[N, K])."""
    return c._replace(
        time_in_mesh=jnp.where(grafted, 0.0, c.time_in_mesh),
        mesh_time_active=jnp.where(grafted, 0.0, c.mesh_time_active),
    )


def on_prune(
    c: TopicCounters, pruned: jax.Array, p: ScoreParams
) -> TopicCounters:
    """Sticky mesh-failure penalty for slots pruned with a delivery deficit
    (the spec's P3b), and mesh-clock reset."""
    active = c.mesh_time_active >= p.mesh_message_deliveries_activation_s
    capped = jnp.minimum(c.mesh_message_deliveries, p.mesh_message_deliveries_cap)
    deficit = jnp.maximum(p.mesh_message_deliveries_threshold - capped, 0.0)
    penalty = jnp.where(pruned & active, deficit * deficit, 0.0)
    return c._replace(
        mesh_failure_penalty=c.mesh_failure_penalty + penalty,
        time_in_mesh=jnp.where(pruned, 0.0, c.time_in_mesh),
        mesh_time_active=jnp.where(pruned, 0.0, c.mesh_time_active),
    )


def tick_mesh_clocks(
    c: TopicCounters, in_mesh: jax.Array, dt_s: float | jax.Array
) -> TopicCounters:
    """Advance P1 time-in-mesh and the P3 activation clock for mesh slots."""
    return c._replace(
        time_in_mesh=jnp.where(in_mesh, c.time_in_mesh + dt_s, c.time_in_mesh),
        mesh_time_active=jnp.where(
            in_mesh, c.mesh_time_active + dt_s, c.mesh_time_active
        ),
    )
