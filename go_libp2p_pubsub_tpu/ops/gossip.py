"""GossipSub mesh kernels: eager push, lazy gossip, heartbeat maintenance.

North-star components (BASELINE.json configs b/e): "GossipSub's heartbeat
mesh-maintenance and IHAVE/IWANT gossip emission become sparse
graph-propagation kernels over a device-resident peer x topic adjacency".

Representation: a static neighbor-slot adjacency — ``nbrs`` i32[N, K] maps
each peer's K connection slots to remote peer ids, ``rev`` i32[N, K] gives the
remote's slot index pointing back (so edge state can be updated symmetrically
without searches).  Mesh membership, score counters, and message possession
are dense masks over those slots — every protocol rule becomes an elementwise
op + a slot-axis reduction, which is exactly what the VPU wants.

v1.1 mechanisms implemented here (each read from ``GossipSubParams``):

- prune-backoff window (``heartbeat_mesh``'s ``backoff`` state): a pruned
  edge cannot re-graft for ``prune_backoff_heartbeats`` heartbeats;
- outbound-degree quota ``d_out``: the oversubscription keep-rule retains at
  least ``d_out`` dialed-by-me edges, and under-quota peers graft outbound
  candidates even at full degree (the spec's eclipse defense: a victim whose
  mesh is all inbound attacker connections keeps some self-chosen links);
- opportunistic grafting: every ``opportunistic_graft_ticks`` heartbeats, a
  peer whose median mesh score sits below the threshold (passed in from
  ``ScoreParams.opportunistic_graft_threshold`` — it is a score threshold,
  so it lives with the other score thresholds) grafts
  ``opportunistic_graft_peers`` candidates scoring above that median
  (breaks slow-eclipse meshes that keep scores just above zero);
- two-phase IHAVE/IWANT: ``ihave_advertise`` emits heartbeat advertisements
  (an adjacency-slot-indexed window snapshot) honoring ``history_gossip``,
  ``gossip_factor`` and ``max_ihave_length``; the IWANT request + delivery
  happen on the following rounds in the model's propagate (one extra hop of
  latency vs the eager path, as on the wire).  Peer exchange on prune (PX)
  lives in ``ops/px.py``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import GossipSubParams
from .graphs import safe_gather, top_mask


def uniform_by_uid(
    key: jax.Array,
    shape: Tuple[int, ...],
    uid: Optional[jax.Array],
    minval: float = 0.0,
    maxval: float = 1.0,
) -> jax.Array:
    """Per-peer uniform draw keyed on canonical peer identity.

    Row axis 0 of the draw is peer id; when the caller runs under a
    renumbering (``parallel/placement``), ``uid[i]`` is physical row i's
    canonical id and the draw is gathered through it — so the randomness a
    peer sees depends on WHO it is, not where the placement put it, and a
    relabeled rollout stays bit-identical to the canonical one under the
    inverse permutation.  ``uid=None`` (identity) is the everyday path and
    compiles to exactly the plain draw.
    """
    r = jax.random.uniform(key, shape, minval=minval, maxval=maxval)
    return r if uid is None else r[uid]


class PropagateOut(NamedTuple):
    have: jax.Array
    fresh: jax.Array
    first_step: jax.Array
    fmd_inc: jax.Array      # f32[N, K] first-delivery increments (valid msgs)
    mmd_inc: jax.Array      # f32[N, K] mesh-delivery increments
    invalid_inc: jax.Array  # f32[N, K] invalid first-delivery increments


def propagate(
    mesh: jax.Array,        # bool[N, K] symmetric mesh membership
    nbrs: jax.Array,        # i32[N, K]
    nbr_valid: jax.Array,   # bool[N, K]
    alive: jax.Array,       # bool[N]
    have: jax.Array,        # bool[N, M]
    fresh: jax.Array,       # bool[N, M] first-received last round -> forwarded now
    first_step: jax.Array,  # i32[N, M] step of first receipt, -1 = never
    msg_valid: jax.Array,   # bool[M] validation verdict per message
    step: jax.Array,        # i32 current step
    idontwant: bool = False,  # v1.2: senders skip ids the receiver had last
    #                           round (its IDONTWANT notifications); only
    #                           duplicate-copy counting changes
    idw_have=None,            # bool[N, M] the possession snapshot the
    #                           notifications reflect (receiver's knowledge
    #                           one hop ago); defaults to ``have`` — callers
    #                           whose ``have`` already includes same-round
    #                           fold receipts MUST pass the pre-fold view
) -> PropagateOut:
    """One eager-push round: every peer relays last round's first-receipts to
    its mesh neighbors; receivers validate, deduplicate, attribute delivery
    credit to the earliest delivering slot, and queue valid messages for
    relay next round.

    The [N, K, M] incoming tensor is the fused "who sent me what" cube; XLA
    keeps it in registers/VMEM per tile.  Invalid messages are dropped at
    validation and NOT relayed (their P4 blame lands on the delivering slot).

    Graylisting (``ScoreParams.graylist_threshold``) is receiver-side edge
    masking and composes by the caller passing ``mesh & (scores >=
    graylist_threshold)`` — a graylisted sender's frames are ignored exactly
    as the spec ignores RPCs from below-graylist peers.
    """
    n, k = nbrs.shape

    j = jnp.clip(nbrs, 0, n - 1)
    edge_ok = mesh & nbr_valid & safe_gather(alive, nbrs, False)  # bool[N, K]
    incoming = edge_ok[:, :, None] & fresh[j]                     # bool[N, K, M]

    arrived = incoming.any(axis=1)                                # bool[N, M]
    new = arrived & ~have & alive[:, None]

    # First-delivering slot per (peer, msg): the lowest slot among senders.
    prefix = jnp.cumsum(incoming.astype(jnp.int32), axis=1)
    first_sender = incoming & (prefix == 1)                       # bool[N, K, M]
    newly = first_sender & new[:, None, :]

    fmd_inc = (newly & msg_valid[None, None, :]).sum(axis=2).astype(jnp.float32)
    invalid_inc = (newly & ~msg_valid[None, None, :]).sum(axis=2).astype(jnp.float32)
    # Mesh-delivery counter counts first + duplicate copies from mesh links.
    # Under IDONTWANT (v1.2) a sender skips ids the receiver first-received
    # in an EARLIER round (the receiver's notification had a round to
    # arrive); same-round duplicates still cross the wire, exactly as the
    # wire races the notification.  Deliveries/receipts are unaffected —
    # the receiver's dedup already ignored these copies; the suppression
    # removes them from the wire and from P3 counting.
    idw = have if idw_have is None else idw_have
    counted = incoming if not idontwant else (incoming & ~idw[:, None, :])
    mmd_inc = (counted & msg_valid[None, None, :]).sum(axis=2).astype(jnp.float32)

    have_next = have | (new & msg_valid[None, :])
    fresh_next = new & msg_valid[None, :]
    first_step_next = jnp.where(new & (first_step < 0), step, first_step)

    return PropagateOut(
        have_next, fresh_next, first_step_next, fmd_inc, mmd_inc, invalid_inc
    )


def gossip_emission_mask(
    key: jax.Array,
    mesh: jax.Array,        # bool[N, K]
    edge_live: jax.Array,   # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,       # bool[N]
    scores: jax.Array,      # f32[N, K]
    p: GossipSubParams,
    gossip_threshold: float,
    uid: Optional[jax.Array] = None,  # i32[N] canonical id per physical row
) -> jax.Array:
    """bool[N, K]: the neighbor slots each peer advertises to this heartbeat.

    Eligibility: live non-mesh edges whose score clears ``gossip_threshold``.
    Emission degree is the spec's ``max(d_lazy, gossip_factor * n_eligible)``
    — the adaptive-gossip rule that keeps coverage as the eligible set grows.
    """
    n, k = mesh.shape
    eligible = edge_live & ~mesh & alive[:, None] & (scores >= gossip_threshold)
    d_lazy = min(p.d_lazy, k)
    if d_lazy <= 0:  # gossip disabled
        return jnp.zeros((n, k), bool)
    n_eligible = eligible.sum(axis=1).astype(jnp.float32)
    emit = jnp.maximum(
        jnp.int32(d_lazy), jnp.ceil(p.gossip_factor * n_eligible).astype(jnp.int32)
    )
    r = uniform_by_uid(key, (n, k), uid)
    return top_mask(jnp.where(eligible, r, -jnp.inf), emit, kmax=k)


def cap_ihave(adv: jax.Array, max_len: int) -> jax.Array:
    """Truncate each IHAVE (bool[..., M] advertisement) to at most ``max_len``
    message ids, at 32-bit-word granularity.

    The packed kernels can only count set bits per uint32 word, so the cap
    keeps whole words while the cumulative id count fits — always <= the
    spec's ``max_ihave_length`` (under-advertising is compliant; the packed
    and unpacked forms stay bit-identical).
    """
    m = adv.shape[-1]
    w = (m + 31) // 32
    padded = jnp.pad(adv, [(0, 0)] * (adv.ndim - 1) + [(0, w * 32 - m)])
    words = padded.reshape(adv.shape[:-1] + (w, 32))
    counts = words.sum(axis=-1)
    cum = jnp.cumsum(counts, axis=-1)
    keep = (cum <= max_len)[..., None]
    return (words & keep).reshape(adv.shape[:-1] + (w * 32,))[..., :m]


def ihave_advertise(
    key: jax.Array,
    have: jax.Array,        # bool[N, M]
    mesh: jax.Array,        # bool[N, K]
    nbrs: jax.Array,
    rev: jax.Array,
    edge_live: jax.Array,   # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,
    scores: jax.Array,      # f32[N, K] my view of each neighbor slot
    gossip_msgs: jax.Array,  # bool[M] advertisable window (valid & recent)
    p: GossipSubParams,
    gossip_threshold: float,
    uid: Optional[jax.Array] = None,
) -> jax.Array:
    """Heartbeat IHAVE phase -> adv bool[N, K, M]: ``adv[i, s]`` is the set of
    message ids advertised TO peer i BY its neighbor slot s this heartbeat.

    ``gossip_msgs`` restricts advertisements to the ``history_gossip`` recent
    windows (the mcache rule); ``cap_ihave`` enforces ``max_ihave_length``.
    The receiver computes its IWANT against this snapshot next round and the
    transfer lands the round after — the wire protocol's two message hops.

    Formulated target-side as a reverse-index gather (a chooser's target is
    always a slot-paired neighbor): gathers partition under GSPMD where the
    equivalent scatter would serialize — this is what lets the sharded
    100k-peer sim ride ICI collectives.
    """
    n, k = nbrs.shape
    chosen = gossip_emission_mask(
        key, mesh, edge_live, alive, scores, p, gossip_threshold, uid
    )
    jidx = jnp.clip(nbrs, 0, n - 1)
    ridx = jnp.clip(rev, 0, k - 1)
    towards_me = chosen[jidx, ridx] & edge_live               # bool[N, K]
    adv = towards_me[:, :, None] & (have & gossip_msgs[None, :])[jidx]
    return cap_ihave(adv, p.max_ihave_length)


def iwant_priority(
    key: jax.Array, n: int, k: int, uid: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array]:
    """Per-heartbeat random advertiser priority -> (perm, inv), both i32[N, K].

    ``perm[i]`` is a keyed random order of peer i's slots; ``inv`` is its
    inverse.  Shared by the packed and unpacked IWANT kernels so the two
    stay bit-exact under the same key.
    """
    r = uniform_by_uid(key, (n, k), uid)
    perm = jnp.argsort(r, axis=1).astype(jnp.int32)
    inv = jnp.argsort(perm, axis=1).astype(jnp.int32)
    return perm, inv


def iwant_select(
    key: jax.Array,
    adv: jax.Array,        # bool[N, K, M] advertisements received this heartbeat
    have: jax.Array,       # bool[N, M]
    edge_live: jax.Array,  # bool[N, K]
    scores: jax.Array,     # f32[N, K] receiver's score of each advertiser
    serve_ok: jax.Array,   # bool[N, K] the advertiser will actually serve
    alive: jax.Array,      # bool[N]
    max_iwant_length: int,
    gossip_threshold: float,
    uid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """IWANT phase with promise accounting -> (pend bool[N, M],
    broken f32[N, K]).

    Two spec gates from go-gossipsub's handleIHave:

    - IHAVEs from advertisers the receiver scores below ``gossip_threshold``
      are ignored entirely (no ask, no promise) — so a promise-breaker whose
      accrued P7 drags its score under the threshold loses its grip on the
      pull path;
    - the ask target per wanted id is drawn in a keyed RANDOM slot order
      (go samples from shuffled order), not lowest-slot-first — a fixed
      priority would let an adversary occupying a low slot absorb every ask
      for ids an honest higher-slot peer also advertises.

    Asks are capped at ``max_iwant_length`` ids per advertiser per heartbeat
    (go's MaxIHaveLength ask budget, word-granular like ``cap_ihave``).

    ``pend`` is what actually arrives (advertisers with ``serve_ok`` false —
    muted/dead — never serve); ``broken`` counts each slot's broken
    promises, charged to the remote peer as P7 behaviour penalty by the
    caller.  The wire protocol detects a broken promise after the IWANT
    followup timeout; the lockstep model collapses that to the same
    heartbeat (service is deterministic in-model) — a documented deviation.

    Unpacked reference for ``gossip_packed.iwant_select_packed``.
    """
    n, k = edge_live.shape
    accept = edge_live & (scores >= gossip_threshold)
    want = adv & ~have[:, None, :] & accept[:, :, None]
    perm, inv = iwant_priority(key, n, k, uid)
    want_p = jnp.take_along_axis(want, perm[:, :, None], axis=1)
    prefix = jnp.cumsum(want_p.astype(jnp.int32), axis=1)
    first_p = want_p & (prefix == 1)           # one advertiser per id, random order
    first = jnp.take_along_axis(first_p, inv[:, :, None], axis=1)
    asked = cap_ihave(first, max_iwant_length)
    served = asked & serve_ok[:, :, None]
    pend = served.any(axis=1) & alive[:, None]
    broken = (
        (asked & ~serve_ok[:, :, None]).sum(axis=2).astype(jnp.float32)
    )
    return pend, broken


def masked_median(vals: jax.Array, mask: jax.Array) -> jax.Array:
    """Per-row median of ``vals`` over ``mask`` -> f32[N]; +inf where the mask
    is empty (callers compare with ``<`` so empty rows never trigger)."""
    k = vals.shape[1]
    cnt = mask.sum(axis=1)
    s = jnp.sort(jnp.where(mask, vals, jnp.inf), axis=1)
    idx = jnp.clip((cnt - 1) // 2, 0, k - 1)
    med = jnp.take_along_axis(s, idx[:, None], axis=1)[:, 0]
    return jnp.where(cnt > 0, med, jnp.inf)


def heartbeat_mesh(
    key: jax.Array,
    mesh: jax.Array,       # bool[N, K]
    scores: jax.Array,     # f32[N, K]
    nbrs: jax.Array,
    rev: jax.Array,
    edge_live: jax.Array,  # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,
    p: GossipSubParams,
    backoff: Optional[jax.Array] = None,  # i32[N, K] heartbeats left
    outbound: Optional[jax.Array] = None,  # bool[N, K] I dialed this edge
    do_opportunistic=False,  # bool scalar: opportunistic-graft tick
    og_threshold: float = 1.0,  # ScoreParams.opportunistic_graft_threshold
    ignore_backoff: Optional[jax.Array] = None,  # bool[N] misbehaviour model
    uid: Optional[jax.Array] = None,  # i32[N] canonical id per physical row
    edge_idx: Optional[Tuple[jax.Array, jax.Array]] = None,  # shared (jidx, ridx)
    with_px_offer: bool = False,
) -> Tuple[jax.Array, ...]:
    """Mesh maintenance: prune negative-score and over-degree links, graft
    toward D from well-scored candidates, then symmetrize edge state.

    Returns (new_mesh, grafted, pruned, new_backoff, bo_violations); the
    first four are [N, K], ``bo_violations`` is f32[N] — how many of each
    peer's GRAFT attempts this heartbeat were refused because the edge sits
    inside the remote's prune-backoff window.  The spec's P7 behaviour
    penalty charges exactly these; the model feeds them into
    ``GlobalCounters.behaviour_penalty``.

    Fused-prologue hooks: ``edge_idx`` optionally supplies the clipped
    ``(jidx, ridx)`` slot-pairing indices the caller shares across the
    heartbeat's three prologue kernels (scores / mesh / PX), and
    ``with_px_offer=True`` appends a sixth output — ``score_rev_ok``
    bool[N, K], the remote's view ``(scores >= 0)[jidx, ridx]`` that
    :func:`..px.px_rewire` would otherwise re-gather as its PX offer gate.
    The plane already rides this kernel's single bitfield gather (bit 2),
    so returning it is free; gather-then-compare and compare-then-gather
    are the same booleans, so the handoff is bit-exact.

    A spec-following peer never attempts such a graft (its own candidacy is
    gated by the same — symmetric — backoff countdown), so honest rows are
    always 0.  ``ignore_backoff`` (bool[N]) marks peers that graft through
    their own backoff anyway — the knob attack traces use to model GRAFT
    flooders; their attempts are refused on the remote side and counted
    here.

    Desired-set rules (each side computes independently, then edges agree):
    - drop slots whose score < 0 or whose remote died;
    - when degree > d_hi: keep the d_score best-scoring plus a random fill
      back to D, with at least ``d_out`` outbound links retained (swap
      random inbound fills for kept outbound ones if needed) — the spec's
      oversubscription + outbound-quota rule;
    - when degree < d_lo: graft random non-mesh candidates with score >= 0
      up to D (the spec's hysteresis: no topping-up between d_lo and d),
      skipping slots inside their prune-backoff window;
    - regardless of degree, graft outbound candidates while the outbound
      quota ``d_out`` is unmet;
    - on an opportunistic tick, a peer whose median kept-mesh score is below
      ``og_threshold`` grafts up to ``opportunistic_graft_peers``
      candidates scoring above that median.

    Edge agreement: an existing edge survives only if BOTH sides keep it; a
    new edge forms if EITHER side grafts and the other side's view of the
    requester is non-negative (GRAFT accepted) — the array form of
    unilateral PRUNE / accepted GRAFT.  A pruned edge starts a
    ``prune_backoff_heartbeats`` countdown on both endpoints' slots during
    which it may not re-form (spec's PruneBackoff; GRAFTs inside backoff
    are refused and would be penalized upstream).
    """
    n, k = nbrs.shape
    if backoff is None:
        backoff = jnp.zeros((n, k), jnp.int32)
    if outbound is None:
        outbound = jnp.zeros((n, k), bool)
    # Own-liveness folded in makes kmask SYMMETRIC across the slot pairing
    # (valid & alive[i] & alive[j]), so the agreement rules below produce a
    # symmetric mesh by construction — no enforcement gather needed.
    kmask = edge_live & alive[:, None]

    keep = mesh & kmask & (scores >= 0.0)
    deg = keep.sum(axis=1)

    kkeep, kgraft, kog = jax.random.split(key, 3)

    # Oversubscription: keep the d_score best-scoring slots unconditionally,
    # fill the remaining D - d_score UNIFORMLY AT RANDOM from the other kept
    # slots (the spec's rule; pure score-ranking would let an attacker who
    # inflates P1/P2 deterministically occupy every retained slot — the
    # eclipse vector the random fill exists to break), then enforce the
    # outbound quota: if fewer than d_out of the chosen are outbound, swap
    # random non-outbound fills for kept outbound slots.
    noise = uniform_by_uid(kkeep, (n, k), uid, minval=0.0, maxval=1e-3)
    best = top_mask(jnp.where(keep, scores + noise, -jnp.inf), p.d_score)
    fill = top_mask(
        jnp.where(keep & ~best, noise, -jnp.inf), max(p.d - p.d_score, 0)
    )
    chosen = best | fill
    if p.d_out > 0:
        ob_short = jnp.clip(
            p.d_out - (chosen & outbound).sum(axis=1), 0, p.d_out
        ).astype(jnp.int32)
        # Swap at most as many as we can drop back out: each outbound
        # addition must displace a non-outbound random fill, or the kept set
        # would exceed D (the swap is an exchange, not a top-up).
        droppable = (fill & ~outbound).sum(axis=1).astype(jnp.int32)
        add_ob = top_mask(
            jnp.where(keep & outbound & ~chosen, noise, -jnp.inf),
            jnp.minimum(ob_short, droppable),
            kmax=p.d_out,
        )
        n_added = add_ob.sum(axis=1).astype(jnp.int32)
        drop = top_mask(
            jnp.where(fill & ~outbound, noise, -jnp.inf), n_added, kmax=p.d_out
        )
        chosen = (chosen | add_ob) & ~drop
    over = deg > p.d_hi
    keep = keep & jnp.where(over[:, None], chosen, True)

    # Grafting: random eligible non-mesh candidates up to D, only when degree
    # fell below d_lo (spec hysteresis).  My own backoff gates candidacy; the
    # REMOTE's backoff vetoes acceptance below (the wire analog: a GRAFT
    # inside the peer's backoff window is refused).
    deg_now = keep.sum(axis=1)
    score_ok = scores >= 0.0
    bo_ok = backoff <= 0
    cand_bo = bo_ok if ignore_backoff is None else (
        bo_ok | ignore_backoff[:, None]
    )
    cand = kmask & ~keep & score_ok & cand_bo
    r = uniform_by_uid(kgraft, (n, k), uid)
    want_more = jnp.where(
        deg_now < p.d_lo, jnp.maximum(p.d - deg_now, 0), 0
    ).astype(jnp.int32)
    graft = top_mask(jnp.where(cand, r, -jnp.inf), want_more, kmax=p.d)

    # Outbound-quota grafting (v1.1): top up dialed-by-me mesh links to d_out
    # even at full degree.
    if p.d_out > 0:
        ob_have = ((keep | graft) & outbound).sum(axis=1)
        want_ob = jnp.clip(p.d_out - ob_have, 0, p.d_out).astype(jnp.int32)
        graft = graft | top_mask(
            jnp.where(cand & outbound & ~graft, r, -jnp.inf),
            want_ob,
            kmax=p.d_out,
        )

    # Opportunistic grafting (v1.1): median kept-mesh score below the
    # threshold -> graft above-median candidates.  The whole branch (a full
    # [N, K] sort for the median + a top-k chain) runs under ``lax.cond`` so
    # the 7-of-8 non-opportunistic heartbeats skip it entirely.
    if p.opportunistic_graft_peers > 0:

        def _with_og():
            med = masked_median(scores, keep)
            og_want = jnp.where(
                med < og_threshold, p.opportunistic_graft_peers, 0
            ).astype(jnp.int32)
            rog = uniform_by_uid(kog, (n, k), uid)
            return graft | top_mask(
                jnp.where(
                    cand & ~graft & (scores > med[:, None]), rog, -jnp.inf
                ),
                og_want,
                kmax=p.opportunistic_graft_peers,
            )

        graft = jax.lax.cond(
            jnp.asarray(do_opportunistic), _with_og, lambda: graft
        )

    # Edge agreement via the reverse index.  For my slot (i, k) pointing at
    # j = nbrs[i, k], the remote's matching slot is (j, rev[i, k]); indexing
    # a per-slot array at [jidx, ridx] reads the remote's view of this same
    # edge.  Per-element gathers are latency-bound on TPU (~tens of ms at
    # 100k peers), so the four remote views ride ONE int32 bitfield gather.
    if edge_idx is None:
        jidx = jnp.clip(nbrs, 0, n - 1)
        ridx = jnp.clip(rev, 0, k - 1)
    else:
        jidx, ridx = edge_idx
    flags = (
        keep.astype(jnp.int32)
        | (graft.astype(jnp.int32) << 1)
        | (score_ok.astype(jnp.int32) << 2)
        | (bo_ok.astype(jnp.int32) << 3)
    )
    flags_rev = flags[jidx, ridx]
    keep_rev = (flags_rev & 1) > 0
    graft_rev = (flags_rev & 2) > 0
    score_rev_ok = (flags_rev & 4) > 0
    bo_rev_ok = (flags_rev & 8) > 0

    # Existing edge survives only if BOTH sides keep it (unilateral PRUNE).
    survives = mesh & keep & keep_rev
    # New edge forms if either side grafts and the other accepts: its score
    # of the requester is non-negative and it is outside its backoff window
    # (accepted GRAFT semantics).
    forms = ~mesh & (
        (graft & score_rev_ok & bo_rev_ok) | (graft_rev & score_ok & bo_ok)
    )
    # kmask is symmetric and survives/forms are mirrored expressions, so
    # new_mesh[i,k] == new_mesh[j,rev] holds by construction.
    new_mesh = kmask & (survives | forms)

    grafted = new_mesh & ~mesh
    pruned = mesh & ~new_mesh
    # Backoff bookkeeping: pruned edges (either side's view — the pairing is
    # symmetric, so pruned[i,k] == pruned[j,rev]) restart the countdown;
    # everything else ticks down one heartbeat.
    new_backoff = jnp.where(
        pruned,
        jnp.int32(p.prune_backoff_heartbeats),
        jnp.maximum(backoff - 1, 0),
    )
    # GRAFTs refused for landing inside the remote's backoff window — the
    # P7-chargeable misbehaviour (zero for spec-following peers, whose own
    # symmetric countdown gates candidacy).
    bo_violations = (graft & ~bo_rev_ok).sum(axis=1).astype(jnp.float32)
    if with_px_offer:
        return (
            new_mesh, grafted, pruned, new_backoff, bo_violations,
            score_rev_ok,
        )
    return new_mesh, grafted, pruned, new_backoff, bo_violations
