"""GossipSub mesh kernels: eager push, lazy gossip, heartbeat maintenance.

North-star components (BASELINE.json configs b/e): "GossipSub's heartbeat
mesh-maintenance and IHAVE/IWANT gossip emission become sparse
graph-propagation kernels over a device-resident peer x topic adjacency".

Representation: a static neighbor-slot adjacency — ``nbrs`` i32[N, K] maps
each peer's K connection slots to remote peer ids, ``rev`` i32[N, K] gives the
remote's slot index pointing back (so edge state can be updated symmetrically
without searches).  Mesh membership, score counters, and message possession
are dense masks over those slots — every protocol rule becomes an elementwise
op + a slot-axis reduction, which is exactly what the VPU wants.

Simplifications vs the full v1.1 protocol, stated explicitly: no PX peer
exchange, no outbound-degree quota (D_out), and IHAVE/IWANT is modeled as
one fused heartbeat-time transfer instead of two request/response round
trips (the extra hop of latency is accounted by delivering gossip on the
step after the heartbeat).  The spec's prune-backoff window IS implemented
(``heartbeat_mesh``'s ``backoff`` state): a pruned edge cannot re-graft for
``prune_backoff_heartbeats`` heartbeats — without it, a scored-out attacker
re-enters the mesh as soon as its counters decay (see
``tests/test_attacks.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import GossipSubParams
from .graphs import safe_gather, top_mask


class PropagateOut(NamedTuple):
    have: jax.Array
    fresh: jax.Array
    first_step: jax.Array
    fmd_inc: jax.Array      # f32[N, K] first-delivery increments (valid msgs)
    mmd_inc: jax.Array      # f32[N, K] mesh-delivery increments
    invalid_inc: jax.Array  # f32[N, K] invalid first-delivery increments


def propagate(
    mesh: jax.Array,        # bool[N, K] symmetric mesh membership
    nbrs: jax.Array,        # i32[N, K]
    nbr_valid: jax.Array,   # bool[N, K]
    alive: jax.Array,       # bool[N]
    have: jax.Array,        # bool[N, M]
    fresh: jax.Array,       # bool[N, M] first-received last round -> forwarded now
    first_step: jax.Array,  # i32[N, M] step of first receipt, -1 = never
    msg_valid: jax.Array,   # bool[M] validation verdict per message
    step: jax.Array,        # i32 current step
) -> PropagateOut:
    """One eager-push round: every peer relays last round's first-receipts to
    its mesh neighbors; receivers validate, deduplicate, attribute delivery
    credit to the earliest delivering slot, and queue valid messages for
    relay next round.

    The [N, K, M] incoming tensor is the fused "who sent me what" cube; XLA
    keeps it in registers/VMEM per tile.  Invalid messages are dropped at
    validation and NOT relayed (their P4 blame lands on the delivering slot).
    """
    n, k = nbrs.shape

    j = jnp.clip(nbrs, 0, n - 1)
    edge_ok = mesh & nbr_valid & safe_gather(alive, nbrs, False)  # bool[N, K]
    incoming = edge_ok[:, :, None] & fresh[j]                     # bool[N, K, M]

    arrived = incoming.any(axis=1)                                # bool[N, M]
    new = arrived & ~have & alive[:, None]

    # First-delivering slot per (peer, msg): the lowest slot among senders.
    prefix = jnp.cumsum(incoming.astype(jnp.int32), axis=1)
    first_sender = incoming & (prefix == 1)                       # bool[N, K, M]
    newly = first_sender & new[:, None, :]

    fmd_inc = (newly & msg_valid[None, None, :]).sum(axis=2).astype(jnp.float32)
    invalid_inc = (newly & ~msg_valid[None, None, :]).sum(axis=2).astype(jnp.float32)
    # Mesh-delivery counter counts first + duplicate copies from mesh links.
    mmd_inc = (incoming & msg_valid[None, None, :]).sum(axis=2).astype(jnp.float32)

    have_next = have | (new & msg_valid[None, :])
    fresh_next = new & msg_valid[None, :]
    first_step_next = jnp.where(new & (first_step < 0), step, first_step)

    return PropagateOut(
        have_next, fresh_next, first_step_next, fmd_inc, mmd_inc, invalid_inc
    )


def gossip_transfer(
    key: jax.Array,
    have: jax.Array,        # bool[N, M]
    mesh: jax.Array,        # bool[N, K]
    nbrs: jax.Array,
    edge_live: jax.Array,   # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,
    scores: jax.Array,      # f32[N, K] my view of each neighbor slot
    msg_valid: jax.Array,   # bool[M]
    p: GossipSubParams,
    gossip_threshold: float,
) -> jax.Array:
    """Heartbeat-time IHAVE/IWANT: each peer advertises its window to
    ``d_lazy`` random non-mesh neighbors scoring above the gossip threshold;
    targets pull what they miss.  Returns bool[N, M]: messages to deliver via
    gossip next round.

    The two-message exchange is fused: target t pulls ``have[i] & ~have[t]``
    directly.  Only valid messages transfer (invalid ones died at their first
    validation and were never cached).
    """
    n, k = nbrs.shape
    d_lazy = min(p.d_lazy, k)
    if d_lazy <= 0:  # gossip disabled (a negative index would wrap: pick all)
        return jnp.zeros_like(have)
    eligible = (
        edge_live & ~mesh & alive[:, None] & (scores >= gossip_threshold)
    )
    # Random top-d_lazy among eligible slots.
    r = jax.random.uniform(key, (n, k))
    chosen = top_mask(jnp.where(eligible, r, -jnp.inf), d_lazy)

    # Scatter-or into targets: pend[t, m] |= have[i, m] & ~have[t, m].
    t = jnp.where(chosen, nbrs, n).reshape(-1)                    # i32[N*K]
    src_have = jnp.repeat(have, k, axis=0)                        # bool[N*K, M]
    lacks = ~safe_gather(have, jnp.clip(t, 0, n - 1), True)
    offer = src_have & lacks & (t < n)[:, None] & msg_valid[None, :]
    pend = jnp.zeros((n + 1, have.shape[1]), jnp.int32)
    pend = pend.at[t].add(offer.astype(jnp.int32), mode="drop")
    return pend[:n] > 0


def heartbeat_mesh(
    key: jax.Array,
    mesh: jax.Array,       # bool[N, K]
    scores: jax.Array,     # f32[N, K]
    nbrs: jax.Array,
    rev: jax.Array,
    edge_live: jax.Array,  # bool[N, K] valid slot AND remote alive (cached)
    alive: jax.Array,
    p: GossipSubParams,
    backoff: Optional[jax.Array] = None,  # i32[N, K] heartbeats left
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Mesh maintenance: prune negative-score and over-degree links, graft
    toward D from well-scored candidates, then symmetrize edge state.

    Returns (new_mesh, grafted, pruned, new_backoff) as [N, K].

    Desired-set rules (each side computes independently, then edges agree):
    - drop slots whose score < 0 or whose remote died;
    - when degree > d_hi: keep the d_score best-scoring plus a random fill
      back to D (spec's oversubscription rule);
    - when degree < d_lo: graft random non-mesh candidates with score >= 0
      up to D, skipping slots inside their prune-backoff window.
    Edge agreement: an existing edge survives only if BOTH sides keep it; a
    new edge forms if EITHER side grafts and the other side's view of the
    requester is non-negative (GRAFT accepted) — the array form of
    unilateral PRUNE / accepted GRAFT.  A pruned edge starts a
    ``prune_backoff_heartbeats`` countdown on both endpoints' slots during
    which it may not re-form (spec's PruneBackoff; GRAFTs inside backoff
    are refused and would be penalized upstream).
    """
    n, k = nbrs.shape
    if backoff is None:
        backoff = jnp.zeros((n, k), jnp.int32)
    # Own-liveness folded in makes kmask SYMMETRIC across the slot pairing
    # (valid & alive[i] & alive[j]), so the agreement rules below produce a
    # symmetric mesh by construction — no enforcement gather needed.
    kmask = edge_live & alive[:, None]

    keep = mesh & kmask & (scores >= 0.0)
    deg = keep.sum(axis=1)

    kkeep, kgraft = jax.random.split(key)

    # Oversubscription: keep the d_score best-scoring slots unconditionally,
    # fill the remaining D - d_score UNIFORMLY AT RANDOM from the other kept
    # slots (the spec's rule; pure score-ranking would let an attacker who
    # inflates P1/P2 deterministically occupy every retained slot — the
    # eclipse vector the random fill exists to break).
    noise = jax.random.uniform(kkeep, (n, k), minval=0.0, maxval=1e-3)
    best = top_mask(jnp.where(keep, scores + noise, -jnp.inf), p.d_score)
    fill = top_mask(
        jnp.where(keep & ~best, noise, -jnp.inf), max(p.d - p.d_score, 0)
    )
    over = deg > p.d_hi
    keep = keep & jnp.where(over[:, None], best | fill, True)

    # Grafting: random eligible non-mesh candidates up to D.  My own backoff
    # gates candidacy; the REMOTE's backoff vetoes acceptance below (the
    # wire analog: a GRAFT inside the peer's backoff window is refused).
    deg_now = keep.sum(axis=1)
    want_more = jnp.maximum(p.d - deg_now, 0).astype(jnp.int32)
    score_ok = scores >= 0.0
    bo_ok = backoff <= 0
    cand = kmask & ~keep & score_ok & bo_ok
    r = jax.random.uniform(kgraft, (n, k))
    graft = top_mask(jnp.where(cand, r, -jnp.inf), want_more, kmax=p.d)

    # Edge agreement via the reverse index.  For my slot (i, k) pointing at
    # j = nbrs[i, k], the remote's matching slot is (j, rev[i, k]); indexing
    # a per-slot array at [jidx, ridx] reads the remote's view of this same
    # edge.  Per-element gathers are latency-bound on TPU (~tens of ms at
    # 100k peers), so the four remote views ride ONE int32 bitfield gather.
    jidx = jnp.clip(nbrs, 0, n - 1)
    ridx = jnp.clip(rev, 0, k - 1)
    flags = (
        keep.astype(jnp.int32)
        | (graft.astype(jnp.int32) << 1)
        | (score_ok.astype(jnp.int32) << 2)
        | (bo_ok.astype(jnp.int32) << 3)
    )
    flags_rev = flags[jidx, ridx]
    keep_rev = (flags_rev & 1) > 0
    graft_rev = (flags_rev & 2) > 0
    score_rev_ok = (flags_rev & 4) > 0
    bo_rev_ok = (flags_rev & 8) > 0

    # Existing edge survives only if BOTH sides keep it (unilateral PRUNE).
    survives = mesh & keep & keep_rev
    # New edge forms if either side grafts and the other accepts: its score
    # of the requester is non-negative and it is outside its backoff window
    # (accepted GRAFT semantics).
    forms = ~mesh & (
        (graft & score_rev_ok & bo_rev_ok) | (graft_rev & score_ok & bo_ok)
    )
    # kmask is symmetric and survives/forms are mirrored expressions, so
    # new_mesh[i,k] == new_mesh[j,rev] holds by construction.
    new_mesh = kmask & (survives | forms)

    grafted = new_mesh & ~mesh
    pruned = mesh & ~new_mesh
    # Backoff bookkeeping: pruned edges (either side's view — the pairing is
    # symmetric, so pruned[i,k] == pruned[j,rev]) restart the countdown;
    # everything else ticks down one heartbeat.
    new_backoff = jnp.where(
        pruned,
        jnp.int32(p.prune_backoff_heartbeats),
        jnp.maximum(backoff - 1, 0),
    )
    return new_mesh, grafted, pruned, new_backoff
