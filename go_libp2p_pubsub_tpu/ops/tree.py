"""The dissemination-tree overlay as a data-parallel lockstep state machine.

Design
------
The reference (``/root/reference/subtree.go``) runs one event loop per peer:
goroutines block on stream reads, joins serialize under ``chlock``, fan-out is
a serial loop over a children map, and repair runs inline in the publish path.
The TPU-native formulation inverts this: **all N simulated peers are rows of
device-resident arrays** and one ``jax.jit``-compiled :func:`step` advances the
whole network synchronously.  Protocol actions map as:

==============================================  =================================
reference mechanism                             array mechanism (here)
==============================================  =================================
``handleJoin`` admit under ``chlock``           phase B: segment-ranked
  (``subtree.go:110-154``)                      concurrent admission
``redirectJoin`` min-size child walk            phase B: masked argmin redirect,
  (``subtree.go:156-194``)                      one hop per step
``forwardMessage`` serial fan-out + write-      phase C: vectorized scatter to
  error detect (``subtree.go:319-354``)         child queues + dead-detect mask
``redistributeChildren`` priority re-joins      phase D/A: orphans get
  (``subtree.go:356-375``)                      ``join_target = grandparent``
                                                with priority capacity
``Part`` graceful leave (``subtree.go:78-98``)  phase A
pause/15 s repair timeout/``rejoinRoot`` panic  phase E watchdog; rejoin at root
  (``client.go:96-122``)                        is *implemented* (deviation)
``State`` size accounting (``subtree.go:137``)  phase F: iterated bottom-up
                                                subtree-size fixed point
==============================================  =================================

Messages are device-side ``int32`` ids; payload bytes stay host-side in the
engine (api.py).  Static shapes throughout: membership and death are masks,
redirect walks advance one hop per lockstep step (bounded by tree depth).

Deliberate deviations from reference bugs, per SURVEY.md §2.4 (observable
test behavior preserved): real subtree sizes (§2.4.3), full grandchild lists
during repair (§2.4.4), no all-dead nil-deref (§2.4.5), rejoin-at-root instead
of ``panic`` on repair timeout (§2.4.8), wire fanout params validated (§2.4.10).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import SimParams, TreeOpts
from .graphs import nth_free_slot, safe_gather, segment_rank

NO_PEER = -1  # empty slot / no parent / no target
NO_MSG = -1


class TreeState(NamedTuple):
    """Device-resident per-peer state of one topic tree.

    Shapes: N = max_peers, W = max_width, Q = queue_cap, OC = out_cap.
    """

    parent: jax.Array        # i32[N]  parent peer or NO_PEER
    children: jax.Array      # i32[N, W] child peers, NO_PEER = empty slot
    alive: jax.Array         # bool[N] process liveness (host kills abruptly here)
    joined: jax.Array        # bool[N] attached to the tree
    leaving: jax.Array       # bool[N] graceful Part requested
    join_target: jax.Array   # i32[N]  current join/redirect candidate, NO_PEER = none
    join_prio: jax.Array     # bool[N] priority join (repair adoption, subtree.go:110-114)
    join_wait: jax.Array     # i32[N]  steps spent waiting to be (re)joined
    subtree_size: jax.Array  # i32[N]  peers in own subtree incl. self
    q: jax.Array             # i32[N, Q] inbound message ring
    q_when: jax.Array        # i32[N, Q] earliest step each entry may be popped
                             # (the queued-arrival stamp of per-edge latency;
                             # entries pop in FIFO order, so a delayed head
                             # blocks the queue exactly like in-order stream
                             # delivery on the wire)
    q_head: jax.Array        # i32[N]
    q_len: jax.Array         # i32[N]
    out: jax.Array           # i32[N, OC] delivered-message ring (client.out analog)
    out_len: jax.Array       # i32[N]  total delivered (monotonic)
    out_drained: jax.Array   # i32[N]  host-consumed count (backpressure boundary)
    edge_delay: jax.Array    # i32[N, W] extra steps a message spends crossing
                             # the (parent, child-slot) edge (0 = the default
                             # one-hop-per-step fabric)
    edge_drop: jax.Array     # f32[N, W] per-message drop probability on the
                             # edge (lossy link, NOT death: no write error, no
                             # repair — v0-style silent loss)
    key: jax.Array           # u32[2] PRNG key for edge-drop draws
    root: jax.Array          # i32[]   topic root peer
    width: jax.Array         # i32[]   steady-state fanout (TreeWidth)
    max_width: jax.Array     # i32[]   priority fanout (TreeMaxWidth)
    step_num: jax.Array      # i32[]


# Field-name sharding classification for the peer-dimension parallel path
# (see parallel/mesh.py).  Exhaustive and by NAME, not shape, so a non-peer
# array (like the [2] PRNG key) can never be silently sharded — adding a
# TreeState field forces a decision here (parallel.mesh.state_shardings
# errors on any unclassified field).
TREE_REPLICATED_FIELDS = frozenset(
    {"key", "root", "width", "max_width", "step_num"}
)
TREE_PEER_DIMS = {
    name: 0
    for name in (
        "parent", "children", "alive", "joined", "leaving", "join_target",
        "join_prio", "join_wait", "subtree_size", "q", "q_when", "q_head",
        "q_len", "out", "out_len", "out_drained", "edge_delay", "edge_drop",
    )
}


def init_state(
    params: SimParams, opts: TreeOpts, root: int = 0, seed: int = 0
) -> TreeState:
    if params.max_width < opts.tree_max_width:
        raise ValueError(
            f"SimParams.max_width ({params.max_width}) must be >= "
            f"TreeOpts.tree_max_width ({opts.tree_max_width})"
        )
    n, w = params.max_peers, params.max_width
    i32 = jnp.int32
    st = TreeState(
        parent=jnp.full((n,), NO_PEER, i32),
        children=jnp.full((n, w), NO_PEER, i32),
        alive=jnp.zeros((n,), bool).at[root].set(True),
        joined=jnp.zeros((n,), bool).at[root].set(True),
        leaving=jnp.zeros((n,), bool),
        join_target=jnp.full((n,), NO_PEER, i32),
        join_prio=jnp.zeros((n,), bool),
        join_wait=jnp.zeros((n,), i32),
        subtree_size=jnp.zeros((n,), i32).at[root].set(1),
        q=jnp.full((n, params.queue_cap), NO_MSG, i32),
        q_when=jnp.zeros((n, params.queue_cap), i32),
        q_head=jnp.zeros((n,), i32),
        q_len=jnp.zeros((n,), i32),
        out=jnp.full((n, params.out_cap), NO_MSG, i32),
        out_len=jnp.zeros((n,), i32),
        out_drained=jnp.zeros((n,), i32),
        edge_delay=jnp.zeros((n, w), i32),
        edge_drop=jnp.zeros((n, w), jnp.float32),
        key=jax.random.PRNGKey(seed),
        root=jnp.asarray(root, i32),
        width=jnp.asarray(opts.tree_width, i32),
        max_width=jnp.asarray(opts.tree_max_width, i32),
        step_num=jnp.asarray(0, i32),
    )
    return st


# ---------------------------------------------------------------------------
# host-triggered events (all jittable single-peer updates)
# ---------------------------------------------------------------------------

@jax.jit
def begin_subscribe(st: TreeState, peer: jax.Array) -> TreeState:
    """Peer dials the root and starts the join walk (client.go:65-94).

    The walk itself happens one redirect-hop per :func:`step`, mirroring the
    recursive ``joinParents`` chain (``subtree.go:241-307``) whose depth is
    the tree depth.
    """
    return st._replace(
        alive=st.alive.at[peer].set(True),
        join_target=st.join_target.at[peer].set(st.root),
        join_prio=st.join_prio.at[peer].set(False),
        join_wait=st.join_wait.at[peer].set(0),
    )


@jax.jit
def begin_subscribe_many(st: TreeState, peers_mask: jax.Array) -> TreeState:
    """Start the join walk for every masked peer at once.

    Concurrent joiners are legal — phase B serializes them by segment rank the
    way the reference serializes under ``chlock``.  This is the batched form
    used to stand up large trees in O(depth) steps instead of O(N) subscribes.
    """
    new = peers_mask & ~st.joined
    return st._replace(
        alive=st.alive | peers_mask,
        join_target=jnp.where(new, st.root, st.join_target),
        join_prio=jnp.where(new, False, st.join_prio),
        join_wait=jnp.where(new, 0, st.join_wait),
    )


@jax.jit
def set_link_profile(
    st: TreeState, delay: jax.Array, drop_prob: jax.Array
) -> TreeState:
    """Install per-edge latency/drop tensors (SURVEY §2.3: the mocknet
    analog's "per-edge latency/drop tensors", ``pubsub_test.go:18-25``).

    ``delay`` i32[N, W]: extra lockstep rounds a message spends crossing the
    (parent, child-slot) edge.  ``drop_prob`` f32[N, W]: probability each
    forwarded copy is silently lost on that edge.  Both address edges by the
    parent's child SLOT, so a profile describes links, and repair rewires
    which peer sits behind a link.  Zeroes restore the ideal fabric.
    """
    return st._replace(
        edge_delay=delay.astype(jnp.int32),
        edge_drop=drop_prob.astype(jnp.float32),
    )


@jax.jit
def publish_many(st: TreeState, msg_ids: jax.Array) -> TreeState:
    """Enqueue a batch of messages at the root (ids >= 0; NO_MSG entries
    skipped).  Caller is responsible for queue capacity."""
    r = st.root
    qcap = st.q.shape[1]
    valid = msg_ids >= 0
    offsets = jnp.cumsum(valid.astype(jnp.int32)) - 1
    tails = (st.q_head[r] + st.q_len[r] + offsets) % qcap
    rows = jnp.where(valid, r, st.q.shape[0])
    q = st.q.at[rows, tails].set(msg_ids, mode="drop")
    q_when = st.q_when.at[rows, tails].set(st.step_num, mode="drop")
    return st._replace(
        q=q, q_when=q_when,
        q_len=st.q_len.at[r].add(valid.sum().astype(jnp.int32)),
    )


@jax.jit
def kill_peer(st: TreeState, peer: jax.Array) -> TreeState:
    """Abrupt death — no Part is sent (TestNodesDropping's ``hosts[1].Close()``).

    Detection happens lazily at the next forward attempt, like the write-error
    path in ``forwardMessage`` (``subtree.go:333-336``).
    """
    return st._replace(alive=st.alive.at[peer].set(False))


@jax.jit
def leave_peer(st: TreeState, peer: jax.Array) -> TreeState:
    """Graceful leave — Part to parent next step (``subtree.go:78-98``)."""
    return st._replace(leaving=st.leaving.at[peer].set(True))


@jax.jit
def publish(st: TreeState, msg_id: jax.Array) -> TreeState:
    """Root-side ``PublishMessage`` (``pubsub.go:111-120``): enqueue at root.

    The root's queue feeds phase C, which fans out to children; the root never
    delivers to its own out-ring (the reference root is publisher, not
    subscriber).
    """
    r = st.root
    tail = (st.q_head[r] + st.q_len[r]) % st.q.shape[1]
    return st._replace(
        q=st.q.at[r, tail].set(msg_id),
        q_when=st.q_when.at[r, tail].set(st.step_num),
        q_len=st.q_len.at[r].add(1),
    )


@jax.jit
def drain_out(st: TreeState, peer: jax.Array):
    """Host reads a subscriber's delivered-message ring (client.Messages()).

    Returns (new_state, msgs i32[OC], count): ``msgs[:count]`` are the ids
    delivered since the last drain, oldest first.  Draining releases
    backpressure the way reading ``client.out`` unblocks the sender
    (``client.go:124-127``).
    """
    oc = st.out.shape[1]
    start = st.out_drained[peer]
    count = st.out_len[peer] - start
    idx = (start + jnp.arange(oc, dtype=jnp.int32)) % oc
    msgs = jnp.where(jnp.arange(oc) < count, st.out[peer][idx], NO_MSG)
    return st._replace(out_drained=st.out_drained.at[peer].set(st.out_len[peer])), msgs, count


# ---------------------------------------------------------------------------
# the lockstep transition
# ---------------------------------------------------------------------------

def _phase_part(st: TreeState) -> TreeState:
    """Graceful leaves: Part to parent, parent redistributes grandchildren.

    Mirrors ``subtree.Close`` (``subtree.go:78-98``) + the parent's Part
    handling (``subtree.go:62-70``) + ``redistributeChildren``
    (``subtree.go:356-375``): orphans of the leaver are re-adopted by the
    leaver's parent with priority capacity.  Unlike the reference (§2.4.4),
    *all* grandchildren are recovered, not just the most recently joined.
    """
    leaver = st.leaving & st.alive & st.joined & (jnp.arange(st.parent.shape[0]) != st.root)

    # Parent forgets leaving children (slot cleared).
    ch_is_leaver = safe_gather(leaver, st.children.reshape(-1), False).reshape(st.children.shape)
    children = jnp.where(ch_is_leaver, NO_PEER, st.children)

    # Orphans: children of leavers -> adopt at leaver's parent, priority.
    parent_is_leaver = safe_gather(leaver, st.parent, False)
    orphan = st.joined & st.alive & parent_is_leaver
    grandp = safe_gather(st.parent, st.parent, NO_PEER)  # leaver's parent
    grandp = jnp.where(grandp >= 0, grandp, st.root)
    join_target = jnp.where(orphan, grandp, st.join_target)
    join_prio = jnp.where(orphan, True, st.join_prio)
    join_wait = jnp.where(orphan, 0, st.join_wait)
    parent = jnp.where(orphan, NO_PEER, st.parent)

    # Leaver rows torn down (alive=False: the subscriber process exits after
    # Part, like client.Close() -> sub.Close()).
    parent = jnp.where(leaver, NO_PEER, parent)
    children = jnp.where(leaver[:, None], NO_PEER, children)
    return st._replace(
        parent=parent,
        children=children,
        alive=st.alive & ~leaver,
        joined=st.joined & ~leaver,
        leaving=jnp.zeros_like(st.leaving),
        join_target=join_target,
        join_prio=join_prio,
        join_wait=join_wait,
    )


def _phase_watchdog(st: TreeState, timeout_steps: int) -> TreeState:
    """Orphan pause/timeout: the array form of ``processMessages``' pause
    select (``client.go:105-122``).

    An orphan (dead/absent parent, no repair assignment yet) waits for the
    grandparent's repair dial; past ``timeout_steps`` it rejoins at the root —
    the reference's unimplemented ``rejoinRoot`` (``client.go:96-98``), fixed.
    Joiners stuck in a redirect walk are bounded the same way.
    """
    n = st.parent.shape[0]
    is_root = jnp.arange(n) == st.root
    parent_ok = safe_gather(st.alive & st.joined, st.parent, False)
    orphan = st.joined & st.alive & ~is_root & ((st.parent < 0) | ~parent_ok) & (st.join_target < 0)
    waiting = orphan | (st.join_target >= 0)
    join_wait = jnp.where(waiting, st.join_wait + 1, 0)
    timed_out = waiting & (join_wait > timeout_steps)
    return st._replace(
        join_wait=jnp.where(timed_out, 0, join_wait),
        join_target=jnp.where(timed_out, st.root, st.join_target),
        join_prio=jnp.where(timed_out, False, st.join_prio),
    )


def _phase_join(st: TreeState) -> TreeState:
    """Concurrent admission/redirect: ``handleJoin`` + ``redirectJoin``.

    Every peer with a ``join_target`` attempts one protocol round this step:
    admitted into a free child slot if the target has capacity (priority
    joiners get ``max_width``, ``subtree.go:110-119``), otherwise redirected
    to the target's minimum-size live child (``subtree.go:161-185``) and the
    walk continues next step.  Concurrent joiners at one target are ordered by
    segment rank — the array analog of ``chlock`` serialization.
    """
    n, w = st.children.shape
    joiner = (st.join_target >= 0) & st.alive

    # Target sanity: dead/unjoined target -> restart at root (reference would
    # surface a stream error and the client would retry; bounded here).
    t_ok = safe_gather(st.alive & st.joined, st.join_target, False)
    target = jnp.where(joiner & ~t_ok, st.root, st.join_target)

    n_children = jnp.sum(st.children >= 0, axis=1).astype(jnp.int32)
    cap_w = jnp.where(st.join_prio, st.max_width, st.width)  # per-joiner capacity rule
    capacity = jnp.maximum(cap_w - safe_gather(n_children, target, 0), 0)

    rank = segment_rank(target, joiner)
    admitted = joiner & (rank < capacity)

    # --- admissions -> fill the target's free slots in admit-rank order.
    admit_rank = segment_rank(target, admitted)
    used = st.children >= 0
    target_used = safe_gather(used, jnp.clip(target, 0, n - 1), True)  # bool[N, W] rows
    slots = jax.vmap(nth_free_slot)(target_used, admit_rank)  # i32[N], == W when none
    scatter_t = jnp.where(admitted, target, n)  # row n/col W dropped
    scatter_s = jnp.where(admitted, slots, w)
    children = st.children.at[scatter_t, scatter_s].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop"
    )
    parent = jnp.where(admitted, target, st.parent)
    joined = st.joined | admitted
    join_target = jnp.where(admitted, NO_PEER, target)
    join_prio = jnp.where(admitted, False, st.join_prio)
    join_wait = jnp.where(admitted, 0, st.join_wait)

    # --- redirects -> hop to a min-subtree-size live child of the target.
    # The reference increments the chosen child's size per redirect under
    # chlock (subtree.go:176-178) so consecutive redirects spread; the array
    # equivalent is round-robin by redirect rank over the target's children in
    # ascending-size order.  A lone (sequential) joiner lands exactly on the
    # min-size child, matching the reference's serialized behavior.
    redirected = joiner & ~admitted
    redir_rank = segment_rank(target, redirected)
    t_children = st.children[jnp.clip(target, 0, n - 1)]          # i32[N, W]
    t_ch_live = safe_gather(st.alive & st.joined, t_children.reshape(-1), False).reshape(n, w)
    t_ch_live &= t_children >= 0
    t_ch_size = safe_gather(st.subtree_size, t_children.reshape(-1), 0).reshape(n, w)
    has_live_child = t_ch_live.any(axis=1)
    n_live = t_ch_live.sum(axis=1).astype(jnp.int32)
    # Order slots by (size, slot): a stable argsort on masked sizes breaks
    # ties toward the lowest slot, with dead slots pushed last.
    sort_key = jnp.where(t_ch_live, t_ch_size, jnp.iinfo(jnp.int32).max)
    slot_order = jnp.argsort(sort_key, axis=1, stable=True)       # i32[N, W]
    pick = redir_rank % jnp.maximum(n_live, 1)
    chosen_slot = jnp.take_along_axis(slot_order, pick[:, None], axis=1)[:, 0]
    redir_to = jnp.take_along_axis(t_children, chosen_slot[:, None], axis=1)[:, 0]
    # No live child to redirect to (the reference's nil-deref case,
    # subtree.go:172-176): retry the same target next step.
    join_target = jnp.where(redirected & has_live_child, redir_to, join_target)

    return st._replace(
        parent=parent,
        children=children,
        joined=joined,
        join_target=join_target,
        join_prio=join_prio,
        join_wait=join_wait,
    )


def _phase_data(st: TreeState):
    """Data plane: pop one message per peer, deliver, fan out to children.

    Mirrors ``processMessages`` (``client.go:100-132``): delivery to the out
    ring happens *before* forwarding, and a peer only processes when its out
    ring has room and every live child queue has room — the array form of the
    blocking channel send + blocking stream writes (backpressure by design).
    Writes to dead children are dropped and flagged, like the write-error path
    in ``forwardMessage`` (``subtree.go:333-336``).

    Per-edge network modelling (SURVEY §2.3, set via ``set_link_profile``):
    a forwarded copy is stamped poppable at ``now + 1 + edge_delay[i, s]``
    (queued-arrival semantics; the head entry gates the FIFO, which is
    in-order stream delivery), and is silently lost with probability
    ``edge_drop[i, s]`` — a lossy link, distinct from death: no write error
    is surfaced, so no repair triggers (v0-style accepted loss).  Control
    traffic (join/redirect/Part/State) stays instantaneous: the parity
    contracts key on data-plane loss windows, and a delayed control plane
    would only widen convergence, not change loss classes.

    Returns (state, dead_detect bool[N, W]).
    """
    n, w = st.children.shape
    qcap = st.q.shape[1]
    oc = st.out.shape[1]
    is_root = jnp.arange(n) == st.root

    ch_ok = safe_gather(st.alive & st.joined, st.children.reshape(-1), False).reshape(n, w)
    ch_ok &= st.children >= 0
    ch_qlen = safe_gather(st.q_len, st.children.reshape(-1), 0).reshape(n, w)
    child_room = jnp.where(ch_ok, ch_qlen < qcap, True).all(axis=1)
    out_room = is_root | ((st.out_len - st.out_drained) < oc)

    rows = jnp.arange(n)
    head_ready = st.q_when[rows, st.q_head % qcap] <= st.step_num
    popper = (
        st.alive & st.joined & (st.q_len > 0) & head_ready
        & out_room & child_room
    )
    msg = st.q[rows, st.q_head % qcap]
    q_head = jnp.where(popper, (st.q_head + 1) % qcap, st.q_head)
    q_len = jnp.where(popper, st.q_len - 1, st.q_len)

    # Deliver (non-root): append to out ring.
    deliver = popper & ~is_root
    out = st.out.at[
        jnp.where(deliver, jnp.arange(n), n), st.out_len % oc, # row n dropped
    ].set(msg, mode="drop")
    out_len = jnp.where(deliver, st.out_len + 1, st.out_len)

    # Forward: scatter msg into each live child's queue tail.  Each child has
    # exactly one parent, so targets are unique — no write conflicts.
    key, kdrop = jax.random.split(st.key)
    lost = jax.random.uniform(kdrop, (n, w)) < st.edge_drop
    fwd = popper[:, None] & (st.children >= 0)
    fwd_live = fwd & ch_ok & ~lost
    cidx = jnp.where(fwd_live, st.children, n).reshape(-1)
    ctail = (safe_gather(q_head, cidx, 0) + safe_gather(q_len, cidx, 0)) % qcap
    q = st.q.at[cidx, ctail].set(jnp.repeat(msg, w), mode="drop")
    arrive = (st.step_num + 1 + st.edge_delay).reshape(-1)
    q_when = st.q_when.at[cidx, ctail].set(arrive, mode="drop")
    q_len = q_len.at[cidx].add(jnp.where(cidx < n, 1, 0), mode="drop")

    dead_detect = fwd & ~ch_ok  # write failure -> repair in phase D
    return (
        st._replace(
            q=q, q_when=q_when, q_head=q_head, q_len=q_len, out=out,
            out_len=out_len, key=key,
        ),
        dead_detect,
    )


def _phase_repair(st: TreeState, dead_detect: jax.Array) -> TreeState:
    """Write-failure repair: ``forwardMessage``'s dead-reap +
    ``redistributeChildren`` (``subtree.go:342-350, 356-375``).

    The detecting parent removes the dead child and adopts *all* of its
    recorded children with priority joins (full-list fix of §2.4.4).  Orphan
    rows keep their own children and queue backlog — repair swaps only the
    parent edge, like the pause/resume stream swap (``client.go:106-122``).
    """
    n, w = st.children.shape
    # Which peers were detected dead, and by whom.
    dead_ids = jnp.where(dead_detect, st.children, n).reshape(-1)
    dead_set = jnp.zeros((n,), bool).at[dead_ids].set(True, mode="drop")
    dead_set &= ~(st.alive & st.joined)  # only actually-dead peers

    # Orphans: children of detected-dead peers.  The adopter is the detecting
    # parent == parent[dead] (still recorded on the dead row).
    parent_dead = safe_gather(dead_set, st.parent, False)
    orphan = st.joined & st.alive & parent_dead
    adopter = safe_gather(st.parent, st.parent, NO_PEER)
    adopter = jnp.where(adopter >= 0, adopter, st.root)
    join_target = jnp.where(orphan, adopter, st.join_target)
    join_prio = jnp.where(orphan, True, st.join_prio)
    join_wait = jnp.where(orphan, 0, st.join_wait)
    parent = jnp.where(orphan, NO_PEER, st.parent)

    # Tear down dead rows; drop dead children from their parents' slot lists.
    ch_dead = safe_gather(dead_set, st.children.reshape(-1), False).reshape(n, w)
    children = jnp.where(ch_dead, NO_PEER, st.children)
    children = jnp.where(dead_set[:, None], NO_PEER, children)
    parent = jnp.where(dead_set, NO_PEER, parent)
    return st._replace(
        parent=parent,
        children=children,
        joined=st.joined & ~dead_set,
        join_target=join_target,
        join_prio=join_prio,
        join_wait=join_wait,
    )


def _phase_sizes(st: TreeState, iters: int) -> TreeState:
    """Recompute subtree sizes bottom-up (fixed point over tree depth).

    The correct-semantics replacement for the reference's broken ``State``
    accounting (``sub.size`` never incremented, §2.4.3): sizes here are real,
    so redirect load-balancing actually balances.
    """
    n, w = st.children.shape
    member = st.alive & st.joined

    def body(_, sizes):
        ch = safe_gather(sizes, st.children.reshape(-1), 0).reshape(n, w)
        ch = jnp.where(st.children >= 0, ch, 0)
        return jnp.where(member, 1 + ch.sum(axis=1), 0).astype(jnp.int32)

    sizes = jax.lax.fori_loop(0, iters, body, jnp.where(member, 1, 0).astype(jnp.int32))
    return st._replace(subtree_size=sizes)


@functools.partial(jax.jit, static_argnames=("size_iters", "repair_timeout_steps"))
def step(st: TreeState, size_iters: int = 0, repair_timeout_steps: int = 64) -> TreeState:
    """One lockstep transition of the whole network.

    Phase order encodes the reference's observable ordering:

    A. graceful Parts are handled before data flows (a Part is read by the
       parent's ``handleChildMessages`` goroutine independent of publishes),
       so graceful leaves lose no messages except to the leaver — the
       TestNodesDroppingGracefully contract;
    B. watchdog + join/redirect rounds (control plane);
    C. data pop/deliver/forward with write-failure detection — a message
       published after an abrupt kill is lost to the dead subtree because
       detection happens *during* that forward, exactly like the inline
       repair in ``forwardMessage`` (``subtree.go:342-350``) — the
       TestNodesDropping loss-window contract;
    D. repair assignments from this step's write failures (orphans join next
       step, so the loss window is one hop per tree level);
    E. subtree-size refresh for redirect balancing.
    """
    if size_iters <= 0:
        size_iters = max(2, int(math.ceil(math.log2(max(2, st.parent.shape[0])))) + 1)
    st = _phase_part(st)
    st = _phase_watchdog(st, repair_timeout_steps)
    st = _phase_join(st)
    st, dead_detect = _phase_data(st)
    st = _phase_repair(st, dead_detect)
    st = _phase_sizes(st, size_iters)
    return st._replace(step_num=st.step_num + 1)


@functools.partial(
    jax.jit, static_argnames=("n_steps", "size_iters", "repair_timeout_steps")
)
def run_steps(
    st: TreeState,
    n_steps: int,
    size_iters: int = 0,
    repair_timeout_steps: int = 64,
) -> TreeState:
    """Advance ``n_steps`` lockstep rounds inside one XLA program.

    ``lax.scan`` keeps the whole rollout on device — no per-step host
    dispatch — which is how throughput benchmarks and long simulations should
    drive the engine.
    """

    def body(s, _):
        return step(s, size_iters, repair_timeout_steps), None

    st, _ = jax.lax.scan(body, st, None, length=n_steps)
    return st
